"""ResNet-50 bf16 inference throughput on one chip.

Reference bar: V100 fp16 inference 2085-2355 img/s at batch 32/128
(`docs/.../faq/perf.md:208-210`).  Hybridized model-zoo net, one jitted
forward per batch; best of three fully-drained windows (see bench.py for
the sync rationale).  Prints one JSON line.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

BASELINE_IMG_PER_S = 2355.04  # V100 fp16, batch 128
BATCH = 128
WARMUP = 5
ITERS = 50


def main():
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.resnet50_v1()
    net.initialize(init=mx.init.Xavier())
    net.cast("bfloat16")
    net.hybridize(static_alloc=True)

    x = mx.np.array(onp.random.uniform(-1, 1, (BATCH, 3, 224, 224)),
                    dtype="bfloat16")
    for _ in range(WARMUP):
        out = net(x)
    out.wait_to_read()
    mx.waitall()

    windows = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            net(x)
        mx.waitall()
        windows.append(BATCH * ITERS / (time.perf_counter() - t0))

    img_per_s = max(windows)
    print(json.dumps({
        "metric": "resnet50_infer_bf16_img_per_s",
        "value": round(img_per_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_per_s / BASELINE_IMG_PER_S, 3),
        "batch": BATCH,
        "window_img_per_s": [round(w, 2) for w in windows],
    }))


if __name__ == "__main__":
    main()
