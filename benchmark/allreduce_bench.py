"""Bucketed vs per-key gradient allreduce microbenchmark (ISSUE 4).

Sweeps tensor-count x size-distribution x bucket-bytes over the 8-device
virtual mesh (the same dryrun substrate as `__graft_entry__`), per-key vs
bucketed, across {dense, 2bit, int8, fp8} compression modes, and prints
one JSON line per config plus a summary speedup table.  Verdict:
`benchmark/COLLECTIVES_ANALYSIS.md`.

The headline distribution is ResNet-50-like: 160 gradient tensors whose
median is 256 floats (1 KB — BN gamma/beta and biases), with a small
number of wide conv/fc weights carrying most of the bytes.  Per-key,
every one of those 160 tensors pays an XLA program launch; bucketed they
collapse to a handful of packed psums.

Usage::

    python benchmark/allreduce_bench.py            # full sweep
    python benchmark/allreduce_bench.py --iters 20 --dists resnet50
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

# the sweep must own the virtual mesh BEFORE jax initializes (same dance
# as tests/conftest.py and __graft_entry__._acquire_devices)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as onp  # noqa: E402

N_COPIES = 8

# -- size distributions ------------------------------------------------------
# resnet50: the ResNet-50 tensor-count/median profile — 160 tensors,
# median 256 floats (1 KB: the BN gamma/beta + bias tail that makes
# per-key dispatch latency-bound) — at 1/16 channel width, so the
# virtual-mesh run measures the LAUNCH-bound regime this optimization
# targets rather than the CPU backend's memcpy bandwidth.  resnet50_full
# keeps the full-width byte volume (~56 MB) to expose the byte-bound
# regime, where bucketing is decided by the wire, not the launch count.
DISTRIBUTIONS = {
    "resnet50": [256] * 104 + [1024] * 26 + [16384] * 22 + [65536] * 8,
    "resnet50_full": (
        [256] * 104 + [16384] * 26 + [262144] * 22 + [1048576] * 8),
    "tiny64": [1024] * 64,           # uniformly tiny: pure launch latency
    "wide16": [1 << 20] * 16,        # uniformly wide: wire/compute bound
}


def build_pairs(sizes, seed=0):
    import mxnet_tpu as mx

    rs = onp.random.RandomState(seed)
    pairs = []
    for k, size in enumerate(sizes):
        base = rs.randn(size).astype(onp.float32)
        pairs.append((k, [
            mx.np.array(base + c, ctx=mx.cpu(c)) for c in range(N_COPIES)
        ]))
    return pairs


def make_store(mode, bucket_bytes=None):
    from mxnet_tpu import kvstore
    from mxnet_tpu.kvstore.bucketing import GradBucketer

    kv = kvstore.create("tpu_ici")
    if mode == "2bit":
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    elif mode != "dense":
        kv.set_gradient_compression({"type": mode})
    if bucket_bytes is not None:
        kv._bucketer = GradBucketer(bucket_bytes=bucket_bytes)
    return kv


def run_config(dist, impl, mode, iters, warmup):
    """One (distribution, implementation, mode) config; returns the JSON
    row.  ``impl`` is "perkey" or a bucket-bytes int; ``mode`` is dense,
    2bit, int8, or fp8."""
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry

    sizes = DISTRIBUTIONS[dist]
    pairs = build_pairs(sizes)
    issue = list(reversed(pairs))  # the Trainer's reverse-registration order
    bucketed = impl != "perkey"
    kv = make_store(mode, bucket_bytes=impl if bucketed else None)

    def step():
        if bucketed:
            kv.pushpull_list(issue)
        else:
            for k, vals in issue:
                kv.pushpull(k, vals)

    for _ in range(warmup):
        step()
    mx.waitall()

    reg = telemetry.default_registry()
    name = "mxtpu_kvstore_collective_launches_total"
    before = reg.get_sample_value(name) or 0.0
    t0 = time.perf_counter()
    for _ in range(iters):
        step()
    mx.waitall()
    dt = (time.perf_counter() - t0) / iters
    launches = ((reg.get_sample_value(name) or 0.0) - before) / iters

    grad_mb = sum(sizes) * 4 / 2 ** 20
    return {
        "dist": dist,
        "n_tensors": len(sizes),
        "median_kb": round(
            float(onp.median(onp.asarray(sizes))) * 4 / 1024, 2),
        "grad_mb": round(grad_mb, 2),
        "n_copies": N_COPIES,
        "impl": "perkey" if not bucketed else f"bucketed_{impl >> 20}mb",
        "mode": mode,
        "ms_per_step": round(dt * 1e3, 3),
        "grad_mb_per_s": round(grad_mb / dt, 1),
        "launches_per_step": round(launches, 1),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--dists", nargs="*", default=list(DISTRIBUTIONS))
    ap.add_argument("--bucket-bytes", nargs="*", type=int,
                    default=[1 << 20, 4 << 20, 16 << 20])
    ap.add_argument("--modes", nargs="*",
                    default=["dense", "2bit", "int8", "fp8"])
    args = ap.parse_args()

    rows = []
    for dist in args.dists:
        for mode in args.modes:
            for impl in ["perkey"] + args.bucket_bytes:
                row = run_config(dist, impl, mode, args.iters, args.warmup)
                rows.append(row)
                print(json.dumps(row), flush=True)

    # verdict lines: best bucketed config vs per-key, per (dist, mode)
    for dist in args.dists:
        for mode in args.modes:
            perkey = next(r for r in rows if r["dist"] == dist
                          and r["mode"] == mode and r["impl"] == "perkey")
            best = min((r for r in rows if r["dist"] == dist
                        and r["mode"] == mode and r["impl"] != "perkey"),
                       key=lambda r: r["ms_per_step"])
            print(json.dumps({
                "verdict": f"{dist}/{mode}",
                "speedup": round(perkey["ms_per_step"] /
                                 best["ms_per_step"], 2),
                "best_impl": best["impl"],
                "launches": f"{perkey['launches_per_step']:.0f} -> "
                            f"{best['launches_per_step']:.0f}",
            }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
