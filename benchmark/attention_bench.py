"""Long-sequence attention: flash (Pallas) vs dense (XLA) on one chip.

The long-context story's perf evidence: at sequence lengths where the
(T, T) score matrix stresses HBM, the blockwise Pallas kernel keeps
memory O(T * block) and overtakes XLA's dense fusion.  fwd and fwd+bwd
timed with the true-drain methodology (see bench.py).  Prints one JSON
line per (T, variant).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

B, H, D = 4, 8, 64
WARMUP = 3
ITERS = 10


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--output", default=None,
                   help="write all result lines as a JSON array here")
    p.add_argument("--seq-lens", default="512,1024,2048,4096,8192",
                   help="comma-separated sequence lengths")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops import pallas_kernels as pk
    from mxnet_tpu.ndarray.ndarray import waitall

    def dense(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (D ** -0.5)
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    def flash(q, k, v):
        return pk._flash(q, k, v, False, None, 128, 128, None)

    rows = []
    for t in (int(x) for x in args.seq_lens.split(",")):
        qkv = [jnp.asarray(onp.random.randn(B, H, t, D), jnp.bfloat16)
               for _ in range(3)]

        for name, impl in (("dense", dense), ("flash", flash)):
            fn = jax.jit(impl)
            gn = jax.jit(jax.grad(
                lambda q, k, v: impl(q, k, v).sum().astype(jnp.float32),
                argnums=(0, 1, 2)))

            def fwd():
                return fn(*qkv)

            def fwd_bwd():
                return gn(*qkv)

            try:
                for kind, step in (("fwd", fwd), ("fwd_bwd", fwd_bwd)):
                    for _ in range(WARMUP):
                        step()
                    waitall()
                    t0 = time.perf_counter()
                    for _ in range(ITERS):
                        step()
                    waitall()
                    ms = (time.perf_counter() - t0) / ITERS * 1e3
                    row = {
                        "metric": f"attn_{name}_{kind}_ms",
                        "seq_len": t, "value": round(ms, 2), "unit": "ms",
                        "tokens_per_s": round(B * t / (ms / 1e3)),
                    }
                    print(json.dumps(row))
                    rows.append(row)
            except Exception as e:
                row = {"metric": f"attn_{name}_error",
                       "seq_len": t, "error": str(e)[:120]}
                print(json.dumps(row))
                rows.append(row)
    if args.output:
        with open(args.output, "w") as f:
            json.dump(rows, f, indent=1)


if __name__ == "__main__":
    main()
