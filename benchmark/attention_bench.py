"""Long-sequence attention: flash (Pallas) vs dense (XLA) on one chip.

The long-context story's perf evidence: where does the blockwise Pallas
kernel (memory O(T * block)) overtake XLA's dense fusion (materialized
(T, T) scores)?  Timed as device-side `lax.scan` loops — the opperf
treatment — because through the tunnel a host drain costs ~100 ms and a
10-iteration dispatch loop buries every sub-10 ms kernel under it
(dense fwd+bwd "faster than fwd" was the tell).  Each scan iteration
chains the output back into q with a 1e-24 perturbation so nothing is
hoisted or dead-coded; the drain cost is measured separately and
subtracted.  Prints one JSON line per (T, variant, direction) with a
`reliable` flag (scan work >= 2x drain).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

B, H, D = 4, 8, 64


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--output", default=None,
                   help="write all result lines as a JSON array here")
    p.add_argument("--seq-lens", default="512,1024,2048,4096,8192",
                   help="comma-separated sequence lengths")
    p.add_argument("--kinds", default="fwd,fwd_bwd",
                   help="comma-separated subset of fwd,fwd_bwd")
    p.add_argument("--causal", action="store_true",
                   help="causal variants: dense applies a tril mask, flash "
                        "skips fully-masked blocks (metric gains '_causal')")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops import pallas_kernels as pk

    causal = args.causal

    def dense(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (D ** -0.5)
        if causal:
            t = s.shape[-1]
            mask = jnp.tril(jnp.ones((t, t), bool))
            s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    def flash(q, k, v):
        return pk._flash(q, k, v, causal, None, None, None, None)

    def drain(x):
        onp.asarray(jax.tree_util.tree_leaves(x)[0].ravel()[0])

    def scan_ms(impl, qkv, grad):
        """Per-iteration kernel ms via a chained lax.scan; (ms, k, ok)."""
        q0, kk, vv = qkv
        if grad:
            gfn = jax.value_and_grad(
                lambda q, k, v: impl(q, k, v).sum().astype(jnp.float32),
                argnums=(0, 1, 2))

            def body(c, _):
                val, (gq, gk, gv) = gfn(c, kk, vv)
                dep = (val + gq.astype(jnp.float32).sum()
                       + gk.astype(jnp.float32).sum()
                       + gv.astype(jnp.float32).sum()) * 1e-24
                return c + dep.astype(c.dtype), None
        else:
            def body(c, _):
                out = impl(c, kk, vv)
                dep = out.astype(jnp.float32).sum() * 1e-24
                return c + dep.astype(c.dtype), None

        def make(n):
            @jax.jit
            def run(c):
                c, _ = jax.lax.scan(body, c, None, length=n)
                return c
            return run

        drain(q0)
        t_sync = min((lambda t0: (drain(q0),
                                  time.perf_counter() - t0)[1])(
            time.perf_counter()) for _ in range(3))

        # size the scan from a k=2 probe (one extra compile, but immune
        # to wild per-T cost differences: 1 ms at T=1k, ~1 s at 8k fwd)
        run2 = make(2)
        drain(run2(q0))  # compile
        t0 = time.perf_counter()
        drain(run2(q0))
        est = max((time.perf_counter() - t0 - t_sync) / 2, 1e-5)
        # clamp the window to ~12 s of device time so a drift-poisoned
        # probe estimate cannot produce a minutes-long scan
        n = int(min(max(6.0 * t_sync / est, 8), 4096, 12.0 / est))
        n = max(n, 8)
        for attempt in range(2):
            run_n = make(n)
            drain(run_n(q0))  # compile
            best = None
            for _ in range(3):
                t0 = time.perf_counter()
                drain(run_n(q0))
                best = min(best or 1e9, time.perf_counter() - t0)
            work = best - t_sync
            if work >= 2 * t_sync or attempt == 1:
                break
            # probe est was too high -> n too small: regrow from the
            # measured per-iteration work (one extra compile)
            per = max(work / n, 1e-7)
            n2 = int(min(max(6.0 * t_sync / per, n * 4), 4096, 12.0 / per))
            if n2 == n:
                break  # capped: a recompile would reproduce this scan
            n = n2
        # floor at 1 ns/iter: noise can push work <= 0 on a fast backend,
        # and a 0.0 would divide-by-zero in the tokens/s line
        return max(work / n, 1e-9) * 1e3, n, work >= 2 * t_sync

    rows = []
    for t in (int(x) for x in args.seq_lens.split(",")):
        qkv = [jnp.asarray(onp.random.randn(B, H, t, D), jnp.bfloat16)
               for _ in range(3)]
        for kind, grad in (("fwd", False), ("fwd_bwd", True)):
            if kind not in args.kinds.split(","):
                continue
            for name, impl in (("dense", dense), ("flash", flash)):
                tag = f"{name}_{kind}" + ("_causal" if causal else "")
                try:
                    ms, n, ok = scan_ms(impl, qkv, grad)
                    row = {
                        "metric": f"attn_{tag}_ms",
                        "seq_len": t, "value": round(ms, 3), "unit": "ms",
                        "tokens_per_s": round(B * t / (ms / 1e3)),
                        "scan_len": n, "reliable": ok,
                    }
                except Exception as e:
                    row = {"metric": f"attn_{tag}_error",
                           "seq_len": t, "error": str(e)[:120]}
                    if "UNAVAILABLE" in str(e):
                        # the shared worker crashed; give it time to
                        # restart so later combos aren't poisoned
                        time.sleep(90)
                print(json.dumps(row), flush=True)
                rows.append(row)
    if args.output:
        with open(args.output, "w") as f:
            json.dump(rows, f, indent=1)


if __name__ == "__main__":
    main()
