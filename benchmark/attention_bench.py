"""Long-sequence attention: flash (Pallas) vs dense (XLA) on one chip.

The long-context story's perf evidence: where does the blockwise Pallas
kernel (memory O(T * block)) overtake XLA's dense fusion (materialized
(T, T) scores)?  Timed as device-side `lax.scan` loops — the opperf
treatment — because through the tunnel a host drain costs ~100 ms and a
10-iteration dispatch loop buries every sub-10 ms kernel under it
(dense fwd+bwd "faster than fwd" was the tell).  Each scan iteration
chains the output back into q with a 1e-24 perturbation so nothing is
hoisted or dead-coded; the drain cost is measured separately and
subtracted.  Prints one JSON line per (T, variant, direction) with a
`reliable` flag (scan work >= 2x drain).
"""
from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

import numpy as onp

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from timing_util import scan_ms  # noqa: E402

B, H, D = 4, 8, 64


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--output", default=None,
                   help="write all result lines as a JSON array here")
    p.add_argument("--seq-lens", default="512,1024,2048,4096,8192",
                   help="comma-separated sequence lengths")
    p.add_argument("--kinds", default="fwd,fwd_bwd",
                   help="comma-separated subset of fwd,fwd_bwd")
    p.add_argument("--causal", action="store_true",
                   help="causal variants: dense applies a tril mask, flash "
                        "skips fully-masked blocks (metric gains '_causal')")
    p.add_argument("--masked", action="store_true",
                   help="key-padding variants (metric gains '_masked'): "
                        "ragged per-batch valid lengths (~75%% mean "
                        "occupancy, MLPerf-BERT-style); dense applies the "
                        "mask via where(), flash runs it in-kernel and "
                        "skips/declamps fully-padded tail blocks")
    p.add_argument("--dropout", type=float, default=0.0,
                   help="attention-dropout rate (metric gains '_dropN'): "
                        "flash draws in-kernel threefry bits; dense pays "
                        "an explicit (B,H,T,T) bernoulli mask like the "
                        "production dense path does")
    p.add_argument("--block-sweep", default=None,
                   help="comma-separated bqXbk pairs (e.g. "
                        "'512x512,512x1024,256x1024') to re-pick flash "
                        "block sizes for the masked/dropout variants; "
                        "each adds a flash row tagged with the blocks")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops import pallas_kernels as pk

    causal = args.causal
    drop = args.dropout
    key = jax.random.key(7)

    def lengths_for(t):
        # ragged MLPerf-style padding: valid prefixes in [t/2, t]
        rng = onp.random.RandomState(11)
        return rng.randint(t // 2, t + 1, size=B)

    def mask_for(t):
        if not args.masked:
            return None
        lens = lengths_for(t)
        return jnp.asarray(onp.arange(t)[None, :] < lens[:, None],
                           jnp.int32)

    def dense(q, k, v, mask=None):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (D ** -0.5)
        t = s.shape[-1]
        if causal:
            cm = jnp.tril(jnp.ones((t, t), bool))
            s = jnp.where(cm, s, -1e30)
        if mask is not None:
            s = jnp.where(mask[:, None, None, :] != 0, s, -1e30)
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
        if drop:
            keep = jax.random.bernoulli(key, 1.0 - drop, p.shape)
            p = jnp.where(keep, p / (1.0 - drop), 0.0)
        p = p.astype(q.dtype)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    def make_flash(bq=None, bk=None):
        def flash(q, k, v, mask=None):
            return pk.flash_attention(q, k, v, causal=causal, mask=mask,
                                      dropout=drop,
                                      key=key if drop else None,
                                      block_q=bq, block_k=bk)
        return flash

    suffix = ("_causal" if causal else "") + \
        ("_masked" if args.masked else "") + \
        (f"_drop{int(drop * 100)}" if drop else "")
    impls = [("dense", dense), ("flash", make_flash())]
    if args.block_sweep:
        for pair in args.block_sweep.split(","):
            bq, bk = (int(x) for x in pair.lower().split("x"))
            impls.append((f"flash_bq{bq}_bk{bk}", make_flash(bq, bk)))

    rows = []
    for t in (int(x) for x in args.seq_lens.split(",")):
        qkv = [jnp.asarray(onp.random.randn(B, H, t, D), jnp.bfloat16)
               for _ in range(3)]
        mask_t = mask_for(t)
        for kind, grad in (("fwd", False), ("fwd_bwd", True)):
            if kind not in args.kinds.split(","):
                continue
            for name, base in impls:
                impl = (base if mask_t is None else
                        functools.partial(base, mask=mask_t))
                tag = f"{name}_{kind}{suffix}"
                try:
                    # full dq/dk/dv backward, not just dq (grad="all")
                    ms, n, ok = scan_ms(impl, qkv,
                                        grad="all" if grad else False)
                    row = {
                        "metric": f"attn_{tag}_ms",
                        "seq_len": t, "value": round(ms, 3), "unit": "ms",
                        "tokens_per_s": round(B * t / (ms / 1e3)),
                        "scan_len": n, "reliable": ok,
                    }
                except Exception as e:
                    row = {"metric": f"attn_{tag}_error",
                           "seq_len": t, "error": str(e)[:120]}
                    if "UNAVAILABLE" in str(e):
                        # the shared worker crashed; give it time to
                        # restart so later combos aren't poisoned
                        time.sleep(90)
                print(json.dumps(row), flush=True)
                rows.append(row)
    if args.output:
        with open(args.output, "w") as f:
            json.dump(rows, f, indent=1)


if __name__ == "__main__":
    main()
