"""Where do the flash kernel's cycles go, and what is its ceiling?

Round-4 verdict weak #2: the kernel streams 5.5e11 FLOPs in ~14 ms at
T=8192 (≈39 TF/s, 20% of the 197 TF/s bf16 peak) with "no roofline
statement of what the kernel *should* hit".  This experiment answers
with ablation kernels — same grid, same BlockSpecs, same memory
traffic, surgically removed compute (probe-only math; outputs are wrong
by construction for everything but `full`):

  full       production forward kernel (ops/pallas_kernels.py)
  noexp      exp(x) -> x*0.5 in p and alpha (transcendental cost)
  nosoftmax  p = s directly (no max/exp/sum/rescale: MXU dots + pipeline
             floor at this d)
  bf16exp    shift in f32, exp on bf16 (half the transcendental lanes),
             l accumulated in f32

Derived bounds at (B=4, H=8, T=8192, D=64), bf16:

- MXU: 4·B·H·T²·D = 5.50e11 FLOPs.  At 197 TF/s -> 2.79 ms.  BUT both
  dots are D=64-limited: the s-dot contracts over D=64 (half the MXU's
  128-deep systolic contraction) and the pv-dot's output is D=64 wide
  (half the 128-lane output tile) -> ~50% MXU ceiling -> 5.6 ms floor.
- VPU: softmax touches B·H·T² = 2.15e9 f32 score elements ~6-10
  elementwise ops each (max-tree, subtract, exp, sum-tree, casts,
  alpha-rescale amortized) at ~3.9e12 f32 lanes/s -> 3.3-5.5 ms that
  only partially overlaps the MXU.

So ~39 TF/s is NOT 20% of this kernel's own roofline — the d=64 head
geometry halves the MXU bound and adds a comparable VPU term.  The
ablation table quantifies both.  Results:
`results/flash_roofline_tpu_v5e.json`; discussion in
ATTENTION_ANALYSIS.md (roofline section).
"""
from __future__ import annotations

import argparse
import functools
import json
import os
import sys

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from timing_util import scan_ms  # noqa: E402

B, H, D = 4, 8, 64


def _variant_kernel(mode):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from mxnet_tpu.ops.pallas_kernels import _prec

    def kernel(q_ref, kt_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
               *, scale, nk):
        ki = pl.program_id(2)

        @pl.when(ki == 0)
        def _init():
            m_ref[...] = jnp.full_like(m_ref, -1e30)
            l_ref[...] = jnp.zeros_like(l_ref)
            acc_ref[...] = jnp.zeros_like(acc_ref)

        q = q_ref[0]
        kt = kt_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(q, kt, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=_prec(q.dtype)) * scale
        if mode == "nosoftmax":
            acc_ref[...] += jax.lax.dot_general(
                s.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=_prec(v.dtype))
        else:
            m_prev = m_ref[...]
            m_cur = jnp.max(s, axis=1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            if mode == "noexp":
                p = (s - m_new) * 0.5
                alpha = (m_prev - m_new) * 0.5
            elif mode == "bf16exp":
                p = jnp.exp((s - m_new).astype(jnp.bfloat16))
                alpha = jnp.exp(m_prev - m_new)
            else:   # full-equivalent reference path
                p = jnp.exp(s - m_new)
                alpha = jnp.exp(m_prev - m_new)
            l_ref[...] = l_ref[...] * alpha + \
                p.sum(axis=1, keepdims=True, dtype=jnp.float32)
            acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=_prec(v.dtype))
            m_ref[...] = m_new

        @pl.when(ki == nk - 1)
        def _finish():
            if mode == "nosoftmax":
                o_ref[0] = acc_ref[...].astype(o_ref.dtype)
                lse_ref[0] = jnp.zeros_like(lse_ref[0])
            else:
                l = jnp.maximum(l_ref[...], 1e-30)
                o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
                lse_ref[0] = m_ref[...] + jnp.log(l)

    def call(qd, kd, vd, block=512):
        b, h, t, d = qd.shape
        nk = t // block
        qr = qd.reshape(b * h, t, d)
        ktr = kd.reshape(b * h, t, d).swapaxes(1, 2)
        vr = vd.reshape(b * h, t, d)
        out, _lse = pl.pallas_call(
            functools.partial(kernel, scale=d ** -0.5, nk=nk),
            grid=(b * h, t // block, nk),
            in_specs=[
                pl.BlockSpec((1, block, d), lambda bh, qi, ki: (bh, qi, 0)),
                pl.BlockSpec((1, d, block), lambda bh, qi, ki: (bh, 0, ki)),
                pl.BlockSpec((1, block, d), lambda bh, qi, ki: (bh, ki, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block, d), lambda bh, qi, ki: (bh, qi, 0)),
                pl.BlockSpec((1, block, 1), lambda bh, qi, ki: (bh, qi, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b * h, t, d), qd.dtype),
                jax.ShapeDtypeStruct((b * h, t, 1), jnp.float32),
            ],
            scratch_shapes=[pltpu.VMEM((block, 1), jnp.float32),
                            pltpu.VMEM((block, 1), jnp.float32),
                            pltpu.VMEM((block, d), jnp.float32)],
        )(qr, ktr, vr)
        return out.reshape(b, h, t, d)

    return call


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seq-lens", default="4096,8192")
    p.add_argument("--output", default=None)
    p.add_argument("--blocks", action="store_true",
                   help="sweep production-kernel block shapes instead of "
                        "the ablation kernels: wider K blocks mean fewer "
                        "per-block VPU reduction/rescale passes (the 49%% "
                        "softmax share the ablations measured)")
    p.add_argument("--grad", action="store_true",
                   help="with --blocks: time fwd+bwd instead of fwd")
    args = p.parse_args()

    import jax.numpy as jnp

    from mxnet_tpu.ops import pallas_kernels as pk

    def prod(bq, bk):
        return lambda q, k, v: pk.flash_attention(q, k, v,
                                                  block_q=bq,
                                                  block_k=bk)

    def block_variants(t):
        # the autotuner's candidate grid (mxnet_tpu.tune.kernels), not a
        # hand-rolled list — one sweep definition for bench and tool
        from mxnet_tpu.tune import kernels as tk
        spec = tk.get("flash_attention")
        sig = tk.signature("bfloat16", b=B, h=H, t=t, d=D)
        return {f"bq{p['block_q']}_bk{p['block_k']}":
                prod(p["block_q"], p["block_k"])
                for p in spec.grid(sig)}

    if not args.blocks:
        variants = {
            "full": lambda q, k, v: pk.flash_attention(q, k, v),
            "probe_ref": _variant_kernel("ref"),
            "noexp": _variant_kernel("noexp"),
            "nosoftmax": _variant_kernel("nosoftmax"),
            "bf16exp": _variant_kernel("bf16exp"),
        }

    rows = []
    for t in (int(x) for x in args.seq_lens.split(",")):
        if args.blocks:
            variants = block_variants(t)
        qkv = [jnp.asarray(onp.random.randn(B, H, t, D), jnp.bfloat16)
               for _ in range(3)]
        flops = 4.0 * B * H * t * t * D
        if args.grad:
            flops *= 3.5   # dq + dkv recompute + deltas, approx
        kind = "fwd_bwd" if args.grad else "fwd"
        for name, impl in variants.items():
            try:
                ms, n, ok = scan_ms(impl, qkv,
                                    grad="all" if args.grad else False)
                rows.append({
                    "metric": f"flash_roofline_{name}_{kind}_ms",
                    "seq_len": t, "value": round(ms, 3), "unit": "ms",
                    "tf_per_s": round(flops / (ms / 1e3) / 1e12, 1),
                    "scan_len": n, "reliable": ok,
                })
            except Exception as e:   # record, keep going
                rows.append({"metric": f"flash_roofline_{name}_error",
                             "seq_len": t, "error": str(e)[:160]})
            print(json.dumps(rows[-1]), flush=True)
    if args.blocks:
        if args.output:
            with open(args.output, "w") as f:
                json.dump(rows, f, indent=1)
        return
    # bf16exp accuracy vs the f32-exp probe (same ablation harness, so
    # the only difference IS the exp dtype)
    qkv = [jnp.asarray(onp.random.randn(B, H, 2048, D), jnp.bfloat16)
           for _ in range(3)]
    a = variants["probe_ref"](*qkv)
    bref = variants["bf16exp"](*qkv)
    err = float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                - bref.astype(jnp.float32))))
    rows.append({"metric": "flash_bf16exp_max_abs_err_vs_f32exp",
                 "seq_len": 2048, "value": err})
    print(json.dumps(rows[-1]), flush=True)
    if args.output:
        with open(args.output, "w") as f:
            json.dump(rows, f, indent=1)


if __name__ == "__main__":
    main()
