"""BASELINE config 5: the LSTM word language model on one chip.

The reference ships a fused RNN kernel as a *performance* feature
(`/root/reference/src/operator/rnn.cc:295`, cuDNN dispatch at
`rnn-inl.h:421`); here the LSTM lowers to `lax.scan` with the input
projection batched OUTSIDE the scan (one MXU matmul over all T,
`gluon/rnn/rnn_layer.py:_run_single_direction`), so the sequential part
is only the h→h recurrence.  This bench measures the classic
example/rnn "medium" word-LM shape — emb 650, 2×LSTM(650), tied-free
vocab head, bptt 35 — train step via FusedTrainStep, bf16, drained
windows (the repo-root ``bench.py`` documents the tunnel sync rationale).

Where scan-RNN lands vs the roofline (committed chip numbers:
``results/rnn_lm_tpu_v5e.json``; discussion in BERT_ANALYSIS.md
"Config 5" section):

- per-token train FLOPs = 3·2·[Σ_l 4H(in_l+H) + H·V] (3 = fwd + 2×bwd)
- the h→h matmul (B, H)x(H, 4H) inside the scan serializes over T
  steps/layer: at B=32, H=650 that is a 108-MFLOP matmul per step —
  big enough to keep the MXU busy, but every step pays the scan
  iteration latency, which is why tokens/s grows with batch.

Usage: python benchmark/rnn_lm_bench.py [--batch 32] [--bptt 35]
       [--output FILE]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

V, E, H, L = 10000, 650, 650, 2     # example/rnn "medium" (PTB vocab)
WARMUP = 5
PEAK_BF16 = 197e12


def flops_per_token():
    per_layer = [8.0 * H * (E + H), 8.0 * H * (H + H)]  # 2·4H·(in+H)
    fwd = sum(per_layer) + 2.0 * H * V                  # + vocab head
    return 3.0 * fwd                                    # fwd + bwd


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--bptt", type=int, default=35)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--output", default=None)
    p.add_argument("--pre-tune", type=float, default=None,
                   help="pre-autotune tokens/s baseline for this config; "
                        "records pre_tune_tokens_per_s + speedup_vs_pre_"
                        "tune in the artifact (PR 18 acceptance: b=32 "
                        ">= 1.5x)")
    args = p.parse_args()
    b, t = args.batch, args.bptt

    import mxnet_tpu as mx
    from mxnet_tpu.gluon import FusedTrainStep, Trainer, nn, rnn
    from mxnet_tpu.gluon.block import HybridBlock

    class WordLM(HybridBlock):
        def __init__(self):
            super().__init__()
            self.embed = nn.Embedding(V, E)
            self.lstm = rnn.LSTM(H, num_layers=L, layout="TNC",
                                 input_size=E)
            self.decoder = nn.Dense(V, flatten=False)

        def forward(self, data):          # (T, N) int tokens
            x = self.embed(data)
            out = self.lstm(x)
            return self.decoder(out)      # (T, N, V)

    class LMLoss(HybridBlock):
        def __init__(self, m):
            super().__init__()
            self.m = m

        def forward(self, data, target):
            logits = self.m(data)
            logp = mx.npx.log_softmax(logits.astype("float32"), axis=-1)
            return -mx.np.mean(mx.npx.pick(logp, target, axis=-1))

    model = WordLM()
    model.initialize()
    if args.dtype != "float32":
        model.cast(args.dtype)
    mod = LMLoss(model)
    data = mx.np.array(onp.random.randint(0, V, (t, b)), dtype="int32")
    target = mx.np.array(onp.random.randint(0, V, (t, b)), dtype="int32")
    trainer = Trainer(model.collect_params(), "sgd",
                      {"learning_rate": 1.0, "momentum": 0.9})
    step = FusedTrainStep(mod, trainer)

    for _ in range(WARMUP):
        loss = step(data, target, batch_size=b)
    loss.wait_to_read()
    mx.waitall()

    # drain-aware window sizing (shared): at b=32 a step is ~4 ms, and a
    # short window counts the ~100 ms tunnel drain as compute
    from timing_util import measured_step_s, window_iters
    iters = window_iters(measured_step_s(
        lambda: step(data, target, batch_size=b), mx.waitall))

    windows = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            step(data, target, batch_size=b)
        mx.waitall()
        windows.append(b * t * iters / (time.perf_counter() - t0))

    tok_s = max(windows)
    fpt = flops_per_token()
    result = {
        "metric": "lstm_word_lm_tokens_per_s",
        "value": round(tok_s),
        "unit": "tokens/s",
        "dtype": args.dtype,
        "batch": b, "bptt": t,
        "vocab": V, "emb": E, "hidden": H, "layers": L,
        "window_tokens_per_s": [round(w) for w in windows],
        "flops_per_token": round(fpt),
        "model_tflops_per_s": round(tok_s * fpt / 1e12, 2),
        "mfu_vs_197tf_bf16": round(tok_s * fpt / PEAK_BF16, 4),
        "steps_per_s": round(tok_s / (b * t), 2),
    }
    if args.pre_tune:
        result["pre_tune_tokens_per_s"] = round(args.pre_tune)
        result["speedup_vs_pre_tune"] = round(tok_s / args.pre_tune, 4)
    line = json.dumps(result)
    print(line, flush=True)
    if args.output:
        with open(args.output, "a") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
