"""Experiment: Pallas fused matmul + BN-stats epilogue vs XLA unfused.

MFU_ANALYSIS.md "what would move it" #1: the BN training stats (per-channel
sum / sum-of-squares) re-read the conv output from HBM after XLA's conv
kernel has written it.  For the 1x1 convolutions — more than half of
ResNet-50's layers, and exactly a (B*H*W, Cin) @ (Cin, Cout) matmul in
NHWC — a Pallas kernel can accumulate the channel statistics in VMEM as
the matmul epilogue streams tiles out, saving one full HBM read of the
activation per layer.

This script measures, per representative ResNet-50 1x1 shape at batch 128:
  (a) XLA: y = x @ w; s = sum(y); ss = sum(y*y)   (jitted together)
  (b) Pallas: fused kernel emitting y, s, ss in one pass
Timing is the shared scan-amortized discipline in timing_util /
mxnet_tpu.tune.sweep (block_until_ready is acked early by the tunnel).
Prints one JSON line per shape plus a summary.
"""
from __future__ import annotations

import functools
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as onp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from timing_util import scan_ms  # noqa: E402


def _fused_kernel(x_ref, w_ref, y_ref, s_ref, ss_ref, acc_s, acc_ss):
    mi = pl.program_id(1)
    y = jnp.dot(x_ref[:], w_ref[:],
                preferred_element_type=jnp.float32)

    @pl.when(mi == 0)
    def _init():
        acc_s[:] = jnp.zeros_like(acc_s)
        acc_ss[:] = jnp.zeros_like(acc_ss)

    acc_s[:] += jnp.sum(y, axis=0, keepdims=True)
    acc_ss[:] += jnp.sum(y * y, axis=0, keepdims=True)
    y_ref[:] = y.astype(y_ref.dtype)

    @pl.when(mi == pl.num_programs(1) - 1)
    def _finish():
        s_ref[:] = acc_s[:]
        ss_ref[:] = acc_ss[:]


def _pick_tile(m, target=512):
    tm = min(target, m)
    while m % tm or tm % 8:
        tm -= 8
    return max(tm, 8)


@functools.partial(jax.jit, static_argnames=("tm", "tn"))
def matmul_bn_stats_pallas(x, w, tm=None, tn=256):
    m, k = x.shape
    _, n = w.shape
    tn = min(tn, n)
    tm = tm or _pick_tile(m)
    grid = (n // tn, m // tm)  # m innermost: stats block stays resident
    return pl.pallas_call(
        _fused_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tm, k), lambda ni, mi: (mi, 0)),
            pl.BlockSpec((k, tn), lambda ni, mi: (0, ni)),
        ],
        out_specs=[
            pl.BlockSpec((tm, tn), lambda ni, mi: (mi, ni)),
            pl.BlockSpec((1, tn), lambda ni, mi: (0, ni)),
            pl.BlockSpec((1, tn), lambda ni, mi: (0, ni)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, n), x.dtype),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, tn), jnp.float32),
            pltpu.VMEM((1, tn), jnp.float32),
        ],
    )(x, w)


@jax.jit
def matmul_bn_stats_xla(x, w):
    y = jnp.dot(x, w, preferred_element_type=jnp.float32)
    s = jnp.sum(y, axis=0)
    ss = jnp.sum(y * y, axis=0)
    return y.astype(x.dtype), s, ss


SHAPES = [  # (M=B*H*W, K=Cin, N=Cout) for batch-128 ResNet-50 1x1 convs
    (128 * 56 * 56, 64, 256),
    (128 * 56 * 56, 256, 64),
    (128 * 28 * 28, 256, 512),
    (128 * 28 * 28, 512, 128),
    (128 * 14 * 14, 512, 1024),
    (128 * 14 * 14, 1024, 256),
    (128 * 7 * 7, 1024, 2048),
    (128 * 7 * 7, 2048, 512),
]


def main():
    rs = onp.random.RandomState(0)
    speedups = []
    for m, k, n in SHAPES:
        x = jax.device_put(rs.randn(m, k).astype(onp.float32).astype(
            jnp.bfloat16))
        w = jax.device_put(rs.randn(k, n).astype(onp.float32).astype(
            jnp.bfloat16))
        # correctness first
        y1, s1, ss1 = matmul_bn_stats_xla(x, w)
        y2, s2, ss2 = matmul_bn_stats_pallas(x, w)
        onp.testing.assert_allclose(onp.asarray(s1), onp.asarray(s2)[0],
                                    rtol=2e-2)
        onp.testing.assert_allclose(onp.asarray(y1, onp.float32),
                                    onp.asarray(y2, onp.float32), rtol=5e-2,
                                    atol=1.0)
        ms_xla, _, ok_xla = scan_ms(matmul_bn_stats_xla, (x, w))
        ms_pal, _, ok_pal = scan_ms(matmul_bn_stats_pallas, (x, w))
        speedups.append(ms_xla / ms_pal)
        print(json.dumps({
            "shape": [m, k, n],
            "xla_ms": round(ms_xla, 3),
            "pallas_ms": round(ms_pal, 3),
            "speedup": round(ms_xla / ms_pal, 3),
            "reliable": ok_xla and ok_pal,
        }), flush=True)
    print(json.dumps({"geomean_speedup": round(
        float(onp.exp(onp.mean(onp.log(speedups)))), 3)}))


if __name__ == "__main__":
    main()
