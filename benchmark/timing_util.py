"""Scan-amortized device timing through the tunnel (shared helper).

Through this environment's tunnel, `block_until_ready` acks early and a
host readback drain costs ~100 ms, so dispatch-loop timing buries every
sub-10 ms kernel (attention_bench.py documents the failure mode it
caused).  The fix used across benchmark/: chain N calls inside one
`lax.scan`, feeding a 1e-24-scaled summary of each output back into the
carry so nothing is hoisted or dead-coded, measure the drain separately
and subtract, and require scan work >= 2x drain for a `reliable` row.

The implementation now lives in ``mxnet_tpu.tune.sweep`` — the
autotuner's sweep runner — so the benches and ``tools/autotune`` share
ONE timing/trimming discipline.  This module is the benches' import
shim (benchmark/ is not a package).
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mxnet_tpu.tune.sweep import (  # noqa: E402,F401
    DRAIN_S,
    measured_step_s,
    scan_ms,
    trimmed_median,
    window_iters,
)
