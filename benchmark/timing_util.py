"""Scan-amortized device timing through the tunnel (shared helper).

Through this environment's tunnel, `block_until_ready` acks early and a
host readback drain costs ~100 ms, so dispatch-loop timing buries every
sub-10 ms kernel (attention_bench.py documents the failure mode it
caused).  The fix used across benchmark/: chain N calls inside one
`lax.scan`, feeding a 1e-24-scaled summary of each output back into the
carry so nothing is hoisted or dead-coded, measure the drain separately
and subtract, and require scan work >= 2x drain for a `reliable` row.
"""
from __future__ import annotations

import time

import numpy as onp


def scan_ms(impl, args, grad=False, max_seconds=12.0):
    """Per-call device ms of ``impl(*args)`` (or its value+grad when
    ``grad``), via a chained lax.scan.  Returns (ms, scan_len, reliable).

    The first element of ``args`` is the scan carry; the rest close over.
    """
    import jax
    import jax.numpy as jnp

    c0, rest = args[0], tuple(args[1:])

    if grad:
        gfn = jax.value_and_grad(
            lambda c, *r: impl(c, *r).sum().astype(jnp.float32),
            argnums=(0,))

        def body(c, _):
            val, (gc,) = gfn(c, *rest)
            dep = (val + gc.astype(jnp.float32).sum()) * 1e-24
            return c + dep.astype(c.dtype), None
    else:
        def body(c, _):
            out = impl(c, *rest)
            dep = jax.tree_util.tree_reduce(
                lambda a, x: a + x.astype(jnp.float32).sum(),
                out, jnp.float32(0.0)) * 1e-24
            return c + dep.astype(c.dtype), None

    def make(n):
        @jax.jit
        def run(c):
            c, _ = jax.lax.scan(body, c, None, length=n)
            return c
        return run

    def drain(x):
        onp.asarray(jax.tree_util.tree_leaves(x)[0].ravel()[0])

    drain(c0)
    t_sync = min((lambda t0: (drain(c0),
                              time.perf_counter() - t0)[1])(
        time.perf_counter()) for _ in range(3))

    run2 = make(2)
    drain(run2(c0))
    t0 = time.perf_counter()
    drain(run2(c0))
    est = max((time.perf_counter() - t0 - t_sync) / 2, 1e-5)
    n = int(min(max(6.0 * t_sync / est, 8), 4096, max_seconds / est))
    n = max(n, 8)
    for attempt in range(2):
        run_n = make(n)
        drain(run_n(c0))
        best = None
        for _ in range(3):
            t0 = time.perf_counter()
            drain(run_n(c0))
            best = min(best or 1e9, time.perf_counter() - t0)
        work = best - t_sync
        if work >= 2 * t_sync or attempt == 1:
            break
        per = max(work / n, 1e-7)
        n2 = int(min(max(6.0 * t_sync / per, n * 4), 4096,
                     max_seconds / per))
        if n2 == n:
            break
        n = n2
    return max(work / n, 1e-9) * 1e3, n, work >= 2 * t_sync


DRAIN_S = 0.1   # one ~100 ms tunnel readback per window (see module doc)


def window_iters(est_step_s, target_s=3.0, min_iters=10, max_iters=5000):
    """Size a throughput window from a measured per-step time so the
    tunnel drain stays a small fraction of it (~3% at the 3 s default).
    Shared by the FusedTrainStep-style benches (bert_pretrain / rnn_lm /
    lenet_mnist) so the drain-avoidance logic lives in one place.  The
    iteration cap is a runaway guard only — it must stay far above
    target_s / fastest-real-step (~2 ms) or it would silently
    re-shorten windows for exactly the benches this exists for."""
    return int(min(max(target_s / max(est_step_s, 1e-4), min_iters),
                   max_iters))


def measured_step_s(run_step, drain, n=3):
    """Per-step seconds from ``n`` steps + one drain (DRAIN_S subtracted)
    — the probe every bench feeds into :func:`window_iters`."""
    import time
    t0 = time.perf_counter()
    for _ in range(n):
        run_step()
    drain()
    return max((time.perf_counter() - t0 - DRAIN_S) / n, 1e-3)
