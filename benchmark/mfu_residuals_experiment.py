"""ResNet-50 MFU residual levers, measured (round-3 verdict weak #2).

MFU_ANALYSIS.md names the two levers left between the 2.65k img/s
operating point (~17% MFU) and the 3.77k img/s identity-BN bound, plus a
batch lever. This experiment measures all three with the same-window
interleaving methodology (drift cancels; see bench.py _bench_ab):

(a) **BN f32 intermediate**: a variant that keeps the normalize math in
    bf16 (stats still accumulate in f32 via `jnp.sum(..., dtype=f32)`)
    vs the baseline's f32 elementwise chain. Evidence at two levels:
    end-to-end img/s, and per-layer `cost_analysis()` bytes-accessed +
    HLO convert census on a ResNet-representative BN shape.
(b) **BN backward residual policy**: recompute-xhat (baseline: bwd
    re-reads `data` and recomputes xhat) vs store-xhat (fwd writes xhat,
    bwd reads it — trades a fwd write for bwd compute).
(c) **Batch**: 128 / 192 / 256 interleaved; 512 attempted last
    (expected RESOURCE_EXHAUSTED on the shared 16 GB chip — recorded
    either way).

Usage:  python benchmark/mfu_residuals_experiment.py
        [--skip-model] [--batches 128,192,256] [--output FILE]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

# mxlint: disable-file=env-read-at-trace-time -- benchmark orchestration: MFU_BATCH_PROBE is the parent<->child subprocess protocol, read host-side before any compilation

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

WARMUP = 6
ITERS = 20
ROUNDS = 3


# ---------------------------------------------------------------------------
# BN variants (same API as ops/nn.py batch_norm_train)
# ---------------------------------------------------------------------------
def _make_variants():
    import jax
    import jax.numpy as jnp
    from jax import lax

    from mxnet_tpu.ops import nn as _nn

    def shape_of(data, axis):
        s = [1] * data.ndim
        s[axis] = data.shape[axis]
        return tuple(s)

    # -- variant A: bf16 normalize math, f32-accumulated stats ------------
    def bn_bf16_fwd(data, gamma, beta, moving_mean, moving_var, momentum,
                    eps, axis):
        red = tuple(i for i in range(data.ndim) if i != axis)
        n = 1
        for i in red:
            n *= data.shape[i]
        # ONE bf16 read; f32 accumulation happens inside the reductions
        s1 = jnp.sum(data, axis=red, dtype=jnp.float32)
        s2 = jnp.sum(jnp.square(data.astype(jnp.float32)), axis=red)
        mean = s1 / n
        var = jnp.maximum(s2 / n - mean * mean, 0.0)
        inv = lax.rsqrt(var + eps)
        a = (gamma.astype(jnp.float32) * inv).astype(data.dtype)
        b = (beta.astype(jnp.float32) - mean * gamma.astype(jnp.float32)
             * inv).astype(data.dtype)
        sh = shape_of(data, axis)
        out = data * a.reshape(sh) + b.reshape(sh)   # bf16 multiply-add
        new_mean = moving_mean * momentum + \
            mean.astype(moving_mean.dtype) * (1 - momentum)
        new_var = moving_var * momentum + \
            var.astype(moving_var.dtype) * (1 - momentum)
        return (out, new_mean, new_var), (data, gamma, mean, inv)

    def bn_bf16_bwd(momentum, eps, axis, res, cts):
        data, gamma, mean, inv = res
        dy, d_mm, d_mv = cts
        red = tuple(i for i in range(data.ndim) if i != axis)
        n = 1
        for i in red:
            n *= data.shape[i]
        sh = shape_of(data, axis)
        m16 = mean.astype(data.dtype)
        i16 = inv.astype(data.dtype)
        xhat = (data - m16.reshape(sh)) * i16.reshape(sh)    # bf16
        sum_dy = jnp.sum(dy, axis=red, dtype=jnp.float32)
        sum_dy_xhat = jnp.sum((dy * xhat).astype(jnp.float32), axis=red)
        a = (gamma.astype(jnp.float32) * inv).astype(data.dtype)
        dx = a.reshape(sh) * (
            dy - (sum_dy / n).astype(data.dtype).reshape(sh) -
            xhat * (sum_dy_xhat / n).astype(data.dtype).reshape(sh))
        return (dx, sum_dy_xhat.astype(gamma.dtype),
                sum_dy.astype(gamma.dtype), d_mm * momentum,
                d_mv * momentum)

    # custom_vjp with nondiff momentum/eps/axis, mirroring ops/nn.py
    bn_bf16_core = jax.custom_vjp(
        lambda data, gamma, beta, mm, mv, momentum, eps, axis:
        bn_bf16_fwd(data, gamma, beta, mm, mv, momentum, eps, axis)[0],
        nondiff_argnums=(5, 6, 7))
    bn_bf16_core.defvjp(
        lambda data, gamma, beta, mm, mv, momentum, eps, axis:
        bn_bf16_fwd(data, gamma, beta, mm, mv, momentum, eps, axis),
        bn_bf16_bwd)

    def batch_norm_train_bf16(data, gamma, beta, momentum, eps, axis,
                              moving_mean, moving_var):
        return bn_bf16_core(data, gamma, beta, moving_mean, moving_var,
                            momentum, eps, axis)

    # -- variant B: store-xhat residuals (bwd reads xhat, not data) -------
    def bn_store_fwd(data, gamma, beta, moving_mean, moving_var, momentum,
                     eps, axis):
        (out, new_mean, new_var), (d, g, mean, inv) = _nn._bn_train_fwd(
            data, gamma, beta, moving_mean, moving_var, momentum, eps, axis)
        sh = shape_of(data, axis)
        cdt = jnp.promote_types(data.dtype, jnp.float32)
        xhat = ((data.astype(cdt) - mean.reshape(sh)) *
                inv.reshape(sh)).astype(data.dtype)
        return (out, new_mean, new_var), (xhat, g, inv)

    def bn_store_bwd(momentum, eps, axis, res, cts):
        xhat16, gamma, inv = res
        dy, d_mm, d_mv = cts
        red = tuple(i for i in range(xhat16.ndim) if i != axis)
        n = 1
        for i in red:
            n *= xhat16.shape[i]
        sh = shape_of(xhat16, axis)
        cdt = jnp.promote_types(xhat16.dtype, jnp.float32)
        dyf = dy.astype(cdt)
        xhat = xhat16.astype(cdt)
        sum_dy = jnp.sum(dyf, axis=red)
        sum_dy_xhat = jnp.sum(dyf * xhat, axis=red)
        a = (gamma.astype(cdt) * inv).reshape(sh)
        dx = a * (dyf - (sum_dy / n).reshape(sh) -
                  xhat * (sum_dy_xhat / n).reshape(sh))
        return (dx.astype(xhat16.dtype), sum_dy_xhat.astype(gamma.dtype),
                sum_dy.astype(gamma.dtype), d_mm * momentum, d_mv * momentum)

    bn_store_core = jax.custom_vjp(
        lambda data, gamma, beta, mm, mv, momentum, eps, axis:
        bn_store_fwd(data, gamma, beta, mm, mv, momentum, eps, axis)[0],
        nondiff_argnums=(5, 6, 7))
    bn_store_core.defvjp(
        lambda data, gamma, beta, mm, mv, momentum, eps, axis:
        bn_store_fwd(data, gamma, beta, mm, mv, momentum, eps, axis),
        bn_store_bwd)

    def batch_norm_train_store(data, gamma, beta, momentum, eps, axis,
                               moving_mean, moving_var):
        return bn_store_core(data, gamma, beta, moving_mean, moving_var,
                             momentum, eps, axis)

    return {"baseline": _nn.batch_norm_train,
            "bf16_norm": batch_norm_train_bf16,
            "store_xhat": batch_norm_train_store}


# ---------------------------------------------------------------------------
# part 1: per-layer cost analysis at a ResNet-representative shape
# ---------------------------------------------------------------------------
def layer_analysis(variants):
    import jax
    import jax.numpy as jnp

    B, C, H, W = 128, 256, 56, 56
    x = jnp.asarray(onp.random.randn(B, C, H, W), jnp.bfloat16)
    g = jnp.ones((C,), jnp.float32)
    b = jnp.zeros((C,), jnp.float32)
    mm = jnp.zeros((C,), jnp.float32)
    mv = jnp.ones((C,), jnp.float32)
    rows = []
    for name, bn in variants.items():
        def loss(x, g, b, bn=bn):
            out, _nm, _nv = bn(x, g, b, 0.9, 1e-5, 1, mm, mv)
            return jnp.sum(out.astype(jnp.float32))

        from mxnet_tpu.analysis import compiled_cost_summary

        comp = jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(
            x, g, b).compile()
        cs = compiled_cost_summary(comp)
        hlo = comp.as_text()
        rows.append({
            "experiment": "bn_layer_fwd_bwd", "variant": name,
            "shape": [B, C, H, W],
            "bytes_accessed": cs["bytes_accessed"],
            "flops": cs["flops"],
            "hlo_f32_big_buffers": sum(
                1 for l in hlo.splitlines()
                if f"f32[{B},{C}" in l.replace(" ", "")),
            "hlo_convert_count": hlo.count("convert("),
        })
        print(json.dumps(rows[-1]), flush=True)
    return rows


# ---------------------------------------------------------------------------
# part 2: full-model interleaved windows
# ---------------------------------------------------------------------------
def model_ab(variants, batch, rounds=ROUNDS):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.ops import nn as _nn

    from bench import _net_with_loss_classes

    NetWithLoss, _ = _net_with_loss_classes()
    net = vision.resnet50_v1()
    net.initialize(init=mx.init.Xavier())
    net.cast("bfloat16")
    lf = gloss.SoftmaxCrossEntropyLoss()
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1, "momentum": 0.9},
                               kvstore="device")
    rs = onp.random.RandomState(0)
    x = mx.np.array(rs.uniform(-1, 1, (batch, 3, 224, 224)),
                    dtype="bfloat16")
    y = mx.np.array(rs.randint(0, 1000, (batch,)), dtype="int32")

    steps = {}
    orig = _nn.batch_norm_train
    for name, bn in variants.items():
        # each variant needs its own traced program; the patch is active
        # only during this variant's compile (trace happens on first call)
        _nn.batch_norm_train = bn
        mod = NetWithLoss(net, lf)
        step = mx.gluon.FusedTrainStep(mod, trainer)
        for _ in range(WARMUP):
            step(x, y, batch_size=batch)
        mx.waitall()
        _nn.batch_norm_train = orig
        steps[name] = step

    def window(step):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            step(x, y, batch_size=batch)
        mx.waitall()
        return batch * ITERS / (time.perf_counter() - t0)

    per = {name: [] for name in steps}
    for _round in range(rounds):
        for name, step in steps.items():
            per[name].append(window(step))
    rows = []
    base = max(per["baseline"])
    for name, rates in per.items():
        rows.append({
            "experiment": "resnet50_train_interleaved", "batch": batch,
            "variant": name, "img_per_s": round(max(rates), 1),
            "rounds": [round(r, 1) for r in rates],
            "vs_baseline": round(max(rates) / base, 4),
        })
        print(json.dumps(rows[-1]), flush=True)
    return rows


# ---------------------------------------------------------------------------
# part 3: batch sweep (subprocess per batch: OOM poisons the client)
# ---------------------------------------------------------------------------
def batch_probe(batch):
    """Child mode: one batch, baseline BN, prints img/s or exits 42."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo import vision

    from bench import _net_with_loss_classes

    NetWithLoss, _ = _net_with_loss_classes()
    try:
        net = vision.resnet50_v1()
        net.initialize(init=mx.init.Xavier())
        net.cast("bfloat16")
        mod = NetWithLoss(net, gloss.SoftmaxCrossEntropyLoss())
        trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                                   {"learning_rate": 0.1, "momentum": 0.9},
                                   kvstore="device")
        step = mx.gluon.FusedTrainStep(mod, trainer)
        rs = onp.random.RandomState(0)
        x = mx.np.array(rs.uniform(-1, 1, (batch, 3, 224, 224)),
                        dtype="bfloat16")
        y = mx.np.array(rs.randint(0, 1000, (batch,)), dtype="int32")
        for _ in range(WARMUP):
            step(x, y, batch_size=batch)
        mx.waitall()
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(ITERS):
                step(x, y, batch_size=batch)
            mx.waitall()
            best = max(best, batch * ITERS / (time.perf_counter() - t0))
        print(json.dumps({"experiment": "batch_sweep", "batch": batch,
                          "img_per_s": round(best, 1)}))
    except Exception as e:
        if "RESOURCE_EXHAUSTED" in str(e):
            print(json.dumps({"experiment": "batch_sweep", "batch": batch,
                              "error": "RESOURCE_EXHAUSTED"}))
            sys.exit(42)
        raise


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batches", default="128,192,256,512")
    p.add_argument("--skip-model", action="store_true")
    p.add_argument("--skip-batch-sweep", action="store_true")
    p.add_argument("--output",
                   default=os.path.join(os.path.dirname(__file__),
                                        "results",
                                        "mfu_residuals_tpu_v5e.json"))
    args = p.parse_args()

    if os.environ.get("MFU_BATCH_PROBE"):
        batch_probe(int(os.environ["MFU_BATCH_PROBE"]))
        return

    rows = []
    variants = _make_variants()
    rows += layer_analysis(variants)
    if not args.skip_model:
        rows += model_ab(variants, 128)
    if not args.skip_batch_sweep:
        import subprocess
        for b in (int(x) for x in args.batches.split(",")):
            env = dict(os.environ, MFU_BATCH_PROBE=str(b))
            proc = subprocess.run([sys.executable,
                                   os.path.abspath(__file__)], env=env,
                                  stdout=subprocess.PIPE, text=True,
                                  timeout=1800)
            got_row = False
            for line in proc.stdout.splitlines():
                line = line.strip()
                if line.startswith("{"):
                    rows.append(json.loads(line))
                    print(line, flush=True)
                    got_row = True
            if not got_row or proc.returncode not in (0, 42):
                # a crashed probe must be a visible row, not a silent gap
                row = {"experiment": "batch_sweep", "batch": b,
                       "error": f"probe exited {proc.returncode}"}
                rows.append(row)
                print(json.dumps(row), flush=True)
    os.makedirs(os.path.dirname(args.output), exist_ok=True)
    with open(args.output, "w") as f:
        json.dump(rows, f, indent=1)
    print(f"wrote {args.output}", flush=True)


if __name__ == "__main__":
    main()
