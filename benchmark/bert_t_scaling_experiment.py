"""Why does BERT MFU sag from 35.6% (T=128) to 26.9% (T=512)?

Round-4 verdict #1: the 9-point drop is batch-invariant and was "the
next lever to profile, not yet explained".  This experiment explains it
with the mfu_residuals methodology: every comparison is a PAIR of
compiled programs interleaved in ONE process window (drift cancels;
separate windows differ ±10% through the tunnel), one subprocess per
pair so a shared-HBM OOM can't poison the rest.

Pairs (all dense attention, B·T = 4096 tokens/step):

  sag        base128  vs base512        the effect itself, same-window
  drop512    base512  vs nodrop512      attention-dropout RNG+mask cost
  drop128    base128  vs nodrop128      (scales with B·H·T² = tokens·H·T,
                                        so its per-token cost grows with T)
  attn512    base512  vs noattn512      attention-mix excised: q/k/v/proj
  attn128    base128  vs noattn128      matmuls kept (damped by 1e-30 so
                                        XLA can't DCE them), score/softmax/
                                        dropout/context removed
  head512    base512  vs bf16head512    MLM log-softmax: f32 upcast vs
  head128    base128  vs bf16head128    bf16 with f32-accumulated sum

Each pair reports per-round tokens/s for both variants and the median
same-round ratio.  Attribution logic: if excising X closes the sag by
the same number of points at T=512 but not T=128, X is the T-scaling
cost.  Results: `results/bert_t_scaling_tpu_v5e.json`, discussion in
BERT_ANALYSIS.md (round-5 section).
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# This experiment ATTRIBUTES the round-4 numbers, whose dropout masks
# were threefry; production now defaults to the hardware RNG (the change
# this experiment motivated, ops/nn.py:_dropout_key).  Pin the old
# default so base*/nodrop* still measure what the analysis describes and
# the rbg pairs stay threefry-vs-rbg comparisons.
os.environ["MXNET_DROPOUT_RNG"] = "threefry"

L, U, V = 12, 768, 30522
WARMUP = 5
ITERS = 25
ROUNDS = 3
PEAK = 197e12

CONFIGS = {
    # name: (B, T, dropout, surgery)
    "base128": (32, 128, 0.1, None),
    "base512": (8, 512, 0.1, None),
    "nodrop128": (32, 128, 0.0, None),
    "nodrop512": (8, 512, 0.0, None),
    "noattn128": (32, 128, 0.1, "noattn"),
    "noattn512": (8, 512, 0.1, "noattn"),
    "bf16head128": (32, 128, 0.1, "bf16head"),
    "bf16head512": (8, 512, 0.1, "bf16head"),
    "rbgdrop128": (32, 128, 0.1, "rbgdrop"),
    "rbgdrop512": (8, 512, 0.1, "rbgdrop"),
}

PAIRS = {
    "sag": ("base128", "base512"),
    # the decisive pair: if the sag vanishes without dropout, the whole
    # T-scaling cost IS the attention-dropout chain
    "sag_nodrop": ("nodrop128", "nodrop512"),
    "drop512": ("base512", "nodrop512"),
    "drop128": ("base128", "nodrop128"),
    "attn512": ("base512", "noattn512"),
    "attn128": ("base128", "noattn128"),
    "head512": ("base512", "bf16head512"),
    "head128": ("base128", "bf16head128"),
    # same Bernoulli semantics, hardware RNG stream: isolates "threefry
    # bits are expensive" from "the dropout chain breaks XLA fusion"
    "rbg512": ("base512", "rbgdrop512"),
    "rbg128": ("base128", "rbgdrop128"),
}


def _flops_per_token(n_dense, t, with_attention=True):
    return 6.0 * n_dense + (12.0 * L * U * t if with_attention else 0.0)


def _build_step(name):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import FusedTrainStep, Trainer
    from mxnet_tpu.gluon.block import HybridBlock
    from mxnet_tpu.models import BertForPretraining
    from mxnet_tpu.models import transformer as tr

    b, t, drop, surgery = CONFIGS[name]

    if surgery == "rbgdrop":
        # force the hardware-RNG key re-wrap (the production
        # ops.nn._dropout_key with impl pinned), regardless of the
        # threefry baseline env this process runs under
        from mxnet_tpu.ops import nn as _nnops
        _orig_dropout = _nnops.dropout

        def rbg_dropout(data, key, p=0.5, axes=None, mode="training"):
            if p == 0.0 or mode != "training":
                return data
            return _orig_dropout(data, _nnops._dropout_key(key, impl="rbg"),
                                 p=p, axes=axes, mode=mode)
        _nnops.dropout = rbg_dropout

    if surgery == "noattn":
        # keep all four dense projections live (1e-30 damping defeats the
        # algebraic simplifier without letting q/k affect the result),
        # drop the score/softmax/attn-dropout/context chain — the only
        # parts whose cost scales with T at fixed B·T
        def noattn_forward(self, x, mask=None):
            q = self.query(x)
            k = self.key(x)
            v = self.value(x)
            return self.proj(v + (q + k) * 1e-30)
        tr.MultiHeadAttention.forward = noattn_forward

    model = BertForPretraining(vocab_size=V, units=U, hidden_size=3072,
                               num_layers=L, num_heads=12,
                               max_length=512, dropout=drop,
                               use_flash=False)
    model.initialize()
    model.cast("bfloat16")

    bf16_head = surgery == "bf16head"

    class PretrainLoss(HybridBlock):
        def __init__(self, m):
            super().__init__()
            self.m = m

        def forward(self, tokens, segments, labels):
            mlm_logits, nsp_logits = self.m(tokens, segments)
            if bf16_head:
                # bf16 shift/exp with f32-accumulated sum: skips the
                # 2·(B·T·V) f32 materialisation (~1 GB/step at T=512)
                s = mlm_logits - mx.np.max(mlm_logits, axis=-1,
                                           keepdims=True)
                lse = mx.np.log(mx.np.sum(mx.np.exp(s), axis=-1,
                                          keepdims=True,
                                          dtype="float32"))
                logp = s.astype("float32") - lse
            else:
                logp = mx.npx.log_softmax(
                    mlm_logits.astype("float32"), axis=-1)
            mlm = -mx.np.mean(mx.npx.pick(logp, labels, axis=-1))
            nsp = -mx.np.mean(
                mx.npx.log_softmax(nsp_logits.astype("float32"))[:, 0])
            return mlm + nsp

    mod = PretrainLoss(model)
    tokens = mx.np.array(onp.random.randint(0, V, (b, t)), dtype="int32")
    segments = mx.np.array(onp.zeros((b, t)), dtype="int32")
    labels = mx.np.array(onp.random.randint(0, V, (b, t)), dtype="int32")
    trainer = Trainer(model.collect_params(), "adam",
                      {"learning_rate": 1e-4})
    step = FusedTrainStep(mod, trainer)

    for _ in range(WARMUP):
        step(tokens, segments, labels, batch_size=b)
    mx.waitall()

    params = model.collect_params()
    n_total = sum(int(onp.prod(p.shape)) for p in params.values())
    n_embed = sum(int(onp.prod(p.shape)) for pn, p in params.items()
                  if "embed" in pn.lower())
    n_dense = n_total - n_embed + U * V
    assert n_total > 100e6

    def run_window():
        t0 = time.perf_counter()
        for _ in range(ITERS):
            step(tokens, segments, labels, batch_size=b)
        import mxnet_tpu as _mx
        _mx.waitall()
        return b * t * ITERS / (time.perf_counter() - t0)

    return run_window, n_dense, b, t


def run_pair(pair):
    a_name, b_name = PAIRS[pair]
    run_a, nd_a, ba, ta = _build_step(a_name)
    # surgery monkeypatches are process-global; a pair never mixes two
    # different surgeries (base is always the A side), but B must build
    # AFTER A so a surgery B-side patch doesn't leak into A's trace
    run_b, nd_b, bb, tb = _build_step(b_name)

    rows = []
    ratios = []
    for r in range(ROUNDS):
        tok_a = run_a()
        tok_b = run_b()
        ratios.append(tok_b / tok_a)
        rows.append({"round": r, a_name: round(tok_a), b_name: round(tok_b)})
    ratios.sort()
    med = ratios[len(ratios) // 2]

    def mfu(tok, nd, t, attn=True):
        return round(tok * _flops_per_token(nd, t, attn) / PEAK, 4)

    out = {
        "experiment": f"bert_t_scaling:{pair}",
        "pair": [a_name, b_name],
        "rounds": rows,
        "median_ratio_b_over_a": round(med, 4),
        "mfu_a": mfu(max(r[a_name] for r in rows), nd_a, ta),
        "mfu_b": mfu(max(r[b_name] for r in rows), nd_b, tb,
                     attn=not b_name.startswith("noattn")),
    }
    print(json.dumps(out), flush=True)
    return out


def run_census():
    """Compiled-program census of the isolated dense-attention subgraph
    (exactly MultiHeadAttention's einsum path) fwd+bwd, with and without
    attention dropout, at T=128 and T=512: XLA cost_analysis flops /
    bytes accessed + transcendental count.  Distinguishes 'threefry bits
    are expensive' (flops/transcendentals jump) from 'dropout breaks
    fusion' (bytes jump)."""
    import jax
    import jax.numpy as jnp

    h, d = 12, 64
    out = {"experiment": "bert_t_scaling:census", "rows": []}
    for (b, t) in ((32, 128), (8, 512)):
        for drop in (0.0, 0.1):
            def attn_loss(q, k, v, key):
                s = jnp.einsum("bthd,bshd->bhts", q, k) / (d ** 0.5)
                a = jax.nn.softmax(s, axis=-1)
                if drop:
                    m = jax.random.bernoulli(key, 1 - drop, a.shape)
                    a = jnp.where(m, a / (1 - drop), 0).astype(a.dtype)
                o = jnp.einsum("bhts,bshd->bthd", a, v)
                return (o.astype(jnp.float32) ** 2).sum()

            g = jax.jit(jax.grad(attn_loss, argnums=(0, 1, 2)))
            args_ = [jnp.ones((b, t, h, d), jnp.bfloat16)] * 3 + [
                jax.random.key(0)]
            from mxnet_tpu.analysis import compiled_cost_summary
            cs = compiled_cost_summary(g.lower(*args_).compile())
            out["rows"].append({"batch": b, "seq": t, "dropout": drop, **cs})
    print(json.dumps(out), flush=True)
    return out


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--pair", default=None, choices=sorted(PAIRS))
    p.add_argument("--pairs", default=None,
                   help="comma-separated subset to run (default: all)")
    p.add_argument("--census", action="store_true")
    p.add_argument("--output", default=None)
    args = p.parse_args()

    if args.census:
        row = run_census()
        if args.output:
            merged = [row]
            if os.path.exists(args.output):
                old = json.load(open(args.output))
                merged = [r for r in old
                          if r["experiment"] != row["experiment"]] + [row]
            with open(args.output, "w") as f:
                json.dump(merged, f, indent=1)
        return
    if args.pair:
        run_pair(args.pair)
        return

    rows = []
    wanted = args.pairs.split(",") if args.pairs else list(PAIRS)
    for pair in wanted:
        for attempt in range(2):
            res = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--pair", pair],
                capture_output=True, text=True, timeout=2400)
            lines = [ln for ln in res.stdout.splitlines()
                     if ln.startswith("{")]
            if lines:
                rows.append(json.loads(lines[-1]))
                break
            err = (res.stderr or "")[-400:]
            err_row = {"experiment": f"bert_t_scaling:{pair}",
                       "error": err}
            print(json.dumps(err_row), flush=True)
            if "UNAVAILABLE" in err and attempt == 0:
                time.sleep(90)   # shared worker restart
                continue
            # a failed re-run must not leave the pair's STALE row in the
            # artifact looking fresh — the error row replaces it
            rows.append(err_row)
            break
    if args.output:
        merged = rows
        if os.path.exists(args.output):
            # merge with prior pairs: latest run of a pair wins
            old = json.load(open(args.output))
            have = {r["experiment"] for r in rows}
            merged = [r for r in old if r["experiment"] not in have] + rows
        with open(args.output, "w") as f:
            json.dump(merged, f, indent=1)


if __name__ == "__main__":
    main()
