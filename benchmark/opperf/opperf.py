"""Per-operator forward/backward benchmark harness.

Reference: `benchmark/opperf/opperf.py` (runs every registered op with
default shapes, times fwd/bwd via the profiler, dumps md/json tables used
as a perf-regression gate).

TPU-native design: each op is timed twice — `eager` (per-call dispatch
through the imperative tape, the cost a user pays op-at-a-time) and
`jit` (the op compiled alone, measuring the XLA kernel itself).  The gap
between the two columns is the dispatch overhead the reference's engine
bulking hides, which on TPU is the argument for `hybridize()`.

Usage:
    python benchmark/opperf/opperf.py [--category elemwise,nn,...]
        [--output results.json] [--iters 50] [--dtype float32]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as onp

# runnable from a checkout without installation, like the reference harness
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def _corpus(dtype):
    """op name -> (category, fn(mx) -> (callable, args...)) with
    reference-comparable default shapes (benchmark/opperf/rules/
    default_params.py uses 1024x1024 style shapes)."""
    import mxnet_tpu as mx
    npx = mx.npx
    np_ = mx.np

    def arr(*shape):
        return np_.array(onp.random.uniform(-1, 1, shape).astype(dtype))

    big = (1024, 1024)
    conv_x = (32, 64, 56, 56)

    ops = {
        # elemwise / broadcast (reference src/operator/tensor/)
        "add": ("elemwise", lambda: (lambda a, b: a + b, arr(*big), arr(*big))),
        "mul": ("elemwise", lambda: (lambda a, b: a * b, arr(*big), arr(*big))),
        "exp": ("elemwise", lambda: (np_.exp, arr(*big))),
        "tanh": ("elemwise", lambda: (np_.tanh, arr(*big))),
        "broadcast_add": ("elemwise",
                          lambda: (lambda a, b: a + b, arr(*big), arr(1024))),
        # reduce
        "sum": ("reduce", lambda: (np_.sum, arr(*big))),
        "mean_axis": ("reduce", lambda: (lambda a: np_.mean(a, axis=1),
                                         arr(*big))),
        "argmax": ("reduce", lambda: (lambda a: np_.argmax(a, axis=1),
                                      arr(*big))),
        # gemm (MXU)
        "dot": ("gemm", lambda: (np_.dot, arr(*big), arr(*big))),
        "batch_dot": ("gemm", lambda: (npx.batch_dot,
                                       arr(32, 256, 256), arr(32, 256, 256))),
        "fully_connected": ("gemm", lambda: (
            lambda x, w, b: npx.fully_connected(x, w, b, num_hidden=1024),
            arr(128, 1024), arr(1024, 1024), arr(1024))),
        # nn (reference src/operator/nn/)
        "convolution": ("nn", lambda: (
            lambda x, w: npx.convolution(x, w, kernel=(3, 3), pad=(1, 1),
                                         num_filter=64),
            arr(*conv_x), arr(64, 64, 3, 3))),
        "pooling": ("nn", lambda: (
            lambda x: npx.pooling(x, kernel=(2, 2), stride=(2, 2),
                                  pool_type="max"), arr(*conv_x))),
        "softmax": ("nn", lambda: (npx.softmax, arr(128, 1024))),
        "layer_norm": ("nn", lambda: (
            lambda x, g, b: npx.layer_norm(x, g, b), arr(128, 1024),
            arr(1024), arr(1024))),
        "relu": ("nn", lambda: (npx.relu, arr(*conv_x))),
        # indexing
        "topk": ("indexing", lambda: (
            lambda a: npx.topk(a, k=10, axis=1), arr(*big))),
        "take": ("indexing", lambda: (
            np_.take, arr(*big),
            np_.array(onp.random.randint(0, 1024, 4096).astype("int32")))),
        "one_hot": ("indexing", lambda: (
            lambda i: npx.one_hot(i, 1024),
            np_.array(onp.random.randint(0, 1024, 4096).astype("int32")))),
        # --- round-3 breadth (VERDICT r2 #5): toward the reference
        # corpus's categories (mxnet_operator_benchmark_results_cpu.md) ---
        # unary elemwise
        "sqrt": ("elemwise", lambda: (np_.sqrt,
                                      np_.abs(arr(*big)) + 0.1)),
        "log": ("elemwise", lambda: (np_.log, np_.abs(arr(*big)) + 0.1)),
        "sigmoid": ("elemwise", lambda: (npx.sigmoid, arr(*big))),
        "abs": ("elemwise", lambda: (np_.abs, arr(*big))),
        "negative": ("elemwise", lambda: (np_.negative, arr(*big))),
        "floor": ("elemwise", lambda: (np_.floor, arr(*big))),
        "clip": ("elemwise", lambda: (
            lambda a: np_.clip(a, -0.5, 0.5), arr(*big))),
        "gelu": ("elemwise", lambda: (npx.gelu, arr(*big))),
        "erf": ("elemwise", lambda: (npx.erf, arr(*big))),
        # binary elemwise
        "sub": ("elemwise", lambda: (lambda a, b: a - b,
                                     arr(*big), arr(*big))),
        "div": ("elemwise", lambda: (lambda a, b: a / b, arr(*big),
                                     np_.abs(arr(*big)) + 0.5)),
        "power": ("elemwise", lambda: (
            np_.power, np_.abs(arr(*big)) + 0.1, arr(*big))),
        "maximum": ("elemwise", lambda: (np_.maximum,
                                         arr(*big), arr(*big))),
        "broadcast_mul": ("elemwise", lambda: (
            lambda a, b: a * b, arr(*big), arr(1024))),
        # reduce
        "max": ("reduce", lambda: (np_.max, arr(*big))),
        "min": ("reduce", lambda: (np_.min, arr(*big))),
        "prod": ("reduce", lambda: (
            lambda a: np_.prod(a, axis=1), np_.abs(arr(*big)) + 0.5)),
        "var": ("reduce", lambda: (lambda a: np_.var(a, axis=1),
                                   arr(*big))),
        "norm": ("reduce", lambda: (
            lambda a: np_.linalg.norm(a, axis=1), arr(*big))),
        "argmin": ("reduce", lambda: (lambda a: np_.argmin(a, axis=1),
                                      arr(*big))),
        "cumsum": ("reduce", lambda: (lambda a: np_.cumsum(a, axis=1),
                                      arr(*big))),
        # gemm / linalg
        "dot_transb": ("gemm", lambda: (
            lambda a, b: np_.dot(a, b.T), arr(*big), arr(*big))),
        "einsum_bmm": ("gemm", lambda: (
            lambda a, b: np_.einsum("bij,bjk->bik", a, b),
            arr(32, 256, 256), arr(32, 256, 256))),
        "linalg_gemm2": ("gemm", lambda: (
            lambda a, b: mx.nd.linalg.gemm2(a, b), arr(*big), arr(*big))),
        "linalg_potrf": ("linalg", lambda: (
            lambda a: mx.nd.linalg.potrf(
                np_.matmul(a, a.T) / 32.0 +
                np_.array(onp.eye(256, dtype=dtype) * 4)),
            arr(256, 256))),
        "linalg_trsm": ("linalg", lambda: (
            lambda a, b: mx.nd.linalg.trsm(a, b),
            np_.array(onp.tril(onp.random.uniform(
                0.5, 1, (256, 256))).astype(dtype) +
                2 * onp.eye(256, dtype=dtype)),
            arr(256, 256))),
        "linalg_syrk": ("linalg", lambda: (
            lambda a: mx.nd.linalg.syrk(a), arr(256, 512))),
        "cholesky_inverse": ("linalg", lambda: (
            lambda a: np_.linalg.inv(
                np_.matmul(a, a.T) / 32.0 +
                np_.array(onp.eye(256, dtype=dtype) * 4)),
            arr(256, 256))),
        # nn
        "batch_norm": ("nn", lambda: (
            lambda x, g, b, m, v: npx.batch_norm(
                x, g, b, m, v, use_global_stats=True),
            arr(*conv_x), arr(64), np_.abs(arr(64)) + 0.5,
            arr(64), np_.abs(arr(64)) + 0.5)),
        "group_norm": ("nn", lambda: (
            lambda x, g, b: npx.group_norm(x, g, b, num_groups=8),
            arr(*conv_x), arr(64), arr(64))),
        "log_softmax": ("nn", lambda: (npx.log_softmax, arr(128, 1024))),
        "leaky_relu": ("nn", lambda: (
            lambda x: npx.leaky_relu(x, act_type="leaky", slope=0.1),
            arr(*conv_x))),
        "deconvolution": ("nn", lambda: (
            lambda x, w: npx.deconvolution(x, w, kernel=(3, 3),
                                           num_filter=64),
            arr(32, 64, 28, 28), arr(64, 64, 3, 3))),
        "depthwise_conv": ("nn", lambda: (
            lambda x, w: npx.convolution(x, w, kernel=(3, 3), pad=(1, 1),
                                         num_filter=64, num_group=64),
            arr(*conv_x), arr(64, 1, 3, 3))),
        "embedding": ("nn", lambda: (
            lambda i, w: npx.embedding(i, w),
            np_.array(onp.random.randint(0, 1024, (128, 32)).astype(
                "int32")), arr(1024, 512))),
        "sequence_mask": ("nn", lambda: (
            lambda x: npx.sequence_mask(
                x, np_.array(onp.full((32,), 20, "float32")),
                use_sequence_length=True),
            arr(24, 32, 512))),
        "avg_pooling": ("nn", lambda: (
            lambda x: npx.pooling(x, kernel=(2, 2), stride=(2, 2),
                                  pool_type="avg"), arr(*conv_x))),
        "global_pooling": ("nn", lambda: (
            lambda x: npx.pooling(x, global_pool=True, pool_type="avg"),
            arr(*conv_x))),
        # transform
        "transpose": ("transform", lambda: (
            lambda a: np_.transpose(a, (1, 0)), arr(*big))),
        "reshape": ("transform", lambda: (
            lambda a: np_.reshape(a, (512, 2048)), arr(*big))),
        "concat": ("transform", lambda: (
            lambda a, b: np_.concatenate([a, b], axis=1),
            arr(*big), arr(*big))),
        "stack2": ("transform", lambda: (
            lambda a, b: np_.stack([a, b]), arr(*big), arr(*big))),
        "split2": ("transform", lambda: (
            lambda a: np_.split(a, 2, axis=1)[0], arr(*big))),
        "tile": ("transform", lambda: (
            lambda a: np_.tile(a, (2, 1)), arr(*big))),
        "repeat": ("transform", lambda: (
            lambda a: np_.repeat(a, 2, axis=0), arr(512, 1024))),
        "flip": ("transform", lambda: (
            lambda a: np_.flip(a, axis=1), arr(*big))),
        "pad2d": ("transform", lambda: (
            lambda a: np_.pad(a, ((0, 0), (0, 0), (1, 1), (1, 1))),
            arr(32, 64, 56, 56))),
        "where": ("transform", lambda: (
            lambda c, a, b: np_.where(c > 0, a, b),
            arr(*big), arr(*big), arr(*big))),
        "expand_dims": ("transform", lambda: (
            lambda a: np_.expand_dims(a, 0), arr(*big))),
        # sorting
        "sort": ("sorting", lambda: (
            lambda a: np_.sort(a, axis=1), arr(*big))),
        "argsort": ("sorting", lambda: (
            lambda a: np_.argsort(a, axis=1), arr(*big))),
        # random (stateless key per call folds into the scan carry)
        "random_uniform": ("random", lambda: (
            lambda a: a + mx.np.random.uniform(size=(1024, 1024)),
            arr(*big))),
        "random_normal": ("random", lambda: (
            lambda a: a + mx.np.random.normal(size=(1024, 1024)),
            arr(*big))),
        # optimizer update kernels (reference optimizer_op.cc)
        "sgd_mom_update": ("optimizer", lambda: (
            lambda w, g, m: mx.nd.sgd_mom_update(w, g, m, lr=0.1,
                                                 momentum=0.9),
            arr(*big), arr(*big), arr(*big))),
        "adam_update": ("optimizer", lambda: (
            lambda w, g, m, v: mx.nd.adam_update(w, g, m, v, lr=1e-3),
            arr(*big), arr(*big), arr(*big),
            np_.abs(arr(*big)) + 0.01)),
        # image ops
        "image_to_tensor": ("image", lambda: (
            mx.nd.image.to_tensor,
            np_.array(onp.random.randint(
                0, 255, (32, 224, 224, 3)).astype("uint8")))),
        "image_normalize": ("image", lambda: (
            lambda x: mx.nd.image.normalize(x, mean=(0.5, 0.5, 0.5),
                                            std=(0.2, 0.2, 0.2)),
            arr(32, 3, 224, 224))),
        # attention building blocks
        "interleaved_selfatt_qk": ("attention", lambda: (
            lambda qkv: mx.nd.contrib.interleaved_matmul_selfatt_qk(
                qkv, heads=8),
            arr(128, 8, 8 * 64 * 3))),
        "masked_softmax": ("attention", lambda: (
            lambda x: npx.masked_softmax(
                x, np_.array(onp.ones((64, 128, 128), "bool"))),
            arr(64, 128, 128))),
    }
    return ops


def _window(fn, n, sync, t_sync):
    t0 = time.perf_counter()
    for _ in range(n):
        fn()
    sync()
    return max(time.perf_counter() - t0 - t_sync, 1e-9) / n


_SMOKE = False  # harness smoke: tiny fixed windows, no adaptive growth


class _NotDifferentiable(Exception):
    """Sentinel: the op has no float input/output to differentiate —
    distinct from real fwd+bwd failures (r4 review finding: a generic
    ValueError catch would let vjp regressions masquerade as this)."""


def _time(fn, iters, *, sync):
    """Best-of-3 windows, iteration count adapted so the op work dominates
    the drain: the drain is a host round trip (~100 ms with ±tens of ms of
    jitter through a tunneled chip), so a fixed small count would measure
    the tunnel, not the op."""
    fn()  # warmup / compile
    sync()
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        sync()
        samples.append(time.perf_counter() - t0)
    t_sync = min(samples)

    if _SMOKE:
        return _window(fn, 3, sync, t_sync) * 1e6, True

    est = _window(fn, max(iters, 10), sync, t_sync)
    n = min(max(iters, int(4 * t_sync / est) + 1), 500_000)
    # grow the window until op work dominates the drain (round-3 fix:
    # a single shot left most rows below the 2-drain reliability bar
    # when the first estimate ran fast)
    best = None
    for _attempt in range(4):
        best = min(_window(fn, n, sync, t_sync) for _ in range(3))
        if best * n >= 2 * t_sync or n >= 500_000:
            break
        n = min(int(max(3 * t_sync / max(best, 1e-9), n * 4)), 500_000)
    reliable = best * n >= 2 * t_sync
    return best * 1e6, reliable  # us


def _scan_time(fn, datas, hint_us=None, grad=False):
    """Per-op kernel time via `lax.scan` on device.

    The op's output is folded back into its first float input with a
    ~1e-24 perturbation, so every iteration depends on the previous one
    (no hoisting/DCE) while numerics stay put.  Returns (us, reliable);
    ops with no float input fall through as unreliable single-dispatch.

    With ``grad=True`` each scan iteration runs forward AND backward —
    `jax.grad` of sum(float outputs) w.r.t. every float input — so the
    column is a reliable jitted fwd+bwd kernel time (round-3 verdict
    weak #4: the tape-based `fwd_bwd_us` is dispatch-dominated and would
    hide a backward kernel regression under tunnel noise).  All gradient
    outputs fold into the carry, so no part of the backward is DCE'd.
    Raises at trace time for non-differentiable ops (no float output).
    """
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ndarray.ndarray import NDArray

    chain = next((i for i, d in enumerate(datas)
                  if hasattr(d, "dtype") and d.dtype.kind == "f"), None)
    if chain is None:
        if grad:
            raise _NotDifferentiable("no float input")
        return _fallback_single_dispatch(fn, datas)

    def _float_leaves(out):
        leaves = [o._data if isinstance(o, NDArray) else o
                  for o in (out if isinstance(out, (tuple, list)) else
                            [out])]
        return [l for l in leaves
                if hasattr(l, "dtype") and
                jnp.issubdtype(l.dtype, jnp.floating)]

    if grad:
        float_idx = [i for i, d in enumerate(datas)
                     if hasattr(d, "dtype") and d.dtype.kind == "f"]
        chain_pos = float_idx.index(chain)

        def loss_fn(*fl):
            ins = list(datas)
            for j, i in enumerate(float_idx):
                ins[i] = fl[j]
            fleaves = _float_leaves(fn(*[NDArray(d) for d in ins]))
            if not fleaves:
                raise _NotDifferentiable("no float output")
            total = fleaves[0].astype(jnp.float32).sum()
            for l in fleaves[1:]:
                total = total + l.astype(jnp.float32).sum()
            return total

        # value_and_grad, with BOTH the loss value and every gradient
        # folded into the carry: grad alone would let XLA dead-code the
        # forward pass for linear ops (grad of sum(x@w) w.r.t. x never
        # computes x@w), and the column would time backward only
        grad_fn = jax.value_and_grad(loss_fn,
                                     argnums=tuple(range(len(float_idx))))

        def body(carry, _):
            fl = [datas[i] for i in float_idx]
            fl[chain_pos] = carry
            val, grads = grad_fn(*fl)
            dep = (val + sum(jnp.sum(g.astype(jnp.float32))
                             for g in grads)) * 1e-24
            return carry + dep.astype(carry.dtype), None

        # trace once up front so non-differentiable ops raise here, not
        # inside the timed compile
        jax.eval_shape(lambda c: body(c, None), datas[chain])
    else:
        def body(carry, _):
            ins = list(datas)
            ins[chain] = carry
            out = fn(*[NDArray(d) for d in ins])
            leaves = [o._data if isinstance(o, NDArray) else o
                      for o in (out if isinstance(out, (tuple, list)) else
                                [out])]
            leaf = next(l for l in leaves if hasattr(l, "dtype"))
            dep = jnp.sum(leaf.astype(jnp.float32)) * 1e-24
            return carry + dep.astype(carry.dtype), None

    def make(k):
        @jax.jit
        def run_k(c):
            c, _ = jax.lax.scan(body, c, None, length=k)
            return c
        return run_k

    c0 = datas[chain]

    def drain(x):
        onp.asarray(jax.tree_util.tree_leaves(x)[0].ravel()[0])

    # the readback itself costs ~100 ms through the tunnel; measure it on
    # an already-materialized value and SUBTRACT it everywhere, otherwise
    # it owns every number (the round-1 failure mode)
    drain(c0)
    t_sync = min((lambda t0: (drain(c0), time.perf_counter() - t0)[1])(
        time.perf_counter()) for _ in range(3))

    if _SMOKE:
        run_k = make(4)
        drain(run_k(c0))
        t0 = time.perf_counter()
        drain(run_k(c0))
        return (time.perf_counter() - t0) / 4 * 1e6, True

    # each distinct scan length is a fresh XLA compile, and through the
    # tunnel a compile costs ~40 s — so compiles, not device time, budget
    # this harness.  A caller-provided per-iteration hint (eager timing
    # for the fwd column, the measured fwd kernel time for the grad
    # column) sizes the first scan directly; without one, fall back to a
    # small estimation loop (one extra compile).
    if hint_us is not None and hint_us > 0:
        # eager hints overestimate the kernel (dispatch-dominated): guess
        # hint/8 per iteration; an oversized k only costs device seconds,
        # an undersized one costs a recompile
        per = max(hint_us / 8.0, 1e-3) * 1e-6
        k = int(min(max(2.5 * t_sync / per, 2048), 20_000_000))
    else:
        k = 4096
        run_k = make(k)
        drain(run_k(c0))  # compile
        t0 = time.perf_counter()
        drain(run_k(c0))
        est = max((time.perf_counter() - t0 - t_sync) / k, 1e-9)
        k = int(min(max(3 * t_sync / est, 4096), 20_000_000))

    run_k = make(k)
    drain(run_k(c0))  # compile
    best = None
    for _attempt in range(2):
        best = None
        for _ in range(2):
            t0 = time.perf_counter()
            drain(run_k(c0))
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        work = best - t_sync
        # rescale only when another timed attempt will actually run —
        # recompiling on the way out would divide old-k work by new k
        # (r4 review finding)
        if work >= 2 * t_sync or k >= 20_000_000 or _attempt == 1:
            break
        k = int(min(max(k * 3 * t_sync / max(work, 1e-4), k * 4),
                    20_000_000))
        run_k = make(k)
        drain(run_k(c0))  # one rescale compile when the hint was far off
    work = best - t_sync
    reliable = work >= 2 * t_sync
    return max(work, 0.0) / k * 1e6, reliable


def _fallback_single_dispatch(fn, datas):
    from mxnet_tpu.ndarray.ndarray import NDArray
    import jax

    def jfn():
        out = fn(*[NDArray(d) for d in datas])
        return out._data if isinstance(out, NDArray) else out
    jj = jax.jit(lambda: jfn())

    def sync():
        out = jj()
        onp.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[0])
    return _time(lambda: jj(), 50, sync=sync)


def _dump(results, output):
    """Incremental write: a timeout/crash keeps every row measured so
    far (incl. error rows)."""
    if output:
        with open(output, "w") as f:
            json.dump(results, f, indent=2)


def _error_row(name, cat, e):
    # keep the schema stable: error rows carry the timing keys too
    return {"op": name, "category": cat, "error": str(e)[:200],
            "eager_us": None, "jit_us": None, "fwd_bwd_jit_us": None,
            "fwd_bwd_us": None, "reliable": False}


_DEAD_BACKEND = ("UNAVAILABLE", "crashed or restarted", "DataLoss",
                 "Socket closed")


def _backend_dead(e):
    s = str(e)
    return any(m in s for m in _DEAD_BACKEND)


def run(categories=None, iters=50, dtype="float32", warmup=None, ops=None,
        output=None, resume=None):
    import mxnet_tpu as mx
    import jax

    results = list(resume or [])
    done = {r["op"] for r in results if "error" not in r}
    for name, (cat, make) in _corpus(dtype).items():
        if categories and cat not in categories:
            continue
        if ops and name not in ops:
            continue
        if name in done:
            continue
        results = [r for r in results if r["op"] != name]  # replace errors
        try:
            fn, *args = make()
        except Exception as e:
            if _backend_dead(e):
                # the device client is gone: every later op would emit the
                # same junk row — stop so a fresh process can --resume
                _dump(results, output)
                raise
            print(f"{name:20s} {cat:9s} SETUP ERROR: {e}", flush=True)
            results.append(_error_row(name, cat, e))
            _dump(results, output)
            continue

        try:
            # eager: imperative dispatch per call (tape + device dispatch)
            eager_us, eager_ok = _time(lambda: fn(*args), iters,
                                       sync=mx.waitall)

            # jit: the compiled kernel, timed as a DEVICE-SIDE scan loop —
            # one dispatch runs K data-chained iterations, so the per-op
            # number is pure kernel time and the tunnel's dispatch
            # latency/jitter divides away (VERDICT r1: single dispatches
            # made 16/19 rows unreliable)
            datas = [a._data for a in args]
            jit_us, jit_ok = _scan_time(fn, datas, hint_us=eager_us)
        except Exception as e:
            if _backend_dead(e):
                _dump(results, output)
                raise
            print(f"{name:20s} {cat:9s} RUN ERROR: {e}", flush=True)
            results.append(_error_row(name, cat, e))
            _dump(results, output)
            continue

        # jitted fwd+bwd: jax.grad inside the same device-side scan, so
        # backward kernel time gets the same reliability treatment as
        # forward (round-3 verdict weak #4); None = not differentiable
        fbj_us, fbj_ok = None, True
        try:
            # the measured fwd kernel time is a tight hint: bwd ≈ 2-3x fwd
            fbj_us, fbj_ok = _scan_time(fn, datas, grad=True,
                                        hint_us=24 * max(jit_us, 0.5))
        except _NotDifferentiable:
            pass
        except Exception as e:
            if _backend_dead(e):
                _dump(results, output)
                raise
            # a real fwd+bwd failure must not masquerade as "not
            # differentiable" (r4 review finding)
            print(f"{name:20s} {cat:9s} FWD+BWD ERROR: {e}", flush=True)
            fbj_ok = False


        # fwd+bwd through the tape where the op is differentiable
        # (eager-dispatch cost, kept for the dispatch-overhead story)
        bwd_us = None
        try:
            for a in args:
                if a._data.dtype.kind == "f":
                    a.attach_grad()

            def step():
                with mx.autograd.record():
                    out = fn(*args)
                out.backward()
                return out
            bwd_us, _bwd_ok = _time(step, max(1, iters // 5),
                                    sync=mx.waitall)
        except Exception as e:
            if _backend_dead(e):
                _dump(results, output)
                raise

        row = {"op": name, "category": cat, "eager_us": round(eager_us, 1),
               "jit_us": round(jit_us, 1),
               "fwd_bwd_jit_us": None if fbj_us is None else round(fbj_us, 1),
               "fwd_bwd_us": None if bwd_us is None else round(bwd_us, 1),
               "reliable": bool(eager_ok and jit_ok and fbj_ok and
                                (bwd_us is None or _bwd_ok))}
        results.append(row)
        print(f"{name:20s} {cat:9s} eager {row['eager_us']:>10} us   "
              f"jit {row['jit_us']:>10} us   "
              f"fwd+bwd(jit) {row['fwd_bwd_jit_us'] or '-':>10}   "
              f"fwd+bwd {row['fwd_bwd_us'] or '-':>10}", flush=True)
        _dump(results, output)
    return results


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--category", default=None,
                   help="comma-separated: elemwise,reduce,gemm,nn,indexing")
    p.add_argument("--iters", type=int, default=50)
    p.add_argument("--dtype", default="float32")
    p.add_argument("--output", default=None, help="write JSON results here")
    p.add_argument("--smoke", action="store_true",
                   help="harness-regression smoke: a handful of ops, "
                        "assert every row completes (numbers not "
                        "meaningful on CPU)")
    p.add_argument("--ops", default=None,
                   help="comma-separated op-name filter")
    p.add_argument("--resume", action="store_true",
                   help="keep completed rows in --output; re-run error "
                        "rows and missing ops (device-crash recovery)")
    args = p.parse_args()
    cats = set(args.category.split(",")) if args.category else None
    ops = set(args.ops.split(",")) if args.ops else None
    if args.smoke:
        global _SMOKE
        _SMOKE = True
        ops = {"add", "dot", "softmax", "transpose", "sgd_mom_update"}
    resume = None
    if args.resume and args.output and os.path.exists(args.output):
        with open(args.output) as f:
            resume = json.load(f)
    results = run(cats, args.iters, args.dtype, ops=ops,
                  output=args.output, resume=resume)
    if args.smoke:
        assert len(results) == len(ops), (len(results), ops)
        for r in results:
            assert "error" not in r, f"smoke op failed: {r}"
            assert r["jit_us"] is not None and r["jit_us"] >= 0, r
            if r["op"] in ("add", "dot", "softmax"):
                assert r["fwd_bwd_jit_us"] is not None and \
                    r["fwd_bwd_jit_us"] >= 0, r
        print("opperf smoke OK")
    if args.output:
        # run() already wrote the file incrementally after every row
        print(f"wrote {args.output}")


if __name__ == "__main__":
    main()
