"""BERT-base pretraining throughput + MFU on one chip (BASELINE config 4).

MLM+NSP loss over the Gluon BERT, bf16, batch 32 x seq 128, driven by
`gluon.FusedTrainStep` (one XLA program per step).  Prints one JSON line
(best of three fully-drained windows; see bench.py for the sync
rationale) carrying tokens/s AND model-FLOPs-utilization against the
chip's 197 TF/s bf16 peak, so the transformer perf story is judged the
same way the ResNet one is (MFU_ANALYSIS.md / BERT_ANALYSIS.md).

The measured configuration is RECIPE-REALISTIC (round 6): padded
variable-length batches (ragged valid lengths, MLPerf-BERT-style) with
the padding mask threaded through attention, and attention dropout 0.1
— the configuration MLPerf-style BERT actually trains under.  The flash
tier runs both in-kernel, so long-T runs stay on the fast path instead
of silently falling back to the dense O(T^2) softmax (``--unmasked``
restores the old idealized A/B configuration).

MFU accounting: training FLOPs/token = 6·N_dense (fwd+bwd weight
matmuls; N_dense excludes embedding tables, whose forward is a gather)
+ 12·L·U·T attention-score/context FLOPs.  The MLM head's vocab
projection (tied embedding, U×V matmul) IS dense compute and dominates
at T=128 — it is counted in N_dense.  Tokens/s counts B·T slots (padded
included) so numbers stay comparable across rounds; the JSON also
carries the mean valid occupancy.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

B, T = 32, 128
L, U, V = 12, 768, 30522
WARMUP = 6
ITERS = 30
PEAK_BF16 = 197e12  # one v5e chip


def flops_per_token(n_dense, t):
    # 6 FLOPs per dense weight per token (2 fwd + 4 bwd) + attention
    # scores/context: 2 matmuls of 2·t·U each, fwd+bwd -> 12·t·U per
    # layer per token
    return 6.0 * n_dense + 12.0 * L * U * t


def main():
    global B, T
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--output", default=None)
    p.add_argument("--batch", type=int, default=B)
    p.add_argument("--seq", type=int, default=T)
    p.add_argument("--dp", type=int, default=0,
                   help="data-parallel mesh size (multi-host runs)")
    p.add_argument("--use-flash", default="auto",
                   choices=("auto", "true", "false"),
                   help="auto (measured crossovers) | true | false")
    p.add_argument("--remat", action="store_true",
                   help="rematerialization boundary around each encoder "
                        "layer (npx.remat): backward recomputes "
                        "activations, memory O(layers) -> O(1)")
    p.add_argument("--unmasked", action="store_true",
                   help="idealized A/B configuration: full-length batches, "
                        "no padding mask, no attention dropout (the pre-"
                        "round-6 setup)")
    args = p.parse_args()
    B, T = args.batch, args.seq

    import mxnet_tpu as mx
    from mxnet_tpu.gluon import FusedTrainStep, Trainer
    from mxnet_tpu.gluon.block import HybridBlock
    from mxnet_tpu.models import BertForPretraining

    use_flash = {"auto": "auto", "true": True, "false": False}[args.use_flash]
    # the recipe-realistic headline keeps the reference's dropout=0.1 at
    # EVERY T — the flash tier applies attention dropout (and the padding
    # mask) in-kernel, so long-T no longer needs a dropout-free carve-out
    drop = 0.0 if args.unmasked else 0.1
    model = BertForPretraining(vocab_size=V, units=U, hidden_size=3072,
                               num_layers=L, num_heads=12,
                               max_length=max(512, T), dropout=drop,
                               use_flash=use_flash, remat=args.remat)
    model.initialize()
    model.cast("bfloat16")

    class PretrainLoss(HybridBlock):
        def __init__(self, m):
            super().__init__()
            self.m = m

        def forward(self, tokens, segments, labels, valid_mask=None):
            mlm_logits, nsp_logits = self.m(tokens, segments, valid_mask)
            logp = mx.npx.log_softmax(mlm_logits.astype("float32"), axis=-1)
            picked = mx.npx.pick(logp, labels, axis=-1)
            if valid_mask is None:
                mlm = -mx.np.mean(picked)
            else:
                # padded positions carry no loss (MLPerf-style accounting)
                m = valid_mask.astype("float32")
                mlm = -(picked * m).sum() / m.sum()
            nsp = -mx.np.mean(
                mx.npx.log_softmax(nsp_logits.astype("float32"))[:, 0])
            return mlm + nsp

    mod = PretrainLoss(model)
    tokens = mx.np.array(onp.random.randint(0, V, (B, T)), dtype="int32")
    segments = mx.np.array(onp.zeros((B, T)), dtype="int32")
    labels = mx.np.array(onp.random.randint(0, V, (B, T)), dtype="int32")
    if args.unmasked:
        batch = (tokens, segments, labels)
        occupancy = 1.0
    else:
        # ragged MLPerf-style padding: valid prefixes in [T/2, T]
        lens = onp.random.RandomState(11).randint(T // 2, T + 1, size=B)
        mask_np = (onp.arange(T)[None, :] < lens[:, None])
        occupancy = float(mask_np.mean())
        batch = (tokens, segments, labels,
                 mx.np.array(mask_np.astype(onp.int32), dtype="int32"))
    trainer = Trainer(model.collect_params(), "adam", {"learning_rate": 1e-4})
    mesh = None
    if args.dp:
        from mxnet_tpu.parallel import mesh as pmesh
        mesh = pmesh.make_mesh({"dp": args.dp})
    step = FusedTrainStep(mod, trainer, mesh=mesh)

    for _ in range(WARMUP):
        loss = step(*batch, batch_size=B)
    loss.wait_to_read()
    mx.waitall()

    # size the window from a measured step so it dwarfs the ~100 ms
    # tunnel drain (a 0.85 s window at T=128 understated tokens/s ~10%)
    from timing_util import measured_step_s, window_iters
    global ITERS
    ITERS = window_iters(measured_step_s(
        lambda: step(*batch, batch_size=B), mx.waitall))

    # dense-param count for MFU: everything except the embedding tables
    # (their forward is a gather, not a matmul; the TIED mlm vocab
    # projection is a real U x V matmul and is added back explicitly).
    # Counted AFTER warmup: deferred shape inference leaves ~75 dense
    # params shapeless until the first forward materialises them.
    params = model.collect_params()
    n_total = sum(int(onp.prod(p.shape)) for p in params.values())
    n_embed = sum(int(onp.prod(p.shape)) for name, p in params.items()
                  if "embed" in name.lower())
    n_dense = n_total - n_embed + U * V  # + tied vocab projection matmul
    assert n_total > 100e6, f"param shapes not materialised: {n_total}"

    windows = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            step(*batch, batch_size=B)
        mx.waitall()
        windows.append(B * T * ITERS / (time.perf_counter() - t0))

    tok_s = max(windows)
    fpt = flops_per_token(n_dense, T)
    n_chips = max(args.dp, 1)  # tok_s is the global rate on a dp mesh
    result = {
        "metric": "bert_base_pretrain_bf16_tokens_per_s",
        "value": round(tok_s, 0),
        "unit": "tokens/s",
        "use_flash": args.use_flash,
        "remat": args.remat,
        "dropout": drop,
        "masked": not args.unmasked,
        "valid_occupancy": round(occupancy, 4),
        "batch": B, "seq_len": T,
        "window_tokens_per_s": [round(w) for w in windows],
        "params_total": n_total,
        "params_dense_for_mfu": int(n_dense),
        "flops_per_token": round(fpt),
        "n_chips": n_chips,
        "model_tflops_per_s": round(tok_s * fpt / 1e12, 2),
        "mfu_vs_197tf_bf16": round(tok_s * fpt / (PEAK_BF16 * n_chips), 4),
    }
    line = json.dumps(result)
    print(line)
    if args.output:
        with open(args.output, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
