"""BERT-base pretraining throughput on one chip (BASELINE config 4 path).

MLM+NSP loss over the Gluon BERT, bf16, batch 32 x seq 128, driven by
`gluon.FusedTrainStep` (one XLA program per step).  Prints one JSON line;
best of three fully-drained windows (see bench.py for the sync rationale).
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

B, T = 32, 128
WARMUP = 6
ITERS = 30


def main():
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import FusedTrainStep, Trainer
    from mxnet_tpu.gluon.block import HybridBlock
    from mxnet_tpu.models import BertForPretraining

    model = BertForPretraining(vocab_size=30522, units=768, hidden_size=3072,
                               num_layers=12, num_heads=12, max_length=512,
                               dropout=0.1)
    model.initialize()
    model.cast("bfloat16")

    class PretrainLoss(HybridBlock):
        def __init__(self, m):
            super().__init__()
            self.m = m

        def forward(self, tokens, segments, labels):
            mlm_logits, nsp_logits = self.m(tokens, segments)
            logp = mx.npx.log_softmax(mlm_logits.astype("float32"), axis=-1)
            mlm = -mx.np.mean(mx.npx.pick(logp, labels, axis=-1))
            nsp = -mx.np.mean(
                mx.npx.log_softmax(nsp_logits.astype("float32"))[:, 0])
            return mlm + nsp

    mod = PretrainLoss(model)
    tokens = mx.np.array(onp.random.randint(0, 30522, (B, T)), dtype="int32")
    segments = mx.np.array(onp.zeros((B, T)), dtype="int32")
    labels = mx.np.array(onp.random.randint(0, 30522, (B, T)), dtype="int32")
    trainer = Trainer(model.collect_params(), "adam", {"learning_rate": 1e-4})
    step = FusedTrainStep(mod, trainer)

    for _ in range(WARMUP):
        loss = step(tokens, segments, labels, batch_size=B)
    loss.wait_to_read()
    mx.waitall()

    windows = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            step(tokens, segments, labels, batch_size=B)
        mx.waitall()
        windows.append(B * T * ITERS / (time.perf_counter() - t0))

    print(json.dumps({
        "metric": "bert_base_pretrain_bf16_tokens_per_s",
        "value": round(max(windows), 0),
        "unit": "tokens/s",
        "batch": B, "seq_len": T,
        "window_tokens_per_s": [round(w) for w in windows],
    }))


if __name__ == "__main__":
    main()
