"""BASELINE config 1: Gluon LeNet on MNIST-shaped data, one chip.

Trivial by FLOPs (the model is ~0.4 MFLOP/image forward) — the number
this config actually measures is the framework's per-step overhead at
small scale: Gluon model → FusedTrainStep → one donated XLA program.
Drained windows, bf16.  Reference entrypoint: `example/gluon/mnist.py`
(ctx=mx.gpu() → the TPU context here).

Usage: python benchmark/lenet_mnist_bench.py [--batch 256] [--output F]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

WARMUP = 5


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--output", default=None)
    args = p.parse_args()
    b = args.batch

    import mxnet_tpu as mx
    from mxnet_tpu.gluon import FusedTrainStep, Trainer, nn
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.block import HybridBlock

    net = nn.HybridSequential()
    net.add(nn.Conv2D(20, kernel_size=5, activation="tanh"),
            nn.MaxPool2D(pool_size=2, strides=2),
            nn.Conv2D(50, kernel_size=5, activation="tanh"),
            nn.MaxPool2D(pool_size=2, strides=2),
            nn.Flatten(),
            nn.Dense(500, activation="tanh"),
            nn.Dense(10))
    net.initialize()
    if args.dtype != "float32":
        net.cast(args.dtype)

    class WithLoss(HybridBlock):
        def __init__(self, m):
            super().__init__()
            self.m = m
            self.loss = gloss.SoftmaxCrossEntropyLoss()

        def forward(self, x, y):
            return self.loss(self.m(x), y).mean()

    mod = WithLoss(net)
    x = mx.np.array(onp.random.rand(b, 1, 28, 28), dtype=args.dtype)
    y = mx.np.array(onp.random.randint(0, 10, (b,)), dtype="int32")
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})
    step = FusedTrainStep(mod, trainer)

    for _ in range(WARMUP):
        loss = step(x, y, batch_size=b)
    loss.wait_to_read()
    mx.waitall()

    # drain-aware window sizing (shared helper; LeNet steps are ~2-3 ms)
    from timing_util import measured_step_s, window_iters
    iters = window_iters(measured_step_s(
        lambda: step(x, y, batch_size=b), mx.waitall))

    windows = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            step(x, y, batch_size=b)
        mx.waitall()
        windows.append(b * iters / (time.perf_counter() - t0))

    result = {
        "metric": "lenet_mnist_train_imgs_per_s",
        "value": round(max(windows)),
        "unit": "imgs/s",
        "dtype": args.dtype, "batch": b,
        "window_imgs_per_s": [round(w) for w in windows],
        "steps_per_s": round(max(windows) / b, 1),
    }
    line = json.dumps(result)
    print(line, flush=True)
    if args.output:
        with open(args.output, "a") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
