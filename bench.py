"""Headline benchmark: ResNet-50 training throughput (img/s) on one chip.

Baseline (BASELINE.md / reference `docs/.../faq/perf.md:252-254`): MXNet-CUDA
ResNet-50 fp32 training on V100 ≈ 364 img/s.  This drives the framework's
user-facing path — Gluon model zoo + bf16 cast (the TPU-native operating
point, as fp16 was for V100) + hybridized net-with-loss block + autograd +
Trainer(sgd) — on synthetic ImageNet-shaped data, and prints ONE JSON line.

Batch 128 bf16 fits the 16GB HBM; the whole step is 3 XLA dispatches
(forward, backward, fused optimizer), which matters when the chip sits
behind a network tunnel.
"""
from __future__ import annotations

import json
import time

import numpy as onp

BASELINE_IMG_PER_S = 363.69  # V100 fp32 train (batch-128 row; ~flat in batch)
BATCH = 128
WARMUP = 5
ITERS = 30


def main():
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.block import HybridBlock
    from mxnet_tpu.gluon.model_zoo import vision

    class NetWithLoss(HybridBlock):
        def __init__(self, net, loss_fn):
            super().__init__()
            self.net = net
            self.loss_fn = loss_fn

        def forward(self, x, y):
            return self.loss_fn(self.net(x), y)

    net = vision.resnet50_v1()
    net.initialize(init=mx.init.Xavier())
    net.cast("bfloat16")
    mod = NetWithLoss(net, gloss.SoftmaxCrossEntropyLoss())
    mod.hybridize(static_alloc=True)

    x = mx.np.array(onp.random.uniform(-1, 1, (BATCH, 3, 224, 224)),
                    dtype="bfloat16")
    y = mx.np.array(onp.random.randint(0, 1000, (BATCH,)), dtype="int32")

    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1, "momentum": 0.9},
                               kvstore="device")

    def step():
        with mx.autograd.record():
            loss = mod(x, y)
        loss.backward()
        trainer.step(BATCH)
        return loss

    for _ in range(WARMUP):
        loss = step()
    loss.wait_to_read()

    # best of three windows: the chip sits behind a shared tunnel whose
    # load varies run to run; peak throughput is the capability number.
    # waitall() drains ALL queued work (not just the last loss buffer) so
    # no window's tail bleeds into the next window's timer.
    mx.waitall()
    windows = []
    for _window in range(3):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            step()
        mx.waitall()
        windows.append(BATCH * ITERS / (time.perf_counter() - t0))

    img_per_s = max(windows)
    print(json.dumps({
        "metric": "resnet50_train_bf16_img_per_s",
        "value": round(img_per_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_per_s / BASELINE_IMG_PER_S, 3),
        "window_img_per_s": [round(w, 2) for w in windows],
    }))


if __name__ == "__main__":
    main()
