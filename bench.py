"""Headline benchmark: ResNet-50 training throughput (img/s) on one chip.

Baseline (BASELINE.md / reference `docs/.../faq/perf.md:252-254`): MXNet-CUDA
ResNet-50 fp32 training on V100 ≈ 364 img/s.  This drives the framework's
user-facing path — Gluon model zoo + bf16 cast (the TPU-native operating
point, as fp16 was for V100) + net-with-loss block + Trainer(sgd) via
FusedTrainStep — on synthetic ImageNet-shaped data, prints ONE JSON line.

The whole step (loss, grads, optimizer) is ONE donated XLA program
(`gluon/fused_step.py`), which matters when the chip sits behind a
network tunnel; batch size adapts downward when the shared HBM is tight.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as onp

BASELINE_IMG_PER_S = 363.69  # V100 fp32 train (batch-128 row; ~flat in batch)
BATCHES = (128, 64, 32)      # try large first; the chip's HBM is shared
WARMUP = 8
ITERS = 40


def _bench_at_batch(batch):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.block import HybridBlock
    from mxnet_tpu.gluon.model_zoo import vision

    class NetWithLoss(HybridBlock):
        def __init__(self, net, loss_fn):
            super().__init__()
            self.net = net
            self.loss_fn = loss_fn

        def forward(self, x, y):
            return self.loss_fn(self.net(x), y)

    net = vision.resnet50_v1()
    net.initialize(init=mx.init.Xavier())
    net.cast("bfloat16")
    mod = NetWithLoss(net, gloss.SoftmaxCrossEntropyLoss())

    x = mx.np.array(onp.random.uniform(-1, 1, (batch, 3, 224, 224)),
                    dtype="bfloat16")
    y = mx.np.array(onp.random.randint(0, 1000, (batch,)), dtype="int32")

    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1, "momentum": 0.9},
                               kvstore="device")
    # the documented fast path: loss+grads+update as ONE donated XLA
    # program (gluon/fused_step.py) — one dispatch per step
    fused = mx.gluon.FusedTrainStep(mod, trainer)

    def step():
        return fused(x, y, batch_size=batch)

    for _ in range(WARMUP):
        loss = step()
    loss.wait_to_read()

    # best of three windows: the chip sits behind a shared tunnel whose
    # load varies run to run; peak throughput is the capability number.
    # waitall() truly drains via a host readback (ordered after all queued
    # work) — block_until_ready alone is acked early by the tunnel.
    mx.waitall()
    windows = []
    for _window in range(3):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            step()
        mx.waitall()
        windows.append(batch * ITERS / (time.perf_counter() - t0))
    return windows


# rough peak-footprint table (bf16 activations dominate; measured b128 ≈
# 12 GB on a dedicated chip) used to probe free HBM before the expensive
# model compile — the backend exposes no memory_stats
_EST_PEAK_GB = {128: 12.0, 64: 6.5, 32: 3.5}


def _probe_hbm(batch):
    import jax
    import jax.numpy as jnp

    gb = _EST_PEAK_GB.get(batch, 12.0)
    n = int(gb * 2 ** 30 / 2)  # bf16 elements
    try:
        buf = jax.jit(lambda: jnp.zeros((n,), jnp.bfloat16))()
        onp.asarray(buf[0])    # force materialization through the tunnel
        del buf
    except Exception as e:
        if "RESOURCE_EXHAUSTED" in str(e):
            sys.exit(42)
        raise


def _attempt(batch):
    """Single-batch attempt (child-process mode): JSON on success,
    exit 42 on HBM exhaustion."""
    _probe_hbm(batch)
    try:
        windows = _bench_at_batch(batch)
    except Exception as e:
        if "RESOURCE_EXHAUSTED" in str(e):
            sys.exit(42)
        raise
    img_per_s = max(windows)
    print(json.dumps({
        "metric": "resnet50_train_bf16_img_per_s",
        "value": round(img_per_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_per_s / BASELINE_IMG_PER_S, 3),
        "batch": batch,
        "window_img_per_s": [round(w, 2) for w in windows],
    }))


def main():
    if os.environ.get("BENCH_BATCH"):
        _attempt(int(os.environ["BENCH_BATCH"]))
        return
    # the TPU client cannot reclaim HBM inside a process once an attempt
    # OOMs (and the chip's HBM is shared), so each batch size runs in its
    # own subprocess; the first that fits wins
    import subprocess
    for batch in BATCHES:
        env = dict(os.environ, BENCH_BATCH=str(batch))
        proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                              env=env, stdout=subprocess.PIPE, text=True)
        if proc.returncode == 0:
            sys.stdout.write(proc.stdout)
            return
        if proc.returncode != 42:
            sys.stderr.write(proc.stdout)
            sys.exit(proc.returncode)
    raise RuntimeError("all batch sizes exhausted HBM")


if __name__ == "__main__":
    main()
