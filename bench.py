"""Headline benchmark: ResNet-50 training throughput (img/s) on one chip.

Baseline (BASELINE.md / reference `docs/.../faq/perf.md:254`): MXNet-CUDA
ResNet-50 fp32 training on V100 at batch 64 ≈ 360 img/s (interpolated from batch-32/128 rows).  This script
drives the framework's *user-facing* path — Gluon model zoo + hybridize +
SoftmaxCrossEntropyLoss + Trainer(sgd) — on synthetic ImageNet-shaped data,
and prints ONE JSON line.
"""
from __future__ import annotations

import json
import time

import numpy as onp

BASELINE_IMG_PER_S = 363.69  # V100 fp32 train (batch-128 row; ~flat in batch)
BATCH = 64
WARMUP = 5
ITERS = 20


def main():
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.resnet50_v1()
    net.initialize(init=mx.init.Xavier())
    net.hybridize(static_alloc=True)
    loss_fn = gloss.SoftmaxCrossEntropyLoss()

    x = mx.np.array(onp.random.uniform(-1, 1, (BATCH, 3, 224, 224)),
                    dtype="float32")
    y = mx.np.array(onp.random.randint(0, 1000, (BATCH,)), dtype="int32")

    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1, "momentum": 0.9},
                               kvstore="device")

    def step():
        with mx.autograd.record():
            out = net(x)
            loss = loss_fn(out, y)
        loss.backward()
        trainer.step(BATCH)
        return loss

    for _ in range(WARMUP):
        loss = step()
    loss.wait_to_read()

    t0 = time.perf_counter()
    for _ in range(ITERS):
        loss = step()
    loss.wait_to_read()
    dt = time.perf_counter() - t0

    img_per_s = BATCH * ITERS / dt
    print(json.dumps({
        "metric": "resnet50_train_fp32_img_per_s",
        "value": round(img_per_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_per_s / BASELINE_IMG_PER_S, 3),
    }))


if __name__ == "__main__":
    main()
