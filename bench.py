"""Headline benchmark: ResNet-50 training throughput (img/s) on one chip.

Baseline (BASELINE.md / reference `docs/.../faq/perf.md:252-254`): MXNet-CUDA
ResNet-50 fp32 training on V100 ≈ 364 img/s.  This drives the framework's
user-facing path — Gluon model zoo + bf16 cast (the TPU-native operating
point, as fp16 was for V100) + net-with-loss block + Trainer(sgd) via
FusedTrainStep — on synthetic ImageNet-shaped data, prints ONE JSON line.

The whole step (loss, grads, optimizer) is ONE donated XLA program
(`gluon/fused_step.py`), which matters when the chip sits behind a
network tunnel; batch size adapts downward when the shared HBM is tight.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as onp

BASELINE_IMG_PER_S = 363.69  # V100 fp32 train (batch-128 row; ~flat in batch)
BATCHES = (128, 64, 32)      # try large first; the chip's HBM is shared
WARMUP = 8
ITERS = 40


def _net_with_loss_classes():
    """The two step bodies every bench mode shares: bf16-NCHW-in, and the
    recordio prologue (uint8 NHWC in; normalize + layout INSIDE the
    program so XLA fuses them into the first conv)."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.block import HybridBlock

    class NetWithLoss(HybridBlock):
        def __init__(self, net, loss_fn):
            super().__init__()
            self.net = net
            self.loss_fn = loss_fn

        def forward(self, x, y):
            return self.loss_fn(self.net(x), y)

    class RecNetWithLoss(HybridBlock):
        def __init__(self, net, loss_fn):
            super().__init__()
            self.net = net
            self.loss_fn = loss_fn

        def forward(self, x_u8, y):
            x = x_u8.astype("float32")
            mean = mx.np.array([123.68, 116.779, 103.939])
            std = mx.np.array([58.393, 57.12, 57.375])
            x = ((x - mean) / std).astype("bfloat16")
            x = mx.np.transpose(x, (0, 3, 1, 2))
            return self.loss_fn(self.net(x), y)

    return NetWithLoss, RecNetWithLoss


def _augmented_net_with_loss():
    """The ISSUE-10 prologue: uint8 NHWC canvas in, random crop/flip +
    normalize + bf16 NCHW all INSIDE the fused program (DeviceAugment) —
    the host never touches float pixels."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.block import HybridBlock
    from mxnet_tpu.gluon.data import DeviceAugment

    class AugNetWithLoss(HybridBlock):
        def __init__(self, net, loss_fn):
            super().__init__()
            self.net = net
            self.loss_fn = loss_fn
            self.aug = DeviceAugment(
                (224, 224), rand_crop=True, rand_mirror=True,
                mean=(123.68, 116.779, 103.939),
                std=(58.393, 57.12, 57.375), dtype="bfloat16")

        def forward(self, x_u8, y):
            return self.loss_fn(self.net(self.aug(x_u8)), y)

    return AugNetWithLoss


def _bench_at_batch(batch):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo import vision

    NetWithLoss, _ = _net_with_loss_classes()
    net = vision.resnet50_v1()
    net.initialize(init=mx.init.Xavier())
    net.cast("bfloat16")
    mod = NetWithLoss(net, gloss.SoftmaxCrossEntropyLoss())

    x = mx.np.array(onp.random.uniform(-1, 1, (batch, 3, 224, 224)),
                    dtype="bfloat16")
    y = mx.np.array(onp.random.randint(0, 1000, (batch,)), dtype="int32")

    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1, "momentum": 0.9},
                               kvstore="device")
    # the documented fast path: loss+grads+update as ONE donated XLA
    # program (gluon/fused_step.py) — one dispatch per step
    fused = mx.gluon.FusedTrainStep(mod, trainer)

    def step():
        return fused(x, y, batch_size=batch)

    for _ in range(WARMUP):
        loss = step()
    loss.wait_to_read()

    # best of three windows: the chip sits behind a shared tunnel whose
    # load varies run to run; peak throughput is the capability number.
    # waitall() truly drains via a host readback (ordered after all queued
    # work) — block_until_ready alone is acked early by the tunnel.
    mx.waitall()
    windows = []
    for _window in range(3):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            step()
        mx.waitall()
        windows.append(batch * ITERS / (time.perf_counter() - t0))
    return windows


# rough peak-footprint table (bf16 activations dominate; measured b128 ≈
# 12 GB on a dedicated chip) used to probe free HBM before the expensive
# model compile — the backend exposes no memory_stats
_EST_PEAK_GB = {128: 12.0, 64: 6.5, 32: 3.5}


def _ensure_bench_rec(n_images=2048, side=256):
    """Build (once) an ImageNet-shaped .rec: JPEG-encoded low-frequency
    textures (realistic entropy — pure noise over-costs the decoder)."""
    path = "/tmp/mxtpu_bench_imagenet.rec"
    if os.path.exists(path) and os.path.getsize(path) > 0:
        return path
    from PIL import Image
    import io as pio

    from mxnet_tpu import recordio

    rs = onp.random.RandomState(0)
    w = recordio.MXRecordIO(path + ".tmp", "w")
    for i in range(n_images):
        small = rs.randint(0, 255, (32, 32, 3), dtype=onp.uint8)
        img = onp.asarray(Image.fromarray(small).resize((side, side),
                                                        Image.BILINEAR))
        buf = pio.BytesIO()
        Image.fromarray(img).save(buf, "JPEG", quality=85)
        w.write(recordio.pack(
            recordio.IRHeader(0, float(rs.randint(0, 1000)), i, 0),
            buf.getvalue()))
    w.close()
    os.replace(path + ".tmp", path)
    return path


RITERS = 20  # recordio window length: the tunnel H2D may be seconds/batch


def _timeit(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def _bench_recordio(batch):
    """ResNet-50 bf16 training fed by the NATIVE RecordIO pipeline through
    prefetch-to-device double buffering (``io.DevicePrefetcher``): C++ JPEG
    decode threads -> NHWC uint8 -> async H2D for batch N+1 while step N
    runs -> normalize on device (fused into the program) -> train step.

    With overlap the steady-state law is max(decode, H2D, chip), not the
    sum; all three component rates are measured and reported so the
    end-to-end number can be judged against its own bound.  On this
    environment the chip sits behind a network tunnel whose H2D bandwidth
    (measured each run, often 8-30 MB/s) is the binding constraint — a real
    TPU host feeds over PCIe at GB/s where decode would bind instead.  See
    benchmark/IO_ANALYSIS.md."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo import vision

    rec = _ensure_bench_rec()
    it = mx.io.ImageRecordIter(
        path_imgrec=rec, batch_size=batch, data_shape=(3, 224, 224),
        rand_crop=True, rand_mirror=True, shuffle=True)

    _, RecNetWithLoss = _net_with_loss_classes()
    net = vision.resnet50_v1()
    net.initialize(init=mx.init.Xavier())
    net.cast("bfloat16")
    mod = RecNetWithLoss(net, gloss.SoftmaxCrossEntropyLoss())
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1, "momentum": 0.9},
                               kvstore="device")
    fused = mx.gluon.FusedTrainStep(mod, trainer)

    pf = mx.io.DevicePrefetcher(it, depth=3, dtypes=(None, onp.int32))

    def step():
        x, y = next(pf)
        return fused(x, y, batch_size=batch)

    for _ in range(WARMUP):
        loss = step()
    loss.wait_to_read()
    mx.waitall()

    # --- component rates for the overlap-bound analysis -----------------
    # (1) decoder-only: ITERS batches so the ring's pre-decoded slots
    #     don't inflate the number (pf keeps pulling concurrently; pause it
    #     by measuring through the same prefetcher's source is unfair —
    #     measure the raw iterator on a fresh handle instead)
    it2 = mx.io.ImageRecordIter(
        path_imgrec=rec, batch_size=batch, data_shape=(3, 224, 224),
        rand_crop=True, rand_mirror=True, shuffle=True)
    it2.next_arrays()
    t0 = time.perf_counter()
    for _ in range(ITERS):
        data, labels = it2.next_arrays()
    decode_rate = batch * ITERS / (time.perf_counter() - t0)
    it2.close()

    # (2) true H2D wire rate: K pipelined async puts, then a one-element
    #     readback of the LAST one (this tunnel acks block_until_ready
    #     early; only a value fetch proves the bytes landed; pipelining
    #     amortizes the tunnel round-trip latency out of the estimate).
    #     The shared tunnel's bandwidth drifts minute to minute, so the
    #     probe runs before AND after the end-to-end windows; the bound
    #     uses the best sample (the wire the windows could have seen).
    import jax as _jax
    mb = data.nbytes / 2 ** 20
    buf = _jax.device_put(data)
    onp.asarray(buf[0, 0, 0])
    t_rtt = min(_timeit(lambda: onp.asarray(buf[0, 0, 0])) for _ in range(3))

    def h2d_probe(K=4):
        t0 = time.perf_counter()
        bufs = [_jax.device_put(data) for _ in range(K)]
        onp.asarray(bufs[-1][0, 0, 0])  # wire is FIFO: last lands last
        return max(time.perf_counter() - t0 - t_rtt, 1e-9) / K

    t_h2d = h2d_probe()

    # (3) chip-only: re-step on one device-resident batch
    x0, y0 = next(pf)
    for _ in range(2):
        fused(x0, y0, batch_size=batch)
    mx.waitall()
    t0 = time.perf_counter()
    for _ in range(ITERS):
        fused(x0, y0, batch_size=batch)
    mx.waitall()
    chip_rate = batch * ITERS / (time.perf_counter() - t0)

    # --- end-to-end through the prefetcher ------------------------------
    # the ring holds `depth` pre-transferred batches at window start and
    # (steady-state) at window end, so the preload bias cancels; RITERS
    # >> depth keeps any residue small
    windows = []
    for _window in range(2):
        t0 = time.perf_counter()
        for _ in range(RITERS):
            step()
        mx.waitall()
        windows.append(batch * RITERS / (time.perf_counter() - t0))
    t_h2d = min(t_h2d, h2d_probe())
    h2d_rate = batch / t_h2d
    pf.close()
    bound = min(decode_rate, h2d_rate, chip_rate)
    return windows, {
        "decode_only_img_per_s": round(decode_rate, 2),
        "h2d_mb_per_s": round(mb / t_h2d, 2),
        "h2d_img_per_s": round(h2d_rate, 2),
        "chip_only_img_per_s": round(chip_rate, 2),
        "overlap_bound_img_per_s": round(bound, 2),
    }


def _bench_sharded(batch):
    """ISSUE-10 rider: the three-stage pipeline end to end — sharded
    parallel readers (decode pool) -> compact uint8 canvas over the wire
    exactly once (``parallel.shard_put`` per-device puts) -> crop/flip/
    normalize INSIDE the fused dp program (``DeviceAugment``) -> train
    step on a dp mesh over all local devices.

    Reports each stage's own rate (decode pool, wire, chip) so the
    end-to-end number can be judged against max(decode, wire, chip), and
    proves the zero-host-replication law from the telemetry transfer
    counters: over the steady windows, ``kind="shard_put"`` bytes grow by
    ~one batch per step while ``kind="device_put"`` bytes stay flat (the
    fused step's place() passes pre-sharded globals through)."""
    import mxnet_tpu as mx
    from mxnet_tpu import env as menv, parallel
    from mxnet_tpu import telemetry as tm
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo import vision

    rec = _ensure_bench_rec()
    side = 256  # ship the full canvas; the 224-crop happens on device

    def reader(threads):
        return mx.io.ImageRecordIter(
            path_imgrec=rec, batch_size=batch, data_shape=(3, side, side),
            shuffle=True, seed=7, preprocess_threads=threads)

    def decode_rate(threads, iters=ITERS):
        it = reader(threads)
        it.next_arrays()  # first pop waits out the ring fill
        t0 = time.perf_counter()
        for _ in range(iters):
            it.next_arrays()
        r = batch * iters / (time.perf_counter() - t0)
        it.close()
        return r

    single_rate = decode_rate(1)
    pool_threads = menv.decode_threads()
    pool_rate = decode_rate(pool_threads)

    mesh = parallel.make_mesh({"dp": -1})
    sh = parallel.data_sharding(mesh)

    AugNetWithLoss = _augmented_net_with_loss()
    net = vision.resnet50_v1()
    net.initialize(init=mx.init.Xavier())
    net.cast("bfloat16")
    mod = AugNetWithLoss(net, gloss.SoftmaxCrossEntropyLoss())
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1, "momentum": 0.9},
                               kvstore="device")
    fused = mx.gluon.FusedTrainStep(mod, trainer, mesh=mesh)

    # wire rate through the sharded path itself: K pipelined shard_puts,
    # readback of the last (same tunnel-honest methodology as the
    # recordio rider; each byte crosses once regardless of dp degree)
    it2 = reader(pool_threads)
    probe_data, _ = it2.next_arrays()
    it2.close()
    mb = probe_data.nbytes / 2 ** 20
    buf = parallel.shard_put(probe_data, sh)
    onp.asarray(buf[0, 0, 0, 0])
    t_rtt = min(_timeit(lambda: onp.asarray(buf[0, 0, 0, 0]))
                for _ in range(3))

    def wire_probe(K=4):
        t0 = time.perf_counter()
        bufs = [parallel.shard_put(probe_data, sh) for _ in range(K)]
        onp.asarray(bufs[-1][0, 0, 0, 0])
        return max(time.perf_counter() - t0 - t_rtt, 1e-9) / K

    t_wire = wire_probe()

    it = reader(pool_threads)
    pf = mx.io.DevicePrefetcher(it, sharding=sh, transfer_threads=4,
                                dtypes=(None, onp.int32))

    def step():
        x, y = next(pf)
        return fused(x, y, batch_size=batch)

    for _ in range(WARMUP):
        loss = step()
    loss.wait_to_read()
    mx.waitall()

    # chip-only: re-step one pre-sharded device-resident batch
    x0, y0 = next(pf)
    for _ in range(2):
        fused(x0, y0, batch_size=batch)
    mx.waitall()
    t0 = time.perf_counter()
    for _ in range(ITERS):
        fused(x0, y0, batch_size=batch)
    mx.waitall()
    chip_rate = batch * ITERS / (time.perf_counter() - t0)

    reg = tm.default_registry() if callable(
        getattr(tm, "default_registry", None)) else tm.registry

    def tbytes(kind):
        v = reg.get_sample_value("mxtpu_mesh_transfer_bytes_total",
                                 {"kind": kind})
        return 0.0 if v is None else v

    sp0, dput0 = tbytes("shard_put"), tbytes("device_put")
    windows = []
    for _window in range(2):
        t0 = time.perf_counter()
        for _ in range(RITERS):
            step()
        mx.waitall()
        windows.append(batch * RITERS / (time.perf_counter() - t0))
    sp1, dput1 = tbytes("shard_put"), tbytes("device_put")
    t_wire = min(t_wire, wire_probe())
    wire_rate = batch / t_wire
    pf.close()
    it.close()

    steps = 2 * RITERS
    sp_per_step = (sp1 - sp0) / steps
    dput_per_step = (dput1 - dput0) / steps
    batch_bytes = probe_data.nbytes + batch * 4  # + int32 labels
    # the feeder rides up to `depth` batches ahead, so shard_put may land
    # a few extra batches inside the window; 1.25x bounds that slack
    zero_rep = dput_per_step < 4096 and sp_per_step <= 1.25 * batch_bytes
    bound = min(pool_rate, wire_rate, chip_rate)
    return windows, {
        "decode_single_img_per_s": round(single_rate, 2),
        "decode_pool_img_per_s": round(pool_rate, 2),
        "decode_pool_threads": pool_threads,
        "decode_pool_scaling": round(pool_rate / single_rate, 2),
        "wire_mb_per_s": round(mb / t_wire, 2),
        "wire_img_per_s": round(wire_rate, 2),
        "chip_only_img_per_s": round(chip_rate, 2),
        "overlap_bound_img_per_s": round(bound, 2),
        "dp_devices": int(mesh.devices.size),
        "shard_put_bytes_per_step": int(sp_per_step),
        "device_put_bytes_per_step": int(dput_per_step),
        "batch_bytes": int(batch_bytes),
        "zero_host_replication": bool(zero_rep),
    }


def _attempt_sharded(batch):
    try:
        windows, comp = _bench_sharded(batch)
    except Exception as e:
        if "RESOURCE_EXHAUSTED" in str(e):
            sys.exit(42)
        raise
    img_per_s = max(windows)
    print(json.dumps({
        "metric": "resnet50_train_bf16_sharded_recordio_img_per_s",
        "value": round(img_per_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_per_s / BASELINE_IMG_PER_S, 3),
        "vs_overlap_bound": round(
            img_per_s / comp["overlap_bound_img_per_s"], 3),
        "batch": batch,
        "window_img_per_s": [round(w, 2) for w in windows],
        "host_cpus": os.cpu_count(),
        **comp,
    }))


AB_ITERS = 20
AB_ROUNDS = 4


def _bench_ab(batch):
    """Same-window A/B: the synthetic step (bf16 NCHW device batch) vs the
    recordio-prologue step (uint8 NHWC device batch; normalize + layout
    inside the program) interleaved in ONE process, so tunnel/chip drift
    cancels (round-3 verdict weak #1: the two rates came from separate
    subprocesses minutes apart and disagreed by 45%).

    Both steps train the SAME net instance (one set of params/momentum in
    HBM); the per-round ratio B/A isolates what the prologue itself
    costs."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo import vision

    NetWithLoss, RecNetWithLoss = _net_with_loss_classes()
    net = vision.resnet50_v1()
    net.initialize(init=mx.init.Xavier())
    net.cast("bfloat16")
    lf = gloss.SoftmaxCrossEntropyLoss()
    mod_a = NetWithLoss(net, lf)
    mod_b = RecNetWithLoss(net, lf)
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1, "momentum": 0.9},
                               kvstore="device")
    fused_a = mx.gluon.FusedTrainStep(mod_a, trainer)
    fused_b = mx.gluon.FusedTrainStep(mod_b, trainer)

    rs = onp.random.RandomState(0)
    x_a = mx.np.array(rs.uniform(-1, 1, (batch, 3, 224, 224)),
                      dtype="bfloat16")
    x_b = mx.np.array(rs.randint(0, 255, (batch, 224, 224, 3)),
                      dtype="uint8")
    y = mx.np.array(rs.randint(0, 1000, (batch,)), dtype="int32")

    # leg C (round-4 verdict weak #5): the device-resident RECORDIO step —
    # a real JPEG-decoded batch through the same prologue program,
    # interleaved in this same window.  Closes the last cross-window gap:
    # round-4's `chip_only` was measured in a different window than the
    # headline and sat 16% under it, bracketed only by inference.
    rec_it = mx.io.ImageRecordIter(
        path_imgrec=_ensure_bench_rec(), batch_size=batch,
        data_shape=(3, 224, 224), rand_crop=True, rand_mirror=True,
        shuffle=True)
    data_rec, labels_rec = rec_it.next_arrays()
    x_c = mx.np.array(data_rec)               # uint8 NHWC, device-resident
    y_c = mx.np.array(labels_rec.astype(onp.int32))
    rec_it.close()

    for _ in range(WARMUP):
        fused_a(x_a, y, batch_size=batch)
        fused_b(x_b, y, batch_size=batch)
        fused_b(x_c, y_c, batch_size=batch)
    mx.waitall()

    def window(fused, x, yy):
        t0 = time.perf_counter()
        for _ in range(AB_ITERS):
            fused(x, yy, batch_size=batch)
        mx.waitall()
        return batch * AB_ITERS / (time.perf_counter() - t0)

    rates_a, rates_b, rates_c, ratios, ratios_c = [], [], [], [], []
    for _round in range(AB_ROUNDS):
        ra = window(fused_a, x_a, y)
        rb = window(fused_b, x_b, y)
        rc = window(fused_b, x_c, y_c)
        rates_a.append(ra)
        rates_b.append(rb)
        rates_c.append(rc)
        ratios.append(rb / ra)
        ratios_c.append(rc / ra)
    ratios.sort()
    ratios_c.sort()
    return {
        "ab_synthetic_img_per_s": round(max(rates_a), 2),
        "ab_prologue_img_per_s": round(max(rates_b), 2),
        "ab_chip_only_img_per_s": round(max(rates_c), 2),
        "ab_rounds_synthetic": [round(r, 2) for r in rates_a],
        "ab_rounds_prologue": [round(r, 2) for r in rates_b],
        "ab_rounds_chip_only": [round(r, 2) for r in rates_c],
        "ab_prologue_over_synthetic": round(
            ratios[len(ratios) // 2], 4),
        "ab_chip_only_over_synthetic": round(
            ratios_c[len(ratios_c) // 2], 4),
    }


def _attempt_ab(batch):
    _probe_hbm(batch)
    try:
        comp = _bench_ab(batch)
    except Exception as e:
        if "RESOURCE_EXHAUSTED" in str(e):
            sys.exit(42)
        raise
    print(json.dumps({"metric": "resnet50_ab_prologue", "batch": batch,
                      **comp}))


def _attempt_recordio(batch):
    try:
        windows, comp = _bench_recordio(batch)
    except Exception as e:
        if "RESOURCE_EXHAUSTED" in str(e):
            sys.exit(42)
        raise
    img_per_s = max(windows)
    print(json.dumps({
        "metric": "resnet50_train_bf16_recordio_img_per_s",
        "value": round(img_per_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_per_s / BASELINE_IMG_PER_S, 3),
        "vs_overlap_bound": round(
            img_per_s / comp["overlap_bound_img_per_s"], 3),
        "batch": batch,
        "window_img_per_s": [round(w, 2) for w in windows],
        "host_cpus": os.cpu_count(),
        **comp,
    }))


def _probe_hbm(batch):
    import jax
    import jax.numpy as jnp

    gb = _EST_PEAK_GB.get(batch, 12.0)
    n = int(gb * 2 ** 30 / 2)  # bf16 elements
    try:
        buf = jax.jit(lambda: jnp.zeros((n,), jnp.bfloat16))()
        onp.asarray(buf[0])    # force materialization through the tunnel
        del buf
    except Exception as e:
        if "RESOURCE_EXHAUSTED" in str(e):
            sys.exit(42)
        raise


def _attempt(batch):
    """Single-batch attempt (child-process mode): JSON on success,
    exit 42 on HBM exhaustion."""
    _probe_hbm(batch)
    try:
        windows = _bench_at_batch(batch)
    except Exception as e:
        if "RESOURCE_EXHAUSTED" in str(e):
            sys.exit(42)
        raise
    img_per_s = max(windows)
    print(json.dumps({
        "metric": "resnet50_train_bf16_img_per_s",
        "value": round(img_per_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_per_s / BASELINE_IMG_PER_S, 3),
        "batch": batch,
        "window_img_per_s": [round(w, 2) for w in windows],
    }))


def main():
    recordio_mode = "--recordio" in sys.argv or \
        os.environ.get("BENCH_MODE") == "recordio"
    ab_mode = "--ab" in sys.argv or os.environ.get("BENCH_MODE") == "ab"
    sharded_mode = "--sharded" in sys.argv or \
        os.environ.get("BENCH_MODE") == "sharded"
    if os.environ.get("BENCH_BATCH"):
        if ab_mode:
            _attempt_ab(int(os.environ["BENCH_BATCH"]))
        elif sharded_mode:
            _attempt_sharded(int(os.environ["BENCH_BATCH"]))
        elif recordio_mode:
            _attempt_recordio(int(os.environ["BENCH_BATCH"]))
        else:
            _attempt(int(os.environ["BENCH_BATCH"]))
        return
    # the TPU client cannot reclaim HBM inside a process once an attempt
    # OOMs (and the chip's HBM is shared), so each batch size runs in its
    # own subprocess; the first that fits wins
    import subprocess

    def run_mode(mode, timeout=None):
        for batch in BATCHES:
            env = dict(os.environ, BENCH_BATCH=str(batch))
            if mode in ("recordio", "ab", "sharded"):
                env["BENCH_MODE"] = mode
            else:
                env.pop("BENCH_MODE", None)
            try:
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__)],
                    env=env, stdout=subprocess.PIPE, text=True,
                    timeout=timeout)
            except subprocess.TimeoutExpired:
                raise RuntimeError(f"{mode} timed out after {timeout}s")
            if proc.returncode == 0:
                return json.loads(proc.stdout.strip().splitlines()[-1])
            if proc.returncode != 42:
                sys.stderr.write(proc.stdout)
                sys.exit(proc.returncode)
        raise RuntimeError("all batch sizes exhausted HBM")

    if recordio_mode:
        print(json.dumps(run_mode("recordio")))
        return
    if ab_mode:
        print(json.dumps(run_mode("ab")))
        return
    if sharded_mode:
        print(json.dumps(run_mode("sharded")))
        return
    result = run_mode("synthetic")
    # the real-data number rides along in the same line (VERDICT r2 #1):
    # recordio_* keys give end-to-end RecordIO-fed training plus the
    # measured component rates (decode / tunnel H2D / chip) bounding it.
    # Hard-capped so a congested wire can never cost the headline artifact
    # (BENCH_RECORDIO_TIMEOUT=0 skips the rider entirely).
    rio_timeout = float(os.environ.get("BENCH_RECORDIO_TIMEOUT", "600"))
    if rio_timeout > 0:
        try:
            rec = run_mode("recordio", timeout=rio_timeout)
            result["recordio_img_per_s"] = rec["value"]
            result["recordio_vs_overlap_bound"] = rec["vs_overlap_bound"]
            for k in ("decode_only_img_per_s", "h2d_mb_per_s",
                      "h2d_img_per_s", "chip_only_img_per_s",
                      "overlap_bound_img_per_s"):
                result[k] = rec[k]
        except Exception as e:  # the headline must not die with the rider
            result["recordio_error"] = str(e)[:200]
    # ISSUE-10 rider: the sharded global-array pipeline (decode pool ->
    # one-wire-crossing uint8 canvas via per-device shard puts -> device
    # augment inside the program) with per-stage rates and the telemetry
    # zero-replication proof.  BENCH_SHARDED_TIMEOUT=0 skips it.
    sharded_timeout = float(os.environ.get("BENCH_SHARDED_TIMEOUT", "600"))
    if sharded_timeout > 0:
        try:
            shd = run_mode("sharded", timeout=sharded_timeout)
            result["sharded_recordio_img_per_s"] = shd["value"]
            result["sharded_vs_overlap_bound"] = shd["vs_overlap_bound"]
            for k in ("decode_single_img_per_s", "decode_pool_img_per_s",
                      "decode_pool_threads", "decode_pool_scaling",
                      "wire_mb_per_s", "wire_img_per_s",
                      "chip_only_img_per_s", "overlap_bound_img_per_s",
                      "dp_devices", "shard_put_bytes_per_step",
                      "device_put_bytes_per_step", "batch_bytes",
                      "zero_host_replication"):
                result["sharded_" + k] = shd[k]
        except Exception as e:
            result["sharded_error"] = str(e)[:200]
    # same-window A/B rider (r3 verdict weak #1): the synthetic step and
    # the recordio-prologue step interleaved in ONE process, so the
    # chip-rate comparison is drift-free.  BENCH_AB_TIMEOUT=0 skips it.
    ab_timeout = float(os.environ.get("BENCH_AB_TIMEOUT", "600"))
    if ab_timeout > 0:
        try:
            ab = run_mode("ab", timeout=ab_timeout)
            for k in ("ab_synthetic_img_per_s", "ab_prologue_img_per_s",
                      "ab_prologue_over_synthetic",
                      "ab_chip_only_img_per_s",
                      "ab_chip_only_over_synthetic"):
                result[k] = ab[k]
        except Exception as e:
            result["ab_error"] = str(e)[:200]
    # transformer rider (r3 verdict #2): BERT-base pretraining tokens/s +
    # MFU in the same artifact line.  Since round 6 the rider trains the
    # RECIPE-REALISTIC configuration — padded variable-length batches
    # with the padding mask threaded through attention, plus attention
    # dropout 0.1 — and a second long-T point (B=4, T=2048) where the
    # auto policy puts that configuration on the in-kernel flash path.
    # Subprocess-isolated like the other riders; BENCH_BERT_TIMEOUT=0
    # skips both.
    bert_timeout = float(os.environ.get("BENCH_BERT_TIMEOUT", "600"))

    def bert_rider(extra_args):
        proc = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "benchmark", "bert_pretrain_bench.py"),
             *extra_args],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            timeout=bert_timeout)
        rows = [json.loads(l) for l in proc.stdout.strip().splitlines()
                if l.startswith("{")]
        if proc.returncode != 0 or not rows:
            raise RuntimeError(
                f"bert rider rc={proc.returncode}: "
                f"{proc.stderr.strip()[-160:]}")
        return rows[0]

    if bert_timeout > 0:
        try:
            row = bert_rider([])
            result["bert_tokens_per_s"] = row["value"]
            result["bert_mfu_vs_197tf_bf16"] = row["mfu_vs_197tf_bf16"]
            result["bert_masked_dropout"] = row.get("masked", False)
        except Exception as e:
            result["bert_error"] = str(e)[:200]
        try:
            row = bert_rider(["--batch", "4", "--seq", "2048"])
            result["bert_flash_t2048_tokens_per_s"] = row["value"]
            result["bert_flash_t2048_mfu"] = row["mfu_vs_197tf_bf16"]
        except Exception as e:
            result["bert_flash_error"] = str(e)[:200]
    # layer-census rider (ISSUE 8): where the step's FLOPs live, layer by
    # layer, with roofline bound classes — the top-5 sag summary rides in
    # the same artifact line so a throughput regression points at a layer,
    # not just a number.  Subprocess-isolated (the census captures on the
    # 8-device virtual mesh, which must own backend init); cost-model-only,
    # so it is cheap and deterministic.  BENCH_CENSUS_TIMEOUT=0 skips it.
    census_timeout = float(os.environ.get("BENCH_CENSUS_TIMEOUT", "300"))
    if census_timeout > 0:
        try:
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       XLA_FLAGS="--xla_force_host_platform_device_count=8")
            proc = subprocess.run(
                [sys.executable, "-m", "tools.layerscope",
                 "--entry", "fused_train_step_dp", "--format", "json",
                 "--no-artifact", "--no-metrics"],
                cwd=os.path.dirname(os.path.abspath(__file__)), env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                timeout=census_timeout)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"layerscope rc={proc.returncode}: "
                    f"{proc.stderr.strip()[-160:]}")
            report = json.loads(proc.stdout)
            result["layer_census_top_sag"] = \
                report["entries"][0]["top_sag"]
        except Exception as e:
            result["layer_census_error"] = str(e)[:200]
    print(json.dumps(result))


if __name__ == "__main__":
    main()
