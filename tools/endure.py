"""Endurance gate for elastic training (``tools/ci.sh endure``).

One emulated 3-host pod (rank r trains on virtual device ``cpu(r)``,
block-scaled int8 compressed allreduce) is driven through a seeded
faultline plan in two phases:

1. **Preempt x2, same topology** — two mid-run preemptions inside the
   bucketed collective.  The :class:`ElasticSupervisor` rebuilds against
   the SAME world and resumes from the last checkpoint; the final
   parameters must match a fault-free oracle **bitwise** (the PR 9
   trajectory-parity fence, now owned by the supervisor), with
   ``mxtpu_faults_recovered_total{collective.dispatch,preempt}`` += 2
   and zero re-shards.
2. **Permanent host kill** — a ``dead_node`` fault kills rank 1's
   heartbeat mid-run.  The supervisor must re-shard 3 -> 2 (survivors
   keep their own devices AND their own per-rank data streams), apply
   the linear lr scaling rule (lr x 2/3, logged), tick
   ``mxtpu_elastic_reshards_total`` and
   ``mxtpu_faults_recovered_total{kvstore.kv,dead_node}``, finish the
   run with finite parameters, and recover **per-host** throughput to
   >= 95% of the pre-fault rate within the recovery window (global
   throughput necessarily drops with the dead host — the gate is that
   each survivor keeps its own pace; measured on the last
   ``RECOVER_WINDOW`` steps so the one-off re-shard cost — rebuild,
   restore, recompile — is excluded, which is the "within N steps"
   clause).

Deterministic: data is a pure function of (rank, step), faults are
arrival-indexed plans, checkpoints are every-step — a failing run
replays exactly.  Run directly::

    python -m tools.endure --gate

Prints one ``endure_verdict: PASS|FAIL`` line; ``--gate`` exits nonzero
on FAIL.
"""
from __future__ import annotations

import argparse
import os
import statistics
import sys
import time

# standalone process: same virtual-device rig as tests/conftest.py, and
# it must be set before jax initializes its backends
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, telemetry
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.utils import split_and_load
from mxnet_tpu.resilience import (CheckpointManager, ElasticSupervisor,
                                  ElasticWorld, EmulatedPod, faultline)

HOSTS = 3
IN_UNITS = 12
PER_HOST_BATCH = 2
SEED = 4242
BASE_LR = 0.05

STEPS_A = 6          # phase 1 run length
STEPS_B = 14         # phase 2 run length
KILL_POLL = 6        # liveness poll on which rank 1's heartbeat dies
RECOVER_WINDOW = 4   # post-reshard steps the throughput gate averages
WARMUP = 2           # leading compile steps excluded from the baseline
THROUGHPUT_FLOOR = 0.95


def _host_batch(t, rank):
    # keyed by RANK, not by position in the world: a survivor keeps its
    # own data stream across a re-shard
    rs = onp.random.RandomState(1000 + 997 * rank + t)
    return rs.randn(PER_HOST_BATCH, IN_UNITS).astype(onp.float32)


def _global_batch(t, ranks):
    return onp.concatenate([_host_batch(t, r) for r in ranks], axis=0)


class _Job:
    """One incarnation of the emulated pod job: the ``build(world)``
    handle the supervisor expects (``.trainer`` / ``.run_step``)."""

    def __init__(self, world):
        mx.random.seed(SEED)
        self.world = world
        self.ctxs = [mx.cpu(r) for r in world.ranks]
        net = nn.HybridSequential()
        net.add(nn.Dense(16, in_units=IN_UNITS, activation="relu"))
        net.add(nn.Dense(8, in_units=16))
        net.initialize(ctx=self.ctxs)
        self.net = net
        self.trainer = gluon.Trainer(
            net.collect_params(), "sgd",
            {"learning_rate": BASE_LR, "momentum": 0.9},
            kvstore="tpu_ici",
            compression_params={"type": "int8", "block": 64})
        self.step_seconds = []  # (step, wall_seconds, world_size)

    def run_step(self, t):
        t0 = time.perf_counter()
        x = mx.np.array(_global_batch(t, self.world.ranks))
        xs = split_and_load(x, self.ctxs)
        with autograd.record():
            ls = [(self.net(xb) ** 2).mean() for xb in xs]
        autograd.backward(ls)
        self.trainer.step(PER_HOST_BATCH * len(self.ctxs))
        mx.waitall()
        self.step_seconds.append(
            (t, time.perf_counter() - t0, self.world.size))

    def params_np(self):
        return {k: onp.asarray(p.data()._data)
                for k, p in self.net.collect_params().items()}


def _phase_preempt(root):
    """Two preemptions, same topology: bitwise trajectory parity."""
    faultline.clear()
    world = ElasticWorld.fresh(HOSTS)

    oracle = _Job(world)
    for t in range(STEPS_A):
        oracle.run_step(t)
    want = oracle.params_np()

    reg = telemetry.default_registry()
    labels = {"site": "collective.dispatch", "kind": "preempt"}
    rec0 = reg.get_sample_value(
        "mxtpu_faults_recovered_total", labels) or 0
    res0 = reg.get_sample_value("mxtpu_elastic_reshards_total") or 0
    # one bucket dispatch per step (the whole model fits one bucket):
    # arrival 3 preempts step 2, the replay re-arrives as 4, arrival 5
    # then preempts step 3 — two distinct preempt/resume cycles
    faultline.plan([
        {"site": "collective.dispatch", "kind": "preempt", "at": 3},
        {"site": "collective.dispatch", "kind": "preempt", "at": 5},
    ])
    mgr = CheckpointManager(os.path.join(root, "preempt"),
                            async_write=False, rank=0)
    sup = ElasticSupervisor(_Job, mgr, world=world,
                            pod=EmulatedPod(world.ranks), elastic=True,
                            min_world=1, scaling="linear")
    handle = sup.run(STEPS_A, checkpoint_every=1)
    faultline.clear()
    mgr.close()

    got = handle.params_np()
    recovered = (reg.get_sample_value(
        "mxtpu_faults_recovered_total", labels) or 0) - rec0
    reshards = (reg.get_sample_value(
        "mxtpu_elastic_reshards_total") or 0) - res0
    sup.close()
    return {
        "preempt_bitwise": all(
            got[k].tobytes() == want[k].tobytes() for k in want),
        "preempt_recovered_2": recovered == 2,
        "preempt_no_reshard": reshards == 0,
    }, {"preempts_recovered": recovered}


def _phase_dead_node(root):
    """Permanent host kill: re-shard 3 -> 2 and keep training."""
    faultline.clear()
    world = ElasticWorld.fresh(HOSTS)
    pod = EmulatedPod(world.ranks)
    # one kvstore.kv arrival per live rank per liveness poll (one poll
    # per step): rank 1's stamp goes stale on poll KILL_POLL; the
    # two-observation rule declares it dead one poll later
    faultline.plan([{"site": "kvstore.kv", "kind": "dead_node",
                     "rank": 1, "at": HOSTS * (KILL_POLL - 1) + 2}])

    reg = telemetry.default_registry()
    labels = {"site": "kvstore.kv", "kind": "dead_node"}
    rec0 = reg.get_sample_value(
        "mxtpu_faults_recovered_total", labels) or 0
    res0 = reg.get_sample_value("mxtpu_elastic_reshards_total") or 0

    times = []  # shared across job incarnations

    def build(w):
        job = _Job(w)
        job.step_seconds = times
        return job

    mgr = CheckpointManager(os.path.join(root, "dead"),
                            async_write=False, rank=0)
    sup = ElasticSupervisor(build, mgr, world=world, pod=pod,
                            elastic=True, min_world=2, scaling="linear")
    handle = sup.run(STEPS_B, checkpoint_every=1)
    faultline.clear()
    mgr.close()

    reshards = (reg.get_sample_value(
        "mxtpu_elastic_reshards_total") or 0) - res0
    recovered = (reg.get_sample_value(
        "mxtpu_faults_recovered_total", labels) or 0) - rec0
    world_size = reg.get_sample_value("mxtpu_elastic_world_size")

    # per-host throughput: pre-fault steady median vs the last
    # RECOVER_WINDOW post-reshard steps (both in seconds per step; one
    # step is one global batch, per-host batch constant)
    pre = [dt for _t, dt, size in times if size == HOSTS][WARMUP:]
    post = [dt for _t, dt, size in times if size == HOSTS - 1]
    post = post[-RECOVER_WINDOW:]
    ratio = (statistics.median(pre) / statistics.median(post)
             if pre and post else 0.0)

    finite = all(onp.isfinite(a).all()
                 for a in handle.params_np().values())
    lr = float(handle.trainer.learning_rate)
    want_lr = BASE_LR * (HOSTS - 1) / HOSTS
    sup.close()
    checks = {
        "resharded_once": reshards == 1,
        "dead_node_recovered": recovered >= 1,
        "survivor_world": sup.world.ranks == (0, 2),
        "world_gauge": world_size == HOSTS - 1,
        "lr_linear_rule": abs(lr - want_lr) < 1e-12,
        "params_finite": finite,
        "throughput_recovered": ratio >= THROUGHPUT_FLOOR,
    }
    extra = {"reshards": reshards, "throughput_ratio": ratio, "lr": lr,
             "post_steps": len(post)}
    return checks, extra


def run_endure(root):
    t0 = time.perf_counter()
    checks_a, extra_a = _phase_preempt(root)
    checks_b, extra_b = _phase_dead_node(root)
    checks = dict(checks_a, **checks_b)
    ok = all(checks.values())
    wall = time.perf_counter() - t0
    fail_bits = "" if ok else " FAILED: " + ",".join(
        k for k, v in checks.items() if not v)
    verdict = (
        f"endure_verdict: {'PASS' if ok else 'FAIL'} — "
        f"preempts recovered={extra_a['preempts_recovered']:.0f}/2 "
        f"bitwise={'yes' if checks['preempt_bitwise'] else 'NO'}, "
        f"reshards={extra_b['reshards']:.0f} (3->2 on dead rank 1), "
        f"lr={extra_b['lr']:.4g} (linear rule), per-host throughput "
        f"{extra_b['throughput_ratio']:.2f}x pre-fault over last "
        f"{extra_b['post_steps']} steps (floor {THROUGHPUT_FLOOR}), "
        f"wall={wall:.1f}s{fail_bits}")
    summary = dict(checks, **extra_a, **extra_b, wall=wall)
    return verdict, ok, summary


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--gate", action="store_true",
                    help="exit nonzero when the gate fails")
    ap.add_argument("--root", default=None,
                    help="checkpoint scratch dir (default: a tempdir)")
    args = ap.parse_args(argv)
    import tempfile
    if args.root:
        verdict, ok, _ = run_endure(args.root)
    else:
        with tempfile.TemporaryDirectory(prefix="mxtpu-endure-") as root:
            verdict, ok, _ = run_endure(root)
    print(verdict)
    return 1 if (args.gate and not ok) else 0


if __name__ == "__main__":
    sys.exit(main())
