"""Endurance gate for elastic training (``tools/ci.sh endure``).

One emulated 3-host pod (rank r trains on virtual device ``cpu(r)``,
block-scaled int8 compressed allreduce) is driven through a seeded
faultline plan in two phases:

1. **Preempt x2, same topology** — two mid-run preemptions inside the
   bucketed collective.  The :class:`ElasticSupervisor` rebuilds against
   the SAME world and resumes from the last checkpoint; the final
   parameters must match a fault-free oracle **bitwise** (the PR 9
   trajectory-parity fence, now owned by the supervisor), with
   ``mxtpu_faults_recovered_total{collective.dispatch,preempt}`` += 2
   and zero re-shards.
2. **Permanent host kill** — a ``dead_node`` fault kills rank 1's
   heartbeat mid-run.  The supervisor must re-shard 3 -> 2 (survivors
   keep their own devices AND their own per-rank data streams), apply
   the linear lr scaling rule (lr x 2/3, logged), tick
   ``mxtpu_elastic_reshards_total`` and
   ``mxtpu_faults_recovered_total{kvstore.kv,dead_node}``, finish the
   run with finite parameters, and recover **per-host** throughput to
   >= 95% of the pre-fault rate within the recovery window (global
   throughput necessarily drops with the dead host — the gate is that
   each survivor keeps its own pace; measured on the last
   ``RECOVER_WINDOW`` steps so the one-off re-shard cost — rebuild,
   restore, recompile — is excluded, which is the "within N steps"
   clause).

Three GRAY-failure phases follow (``MXTPU_CHAOS_GRAY=0`` opts out,
ISSUE 14):

3. **Straggler demotion** — seeded ``slow`` faults delay rank 1's data
   fetch for two consecutive steps; the per-rank step-time stamps make
   the :class:`StragglerPolicy` declare it DEGRADED and the supervisor
   re-shards 3 -> 2 exactly like a death, with
   ``mxtpu_node_degraded_total{rank="1"}`` ticked and per-host
   throughput back to >= 95% of the pre-fault clean baseline.
4. **Bitflip caught in-program** — ``MXNET_KVSTORE_INTEGRITY=1`` plus a
   planned ``bitflip`` at ``collective.dispatch``: the digest sideband
   trips inside the fused launch, the trainer's step-guard skips the
   update with params BITWISE unchanged, and
   ``mxtpu_integrity_violations_total`` /
   ``mxtpu_train_steps_skipped_total`` tick.
5. **Divergence auto-rollback** — a ``bitflip`` on the data iterator
   (exponent bit of element 0) spikes the loss; the
   :class:`DivergenceSentinel` trips, the supervisor rolls back to the
   newest complete checkpoint (``mxtpu_sentinel_rollbacks_total`` += 1,
   within ``MXNET_SENTINEL_ROLLBACKS``) and the run completes with
   finite parameters.

Deterministic: data is a pure function of (rank, step), faults are
arrival-indexed plans, checkpoints are every-step — a failing run
replays exactly.  Run directly::

    python -m tools.endure --gate

Prints an ``endure_verdict: PASS|FAIL`` line (and a ``gray_verdict``
line unless opted out); ``--gate`` exits nonzero when either fails.
"""
from __future__ import annotations

import argparse
import os
import statistics
import sys
import time

# standalone process: same virtual-device rig as tests/conftest.py, and
# it must be set before jax initializes its backends
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, observe, telemetry
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.utils import split_and_load
from mxnet_tpu.resilience import (CheckpointManager, ElasticSupervisor,
                                  ElasticWorld, EmulatedPod, faultline)

HOSTS = 3
IN_UNITS = 12
PER_HOST_BATCH = 2
SEED = 4242
BASE_LR = 0.05

STEPS_A = 6          # phase 1 run length
STEPS_B = 14         # phase 2 run length
KILL_POLL = 6        # liveness poll on which rank 1's heartbeat dies
RECOVER_WINDOW = 4   # post-reshard steps the throughput gate averages
WARMUP = 2           # leading compile steps excluded from the baseline
THROUGHPUT_FLOOR = 0.95

# gray phases
CLEAN_STEPS = 4      # straggler phase: clean baseline before the slow window
SLOW_STEPS = 2       # consecutive slow fetches = StragglerPolicy windows
SLOW_DELAY = 0.25    # injected per-fetch delay (seconds) on the straggler
BASE_STAMP = 0.01    # deterministic stamp floor so micro-jitter on the
                     # healthy ranks' ~us fetches can never fake a 3x ratio
DIVERGE_STEP = 5     # step whose batch the exponent bitflip poisons


def _host_batch(t, rank):
    # keyed by RANK, not by position in the world: a survivor keeps its
    # own data stream across a re-shard
    rs = onp.random.RandomState(1000 + 997 * rank + t)
    return rs.randn(PER_HOST_BATCH, IN_UNITS).astype(onp.float32)


def _global_batch(t, ranks):
    return onp.concatenate([_host_batch(t, r) for r in ranks], axis=0)


class _Job:
    """One incarnation of the emulated pod job: the ``build(world)``
    handle the supervisor expects (``.trainer`` / ``.run_step``)."""

    def __init__(self, world):
        mx.random.seed(SEED)
        self.world = world
        self.ctxs = [mx.cpu(r) for r in world.ranks]
        net = nn.HybridSequential()
        net.add(nn.Dense(16, in_units=IN_UNITS, activation="relu"))
        net.add(nn.Dense(8, in_units=16))
        net.initialize(ctx=self.ctxs)
        self.net = net
        self.trainer = gluon.Trainer(
            net.collect_params(), "sgd",
            {"learning_rate": BASE_LR, "momentum": 0.9},
            kvstore="tpu_ici",
            compression_params={"type": "int8", "block": 64})
        self.step_seconds = []  # (step, wall_seconds, world_size)

    def run_step(self, t):
        t0 = time.perf_counter()
        x = mx.np.array(_global_batch(t, self.world.ranks))
        xs = split_and_load(x, self.ctxs)
        with autograd.record():
            ls = [(self.net(xb) ** 2).mean() for xb in xs]
        autograd.backward(ls)
        self.trainer.step(PER_HOST_BATCH * len(self.ctxs))
        mx.waitall()
        self.step_seconds.append(
            (t, time.perf_counter() - t0, self.world.size))

    def params_np(self):
        return {k: onp.asarray(p.data()._data)
                for k, p in self.net.collect_params().items()}


def _blackbox_root_cause(site, kind, rank=None, dumps=None):
    """Analyze the flight record the phase just produced (a live
    snapshot, or on-disk crash dumps when given) and check the verdict
    names the injected fault's site/kind (and rank when planned)."""
    from tools import blackbox
    if dumps is None:
        dumps = [observe.snapshot(reason="endure")]
    verdict = blackbox.analyze(dumps)
    ok = (verdict["site"] == site and verdict["kind"] == kind
          and (rank is None or verdict["rank"] == rank))
    return ok, verdict


def _phase_preempt(root):
    """Two preemptions, same topology: bitwise trajectory parity."""
    faultline.clear()
    observe.reset()
    world = ElasticWorld.fresh(HOSTS)

    oracle = _Job(world)
    for t in range(STEPS_A):
        oracle.run_step(t)
    want = oracle.params_np()

    reg = telemetry.default_registry()
    labels = {"site": "collective.dispatch", "kind": "preempt"}
    rec0 = reg.get_sample_value(
        "mxtpu_faults_recovered_total", labels) or 0
    res0 = reg.get_sample_value("mxtpu_elastic_reshards_total") or 0
    # one bucket dispatch per step (the whole model fits one bucket):
    # arrival 3 preempts step 2, the replay re-arrives as 4, arrival 5
    # then preempts step 3 — two distinct preempt/resume cycles
    faultline.plan([
        {"site": "collective.dispatch", "kind": "preempt", "at": 3},
        {"site": "collective.dispatch", "kind": "preempt", "at": 5},
    ])
    mgr = CheckpointManager(os.path.join(root, "preempt"),
                            async_write=False, rank=0)
    sup = ElasticSupervisor(_Job, mgr, world=world,
                            pod=EmulatedPod(world.ranks), elastic=True,
                            min_world=1, scaling="linear")
    handle = sup.run(STEPS_A, checkpoint_every=1)
    faultline.clear()
    mgr.close()

    got = handle.params_np()
    recovered = (reg.get_sample_value(
        "mxtpu_faults_recovered_total", labels) or 0) - rec0
    reshards = (reg.get_sample_value(
        "mxtpu_elastic_reshards_total") or 0) - res0
    sup.close()
    bb_ok, _ = _blackbox_root_cause("collective.dispatch", "preempt")
    return {
        "preempt_bitwise": all(
            got[k].tobytes() == want[k].tobytes() for k in want),
        "preempt_recovered_2": recovered == 2,
        "preempt_no_reshard": reshards == 0,
        "preempt_blackbox_root_cause": bb_ok,
    }, {"preempts_recovered": recovered}


def _phase_dead_node(root):
    """Permanent host kill: re-shard 3 -> 2 and keep training."""
    faultline.clear()
    observe.reset()
    world = ElasticWorld.fresh(HOSTS)
    pod = EmulatedPod(world.ranks)
    # one kvstore.kv arrival per live rank per liveness poll (one poll
    # per step): rank 1's stamp goes stale on poll KILL_POLL; the
    # two-observation rule declares it dead one poll later
    faultline.plan([{"site": "kvstore.kv", "kind": "dead_node",
                     "rank": 1, "at": HOSTS * (KILL_POLL - 1) + 2}])

    reg = telemetry.default_registry()
    labels = {"site": "kvstore.kv", "kind": "dead_node"}
    rec0 = reg.get_sample_value(
        "mxtpu_faults_recovered_total", labels) or 0
    res0 = reg.get_sample_value("mxtpu_elastic_reshards_total") or 0

    times = []  # shared across job incarnations

    def build(w):
        job = _Job(w)
        job.step_seconds = times
        return job

    mgr = CheckpointManager(os.path.join(root, "dead"),
                            async_write=False, rank=0)
    sup = ElasticSupervisor(build, mgr, world=world, pod=pod,
                            elastic=True, min_world=2, scaling="linear")
    handle = sup.run(STEPS_B, checkpoint_every=1)
    faultline.clear()
    mgr.close()

    reshards = (reg.get_sample_value(
        "mxtpu_elastic_reshards_total") or 0) - res0
    recovered = (reg.get_sample_value(
        "mxtpu_faults_recovered_total", labels) or 0) - rec0
    world_size = reg.get_sample_value("mxtpu_elastic_world_size")

    # per-host throughput: pre-fault steady median vs the last
    # RECOVER_WINDOW post-reshard steps (both in seconds per step; one
    # step is one global batch, per-host batch constant)
    pre = [dt for _t, dt, size in times if size == HOSTS][WARMUP:]
    post = [dt for _t, dt, size in times if size == HOSTS - 1]
    post = post[-RECOVER_WINDOW:]
    ratio = (statistics.median(pre) / statistics.median(post)
             if pre and post else 0.0)

    finite = all(onp.isfinite(a).all()
                 for a in handle.params_np().values())
    lr = float(handle.trainer.learning_rate)
    want_lr = BASE_LR * (HOSTS - 1) / HOSTS
    sup.close()
    # the abort wrote per-host crash dumps next to the checkpoint dir;
    # the analyzer must root-cause the kill from those dumps alone
    from tools import blackbox
    dumps = blackbox.load(os.path.join(root, "dead", "blackbox"))
    bb_ok, _ = _blackbox_root_cause("kvstore.kv", "dead_node", rank=1,
                                    dumps=dumps) if dumps else (False, None)
    checks = {
        "dead_blackbox_dumped": len(dumps) >= 1,
        "dead_blackbox_root_cause": bb_ok,
        "resharded_once": reshards == 1,
        "dead_node_recovered": recovered >= 1,
        "survivor_world": sup.world.ranks == (0, 2),
        "world_gauge": world_size == HOSTS - 1,
        "lr_linear_rule": abs(lr - want_lr) < 1e-12,
        "params_finite": finite,
        "throughput_recovered": ratio >= THROUGHPUT_FLOOR,
    }
    extra = {"reshards": reshards, "throughput_ratio": ratio, "lr": lr,
             "post_steps": len(post)}
    return checks, extra


class _GrayJob(_Job):
    """The straggler-phase job: stamps per-RANK step times itself (each
    rank's data fetch is timed around the ``data.iterator`` faultline
    hook, where the planned ``slow`` specs fire), so the supervisor's
    own wall timing — which cannot tell ranks apart in one process —
    stays out of the way (``stamps_steptimes``)."""

    stamps_steptimes = True

    def __init__(self, world, pod):
        super().__init__(world)
        self._pod = pod

    def run_step(self, t):
        t0 = time.perf_counter()
        parts, fetch = [], {}
        for r in self.world.ranks:
            f0 = time.perf_counter()
            faultline.check("data.iterator")
            parts.append(_host_batch(t, r))
            fetch[r] = time.perf_counter() - f0
        x = mx.np.array(onp.concatenate(parts, axis=0))
        xs = split_and_load(x, self.ctxs)
        with autograd.record():
            ls = [(self.net(xb) ** 2).mean() for xb in xs]
        autograd.backward(ls)
        self.trainer.step(PER_HOST_BATCH * len(self.ctxs))
        mx.waitall()
        for r in self.world.ranks:
            self._pod.record_steptime(BASE_STAMP + fetch[r], rank=r)
        self.step_seconds.append(
            (t, time.perf_counter() - t0, self.world.size))


def _phase_straggler(root):
    """Gray phase: rank 1 turns 25x slower, gets demoted and resharded
    away, and the survivors keep their pre-fault per-host pace."""
    faultline.clear()
    observe.reset()
    world = ElasticWorld.fresh(HOSTS)
    pod = EmulatedPod(world.ranks)
    # one data.iterator arrival per rank per step (ranks in sorted
    # order): step t, rank r arrives as 3t + r + 1.  Rank 1's fetch is
    # slowed for SLOW_STEPS consecutive steps right after the clean
    # baseline — exactly the StragglerPolicy's window count, so the
    # demotion lands on the check after the second slow step and no
    # slow spec is left to hit a survivor's arrivals post-reshard.
    faultline.plan([
        {"site": "data.iterator", "kind": "slow", "delay": SLOW_DELAY,
         "at": HOSTS * (CLEAN_STEPS + k) + 2}
        for k in range(SLOW_STEPS)])

    reg = telemetry.default_registry()
    deg0 = reg.get_sample_value(
        "mxtpu_node_degraded_total", {"rank": "1"}) or 0
    res0 = reg.get_sample_value("mxtpu_elastic_reshards_total") or 0

    times = []

    def build(w):
        job = _GrayJob(w, pod)
        job.step_seconds = times
        return job

    mgr = CheckpointManager(os.path.join(root, "straggler"),
                            async_write=False, rank=0)
    sup = ElasticSupervisor(build, mgr, world=world, pod=pod,
                            elastic=True, min_world=2, scaling="linear")
    handle = sup.run(STEPS_B, checkpoint_every=1)
    faultline.clear()
    mgr.close()

    degraded = (reg.get_sample_value(
        "mxtpu_node_degraded_total", {"rank": "1"}) or 0) - deg0
    reshards = (reg.get_sample_value(
        "mxtpu_elastic_reshards_total") or 0) - res0
    # pre-fault clean baseline (full world, before the slow window,
    # compile warmup excluded) vs the last RECOVER_WINDOW survivor steps
    pre = [dt for t, dt, size in times
           if size == HOSTS and WARMUP <= t < CLEAN_STEPS]
    post = [dt for _t, dt, size in times if size == HOSTS - 1]
    post = post[-RECOVER_WINDOW:]
    ratio = (statistics.median(pre) / statistics.median(post)
             if pre and post else 0.0)
    finite = all(onp.isfinite(a).all()
                 for a in handle.params_np().values())
    sup.close()
    bb_ok, _ = _blackbox_root_cause("data.iterator", "slow")
    checks = {
        "straggler_demoted": degraded == 1,
        "straggler_resharded": reshards == 1,
        "straggler_survivors": sup.world.ranks == (0, 2),
        "straggler_params_finite": finite,
        "straggler_throughput": ratio >= THROUGHPUT_FLOOR,
        "straggler_blackbox_root_cause": bb_ok,
    }
    return checks, {"straggler_ratio": ratio}


def _phase_bitflip(root):
    """Gray phase: a payload bit flips inside the bucketed allreduce;
    the integrity sideband catches it IN-PROGRAM and the step-guard
    keeps the parameters bitwise untouched that step."""
    del root  # no checkpoints needed: the guard must prevent the damage
    faultline.clear()
    observe.reset()
    reg = telemetry.default_registry()
    vio0 = reg.get_sample_value(
        "mxtpu_integrity_violations_total",
        {"site": "collective.dispatch"}) or 0
    skip0 = reg.get_sample_value("mxtpu_train_steps_skipped_total") or 0
    rec0 = reg.get_sample_value(
        "mxtpu_faults_recovered_total",
        {"site": "collective.dispatch", "kind": "bitflip"}) or 0

    # mxlint: disable=env-read-at-trace-time -- host-side save/restore of the chaos scenario's knob, before any trace exists for this phase's fresh job
    prev = os.environ.get("MXNET_KVSTORE_INTEGRITY")
    os.environ["MXNET_KVSTORE_INTEGRITY"] = "1"
    try:
        job = _Job(ElasticWorld.fresh(HOSTS))
        for t in range(2):          # clean steps: integrity mode is quiet
            job.run_step(t)
        before = {k: v.tobytes() for k, v in job.params_np().items()}
        # the payload channel counts bitflip arrivals separately, so
        # at=1 is the NEXT bucket launch — rank 1's shard gets the flip
        faultline.plan([{"site": "collective.dispatch", "kind": "bitflip",
                         "at": 1, "seed": 5, "rank": 1}])
        job.run_step(2)             # corrupted: caught, update skipped
        after = {k: v.tobytes() for k, v in job.params_np().items()}
        faultline.clear()
        job.run_step(3)             # clean again: training resumes
        resumed = {k: v.tobytes() for k, v in job.params_np().items()}
    finally:
        if prev is None:
            # mxlint: disable=env-read-at-trace-time -- host-side restore of the saved knob on scenario exit; nothing traces here
            os.environ.pop("MXNET_KVSTORE_INTEGRITY", None)
        else:
            os.environ["MXNET_KVSTORE_INTEGRITY"] = prev
        faultline.clear()

    violations = (reg.get_sample_value(
        "mxtpu_integrity_violations_total",
        {"site": "collective.dispatch"}) or 0) - vio0
    skipped = (reg.get_sample_value(
        "mxtpu_train_steps_skipped_total") or 0) - skip0
    recovered = (reg.get_sample_value(
        "mxtpu_faults_recovered_total",
        {"site": "collective.dispatch", "kind": "bitflip"}) or 0) - rec0
    # no checkpoint root here, so the verdict comes from a live snapshot
    bb_ok, _ = _blackbox_root_cause("collective.dispatch", "bitflip",
                                    rank=1)
    checks = {
        "bitflip_caught": violations >= 1,
        "bitflip_step_skipped": skipped == 1,
        "bitflip_params_unchanged": before == after,
        "bitflip_recovered": recovered == 1,
        "bitflip_training_resumed": resumed != after,
        "bitflip_blackbox_root_cause": bb_ok,
    }
    return checks, {"bitflip_violations": violations}


class _DivergeJob(_Job):
    """The divergence-phase job: the global batch passes through the
    ``data.iterator`` corruption hook (where the planned ``bitflip``
    flips an exponent bit), and ``run_step`` returns the synced loss so
    the supervisor's :class:`DivergenceSentinel` sees it."""

    def run_step(self, t):
        t0 = time.perf_counter()
        batch = faultline.corrupt("data.iterator",
                                  _global_batch(t, self.world.ranks))
        x = mx.np.array(batch)
        xs = split_and_load(x, self.ctxs)
        with autograd.record():
            ls = [(self.net(xb) ** 2).mean() for xb in xs]
        autograd.backward(ls)
        self.trainer.step(PER_HOST_BATCH * len(self.ctxs))
        mx.waitall()
        loss = float(sum(float(l.asnumpy()) for l in ls) / len(ls))
        self.step_seconds.append(
            (t, time.perf_counter() - t0, self.world.size))
        return loss


def _phase_divergence(root):
    """Gray phase: a poisoned batch spikes the loss; the supervisor
    rolls back to the newest complete checkpoint once and the run
    completes with finite parameters."""
    faultline.clear()
    observe.reset()
    world = ElasticWorld.fresh(HOSTS)
    reg = telemetry.default_registry()
    rb0 = reg.get_sample_value("mxtpu_sentinel_rollbacks_total") or 0

    # flip the exponent MSB of element 0 of step DIVERGE_STEP's batch
    # (one corrupt call per step, so the payload arrival IS step+1):
    # ~1e38 activations square into an inf/huge loss — a spike the
    # sentinel must catch BEFORE the step is counted or checkpointed
    faultline.plan([{"site": "data.iterator", "kind": "bitflip",
                     "at": DIVERGE_STEP + 1, "seed": 9,
                     "index": 0, "bit": 30}])
    mgr = CheckpointManager(os.path.join(root, "diverge"),
                            async_write=False, rank=0)
    sup = ElasticSupervisor(_DivergeJob, mgr, world=world,
                            pod=EmulatedPod(world.ranks), elastic=True,
                            min_world=2, scaling="linear")
    handle = sup.run(STEPS_B, checkpoint_every=1)
    faultline.clear()
    mgr.close()

    rollbacks = (reg.get_sample_value(
        "mxtpu_sentinel_rollbacks_total") or 0) - rb0
    finite = all(onp.isfinite(a).all()
                 for a in handle.params_np().values())
    steps_run = max(t for t, _dt, _s in handle.step_seconds) + 1
    sup.close()
    bb_ok, _ = _blackbox_root_cause("data.iterator", "bitflip")
    checks = {
        "diverge_rolled_back_once": rollbacks == 1,
        "diverge_run_completed": steps_run == STEPS_B,
        "diverge_params_finite": finite,
        "diverge_blackbox_root_cause": bb_ok,
    }
    return checks, {"diverge_rollbacks": rollbacks}


def run_gray(root):
    t0 = time.perf_counter()
    checks_s, extra_s = _phase_straggler(root)
    checks_f, extra_f = _phase_bitflip(root)
    checks_d, extra_d = _phase_divergence(root)
    checks = dict(checks_s, **checks_f, **checks_d)
    ok = all(checks.values())
    wall = time.perf_counter() - t0
    fail_bits = "" if ok else " FAILED: " + ",".join(
        k for k, v in checks.items() if not v)
    verdict = (
        f"gray_verdict: {'PASS' if ok else 'FAIL'} — straggler rank 1 "
        f"demoted+resharded (per-host throughput "
        f"{extra_s['straggler_ratio']:.2f}x pre-fault, floor "
        f"{THROUGHPUT_FLOOR}), bitflip caught in-program "
        f"({extra_f['bitflip_violations']:.0f} violation(s), params "
        f"bitwise-unchanged that step), divergence rolled back "
        f"{extra_d['diverge_rollbacks']:.0f}x and completed, "
        f"wall={wall:.1f}s{fail_bits}")
    summary = dict(checks, **extra_s, **extra_f, **extra_d,
                   gray_wall=wall)
    return verdict, ok, summary


def run_endure(root):
    t0 = time.perf_counter()
    checks_a, extra_a = _phase_preempt(root)
    checks_b, extra_b = _phase_dead_node(root)
    checks = dict(checks_a, **checks_b)
    ok = all(checks.values())
    wall = time.perf_counter() - t0
    fail_bits = "" if ok else " FAILED: " + ",".join(
        k for k, v in checks.items() if not v)
    verdict = (
        f"endure_verdict: {'PASS' if ok else 'FAIL'} — "
        f"preempts recovered={extra_a['preempts_recovered']:.0f}/2 "
        f"bitwise={'yes' if checks['preempt_bitwise'] else 'NO'}, "
        f"reshards={extra_b['reshards']:.0f} (3->2 on dead rank 1), "
        f"lr={extra_b['lr']:.4g} (linear rule), per-host throughput "
        f"{extra_b['throughput_ratio']:.2f}x pre-fault over last "
        f"{extra_b['post_steps']} steps (floor {THROUGHPUT_FLOOR}), "
        f"wall={wall:.1f}s{fail_bits}")
    summary = dict(checks, **extra_a, **extra_b, wall=wall)
    return verdict, ok, summary


def _run_all(root):
    verdict, ok, _ = run_endure(root)
    print(verdict)
    # mxlint: disable=env-read-at-trace-time -- CI gate opt-out read once per endure run, host-side only
    if os.environ.get("MXTPU_CHAOS_GRAY", "1") != "0":
        gray_verdict, gray_ok, _ = run_gray(root)
        print(gray_verdict)
        ok = ok and gray_ok
    return ok


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--gate", action="store_true",
                    help="exit nonzero when the gate fails")
    ap.add_argument("--root", default=None,
                    help="checkpoint scratch dir (default: a tempdir)")
    args = ap.parse_args(argv)
    import tempfile
    if args.root:
        ok = _run_all(args.root)
    else:
        with tempfile.TemporaryDirectory(prefix="mxtpu-endure-") as root:
            ok = _run_all(root)
    return 1 if (args.gate and not ok) else 0


if __name__ == "__main__":
    sys.exit(main())
