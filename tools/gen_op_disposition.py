"""Generate the full reference-op disposition table.

SURVEY.md §2.2 counts 554 distinct `NNVM_REGISTER_OP` names in the
reference (`grep -rh 'NNVM_REGISTER_OP(' src/operator --include=*.cc`,
registration pattern at
`/root/reference/src/operator/tensor/elemwise_binary_op_basic.cc:82-111`).
This tool maps EVERY one of them to a disposition and writes
`tests/data/op_disposition.tsv`, which `tests/test_op_name_parity.py`
walks:

  path <dotted>        resolves to a callable under `mx.`
  composite <paths>    expressible with the listed public callables
                       (each listed path must resolve)
  autodiff             `_backward_*` registration — jax.vjp dual of the
                       forward op; no explicit backward symbol exists by
                       design (SURVEY §7: XLA/autograd own gradients)
  template <note>      token-pasting macro artifact in the grep (`##`);
                       the concrete expansions are separate rows / noted
  skip <rationale>     intentionally absent, with the reason

Usage:  python tools/gen_op_disposition.py [--reference /root/reference]
Re-run it when the table drifts; the test also re-greps the reference
when it is present and fails on any name the table misses.
"""
from __future__ import annotations

import argparse
import os
import re
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "tests", "data", "op_disposition.tsv")

# ---------------------------------------------------------------------------
# hand triage: names the namespace probe cannot map mechanically.
# Format: name -> (kind, detail)
# ---------------------------------------------------------------------------
HAND = {
    # --- macro/token-pasting artifacts the grep catches literally ---
    "__name$": ("template",
                "UNARY_MATH_OP macro text; concrete unary ops are their own "
                "rows (src/operator/mshadow_op.h)"),
    "name": ("template", "same macro family as __name$"),
    "_npi_##name": ("template",
                    "NPI unary macro; concrete _npi_* rows cover expansions"),
    "_npi_##name##_scalar": ("template",
                             "NPI scalar-rhs macro; np.* binary ops accept "
                             "python scalars directly"),
    "_npi_atleast_##N##d": ("composite",
                            "np.atleast_1d np.atleast_2d np.atleast_3d"),
    "_sample_##distr": ("template",
                        "multisample macro; expansions are nd.sample_"
                        "{uniform,normal,gamma,...} (ndarray/legacy.py)"),
    "_random_pdf_##distr": ("composite", "gluon.probability",
                            ),
    # --- backend/accelerator-specific registrations ---
    "_sg_mkldnn_conv": ("skip",
                        "oneDNN subgraph fusion op; XLA owns op fusion on "
                        "TPU (SURVEY §7 triage, same as subgraph/ "
                        "partitioners)"),
    "_sg_mkldnn_fully_connected": ("skip", "oneDNN subgraph op; see "
                                   "_sg_mkldnn_conv"),
    "_TensorRT": ("skip",
                  "TensorRT subgraph wrapper, CUDA-only; XLA is the TPU "
                  "compiler"),
    "_FusedOp": ("skip", "CUDA RTC fusion container; XLA fuses on TPU"),
    "_FusedOpHelper": ("skip", "see _FusedOp"),
    "_FusedOpOutHelper": ("skip", "see _FusedOp"),
    "CuDNNBatchNorm": ("path", "nd.CuDNNBatchNorm"),
    # --- tvm ---
    "_contrib_tvm_dot": ("skip", "tvmop experiment; moot on TPU (VERDICT "
                         "§2.2 accepted)"),
    "_contrib_tvm_dot_fallback": ("skip", "see _contrib_tvm_dot"),
    "_contrib_tvm_vadd": ("skip", "see _contrib_tvm_dot"),
    # --- intgemm (x86 SIMD int8 GEMM) ---
    "_contrib_intgemm_fully_connected": (
        "composite", "nd.contrib.quantized_fully_connected"),
    "_contrib_intgemm_maxabsolute": ("composite", "np.max np.abs"),
    "_contrib_intgemm_prepare_data": ("composite", "nd.contrib.quantize_v2"),
    "_contrib_intgemm_prepare_weight": ("composite",
                                        "nd.contrib.quantize_v2"),
    "_contrib_intgemm_take_weight": ("composite", "np.take"),
    # --- DGL graph-sampling family (host-side irregular graph work) ---
    "_contrib_dgl_adjacency": ("skip",
                               "DGL plugin graph op; CSR adjacency exists "
                               "(nd.sparse), graph sampling is the external "
                               "library's host-side job"),
    "_contrib_dgl_csr_neighbor_non_uniform_sample": ("skip",
                                                     "see _contrib_dgl_"
                                                     "adjacency"),
    "_contrib_dgl_csr_neighbor_uniform_sample": ("skip",
                                                 "see _contrib_dgl_"
                                                 "adjacency"),
    "_contrib_dgl_graph_compact": ("skip", "see _contrib_dgl_adjacency"),
    "_contrib_dgl_subgraph": ("skip", "see _contrib_dgl_adjacency"),
    "_contrib_edge_id": ("path", "nd.contrib.edge_id"),
    # --- quantization family ---
    "_contrib_quantize": ("path", "nd.contrib.quantize"),
    "_contrib_quantize_v2": ("path", "nd.contrib.quantize_v2"),
    "_contrib_dequantize": ("path", "nd.contrib.dequantize"),
    "_contrib_requantize": ("path", "nd.contrib.requantize"),
    "_contrib_calibrate_entropy": ("path", "nd.contrib.calibrate_entropy"),
    "_contrib_quantized_act": ("composite",
                               "nd.contrib.dequantize nd.Activation "
                               "nd.contrib.quantize_v2"),
    "_contrib_quantized_batch_norm": ("composite",
                                      "nd.contrib.dequantize nd.BatchNorm "
                                      "nd.contrib.quantize_v2"),
    "_contrib_quantized_concat": ("composite",
                                  "nd.contrib.requantize nd.Concat"),
    "_contrib_quantized_conv": ("path", "nd.contrib.quantized_conv"),
    "_contrib_quantized_elemwise_add": ("composite",
                                        "nd.contrib.dequantize "
                                        "nd.elemwise_add "
                                        "nd.contrib.quantize_v2"),
    "_contrib_quantized_elemwise_mul": ("composite",
                                        "nd.contrib.dequantize "
                                        "nd.elemwise_mul "
                                        "nd.contrib.quantize_v2"),
    "_contrib_quantized_embedding": ("composite",
                                     "nd.Embedding nd.contrib.quantize_v2"),
    "_contrib_quantized_flatten": ("composite",
                                   "nd.Flatten"),
    "_contrib_quantized_fully_connected": (
        "path", "nd.contrib.quantized_fully_connected"),
    "_contrib_quantized_pooling": ("composite",
                                   "nd.contrib.dequantize nd.Pooling "
                                   "nd.contrib.quantize_v2"),
    # --- contrib layers now implemented ---
    "_contrib_AdaptiveAvgPooling2D": ("path",
                                      "nd.contrib.AdaptiveAvgPooling2D"),
    "_contrib_BilinearResize2D": ("path", "nd.contrib.BilinearResize2D"),
    "_contrib_BatchNormWithReLU": ("path", "nd.contrib.BatchNormWithReLU"),
    "_contrib_SyncBatchNorm": ("path", "gluon.nn.SyncBatchNorm"),
    "_contrib_RROIAlign": ("skip",
                           "rotated-ROI align; CPU-only in the reference "
                           "(src/operator/contrib/rroi_align.cc), no "
                           "model-zoo user"),
    "_contrib_box_decode": ("path", "nd.contrib.box_decode"),
    "_contrib_box_encode": ("path", "nd.contrib.box_encode"),
    "_contrib_quadratic": ("path", "nd.contrib.quadratic"),
    "_contrib_getnnz": ("path", "nd.contrib.getnnz"),
    "_contrib_dynamic_reshape": ("path", "nd.contrib.dynamic_reshape"),
    "_contrib_group_adagrad_update": ("path",
                                      "nd.contrib.group_adagrad_update"),
    "_contrib_hawkesll": ("path", "nd.contrib.hawkes_ll"),
    "_contrib_backward_hawkesll": ("autodiff", ""),
    "_contrib_backward_index_copy": ("autodiff", ""),
    "_contrib_backward_quadratic": ("autodiff", ""),
    # --- control flow ---
    "_cond": ("path", "nd.contrib.cond"),
    "_foreach": ("path", "nd.contrib.foreach"),
    "_while_loop": ("path", "nd.contrib.while_loop"),
    # --- optimizer families now implemented ---
    "_adamw_update": ("path", "nd.adamw_update"),
    "_mp_adamw_update": ("path", "nd.mp_adamw_update"),
    "_multi_adamw_update": ("path", "nd.multi_adamw_update"),
    "_multi_mp_adamw_update": ("path", "nd.multi_mp_adamw_update"),
    "_multi_lamb_update": ("path", "nd.multi_lamb_update"),
    "_multi_mp_lamb_update": ("path", "nd.multi_mp_lamb_update"),
    "_multi_lans_update": ("path", "nd.multi_lans_update"),
    "_multi_mp_lans_update": ("path", "nd.multi_mp_lans_update"),
    "_sparse_adagrad_update": ("path", "nd.sparse.adagrad_update"),
    # --- numpy stragglers ---
    "_npi_blackman": ("path", "np.blackman"),
    "_npi_hamming": ("path", "np.hamming"),
    "_npi_hanning": ("path", "np.hanning"),
    "_npi_insert_slice": ("path", "np.insert"),
    "_npi_insert_tensor": ("path", "np.insert"),
    "_npi_where_lscalar": ("path", "np.where"),
    "_npi_where_rscalar": ("path", "np.where"),
    "_npi_where_scalar2": ("path", "np.where"),
    "_npi_matrix_rank_none_tol": ("path", "np.linalg.matrix_rank"),
    "_npi_pinv_scalar_rcond": ("path", "np.linalg.pinv"),
    "_npi_normal_n": ("path", "np.random.normal"),
    "_npi_uniform_n": ("path", "np.random.uniform"),
    "_npi_powerd": ("path", "np.power"),
    "_npi_repeats": ("path", "np.repeat"),
    "_npi_share_memory": ("path", "np.may_share_memory"),
    "_npi_tensordot_int_axes": ("path", "np.tensordot"),
    "_npi_advanced_indexing": ("composite", "np.take np.where",),
    "_npi_advanced_indexing_multiple": ("composite", "np.take np.where"),
    "_npi_boolean_mask_assign_scalar": ("composite", "np.where"),
    "_npi_boolean_mask_assign_tensor": ("composite", "np.where"),
    "_npi_backward_ediff1d": ("autodiff", ""),
    "_npi_backward_nan_to_num": ("autodiff", ""),
    "_npi_backward_polyval": ("autodiff", ""),
    "_npi_hsplit_backward": ("autodiff", ""),
    "_npi_rollaxis_backward": ("autodiff", ""),
    "_split_v2_backward": ("autodiff", ""),
    "_broadcast_backward": ("autodiff", ""),
    # --- legacy stragglers ---
    "_split_v2": ("path", "np.split"),
    "_shuffle": ("path", "np.random.shuffle"),
    "_ravel_multi_index": ("path", "np.ravel_multi_index"),
    "_scatter_set_nd": ("path", "nd.scatter_nd"),
    "_slice_assign": ("composite", "NDArray.__setitem__"),
    "_slice_assign_scalar": ("composite", "NDArray.__setitem__"),
    "_zeros_without_dtype": ("path", "np.zeros"),
    "_identity_with_attr_like_rhs": ("composite",
                                     "nd.reshape_like (sparse-grad "
                                     "plumbing helper; tape handles "
                                     "storage metadata)"),
    "_rnn_param_concat": ("composite",
                          "np.concatenate (RNN layers pack params "
                          "functionally, gluon/rnn/rnn_layer.py)"),
    "_sparse_retain": ("path", "nd.sparse.retain"),
    "IdentityAttachKLSparseReg": ("skip",
                                  "sparse-activation KL regularizer from "
                                  "MXNet v0 sparse autoencoders; no gluon "
                                  "or model-zoo user in the reference"),
}

# composite detail strings list space-separated resolvable paths; entries
# that are prose (not dotted paths) are allowed after a path.


def probe(name, mx):
    cands = []
    if name.startswith("_npi_"):
        b = name[5:]
        cands += [f"np.{b}", f"np.random.{b}", f"npx.{b}",
                  f"np.linalg.{b}"]
        for suf in ("_scalar",):
            if b.endswith(suf):
                cands.append(f"np.{b[:-len(suf)]}")
        if b.startswith("r") and b.endswith("_scalar"):
            cands.append(f"np.{b[1:-7]}")
    elif name.startswith("_npx_"):
        cands += [f"npx.{name[5:]}"]
    elif name.startswith("_np_"):
        cands += [f"np.{name[4:]}"]
    elif name.startswith("_contrib_"):
        b = name[9:]
        cands += [f"nd.contrib.{b}", f"nd.contrib.{b.lower()}", f"npx.{b}"]
    elif name.startswith("_image_"):
        cands += [f"nd.image.{name[7:]}"]
    elif name.startswith("_linalg_"):
        cands += [f"nd.linalg.{name[8:]}"]
    elif name.startswith(("_sample_", "_random_")):
        cands += [f"nd.{name}", f"np.random.{name[8:]}"]
    cands += [f"nd.{name}", f"nd.{name.lstrip('_')}", f"np.{name}"]
    for c in cands:
        obj = mx
        ok = True
        for part in c.split("."):
            obj = getattr(obj, part, None)
            if obj is None:
                ok = False
                break
        if ok and obj is not None:
            return c
    return None


def grep_reference(ref):
    res = subprocess.run(
        ["grep", "-rh", "NNVM_REGISTER_OP", os.path.join(
            ref, "src", "operator"), "--include=*.cc"],
        capture_output=True, text=True, check=True)
    names = set()
    for line in res.stdout.splitlines():
        m = re.search(r"NNVM_REGISTER_OP\(([^)]*)\)", line)
        if m:
            names.add(m.group(1))
    return sorted(names)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reference", default="/root/reference")
    args = ap.parse_args()

    import mxnet_tpu as mx

    names = grep_reference(args.reference)
    rows = []
    unresolved = []
    for n in names:
        if n in HAND:
            kind, detail = HAND[n][0], HAND[n][1]
            rows.append((n, kind, detail))
        elif n.startswith("_backward") or "_backward_" in n:
            rows.append((n, "autodiff", ""))
        else:
            p = probe(n, mx)
            if p:
                rows.append((n, "path", p))
            else:
                unresolved.append(n)
                rows.append((n, "MISSING", ""))

    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w") as f:
        f.write("# reference_op\tdisposition\tdetail\n")
        f.write(f"# {len(rows)} names from NNVM_REGISTER_OP grep of "
                "reference src/operator (SURVEY §2.2)\n")
        for n, kind, detail in rows:
            f.write(f"{n}\t{kind}\t{detail}\n")
    counts = {}
    for _, kind, _ in rows:
        counts[kind] = counts.get(kind, 0) + 1
    print(f"wrote {OUT}: {len(rows)} rows, {counts}")
    if unresolved:
        print("UNRESOLVED:")
        for n in unresolved:
            print(" ", n)
        sys.exit(1)


if __name__ == "__main__":
    main()
