#!/usr/bin/env python
"""Multi-process / multi-host training launcher.

Reference: `tools/launch.py` (`:72-74`) — spawns the ps-lite scheduler,
servers, and workers for `kvstore='dist_*'` via local/ssh/mpi launchers.

TPU-native equivalent: SPMD has no scheduler/server roles; every process
is a worker running the same script.  This launcher spawns N processes
(`--launcher local`, the mode the reference CI uses for distributed tests)
wired for `jax.distributed.initialize()`:

  JAX_COORDINATOR_ADDRESS   host:port of process 0
  JAX_NUM_PROCESSES         N
  JAX_PROCESS_ID            0..N-1

On a real TPU pod each host runs one process and the TPU runtime supplies
the topology; `--launcher local` is for CPU-mesh testing (each process gets
a slice of virtual devices), mirroring how the reference tests dist kvstore
with N local processes (`tests/nightly/test_distributed_training-gpu.sh`).

Example:
  python tools/launch.py -n 4 --launcher local -- python train.py --kv-store tpu_ici
"""
from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys

__all__ = ["launch_local"]


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def launch_local(num_workers, command, env_extra=None,
                 devices_per_worker=None):
    """Spawn `num_workers` local processes running `command`; returns the
    list of exit codes (reference local launcher semantics: fail if any
    worker fails)."""
    port = _free_port()
    procs = []
    for rank in range(num_workers):
        env = dict(os.environ)
        env.update(env_extra or {})
        env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["JAX_NUM_PROCESSES"] = str(num_workers)
        env["JAX_PROCESS_ID"] = str(rank)
        # reference-compatible names some scripts read
        env["DMLC_NUM_WORKER"] = str(num_workers)
        env["DMLC_WORKER_ID"] = str(rank)
        if devices_per_worker:
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "") +
                f" --xla_force_host_platform_device_count={devices_per_worker}"
            ).strip()
        procs.append(subprocess.Popen(command, env=env))
    codes = [p.wait() for p in procs]
    return codes


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-n", "--num-workers", type=int, required=True)
    p.add_argument("--launcher", choices=["local"], default="local",
                   help="ssh/mpi/sge/yarn launchers of the reference are "
                        "out of scope: TPU pods schedule one process per "
                        "host through their own runtime")
    p.add_argument("--devices-per-worker", type=int, default=0,
                   help="virtual CPU devices per process (testing)")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="training command (prefix with --)")
    args = p.parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        p.error("no command given")
    codes = launch_local(args.num_workers, command,
                         devices_per_worker=args.devices_per_worker or None)
    bad = [i for i, c in enumerate(codes) if c != 0]
    if bad:
        print(f"workers failed: {bad}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
