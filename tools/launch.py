#!/usr/bin/env python
"""Multi-process / multi-host training launcher.

Reference: `tools/launch.py` (`:72-74`) — spawns the ps-lite scheduler,
servers, and workers for `kvstore='dist_*'` via local/ssh/mpi launchers.

TPU-native equivalent: SPMD has no scheduler/server roles; every process
is a worker running the same script.  This launcher spawns N processes
(`--launcher local`, the mode the reference CI uses for distributed tests)
wired for `jax.distributed.initialize()`:

  JAX_COORDINATOR_ADDRESS   host:port of process 0
  JAX_NUM_PROCESSES         N
  JAX_PROCESS_ID            0..N-1

On a real TPU pod each host runs one process and the TPU runtime supplies
the topology; `--launcher local` is for CPU-mesh testing (each process gets
a slice of virtual devices), mirroring how the reference tests dist kvstore
with N local processes (`tests/nightly/test_distributed_training-gpu.sh`).
`--launcher ssh -H hostfile` drives a real multi-host cluster the way the
reference's ssh launcher does: one peer process per host, env-wired over
the ssh command line (see examples/distributed/README.md for the
v5p-64-shaped invocation).

Examples:
  python tools/launch.py -n 4 --launcher local -- python train.py --kv-store tpu_ici
  python tools/launch.py -n 8 --launcher ssh -H hosts.txt -- python train.py
"""
from __future__ import annotations

import argparse
import os
import shlex
import socket
import subprocess
import sys

# mxlint: disable-file=env-read-at-trace-time -- launcher plumbing: forwards the caller's environment into worker processes before mxnet_tpu ever imports
__all__ = ["launch_local", "launch_ssh", "parse_hostfile"]


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def launch_local(num_workers, command, env_extra=None,
                 devices_per_worker=None):
    """Spawn `num_workers` local processes running `command`; returns the
    list of exit codes (reference local launcher semantics: fail if any
    worker fails)."""
    port = _free_port()
    procs = []
    for rank in range(num_workers):
        env = dict(os.environ)
        env.update(env_extra or {})
        env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["JAX_NUM_PROCESSES"] = str(num_workers)
        env["JAX_PROCESS_ID"] = str(rank)
        # reference-compatible names some scripts read
        env["DMLC_NUM_WORKER"] = str(num_workers)
        env["DMLC_WORKER_ID"] = str(rank)
        if devices_per_worker:
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "") +
                f" --xla_force_host_platform_device_count={devices_per_worker}"
            ).strip()
        procs.append(subprocess.Popen(command, env=env))
    codes = [p.wait() for p in procs]
    return codes


def parse_hostfile(path):
    """One host per line (`#` comments allowed); `host slots=N` MPI-style
    suffixes are accepted and the slot count ignored — on TPU pods each
    host runs exactly one process (reference hostfile format:
    `tools/launch.py -H`, dmlc-tracker ssh launcher)."""
    hosts = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if line:
                hosts.append(line.split()[0])
    if not hosts:
        raise ValueError(f"hostfile {path} lists no hosts")
    return hosts


def launch_ssh(num_workers, command, hosts, coordinator_port=41299,
               env_extra=None, env_forward=(), ssh_binary="ssh",
               remote_cwd=None):
    """Spawn one process per host over ssh (reference
    `tools/launch.py:72-74` ssh launcher, re-wired for SPMD: no
    scheduler/server roles, every process is a peer).

    Ranks are assigned round-robin over ``hosts``; process 0's host serves
    as the JAX coordinator (must be reachable from every worker on
    ``coordinator_port``).  ssh does not forward the environment, so the
    JAX_* wiring plus any ``env_extra``/``env_forward`` variables are
    inlined into the remote command.  ``ssh_binary`` is swappable so tests
    can run the transport against a local shell
    (tests/test_launch_ssh.py)."""
    coordinator = f"{hosts[0]}:{coordinator_port}"
    base_env = {
        "JAX_COORDINATOR_ADDRESS": coordinator,
        "JAX_NUM_PROCESSES": str(num_workers),
        "DMLC_NUM_WORKER": str(num_workers),
    }
    base_env.update(env_extra or {})
    for key in env_forward:
        if key in os.environ:
            base_env.setdefault(key, os.environ[key])
    procs = []
    for rank in range(num_workers):
        host = hosts[rank % len(hosts)]
        env = dict(base_env)
        env["JAX_PROCESS_ID"] = str(rank)
        env["DMLC_WORKER_ID"] = str(rank)
        assigns = " ".join(f"{k}={shlex.quote(v)}" for k, v in
                           sorted(env.items()))
        payload = " ".join(shlex.quote(c) for c in command)
        # cd first, THEN apply env to the actual command — `env VARS cd
        # DIR && cmd` would bind the variables to `cd` and leave the
        # training process unwired
        remote = f"env {assigns} {payload}"
        if remote_cwd:
            remote = f"cd {shlex.quote(remote_cwd)} && {remote}"
        argv = [ssh_binary, "-o", "StrictHostKeyChecking=no",
                "-o", "BatchMode=yes", host, remote]
        procs.append(subprocess.Popen(argv))
    return [p.wait() for p in procs]


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-n", "--num-workers", type=int, required=True)
    p.add_argument("--launcher", choices=["local", "ssh"], default="local",
                   help="'local' spawns N processes on this machine (the "
                        "reference CI pattern); 'ssh' spawns one process "
                        "per hostfile entry (reference ssh launcher). "
                        "mpi/sge/yarn are out of scope: TPU pods schedule "
                        "through their own runtime or ssh")
    p.add_argument("-H", "--hostfile", type=str, default=None,
                   help="hostfile (one host per line), required for ssh")
    p.add_argument("--coordinator-port", type=int, default=41299,
                   help="port on host 0 for jax.distributed coordination")
    p.add_argument("--env", action="append", default=[],
                   help="KEY=VAL to set remotely, or bare KEY to forward "
                        "its current value (reference --env)")
    p.add_argument("--ssh-binary", default="ssh",
                   help="transport override (testing)")
    p.add_argument("--remote-cwd", default=None,
                   help="directory to cd into on each host before running")
    p.add_argument("--devices-per-worker", type=int, default=0,
                   help="virtual CPU devices per process (testing)")
    p.add_argument("--profile-rank", type=int, default=None,
                   help="profile worker rank N from the launcher "
                        "(reference: rank 0 toggling a server profiler "
                        "over a kvstore command, kvstore_dist.h:99); the "
                        "rank dumps profile_rank{N}.json at exit; -1 = "
                        "every rank")
    p.add_argument("--profile-dir", default=".",
                   help="directory for --profile-rank dumps")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="training command (prefix with --)")
    args = p.parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        p.error("no command given")
    env_extra, env_forward = {}, []
    if args.profile_rank is not None:
        if args.profile_rank >= args.num_workers or args.profile_rank < -1:
            p.error(f"--profile-rank {args.profile_rank} out of range "
                    f"(ranks are 0..{args.num_workers - 1}, or -1 for all)")
        env_extra["MXNET_PROFILE_RANK"] = str(args.profile_rank)
        env_extra["MXNET_PROFILE_DIR"] = args.profile_dir
    for item in args.env:
        if "=" in item:
            k, v = item.split("=", 1)
            env_extra[k] = v
        else:
            env_forward.append(item)
    if args.launcher == "ssh":
        if not args.hostfile:
            p.error("--launcher ssh requires -H/--hostfile")
        hosts = parse_hostfile(args.hostfile)
        if args.devices_per_worker:
            env_extra.setdefault(
                "XLA_FLAGS",
                f"--xla_force_host_platform_device_count="
                f"{args.devices_per_worker}")
        codes = launch_ssh(args.num_workers, command, hosts,
                           coordinator_port=args.coordinator_port,
                           env_extra=env_extra, env_forward=env_forward,
                           ssh_binary=args.ssh_binary,
                           remote_cwd=args.remote_cwd)
    else:
        codes = launch_local(args.num_workers, command, env_extra=env_extra,
                             devices_per_worker=args.devices_per_worker or None)
    bad = [i for i, c in enumerate(codes) if c != 0]
    if bad:
        print(f"workers failed: {bad}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
