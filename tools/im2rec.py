#!/usr/bin/env python
"""Pack an image dataset into RecordIO (.rec/.idx/.lst).

Reference: `tools/im2rec.py` / `tools/im2rec.cc` — same three modes:

  1. make a .lst file from an image directory (one class per subfolder):
       python tools/im2rec.py --list prefix image_root
  2. pack a .lst into .rec/.idx (images JPEG-encoded, optionally resized):
       python tools/im2rec.py prefix image_root [--resize N] [--quality Q]

The .rec format is byte-compatible with the reference (pack_img framing
over dmlc recordio), written through the native C++ writer when built.
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mxnet_tpu import recordio  # noqa: E402
from mxnet_tpu import image as mximg  # noqa: E402

_EXTS = {".jpg", ".jpeg", ".png", ".bmp"}


def list_images(root):
    """Yield (relpath, label) with one label per sorted subdirectory."""
    classes = sorted(
        d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d)))
    if classes:
        for label, cls in enumerate(classes):
            for dirpath, _dirs, files in sorted(os.walk(os.path.join(root, cls))):
                for f in sorted(files):
                    if os.path.splitext(f)[1].lower() in _EXTS:
                        yield os.path.relpath(os.path.join(dirpath, f), root), label
    else:
        for i, f in enumerate(sorted(os.listdir(root))):
            if os.path.splitext(f)[1].lower() in _EXTS:
                yield f, 0


def write_list(prefix, root, shuffle=True):
    items = list(list_images(root))
    if shuffle:
        random.shuffle(items)
    lst = prefix + ".lst"
    with open(lst, "w") as f:
        for i, (path, label) in enumerate(items):
            f.write(f"{i}\t{float(label)}\t{path}\n")
    print(f"wrote {len(items)} entries to {lst}")
    return lst


def read_list(lst):
    with open(lst) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            idx = int(parts[0])
            label = [float(x) for x in parts[1:-1]]
            path = parts[-1]
            yield idx, label[0] if len(label) == 1 else label, path


def pack(prefix, root, resize=0, quality=95, color=1, shuffle=True):
    lst = prefix + ".lst"
    if not os.path.exists(lst):
        write_list(prefix, root, shuffle=shuffle)
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    count = 0
    for idx, label, path in read_list(lst):
        img = mximg.imread(os.path.join(root, path), flag=color)
        if resize:
            img = mximg.resize_short(img, resize)
        header = recordio.IRHeader(0, label, idx, 0)
        rec.write_idx(idx, recordio.pack_img(header, img, quality=quality))
        count += 1
        if count % 1000 == 0:
            print(f"packed {count} images")
    rec.close()
    print(f"wrote {count} records to {prefix}.rec")


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("prefix", help="output prefix for .lst/.rec/.idx")
    p.add_argument("root", help="image root directory")
    p.add_argument("--list", action="store_true",
                   help="only generate the .lst file")
    p.add_argument("--resize", type=int, default=0,
                   help="resize shorter side to this many pixels")
    p.add_argument("--quality", type=int, default=95, help="JPEG quality")
    p.add_argument("--no-shuffle", action="store_true")
    p.add_argument("--color", type=int, default=1, choices=[0, 1],
                   help="1: color, 0: grayscale")
    args = p.parse_args(argv)
    if args.list:
        write_list(args.prefix, args.root, shuffle=not args.no_shuffle)
    else:
        pack(args.prefix, args.root, resize=args.resize,
             quality=args.quality, color=args.color,
             shuffle=not args.no_shuffle)


if __name__ == "__main__":
    main()
