"""blackbox — merge per-host flight-recorder dumps into one pod
timeline and name the first domino.

Input: N per-host dumps written by ``mxnet_tpu.observe`` (atomic JSON,
one per host, each a bounded ring of ``(mono_ns, wall_ns, rank,
generation, category, name, payload)`` events).  Output:

* a **merged timeline** — events from every host on one axis, ordered
  by clock-skew-corrected wall time;
* a **chrome-trace JSON** (``{"traceEvents": [...]}``, pid = host rank)
  loadable in Perfetto next to the profiler's own traces;
* a **root-cause verdict** — the earliest anomalous event (injected
  fault, integrity violation, heartbeat gap, non-finite loss, straggler
  demotion) preceding the terminal error in merged order, plus the
  causal chain from it to the outcome.  A clean record yields ``NONE``.

Clock-skew correction: every heartbeat *observation* a host records
carries the peer's stamp (the peer's wall clock at write time) next to
the observer's own ``wall_ns`` — a paired reading of two clocks.  The
median of those pairs estimates each host's offset from the reference
host (biased low by at most one beat of delivery delay, far below the
skews that matter).  Hosts with no heartbeat pairs fall back to
mono-offset alignment on shared generation-bump (``elastic/reshard``)
events; a host with neither is left uncorrected and REPORTED in the
verdict's warnings rather than silently mis-ordered.  Skews beyond
``timeout/2`` — large enough to fool the liveness rule — are corrected
like any other but also called out.

Pure stdlib: the analyzer must run on a machine that has only the
dumps, not the training stack.
"""
from __future__ import annotations

import json
import os
import statistics

__all__ = ["load", "load_dump", "merge", "analyze", "estimate_offsets",
           "render_timeline", "chrome_trace", "verdict_line",
           "is_anomalous"]

_ANOMALOUS_SENTINEL = ("integrity_violation", "divergence_trip",
                       "straggler_demoted")
_CHAIN_FLEET = ("replica_dead", "replica_ejected", "reroute",
                "failover", "replica_readmitted")


def load_dump(path):
    with open(path) as f:
        return json.load(f)


def load(paths):
    """Load dumps from a mix of dump dicts, file paths, and directories
    (directories contribute every ``blackbox-*.json`` inside)."""
    if isinstance(paths, (str, os.PathLike, dict)):
        paths = [paths]
    dumps = []
    for p in paths:
        if isinstance(p, dict):
            dumps.append(p)
            continue
        p = os.fspath(p)
        if os.path.isdir(p):
            for name in sorted(os.listdir(p)):
                if name.startswith("blackbox-") and name.endswith(".json"):
                    dumps.append(load_dump(os.path.join(p, name)))
        else:
            dumps.append(load_dump(p))
    return dumps


def _streams(dumps):
    """Per-host event streams, deduped across overlapping dumps of the
    same host (later dumps of one ring re-contain earlier events)."""
    streams = {}
    dropped = 0
    for d in dumps:
        host = int(d.get("host", 0))
        dropped += int(d.get("dropped", 0) or 0)
        seen = streams.setdefault(host, {})
        for ev in d.get("events", []):
            mono, wall, rank, gen, cat, name = ev[:6]
            payload = ev[6] if len(ev) > 6 else None
            key = (mono, cat, name)
            if key not in seen:
                seen[key] = {"mono_ns": int(mono), "wall_ns": int(wall),
                             "host": host, "rank": rank, "gen": gen,
                             "cat": cat, "name": name,
                             "payload": payload or {}}
    out = {h: sorted(s.values(), key=lambda e: e["mono_ns"])
           for h, s in streams.items()}
    return out, dropped


def estimate_offsets(streams, timeout=60.0):
    """Per-host wall-clock offsets (ns) relative to the lowest host id.

    Returns ``(offsets, method, warnings)`` where ``method[h]`` is one
    of ``reference`` / ``heartbeat`` / ``generation`` / ``uncorrected``.
    """
    hosts = sorted(streams)
    if not hosts:
        return {}, {}, []
    ref = hosts[0]
    samples = {}   # (observer, subject) -> [subject_clock - observer_clock]
    for a, evs in streams.items():
        for e in evs:
            if e["cat"] != "heartbeat" or e["name"] != "observe":
                continue
            stamp = e["payload"].get("stamp")
            b = e["payload"].get("rank")
            if stamp is None or b is None:
                continue
            b = int(b)
            if b == a or b not in streams:
                continue
            samples.setdefault((a, b), []).append(
                float(stamp) * 1e9 - e["wall_ns"])
    offsets = {ref: 0.0}
    method = {ref: "reference"}
    changed = True
    while changed:
        changed = False
        for (a, b), ss in samples.items():
            if a in offsets and b not in offsets:
                offsets[b] = offsets[a] + statistics.median(ss)
                method[b] = "heartbeat"
                changed = True
            elif b in offsets and a not in offsets:
                offsets[a] = offsets[b] - statistics.median(ss)
                method[a] = "heartbeat"
                changed = True
    # fallback: mono-offset alignment on shared generation-bump events
    gens = {}
    for h, evs in streams.items():
        gens[h] = {e["payload"].get("generation"): e["wall_ns"]
                   for e in evs
                   if e["cat"] == "elastic" and e["name"] == "reshard"
                   and e["payload"].get("generation") is not None}
    changed = True
    while changed:
        changed = False
        for h in hosts:
            if h in offsets:
                continue
            for r in [x for x in hosts if x in offsets]:
                shared = set(gens.get(h, ())) & set(gens.get(r, ()))
                if shared:
                    g = min(shared)
                    offsets[h] = (gens[h][g] - gens[r][g]) + offsets[r]
                    method[h] = "generation"
                    changed = True
                    break
    warnings = []
    half = float(timeout) / 2.0
    for h in hosts:
        if h not in offsets:
            offsets[h] = 0.0
            method[h] = "uncorrected"
            warnings.append(
                f"clock skew for host {h} UNCORRECTABLE (no heartbeat "
                f"pairs and no shared generation events): its events "
                f"keep raw wall-clock order and cross-host ordering "
                f"against it is unreliable")
        elif abs(offsets[h]) > half * 1e9:
            warnings.append(
                f"host {h} clock skew {offsets[h] / 1e9:+.3f}s exceeds "
                f"timeout/2 ({half:.1f}s) — uncorrected this would fool "
                f"the heartbeat liveness rule; timeline uses the "
                f"corrected clock")
    return {h: int(offsets[h]) for h in hosts}, method, warnings


def merge(dumps, timeout=60.0):
    """Merge dumps into one corrected timeline.

    Returns ``(entries, offsets, warnings, dropped)``; each entry gains
    ``t_ns`` — wall time mapped onto the reference host's clock."""
    streams, dropped = _streams(dumps)
    offsets, method, warnings = estimate_offsets(streams, timeout=timeout)
    entries = []
    for h, evs in streams.items():
        off = offsets.get(h, 0)
        for i, e in enumerate(evs):
            e = dict(e)
            e["t_ns"] = e["wall_ns"] - off
            e["skew_method"] = method.get(h, "reference")
            e["seq"] = i
            entries.append(e)
    entries.sort(key=lambda e: (e["t_ns"], e["host"], e["seq"]))
    return entries, offsets, warnings, dropped


def is_anomalous(entry):
    cat, name = entry["cat"], entry["name"]
    if cat == "fault":
        return True
    if cat == "sentinel" and name in _ANOMALOUS_SENTINEL:
        return True
    if cat == "heartbeat" and name == "observe" \
            and entry["payload"].get("stale"):
        return True
    return False


def _site_kind_rank(entry):
    cat, name, p = entry["cat"], entry["name"], entry["payload"]
    if cat == "fault":
        return p.get("site"), p.get("kind"), p.get("rank")
    if cat == "heartbeat":
        return "kvstore.kv", "heartbeat_gap", p.get("rank")
    if name == "integrity_violation":
        return p.get("site"), "integrity_violation", None
    if name == "divergence_trip":
        kind = "divergence" if p.get("finite", True) else "non_finite_loss"
        return "train.loss", kind, None
    if name == "straggler_demoted":
        return "kvstore.steptime", "straggler", p.get("rank")
    return cat, name, None


def _in_chain(entry):
    cat, name = entry["cat"], entry["name"]
    if is_anomalous(entry) or cat in ("terminal", "elastic", "recovery"):
        return True
    if cat == "fleet" and name in _CHAIN_FLEET:
        return True
    if cat == "checkpoint" \
            and entry["payload"].get("outcome") not in ("ok", "written"):
        return True
    return False


def analyze(dumps, timeout=60.0, chain_limit=50):
    """The root-cause verdict over the merged timeline."""
    dumps = load(dumps)
    entries, offsets, warnings, dropped = merge(dumps, timeout=timeout)
    hosts = sorted(offsets)
    terminals = [e for e in entries if e["cat"] == "terminal"]
    terminal = terminals[-1] if terminals else None
    anomalies = [e for e in entries if is_anomalous(e)]
    if terminal is not None:
        before = [e for e in anomalies if e["t_ns"] <= terminal["t_ns"]]
        root = before[0] if before else (anomalies[0] if anomalies
                                         else None)
    else:
        root = anomalies[0] if anomalies else None
    verdict = {
        "hosts": hosts, "events": len(entries), "dropped": dropped,
        "offsets_ns": offsets, "warnings": warnings,
        "terminal": terminal, "root_cause": root, "chain": [],
        "site": None, "kind": None, "rank": None,
    }
    if root is None:
        verdict["verdict"] = "NONE"
        return verdict
    site, kind, rank = _site_kind_rank(root)
    verdict["site"], verdict["kind"], verdict["rank"] = site, kind, rank
    verdict["verdict"] = f"{site}/{kind}"
    end_ns = terminal["t_ns"] if terminal is not None \
        else entries[-1]["t_ns"]
    chain = [e for e in entries
             if root["t_ns"] <= e["t_ns"] <= end_ns and _in_chain(e)]
    verdict["chain"] = chain[:chain_limit]
    return verdict


def _fmt_payload(payload, limit=5):
    bits = []
    for k, v in list(payload.items())[:limit]:
        if isinstance(v, float):
            v = f"{v:.6g}"
        bits.append(f"{k}={v}")
    return " ".join(bits)


def render_timeline(entries, limit=None):
    """The merged timeline as text, one line per event, times relative
    to the first event on the reference clock."""
    if not entries:
        return "(no events)"
    t0 = entries[0]["t_ns"]
    shown = entries if limit is None else entries[-limit:]
    lines = []
    for e in shown:
        lines.append(
            f"+{(e['t_ns'] - t0) / 1e6:12.3f}ms host{e['host']} "
            f"g{e['gen']} [{e['cat']}] {e['name']} "
            f"{_fmt_payload(e['payload'])}".rstrip())
    return "\n".join(lines)


def chrome_trace(entries):
    """Chrome-trace/Perfetto JSON, same shape as ``profiler.dumps()``:
    ``{"traceEvents": [...]}`` with pid = host rank.  Events carrying a
    ``seconds`` payload become complete (``X``) spans ending at their
    record time; everything else is an instant (``i``)."""
    if not entries:
        return {"traceEvents": []}
    t0 = entries[0]["t_ns"]
    cats = sorted({e["cat"] for e in entries})
    tid = {c: i for i, c in enumerate(cats)}
    out = []
    for e in entries:
        ts = (e["t_ns"] - t0) / 1e3
        base = {"name": e["name"], "cat": e["cat"], "pid": e["host"],
                "tid": tid[e["cat"]], "args": e["payload"]}
        seconds = e["payload"].get("seconds")
        if isinstance(seconds, (int, float)) and seconds >= 0:
            dur = float(seconds) * 1e6
            out.append(dict(base, ph="X", ts=max(0.0, ts - dur), dur=dur))
        else:
            out.append(dict(base, ph="i", ts=ts, s="p"))
    return {"traceEvents": out}


def verdict_line(verdict):
    warn = (f" [{len(verdict['warnings'])} warning(s): "
            + "; ".join(verdict["warnings"]) + "]"
            if verdict.get("warnings") else "")
    if verdict["verdict"] == "NONE":
        return (f"blackbox_verdict: NONE — no anomalous events "
                f"({verdict['events']} events from "
                f"{len(verdict['hosts'])} host(s)){warn}")
    root, term = verdict["root_cause"], verdict["terminal"]
    rank = f" rank={verdict['rank']}" if verdict["rank"] is not None else ""
    outcome = (f"terminal {term['name']}" if term is not None
               else "no terminal error (recovered in-run)")
    return (f"blackbox_verdict: ROOT-CAUSE {verdict['verdict']}{rank} "
            f"host={root['host']} gen={root['gen']} -> {outcome} "
            f"(chain {len(verdict['chain'])} events, "
            f"{verdict['events']} total from "
            f"{len(verdict['hosts'])} host(s)){warn}")
