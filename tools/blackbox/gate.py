"""The ci.sh ``blackbox`` stage (``python -m tools.blackbox --gate``).

Two halves:

1. **Root-cause on a real crash** — re-runs the endure permanent-kill
   phase with recording on.  ``abort_to_checkpoint`` must have written
   per-host dumps next to the checkpoint dir, and the analyzer must
   root-cause the injected fault by site, kind, AND rank
   (``kvstore.kv/dead_node rank=1``) from those dumps alone.

2. **Overhead on a fault-free run** — 20 clean steps with recording on
   must yield verdict ``NONE``, and the recorder's share of step time
   must stay under 1%.  To keep the gate immune to CI timing noise the
   overhead is measured as *events actually recorded during the run* x
   *microbenchmarked per-record cost* / *run wall time* — not as the
   difference of two noisy end-to-end timings.

Prints one ``blackbox_verdict: PASS|FAIL`` line.
"""
from __future__ import annotations

import os
import tempfile
import time

# standalone process: same virtual-device rig as tools/endure.py, and it
# must be in place before anything imports mxnet_tpu (jax reads
# XLA_FLAGS once, at backend init)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()

OVERHEAD_CEILING = 0.01   # recorder cost / step wall time
CLEAN_STEPS = 20
BENCH_RECORDS = 20000


def run_gate():
    from mxnet_tpu import observe
    from mxnet_tpu.observe import FlightRecorder
    from mxnet_tpu.resilience import ElasticWorld
    from tools import blackbox, endure

    checks = {}

    # -- 1: endure permanent-kill with recording; analyze the dumps ----
    observe.reset()
    ndumps = 0
    with tempfile.TemporaryDirectory(prefix="mxtpu-blackbox-") as root:
        phase_checks, _extra = endure._phase_dead_node(root)
        checks.update({f"endure_{k}": v for k, v in phase_checks.items()})
        dumps = blackbox.load(os.path.join(root, "dead", "blackbox"))
        ndumps = len(dumps)
        checks["crash_dump_written"] = ndumps >= 1
        verdict = blackbox.analyze(dumps) if dumps else {}
        checks["root_cause_site"] = verdict.get("site") == "kvstore.kv"
        checks["root_cause_kind"] = verdict.get("kind") == "dead_node"
        checks["root_cause_rank"] = verdict.get("rank") == 1
        checks["terminal_named"] = (
            (verdict.get("terminal") or {}).get("name") in
            ("DeadNodeError", "DegradedNodeError"))

    # -- 2: fault-free run: verdict NONE + overhead < 1% ---------------
    observe.reset()
    job = endure._Job(ElasticWorld.fresh(endure.HOSTS))
    for t in range(2):                      # compile warmup
        job.run_step(t)
    r0 = observe.snapshot()["recorded"]
    t0 = time.perf_counter()
    for t in range(2, 2 + CLEAN_STEPS):
        job.run_step(t)
    wall = time.perf_counter() - t0
    events_in_run = observe.snapshot()["recorded"] - r0

    scratch = FlightRecorder(capacity=4096, enabled=True)
    b0 = time.perf_counter()
    for _ in range(BENCH_RECORDS):
        scratch.record("bench", "tick", seconds=0.0)
    per_record = (time.perf_counter() - b0) / BENCH_RECORDS
    overhead = events_in_run * per_record / wall if wall > 0 else 1.0

    clean = blackbox.analyze([observe.snapshot(reason="fault_free")])
    checks["fault_free_verdict_none"] = clean["verdict"] == "NONE"
    checks["overhead_under_1pct"] = overhead < OVERHEAD_CEILING

    ok = all(checks.values())
    fail_bits = "" if ok else " FAILED: " + ",".join(
        k for k, v in checks.items() if not v)
    print(
        f"blackbox_verdict: {'PASS' if ok else 'FAIL'} — root-caused "
        f"kvstore.kv/dead_node rank=1 from {ndumps} crash dump(s); "
        f"fault-free {CLEAN_STEPS}-step verdict "
        f"{clean['verdict']} with recorder overhead {overhead * 100:.3f}% "
        f"of step time ({events_in_run} events over {wall:.2f}s at "
        f"{per_record * 1e6:.2f}us/record, ceiling "
        f"{OVERHEAD_CEILING:.0%}){fail_bits}")
    return ok
