"""``python -m tools.blackbox`` — merge per-host flight-recorder dumps
into one pod timeline and print the root-cause verdict.

    python -m tools.blackbox <ckpt_root>/blackbox --timeline
    python -m tools.blackbox dump0.json dump1.json --trace pod.trace.json
    python -m tools.blackbox --gate        # the ci.sh blackbox stage
"""
from __future__ import annotations

import argparse
import json
import sys

from . import (analyze, chrome_trace, load, merge, render_timeline,
               verdict_line)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m tools.blackbox",
        description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="dump files and/or directories holding "
                         "blackbox-*.json (e.g. <ckpt_root>/blackbox)")
    ap.add_argument("--timeout", type=float, default=60.0,
                    help="heartbeat timeout (s) the skew warnings are "
                         "judged against (default 60)")
    ap.add_argument("--timeline", action="store_true",
                    help="print the merged text timeline")
    ap.add_argument("--limit", type=int, default=None,
                    help="timeline: show only the last N events")
    ap.add_argument("--trace", metavar="FILE",
                    help="write a chrome-trace JSON (Perfetto-loadable)")
    ap.add_argument("--json", dest="as_json", action="store_true",
                    help="print the full verdict as JSON")
    ap.add_argument("--gate", action="store_true",
                    help="run the CI gate instead (ignores paths); "
                         "exits nonzero on FAIL")
    args = ap.parse_args(argv)

    if args.gate:
        from .gate import run_gate
        return 0 if run_gate() else 1
    if not args.paths:
        ap.error("no dumps given (pass paths, or --gate)")

    dumps = load(args.paths)
    entries, _offsets, _warnings, _dropped = merge(dumps,
                                                   timeout=args.timeout)
    if args.trace:
        with open(args.trace, "w") as f:
            json.dump(chrome_trace(entries), f)
        print(f"wrote chrome trace: {args.trace} "
              f"({len(entries)} events)")
    if args.timeline:
        print(render_timeline(entries, limit=args.limit))
    verdict = analyze(dumps, timeout=args.timeout)
    if args.as_json:
        print(json.dumps(verdict, indent=2, default=str))
    print(verdict_line(verdict))
    return 0


if __name__ == "__main__":
    sys.exit(main())
