#!/usr/bin/env bash
# Pre-commit smoke gate (VERDICT r1 "Next round" #1): never ship a snapshot
# that cannot import, train a step, or start the bench.  Run from repo root:
#   bash tools/smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"

# 0. import gate (ISSUE 1): a bare import must succeed and the test tree
# must collect with ZERO errors — an import-time crash (like the jax
# shard_map move that broke the seed) can never land again.
python -c "import mxnet_tpu; print('smoke: import ok')"
collect_log=$(mktemp)
if ! python -m pytest tests/ -q --collect-only -p no:cacheprovider \
    > "$collect_log" 2>&1; then
  echo "smoke: FAIL — test collection errored:" >&2
  grep -E "ERROR|error" "$collect_log" | head -20 >&2
  rm -f "$collect_log"
  exit 1
fi
if grep -qE "[0-9]+ errors?" "$collect_log"; then
  echo "smoke: FAIL — collection reported errors:" >&2
  tail -5 "$collect_log" >&2
  rm -f "$collect_log"
  exit 1
fi
rm -f "$collect_log"
echo "smoke: collect-only 0 errors"

# 0b. quick concurrency-contract gate (ISSUE 20): the interprocedural
# lock-order / blocking-under-lock scan is pure-AST (no package import)
# and must stay clean against the EMPTY committed baseline — a new lock
# ordering or a blocking call slipped under a lock can never land
python -m tools.lockscan --verdicts --no-metrics
echo "smoke: lockscan concurrency contracts ok"

python - <<'EOF'
import mxnet_tpu as mx
import numpy as onp

# 1. import + one tiny train step through the Gluon path
net = mx.gluon.nn.Dense(4)
net.initialize()
trainer = mx.gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
x = mx.np.array(onp.random.randn(2, 3).astype(onp.float32))
with mx.autograd.record():
    loss = (net(x) ** 2).mean()
loss.backward()
trainer.step(2)
assert onp.isfinite(loss.asnumpy()).all()
print("smoke: train step ok")

# 1b. resilience gate (ISSUE 9): the full-state checkpoint round-trip —
# a snapshot of the trainer we just stepped must commit atomically and
# restore bitwise into a FRESH net+trainer (docs/RESILIENCE.md)
import tempfile
from mxnet_tpu.resilience import (CheckpointManager, gather_training_state,
                                  restore_training_state)
with tempfile.TemporaryDirectory() as _root:
    with CheckpointManager(_root, async_write=False, rank=0) as _mgr:
        _arrays, _meta = gather_training_state(trainer, step=1)
        _mgr.save(1, _arrays, _meta)
        _net2 = mx.gluon.nn.Dense(4)
        _net2.initialize()
        _net2(x)  # materialize deferred shapes
        _tr2 = mx.gluon.Trainer(_net2.collect_params(), "sgd",
                                {"learning_rate": 0.1})
        _step, _arrays_r, _meta_r = _mgr.restore_latest()
        assert _step == 1, _step
        restore_training_state(_arrays_r, _meta_r, _tr2)
        for _p, _q in zip(trainer._params, _tr2._params):
            assert _p.data().asnumpy().tobytes() == \
                _q.data().asnumpy().tobytes(), _p.name
print("smoke: checkpoint round-trip ok")

# 2. the serving subsystem answers one request end to end
ep = mx.serve.Endpoint(net, max_batch_size=4, max_latency_ms=2)
out = ep.predict(x)
assert out.shape == (2, 4)
assert ep.stats()["completed"] == 1
ep.shutdown(drain=True)
print("smoke: serve round-trip ok")

# 2a'. fleet failover gate (ISSUE 12): 2 replicas, a faultline plan
# kills one at its first dispatch, and the request must complete on the
# survivor with the recovery visible in mxtpu_faults_recovered_total —
# the quick round-trip version of the ci.sh storm stage
from mxnet_tpu import telemetry as _tel
from mxnet_tpu.resilience import faultline as _fl
_fl.clear()
_fl.plan([{"site": "serve.replica", "kind": "preempt", "at": 1}])
_fleet = mx.serve.Fleet(net, replicas=2, name="smoke_fleet",
                        max_batch_size=4, max_latency_ms=2)
_fout = _fleet.predict(x, cls="interactive", timeout_ms=60000)
assert _fout.shape == (2, 4)
_fl.clear()
_dead = [r.index for r in _fleet.replicas if r.state == "dead"]
assert len(_dead) == 1, _fleet.describe_state()
_frec = _tel.default_registry().get_sample_value(
    "mxtpu_faults_recovered_total",
    {"site": "serve.replica", "kind": "preempt"})
assert _frec and _frec >= 1, _frec
_fleet.shutdown(drain=True)
print(f"smoke: fleet failover ok (r{_dead[0]} killed, survivor answered)")

# 2b. telemetry gate (ISSUE 2): the Prometheus exposition must parse and
# reflect the traffic just served — a broken exporter or a silently
# non-publishing endpoint can never land
import re as _re
from mxnet_tpu import telemetry
text = telemetry.export_prometheus()
line_re = _re.compile(
    r'^(# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* ?.*'
    r'|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [-+0-9.eE]+)$')
for line in text.splitlines():
    if line:
        assert line_re.match(line), f"unparseable exposition line: {line!r}"
completed = telemetry.default_registry().get_sample_value(
    "mxtpu_serve_requests_total", {"endpoint": ep.name, "event": "completed"})
assert completed and completed >= 1, f"serve counter not published: {completed}"
assert "mxtpu_trainer_step_phase_seconds" in text  # trainer series present
print("smoke: telemetry export ok")

# 2c. bucketed allreduce gate (ISSUE 4): a multi-copy trainer step must
# collapse gradient collectives below one-per-parameter — if this fires,
# bucketing silently disengaged and every step pays per-key launches
ctxs = [mx.cpu(i) for i in range(4)]
net2 = mx.gluon.nn.HybridSequential()
net2.add(mx.gluon.nn.Dense(8, in_units=6))
net2.add(mx.gluon.nn.Dense(8, in_units=8))
net2.add(mx.gluon.nn.Dense(4, in_units=8))
net2.initialize(ctx=ctxs)
tr2 = mx.gluon.Trainer(net2.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore="tpu_ici")
from mxnet_tpu import autograd as _ag
from mxnet_tpu.gluon.utils import split_and_load as _sal

def _dp_step():
    xs = _sal(mx.np.array(onp.random.randn(8, 6).astype(onp.float32)), ctxs)
    with _ag.record():
        ls = [(net2(xb) ** 2).mean() for xb in xs]
    _ag.backward(ls)
    tr2.step(8)

_dp_step()  # kv init + broadcast + first-step traces
_reg = telemetry.default_registry()
_launch_name = "mxtpu_kvstore_collective_launches_total"
_before = _reg.get_sample_value(_launch_name) or 0.0
_dp_step()
_delta = (_reg.get_sample_value(_launch_name) or 0.0) - _before
_n_params = len([k for k in net2.collect_params()])
assert _n_params == 6 and _delta < _n_params, (_delta, _n_params)
print(f"smoke: bucketed allreduce ok ({int(_delta)} launches for "
      f"{_n_params} params)")

# 2c'. block-scaled quantized allreduce gate (ISSUE 11): the int8 path
# must keep every copy bitwise in sync, reproduce bitwise across fresh
# stores (integer psum is reduction-order-free), and land within the
# block-scale rounding envelope of the dense sum
from mxnet_tpu import kvstore as _kvs

_QN, _QBLK = 128, 64
_qxs = [(onp.random.RandomState(5).randn(_QN) * (c + 1)).astype(onp.float32)
        for c in range(4)]

def _int8_reduce():
    _kv = _kvs.create("tpu_ici")
    _kv.set_gradient_compression({"type": "int8", "block": _QBLK})
    _vals = [mx.np.array(_x, ctx=mx.cpu(c)) for c, _x in enumerate(_qxs)]
    _kv.pushpull(0, _vals)
    return [_v.asnumpy() for _v in _vals]

_q1, _q2 = _int8_reduce(), _int8_reduce()
assert all(onp.array_equal(_q1[0], _c) for _c in _q1[1:]), \
    "int8 reduce left device copies out of sync"
assert all(onp.array_equal(_a, _b) for _a, _b in zip(_q1, _q2)), \
    "int8 reduce must be run-to-run deterministic"
# shared per-block scale = pmax(amax)/127; each copy rounds once, so
# |quantized sum - dense sum| <= n_copies * scale / 2 per element
_qdense = sum(_qxs)
_scale = onp.max(onp.abs(onp.stack(_qxs)).reshape(4, -1, _QBLK),
                 axis=(0, 2)) / 127.0
_qerr = onp.abs(_q1[0] - _qdense).reshape(-1, _QBLK)
assert (_qerr <= len(_qxs) * _scale[:, None] / 2 + 1e-6).all(), \
    "int8 reduce outside the block-scale rounding envelope"
print("smoke: block-scaled int8 allreduce parity ok")

# 2d. input-pipeline gate (ISSUE 10): sharded readers must partition the
# record file deterministically, and the sharded prefetcher must build dp
# global batches accounted under kind=shard_put (one wire crossing, no
# host-side replication)
import io as _pio
import os as _os
import tempfile as _tf
from PIL import Image as _Image
from mxnet_tpu import parallel as _par
from mxnet_tpu import recordio as _rio
from mxnet_tpu.io import DevicePrefetcher as _DPF, ImageRecordIter as _IRI

_tmpd = _tf.mkdtemp()
_rec = _os.path.join(_tmpd, "smoke.rec")
_w = _rio.MXRecordIO(_rec, "w")
_rs = onp.random.RandomState(0)
for _i in range(16):
    _b = _pio.BytesIO()
    _Image.fromarray(_rs.randint(0, 255, (16, 16, 3), dtype=onp.uint8)
                     ).save(_b, "JPEG")
    _w.write(_rio.pack(_rio.IRHeader(0, float(_i), _i, 0), _b.getvalue()))
_w.close()

def _part_labels(part):
    _it = _IRI(_rec, batch_size=4, data_shape=(3, 16, 16), shuffle=True,
               seed=3, num_parts=2, part_index=part, preprocess_threads=2)
    _out = []
    for _ in range(2):
        _, _lab = _it.next_arrays()
        _out.extend(int(_v) for _v in _lab)
    _it.close()
    return _out

_p0, _p1 = _part_labels(0), _part_labels(1)
assert _p0 == _part_labels(0), "sharded reader order must be deterministic"
assert sorted(_p0 + _p1) == list(range(16)), "parts must partition exactly"

_mesh = _par.make_mesh({"dp": -1})
_sh = _par.data_sharding(_mesh)
_it = _IRI(_rec, batch_size=8, data_shape=(3, 16, 16), shuffle=True, seed=3)
_spb = telemetry.default_registry().get_sample_value(
    "mxtpu_mesh_transfer_bytes_total", {"kind": "shard_put"}) or 0.0
with _DPF(_it, sharding=_sh, dtypes=(None, onp.int32)) as _pf:
    _xb, _yb = next(_pf)
assert _xb._data.sharding.is_equivalent_to(_sh, 4), _xb._data.sharding
_spa = telemetry.default_registry().get_sample_value(
    "mxtpu_mesh_transfer_bytes_total", {"kind": "shard_put"}) or 0.0
assert _spa > _spb, "sharded feed must account bytes under kind=shard_put"
_it.close()
print("smoke: input pipeline ok (sharded readers + dp global feed)")

# 2e. flaky-kv retry-storm gate (ISSUE 14): a burst of intermittent
# ConnectionErrors at the pushpull site must be absorbed by the
# per-rank-jittered bounded-backoff retry policy — every pushpull
# completes, the storm is visible in mxtpu_kvstore_retries_total, and
# the recoveries are booked under kind="flaky" (not "timeout") — all
# inside a 10 s wall budget
import time as _time
_fl.clear()
_fl.plan([{"site": "kvstore.pushpull", "kind": "flaky",
           "at": 3 * _k + 1, "times": 2, "seed": _k} for _k in range(6)])
_ret_b = _reg.get_sample_value(
    "mxtpu_kvstore_retries_total", {"site": "kvstore.pushpull"}) or 0.0
_rec_b = _reg.get_sample_value(
    "mxtpu_faults_recovered_total",
    {"site": "kvstore.pushpull", "kind": "flaky"}) or 0.0
_skv = _kvs.create("tpu_ici")
_sval = mx.np.array(onp.ones(8, dtype=onp.float32))
_t0 = _time.monotonic()
for _i in range(12):
    _skv.pushpull(_i, _sval)
_storm_wall = _time.monotonic() - _t0
_fl.clear()
_ret_d = (_reg.get_sample_value(
    "mxtpu_kvstore_retries_total", {"site": "kvstore.pushpull"}) or 0.0
    ) - _ret_b
_rec_d = (_reg.get_sample_value(
    "mxtpu_faults_recovered_total",
    {"site": "kvstore.pushpull", "kind": "flaky"}) or 0.0) - _rec_b
assert _ret_d >= 1, "flaky storm produced no retries"
assert _rec_d >= 1, "recoveries not booked under kind=flaky"
assert _storm_wall < 10.0, f"retry storm blew the wall budget: {_storm_wall}"
print(f"smoke: flaky-kv retry storm ok ({int(_ret_d)} retries, "
      f"{int(_rec_d)} flaky recoveries, {_storm_wall:.1f}s)")

# 3. bench.py must at least import (its main guard must not run)
import importlib.util as _u
spec = _u.spec_from_file_location("bench", "bench.py")
m = _u.module_from_spec(spec)
spec.loader.exec_module(m)
print("smoke: bench import ok")
EOF

# 3b. quick compiled-program contract gate (ISSUE 7): the cheap
# allreduce artifacts only — bucket census + resharding-freedom at the
# HLO level; the full artifact set runs in ci.sh's hloscan stage.  The
# block-scaled programs (ISSUE 11) are pinned here too: quantize +
# scale-agreement pmax + payload psum + dequantize must stay ONE launch
# per bucket (2 all-reduce ops, zero extra dispatches).  The integrity
# variants (ISSUE 14) are pinned too: the digest-agreement sideband must
# cost exactly one extra collective in the SAME program, never a second
# launch
python -m tools.hloscan allreduce.bucket_dense allreduce.bucket_2bit \
  allreduce.bucket_int8 allreduce.bucket_fp8 \
  allreduce.bucket_dense_integrity allreduce.bucket_int8_integrity \
  allreduce.bucketed_step allreduce.bucketed_step_int8 \
  --verdicts --no-metrics
echo "smoke: hloscan allreduce contracts ok"

# 3c. layer-census gate (ISSUE 8): the dp FusedTrainStep census artifact
# must parse and attribute nonzero FLOPs to named Gluon layers — a
# silently-empty census (name scopes stripped, metadata lost) can never
# land.  The full contract gate runs in ci.sh's census stage.
python - <<'EOF'
import json
from tools.layerscope import driver as layerscope

docs = layerscope.census_docs(["fused_train_step_dp"])
path = layerscope.write_artifact(docs[0])
doc = json.loads(open(path).read())
assert doc["schema"] == "mxtpu-layer-census-v1", doc.get("schema")
named = sum(r["flops"] for r in doc["rows"]
            if r["layer"] != "(unattributed)")
assert named > 0, "census attributed zero FLOPs to named layers"
assert doc["attributed_flops_fraction"] >= 0.9, \
    doc["attributed_flops_fraction"]
print(f"smoke: layer census ok ({doc['attributed_flops_fraction']:.1%} "
      f"of {doc['totals']['flops']:.0f} FLOPs attributed)")
EOF

# 3d. sharding-recipe parity gate (ISSUE 16): a dp2.tp2 recipe-built
# fused step must match the dp-only oracle's 3-step loss trajectory
# bitwise at the same global batch — sharding annotations never change
# numerics, so ANY drift means the recipe subsystem broke placement or
# rule collection.  The full recipe rider (3D step + hloscan contract +
# giant-model placement) runs in ci.sh's dryrun stage.
python - <<'EOF'
import numpy as onp
import mxnet_tpu.random as _rng
from mxnet_tpu.analysis.capture import (build_dp_fused_step,
                                        build_recipe_fused_step)

def run3(builder):
    _rng.seed(0)
    fused, (x, y), bs, _meta = builder()
    return [onp.asarray(fused(x, y, batch_size=bs)._data).sum()
            for _ in range(3)]

dp, tp = run3(build_dp_fused_step), run3(build_recipe_fused_step)
assert dp == tp, f"recipe dp2.tp2 diverged from the dp oracle: {dp} vs {tp}"
print(f"smoke: recipe dp2.tp2 parity ok (3-step losses {tp})")
EOF

# 3e. autotune dispatch gate (ISSUE 18): the flash blocks the kernel
# would actually launch with must come from the committed cache entry —
# if dispatch silently falls back to static defaults (cache unreadable,
# fingerprint drift, signature mismatch) this fires.  The full cache
# gate (coverage, stale entries, model re-derivation) runs in ci.sh's
# autotune stage.
python - <<'EOF'
import jax
import jax.numpy as jnp

from mxnet_tpu import tune
from mxnet_tpu.ops.pallas_kernels import _pick_block, _resolve

b, h, t, d = 8, 8, 4096, 64   # the attention bench shape
entry = tune.lookup("flash_attention",
                    tune.signature(jnp.bfloat16, b=b, h=h, t=t, d=d))
assert entry is not None, \
    "committed cache has no flash_attention entry for the bench shape"
qd = jax.ShapeDtypeStruct((b, h, t, d), jnp.bfloat16)
bq, bk, _, _ = _resolve(qd, None, None, None, None)
want = (_pick_block(t, entry["block_q"]), _pick_block(t, entry["block_k"]))
assert (bq, bk) == want, \
    f"flash dispatch chose {(bq, bk)} but the cache pins {want}"
print(f"smoke: autotuned flash blocks ok (bq={bq}, bk={bk} from cache)")
EOF

# 4. the driver entry points compile on the virtual mesh (the full
# hloscan + census + recipe + autotune dryrun riders run in ci.sh's
# dryrun stage, not here — 3d/3e above cover the quick checks)
MXTPU_DRYRUN_HLOSCAN=0 MXTPU_DRYRUN_CENSUS=0 MXTPU_DRYRUN_RESILIENCE=0 \
  MXTPU_DRYRUN_FLEET=0 MXTPU_DRYRUN_GRAY=0 MXTPU_DRYRUN_RECIPE=0 \
  MXTPU_DRYRUN_AUTOTUNE=0 MXTPU_DRYRUN_LOCKSCAN=0 \
  python -c "
import __graft_entry__ as g
g.dryrun_multichip(8)
print('smoke: dryrun_multichip(8) ok')
"
echo "SMOKE PASS"
