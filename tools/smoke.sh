#!/usr/bin/env bash
# Pre-commit smoke gate (VERDICT r1 "Next round" #1): never ship a snapshot
# that cannot import, train a step, or start the bench.  Run from repo root:
#   bash tools/smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"

# 0. import gate (ISSUE 1): a bare import must succeed and the test tree
# must collect with ZERO errors — an import-time crash (like the jax
# shard_map move that broke the seed) can never land again.
python -c "import mxnet_tpu; print('smoke: import ok')"
collect_log=$(mktemp)
if ! python -m pytest tests/ -q --collect-only -p no:cacheprovider \
    > "$collect_log" 2>&1; then
  echo "smoke: FAIL — test collection errored:" >&2
  grep -E "ERROR|error" "$collect_log" | head -20 >&2
  rm -f "$collect_log"
  exit 1
fi
if grep -qE "[0-9]+ errors?" "$collect_log"; then
  echo "smoke: FAIL — collection reported errors:" >&2
  tail -5 "$collect_log" >&2
  rm -f "$collect_log"
  exit 1
fi
rm -f "$collect_log"
echo "smoke: collect-only 0 errors"

python - <<'EOF'
import mxnet_tpu as mx
import numpy as onp

# 1. import + one tiny train step through the Gluon path
net = mx.gluon.nn.Dense(4)
net.initialize()
trainer = mx.gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
x = mx.np.array(onp.random.randn(2, 3).astype(onp.float32))
with mx.autograd.record():
    loss = (net(x) ** 2).mean()
loss.backward()
trainer.step(2)
assert onp.isfinite(loss.asnumpy()).all()
print("smoke: train step ok")

# 2. the serving subsystem answers one request end to end
ep = mx.serve.Endpoint(net, max_batch_size=4, max_latency_ms=2)
out = ep.predict(x)
assert out.shape == (2, 4)
assert ep.stats()["completed"] == 1
ep.shutdown(drain=True)
print("smoke: serve round-trip ok")

# 2b. telemetry gate (ISSUE 2): the Prometheus exposition must parse and
# reflect the traffic just served — a broken exporter or a silently
# non-publishing endpoint can never land
import re as _re
from mxnet_tpu import telemetry
text = telemetry.export_prometheus()
line_re = _re.compile(
    r'^(# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* ?.*'
    r'|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [-+0-9.eE]+)$')
for line in text.splitlines():
    if line:
        assert line_re.match(line), f"unparseable exposition line: {line!r}"
completed = telemetry.default_registry().get_sample_value(
    "mxtpu_serve_requests_total", {"endpoint": ep.name, "event": "completed"})
assert completed and completed >= 1, f"serve counter not published: {completed}"
assert "mxtpu_trainer_step_phase_seconds" in text  # trainer series present
print("smoke: telemetry export ok")

# 3. bench.py must at least import (its main guard must not run)
import importlib.util as _u
spec = _u.spec_from_file_location("bench", "bench.py")
m = _u.module_from_spec(spec)
spec.loader.exec_module(m)
print("smoke: bench import ok")
EOF

# 4. the driver entry points compile on the virtual mesh
python -c "
import __graft_entry__ as g
g.dryrun_multichip(8)
print('smoke: dryrun_multichip(8) ok')
"
echo "SMOKE PASS"
