"""lockscan — interprocedural lock-order / blocking-under-lock analysis.

Static pass over the whole ``mxnet_tpu`` package (lock discovery,
cross-class acquisition-order graph, blocking-call reachability,
condition-variable discipline, signal-handler safety) plus the
crosscheck against the opt-in runtime witness
(``mxnet_tpu.lockwitness``, ``MXNET_LOCKSCAN_WITNESS=1``).  Contract
discipline mirrors mxlint/hloscan: stable finding IDs, reason-REQUIRED
``# lockscan: disable=<rule> -- <reason>`` waivers, an EMPTY committed
``tools/lockscan_baseline.json`` where stale entries FAIL, text/JSON
reporters, ``mxtpu_lockscan_findings`` telemetry, exit 0/1/2.
See docs/STATIC_ANALYSIS.md "Concurrency contracts".
"""
from .driver import main, run, scan, verdict_lines  # noqa: F401
from .model import LockModel, build, crosscheck, find_cycles  # noqa: F401
