"""Condition-variable discipline: predicate loops and owned notifies.

``Condition.wait`` returns on spurious wakeups and on notifies meant
for other waiters, so a wait outside a re-check loop acts on a state
that may not hold — ``wait_for`` (which loops internally) or a
``while``-enclosed ``wait`` are the only safe shapes.  ``notify``
without the condition's lock held races the waiter's predicate check
and raises RuntimeError at runtime.
"""
from __future__ import annotations

from tools.mxlint.core import Finding

from . import Rule


class ConditionWaitNoPredicate(Rule):
    name = "condition-wait-no-predicate"
    description = ("Condition.wait() outside a predicate re-check loop "
                   "(spurious wakeups; use wait_for or while-wrap)")

    def check(self, model):
        for ev in model.waits:
            if ev.wait_for or ev.in_loop:
                continue
            yield Finding(
                rule=self.name, path=ev.relpath, line=ev.line, col=0,
                qualname=ev.qualname,
                message=f"{ev.cond}.wait() has no enclosing predicate "
                        f"loop — a spurious wakeup proceeds on a stale "
                        f"state; use wait_for(pred, timeout)")


class NotifyOutsideLock(Rule):
    name = "notify-outside-lock"
    description = ("Condition.notify()/notify_all() without the owning "
                   "lock lexically held")

    def check(self, model):
        for ev in model.notifies:
            if ev.held:
                continue
            yield Finding(
                rule=self.name, path=ev.relpath, line=ev.line, col=0,
                qualname=ev.qualname,
                message=f"{ev.cond}.notify() outside `with {ev.cond.split(':')[-1]}:` "
                        f"— races the waiter's predicate check and raises "
                        f"RuntimeError('cannot notify on un-acquired lock')")
