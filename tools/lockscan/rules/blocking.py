"""blocking-under-lock: a blocking operation runs while a lock is held.

Anything parked under a lock parks every other thread that wants the
lock too — ``Future.result``/``Thread.join`` turn into deadlocks the
moment the worker being waited on needs the held lock, unbounded
``queue.get`` and device syncs turn tail latency into lock hold time,
and file I/O under a hot-path lock is a p99 cliff.  Interprocedural:
the blocking call may be several resolved calls below the ``with``.
"""
from __future__ import annotations

from tools.mxlint.core import Finding

from . import Rule


class BlockingUnderLock(Rule):
    name = "blocking-under-lock"
    description = ("blocking call (result/join/get-no-timeout/device "
                   "sync/file I/O/subprocess) while a lock is held")

    def check(self, model):
        seen = set()
        for ev in model.blocking:
            key = (ev.relpath, ev.line, ev.desc, ev.chain)
            if key in seen:
                continue
            seen.add(key)
            held = ", ".join(ev.held)
            via = f" via {ev.chain}" if ev.chain else ""
            yield Finding(
                rule=self.name, path=ev.relpath, line=ev.line, col=0,
                qualname=ev.qualname,
                message=f"{ev.desc} while holding {held}{via}")
