"""lock-order-cycle: a cycle in the acquisition-order graph.

Two threads walking the same cycle from different entry edges deadlock;
a self-edge on a non-reentrant ``threading.Lock`` deadlocks a single
thread on its own.  The finding is anchored at the evidence site of the
cycle's first edge (smallest lock key first, so the anchor is stable),
and the message spells out every edge with its site and call chain.
"""
from __future__ import annotations

from tools.mxlint.core import Finding

from . import Rule
from ..model import find_cycles


class LockOrderCycle(Rule):
    name = "lock-order-cycle"
    description = ("cycle in the lock acquisition-order graph "
                   "(potential deadlock; self-edge on a plain Lock "
                   "is a single-thread deadlock)")

    def check(self, model):
        evidence = {}
        for e in model.edges:
            evidence.setdefault((e.src, e.dst), e)
        for cyc in find_cycles(evidence):
            hops = list(zip(cyc, cyc[1:] + cyc[:1]))
            sites = []
            for src, dst in hops:
                e = evidence[(src, dst)]
                via = f" via {e.chain}" if e.chain else ""
                sites.append(f"{src} -> {dst} at {e.relpath}:{e.line}"
                             f" ({e.qualname}){via}")
            anchor = evidence[hops[0]]
            if len(cyc) == 1:
                msg = (f"non-reentrant Lock {cyc[0]} re-acquired while "
                       f"already held: {sites[0]}")
            else:
                msg = ("lock-order cycle " +
                       " -> ".join(cyc + (cyc[0],)) + ": " +
                       "; ".join(sites))
            yield Finding(rule=self.name, path=anchor.relpath,
                          line=anchor.line, col=0, message=msg,
                          qualname=anchor.qualname)
