"""signal-unsafe: locks or blocking work reachable from a signal handler.

A signal handler runs *on top of* whatever bytecode the main thread was
executing — if that thread holds the lock the handler wants, the
handler deadlocks the process at the exact moment (SIGTERM on
preemption) it most needs to make progress.  The safe shape is the
classic self-pipe: the handler only sets a flag or ``os.write``s a
pre-opened fd, and a normal thread does the real work.
"""
from __future__ import annotations

from tools.mxlint.core import Finding

from . import Rule


class SignalUnsafe(Rule):
    name = "signal-unsafe"
    description = ("signal handler reaches a lock acquisition or "
                   "blocking call (handler may interrupt the holder)")

    def check(self, model):
        seen = set()
        for ev in model.signals:
            key = (ev.relpath, ev.line, ev.handler, ev.desc)
            if key in seen:
                continue
            seen.add(key)
            yield Finding(
                rule=self.name, path=ev.relpath, line=ev.line, col=0,
                qualname=ev.qualname,
                message=f"handler {ev.handler} {ev.desc} — a handler "
                        f"interrupting the holder deadlocks; only set a "
                        f"flag or os.write a pre-opened fd")
