"""lockscan rule registry.

Unlike mxlint's per-file rules, every lockscan rule reads the finished
interprocedural :class:`~tools.lockscan.model.LockModel`: a rule is a
class with a unique ``name`` (the waiver token), a one-line
``description``, and a ``check(model)`` hook yielding
:class:`~tools.mxlint.core.Finding`.  Waivers use the mxlint grammar
with the ``lockscan`` tag::

    with self._lock:  # lockscan: disable=blocking-under-lock -- build-once barrier
"""
from __future__ import annotations


class Rule:
    name = ""
    description = ""

    def check(self, model):
        return []


def all_rules():
    """Fresh instances of every shipped rule."""
    from .blocking import BlockingUnderLock
    from .condition import ConditionWaitNoPredicate, NotifyOutsideLock
    from .order import LockOrderCycle
    from .signal_safe import SignalUnsafe
    return [
        LockOrderCycle(),
        BlockingUnderLock(),
        ConditionWaitNoPredicate(),
        NotifyOutsideLock(),
        SignalUnsafe(),
    ]
