"""lockscan driver: build the lock model, check, waive, baseline, report.

Exit status mirrors mxlint/hloscan: 0 when every finding is waived or
baselined AND the baseline is current, 1 when an unbaselined finding
remains OR the baseline names findings that no longer exist (stale
entries are paid debts — prune them in the same change via
``--update-baseline``), 2 on usage error.

``--crosscheck REPORT.json`` additionally verifies a runtime witness
report (written by ``mxnet_tpu.lockwitness`` when
``MXNET_LOCKSCAN_REPORT`` is set): the merged static+observed
acquisition graph must be acyclic, and every observed edge into a
non-leaf lock must exist in the static model.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from tools.mxlint import core

from . import model as lockmodel
from .rules import all_rules

DEFAULT_BASELINE = os.path.join(core.REPO_ROOT, "tools",
                                "lockscan_baseline.json")

JSON_SCHEMA_VERSION = 1


def scan(paths=None, rules=None, repo_root=None):
    """Build the model and run ``rules`` (default: all) over it.
    Returns (findings, n_files, model); waivers applied, IDs assigned,
    no baseline."""
    rules = all_rules() if rules is None else rules
    model, ctx_by_path, n_files, parse_findings = lockmodel.build(
        paths, repo_root=repo_root)
    by_file = {}
    for f in parse_findings:
        by_file.setdefault(f.path, []).append(f)
    for rule in rules:
        for f in rule.check(model) or ():
            by_file.setdefault(f.path, []).append(f)
    findings = []
    for relpath, ctx in ctx_by_path.items():
        findings.extend(core.apply_waivers(by_file.pop(relpath, []), ctx,
                                           tool="lockscan"))
    for leftover in by_file.values():    # parse errors: no ctx, no waivers
        findings.extend(leftover)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    core.assign_ids(findings, ctx_by_path)
    return findings, n_files, model


def load_baseline(path):
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return data.get("findings", {})


def write_baseline(path, findings):
    """Grandfather every current unwaived finding (``--update-baseline``)."""
    entries = {
        f.id: {"rule": f.rule, "path": f.path, "qualname": f.qualname,
               "message": f.message}
        for f in findings if not f.waived}
    payload = {
        "comment": "lockscan grandfathered findings — entries are debts, "
                   "not permissions; remove as they are fixed. Stale "
                   "entries FAIL the scan. Regenerate with "
                   "`python -m tools.lockscan --update-baseline`.",
        "version": JSON_SCHEMA_VERSION,
        "findings": dict(sorted(entries.items())),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=False)
        f.write("\n")
    return entries


def verdict_lines(findings, n_files, rules=None):
    """Per-rule ``lockscan <rule> PASS|FAIL`` lines for the dryrun rider —
    a rule FAILs when any unwaived, unbaselined finding of it exists."""
    rules = all_rules() if rules is None else rules
    live = {}
    for f in findings:
        if not f.waived and not f.baselined:
            live[f.rule] = live.get(f.rule, 0) + 1
    lines = []
    for rule in rules:
        n = live.get(rule.name, 0)
        verdict = "PASS" if not n else f"FAIL ({n})"
        lines.append(f"lockscan {rule.name:28s} {verdict}  "
                     f"[{n_files} files]")
    return lines


def publish_metrics(findings):
    """Mirror the finding census into the telemetry registry (best
    effort: lockscan must work without mxnet_tpu importable)."""
    try:
        from mxnet_tpu import telemetry
    except Exception:  # mxlint: disable=swallowed-exception -- lockscan must run without mxnet_tpu importable; the False return IS the report
        return False
    g = telemetry.gauge(
        "mxtpu_lockscan_findings",
        "lockscan findings by rule and disposition",
        labelnames=("rule", "disposition"))
    per = {}
    for f in findings:
        disp = "waived" if f.waived else (
            "baselined" if f.baselined else "live")
        per[(f.rule, disp)] = per.get((f.rule, disp), 0) + 1
    for rule in all_rules():
        for disp in ("live", "waived", "baselined"):
            g.labels(rule=rule.name, disposition=disp).set(
                per.get((rule.name, disp), 0))
    return True


def report_text(findings, n_files, stale_ids, out=sys.stdout):
    unbaselined = [f for f in findings if not f.waived and not f.baselined]
    for f in unbaselined:
        out.write(f"{f.path}:{f.line}:{f.col + 1}: [{f.rule}] "
                  f"{f.message}  (id {f.id})\n")
    n_w = sum(1 for f in findings if f.waived)
    n_b = sum(1 for f in findings if f.baselined)
    if stale_ids:
        out.write(f"lockscan: FAIL — {len(stale_ids)} baseline entr"
                  f"{'y names a finding' if len(stale_ids) == 1 else 'ies name findings'} "
                  f"that no longer exist{'s' if len(stale_ids) == 1 else ''} "
                  f"(debt paid — prune it in the same change with "
                  f"--update-baseline): {', '.join(sorted(stale_ids))}\n")
    verdict = "clean" if not unbaselined else \
        f"{len(unbaselined)} unbaselined finding" + \
        ("s" if len(unbaselined) != 1 else "")
    out.write(f"lockscan: {verdict} — {n_files} files, "
              f"{len(findings)} findings ({n_w} waived, {n_b} baselined)\n")


def report_json(findings, n_files, stale_ids, out=sys.stdout):
    unbaselined = [f for f in findings if not f.waived and not f.baselined]
    payload = {
        "version": JSON_SCHEMA_VERSION,
        "tool": "lockscan",
        "files_scanned": n_files,
        "findings": [f.to_json() for f in findings],
        "stale_baseline_ids": sorted(stale_ids),
        "summary": {
            "total": len(findings),
            "waived": sum(1 for f in findings if f.waived),
            "baselined": sum(1 for f in findings if f.baselined),
            "unbaselined": len(unbaselined),
        },
    }
    json.dump(payload, out, indent=2)
    out.write("\n")


def run_crosscheck(model, report_path, out=sys.stdout):
    """Verify a witness report against the static model; 0 = consistent."""
    try:
        with open(report_path, "r", encoding="utf-8") as f:
            report = json.load(f)
    except (OSError, ValueError) as e:
        out.write(f"lockscan: crosscheck FAIL — cannot read "
                  f"{report_path}: {e}\n")
        return 1
    edges = [tuple(e) for e in report.get("edges", ())]
    problems, unmodeled = lockmodel.crosscheck(model, edges)
    if report.get("violations"):
        for v in report["violations"]:
            problems.append(f"witness-reported violation: {v}")
    for p in problems:
        out.write(f"lockscan: crosscheck FAIL — {p}\n")
    tolerated = len(unmodeled) - sum(
        1 for p in problems if "under-approximating" in p)
    out.write(f"lockscan: crosscheck {'FAIL' if problems else 'ok'} — "
              f"{len(edges)} observed edges, {len(unmodeled)} unmodeled "
              f"({tolerated} into leaf locks, tolerated), "
              f"{len(problems)} problems\n")
    return 1 if problems else 0


def run(paths=None, baseline_path=None, update_baseline=False,
        fmt="text", verdicts=False, metrics=True, crosscheck_path=None,
        out=sys.stdout, repo_root=None):
    """Full pipeline; returns the process exit code."""
    findings, n_files, model = scan(paths, repo_root=repo_root)
    baseline = {}
    if baseline_path:
        baseline = load_baseline(baseline_path)
        for f in findings:
            if not f.waived and f.id in baseline:
                f.baselined = True
    if update_baseline:
        if not baseline_path:
            out.write("lockscan: --update-baseline needs --baseline PATH\n")
            return 2
        entries = write_baseline(baseline_path, findings)
        out.write(f"lockscan: baseline written — {len(entries)} entr"
                  f"{'y' if len(entries) == 1 else 'ies'} -> "
                  f"{baseline_path}\n")
        return 0
    present = {f.id for f in findings if not f.waived}
    stale_ids = set(baseline) - present
    if metrics:
        publish_metrics(findings)
    (report_json if fmt == "json" else report_text)(
        findings, n_files, stale_ids, out=out)
    if verdicts:
        for line in verdict_lines(findings, n_files):
            out.write(line + "\n")
    rc_cross = 0
    if crosscheck_path:
        rc_cross = run_crosscheck(model, crosscheck_path, out=out)
    failed = any(not f.waived and not f.baselined for f in findings)
    return 1 if (failed or stale_ids or rc_cross) else 0


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m tools.lockscan",
        description="Interprocedural lock-order / blocking-under-lock "
                    "analysis with a runtime acquisition witness "
                    "(docs/STATIC_ANALYSIS.md).")
    p.add_argument("paths", nargs="*",
                   help="files/directories to scan (default: mxnet_tpu/)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="baseline JSON of grandfathered finding IDs "
                        "(default: tools/lockscan_baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline (report everything)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from current findings")
    p.add_argument("--verdicts", action="store_true",
                   help="append per-rule PASS/FAIL verdict lines")
    p.add_argument("--no-metrics", action="store_true",
                   help="skip publishing the finding census to telemetry")
    p.add_argument("--crosscheck", metavar="REPORT",
                   help="verify a witness report (MXNET_LOCKSCAN_REPORT "
                        "dump) against the static model")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.name:30s} {rule.description}")
        return 0

    return run(paths=args.paths or None,
               baseline_path=None if args.no_baseline else args.baseline,
               update_baseline=args.update_baseline,
               fmt=args.format, verdicts=args.verdicts,
               metrics=not args.no_metrics,
               crosscheck_path=args.crosscheck)


if __name__ == "__main__":
    sys.exit(main())
