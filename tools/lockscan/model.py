"""lockscan lock model: discovery, interprocedural summaries, events.

The model is built once per scan from the parsed project (mxlint's
:class:`~tools.mxlint.core.ProjectIndex` does symbol/call resolution;
this module adds the concurrency semantics on top):

* **Locks** — every ``self.X = threading.Lock/RLock/Condition()``
  attribute and every module-level ``_lock = threading.Lock()`` gets a
  stable key ``"<relpath>:<Class>.<attr>"`` / ``"<relpath>:<name>"``
  plus a creation-site index the runtime witness's report maps back
  onto.
* **Edges** — walking every function with a per-thread-style held
  stack: each acquisition (lexical ``with lock:`` or one reached
  through a resolved call chain) while ``h`` is held adds the order
  edge ``h -> acquired``, with the evidence site and call chain kept
  for the report.
* **Events** — blocking operations under a held lock,
  ``Condition.wait`` calls and whether a predicate loop encloses them,
  ``notify`` calls and whether the owning lock is lexically held, and
  the closure of work reachable from installed signal handlers.

Summaries are memoized per function and recursion-guarded, so the walk
is linear in project size even with call cycles.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field

from tools.mxlint import core

#: constructor type tags (from ProjectIndex attr/var inference) that are
#: lock objects, and whether re-acquiring one on the same thread
#: deadlocks (plain Lock) or not (RLock; Condition wraps an RLock).
LOCK_KINDS = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
}

#: module/function calls that block the calling thread.  Receiver-typed
#: entries (queue get, thread join, future result) are handled in
#: :meth:`_Walker._classify_blocking` with extra context.
_BLOCKING_NAME_CALLS = {
    "sleep": "time.sleep() blocks the holder",
    "fsync": "os.fsync() blocks on storage",
    "open": "open() is file I/O",
}
_BLOCKING_ATTR_CALLS = {
    "sleep": "time.sleep() blocks the holder",
    "fsync": "os.fsync() blocks on storage",
    "block_until_ready": "device sync blocks until the accelerator drains",
    "asnumpy": "asnumpy() is a device->host sync",
    "device_put": "jax.device_put() is host->device traffic",
}
_SUBPROCESS_CALLS = {"run", "call", "check_call", "check_output"}


@dataclass
class LockInfo:
    key: str            # "<relpath>:<Class>.<attr>" or "<relpath>:<var>"
    kind: str           # Lock | RLock | Condition
    relpath: str
    line: int           # creation-site line (witness report maps here)


@dataclass
class Edge:
    """One piece of evidence that ``src`` is held while ``dst`` is
    acquired.  ``chain`` is the resolved call path ("" when lexical)."""
    src: str
    dst: str
    relpath: str
    line: int
    qualname: str
    chain: str = ""


@dataclass
class BlockingEvent:
    held: tuple         # lock keys held, outermost first
    desc: str           # what blocks, e.g. "queue.Queue.get() without timeout"
    relpath: str
    line: int
    qualname: str
    chain: str = ""


@dataclass
class WaitEvent:
    cond: str
    relpath: str
    line: int
    qualname: str
    in_loop: bool
    wait_for: bool


@dataclass
class NotifyEvent:
    cond: str
    relpath: str
    line: int
    qualname: str
    held: bool          # owning Condition lexically held at the call


@dataclass
class SignalEvent:
    """Blocking/locking work reachable from an installed signal handler."""
    handler: str        # handler qualname
    desc: str           # offending operation
    relpath: str        # site of the signal.signal() installation
    line: int
    qualname: str
    chain: str


@dataclass
class _Summary:
    """What calling this function does, as seen by a caller that may be
    holding locks: every lock key it can acquire (transitively) and
    every blocking op it exposes that is NOT already under one of its
    own locks (those are reported at the inner site instead)."""
    acquires: dict = field(default_factory=dict)   # key -> (site, chain)
    blocking: list = field(default_factory=list)   # (desc, site, chain)


class LockModel:
    def __init__(self, ctxs):
        self.ctxs = {ctx.relpath: ctx for ctx in ctxs}
        self.index = core.ProjectIndex(ctxs)
        self.locks = {}          # key -> LockInfo
        self.site_index = {}     # (relpath, line) -> key
        self.edges = []          # list[Edge]
        self.blocking = []       # list[BlockingEvent]
        self.waits = []          # list[WaitEvent]
        self.notifies = []       # list[NotifyEvent]
        self.signals = []        # list[SignalEvent]
        self._summaries = {}     # id(fn) -> _Summary
        self._in_progress = set()
        self._discover_locks()
        self._walk_all()
        self._walk_signal_handlers()

    # -- lock discovery ----------------------------------------------------
    def _discover_locks(self):
        for relpath, ctx in self.ctxs.items():
            mod = self.index.modules.get(relpath)
            if mod is None:
                continue
            for name, tag in mod.var_types.items():
                if tag in LOCK_KINDS:
                    self._add_lock(f"{relpath}:{name}", LOCK_KINDS[tag],
                                   relpath, self._var_line(mod, name))
            for cls in mod.classes.values():
                for attr, tag in cls.attr_types.items():
                    if tag in LOCK_KINDS:
                        self._add_lock(
                            f"{relpath}:{cls.name}.{attr}", LOCK_KINDS[tag],
                            relpath, self._attr_line(cls, attr))

    def _add_lock(self, key, kind, relpath, line):
        self.locks[key] = LockInfo(key=key, kind=kind, relpath=relpath,
                                   line=line)
        self.site_index[(relpath, line)] = key

    @staticmethod
    def _var_line(mod, name):
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    node.targets[0].id == name:
                return node.lineno
        return 1

    @staticmethod
    def _attr_line(cls, attr):
        for m in cls.methods.values():
            for node in ast.walk(m):
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    t = node.targets[0]
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self" and t.attr == attr:
                        return node.lineno
        return cls.node.lineno

    # -- lock expression resolution ----------------------------------------
    def lock_key_of(self, expr, mod, cls):
        """Lock key named by ``expr`` in (mod, cls) scope, or None."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            if expr.value.id == "self" and cls is not None:
                return self._class_lock(cls.key, expr.attr)
            # `with _state.lock:` — module-level instance of a project class
            tkey = mod.var_types.get(expr.value.id)
            if tkey is not None:
                return self._class_lock(tkey, expr.attr)
            return None
        if isinstance(expr, ast.Name):
            lk = f"{mod.relpath}:{expr.id}"
            return lk if lk in self.locks else None
        return None

    def _class_lock(self, class_key, attr):
        # walk project bases so subclasses see inherited locks
        seen, stack = set(), [class_key]
        while stack:
            key = stack.pop()
            if key in seen:
                continue
            seen.add(key)
            c = self.index.class_by_key(key)
            if c is None:
                continue
            lk = f"{c.relpath}:{c.name}.{attr}"
            if lk in self.locks:
                return lk
            stack.extend(c.base_keys)
        return None

    # -- interprocedural walk ----------------------------------------------
    def _walk_all(self):
        for relpath in sorted(self.ctxs):
            mod = self.index.modules.get(relpath)
            if mod is None:
                continue
            for fn in mod.functions.values():
                self.summarize(fn, mod, None)
            for cls in mod.classes.values():
                for fn in cls.methods.values():
                    self.summarize(fn, mod, cls)

    def summarize(self, fn, mod, cls):
        key = id(fn)
        if key in self._summaries:
            return self._summaries[key]
        if key in self._in_progress:        # recursion: fixpoint = empty
            return _Summary()
        self._in_progress.add(key)
        summary = _Summary()
        walker = _Walker(self, mod, cls, fn, summary)
        walker.run()
        self._in_progress.discard(key)
        self._summaries[key] = summary
        return summary

    # -- signal safety ------------------------------------------------------
    def _walk_signal_handlers(self):
        for relpath, ctx in self.ctxs.items():
            mod = self.index.modules.get(relpath)
            if mod is None:
                continue
            for node in ast.walk(ctx.tree):
                if not (isinstance(node, ast.Call) and
                        isinstance(node.func, ast.Attribute) and
                        node.func.attr == "signal" and
                        isinstance(node.func.value, ast.Name) and
                        node.func.value.id == "signal" and
                        len(node.args) >= 2):
                    continue
                handler = node.args[1]
                targets = self._resolve_handler(handler, mod, ctx, node)
                for hmod, hcls, hfn in targets:
                    hname = hfn.name if hcls is None else \
                        f"{hcls.name}.{hfn.name}"
                    sub = self.summarize(hfn, hmod, hcls)
                    for lk, (site, chain) in sorted(sub.acquires.items()):
                        self.signals.append(SignalEvent(
                            handler=hname,
                            desc=f"acquires {lk}"
                                 f"{' via ' + chain if chain else ''}",
                            relpath=relpath, line=node.lineno,
                            qualname=ctx.qualname_at(node.lineno),
                            chain=chain))
                    for desc, site, chain in sub.blocking:
                        self.signals.append(SignalEvent(
                            handler=hname,
                            desc=f"{desc}"
                                 f"{' via ' + chain if chain else ''}",
                            relpath=relpath, line=node.lineno,
                            qualname=ctx.qualname_at(node.lineno),
                            chain=chain))

    def _resolve_handler(self, handler, mod, ctx, site):
        """The function object(s) a handler expression names."""
        if isinstance(handler, ast.Name):
            if handler.id in mod.functions:
                return [(mod, None, mod.functions[handler.id])]
            imp = mod.imports.get(handler.id)
            if imp and imp[0] == "symbol":
                tgt = self.index.by_dotted.get(imp[1])
                if tgt and imp[2] in tgt.functions:
                    return [(tgt, None, tgt.functions[imp[2]])]
        elif isinstance(handler, ast.Attribute) and \
                isinstance(handler.value, ast.Name) and \
                handler.value.id == "self":
            qn = ctx.qualname_at(site.lineno)
            cls = mod.classes.get(qn.split(".")[0])
            if cls is not None:
                owner, fn = self.index.method_of(cls.key, handler.attr)
                if fn is not None:
                    return [(self.index.modules[owner.relpath], owner, fn)]
        # nested def registered as handler: find an enclosing-scope def
        if isinstance(handler, ast.Name):
            qn = ctx.qualname_at(site.lineno)
            for node in ast.walk(ctx.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and node.name == handler.id and \
                        node.lineno <= site.lineno:
                    return [(mod, None, node)]
        return []


class _Walker:
    """One function's body walk with a held-lock stack."""

    def __init__(self, model, mod, cls, fn, summary):
        self.model = model
        self.mod = mod
        self.cls = cls
        self.fn = fn
        self.summary = summary
        self.ctx = model.ctxs[mod.relpath]
        self.qualname = self.ctx.qualname_at(fn.lineno)

    def run(self):
        for stmt in self.fn.body:
            self._visit(stmt, held=(), loops=0)

    # -- traversal ---------------------------------------------------------
    def _visit(self, node, held, loops):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return      # nested defs are walked when (if) resolved as calls
        if isinstance(node, (ast.With, ast.AsyncWith)):
            self._visit_with(node, held, loops)
            return
        if isinstance(node, (ast.While, ast.For, ast.AsyncFor)):
            loops += 1
        if isinstance(node, ast.Call):
            self._visit_call(node, held, loops)
        for child in ast.iter_child_nodes(node):
            self._visit(child, held, loops)

    def _visit_with(self, node, held, loops):
        inner = held
        for item in node.items:
            expr = item.context_expr
            # `with lock:` / `with cond:` (a bare Call like
            # `with open(...)` is visited as a call, not an acquisition)
            lk = self.model.lock_key_of(expr, self.mod, self.cls)
            if lk is not None:
                self._acquire(lk, node.lineno, inner, chain="")
                inner = inner + (lk,)
            else:
                self._visit(expr, held, loops)
        for stmt in node.body:
            self._visit(stmt, inner, loops)

    def _acquire(self, lk, line, held, chain):
        for h in held:
            if h == lk:
                continue    # re-acquisition is not an ordering edge
            self.model.edges.append(Edge(
                src=h, dst=lk, relpath=self.mod.relpath, line=line,
                qualname=self.qualname, chain=chain))
        if lk in held and self.model.locks[lk].kind == "Lock":
            # re-acquiring a non-reentrant Lock on the same thread is a
            # guaranteed self-deadlock: model it as a self-edge
            self.model.edges.append(Edge(
                src=lk, dst=lk, relpath=self.mod.relpath, line=line,
                qualname=self.qualname, chain=chain))
        self.summary.acquires.setdefault(
            lk, ((self.mod.relpath, line), chain))

    # -- calls -------------------------------------------------------------
    def _visit_call(self, node, held, loops):
        cond = self._condition_receiver(node)
        if cond is not None:
            meth = node.func.attr
            if meth in ("wait", "wait_for"):
                self.model.waits.append(WaitEvent(
                    cond=cond, relpath=self.mod.relpath, line=node.lineno,
                    qualname=self.qualname, in_loop=loops > 0,
                    wait_for=meth == "wait_for"))
            elif meth in ("notify", "notify_all"):
                self.model.notifies.append(NotifyEvent(
                    cond=cond, relpath=self.mod.relpath, line=node.lineno,
                    qualname=self.qualname, held=cond in held))

        desc = self._classify_blocking(node)
        if desc is not None:
            self._blocked(desc, node.lineno, held, chain="")

        for tmod, tcls, tfn in self.model.index.resolve_call(
                node, self.mod, self.cls):
            sub = self.model.summarize(tfn, tmod, tcls)
            callee = tfn.name if tcls is None else f"{tcls.name}.{tfn.name}"
            for lk, (site, chain) in sub.acquires.items():
                link = f"{callee} -> {chain}" if chain else callee
                self._acquire_via_call(lk, node.lineno, held, link)
            for bdesc, site, chain in sub.blocking:
                link = f"{callee} -> {chain}" if chain else callee
                self._blocked(bdesc, node.lineno, held, link)

    def _acquire_via_call(self, lk, line, held, chain):
        for h in held:
            if h == lk:
                continue    # re-acquisition is not an ordering edge
            self.model.edges.append(Edge(
                src=h, dst=lk, relpath=self.mod.relpath, line=line,
                qualname=self.qualname, chain=chain))
        if lk in held and self.model.locks[lk].kind == "Lock":
            self.model.edges.append(Edge(
                src=lk, dst=lk, relpath=self.mod.relpath, line=line,
                qualname=self.qualname, chain=chain))
        self.summary.acquires.setdefault(
            lk, ((self.mod.relpath, line), chain))

    def _blocked(self, desc, line, held, chain):
        if held:
            self.model.blocking.append(BlockingEvent(
                held=held, desc=desc, relpath=self.mod.relpath, line=line,
                qualname=self.qualname, chain=chain))
        else:
            self.summary.blocking.append(
                (desc, (self.mod.relpath, line), chain))

    def _condition_receiver(self, node):
        if not isinstance(node.func, ast.Attribute):
            return None
        lk = self.model.lock_key_of(node.func.value, self.mod, self.cls)
        if lk is not None and self.model.locks[lk].kind == "Condition":
            return lk
        return None

    def _classify_blocking(self, node):
        func = node.func
        kwargs = {kw.arg for kw in node.keywords}
        if isinstance(func, ast.Name):
            if func.id == "open":
                return _BLOCKING_NAME_CALLS["open"]
            if func.id in ("sleep", "fsync") and self._is_imported_from(
                    func.id, ("time", "os")):
                return _BLOCKING_NAME_CALLS[func.id]
            if func.id == "device_put":
                return _BLOCKING_ATTR_CALLS["device_put"]
            return None
        if not isinstance(func, ast.Attribute):
            return None
        attr = func.attr
        recv = func.value
        recv_mod = recv.id if isinstance(recv, ast.Name) else None
        if attr in _SUBPROCESS_CALLS and recv_mod == "subprocess":
            return f"subprocess.{attr}() blocks on a child process"
        if attr in _BLOCKING_ATTR_CALLS:
            if attr in ("sleep", "fsync"):
                return _BLOCKING_ATTR_CALLS[attr] \
                    if recv_mod in ("time", "os") else None
            return _BLOCKING_ATTR_CALLS[attr]
        rtype = self.model.index.receiver_type(recv, self.mod, self.cls)
        if attr == "get" and rtype == "queue.Queue":
            if "timeout" in kwargs or len(node.args) >= 2 or \
                    self._block_false(node):
                return None
            return "queue.Queue.get() without timeout parks the holder"
        if attr == "join":
            if rtype == "threading.Thread":
                return "Thread.join() blocks until the worker exits"
            return None
        if attr == "result" and not isinstance(recv, ast.Constant):
            # a bounded result(timeout) still parks the holder for up to
            # the timeout — flagged the same
            return "Future.result() parks the holder on another thread"
        return None

    @staticmethod
    def _block_false(node):
        for kw in node.keywords:
            if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is False:
                return True
        if node.args and isinstance(node.args[0], ast.Constant) and \
                node.args[0].value is False:
            return True
        return False

    def _is_imported_from(self, name, modules):
        imp = self.mod.imports.get(name)
        return bool(imp and imp[0] == "symbol" and imp[1] in modules)


# --------------------------------------------------------------------------
# graph utilities (shared by the order rule and the witness crosscheck)
# --------------------------------------------------------------------------
def find_cycles(edge_pairs):
    """Elementary cycles in the digraph given as (src, dst) pairs,
    canonicalized (rotated to start at the smallest key) and deduped.
    Self-loops come out as 1-cycles."""
    graph = {}
    for s, d in edge_pairs:
        graph.setdefault(s, set()).add(d)
    cycles = set()

    def dfs(start, node, path, on_path):
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                i = path.index(min(path))
                cycles.add(tuple(path[i:] + path[:i]))
            elif nxt not in on_path and nxt > start:
                # only explore nodes > start: each cycle is found exactly
                # once, rooted at its smallest node
                dfs(start, nxt, path + [nxt], on_path | {nxt})

    for start in sorted(graph):
        dfs(start, start, [start], {start})
    return sorted(cycles)


def crosscheck(model, observed_edges, observed_names=None):
    """Compare a witness run's observed acquisition edges against the
    static model.  ``observed_edges`` is an iterable of (src, dst) lock
    names as the witness emits them — either ``"relpath:line"`` creation
    sites (mapped through the model's site index) or already-static
    keys/explicit ``named_lock`` names.

    Returns (problems, unmodeled): ``problems`` is a list of strings —
    a cycle in the merged static+observed graph, or an observed edge
    into a NON-leaf lock the static pass missed (under-approximation).
    Edges into leaf locks (no outgoing edges anywhere) are tolerated:
    statically-unresolvable receivers like telemetry child locks can
    never invert an order through a lock that nests nothing."""
    def map_name(name):
        if name in model.locks:
            return name
        relpath, _, line = name.rpartition(":")
        if line.isdigit() and (relpath, int(line)) in model.site_index:
            return model.site_index[(relpath, int(line))]
        return name

    observed = [(map_name(s), map_name(d)) for s, d in observed_edges]
    static_pairs = {(e.src, e.dst) for e in model.edges}
    merged = static_pairs | set(observed)
    problems = []
    for cyc in find_cycles(merged):
        problems.append("cycle in merged static+observed graph: " +
                        " -> ".join(cyc + (cyc[0],)))
    out_degree = {}
    for s, d in merged:
        out_degree.setdefault(s, 0)
        out_degree[s] += 1
    unmodeled = sorted({(s, d) for s, d in observed
                        if (s, d) not in static_pairs})
    for s, d in unmodeled:
        if out_degree.get(d, 0) > 0:
            problems.append(
                f"observed edge {s} -> {d} missing from the static model "
                f"and {d} is not a leaf lock — the analyzer is "
                f"under-approximating")
    return problems, unmodeled


def build(paths=None, repo_root=None):
    """Parse the scan roots and build the model.  Returns
    (model, ctx_by_path, n_files, parse_findings)."""
    root = repo_root or core.REPO_ROOT
    if paths is None:
        paths = [core.REPO_ROOT + "/mxnet_tpu"]
    ctxs = []
    parse_findings = []
    n_files = 0
    import os
    for abspath in core.iter_py_files(paths, repo_root=root):
        n_files += 1
        try:
            ctxs.append(core.load_file(abspath, repo_root=root,
                                       tool="lockscan"))
        except SyntaxError as e:
            parse_findings.append(core.Finding(
                rule="parse-error",
                path=os.path.relpath(abspath, root).replace(os.sep, "/"),
                line=e.lineno or 1, col=e.offset or 0,
                message=f"file does not parse: {e.msg}"))
        except UnicodeDecodeError:
            continue
    model = LockModel(ctxs)
    return model, {c.relpath: c for c in ctxs}, n_files, parse_findings
