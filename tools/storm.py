"""Chaos load-storm gate for the serving fleet (``tools/ci.sh storm``).

Drives heavy mixed-shape, mixed-priority traffic through a
:class:`mxnet_tpu.serve.Fleet` WHILE a seeded faultline plan kills one
replica mid-storm, then gates on the fleet's contract:

1. **zero dropped requests** — every submitted future resolves as
   completed, shed (:class:`DeadlineExceeded`, the distinct error), or
   failed; completed outputs are bit-checked against the bare model;
2. **zero failed requests** — the storm's model never errors, so any
   failure is a fleet bug;
3. **per-class p99 within the declared SLA** — measured from the
   ``mxtpu_fleet_latency_seconds`` histograms via
   ``Histogram.quantile``;
4. **visible failover** — the mid-storm replica death must tick
   ``mxtpu_faults_recovered_total{site="serve.replica"}`` and record a
   death-to-rerouted-completion time in
   ``mxtpu_fleet_failover_seconds``.

Deterministic: the traffic mix is seeded per client and the kill is a
faultline arrival plan, so a failing storm replays exactly.  Run
directly::

    python -m tools.storm --gate

Prints one ``storm_verdict: PASS|FAIL`` line; ``--gate`` exits nonzero
on FAIL.
"""
from __future__ import annotations

import argparse
import sys
import threading
import time

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import observe, telemetry
from mxnet_tpu.gluon import nn
from mxnet_tpu.resilience import faultline
from mxnet_tpu.serve import DeadlineExceeded, Fleet, SLAClass

IN_UNITS = 16
OUT_UNITS = 8

# class mix: mostly standard, a hot interactive tier, a bulk tail
_CLASS_MIX = (("interactive", 0.3), ("standard", 0.5), ("batch", 0.2))


def _build_model(seed):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, in_units=IN_UNITS, activation="relu"))
    net.add(nn.Dense(OUT_UNITS, in_units=32))
    net.initialize()
    return net


def _classes(base_deadline_ms):
    # declared SLA: p99 objective = 2x the class deadline (the shed
    # bound plus one in-flight device call) — generous in absolute
    # terms because CI runs 8 virtual devices on one contended CPU
    return {
        "interactive": SLAClass("interactive", 0, base_deadline_ms),
        "standard": SLAClass("standard", 1, 4 * base_deadline_ms),
        "batch": SLAClass("batch", 2, 20 * base_deadline_ms),
    }


def _client(idx, seed, fleet, net_ref, n_requests, results, max_rows):
    rng = onp.random.default_rng(seed + idx)
    names = [n for n, _ in _CLASS_MIX]
    probs = onp.asarray([p for _, p in _CLASS_MIX])
    for _ in range(n_requests):
        rows = int(rng.integers(1, max_rows + 1))
        x = rng.standard_normal((rows, IN_UNITS)).astype(onp.float32)
        cls = names[int(rng.choice(len(names), p=probs))]
        want = net_ref(mx.np.array(x)).asnumpy()
        fut = fleet.submit(x, cls=cls)
        results.append((fut, want, cls))
        time.sleep(float(rng.uniform(0.0, 0.004)))


def run_storm(replicas=3, clients=6, requests=20, seed=7, kill_at=None,
              base_deadline_ms=8000.0, no_fault=False):
    """Returns (verdict_line, ok, summary_dict)."""
    total = clients * requests
    if kill_at is None:
        kill_at = max(2, total // 4)   # mid-storm, after warm traffic
    net = _build_model(seed)
    fleet = Fleet(net, replicas=replicas, name="storm",
                  classes=_classes(base_deadline_ms),
                  max_batch_size=8, max_latency_ms=2.0)
    example = onp.zeros((1, IN_UNITS), onp.float32)
    compiled = fleet.warmup(example)
    faultline.clear()
    observe.reset()
    if not no_fault:
        faultline.plan([{"site": "serve.replica", "kind": "preempt",
                         "at": int(kill_at)}])

    results = []
    threads = [threading.Thread(
        target=_client, name=f"storm-client-{i}",
        args=(i, seed, fleet, net, requests, results, 4))
        for i in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    completed = shed = failed = wrong = 0
    first_error = None
    for fut, want, _cls in results:
        try:
            got = fut.result(timeout=240)
            completed += 1
            if not onp.allclose(got.asnumpy(), want, atol=1e-5):
                wrong += 1
        except DeadlineExceeded:
            shed += 1
        except Exception as exc:                     # noqa: BLE001
            failed += 1                  # a failed answer, not a drop —
            if first_error is None:      # named in the verdict line
                first_error = f"{type(exc).__name__}: {exc}"
    wall = time.perf_counter() - t0
    faultline.clear()

    answered = completed + shed + failed
    dropped = total - answered
    sla = fleet.sla_report()
    dead = [f"r{r.index}" for r in fleet.replicas if r.state == "dead"]
    reg = telemetry.default_registry()
    recovered = reg.get_sample_value(
        "mxtpu_faults_recovered_total",
        {"site": "serve.replica", "kind": "preempt"}) or 0
    failover_n = fleet.metrics._failover.count
    failover_s = fleet.metrics._failover.sum
    fleet.shutdown(drain=True)

    checks = {
        "zero_dropped": dropped == 0,
        "zero_failed": failed == 0,
        "outputs_correct": wrong == 0,
        "sla_p99": all(v["ok"] for v in sla.values()),
    }
    # the flight record of the storm must root-cause the injected kill
    # (or stay clean when none was planned)
    from tools import blackbox
    bb = blackbox.analyze([observe.snapshot(reason="storm")])
    if not no_fault:
        checks["replica_killed"] = len(dead) == 1
        checks["fault_recovered"] = recovered >= 1
        checks["failover_measured"] = failover_n >= 1
        checks["blackbox_root_cause"] = (bb["site"] == "serve.replica"
                                         and bb["kind"] == "preempt")
    else:
        checks["blackbox_clean"] = bb["verdict"] == "NONE"
    ok = all(checks.values())

    p99s = ", ".join(
        f"p99[{c}]={v['p99_ms']:.0f}ms<=SLO {v['slo_p99_ms']:.0f}ms"
        if v["p99_ms"] is not None else f"p99[{c}]=n/a"
        for c, v in sla.items())
    fail_bits = "" if ok else " FAILED: " + ",".join(
        k for k, v in checks.items() if not v)
    if first_error is not None:
        fail_bits += f" [first error: {first_error}]"
    verdict = (
        f"storm_verdict: {'PASS' if ok else 'FAIL'} — {answered}/{total} "
        f"answered ({completed} completed, {shed} shed, {failed} failed, "
        f"{dropped} dropped), {p99s}, dead={dead or 'none'}, "
        f"recovered={recovered:.0f}, failover={failover_s:.2f}s "
        f"(n={failover_n}), {compiled} exes warmed, wall={wall:.1f}s"
        f"{fail_bits}")
    summary = dict(checks, completed=completed, shed=shed, failed=failed,
                   dropped=dropped, wrong=wrong, wall=wall, sla=sla)
    return verdict, ok, summary


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--clients", type=int, default=6)
    ap.add_argument("--requests", type=int, default=20,
                    help="requests per client")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--kill-at", type=int, default=None,
                    help="faultline arrival index of the replica kill "
                         "(default: total/4)")
    ap.add_argument("--base-deadline-ms", type=float, default=8000.0)
    ap.add_argument("--no-fault", action="store_true",
                    help="load only, no replica kill")
    ap.add_argument("--gate", action="store_true",
                    help="exit 1 on FAIL (the CI mode)")
    args = ap.parse_args(argv)
    verdict, ok, _summary = run_storm(
        replicas=args.replicas, clients=args.clients,
        requests=args.requests, seed=args.seed, kill_at=args.kill_at,
        base_deadline_ms=args.base_deadline_ms, no_fault=args.no_fault)
    print(verdict)
    return 0 if (ok or not args.gate) else 1


if __name__ == "__main__":
    sys.exit(main())
