"""autotune: Pallas kernel parameter sweeps with a committed winner cache.

ROADMAP item 5's last open edge: kernel block/tile choices (flash
attention block_q/block_k, the scan-LSTM cell unroll, the s2d stem and
BN-backward-epilogue tiles) used to be constants justified by one-off
hand sweeps in comments.  This tool makes each choice a reviewed,
diffable artifact:

* the sweep half (``--sweep`` / ``--update-cache``) runs every
  registered kernel's candidate grid — deterministic roofline scoring
  (``--mode model``) or real timing with the benchmark/timing_util.py
  discipline (``--mode time``, optionally one subprocess per candidate)
  — and persists winners into ``tools/autotune_cache.json``;
* the gate half (the default command; what ``tools/ci.sh autotune``
  runs) verifies the committed cache hloscan-style: fingerprint match,
  full registry coverage, no stale entries, and — for kernels with a
  deterministic model — that the committed winner is re-derived
  bit-for-bit by the model.  Exit 0 clean / 1 findings / 2 usage error.

Dispatch reads the cache at trace time through the one
``mxnet_tpu.tune.best`` choke point; a miss falls back to the kernel's
documented static default with ONE warning, never a silent in-process
sweep.  See docs/AUTOTUNE.md for cache-key anatomy and the re-tune
policy.

Usage::

    python -m tools.autotune                     # verify committed cache
    python -m tools.autotune --sweep             # sweep + tables, no write
    python -m tools.autotune --sweep --kernel flash_attention
    python -m tools.autotune --update-cache      # sweep and commit winners
"""
from .driver import main, render_sweep, run_sweeps, verify_cache  # noqa: F401
