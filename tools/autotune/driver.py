"""autotune driver: sweep, table, cache update, CI gate.

Exit status mirrors hloscan/layerscope: 0 when the committed cache is
clean, 1 when any finding is live, 2 on usage error.  Findings are not
baselinable — the cache is itself the reviewed artifact, so a stale or
drifted entry must be fixed (re-sweep with ``--update-cache``), not
grandfathered.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

JSON_SCHEMA_VERSION = 1

#: Every rule the cache gate can emit, for the verdict lines.
RULES = ("cache-readable", "fingerprint", "coverage", "stale-entry",
         "model-drift")


def expected_entries(kernels_filter=None):
    """``{cache key: (kernel, signature)}`` for the registry — the
    coverage contract the committed cache must satisfy."""
    from mxnet_tpu.tune import cache, kernels
    out = {}
    for name in kernels.names():
        if kernels_filter and name not in kernels_filter:
            continue
        spec = kernels.get(name)
        for sig in spec.signatures():
            out[cache.make_key(name, sig)] = (name, sig)
    return out


# --------------------------------------------------------------------------
# the gate
# --------------------------------------------------------------------------
def verify_cache(path=None, kernels_filter=None):
    """Verify the committed cache against the live registry + toolchain.

    Returns ``(findings, info)``: findings are ``{"rule", "key",
    "message"}`` dicts (empty == clean); info carries the verified
    entry count and cache path for reporting."""
    from mxnet_tpu.tune import cache, kernels, sweep

    path = path or cache.default_cache_path()
    findings = []

    def finding(rule, key, message):
        findings.append({"rule": rule, "key": key, "message": message})

    try:
        doc = cache.load_cache(path)
    except FileNotFoundError:
        finding("cache-readable", path,
                f"committed cache {path} is missing — every tuned kernel "
                f"would run on static defaults; sweep it with "
                f"tools/autotune --update-cache")
        return findings, {"path": path, "entries": 0}
    except (ValueError, json.JSONDecodeError) as e:
        finding("cache-readable", path, f"{path} unreadable: {e}")
        return findings, {"path": path, "entries": 0}

    if not cache.fingerprint_matches(doc):
        finding("fingerprint", "fingerprint",
                f"cache swept under {doc.get('fingerprint')} but this "
                f"toolchain is {cache.fingerprint()} — optima may have "
                f"moved; re-sweep with tools/autotune --update-cache")

    expected = expected_entries(kernels_filter)
    entries = doc.get("entries", {})

    for key, ent in sorted(entries.items()):
        if kernels_filter and cache.split_key(key)[0] not in kernels_filter:
            continue
        if key not in expected:
            finding("stale-entry", key,
                    f"cache entry {key!r} matches no registered "
                    f"(kernel, signature) — the kernel or its shape "
                    f"bucket was renamed or removed; prune it")
            continue
        name, sig = expected[key]
        spec = kernels.get(name)
        params = ent["params"]
        grid = spec.grid(sig)
        if params not in grid and params != spec.default(sig):
            finding("stale-entry", key,
                    f"cache entry {key!r} pins {params} which is no "
                    f"longer in the swept grid — re-sweep")

    for key, (name, sig) in sorted(expected.items()):
        if key not in entries:
            finding("coverage", key,
                    f"no cache entry for registered kernel signature "
                    f"{key!r} — sweep it with tools/autotune --kernel "
                    f"{name} --update-cache")

    # kernels with a deterministic model: the committed winner must be
    # re-derivable bit-for-bit, on any machine, with no device
    for key, (name, sig) in sorted(expected.items()):
        ent = entries.get(key)
        if ent is None or ent.get("mode") == "time":
            continue
        spec = kernels.get(name)
        if spec._model_time is None:
            continue
        got = sweep.sweep_kernel(name, sig, mode="model")["winner"]
        if got != ent["params"]:
            finding("model-drift", key,
                    f"cache entry {key!r} pins {ent['params']} but the "
                    f"roofline model derives {got} — the model or grid "
                    f"changed under the committed winner; re-sweep with "
                    f"--update-cache (or fix the model)")

    return findings, {"path": path, "entries": len(entries)}


# --------------------------------------------------------------------------
# sweeps
# --------------------------------------------------------------------------
def run_sweeps(kernels_filter=None, mode=None, isolate=False, repeats=3,
               log=None):
    """Sweep every registered (kernel, signature) — ``mode=None`` picks
    ``model`` when the kernel has one, else ``time``."""
    from mxnet_tpu.tune import kernels, sweep
    results = []
    for name in kernels.names():
        if kernels_filter and name not in kernels_filter:
            continue
        spec = kernels.get(name)
        m = mode or ("model" if spec._model_time is not None else "time")
        for sig in spec.signatures():
            results.append(sweep.sweep_kernel(
                name, sig, mode=m, isolate=isolate, repeats=repeats,
                log=log))
    return results


def _fmt_score(row):
    if "error" in row:
        return f"ERROR {row['error'][:48]}"
    if "ms" in row:
        return f"{row['ms']:9.3f} ms"
    return f"{row['modeled_s'] * 1e6:9.2f} us(model)"


def render_sweep(result, out=None):
    out = out or sys.stdout
    lines = [f"autotune: {result['kernel']} [{result['signature']}] "
             f"mode={result['mode']}"]
    best = result["winner"]
    default = result["default"]
    for row in sorted(result["rows"],
                      key=lambda r: r.get("ms", r.get("modeled_s",
                                                      float("inf")))):
        marks = []
        if row["params"] == best:
            marks.append("WINNER")
        if row["params"] == default:
            marks.append("default")
        pstr = " ".join(f"{k}={v}" for k, v in sorted(row["params"].items()))
        lines.append(f"  {pstr:<36} {_fmt_score(row):>22}"
                     f"{('  <- ' + ','.join(marks)) if marks else ''}")
    if result["speedup_vs_default"] is not None:
        lines.append(f"  winner vs default: "
                     f"{result['speedup_vs_default']:.3f}x")
    text = "\n".join(lines) + "\n"
    out.write(text)
    return text


def update_cache(results, path=None):
    """Fold sweep winners into the cache.  Existing entries survive a
    partial (``--kernel``-filtered) sweep only when the fingerprint
    still matches — a toolchain bump invalidates everything."""
    from mxnet_tpu.tune import cache
    path = path or cache.default_cache_path()
    doc = None
    try:
        old = cache.load_cache(path)
        if cache.fingerprint_matches(old):
            doc = old
    except (OSError, ValueError, json.JSONDecodeError):
        pass
    if doc is None:
        doc = cache.empty_cache()
    for r in results:
        key = cache.make_key(r["kernel"], r["signature"])
        doc["entries"][key] = {
            "params": r["winner"],
            "mode": r["mode"],
            "speedup_vs_default": r["speedup_vs_default"],
        }
    return cache.save_cache(doc, path)


# --------------------------------------------------------------------------
# reporting
# --------------------------------------------------------------------------
def verdict_lines(findings):
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f["rule"], []).append(f)
    out = []
    for rule in RULES:
        n = len(by_rule.get(rule, ()))
        verdict = "PASS" if n == 0 else f"FAIL  [{n}]"
        out.append(f"autotune {rule:<18} {verdict}")
    return out


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m tools.autotune",
        description="Pallas kernel autotuner: sweep candidate grids, "
                    "commit winners, gate the committed cache "
                    "(docs/AUTOTUNE.md).")
    p.add_argument("--cache", default=None, metavar="PATH",
                   help="cache file (default: tools/autotune_cache.json "
                        "or MXNET_AUTOTUNE_CACHE)")
    p.add_argument("--kernel", action="append", dest="kernels",
                   metavar="NAME",
                   help="restrict to one kernel (repeatable; see "
                        "--list-kernels)")
    p.add_argument("--sweep", action="store_true",
                   help="run sweeps and print candidate tables "
                        "(no cache write)")
    p.add_argument("--update-cache", action="store_true",
                   help="run sweeps and persist winners to the cache")
    p.add_argument("--mode", choices=("model", "time"), default=None,
                   help="force scoring mode (default: model when the "
                        "kernel has one, else time)")
    p.add_argument("--isolate", action="store_true",
                   help="time mode: one subprocess per candidate "
                        "(crash isolation)")
    p.add_argument("--repeats", type=int, default=3,
                   help="time mode: repeats per candidate (trimmed "
                        "median; default 3)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--verdicts", action="store_true",
                   help="append per-rule PASS/FAIL verdict lines")
    p.add_argument("--list-kernels", action="store_true")
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args(argv)

    from mxnet_tpu.tune import kernels
    if args.list_kernels:
        for name in kernels.names():
            print(name)
        return 0
    if args.kernels:
        unknown = [k for k in args.kernels if k not in kernels.names()]
        if unknown:
            p.error(f"unknown kernel(s) {unknown}; have {kernels.names()}")

    out = sys.stdout
    log = (lambda s: print(s, file=sys.stderr)) if args.verbose else None

    if args.sweep or args.update_cache:
        results = run_sweeps(kernels_filter=args.kernels, mode=args.mode,
                             isolate=args.isolate, repeats=args.repeats,
                             log=log)
        if args.format == "json":
            json.dump({"version": JSON_SCHEMA_VERSION, "tool": "autotune",
                       "sweeps": results}, out, indent=2)
            out.write("\n")
        else:
            for r in results:
                render_sweep(r, out=out)
        if args.update_cache:
            path = update_cache(results, path=args.cache)
            out.write(f"autotune: cache updated — {path}\n")
        return 0

    findings, info = verify_cache(path=args.cache,
                                  kernels_filter=args.kernels)
    if args.format == "json":
        json.dump({"version": JSON_SCHEMA_VERSION, "tool": "autotune",
                   "cache": info["path"], "entries": info["entries"],
                   "findings": findings,
                   "summary": {"live": len(findings)}}, out, indent=2)
        out.write("\n")
    else:
        for f in findings:
            out.write(f"autotune: [{f['rule']}] {f['message']}\n")
        verdict = "clean" if not findings else \
            f"{len(findings)} live finding{'s' if len(findings) != 1 else ''}"
        out.write(f"autotune: {verdict} — {info['entries']} cache "
                  f"entr{'y' if info['entries'] == 1 else 'ies'} "
                  f"({info['path']})\n")
    if args.verdicts:
        for line in verdict_lines(findings):
            out.write(line + "\n")
    return 1 if findings else 0
