# Makes `tools` importable so `python -m tools.mxlint` and
# `import tools.mxlint` resolve from the repo root.
