"""hloscan driver: capture, check, waive, baseline, report.

Exit status mirrors mxlint: 0 when every finding is waived or
baselined AND the baseline is not stale, 1 when an unbaselined finding
remains or the baseline names findings that no longer exist, 2 on
usage error.  Stale baseline entries are a *failure* here (not a note):
a stale entry means a grandfathered debt was paid and the baseline no
longer reflects reality — prune it in the same change
(``--update-baseline``) or CI stops.

The default artifact set is the project's real entry points, captured
live by ``mxnet_tpu.analysis`` (train step on the virtual 8-device
mesh, bucketed allreduce dense+2bit, flash attention fwd/bwd, the
serve endpoint executable).  Tests and the dryrun rider pass their own
``artifacts=`` instead.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from . import core
from .rules import all_rules

DEFAULT_BASELINE = os.path.join(core.REPO_ROOT, "tools",
                                "hloscan_baseline.json")

JSON_SCHEMA_VERSION = 1


def scan(artifacts, rules=None):
    """Run ``rules`` (default: all) over ``artifacts``.  Returns the
    finding list with waivers applied and IDs assigned, no baseline."""
    rules = all_rules() if rules is None else rules
    findings = []
    for artifact in artifacts:
        per_artifact = []
        for rule in rules:
            per_artifact.extend(rule.check(artifact) or ())
        findings.extend(core.apply_waivers(per_artifact, artifact))
    findings.sort(key=lambda f: (f.artifact, f.rule, f.key))
    core.assign_ids(findings)
    return findings


def default_artifacts(names=None):
    """Capture the project's real entry points (imports jax; compiles).
    ``mxnet_tpu.analysis`` returns plain dict specs so the library
    carries no tooling dependency; the Artifact wrapper lives here."""
    from mxnet_tpu.analysis import capture_all
    return [core.Artifact(**spec) for spec in capture_all(names)]


def load_baseline(path):
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return data.get("findings", {})


def write_baseline(path, findings):
    """Grandfather every current unwaived finding (``--update-baseline``)."""
    entries = {
        f.id: {"rule": f.rule, "artifact": f.artifact, "key": f.key,
               "message": f.message}
        for f in findings if not f.waived}
    payload = {
        "comment": "hloscan grandfathered findings — entries are debts, not "
                   "permissions; remove as they are fixed. Stale entries "
                   "FAIL the scan. Regenerate with "
                   "`python -m tools.hloscan --update-baseline`.",
        "version": JSON_SCHEMA_VERSION,
        "findings": dict(sorted(entries.items())),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=False)
        f.write("\n")
    return entries


def verdict_lines(findings, artifacts, rules=None):
    """Per-rule ``hloscan <rule> PASS|FAIL`` lines for the dryrun rider —
    a rule FAILs when any unwaived, unbaselined finding of it exists."""
    rules = all_rules() if rules is None else rules
    live = {}
    for f in findings:
        if not f.waived and not f.baselined:
            live.setdefault(f.rule, 0)
            live[f.rule] += 1
    n_art = len(list(artifacts))
    lines = []
    for rule in rules:
        n = live.get(rule.name, 0)
        verdict = "PASS" if not n else f"FAIL ({n})"
        lines.append(f"hloscan {rule.name:22s} {verdict}  "
                     f"[{n_art} artifacts]")
    return lines


def publish_metrics(findings):
    """Mirror the finding census into the telemetry registry (best
    effort: hloscan must work without mxnet_tpu importable)."""
    try:
        from mxnet_tpu import telemetry
    except Exception:  # mxlint: disable=swallowed-exception -- hloscan must run without mxnet_tpu importable; the False return IS the report
        return False
    g = telemetry.gauge(
        "mxtpu_hloscan_findings",
        "hloscan findings by rule and disposition",
        labelnames=("rule", "disposition"))
    per = {}
    for f in findings:
        disp = "waived" if f.waived else (
            "baselined" if f.baselined else "live")
        per[(f.rule, disp)] = per.get((f.rule, disp), 0) + 1
    for rule in all_rules():
        for disp in ("live", "waived", "baselined"):
            g.labels(rule=rule.name, disposition=disp).set(
                per.get((rule.name, disp), 0))
    return True


def report_text(findings, artifacts, stale_ids, out=sys.stdout):
    unbaselined = [f for f in findings if not f.waived and not f.baselined]
    for f in unbaselined:
        loc = f"{f.artifact}[{f.where}]" if f.where else f.artifact
        out.write(f"{loc}: [{f.rule}] {f.message}  (id {f.id})\n")
    n_w = sum(1 for f in findings if f.waived)
    n_b = sum(1 for f in findings if f.baselined)
    if stale_ids:
        out.write(f"hloscan: FAIL — {len(stale_ids)} baseline entr"
                  f"{'y names a finding' if len(stale_ids) == 1 else 'ies name findings'} "
                  f"that no longer exist{'s' if len(stale_ids) == 1 else ''}; "
                  f"prune with --update-baseline: "
                  f"{', '.join(sorted(stale_ids))}\n")
    verdict = "clean" if not unbaselined else \
        f"{len(unbaselined)} unbaselined finding" + \
        ("s" if len(unbaselined) != 1 else "")
    out.write(f"hloscan: {verdict} — {len(artifacts)} artifacts, "
              f"{len(findings)} findings ({n_w} waived, {n_b} baselined)\n")


def report_json(findings, artifacts, stale_ids, out=sys.stdout):
    unbaselined = [f for f in findings if not f.waived and not f.baselined]
    payload = {
        "version": JSON_SCHEMA_VERSION,
        "tool": "hloscan",
        "artifacts": [a.name for a in artifacts],
        "findings": [f.to_json() for f in findings],
        "stale_baseline_ids": sorted(stale_ids),
        "summary": {
            "total": len(findings),
            "waived": sum(1 for f in findings if f.waived),
            "baselined": sum(1 for f in findings if f.baselined),
            "unbaselined": len(unbaselined),
            "stale_baseline": len(stale_ids),
        },
    }
    json.dump(payload, out, indent=2)
    out.write("\n")


def run(artifacts=None, artifact_names=None, baseline_path=None,
        update_baseline=False, fmt="text", verdicts=False,
        metrics=True, out=sys.stdout):
    """Full pipeline; returns the process exit code."""
    if artifacts is None:
        artifacts = default_artifacts(artifact_names)
    artifacts = list(artifacts)
    findings = scan(artifacts)
    baseline = {}
    if baseline_path:
        baseline = load_baseline(baseline_path)
        for f in findings:
            if not f.waived and f.id in baseline:
                f.baselined = True
    if update_baseline:
        if not baseline_path:
            out.write("hloscan: --update-baseline needs --baseline PATH\n")
            return 2
        entries = write_baseline(baseline_path, findings)
        out.write(f"hloscan: baseline written — {len(entries)} entr"
                  f"{'y' if len(entries) == 1 else 'ies'} -> "
                  f"{baseline_path}\n")
        return 0
    present = {f.id for f in findings if not f.waived}
    stale_ids = set(baseline) - present
    if metrics:
        publish_metrics(findings)
    (report_json if fmt == "json" else report_text)(
        findings, artifacts, stale_ids, out=out)
    if verdicts:
        for line in verdict_lines(findings, artifacts):
            out.write(line + "\n")
    failed = any(not f.waived and not f.baselined for f in findings)
    return 1 if (failed or stale_ids) else 0


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m tools.hloscan",
        description="Compiled-program contract checker over captured "
                    "jaxprs and lowered HLO (docs/STATIC_ANALYSIS.md).")
    p.add_argument("artifacts", nargs="*",
                   help="artifact names to scan (default: all real entry "
                        "points; see --list-artifacts)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="baseline JSON of grandfathered finding IDs "
                        "(default: tools/hloscan_baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline (report everything)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from current findings")
    p.add_argument("--verdicts", action="store_true",
                   help="append per-rule PASS/FAIL verdict lines")
    p.add_argument("--no-metrics", action="store_true",
                   help="skip publishing the finding census to telemetry")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--list-artifacts", action="store_true")
    args = p.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.name:24s} {rule.description}")
        return 0
    if args.list_artifacts:
        from mxnet_tpu.analysis import entrypoint_names
        for name in entrypoint_names():
            print(name)
        return 0

    return run(artifact_names=args.artifacts or None,
               baseline_path=None if args.no_baseline else args.baseline,
               update_baseline=args.update_baseline,
               fmt=args.format, verdicts=args.verdicts,
               metrics=not args.no_metrics)


if __name__ == "__main__":
    sys.exit(main())
