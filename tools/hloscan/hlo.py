"""Lightweight parser + dependence analysis for XLA HLO *text*.

hloscan's rules read the artifact XLA actually runs, so the input is the
textual HLO the toolchain prints — both forms:

* **unoptimized** (``lowered.compiler_ir(dialect="hlo").as_hlo_text()``):
  instruction names without ``%``, operands as bare names — this is the
  user program as lowered, before any compiler pass (the right layer for
  dtype intent: the optimizer is allowed to upcast);
* **optimized/scheduled** (``compiled.as_text()``): ``%``-prefixed names,
  typed operands, ``is_scheduled=true`` — the instruction order of the
  entry computation IS the schedule the backend executes.

This is deliberately NOT a full HLO grammar: it recovers what the rules
need — per-computation instruction lists in schedule order, opcodes,
result dtypes/shapes, operand edges (the dependence graph), attribute
text — and stays robust to the attribute soup (metadata, layouts,
sharding annotations) by keeping it as raw text with regex accessors.

Async-collective modeling
-------------------------
On TPU the compiler splits collectives into ``all-reduce-start`` /
``all-reduce-done`` pairs and the latency-hiding scheduler moves real
compute between them.  The CPU backend this repo's CI runs on keeps
collectives synchronous in HLO (the async split happens below HLO, in
the thunk runtime), so :func:`overlap_report` covers both shapes:

* literal ``*-start``/``*-done`` pairs → the compute *actually
  scheduled* strictly between them;
* synchronous collectives → the compute an async scheduler *may* place
  in the start→done window, which is exactly the set of ops neither
  upstream (producers must finish before start) nor downstream
  (consumers must wait for done) of the collective in the dependence
  graph.  Zero such ops means no scheduler on any backend can overlap
  this collective — the dependence structure, not the toolchain, forbids
  it.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field

# --------------------------------------------------------------------------
# opcode taxonomy
# --------------------------------------------------------------------------
#: Cross-device collectives (base opcodes; async forms append -start/-done).
COLLECTIVE_OPS = frozenset({
    "all-reduce", "all-gather", "all-to-all", "reduce-scatter",
    "collective-permute", "collective-broadcast", "ragged-all-to-all",
})

#: Collectives that move/reshape data rather than reduce it — the ones a
#: fully-specified sharding should never need (resharding-detector).
RESHARD_OPS = frozenset({
    "all-gather", "all-to-all", "collective-permute", "ragged-all-to-all",
})

#: Ops that cross the host boundary by construction.
HOST_OPS = frozenset({
    "infeed", "outfeed", "send", "send-done", "recv", "recv-done",
})

#: custom-call targets that reach back into the host Python process.
HOST_CALLBACK_TARGET_RE = re.compile(
    r"callback|host_callback|xla_ffi_python|HostExecute", re.IGNORECASE)

#: Pure data movement / bookkeeping — never "real compute" for overlap.
_NON_COMPUTE = frozenset({
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "bitcast-convert", "copy", "copy-start", "copy-done", "reshape",
    "transpose", "broadcast", "slice", "dynamic-slice",
    "dynamic-update-slice", "concatenate", "convert", "iota", "after-all",
    "partition-id", "replica-id", "optimization-barrier", "domain", "pad",
    "reverse", "gather", "get-dimension-size", "set-dimension-size",
    "add-dependency", "tuple-select", "rng-get-and-update-state",
}) | COLLECTIVE_OPS | HOST_OPS | frozenset(
    op + "-start" for op in COLLECTIVE_OPS) | frozenset(
    op + "-done" for op in COLLECTIVE_OPS) | frozenset(
    {"async-start", "async-update", "async-done"})

_DTYPE_RE = re.compile(
    r"\b(pred|bf16|f8e\w+|f16|f32|f64|s4|s8|s16|s32|s64|"
    r"u4|u8|u16|u32|u64|c64|c128)\[")

_INSTR_RE = re.compile(
    r"^\s*(?P<root>ROOT\s+)?%?(?P<name>[A-Za-z_][\w.\-]*)\s*=\s*"
    r"(?P<shape>\([^)]*\)|[A-Za-z0-9_\[\],]+(?:\{[\d,]*\})?)\s+"
    r"(?P<op>[a-z][\w\-]*)\((?P<rest>.*)$")

_COMP_RE = re.compile(
    r"^\s*(?P<entry>ENTRY\s+)?%?(?P<name>[\w.\-]+)\s*(\([^)]*\)\s*"
    r"->\s*[^{]+)?\{\s*$")

_CALLED_RE = re.compile(
    r"\b(?:to_apply|calls|condition|body|then_computation|else_computation|"
    r"called_computation)=%?([\w.\-]+)")

_TARGET_RE = re.compile(r'custom_call_target="([^"]*)"')


@dataclass(eq=False)   # identity semantics: usable in sets, one node per parse
class Instruction:
    name: str
    shape: str                 # raw result shape text, e.g. f32[8,4]{1,0}
    opcode: str
    operands: tuple            # operand instruction names (resolved later)
    attrs: str                 # raw attribute text after the operand list
    is_root: bool = False
    index: int = -1            # schedule position within its computation

    @property
    def result_dtypes(self):
        return tuple(m.group(1) for m in _DTYPE_RE.finditer(self.shape))

    @property
    def clean_shape(self):
        """Shape without layout braces — stable across layout assignment."""
        return re.sub(r"\{[\d,]*\}", "", self.shape).replace(" ", "")

    def attr(self, regex):
        m = re.search(regex, self.attrs)
        return m.group(1) if m else None

    @property
    def custom_call_target(self):
        m = _TARGET_RE.search(self.attrs)
        return m.group(1) if m else None

    def called_computations(self):
        return [m for m in _CALLED_RE.findall(self.attrs)]


@dataclass
class Computation:
    name: str
    is_entry: bool
    instructions: list = field(default_factory=list)
    by_name: dict = field(default_factory=dict)

    def consumers(self):
        """name -> list of instructions using it (built on demand)."""
        cons = {i.name: [] for i in self.instructions}
        for instr in self.instructions:
            for op in instr.operands:
                if op in cons:
                    cons[op].append(instr)
        return cons

    def ancestors(self, instr):
        """Transitive producers of ``instr`` (operand closure)."""
        seen, stack = set(), list(instr.operands)
        while stack:
            n = stack.pop()
            if n in seen or n not in self.by_name:
                continue
            seen.add(n)
            stack.extend(self.by_name[n].operands)
        return {self.by_name[n] for n in seen}

    def descendants(self, instr, cons=None):
        """Transitive consumers of ``instr``'s result."""
        cons = cons or self.consumers()
        seen, stack = set(), [instr.name]
        while stack:
            n = stack.pop()
            for user in cons.get(n, ()):
                if user.name not in seen:
                    seen.add(user.name)
                    stack.append(user.name)
        return {self.by_name[n] for n in seen}


@dataclass
class Module:
    name: str
    is_scheduled: bool
    num_partitions: int
    computations: dict = field(default_factory=dict)
    entry: Computation = None

    def all_instructions(self):
        for comp in self.computations.values():
            yield from comp.instructions


def _split_operands(args):
    """Top-level comma split of an operand list; each operand's *name* is
    its last ``%``-or-bare identifier (typed operands in optimized text,
    bare names in unoptimized text).  Non-name pieces (constant literals)
    yield nothing and are skipped at graph build via by_name lookup."""
    parts, depth, cur = [], 0, []
    for ch in args:
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append("".join(cur))
    names = []
    for p in parts:
        m = re.search(r"%?([A-Za-z_][\w.\-]*)\s*$", p.strip())
        if m:
            names.append(m.group(1))
    return tuple(names)


def _parse_instruction(line):
    m = _INSTR_RE.match(line)
    if not m:
        return None
    rest = m.group("rest")
    depth, cut = 1, len(rest)
    for i, ch in enumerate(rest):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                cut = i
                break
    return Instruction(
        name=m.group("name"), shape=m.group("shape").strip(),
        opcode=m.group("op"), operands=_split_operands(rest[:cut]),
        attrs=rest[cut + 1:], is_root=bool(m.group("root")))


def parse(text):
    """Parse HLO text into a :class:`Module`.  Tolerant: unrecognized
    lines are skipped (attribute continuations, comments)."""
    lines = text.splitlines()
    header = next((ln for ln in lines if ln.startswith("HloModule")), "")
    mod = Module(
        name=(re.match(r"HloModule ([\w.\-]+)", header) or [None, "?"])[1]
        if header else "?",
        is_scheduled="is_scheduled=true" in header,
        num_partitions=int(
            (re.search(r"num_partitions=(\d+)", header) or [None, "1"])[1]),
    )
    comp = None
    for ln in lines:
        stripped = ln.strip()
        if comp is None:
            if stripped.endswith("{") and not stripped.startswith("HloModule"):
                m = _COMP_RE.match(ln)
                if m:
                    comp = Computation(name=m.group("name"),
                                       is_entry=bool(m.group("entry")))
            continue
        if stripped == "}" or stripped.startswith("} "):
            mod.computations[comp.name] = comp
            if comp.is_entry:
                mod.entry = comp
            comp = None
            continue
        instr = _parse_instruction(ln)
        if instr is not None:
            instr.index = len(comp.instructions)
            comp.instructions.append(instr)
            comp.by_name[instr.name] = instr
    if mod.entry is None and mod.computations:
        # single-computation modules without an ENTRY tag
        mod.entry = next(iter(mod.computations.values()))
    return mod


# --------------------------------------------------------------------------
# classification
# --------------------------------------------------------------------------
def base_collective(opcode):
    """'all-reduce-start' -> 'all-reduce'; None for non-collectives."""
    for suffix in ("-start", "-done"):
        if opcode.endswith(suffix):
            opcode = opcode[: -len(suffix)]
            break
    return opcode if opcode in COLLECTIVE_OPS else None


def is_collective_issue(instr):
    """A collective's *issue* op: the sync form or the -start half (the
    -done half is the same launch completing, never counted twice)."""
    base = base_collective(instr.opcode)
    return base is not None and not instr.opcode.endswith("-done")


def is_compute(instr):
    """Real work the scheduler can hide a collective behind: dots,
    convolutions, fusions, reductions, elementwise arithmetic, kernels —
    everything that is not pure data movement or bookkeeping."""
    return instr.opcode not in _NON_COMPUTE


def is_host_op(instr):
    if instr.opcode in HOST_OPS:
        return True
    if instr.opcode == "custom-call":
        target = instr.custom_call_target or ""
        return bool(HOST_CALLBACK_TARGET_RE.search(target))
    return False


# --------------------------------------------------------------------------
# collective-overlap modeling
# --------------------------------------------------------------------------
def overlap_report(comp):
    """Per collective issue in ``comp``: can real compute overlap it?

    Returns a list of dicts::

        {"instr": Instruction, "mode": "paired"|"modeled",
         "compute": [Instruction, ...],   # overlappable real compute
         "first_consumer": str|None}

    ``paired``: the module already carries ``*-start``/``*-done`` —
    compute is what sits strictly between them in the schedule (the
    scheduler's actual decision).  ``modeled``: the collective is
    synchronous in HLO — compute is every op independent of it in the
    dependence graph (neither ancestor nor descendant), i.e. what an
    async split + latency-hiding schedule is free to move into the
    start→done window.
    """
    cons = comp.consumers()
    out = []
    done_for = {}
    for instr in comp.instructions:
        if base_collective(instr.opcode) and instr.opcode.endswith("-done"):
            for op in instr.operands:
                done_for[op] = instr
    for instr in comp.instructions:
        if not is_collective_issue(instr):
            continue
        users = cons.get(instr.name, [])
        first_consumer = min(users, key=lambda u: u.index).name if users \
            else None
        if instr.opcode.endswith("-start"):
            done = done_for.get(instr.name)
            hi = done.index if done is not None else len(comp.instructions)
            compute = [i for i in comp.instructions
                       if instr.index < i.index < hi and is_compute(i)]
            out.append({"instr": instr, "mode": "paired",
                        "compute": compute,
                        "first_consumer": done.name if done else None})
        else:
            blocked = comp.ancestors(instr) | comp.descendants(instr, cons)
            blocked.add(instr)
            compute = [i for i in comp.instructions
                       if i not in blocked and is_compute(i)]
            out.append({"instr": instr, "mode": "modeled",
                        "compute": compute,
                        "first_consumer": first_consumer})
    return out


def collective_counts(module, entry_only=False):
    """Issue-count per base collective opcode (starts counted, dones not)."""
    counts = {}
    comps = [module.entry] if (entry_only and module.entry) \
        else list(module.computations.values())
    for comp in comps:
        for instr in comp.instructions:
            if is_collective_issue(instr):
                base = base_collective(instr.opcode)
                counts[base] = counts.get(base, 0) + 1
    return counts


def stable_key(instr, ordinal):
    """Finding-key fragment for one instruction that survives unrelated
    edits: opcode + layout-free shape + ordinal among same-keyed ops —
    never the instruction's numeric suffix or channel id, which renumber
    on any recompile."""
    return f"{instr.opcode}{instr.clean_shape}#{ordinal}"
