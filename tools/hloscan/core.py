"""hloscan infrastructure: artifacts, findings, waivers, stable IDs.

mxlint's unit of analysis is a source *file*; hloscan's is an
*artifact* — one captured program (jaxpr + lowered HLO + optimized HLO)
for one real entry point, plus the **contract** that entry point
declares (expected collective counts, dtype policy, sharding promises).
Rules read the artifact and emit findings where the compiled program
breaks the contract.

Finding IDs are stable across unrelated edits the same way mxlint's
are: they hash ``rule|artifact|key`` where ``key`` is derived from the
offending instruction's opcode + layout-free shape + ordinal among
same-shaped ops — never the instruction's numeric suffix or channel
id, which XLA renumbers on every recompile (see
:func:`tools.hloscan.hlo.stable_key`).

Waivers cannot live inline (HLO text is generated, not authored), so
they are declared on the artifact's contract::

    "waivers": [
        {"rule": "dtype-cliff", "match": "convert[f32]",
         "reason": "loss is accumulated in f32 by design"},
    ]

``reason`` is REQUIRED — a reasonless waiver is itself a ``bad-waiver``
finding, exactly as in mxlint.  ``match`` (optional) restricts the
waiver to findings whose key contains the substring; without it the
waiver covers every finding of that rule on that artifact.
"""
from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass, field

from . import hlo

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: Contract keys understood by the shipped rules (checked so a typo'd
#: contract fails loudly instead of silently waiving a rule).
KNOWN_CONTRACT_KEYS = frozenset({
    "expect_overlap",          # collective-overlap: require hideable compute
    "allow_host_roundtrip",    # no-host-roundtrip: opt OUT of the rule
    "dtype_policy",            # dtype-cliff: "bf16" | None
    "resharding_free",         # resharding-detector: no data-movement colls
    "allowed_reshard_ops",     # ...except these base opcodes
    "expected_collectives",    # launch-count: {"all-reduce": 4} or int
    "collective_free",         # launch-count: require zero collectives
    "waivers",
})


@dataclass
class Finding:
    rule: str
    artifact: str        # artifact name, e.g. "fused_train_step.dp"
    key: str             # stable instruction key or rule-defined anchor
    message: str
    where: str = ""      # human hint: computation/instruction name
    id: str = ""
    waived: bool = False
    waive_reason: str | None = None
    baselined: bool = False

    def to_json(self):
        return {
            "id": self.id,
            "rule": self.rule,
            "artifact": self.artifact,
            "key": self.key,
            "where": self.where,
            "message": self.message,
            "waived": self.waived,
            "waive_reason": self.waive_reason,
            "baselined": self.baselined,
        }


@dataclass
class Artifact:
    """One captured program.  ``jaxpr``/``lowered``/``optimized`` are the
    raw texts (any may be None when that stage is unavailable); parsed
    modules are cached on first access."""
    name: str
    kind: str                       # train_step|allreduce|kernel|serve|fixture
    jaxpr: str | None = None
    lowered: str | None = None
    optimized: str | None = None
    contract: dict = field(default_factory=dict)
    meta: dict = field(default_factory=dict)
    _mods: dict = field(default_factory=dict, repr=False)

    def __post_init__(self):
        unknown = set(self.contract) - KNOWN_CONTRACT_KEYS
        if unknown:
            raise ValueError(
                f"artifact {self.name!r}: unknown contract key(s) "
                f"{sorted(unknown)} — known: {sorted(KNOWN_CONTRACT_KEYS)}")

    def module(self, stage):
        """Parsed :class:`hlo.Module` for ``stage`` in
        {"lowered", "optimized"}; None when the text is absent."""
        if stage not in self._mods:
            text = getattr(self, stage)
            self._mods[stage] = hlo.parse(text) if text else None
        return self._mods[stage]

    @property
    def best_module(self):
        """Optimized module when captured, else lowered — rules that care
        about *presence* of ops (host round-trip, resharding) read
        whichever is closest to what runs."""
        return self.module("optimized") or self.module("lowered")

    def finding(self, rule, key, message, where=""):
        return Finding(rule=rule, artifact=self.name, key=key,
                       message=message, where=where)

    def keyed(self, rule, instr, ordinal, message, where=""):
        """Finding anchored on one instruction via its stable key."""
        return self.finding(rule, hlo.stable_key(instr, ordinal), message,
                            where=where or instr.name)


def assign_ids(findings):
    """Stable IDs: sha1-12 of ``rule|artifact|key``, disambiguated by
    occurrence order for identical triples."""
    seen = {}
    for f in findings:
        key = f"{f.rule}|{f.artifact}|{f.key}"
        n = seen.get(key, 0)
        seen[key] = n + 1
        if n:
            key = f"{key}|#{n + 1}"
        f.id = hashlib.sha1(key.encode("utf-8")).hexdigest()[:12]
    return findings


def apply_waivers(findings, artifact):
    """Mark findings covered by the artifact's contract waivers; emit a
    ``bad-waiver`` finding per waiver missing its reason."""
    waivers = artifact.contract.get("waivers", ())
    out = []
    for f in findings:
        for w in waivers:
            if w.get("rule") != f.rule or not w.get("reason"):
                continue
            match = w.get("match")
            if match and match not in f.key:
                continue
            f.waived, f.waive_reason = True, w["reason"]
            break
        out.append(f)
    for i, w in enumerate(waivers):
        if not w.get("reason"):
            out.append(Finding(
                rule="bad-waiver", artifact=artifact.name,
                key=f"waiver[{i}]:{w.get('rule', '?')}",
                message="contract waiver without a reason — add "
                        '"reason": "<why the compiled program is allowed '
                        'to do this>" (unreasoned waivers hide intent)'))
    return out


def ordinal_keys(instructions):
    """Pair each instruction with its ordinal among same-(opcode, shape)
    peers — the disambiguator :func:`hlo.stable_key` expects."""
    counts = {}
    out = []
    for instr in instructions:
        k = (instr.opcode, instr.clean_shape)
        n = counts.get(k, 0)
        counts[k] = n + 1
        out.append((instr, n))
    return out
