"""collective-overlap: gradient collectives must be hideable behind compute.

Ancestor claim (PR 4, locked by ROADMAP item 1): the bucketed allreduce
path issues each bucket's collective *as soon as its last gradient is
produced*, so the transfer for bucket k overlaps the backward compute
of buckets k+1..n.  That claim is only real if the compiled program's
dependence structure permits it — a collective whose operands transitively
include (or whose result transitively feeds) *every* compute op cannot be
hidden by any scheduler on any backend.

Two checking modes (see :func:`tools.hloscan.hlo.overlap_report`):

* ``paired`` — the module already carries ``all-reduce-start``/``-done``
  (TPU latency-hiding pipeline ran): the rule requires real compute
  scheduled strictly between start and done.
* ``modeled`` — collectives are synchronous in HLO (this repo's CPU CI;
  the async split happens in the thunk runtime below HLO): the rule
  requires that compute *independent* of the collective exists — the
  exact set XLA's AsyncCollectiveCreator + LatencyHidingScheduler may
  move into the start→done window on TPU.

Only artifacts that declare ``"expect_overlap": true`` are checked: a
standalone allreduce microbenchmark has nothing to overlap with, and
demanding it would force fake compute into the program.
"""
from __future__ import annotations

from .. import hlo
from . import Rule


class CollectiveOverlap(Rule):
    name = "collective-overlap"
    description = ("collectives whose dependence structure (or actual "
                   "schedule) forbids overlap with real compute")

    def check(self, artifact):
        if not artifact.contract.get("expect_overlap"):
            return
        mod = artifact.module("optimized") or artifact.module("lowered")
        if mod is None or mod.entry is None:
            yield artifact.finding(
                self.name, "no-module",
                "expect_overlap declared but no HLO captured for this "
                "artifact — capture layer broken")
            return
        reports = hlo.overlap_report(mod.entry)
        if not reports:
            yield artifact.finding(
                self.name, "no-collectives",
                "expect_overlap declared but the entry computation issues "
                "no collectives — either the contract is stale or the "
                "collective was traced away (check shardings)")
            return
        ordinals = {}
        for rep in reports:
            instr = rep["instr"]
            k = (instr.opcode, instr.clean_shape)
            n = ordinals.get(k, 0)
            ordinals[k] = n + 1
            if rep["compute"]:
                continue
            if rep["mode"] == "paired":
                msg = (f"`{instr.opcode}` pair has NO compute scheduled "
                       f"between start and done: the latency-hiding "
                       f"scheduler exposed this collective on the critical "
                       f"path — check bucket issue order (PR 4 contract)")
            else:
                msg = (f"`{instr.opcode}` {instr.clean_shape} has no "
                       f"compute independent of it in the dependence "
                       f"graph: every op is its producer or consumer, so "
                       f"NO schedule on any backend can hide this "
                       f"collective — it serializes the step")
            yield artifact.keyed(self.name, instr, n, msg)
