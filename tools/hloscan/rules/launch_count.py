"""launch-count: the collective census matches the bucketed contract.

Ancestor claim (PR 4 headline): bucketing collapsed the dp gradient
path from one collective per parameter (160 for the resnet50 profile)
to one per bucket.  That collapse is trivially easy to regress — a
bucketer bypass on an unusual dtype, a cache-key bug that splits
buckets, a refactor that re-introduces per-key launches — and the only
place the truth lives is the compiled module's opcode census.

The contract pins it::

    "expected_collectives": {"all-reduce": 4}     # exact per-opcode
    "expected_collectives": 4                     # exact total
    "collective_free": true                       # zero collectives

Counting convention: *issues*, not instructions — a ``-start``/``-done``
pair is one launch (the start is counted, the done is the same launch
completing).  Counts cover every computation in the module, so
collectives inside while-loop bodies are not hidden.  Both a shortfall
and an excess are findings: fewer collectives than declared means the
contract is stale or a collective was traced away (a silently
non-synchronizing step), more means launches leaked back in.
"""
from __future__ import annotations

from .. import hlo
from . import Rule


class LaunchCount(Rule):
    name = "launch-count"
    description = ("collective issue count per step differs from the "
                   "bucketed contract (PR 4's 160->4 collapse)")

    def check(self, artifact):
        expected = artifact.contract.get("expected_collectives")
        collective_free = artifact.contract.get("collective_free")
        if expected is None and not collective_free:
            return
        mod = artifact.best_module
        if mod is None:
            yield artifact.finding(
                self.name, "no-module",
                "launch-count contract declared but no HLO captured for "
                "this artifact — capture layer broken")
            return
        counts = hlo.collective_counts(mod)
        total = sum(counts.values())
        if collective_free:
            if total:
                census = ", ".join(f"{k}={v}" for k, v in sorted(
                    counts.items()))
                yield artifact.finding(
                    self.name, "collective-free",
                    f"collective_free program issues {total} collective(s) "
                    f"({census}) — a single-device/replicated artifact "
                    f"should compile to zero cross-device traffic")
            return
        if isinstance(expected, dict):
            for op in sorted(set(expected) | set(counts)):
                want, got = expected.get(op, 0), counts.get(op, 0)
                if want == got:
                    continue
                direction = "leaked back in" if got > want else \
                    "were traced away (step may silently not synchronize)"
                yield artifact.finding(
                    self.name, f"count:{op}",
                    f"`{op}` issue count {got} != contract {want}: "
                    f"launches {direction} — recount the bucket plan or "
                    f"update the contract with the change that moved it")
        else:
            if total != int(expected):
                census = ", ".join(f"{k}={v}" for k, v in sorted(
                    counts.items())) or "none"
                direction = "leaked back in" if total > int(expected) else \
                    "were traced away (step may silently not synchronize)"
                yield artifact.finding(
                    self.name, "count:total",
                    f"total collective issues {total} != contract "
                    f"{expected} ({census}): launches {direction}")
