"""dtype-cliff: bf16 recipes must not silently climb back to f32.

Ancestor claim (PR 3, the FusedTrainStep NaN cliff): the bf16 recipe's
whole point is that matmuls *run* in bf16 with f32 accumulation —
``dot(bf16, bf16) -> f32`` via ``preferred_element_type``.  The cliff's
compiled-side twin is the *other* way to get f32 out of a dot: a
``convert(bf16 -> f32)`` feeding the dot's operand, which makes the
MXU/FMA units compute in full f32 — 2x the flops and bandwidth of the
recipe the user asked for, indistinguishable from the intended program
at the Python level (one stray ``.astype`` or dtype-promoting constant
does it).

Checked on the LOWERED module: that is the user program as written —
the optimizer is *allowed* to upcast for its own reasons (CPU has no
bf16 FMA), and flagging its choices would make the rule backend noise.
Two findings:

* **upcast-dot** — ``convert`` producing f32 from a bf16 value whose
  consumer is a ``dot``/``convolution``: the contraction itself now
  runs in f32.
* **f32-roundtrip** — ``convert`` bf16→f32 whose descendants do real
  compute and convert back to bf16: a full-precision detour the recipe
  did not declare.  Intentional f32 islands (softmax accumulation, loss
  reduction) are declared with a contract waiver stating why.

Only artifacts with ``"dtype_policy": "bf16"`` are checked.
"""
from __future__ import annotations

from .. import hlo
from . import Rule

_CONTRACTIONS = ("dot", "convolution")


class DtypeCliff(Rule):
    name = "dtype-cliff"
    description = ("f32 convert chains inside bf16 recipes: upcast "
                   "contractions and undeclared f32 round-trips")

    def check(self, artifact):
        if artifact.contract.get("dtype_policy") != "bf16":
            return
        mod = artifact.module("lowered") or artifact.module("optimized")
        if mod is None:
            return
        ordinals = {}
        for comp in mod.computations.values():
            cons = comp.consumers()
            for instr in comp.instructions:
                if instr.opcode != "convert":
                    continue
                if instr.result_dtypes[:1] != ("f32",):
                    continue
                src = comp.by_name.get(instr.operands[0]) \
                    if instr.operands else None
                if src is None or "bf16" not in src.result_dtypes[:1]:
                    continue
                k = (instr.opcode, instr.clean_shape)
                n = ordinals.get(k, 0)
                ordinals[k] = n + 1
                users = cons.get(instr.name, [])
                contraction = next(
                    (u for u in users if u.opcode in _CONTRACTIONS), None)
                if contraction is not None:
                    yield artifact.keyed(
                        self.name, instr, n,
                        f"bf16->f32 convert feeds `{contraction.opcode}` "
                        f"{contraction.clean_shape}: the contraction runs "
                        f"in full f32 — the bf16 recipe wants bf16 inputs "
                        f"with f32 accumulation (preferred_element_type), "
                        f"not upcast operands; drop the convert or waive "
                        f"with the reason this op needs f32 inputs",
                        where=f"{comp.name}/{instr.name}")
                    continue
                desc = comp.descendants(instr, cons)
                back = any(d.opcode == "convert" and
                           d.result_dtypes[:1] == ("bf16",) for d in desc)
                arith = any(hlo.is_compute(d) for d in desc)
                if back and arith:
                    yield artifact.keyed(
                        self.name, instr, n,
                        f"bf16->f32->compute->bf16 round-trip starting at "
                        f"`{instr.name}`: an f32 detour the recipe did not "
                        f"declare (the PR 3 NaN-cliff's silent-upcast "
                        f"twin) — keep the chain bf16, or waive with the "
                        f"reason this island accumulates in f32 by design",
                        where=f"{comp.name}/{instr.name}")
