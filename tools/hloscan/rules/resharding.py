"""resharding-detector: data-movement collectives the shardings did not buy.

Ancestor claim (PR 4 / PAPERS.md pod-scale scaling): a dp gradient step
needs exactly its ``all-reduce``s — every ``all-gather`` /
``all-to-all`` / ``collective-permute`` in the module is the SPMD
partitioner *repairing a sharding mismatch* the user wrote: an output
sharding that doesn't match the computation's natural layout, a
``PartitionSpec`` that silently replicates, an operand the partitioner
must gather to satisfy a dot.  On 8 virtual CPU devices that repair
costs microseconds; at pod scale the same gather is a full-mesh
broadcast per step.

The rule is declarative: artifacts that promise
``"resharding_free": true`` must compile to a module with NO
data-movement collective; programs whose contract *includes* a gather
(serving a replicated output from sharded params, say) list the base
opcodes under ``"allowed_reshard_ops"``.  Reductions (``all-reduce``,
``reduce-scatter``) are never flagged here — they are the payload, and
launch-count owns their census.

Checked on the best module (optimized when captured): resharding is
inserted by the partitioner, so it only exists post-SPMD.
"""
from __future__ import annotations

from .. import hlo
from . import Rule


class ReshardingDetector(Rule):
    name = "resharding-detector"
    description = ("all-gather/all-to-all/collective-permute not implied "
                   "by the declared in/out shardings")

    def check(self, artifact):
        if not artifact.contract.get("resharding_free"):
            return
        allowed = set(artifact.contract.get("allowed_reshard_ops", ()))
        mod = artifact.best_module
        if mod is None:
            return
        ordinals = {}
        for comp in mod.computations.values():
            for instr in comp.instructions:
                if not hlo.is_collective_issue(instr):
                    continue
                base = hlo.base_collective(instr.opcode)
                if base not in hlo.RESHARD_OPS or base in allowed:
                    continue
                k = (instr.opcode, instr.clean_shape)
                n = ordinals.get(k, 0)
                ordinals[k] = n + 1
                yield artifact.keyed(
                    self.name, instr, n,
                    f"`{base}` {instr.clean_shape} in a resharding_free "
                    f"program: the partitioner inserted this to repair a "
                    f"sharding mismatch — audit the PartitionSpecs "
                    f"(in/out shardings vs the computation's natural "
                    f"layout); at pod scale this is a per-step full-mesh "
                    f"transfer",
                    where=f"{comp.name}/{instr.name}")
