"""hloscan rules: one class per compiled-program contract.

A rule reads one :class:`~tools.hloscan.core.Artifact` (jaxpr + lowered
HLO + optimized HLO + contract) and yields findings where the program
XLA will actually run breaks the invariant the entry point declared.
Rules must be deterministic and total: no finding may depend on
instruction numbering, channel ids, or layout braces (use
``Artifact.keyed`` / ``hlo.stable_key`` so IDs survive recompiles).
"""
from __future__ import annotations


class Rule:
    name = "abstract"
    description = ""

    def check(self, artifact):
        """Yield :class:`~tools.hloscan.core.Finding` for ``artifact``."""
        raise NotImplementedError


def all_rules():
    from .overlap import CollectiveOverlap
    from .host_roundtrip import NoHostRoundtrip
    from .dtype_cliff import DtypeCliff
    from .resharding import ReshardingDetector
    from .launch_count import LaunchCount
    return [
        CollectiveOverlap(),
        NoHostRoundtrip(),
        DtypeCliff(),
        ReshardingDetector(),
        LaunchCount(),
    ]
