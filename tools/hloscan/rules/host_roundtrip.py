"""no-host-roundtrip: step programs never bounce through the host.

Ancestor claim (PR 2 retrace watchdog, PR 5 host-sync-in-jit): the
Python-side lint catches ``.item()``/``onp.asarray`` in *source*; this
rule catches what actually survives into the compiled artifact —
``infeed``/``outfeed``, ``send``/``recv``, and ``custom-call``s whose
target re-enters the Python process (``xla_python_cpu_callback`` and
friends from ``jax.pure_callback`` / ``io_callback`` /
``host_callback``).  Any of these inside a train-step or serve
executable is a per-step device→host→device round-trip that caps step
time at host latency no matter how fast the accelerator is.

Checked on the artifact's best module (optimized when captured): a
callback the optimizer deleted as dead code costs nothing and is not
flagged.  Artifacts that genuinely want host I/O (a debugging harness)
opt out with ``"allow_host_roundtrip": true`` plus a waiver-grade
justification in the contract.
"""
from __future__ import annotations

from .. import hlo
from . import Rule


class NoHostRoundtrip(Rule):
    name = "no-host-roundtrip"
    description = ("infeed/outfeed/send/recv/host-callback custom-calls "
                   "inside step or serve programs")

    def check(self, artifact):
        if artifact.contract.get("allow_host_roundtrip"):
            return
        mod = artifact.best_module
        if mod is None:
            return
        ordinals = {}
        for comp in mod.computations.values():
            for instr in comp.instructions:
                if not hlo.is_host_op(instr):
                    continue
                k = (instr.opcode, instr.clean_shape)
                n = ordinals.get(k, 0)
                ordinals[k] = n + 1
                if instr.opcode == "custom-call":
                    what = (f"host-callback custom-call "
                            f"(target `{instr.custom_call_target}`)")
                else:
                    what = f"`{instr.opcode}`"
                yield artifact.keyed(
                    self.name, instr, n,
                    f"{what} in computation `{comp.name}`: a device->host "
                    f"round-trip inside a step program caps throughput at "
                    f"host latency — move the host work outside the jit "
                    f"boundary, or set allow_host_roundtrip with a reasoned "
                    f"waiver if this artifact is host-interactive by design",
                    where=f"{comp.name}/{instr.name}")
