"""hloscan: compiled-program contract checker over jaxprs and HLO.

mxlint (PR 5) gates Python-source bug classes; hloscan gates the claims
that live in the *compiled* artifact — "communication overlaps
backward", "no host round-trip inside the step", "the bf16 recipe
stays bf16", "the sharding doesn't secretly gather", "4 launches, not
160".  Input is not source text but captured jaxprs and lowered /
optimized HLO of the project's real entry points (see
``mxnet_tpu.analysis``), plus per-artifact contracts declaring the
invariants.

Same conventions as mxlint: stable finding IDs, reasoned waivers (on
the artifact contract — HLO has no comment lines to waive from), an
empty checked-in baseline (``tools/hloscan_baseline.json``), text/JSON
reporters.  One deliberate divergence: stale baseline entries FAIL the
scan instead of printing a note — see ``driver.run``.

Usage::

    python -m tools.hloscan                  # scan all real entry points
    python -m tools.hloscan allreduce.bucket_dense --verdicts
    python -m tools.hloscan --list-rules
"""
from .core import Artifact, Finding                      # noqa: F401
from .driver import run, scan, verdict_lines             # noqa: F401
