"""bits-as-float: int<->float bit reinterpretation outside a boundary.

Ancestor bug (fixed in PR 3): ``FusedTrainStep`` carried its PRNG
counter as int bits viewed into a float gradient buffer; any value
landing in the NaN-payload encoding zone was silently canonicalized by
the next float op and the counter corrupted — a once-a-week NaN cliff.
The fix shipped the counter as its own int32 array; this rule keeps
the pattern from growing back.

Flags ``x.view(<dtype>)`` (ndarray bit reinterpretation) and
``lax.bitcast_convert_type`` / ``.bitcast`` anywhere outside an
explicitly allowlisted module.  Legitimate format-conversion sites
(e.g. the legacy bf16 checkpoint codec) carry a waiver naming the
invariant that makes them safe.
"""
from __future__ import annotations

import ast
import re

from .. import core
from . import Rule

#: Modules allowed to reinterpret bits without a waiver (empty: the
#: repo's codec sites carry explicit per-line waivers instead, so every
#: boundary states its own safety argument).
ALLOWED_MODULES = frozenset()

_DTYPEISH = re.compile(
    r"(?:jnp|onp|np|numpy|jax\.numpy)\.(?:bfloat|float|u?int)[0-9]*|"
    r"(?:^|[(,=\s])[\"'](?:bfloat|float|u?int)[0-9]+[\"']|dtype")


class BitsAsFloat(Rule):
    name = "bits-as-float"
    description = (".view(dtype)/bitcast between int and float bits outside "
                   "an allowlisted quantization/codec boundary")

    def check_file(self, ctx):
        if ctx.relpath in ALLOWED_MODULES:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and \
                    f.attr in ("bitcast_convert_type", "bitcast"):
                yield ctx.finding(
                    self.name, node,
                    f"`{core.unparse(f)}` reinterprets raw bits: payloads "
                    f"that alias NaN encodings get canonicalized by the "
                    f"next float op (the FusedTrainStep counter class) — "
                    f"keep integer payloads in integer arrays, or waive "
                    f"naming the invariant that keeps the bits inert")
            elif isinstance(f, ast.Attribute) and f.attr == "view" \
                    and self._dtype_arg(node):
                yield ctx.finding(
                    self.name, node,
                    f"`.view({core.unparse(node.args[0]) if node.args else ''})`"
                    f" reinterprets array bits across dtypes (the "
                    f"FusedTrainStep NaN-cliff class) — isolate in a codec "
                    f"boundary and waive with the safety invariant")

    @staticmethod
    def _dtype_arg(call):
        exprs = list(call.args) + [kw.value for kw in call.keywords]
        return any(_DTYPEISH.search(core.unparse(e)) for e in exprs)
