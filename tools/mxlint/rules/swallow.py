"""swallowed-exception: broad handlers that eat the error and tell no one.

Ancestor bug: ``DevicePrefetcher._feed`` caught the source's exception
but the dtype cast and ``device_put`` ran OUTSIDE the try — an error
there killed the feeder thread silently and the consumer hung on an
empty queue until its liveness timeout.  The general failure mode: a
``try: ... except Exception: pass`` (or ``return None``) turns a real
fault into a mystery three layers later — the exact opposite of what a
resilience layer needs, which is faults SURFACING at a recovery point.

Heuristic: an ``except`` handler fires when ALL of

* the caught type is broad — bare ``except:``, ``Exception``, or
  ``BaseException`` (alone or in a tuple);
* the body never re-raises (no ``raise`` anywhere in it);
* the bound name (``as e``) is never used in the body — so the error
  object provably doesn't travel anywhere (futures, queues, wrappers);
* nothing in the body looks like reporting: no logging-style call
  (``log.warning``/``.error``/``.exception``/...), no ``warnings.warn``,
  no ``print``, and no telemetry tick (``.inc``/``.observe``/``.set``
  on a metric).

Handlers that genuinely must eat everything (``__del__`` during
interpreter teardown, best-effort probes where absence is the normal
case) carry a waiver saying so.
"""
from __future__ import annotations

import ast

from . import Rule

_BROAD = {"Exception", "BaseException"}

_REPORTING_ATTRS = {
    # logging-ish
    "debug", "info", "warning", "warn", "error", "exception", "critical",
    "log",
    # telemetry-ish: ticking a counter/gauge/histogram IS reporting
    "inc", "observe",
}
_REPORTING_NAMES = {"print"}


def _name_of(node):
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _is_broad(handler):
    """Bare ``except:`` or a type (or tuple member) named Exception/
    BaseException."""
    t = handler.type
    if t is None:
        return True
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    return any(_name_of(e) in _BROAD for e in types)


def _uses_name(body, name):
    for stmt in body:
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Name) and sub.id == name:
                return True
    return False


def _reports(body):
    for stmt in body:
        for sub in ast.walk(stmt):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            if isinstance(f, ast.Name) and f.id in _REPORTING_NAMES:
                return True
            if isinstance(f, ast.Attribute) and f.attr in _REPORTING_ATTRS:
                return True
    return False


def _reraises(body):
    return any(isinstance(sub, ast.Raise)
               for stmt in body for sub in ast.walk(stmt))


class SwallowedException(Rule):
    name = "swallowed-exception"
    description = ("broad except handler (bare/Exception/BaseException) "
                   "that neither re-raises, uses the bound exception, "
                   "logs, prints, nor ticks telemetry — the fault "
                   "vanishes (the DevicePrefetcher silent-feeder-death "
                   "class)")

    def check_file(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node):
                continue
            if _reraises(node.body):
                continue
            if node.name and _uses_name(node.body, node.name):
                continue
            if _reports(node.body):
                continue
            yield ctx.finding(
                self.name, node,
                "broad exception handler swallows the error: no raise, "
                "the exception object is unused, and nothing logs or "
                "ticks a counter — a real fault here dies silently and "
                "resurfaces as a hang or wrong answer far away; "
                "re-raise, propagate the object (queue/future), log it, "
                "or waive with the reason absence-is-normal")
