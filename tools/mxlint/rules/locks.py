"""lock-discipline: lock-owning classes must mutate shared state locked.

Ancestor bug (fixed in PR 2): ``profiler.Counter.increment`` did an
unlocked read-modify-write on ``self._value`` while concurrent serve
threads incremented it — lost updates, silently wrong metrics.  The
class HAD a lock; the bug was one mutation path that bypassed it.

Heuristic (tuned for near-zero noise): in any class whose ``__init__``
creates a ``threading.Lock``/``RLock`` on ``self``, attributes
initialized in ``__init__`` to a plain counter/container literal
(int/float, ``[]``, ``{}``, ``set()``, ``dict()``, ``deque()``,
``defaultdict()``, ``OrderedDict()``, ``Counter()``) are *shared
state*.  Any read-modify-write of those — augmented assignment,
subscript store, or a mutating method call (``append``/``add``/
``update``/``pop``/...) — outside a ``with self.<lock>`` block in a
method other than ``__init__`` is a finding.  Plain rebinding
(``self.x = v``) is NOT flagged: it is atomic under the GIL and common
for benign flags; the lost-update class needs a read first.
"""
from __future__ import annotations

import ast

from . import Rule

_LOCK_CTORS = {"Lock", "RLock"}
_CONTAINER_CTORS = {"dict", "list", "set", "deque", "defaultdict",
                    "OrderedDict", "Counter"}
_MUTATORS = {"append", "extend", "insert", "add", "update", "pop",
             "popitem", "remove", "discard", "clear", "setdefault",
             "appendleft", "extendleft"}


def _ctor_name(call):
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _self_attr(node, names=None):
    """``self.X`` -> 'X' (optionally restricted to ``names``)."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        if names is None or node.attr in names:
            return node.attr
    return None


class LockDiscipline(Rule):
    name = "lock-discipline"
    description = ("class creates a threading.Lock in __init__ but mutates "
                   "shared counters/containers outside `with self.<lock>`")

    def check_file(self, ctx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(ctx, node)

    def _check_class(self, ctx, cls):
        init = next((n for n in cls.body
                     if isinstance(n, ast.FunctionDef)
                     and n.name == "__init__"), None)
        if init is None:
            return
        locks, guarded = set(), set()
        for stmt in ast.walk(init):
            if not (isinstance(stmt, ast.Assign)
                    and isinstance(stmt.value, ast.Call)
                    or isinstance(stmt, ast.Assign)):
                continue
            for tgt in stmt.targets:
                attr = _self_attr(tgt)
                if attr is None:
                    continue
                v = stmt.value
                if isinstance(v, ast.Call):
                    name = _ctor_name(v)
                    if name in _LOCK_CTORS:
                        locks.add(attr)
                    elif name in _CONTAINER_CTORS:
                        guarded.add(attr)
                elif isinstance(v, ast.Constant) and \
                        isinstance(v.value, (int, float)) and \
                        not isinstance(v.value, bool):
                    guarded.add(attr)
                elif isinstance(v, (ast.List, ast.Dict, ast.Set)):
                    guarded.add(attr)
        if not locks or not guarded:
            return
        for method in cls.body:
            if isinstance(method, ast.FunctionDef) and \
                    method.name != "__init__":
                yield from self._check_method(ctx, cls, method, locks,
                                              guarded)

    def _check_method(self, ctx, cls, method, locks, guarded):
        # ancestor stack so we can ask "is this mutation under the lock?"
        def visit(node, locked):
            if isinstance(node, ast.With):
                holds = any(
                    _self_attr(item.context_expr, locks) for item in node.items)
                locked = locked or holds
            mutated = self._mutation(node, guarded)
            if mutated and not locked:
                yield ctx.finding(
                    self.name, node,
                    f"`self.{mutated}` is mutated outside `with self."
                    f"{sorted(locks)[0]}` in {cls.name}.{method.name}; the "
                    f"lock created in __init__ promises shared-state "
                    f"mutations are serialized (the profiler.Counter "
                    f"lost-update class)")
            for child in ast.iter_child_nodes(node):
                yield from visit(child, locked)

        for stmt in method.body:
            yield from visit(stmt, False)

    @staticmethod
    def _mutation(node, guarded):
        """Return the mutated guarded attr name, or None."""
        if isinstance(node, ast.AugAssign):
            attr = _self_attr(node.target, guarded)
            if attr:
                return attr
            if isinstance(node.target, ast.Subscript):
                return _self_attr(node.target.value, guarded)
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript):
                    attr = _self_attr(tgt.value, guarded)
                    if attr:
                        return attr
        if isinstance(node, (ast.Delete,)):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript):
                    attr = _self_attr(tgt.value, guarded)
                    if attr:
                        return attr
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in _MUTATORS:
                return _self_attr(node.func.value, guarded)
        return None
