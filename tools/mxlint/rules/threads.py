"""daemon-thread-no-shutdown: daemon threads need a paired join path.

Ancestor bug: ``kvstore/tpu_ici.py`` started a daemon heartbeat thread
per store and ``close()`` only set the stop event — the thread object
was never retained or joined, so every store constructed in a test
leaked one thread until interpreter exit (daemon=True just means "don't
block exit", not "free").

Heuristic: a ``threading.Thread(..., daemon=True)`` construction is a
finding unless the enclosing class (or module, for free functions)
also calls ``.join(...)`` somewhere — i.e. there exists *some* shutdown
path that waits for the thread.  Fire-and-forget threads that are
genuinely unjoinable (process-lifetime singletons) carry a waiver
saying so.
"""
from __future__ import annotations

import ast

from . import Rule


def _is_thread_ctor(call):
    f = call.func
    name = f.attr if isinstance(f, ast.Attribute) else \
        f.id if isinstance(f, ast.Name) else None
    return name == "Thread"


def _daemon_true(call):
    return any(kw.arg == "daemon" and isinstance(kw.value, ast.Constant)
               and kw.value.value is True for kw in call.keywords)


def _thread_join(call):
    """A call that plausibly joins a thread: ``X.join()`` or
    ``X.join(timeout)`` — not ``", ".join(...)`` / ``os.path.join(...)``."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr == "join"):
        return False
    if isinstance(f.value, ast.Constant):         # "sep".join(...)
        return False
    recv = f.value
    if isinstance(recv, ast.Attribute) and recv.attr == "path":
        return False                              # os.path.join
    if isinstance(recv, ast.Name) and recv.id in ("path", "osp", "op"):
        return False
    if len(call.args) > 1:
        return False                              # join(a, b): path-like
    if call.args and isinstance(call.args[0], ast.Constant) and \
            isinstance(call.args[0].value, str):
        return False
    return True


def _has_join(scope):
    return any(isinstance(n, ast.Call) and _thread_join(n)
               for n in ast.walk(scope))


class DaemonThreadNoShutdown(Rule):
    name = "daemon-thread-no-shutdown"
    description = ("threading.Thread(daemon=True) started with no join() "
                   "anywhere in the owning class/module (leaked per "
                   "construction)")

    def check_file(self, ctx):
        # map each Thread(...) ctor to its nearest enclosing class
        classes = [n for n in ast.walk(ctx.tree)
                   if isinstance(n, ast.ClassDef)]
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and _is_thread_ctor(node)
                    and _daemon_true(node)):
                continue
            owner = None
            for cls in classes:
                if cls.lineno <= node.lineno <= (cls.end_lineno or 0):
                    if owner is None or cls.lineno > owner.lineno:
                        owner = cls
            scope = owner if owner is not None else ctx.tree
            if _has_join(scope):
                continue
            where = f"class {owner.name}" if owner is not None else \
                "this module"
            yield ctx.finding(
                self.name, node,
                f"daemon thread started but {where} never join()s any "
                f"thread: each construction leaks a thread until process "
                f"exit (the tpu_ici heartbeat class) — retain the Thread, "
                f"signal a stop Event on close, and join(); or waive for a "
                f"true process-lifetime singleton")
