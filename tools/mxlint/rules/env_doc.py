"""env-var-undocumented: every ``MXNET_*`` knob must be in env.describe().

Ancestor gap: six live knobs (``MXNET_TELEMETRY_STEADY_STEPS``,
``MXNET_PROFILE_RANK``, ``MXNET_PROFILE_DIR``,
``MXNET_KVSTORE_SPARSE_HOST_BOUND``, ``MXNET_TPU_MODEL_REPO``,
``MXNET_DROPOUT_RNG``) were read by their subsystems but invisible in
``mxnet_tpu/env.py`` — the one place users are told to look.  An
undocumented knob is a support incident: someone sets it, nothing is
specified to happen.

The rule inventories every ``MXNET_[A-Z0-9_]+`` string literal used in
an environment access across the project and requires each to appear
in the ``names`` list inside :func:`mxnet_tpu.env.describe` (and hence,
via describe's own ``n in __doc__`` check, in the docstring table).

``tests/test_env_vars.py`` locks the same inventory against
``describe()`` from the other side, so the two can never drift.
"""
from __future__ import annotations

import ast
import os
import re

from .. import core
from . import Rule

_MXNET_NAME = re.compile(r"^MXNET_[A-Z0-9_]+$")

#: Documented-but-never-read knobs that describe() intentionally carries
#: (accepted no-ops kept for reference parity). test_env_vars asserts
#: this is EXACTLY the documented-minus-discovered set.
DECLARED_NOOPS = frozenset({
    "MXNET_GPU_MEM_POOL_RESERVE",
    "MXNET_STORAGE_FALLBACK_LOG_VERBOSE",
})

ENV_PY = "mxnet_tpu/env.py"


def documented_env_vars(repo_root=None):
    """The ``names`` list literal inside ``env.describe()``, by AST (no
    import of mxnet_tpu needed — the linter must run anywhere)."""
    root = repo_root or core.REPO_ROOT
    path = os.path.join(root, *ENV_PY.split("/"))
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "describe":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == "names"
                        for t in sub.targets) and \
                        isinstance(sub.value, ast.List):
                    return {e.value for e in sub.value.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str)}
    raise RuntimeError(f"could not locate describe()'s names list in {path}")


def discovered_env_vars(paths=None, repo_root=None):
    """``{MXNET_* name: [(relpath, line), ...]}`` for every environment
    access site in the scanned roots (reads AND writes — a written knob
    is still part of the configuration surface)."""
    root = repo_root or core.REPO_ROOT
    inventory = {}
    for abspath in core.iter_py_files(paths, repo_root=root):
        try:
            ctx = core.load_file(abspath, repo_root=root)
        except (SyntaxError, UnicodeDecodeError):
            continue
        for node, name, _is_read in core.iter_env_accesses(ctx.tree):
            if name and _MXNET_NAME.match(name):
                inventory.setdefault(name, []).append(
                    (ctx.relpath, getattr(node, "lineno", 0)))
    return inventory


class EnvVarUndocumented(Rule):
    name = "env-var-undocumented"
    description = ("MXNET_* variable accessed but missing from "
                   "env.describe()'s documented table")

    def __init__(self, repo_root=None):
        self._repo_root = repo_root
        self._sites = []   # (ctx, node, var)

    def check_file(self, ctx):
        for node, name, _is_read in core.iter_env_accesses(ctx.tree):
            if name and _MXNET_NAME.match(name):
                self._sites.append((ctx, node, name))
        return []

    def finalize(self):
        try:
            documented = documented_env_vars(self._repo_root)
        except (OSError, RuntimeError):
            documented = set()   # fixture runs without a real env.py
        seen = set()
        for ctx, node, name in self._sites:
            if name in documented:
                continue
            key = (ctx.relpath, name)
            if key in seen:
                continue   # one finding per (file, var) keeps noise down
            seen.add(key)
            yield ctx.finding(
                self.name, node,
                f"`{name}` is read here but missing from env.py's "
                f"describe() table — every MXNET_* knob must be "
                f"documented in the one place users are told to look")
