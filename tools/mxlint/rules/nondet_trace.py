"""nondeterministic-trace: wall-clock/OS entropy reads at trace time.

Ancestor bug class: same shape as ``env-read-at-trace-time``, but for
*values* instead of configuration.  ``time.time()``, stdlib/numpy
``random.*``, or ``os.urandom`` inside a function that jax traces does
not sample per step — it executes ONCE, at trace time, and the sampled
value is baked into the compiled program as a constant.  Every
subsequent step replays the first step's "random" number; dropout
becomes a fixed mask, a jittered timeout becomes a constant, and in
SPMD each process bakes a DIFFERENT constant, so the supposedly
replicated programs silently diverge (the deadliest form: no error,
just non-reproducible, cross-process-inconsistent numerics).

A function counts as *traced* exactly as in ``host-sync-in-jit``:
decorated with or lexically passed to ``jax.jit`` / ``pjit`` /
``pl.pallas_call`` / ``shard_map``, or the ``forward`` /
``hybrid_forward`` of a direct ``HybridBlock`` subclass.

The fix is jax's functional RNG (``jax.random`` with an explicit key
threaded through the program — the ``mx.random`` stream does this) or
hoisting the host-side sample out of the traced region.  ``jax.random``
calls are never flagged.  Time reads that are genuinely host-side
(a traced helper also called eagerly for logging) take a waiver with
that reason.
"""
from __future__ import annotations

import ast

from .. import core
from . import Rule

#: time-module clock reads (all bake a trace-time timestamp).
_CLOCKS = {"time", "time_ns", "monotonic", "monotonic_ns",
           "perf_counter", "perf_counter_ns", "process_time", "clock"}

#: numpy aliases whose ``.random`` attribute is the legacy global RNG.
_NP_MODULES = {"onp", "np", "numpy"}


def _nondet_call(node):
    """(kind, spelled) when ``node`` is a nondeterministic host call:
    time.<clock>(), random.<fn>(), onp.random.<fn>(), os.urandom()."""
    f = node.func
    if isinstance(f, ast.Attribute):
        base = f.value
        if isinstance(base, ast.Name):
            if base.id == "time" and f.attr in _CLOCKS:
                return "wall clock", f"time.{f.attr}()"
            if base.id == "random":
                return "stdlib RNG", f"random.{f.attr}()"
            if base.id == "os" and f.attr == "urandom":
                return "OS entropy", "os.urandom()"
        if isinstance(base, ast.Attribute) and base.attr == "random" \
                and isinstance(base.value, ast.Name) \
                and base.value.id in _NP_MODULES:
            return "numpy global RNG", \
                f"{base.value.id}.random.{f.attr}()"
    elif isinstance(f, ast.Name) and f.id == "urandom":
        return "OS entropy", "urandom()"
    return None


class NondeterministicTrace(Rule):
    name = "nondeterministic-trace"
    description = ("time.time()/random.*/os.urandom inside traced "
                   "functions: sampled once at trace, baked as constant")

    def check_file(self, ctx):
        for fn in core.iter_traced_functions(ctx.tree):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                hit = _nondet_call(node)
                if hit is None:
                    continue
                kind, spelled = hit
                yield ctx.finding(
                    self.name, node,
                    f"`{spelled}` inside traced `{fn.name}`: the {kind} "
                    f"is read at TRACE time and baked into the compiled "
                    f"program — every step replays the same value, and "
                    f"SPMD processes bake different ones (silent "
                    f"divergence); thread a jax.random key instead, or "
                    f"hoist the read out of the traced region (waive "
                    f"with the reason if this helper is host-side-only)")
