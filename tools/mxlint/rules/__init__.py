"""mxlint rule registry.

Each rule is a class with a unique ``name`` (the waiver token), a
``description`` (one line, shown by ``--list-rules``), a
``check_file(ctx)`` hook yielding :class:`~tools.mxlint.core.Finding`
per file, and an optional ``finalize()`` hook for project-wide checks
that need the whole inventory (e.g. env-var documentation coverage).

Rules are instantiated fresh per run, so ``check_file`` may accumulate
state for ``finalize``.
"""
from __future__ import annotations


class Rule:
    name = ""
    description = ""

    def check_file(self, ctx):
        return []

    def finalize(self):
        return []


def all_rules():
    """Fresh instances of every shipped rule."""
    from .bits import BitsAsFloat
    from .env_doc import EnvVarUndocumented
    from .env_trace import EnvReadAtTraceTime
    from .host_sync import HostSyncInJit
    from .locks import LockDiscipline
    from .nondet_trace import NondeterministicTrace
    from .swallow import SwallowedException
    from .threads import DaemonThreadNoShutdown
    return [
        EnvReadAtTraceTime(),
        EnvVarUndocumented(),
        LockDiscipline(),
        HostSyncInJit(),
        NondeterministicTrace(),
        BitsAsFloat(),
        DaemonThreadNoShutdown(),
        SwallowedException(),
    ]
