"""host-sync-in-jit: device->host syncs inside traced functions.

Ancestor bug class: the PR 2 retrace watchdog exists because host syncs
and shape-driven retraces inside jitted code only announce themselves
as mysterious step-time cliffs at runtime.  The static half: ``.item()``,
``.asnumpy()``, ``float()/int()/bool()`` coercion, or ``onp.asarray``
on a traced value inside a function that is jitted, pallas_call-ed, or
shard_map-ed forces a blocking transfer (or a ConcretizationTypeError)
every step.

A function counts as *traced* when it is decorated with — or lexically
passed to — ``jax.jit`` / ``pjit`` / ``pl.pallas_call`` / ``shard_map``
anywhere in the same module, or when it is the ``forward`` /
``hybrid_forward`` of a ``HybridBlock`` subclass (the framework jits
those under ``hybridize()``; plain ``Block`` transforms are host-side
by design and exempt).  Coercions whose argument is static shape
arithmetic (``.shape``/``.ndim``/``.size``/``len()``/``.dtype``) are
host math on Python ints and are not flagged.
"""
from __future__ import annotations

import ast
import re

from .. import core
from . import Rule

_NP_MODULES = {"onp", "np", "numpy"}
_NP_CONVERTERS = {"asarray", "array", "ascontiguousarray"}
_COERCIONS = {"float", "int", "bool", "complex"}
_STATIC_ARG = re.compile(
    r"\.shape|\.ndim|\.size\b|\.dtype|\.itemsize|len\(|range\(|"
    r"\.num_programs|program_id")


class HostSyncInJit(Rule):
    name = "host-sync-in-jit"
    description = (".item()/float()/onp.asarray on traced values inside "
                   "jit/pallas_call/shard_map functions (host sync)")

    def check_file(self, ctx):
        for fn in core.iter_traced_functions(ctx.tree):
            yield from self._check_body(ctx, fn)

    def _check_body(self, ctx, fn):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in ("item", "asnumpy") \
                    and not node.args:
                yield ctx.finding(
                    self.name, node,
                    f"`.{f.attr}()` inside traced `{fn.name}`: forces a "
                    f"device->host sync (or fails to trace) every step — "
                    f"keep values on device, or compute outside the jit "
                    f"boundary")
            elif isinstance(f, ast.Attribute) and f.attr in _NP_CONVERTERS \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id in _NP_MODULES:
                if node.args and self._static(node.args[0]):
                    continue
                yield ctx.finding(
                    self.name, node,
                    f"`{core.unparse(f)}(...)` inside traced `{fn.name}`: "
                    f"materializes a traced value on host (retrace-watchdog "
                    f"class) — use jnp, or hoist the conversion out of the "
                    f"traced region")
            elif isinstance(f, ast.Name) and f.id in _COERCIONS \
                    and len(node.args) == 1:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) or self._static(arg):
                    continue
                yield ctx.finding(
                    self.name, node,
                    f"`{f.id}(...)` on a (potentially traced) value inside "
                    f"traced `{fn.name}`: concretizes the operand — a host "
                    f"sync at best, ConcretizationTypeError at worst; if "
                    f"the operand is static (shape math), make that visible "
                    f"(`.shape`/`len()`), else waive with the reason")

    @staticmethod
    def _static(arg):
        return bool(_STATIC_ARG.search(core.unparse(arg)))
