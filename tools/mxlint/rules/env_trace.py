"""env-read-at-trace-time: runtime ``os.environ`` reads outside env.py.

Ancestor bug (PR 3): ``MXNET_DROPOUT_RNG`` was consulted inside traced
dropout code, so a post-import change could never reach already-jitted
executables — the read silently returned whatever was baked in at first
trace.  The same class recurred in ``ops/invoke.py`` with
``MXNET_ENGINE_DEBUG`` (read per recorded op).

Contract: environment is configuration, and configuration is read at
import.  ``mxnet_tpu/env.py`` is the sanctioned reader (exempt
wholesale); elsewhere, module-scope reads (executed at import) are
fine, while reads inside a function body need either hoisting to a
module-level constant (the ``_DROPOUT_RNG_IMPL`` convention) or a
waiver stating why the read is host-side-only and re-read on purpose.
"""
from __future__ import annotations

from .. import core
from . import Rule

#: The sanctioned environment reader — exempt wholesale.
EXEMPT_FILES = ("mxnet_tpu/env.py",)


class EnvReadAtTraceTime(Rule):
    name = "env-read-at-trace-time"
    description = ("os.environ read inside a function body (outside env.py):"
                   " hoist to module scope or waive as host-side-only")

    def check_file(self, ctx):
        if ctx.relpath in EXEMPT_FILES:
            return
        deferred = core.enclosing_function_lines(ctx.tree)
        for node, name, is_read in core.iter_env_accesses(ctx.tree):
            if not is_read:
                continue
            if getattr(node, "lineno", 0) not in deferred:
                continue  # module scope: executed once at import
            what = f"`{name}`" if name else "the environment"
            yield ctx.finding(
                self.name, node,
                f"runtime read of {what}: env reads inside functions can "
                f"be consulted at trace time and baked into cached "
                f"executables (the MXNET_DROPOUT_RNG class) — hoist to a "
                f"module-level constant read at import, or waive with the "
                f"reason the read is host-side and intentionally repeated")
