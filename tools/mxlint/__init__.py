"""mxlint — project-aware static analysis for mxnet-tpu.

Rules are distilled from this repo's own recurring bug classes (see
docs/STATIC_ANALYSIS.md for the genealogy): trace-time env reads that
get baked into cached executables, undocumented ``MXNET_*`` knobs,
unlocked mutation of thread-shared state, host syncs inside traced
code, int<->float bit reinterpretation, and daemon threads without a
shutdown path.

Entry points:

* ``python -m tools.mxlint`` — lint the project (mxnet_tpu/, tools/,
  benchmark/), exit nonzero on any unbaselined finding.
* :func:`tools.mxlint.driver.run` — programmatic API (tests use it).
* :func:`tools.mxlint.rules.env_doc.discovered_env_vars` /
  :func:`documented_env_vars` — the env-var inventory that
  ``tests/test_env_vars.py`` locks against ``env.describe()``.
"""
from .core import Finding  # noqa: F401
from .driver import lint, main, run  # noqa: F401

__all__ = ["Finding", "lint", "main", "run"]
