"""mxlint driver: walk, check, waive, baseline, report.

Exit status: 0 when every finding is waived or baselined AND the
baseline is current, 1 when any unbaselined finding remains OR the
baseline names findings that no longer exist (stale entries are paid
debts — prune them in the same change via ``--update-baseline``), 2 on
usage error.  ``tools/ci.sh`` runs this as a hard gate before anything
else.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from . import core
from .rules import all_rules

DEFAULT_BASELINE = os.path.join(core.REPO_ROOT, "tools",
                                "mxlint_baseline.json")

JSON_SCHEMA_VERSION = 1


def lint(paths=None, rules=None, repo_root=None):
    """Run ``rules`` (default: all) over ``paths`` (default: project
    roots).  Returns (findings, n_files); waivers applied, no baseline."""
    root = repo_root or core.REPO_ROOT
    rules = all_rules() if rules is None else rules
    ctx_by_path = {}
    by_file = {}
    n_files = 0
    for abspath in core.iter_py_files(paths, repo_root=root):
        n_files += 1
        try:
            ctx = core.load_file(abspath, repo_root=root)
        except SyntaxError as e:
            f = core.Finding(
                rule="parse-error", path=os.path.relpath(
                    abspath, root).replace(os.sep, "/"),
                line=e.lineno or 1, col=e.offset or 0,
                message=f"file does not parse: {e.msg}")
            by_file.setdefault(f.path, []).append(f)
            continue
        except UnicodeDecodeError:
            continue
        ctx_by_path[ctx.relpath] = ctx
        for rule in rules:
            for f in rule.check_file(ctx) or ():
                by_file.setdefault(ctx.relpath, []).append(f)
    for rule in rules:
        for f in rule.finalize() or ():
            by_file.setdefault(f.path, []).append(f)

    findings = []
    for relpath, ctx in ctx_by_path.items():
        findings.extend(core.apply_waivers(by_file.pop(relpath, []), ctx))
    for leftover in by_file.values():   # parse errors: no ctx, no waivers
        findings.extend(leftover)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    core.assign_ids(findings, ctx_by_path)
    return findings, n_files


def load_baseline(path):
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return data.get("findings", {})


def write_baseline(path, findings):
    """Grandfather every current unwaived finding (``--update-baseline``)."""
    entries = {
        f.id: {"rule": f.rule, "path": f.path, "qualname": f.qualname,
               "message": f.message}
        for f in findings if not f.waived}
    payload = {
        "comment": "mxlint grandfathered findings — entries are debts, not "
                   "permissions; remove as they are fixed. Regenerate with "
                   "`python -m tools.mxlint --update-baseline`.",
        "version": JSON_SCHEMA_VERSION,
        "findings": dict(sorted(entries.items())),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=False)
        f.write("\n")
    return entries


def report_text(findings, n_files, stale_ids, out=sys.stdout):
    unbaselined = [f for f in findings if not f.waived and not f.baselined]
    for f in unbaselined:
        out.write(f"{f.path}:{f.line}:{f.col + 1}: [{f.rule}] "
                  f"{f.message}  (id {f.id})\n")
    n_w = sum(1 for f in findings if f.waived)
    n_b = sum(1 for f in findings if f.baselined)
    if stale_ids:
        out.write(f"mxlint: FAIL — {len(stale_ids)} baseline entr"
                  f"{'y names a finding' if len(stale_ids) == 1 else 'ies name findings'} "
                  f"that no longer exist{'s' if len(stale_ids) == 1 else ''} "
                  f"(debt paid — prune it in the same change with "
                  f"--update-baseline): {', '.join(sorted(stale_ids))}\n")
    verdict = "clean" if not unbaselined else \
        f"{len(unbaselined)} unbaselined finding" + \
        ("s" if len(unbaselined) != 1 else "")
    out.write(f"mxlint: {verdict} — {n_files} files, "
              f"{len(findings)} findings ({n_w} waived, {n_b} baselined)\n")


def report_json(findings, n_files, stale_ids, out=sys.stdout):
    unbaselined = [f for f in findings if not f.waived and not f.baselined]
    payload = {
        "version": JSON_SCHEMA_VERSION,
        "tool": "mxlint",
        "files_scanned": n_files,
        "findings": [f.to_json() for f in findings],
        "stale_baseline_ids": sorted(stale_ids),
        "summary": {
            "total": len(findings),
            "waived": sum(1 for f in findings if f.waived),
            "baselined": sum(1 for f in findings if f.baselined),
            "unbaselined": len(unbaselined),
        },
    }
    json.dump(payload, out, indent=2)
    out.write("\n")


def run(paths=None, baseline_path=None, update_baseline=False,
        fmt="text", out=sys.stdout, repo_root=None):
    """Full pipeline; returns the process exit code."""
    findings, n_files = lint(paths, repo_root=repo_root)
    baseline = {}
    if baseline_path:
        baseline = load_baseline(baseline_path)
        for f in findings:
            if not f.waived and f.id in baseline:
                f.baselined = True
    if update_baseline:
        if not baseline_path:
            out.write("mxlint: --update-baseline needs --baseline PATH\n")
            return 2
        entries = write_baseline(baseline_path, findings)
        out.write(f"mxlint: baseline written — {len(entries)} entr"
                  f"{'y' if len(entries) == 1 else 'ies'} -> "
                  f"{baseline_path}\n")
        return 0
    present = {f.id for f in findings if not f.waived}
    stale_ids = set(baseline) - present
    (report_json if fmt == "json" else report_text)(
        findings, n_files, stale_ids, out=out)
    # stale entries fail too: a baseline that names fixed findings no
    # longer reflects reality, and letting it drift re-grandfathers the
    # next regression that happens to hash onto an old id
    failed = any(not f.waived and not f.baselined for f in findings)
    return 1 if (failed or stale_ids) else 0


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m tools.mxlint",
        description="Project-aware static analysis for mxnet-tpu "
                    "(docs/STATIC_ANALYSIS.md).")
    p.add_argument("paths", nargs="*",
                   help="files/directories to lint (default: mxnet_tpu/ "
                        "tools/ benchmark/)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="baseline JSON of grandfathered finding IDs "
                        "(default: tools/mxlint_baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline (report everything)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from current findings")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.name:28s} {rule.description}")
        return 0

    return run(paths=args.paths or None,
               baseline_path=None if args.no_baseline else args.baseline,
               update_baseline=args.update_baseline,
               fmt=args.format)


if __name__ == "__main__":
    sys.exit(main())
