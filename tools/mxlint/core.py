"""Shared mxlint infrastructure: findings, file contexts, waivers.

A *finding* is one rule violation at one source location.  Its ``id``
is stable across unrelated edits: it hashes (rule, path, enclosing
qualname, normalized source line) rather than the line number, so
inserting code above a grandfathered finding does not invalidate the
baseline, while editing the offending line itself does — exactly when
a human should re-look.

Waiver grammar (reason REQUIRED — an empty reason is itself the
``bad-waiver`` finding)::

    x = os.environ.get("MXNET_FOO")  # mxlint: disable=env-read-at-trace-time -- host-side only
    # mxlint: disable=lock-discipline -- single-writer by construction
    counters[k] += 1

    # mxlint: disable-file=env-read-at-trace-time -- launcher plumbing

Line waivers cover their own line or, when the comment stands alone,
the next line.  File waivers cover the whole module.
"""
from __future__ import annotations

import ast
import hashlib
import os
import re
import tokenize
from dataclasses import dataclass, field

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: Directories walked by default, relative to the repo root.
DEFAULT_ROOTS = ("mxnet_tpu", "tools", "benchmark")

_SKIP_DIRS = {"__pycache__", ".git", "results"}


def _waiver_re(tool):
    """Waiver-comment regex for ``tool`` — mxlint and lockscan share the
    grammar (`# <tool>: disable=<rules> -- <reason>`), each matching only
    its own tag so the two checkers' waivers never shadow each other."""
    return re.compile(
        r"#\s*" + re.escape(tool) + r":\s*(disable|disable-file)="
        r"(?P<rules>[A-Za-z0-9_,-]+)"
        r"(?:\s*--\s*(?P<reason>\S.*?))?\s*$")


_WAIVER_RE = _waiver_re("mxlint")
_WAIVER_RES = {"mxlint": _WAIVER_RE}


@dataclass
class Finding:
    rule: str
    path: str            # repo-relative, forward slashes
    line: int
    col: int
    message: str
    qualname: str = "<module>"
    id: str = ""
    waived: bool = False
    waive_reason: str | None = None
    baselined: bool = False

    def to_json(self):
        return {
            "id": self.id,
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "qualname": self.qualname,
            "message": self.message,
            "waived": self.waived,
            "waive_reason": self.waive_reason,
            "baselined": self.baselined,
        }


@dataclass
class Waiver:
    line: int
    rules: tuple
    reason: str | None
    file_level: bool
    used: bool = False


@dataclass
class FileContext:
    """Everything a rule needs about one source file (parsed once)."""
    abspath: str
    relpath: str
    source: str
    lines: list
    tree: ast.AST
    waivers: list = field(default_factory=list)
    _scopes: list = field(default_factory=list)   # (start, end, qualname)
    _stmt_start: dict = field(default_factory=dict)  # line -> stmt first line

    def finding(self, rule, node, message):
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule=rule, path=self.relpath, line=line, col=col,
                       message=message, qualname=self.qualname_at(line))

    def qualname_at(self, line):
        best = "<module>"
        best_span = None
        for start, end, qn in self._scopes:
            if start <= line <= end:
                span = end - start
                if best_span is None or span <= best_span:
                    best, best_span = qn, span
        return best

    def line_text(self, line):
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""

    def stmt_start(self, line):
        """First line of the innermost statement containing ``line`` —
        waivers on a multi-line statement's opening line cover findings
        anchored anywhere inside it."""
        return self._stmt_start.get(line, line)


def iter_py_files(paths=None, repo_root=None):
    """Yield absolute paths of .py files under ``paths`` (files or
    directories; default: the project roots)."""
    root = repo_root or REPO_ROOT
    if paths is None:
        paths = [os.path.join(root, r) for r in DEFAULT_ROOTS]
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def _build_scopes(tree):
    scopes = []

    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                qn = f"{prefix}.{child.name}" if prefix else child.name
                scopes.append((child.lineno, child.end_lineno or child.lineno,
                               qn))
                walk(child, qn)
            else:
                walk(child, prefix)

    walk(tree, "")
    return scopes


def _parse_waivers(source, tool="mxlint"):
    waivers = []
    try:
        import io
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        comments = [(t.start[0], t.string) for t in tokens
                    if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError):
        comments = [(i + 1, line[line.index("#"):])
                    for i, line in enumerate(source.splitlines())
                    if "#" in line]
    if tool not in _WAIVER_RES:
        _WAIVER_RES[tool] = _waiver_re(tool)
    pattern = _WAIVER_RES[tool]
    for line, text in comments:
        m = pattern.search(text)
        if not m:
            continue
        rules = tuple(r.strip() for r in m.group("rules").split(",")
                      if r.strip())
        waivers.append(Waiver(line=line, rules=rules,
                              reason=m.group("reason"),
                              file_level=m.group(1) == "disable-file"))
    return waivers


def load_file(abspath, repo_root=None, tool="mxlint"):
    """Parse one file into a :class:`FileContext` (None on read error).
    ``tool`` selects which checker's waiver comments are honored."""
    root = repo_root or REPO_ROOT
    with open(abspath, "r", encoding="utf-8") as f:
        source = f.read()
    relpath = os.path.relpath(abspath, root).replace(os.sep, "/")
    tree = ast.parse(source, filename=relpath)
    ctx = FileContext(abspath=abspath, relpath=relpath, source=source,
                      lines=source.splitlines(), tree=tree)
    ctx.waivers = _parse_waivers(source, tool=tool)
    ctx._scopes = _build_scopes(tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.stmt):
            for ln in range(node.lineno, (node.end_lineno or node.lineno) + 1):
                # innermost statement wins: later (deeper) visits overwrite
                # only if they start later
                if ln not in ctx._stmt_start or \
                        node.lineno >= ctx._stmt_start[ln]:
                    ctx._stmt_start[ln] = node.lineno
    return ctx


def assign_ids(findings, ctx_by_path):
    """Stable IDs: hash of (rule, path, qualname, normalized line text),
    disambiguated by occurrence order for identical keys."""
    seen = {}
    for f in findings:
        ctx = ctx_by_path.get(f.path)
        text = ctx.line_text(f.line).strip() if ctx else ""
        key = f"{f.rule}|{f.path}|{f.qualname}|{text}"
        n = seen.get(key, 0)
        seen[key] = n + 1
        if n:
            key = f"{key}|#{n + 1}"
        f.id = hashlib.sha1(key.encode("utf-8")).hexdigest()[:12]
    return findings


def apply_waivers(findings, ctx, tool="mxlint"):
    """Mark findings covered by a (reasoned) waiver; emit ``bad-waiver``
    findings for waivers missing the required reason."""
    out = []
    file_waivers = [w for w in ctx.waivers if w.file_level and w.reason]
    line_waivers = {}
    for w in ctx.waivers:
        if not w.file_level and w.reason:
            line_waivers.setdefault(w.line, []).append(w)

    for f in findings:
        hit = None
        for w in file_waivers:
            if f.rule in w.rules:
                hit = w
                break
        if hit is None:
            anchor_lines = {f.line, ctx.stmt_start(f.line)}
            candidates = []
            for ln in anchor_lines:
                candidates.extend(line_waivers.get(ln, ()))
                # a standalone comment line waives the line BELOW it
                for w in line_waivers.get(ln - 1, ()):
                    if ctx.line_text(w.line).lstrip().startswith("#"):
                        candidates.append(w)
            for w in candidates:
                if f.rule in w.rules:
                    hit = w
                    break
        if hit is not None:
            f.waived, f.waive_reason = True, hit.reason
            hit.used = True
        out.append(f)

    for w in ctx.waivers:
        if not w.reason:
            out.append(Finding(
                rule="bad-waiver", path=ctx.relpath, line=w.line, col=0,
                message=f"{tool} waiver without a reason — append "
                        "`-- <why this is safe>` (unreasoned waivers are "
                        "worse than findings: they hide intent)",
                qualname=ctx.qualname_at(w.line)))
    return out


# --------------------------------------------------------------------------
# shared AST helpers
# --------------------------------------------------------------------------
def unparse(node):
    try:
        return ast.unparse(node)
    except Exception:  # mxlint: disable=swallowed-exception -- display-only helper; an unparseable synthetic node renders as empty, never fails a lint run
        return ""


def is_environ_expr(node):
    """``os.environ`` / bare ``environ`` (from-import)."""
    if isinstance(node, ast.Attribute) and node.attr == "environ" and \
            isinstance(node.value, ast.Name) and node.value.id == "os":
        return True
    return isinstance(node, ast.Name) and node.id == "environ"


def _const_env_name(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def iter_env_accesses(tree):
    """Yield ``(node, var_name_or_None, is_read)`` for every access of the
    process environment: ``os.environ.get/.setdefault/.pop``,
    ``os.environ[...]`` (load and store), ``os.getenv``, ``K in
    os.environ``, and bare ``os.environ`` passed around (``dict(os.environ)``).
    """
    claimed = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and is_environ_expr(fn.value) \
                    and fn.attr in ("get", "setdefault", "pop",
                                    "__getitem__", "__contains__"):
                claimed.add(id(fn.value))
                name = _const_env_name(node.args[0]) if node.args else None
                yield node, name, True
            elif isinstance(fn, ast.Attribute) and is_environ_expr(fn.value):
                # other environ methods (keys/items/update/delete): treat as
                # a read of the whole env except pure writes
                claimed.add(id(fn.value))
                is_read = fn.attr not in ("update", "__setitem__",
                                          "__delitem__", "clear")
                yield node, None, is_read
            elif (isinstance(fn, ast.Attribute) and fn.attr == "getenv"
                  and isinstance(fn.value, ast.Name) and fn.value.id == "os") \
                    or (isinstance(fn, ast.Name) and fn.id == "getenv"):
                name = _const_env_name(node.args[0]) if node.args else None
                yield node, name, True
        elif isinstance(node, ast.Subscript) and is_environ_expr(node.value):
            claimed.add(id(node.value))
            name = _const_env_name(node.slice)
            yield node, name, isinstance(node.ctx, ast.Load)
        elif isinstance(node, ast.Compare) and any(
                is_environ_expr(c) for c in node.comparators) and any(
                isinstance(op, (ast.In, ast.NotIn)) for op in node.ops):
            for c in node.comparators:
                if is_environ_expr(c):
                    claimed.add(id(c))
            yield node, _const_env_name(node.left), True
    # bare `os.environ` loads not consumed above (dict(os.environ), ...)
    for node in ast.walk(tree):
        if is_environ_expr(node) and id(node) not in claimed and \
                isinstance(getattr(node, "ctx", None), ast.Load):
            yield node, None, True


#: Names that mark a function as traced when used as a decorator or as
#: the callable a function is lexically passed to.
TRACERS = {"jit", "pjit", "pallas_call", "shard_map"}


def mentions_tracer(node):
    """``node`` (a decorator or call target) references jit/pjit/
    pallas_call/shard_map anywhere inside it."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in TRACERS:
            return True
        if isinstance(sub, ast.Attribute) and sub.attr in TRACERS:
            return True
    return False


def is_hybrid_block(cls):
    """Base list mentions HybridBlock (direct subclass — transitive bases
    across modules are out of reach for a single-file pass)."""
    for base in cls.bases:
        if isinstance(base, ast.Name) and base.id == "HybridBlock":
            return True
        if isinstance(base, ast.Attribute) and base.attr == "HybridBlock":
            return True
    return False


def collect_traced_names(tree):
    """Function names decorated with, or passed as arguments to, a
    jit/pallas_call/shard_map call in this module."""
    traced = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(mentions_tracer(d) for d in node.decorator_list):
                traced.add(node.name)
        elif isinstance(node, ast.Call) and mentions_tracer(node.func):
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name):
                    traced.add(arg.id)
    return traced


def iter_traced_functions(tree):
    """Yield every function body that is traced in this module: named
    functions collected by :func:`collect_traced_names` plus
    ``forward``/``hybrid_forward`` methods of direct HybridBlock
    subclasses (jitted under ``hybridize()``), each yielded once."""
    traced = collect_traced_names(tree)
    seen = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in traced:
            seen.add(id(node))
            yield node
    for cls in ast.walk(tree):
        if isinstance(cls, ast.ClassDef) and is_hybrid_block(cls):
            for m in cls.body:
                if isinstance(m, ast.FunctionDef) and \
                        m.name in ("forward", "hybrid_forward") and \
                        id(m) not in seen:
                    yield m


def enclosing_function_lines(tree):
    """Set of line numbers that fall inside any def/lambda body — i.e.
    NOT executed at import time."""
    lines = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            body = node.body if not isinstance(node, ast.Lambda) else [node.body]
            for stmt in body:
                for sub in ast.walk(stmt):
                    ln = getattr(sub, "lineno", None)
                    if ln is not None:
                        lines.add(ln)
    return lines


# --------------------------------------------------------------------------
# project-wide call resolution (shared by mxlint rules and tools/lockscan)
# --------------------------------------------------------------------------
#: Constructor calls whose result type is worth tracking even though the
#: class is not defined in this project (queue ops have their own
#: blocking semantics; threading primitives are lock objects).
_BUILTIN_TYPES = {
    ("queue", "Queue"): "queue.Queue",
    ("queue", "SimpleQueue"): "queue.Queue",
    ("queue", "LifoQueue"): "queue.Queue",
    ("queue", "PriorityQueue"): "queue.Queue",
    ("threading", "Lock"): "threading.Lock",
    ("threading", "RLock"): "threading.RLock",
    ("threading", "Condition"): "threading.Condition",
    ("threading", "Event"): "threading.Event",
    ("threading", "Thread"): "threading.Thread",
}


class ClassEntry:
    """One project class: its methods, resolved attribute types, bases."""

    __slots__ = ("relpath", "name", "node", "methods", "attr_types",
                 "base_keys")

    def __init__(self, relpath, name, node):
        self.relpath = relpath
        self.name = name
        self.node = node
        self.methods = {m.name: m for m in node.body
                        if isinstance(m, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))}
        self.attr_types = {}    # "attr" -> class key or builtin type tag
        self.base_keys = []     # resolved project base-class keys

    @property
    def key(self):
        return f"{self.relpath}:{self.name}"


class ModuleEntry:
    """One project module: classes, module functions, imports, globals."""

    __slots__ = ("relpath", "dotted", "tree", "classes", "functions",
                 "imports", "var_types")

    def __init__(self, relpath, dotted, tree):
        self.relpath = relpath
        self.dotted = dotted
        self.tree = tree
        self.classes = {}       # local name -> ClassEntry
        self.functions = {}     # local name -> FunctionDef (module level)
        self.imports = {}       # local name -> ("module", dotted) or
        #                          ("symbol", dotted_module, original_name)
        self.var_types = {}     # module-level var -> class key / type tag


def _dotted_name(relpath):
    parts = relpath[:-3].split("/")      # strip ".py"
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class ProjectIndex:
    """Whole-project symbol index + best-effort static call resolution.

    Resolution is deliberately conservative: ``self.method()``,
    ``self.attr.method()`` (attribute types inferred from constructor
    assignments), module functions, imported symbols, and module-alias
    attribute calls resolve; anything dynamic (dict lookups, callables
    passed as values, inheritance across unknown bases) resolves to
    nothing rather than to a guess.
    """

    def __init__(self, ctxs):
        self.modules = {}            # relpath -> ModuleEntry
        self.by_dotted = {}          # dotted -> ModuleEntry
        self.classes = {}            # class key -> ClassEntry
        self._class_name_index = {}  # bare name -> [class keys]
        self._owner = {}             # id(funcnode) -> (ModuleEntry, ClassEntry|None)
        for ctx in ctxs:
            self._add_module(ctx)
        for mod in self.modules.values():
            self._resolve_imports(mod)
        for mod in self.modules.values():
            for cls in mod.classes.values():
                self._infer_class(mod, cls)
            self._infer_module_vars(mod)

    # -- construction ------------------------------------------------------
    def _add_module(self, ctx):
        mod = ModuleEntry(ctx.relpath, _dotted_name(ctx.relpath), ctx.tree)
        self.modules[ctx.relpath] = mod
        self.by_dotted[mod.dotted] = mod
        for node in ctx.tree.body:
            if isinstance(node, ast.ClassDef):
                entry = ClassEntry(ctx.relpath, node.name, node)
                mod.classes[node.name] = entry
                self.classes[entry.key] = entry
                self._class_name_index.setdefault(node.name, []).append(
                    entry.key)
                for m in entry.methods.values():
                    self._owner[id(m)] = (mod, entry)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mod.functions[node.name] = node
                self._owner[id(node)] = (mod, None)

    def _resolve_imports(self, mod):
        pkg_parts = mod.dotted.split(".")
        if not mod.relpath.endswith("/__init__.py") and \
                mod.relpath != "__init__.py":
            pkg_parts = pkg_parts[:-1]
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    target = alias.name if alias.asname else \
                        alias.name.split(".")[0]
                    mod.imports[local] = ("module", target)
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = pkg_parts[:len(pkg_parts) - (node.level - 1)]
                    src = ".".join(base + ((node.module or "").split(".")
                                           if node.module else []))
                else:
                    src = node.module or ""
                for alias in node.names:
                    local = alias.asname or alias.name
                    if f"{src}.{alias.name}" in self.by_dotted:
                        mod.imports[local] = ("module",
                                              f"{src}.{alias.name}")
                    else:
                        mod.imports[local] = ("symbol", src, alias.name)

    def _type_of_ctor(self, mod, func):
        """The type key constructed by calling ``func`` (a Call's .func),
        or None when it is not a recognizable constructor."""
        if isinstance(func, ast.Name):
            if func.id in mod.classes:
                return mod.classes[func.id].key
            imp = mod.imports.get(func.id)
            if imp and imp[0] == "symbol":
                target = self.by_dotted.get(imp[1])
                if target and imp[2] in target.classes:
                    return target.classes[imp[2]].key
                if (imp[1], imp[2]) in _BUILTIN_TYPES:
                    return _BUILTIN_TYPES[(imp[1], imp[2])]
        elif isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name):
            owner = func.value.id
            imp = mod.imports.get(owner)
            dotted = imp[1] if imp and imp[0] == "module" else owner
            target = self.by_dotted.get(dotted)
            if target and func.attr in target.classes:
                return target.classes[func.attr].key
            if (dotted, func.attr) in _BUILTIN_TYPES:
                return _BUILTIN_TYPES[(dotted, func.attr)]
        return None

    def _infer_class(self, mod, cls):
        for base in cls.node.bases:
            key = None
            if isinstance(base, ast.Name):
                if base.id in mod.classes:
                    key = mod.classes[base.id].key
                else:
                    imp = mod.imports.get(base.id)
                    if imp and imp[0] == "symbol":
                        target = self.by_dotted.get(imp[1])
                        if target and imp[2] in target.classes:
                            key = target.classes[imp[2]].key
            elif isinstance(base, ast.Attribute):
                key = self._type_of_ctor(
                    mod, base) if False else None  # attribute bases: rare
            if key:
                cls.base_keys.append(key)
        for m in cls.methods.values():
            for node in ast.walk(m):
                if not (isinstance(node, ast.Assign) and len(node.targets)
                        == 1):
                    continue
                t = node.targets[0]
                if not (isinstance(t, ast.Attribute) and
                        isinstance(t.value, ast.Name) and
                        t.value.id == "self"):
                    continue
                if isinstance(node.value, ast.Call):
                    key = self._type_of_ctor(mod, node.value.func)
                    if key and t.attr not in cls.attr_types:
                        cls.attr_types[t.attr] = key

    def _infer_module_vars(self, mod):
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    isinstance(node.value, ast.Call):
                key = self._type_of_ctor(mod, node.value.func)
                if key:
                    mod.var_types[node.targets[0].id] = key

    # -- lookup ------------------------------------------------------------
    def owner_of(self, funcnode):
        """(ModuleEntry, ClassEntry-or-None) that defines ``funcnode``."""
        return self._owner.get(id(funcnode), (None, None))

    def class_by_key(self, key):
        return self.classes.get(key)

    def method_of(self, class_key, name, _seen=None):
        """Resolve ``name`` on ``class_key``, walking project bases."""
        _seen = _seen or set()
        if class_key in _seen:
            return None, None
        _seen.add(class_key)
        cls = self.classes.get(class_key)
        if cls is None:
            return None, None
        if name in cls.methods:
            return cls, cls.methods[name]
        for base in cls.base_keys:
            owner, fn = self.method_of(base, name, _seen)
            if fn is not None:
                return owner, fn
        return None, None

    def attr_type(self, class_key, attr, _seen=None):
        """Type key of ``self.<attr>`` on ``class_key`` (bases walked)."""
        _seen = _seen or set()
        if class_key in _seen:
            return None
        _seen.add(class_key)
        cls = self.classes.get(class_key)
        if cls is None:
            return None
        if attr in cls.attr_types:
            return cls.attr_types[attr]
        for base in cls.base_keys:
            t = self.attr_type(base, attr, _seen)
            if t is not None:
                return t
        return None

    def resolve_call(self, call, mod, cls):
        """Targets of ``call`` made from (``mod``, ``cls`` or None):
        a list of (ModuleEntry, ClassEntry-or-None, FunctionDef).
        Empty when the target is dynamic or outside the project."""
        func = call.func
        out = []
        if isinstance(func, ast.Name):
            if func.id in mod.functions:
                out.append((mod, None, mod.functions[func.id]))
            elif func.id in mod.classes:
                e = mod.classes[func.id]
                owner, init = self.method_of(e.key, "__init__")
                if init is not None:
                    out.append((self.modules[owner.relpath], owner, init))
            else:
                imp = mod.imports.get(func.id)
                if imp and imp[0] == "symbol":
                    target = self.by_dotted.get(imp[1])
                    if target:
                        if imp[2] in target.functions:
                            out.append((target, None,
                                        target.functions[imp[2]]))
                        elif imp[2] in target.classes:
                            e = target.classes[imp[2]]
                            owner, init = self.method_of(e.key, "__init__")
                            if init is not None:
                                out.append((self.modules[owner.relpath],
                                            owner, init))
        elif isinstance(func, ast.Attribute):
            recv = func.value
            if isinstance(recv, ast.Name) and recv.id == "self" and cls:
                owner, fn = self.method_of(cls.key, func.attr)
                if fn is not None:
                    out.append((self.modules[owner.relpath], owner, fn))
            elif isinstance(recv, ast.Name):
                imp = mod.imports.get(recv.id)
                if imp and imp[0] == "module":
                    target = self.by_dotted.get(imp[1])
                    if target:
                        if func.attr in target.functions:
                            out.append((target, None,
                                        target.functions[func.attr]))
                        elif func.attr in target.classes:
                            e = target.classes[func.attr]
                            owner, init = self.method_of(e.key, "__init__")
                            if init is not None:
                                out.append((self.modules[owner.relpath],
                                            owner, init))
                else:
                    tkey = mod.var_types.get(recv.id)
                    if tkey:
                        owner, fn = self.method_of(tkey, func.attr)
                        if fn is not None:
                            out.append((self.modules[owner.relpath],
                                        owner, fn))
            elif isinstance(recv, ast.Attribute) and \
                    isinstance(recv.value, ast.Name) and \
                    recv.value.id == "self" and cls:
                tkey = self.attr_type(cls.key, recv.attr)
                if tkey:
                    owner, fn = self.method_of(tkey, func.attr)
                    if fn is not None:
                        out.append((self.modules[owner.relpath], owner, fn))
        return out

    def receiver_type(self, expr, mod, cls):
        """Best-effort type key of an expression used as a receiver:
        ``self.attr`` / module-level var / bare name."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and cls:
            return self.attr_type(cls.key, expr.attr)
        if isinstance(expr, ast.Name):
            return mod.var_types.get(expr.id)
        return None
