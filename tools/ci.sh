#!/usr/bin/env bash
# CI entry (reference: ci/build.py + runtime_functions.sh stages).
# Stages: lint | lockscan | import | hloscan | census | autotune | smoke
# | test | chaos | storm | endure | blackbox | perf | dryrun | all
# (default: all).
set -euo pipefail
cd "$(dirname "$0")/.."
stage="${1:-all}"

export JAX_PLATFORMS=cpu
export PALLAS_AXON_POOL_IPS=
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"

run_lint() {
  # zero-unbaselined-findings gate (ISSUE 5): pure-AST, runs before
  # anything imports — trace-time env reads, lock discipline, host
  # syncs in jit, daemon-thread leaks, undocumented MXNET_* knobs
  # (docs/STATIC_ANALYSIS.md; waive with `# mxlint: disable=<rule> --
  # <reason>`, grandfather with --update-baseline)
  python -m tools.mxlint
}
run_lockscan() {
  # concurrency-contract gate (ISSUE 20): interprocedural lock-order /
  # blocking-under-lock analysis over the package — lock-order cycles,
  # blocking calls under held locks, predicate-free Condition.wait,
  # notify outside the owning lock, lock-taking signal handlers — clean
  # against the EMPTY committed baseline (docs/STATIC_ANALYSIS.md
  # "Concurrency contracts"; waive with `# lockscan: disable=<rule> --
  # <reason>`).  The runtime half (the acquisition witness) rides the
  # chaos/storm/endure stages below via MXNET_LOCKSCAN_WITNESS.
  python -m tools.lockscan --verdicts
}
run_import() {
  # hard gate (ISSUE 1): bare import + zero collection errors, so an
  # import-time crash can never land again
  python -c "import mxnet_tpu; print('ci: import ok')"
  out=$(python -m pytest tests/ -q --collect-only -p no:cacheprovider \
        2>&1 | tail -3)
  if echo "$out" | grep -qE "[0-9]+ errors?"; then
    echo "ci: FAIL — collection errors:" >&2
    echo "$out" >&2
    exit 1
  fi
  echo "ci: collect-only 0 errors"
}
run_hloscan() {
  # compiled-program contract gate (ISSUE 7): captures the real entry
  # points (train step on the virtual mesh, bucketed allreduce, flash
  # attention, serve endpoint) and checks their jaxprs + HLO against the
  # declared contracts — collective overlap, host round-trips, dtype
  # cliffs, resharding, launch counts (docs/STATIC_ANALYSIS.md; waive in
  # the artifact's contract, grandfather with --update-baseline)
  python -m tools.hloscan --verdicts
}
run_census() {
  # per-layer speed-of-light census gate (ISSUE 8): attributes each
  # captured entry point's compiled FLOPs/bytes to Gluon layers and
  # fences them with MFU-floor contracts — cost-model-only on the CPU
  # mesh (docs/OBSERVABILITY.md "Layer census"; waive on the contract
  # with a reason, grandfather with --update-baseline)
  python -m tools.layerscope --verdicts
}
run_autotune() {
  # kernel-parameter cache gate (ISSUE 18): the committed
  # tools/autotune_cache.json must parse, fingerprint the current
  # toolchain, cover every registered (kernel, signature), carry no
  # stale entries, and re-derive every model-mode winner bit-for-bit
  # (docs/AUTOTUNE.md; no baseline — findings are hard FAILs, fix by
  # re-sweeping with --update-cache; opt out with MXTPU_AUTOTUNE_GATE=0)
  if [ "${MXTPU_AUTOTUNE_GATE:-1}" != "0" ]; then
    python -m tools.autotune --verdicts
  fi
}
run_smoke()  { bash tools/smoke.sh; }
run_test()   {
  # masked/dropout flash parity first (ISSUE 3): the kernel tier BERT
  # training rides must fail fast and loud before anything else runs
  python -m pytest tests/test_flash_attention.py -q
  # the three static-analysis gates' own suites next (ISSUEs 5+7+20): a
  # broken checker is worse than no checker
  python -m pytest tests/test_mxlint.py tests/test_hloscan.py \
    tests/test_lockscan.py -q
  # telemetry next: the observability layer every later perf PR reads
  # its numbers from fails fast and loud (ISSUE 2)
  python -m pytest tests/test_telemetry.py -q
  # bucketed collectives (ISSUE 4): the allreduce path every multi-device
  # trainer step rides — bit-parity vs per-key must fail fast
  python -m pytest tests/test_kvstore_bucketing.py -q
  # input pipeline (ISSUE 10): sharded readers, device augment, and the
  # sharded global-array feed — the path every real-data bench rides
  python -m pytest tests/test_image_record.py tests/test_input_pipeline.py -q
  python -m pytest tests/ -q -x
}
run_chaos()  {
  # runtime lock-acquisition witness (ISSUE 20): every process in this
  # gate (and storm/endure below) runs with the lockwitness factory shim
  # installed — an out-of-order acquisition aborts that process with
  # exit 70 and fails the stage; the env-plan run additionally dumps its
  # observed acquisition graph and crosschecks it against the static
  # model (MXNET_LOCKSCAN_WITNESS=0 opts out)
  export MXNET_LOCKSCAN_WITNESS="${MXNET_LOCKSCAN_WITNESS:-1}"
  # chaos gate (ISSUE 9): deterministic fault injection + recovery — the
  # resume-parity fence, the retry/step-guard policies, and the atomic
  # checkpoint round-trip must all survive without process death
  # (docs/RESILIENCE.md)
  python -m pytest tests/test_resilience.py -q
  # whole-process path: a fault plan injected via MXNET_FAULTLINE (not
  # plan()) must fire in a fresh interpreter and be retried away, visible
  # in mxtpu_faults_recovered_total
  MXNET_FAULTLINE='[{"site": "kvstore.pushpull", "kind": "timeout", "at": 1}]' \
  MXNET_LOCKSCAN_REPORT="/tmp/lockscan-chaos-$$.json" \
  python - <<'EOF'
import numpy as onp
import mxnet_tpu as mx
from mxnet_tpu import kvstore, telemetry

kv = kvstore.create("tpu_ici")
vals = [mx.np.array(onp.array([1.0, 2.0], onp.float32), ctx=mx.cpu(c))
        for c in range(2)]
kv.pushpull("k", vals)
assert vals[0].asnumpy().tolist() == [2.0, 4.0]
rec = telemetry.default_registry().get_sample_value(
    "mxtpu_faults_recovered_total",
    {"site": "kvstore.pushpull", "kind": "timeout"})
assert rec == 1, rec
print("ci: env-plan KV timeout injected and recovered")
EOF
  # the witness run above dumped its observed acquisition graph — the
  # merged static+observed order must be acyclic and every observed edge
  # explained by the static model (ISSUE 20)
  if [ "${MXNET_LOCKSCAN_WITNESS}" != "0" ] && \
     [ -f "/tmp/lockscan-chaos-$$.json" ]; then
    python -m tools.lockscan --no-metrics \
      --crosscheck "/tmp/lockscan-chaos-$$.json"
    rm -f "/tmp/lockscan-chaos-$$.json"
  fi
  # quantized preempt/resume parity (ISSUE 11): the resume-parity fence
  # again, but through the block-scaled int8 bucketed path — its
  # error-feedback residuals ride the SAME kvres/bucketres checkpoint
  # schema as 2bit, so a preempted quantized run must resume with a
  # bitwise-identical trajectory (docs/RESILIENCE.md recovery matrix;
  # opt out with MXTPU_CHAOS_QUANTIZED=0)
  if [ "${MXTPU_CHAOS_QUANTIZED:-1}" != "0" ]; then
  python - <<'EOF'
import tempfile

import numpy as onp
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.utils import split_and_load
from mxnet_tpu.resilience import (CheckpointManager, faultline,
                                  gather_training_state,
                                  restore_training_state)

CTXS = [mx.cpu(i) for i in range(2)]
COMP = {"type": "int8", "block": 64}

def build(seed):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=6, activation="relu"))
    net.add(nn.Dense(4, in_units=8))
    net.initialize(ctx=CTXS)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9},
                       kvstore="tpu_ici", compression_params=COMP)
    return net, tr

def batch(t):
    rs = onp.random.RandomState(300 + t)
    return mx.np.array(rs.randn(4, 6).astype(onp.float32))

def step(net, tr, t):
    xs = split_and_load(batch(t), CTXS)
    with autograd.record():
        ls = [(net(xb) ** 2).mean() for xb in xs]
    autograd.backward(ls)
    tr.step(4)

def params_np(net):
    return {k: onp.asarray(p.data()._data)
            for k, p in net.collect_params().items()}

# fault-free reference trajectory
net_a, tr_a = build(seed=11)
for t in range(3):
    step(net_a, tr_a, t)
ref = params_np(net_a)

# chaos run: checkpoint after step 2, preempted during step 3's bucket
# dispatch (the quantized collective itself)
net_b, tr_b = build(seed=11)
for t in range(2):
    step(net_b, tr_b, t)
mgr = CheckpointManager(tempfile.mkdtemp(), async_write=False, rank=0)
arrays, meta = gather_training_state(tr_b, step=2)
assert any(k.startswith("bucketres/") for k in arrays), \
    "int8 bucketer residuals must ride the checkpoint"
mgr.save(2, arrays, meta)
faultline.plan([{"site": "collective.dispatch", "kind": "preempt", "at": 1}])
try:
    step(net_b, tr_b, 2)
    raise SystemExit("ci: FAIL — preemption did not fire")
except faultline.InjectedPreemption:
    pass
faultline.clear()

# 'restarted process': wrong init seed proves restore wins; restore
# runs BEFORE the first step, like a real restart (it materializes the
# kvstore/bucketer itself so the residuals have somewhere to land)
net_c, tr_c = build(seed=77)
s, arrays_r, meta_r = mgr.restore_latest()
assert s == 2 and restore_training_state(arrays_r, meta_r, tr_c) == 2
step(net_c, tr_c, 2)
got = params_np(net_c)
for k in ref:
    assert got[k].tobytes() == ref[k].tobytes(), k
mgr.close()
print("ci: quantized int8 preempt/resume parity bitwise")
EOF
  fi
}
run_storm() {
  # fleet chaos load-storm gate (ISSUE 12): mixed-shape, mixed-priority
  # traffic through a 3-replica fleet WHILE a faultline plan kills one
  # replica mid-storm — zero dropped (non-shed) requests, per-class p99
  # inside the declared SLA, and the failover visible in
  # mxtpu_faults_recovered_total + mxtpu_fleet_failover_seconds
  # (docs/SERVING.md "Fleet"; opt out with MXTPU_CHAOS_STORM=0)
  if [ "${MXTPU_CHAOS_STORM:-1}" != "0" ]; then
    MXNET_LOCKSCAN_WITNESS="${MXNET_LOCKSCAN_WITNESS:-1}" \
      python -m tools.storm --gate
  fi
}
run_endure() {
  # elastic endurance gate (ISSUE 13): one emulated 3-host pod driven
  # through two preemptions (same topology -> bitwise trajectory parity
  # vs the fault-free oracle) and one PERMANENT host kill (dead_node
  # fault -> re-shard onto the 2 survivors, linear lr rule, per-host
  # throughput back to >=95% of pre-fault within the recovery window),
  # visible in mxtpu_elastic_reshards_total and
  # mxtpu_faults_recovered_total{kvstore.kv,dead_node}
  # (docs/RESILIENCE.md "Elastic recovery"; opt out with
  # MXTPU_CHAOS_ENDURE=0)
  if [ "${MXTPU_CHAOS_ENDURE:-1}" != "0" ]; then
    MXNET_LOCKSCAN_WITNESS="${MXNET_LOCKSCAN_WITNESS:-1}" \
      python -m tools.endure --gate
  fi
}
run_blackbox() {
  # flight-recorder postmortem gate (ISSUE 17): the endure permanent-kill
  # phase with recording on must leave crash dumps the analyzer
  # root-causes to kvstore.kv/dead_node rank=1, and a 20-step fault-free
  # run must yield verdict NONE with recorder overhead <1% of step time
  # (docs/OBSERVABILITY.md "Black box / postmortem"; opt out with
  # MXTPU_CHAOS_BLACKBOX=0)
  if [ "${MXTPU_CHAOS_BLACKBOX:-1}" != "0" ]; then
    python -m tools.blackbox --gate
  fi
}
run_perf()   { python benchmark/opperf/opperf.py --smoke; }
run_dryrun() {
  # pytest already runs the 4-process launcher test; skip it inside the
  # in-process dryrun to keep ci wall-clock bounded
  export MXTPU_DRYRUN_MULTIPROC=0
  # the sharding-recipe rider (ISSUE 16) rides the 8-device pass: a
  # dp2.tp2.pp2 fused step, the tp2 hloscan contract, and the giant-model
  # placement proof all print recipe_verdict: lines (MXTPU_DRYRUN_RECIPE=0
  # opts out)
  for n in 8 6 3 2; do
    python -c "import __graft_entry__ as g; g.dryrun_multichip($n); print('dryrun($n) ok')"
  done
}

case "$stage" in
  lint)    run_lint ;;
  lockscan) run_lockscan ;;
  import)  run_import ;;
  hloscan) run_hloscan ;;
  census)  run_census ;;
  autotune) run_autotune ;;
  smoke)   run_smoke ;;
  test)    run_test ;;
  chaos)   run_chaos ;;
  storm)   run_storm ;;
  endure)  run_endure ;;
  blackbox) run_blackbox ;;
  perf)    run_perf ;;
  dryrun)  run_dryrun ;;
  all)     run_lint; run_lockscan; run_import; run_hloscan; run_census
           run_autotune
           run_smoke; run_test; run_chaos; run_storm; run_endure
           run_blackbox; run_perf; run_dryrun ;;
  *) echo "unknown stage $stage" >&2; exit 2 ;;
esac
