#!/usr/bin/env bash
# CI entry (reference: ci/build.py + runtime_functions.sh stages).
# Stages: smoke | test | perf | dryrun | all (default).
set -euo pipefail
cd "$(dirname "$0")/.."
stage="${1:-all}"

export JAX_PLATFORMS=cpu
export PALLAS_AXON_POOL_IPS=
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"

run_smoke()  { bash tools/smoke.sh; }
run_test()   { python -m pytest tests/ -q -x; }
run_perf()   { python benchmark/opperf/opperf.py --smoke; }
run_dryrun() {
  # pytest already runs the 4-process launcher test; skip it inside the
  # in-process dryrun to keep ci wall-clock bounded
  export MXTPU_DRYRUN_MULTIPROC=0
  for n in 8 6 3 2; do
    python -c "import __graft_entry__ as g; g.dryrun_multichip($n); print('dryrun($n) ok')"
  done
}

case "$stage" in
  smoke)  run_smoke ;;
  test)   run_test ;;
  perf)   run_perf ;;
  dryrun) run_dryrun ;;
  all)    run_smoke; run_test; run_perf; run_dryrun ;;
  *) echo "unknown stage $stage" >&2; exit 2 ;;
esac
