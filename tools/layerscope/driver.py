"""layerscope driver: capture, census, fence, report.

Exit status mirrors hloscan/mxlint: 0 when every finding is waived or
baselined AND the baseline is not stale, 1 when a live finding remains
or the baseline names findings that no longer exist, 2 on usage error.
The checked-in baseline (``tools/layerscope_baseline.json``) is EMPTY:
the known offenders (ResNet stem, BN-backward — VERDICT items 3/6) are
waived on the contract with reasons, so the census *documents* them;
the baseline exists for genuinely new debt, and stale entries FAIL.
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_BASELINE = os.path.join(REPO_ROOT, "tools",
                                "layerscope_baseline.json")
RESULTS_DIR = os.path.join(REPO_ROOT, "benchmark", "results")

JSON_SCHEMA_VERSION = 1

#: Every rule the census contract can emit, for the verdict lines.
RULES = ("attribution-coverage", "mfu-floor", "stale-floor",
         "stale-waiver", "bad-waiver")


def finding_id(entry, f):
    """Stable ID for a census finding (sha1-12 of tool|rule|entry|key,
    same recipe as hloscan/mxlint)."""
    blob = f"layerscope|{f['rule']}|{entry}|{f['key']}"
    return hashlib.sha1(blob.encode()).hexdigest()[:12]


def census_docs(names=None, device=None):
    """Run the census over ``names`` (default: every census entry
    point).  Imports jax and compiles — the heavy step."""
    from mxnet_tpu.analysis import census
    kw = {} if device is None else {"device": device}
    names = census.census_entrypoint_names() if not names else list(names)
    return [census.census_one(n, **kw) for n in names]


# --------------------------------------------------------------------------
# reporting
# --------------------------------------------------------------------------
def _fmt_flops(v):
    for unit, div in (("GF", 1e9), ("MF", 1e6), ("kF", 1e3)):
        if v >= div:
            return f"{v / div:.1f}{unit}"
    return f"{v:.0f}F"


def render_table(doc, out=None):
    """The per-layer census table.  Cost-model mode shows modeled %
    step time and speed-of-light MFU; measured mode adds achieved
    TF/s / GB/s / MFU."""
    lines = []
    measured = doc["mode"] == "measured"
    head = (f"layerscope: {doc['entry']} [{doc['device']}, {doc['mode']}] "
            f"— {doc['attributed_flops_fraction']:.1%} of "
            f"{_fmt_flops(doc['totals']['flops'])} attributed")
    lines.append(head)
    cols = f"{'layer':<34} {'ph':<3} {'%time':>6} {'flops':>8} " \
           f"{'intens':>7} {'SOL-MFU':>8}"
    if measured:
        cols += f" {'TF/s':>7} {'GB/s':>7} {'MFU':>7}"
    cols += "  bound"
    lines.append(cols)
    waived_by_key = {f["key"]: f for f in doc["findings"]
                     if f["waived"]}
    for row in doc["rows"]:
        key = f"{row['layer']}@{row['phase']}"
        mark = " [waived]" if key in waived_by_key else ""
        line = (f"{row['layer'][:34]:<34} {row['phase']:<3} "
                f"{row['pct_time']:>5.1f}% "
                f"{_fmt_flops(row['flops']):>8} "
                f"{'-' if row['intensity'] is None else format(row['intensity'], '.1f'):>7} "
                f"{row['mfu_sol']:>7.1%}")
        if measured:
            tf = row["tf_per_s"]
            line += (f" {'-' if tf is None else format(tf, '.2f'):>7}"
                     f" {'-' if row['gb_per_s'] is None else format(row['gb_per_s'], '.1f'):>7}"
                     f" {'-' if row['mfu'] is None else format(row['mfu'], '.1%'):>7}")
        line += f"  {row['bound']}{mark}"
        lines.append(line)
    text = "\n".join(lines) + "\n"
    if out is not None:
        out.write(text)
    return text


def top_sag(doc, n=5):
    """Top-``n`` layers by % of step time with their bound class — the
    bench rider's ``layer_census_top_sag`` summary."""
    rows = [r for r in doc["rows"]][:n]
    return [f"{r['layer']}@{r['phase']} {r['pct_time']:.1f}% {r['bound']}"
            for r in rows]


def verdict_lines(docs, baselined_ids=()):
    """Per-rule ``layerscope <rule> PASS|FAIL`` lines (beside hloscan's
    in the dryrun rider)."""
    live = {}
    for doc in docs:
        for f in doc["findings"]:
            if f["waived"]:
                continue
            if finding_id(doc["entry"], f) in baselined_ids:
                continue
            live[f["rule"]] = live.get(f["rule"], 0) + 1
    lines = []
    for rule in RULES:
        n = live.get(rule, 0)
        verdict = "PASS" if not n else f"FAIL ({n})"
        lines.append(f"layerscope {rule:22s} {verdict}  "
                     f"[{len(docs)} entries]")
    return lines


# --------------------------------------------------------------------------
# baseline (hloscan policy: empty by default, stale entries FAIL)
# --------------------------------------------------------------------------
def load_baseline(path):
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return data.get("findings", {})


def write_baseline(path, docs):
    entries = {}
    for doc in docs:
        for f in doc["findings"]:
            if f["waived"]:
                continue
            entries[finding_id(doc["entry"], f)] = {
                "rule": f["rule"], "entry": doc["entry"], "key": f["key"],
                "message": f["message"]}
    payload = {
        "comment": "layerscope grandfathered findings — entries are debts, "
                   "not permissions; known offenders belong on the contract "
                   "as reasoned waivers instead. Stale entries FAIL the "
                   "census. Regenerate with `python -m tools.layerscope "
                   "--update-baseline`.",
        "version": JSON_SCHEMA_VERSION,
        "findings": dict(sorted(entries.items())),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=False)
        f.write("\n")
    return entries


# --------------------------------------------------------------------------
# pipeline
# --------------------------------------------------------------------------
def artifact_path(entry):
    return os.path.join(RESULTS_DIR, f"layer_census_{entry}.json")


def write_artifact(doc, path=None):
    from mxnet_tpu.analysis import census
    path = path or artifact_path(doc["entry"])
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        f.write(census.dumps(doc))
        f.write("\n")
    return path


def run(names=None, device=None, baseline_path=None,
        update_baseline=False, fmt="text", verdicts=False, metrics=True,
        artifacts=True, docs=None, out=sys.stdout):
    """Full pipeline; returns the process exit code."""
    if docs is None:
        docs = census_docs(names, device=device)
    docs = list(docs)
    baseline = load_baseline(baseline_path) if baseline_path else {}
    if update_baseline:
        if not baseline_path:
            out.write("layerscope: --update-baseline needs --baseline "
                      "PATH\n")
            return 2
        entries = write_baseline(baseline_path, docs)
        out.write(f"layerscope: baseline written — {len(entries)} entr"
                  f"{'y' if len(entries) == 1 else 'ies'} -> "
                  f"{baseline_path}\n")
        return 0

    present, live = set(), []
    for doc in docs:
        for f in doc["findings"]:
            if f["waived"]:
                continue
            fid = finding_id(doc["entry"], f)
            present.add(fid)
            if fid not in baseline:
                live.append((doc["entry"], fid, f))
    stale_ids = set(baseline) - present

    written = []
    if artifacts:
        written = [write_artifact(doc) for doc in docs]
    if metrics:
        try:
            from mxnet_tpu.analysis import census
            for doc in docs:
                census.publish_metrics(doc)
        except Exception:  # mxlint: disable=swallowed-exception -- metrics mirroring is best-effort; the report itself still prints below
            pass

    if fmt == "json":
        payload = {
            "version": JSON_SCHEMA_VERSION,
            "tool": "layerscope",
            "entries": [{"entry": d["entry"], "mode": d["mode"],
                         "attributed_flops_fraction":
                             d["attributed_flops_fraction"],
                         "top_sag": top_sag(d),
                         "findings": d["findings"]} for d in docs],
            "artifacts": written,
            "stale_baseline_ids": sorted(stale_ids),
            "summary": {
                "entries": len(docs),
                "live": len(live),
                "waived": sum(1 for d in docs for f in d["findings"]
                              if f["waived"]),
                "stale_baseline": len(stale_ids),
            },
        }
        json.dump(payload, out, indent=2)
        out.write("\n")
    else:
        for doc in docs:
            render_table(doc, out=out)
            out.write("layer_census_top_sag: " +
                      "; ".join(top_sag(doc)) + "\n")
            for f in doc["findings"]:
                if f["waived"]:
                    out.write(f"  waived [{f['rule']}] {f['key']}: "
                              f"{f['reason']}\n")
        for entry, fid, f in live:
            out.write(f"{entry}: [{f['rule']}] {f['message']}  "
                      f"(id {fid})\n")
        if stale_ids:
            out.write(f"layerscope: FAIL — {len(stale_ids)} stale "
                      f"baseline entr"
                      f"{'y' if len(stale_ids) == 1 else 'ies'}; prune "
                      f"with --update-baseline: "
                      f"{', '.join(sorted(stale_ids))}\n")
        verdict = "clean" if not live else \
            f"{len(live)} live finding{'s' if len(live) != 1 else ''}"
        out.write(f"layerscope: {verdict} — {len(docs)} entries"
                  + (f", artifacts: {', '.join(written)}" if written
                     else "") + "\n")
    if verdicts:
        for line in verdict_lines(docs, baselined_ids=set(baseline)):
            out.write(line + "\n")
    return 1 if (live or stale_ids) else 0


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m tools.layerscope",
        description="Per-layer speed-of-light census with roofline "
                    "attribution (docs/OBSERVABILITY.md, 'Layer "
                    "census').")
    p.add_argument("--entry", action="append", dest="entries",
                   metavar="NAME",
                   help="census entry point (repeatable; default: all — "
                        "see --list-entries)")
    p.add_argument("--device", default=None,
                   help="roofline peaks to classify against "
                        "(default: tpu-v5e)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="baseline JSON of grandfathered finding IDs "
                        "(default: tools/layerscope_baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline (report everything)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from current findings")
    p.add_argument("--verdicts", action="store_true",
                   help="append per-rule PASS/FAIL verdict lines")
    p.add_argument("--no-metrics", action="store_true",
                   help="skip publishing mxtpu_layer_mfu gauges")
    p.add_argument("--no-artifact", action="store_true",
                   help="skip writing benchmark/results/"
                        "layer_census_<entry>.json")
    p.add_argument("--list-entries", action="store_true")
    args = p.parse_args(argv)

    if args.list_entries:
        from mxnet_tpu.analysis import census_entrypoint_names
        for name in census_entrypoint_names():
            print(name)
        return 0

    return run(names=args.entries or None, device=args.device,
               baseline_path=None if args.no_baseline else args.baseline,
               update_baseline=args.update_baseline,
               fmt=args.format, verdicts=args.verdicts,
               metrics=not args.no_metrics,
               artifacts=not args.no_artifact)


if __name__ == "__main__":
    sys.exit(main())
