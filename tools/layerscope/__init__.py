"""layerscope: per-layer speed-of-light census with roofline attribution.

hloscan (PR 7) gates structural claims in the compiled artifact; this
tool gates the *performance shape*: where each compiled step spends its
FLOPs and bytes, layer by layer, against the chip roofline.  The heavy
lifting — name-scope bucketing, the per-instruction cost model, bound
classification, MFU-floor contracts — lives in
``mxnet_tpu/analysis/census.py``; this package is the driver: entry
capture, the text table, the JSON artifact
(``benchmark/results/layer_census_<entry>.json``), the telemetry
gauges, and the baseline gate CI runs (``tools/layerscope_baseline.json``,
checked in EMPTY — all known offenders are waived on the contract with
reasons, same policy as hloscan).

On the virtual CPU mesh the census is cost-model-only (bound classes
and speed-of-light MFU from modeled FLOPs/bytes against the target
chip's peaks); on hardware, ``census.attach_timings`` joins measured
profiler-region seconds for achieved TF/s / GB/s / MFU.

Usage::

    python -m tools.layerscope                          # all entries
    python -m tools.layerscope --entry fused_train_step_dp
    python -m tools.layerscope --entry resnet_profile --verdicts
"""
from .driver import main, render_table, run, top_sag, verdict_lines  # noqa: F401
