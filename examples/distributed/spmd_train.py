"""Multi-chip SPMD training through the Gluon API.

Reference shape: `example/distributed_training*` (dist kvstore / horovod
launch scripts).  The TPU path needs no launcher for a single host: pass a
mesh to `gluon.FusedTrainStep` and the one-program-per-step training loop
runs data/tensor-parallel with XLA inserting the collectives over ICI.

Run on real chips, or simulate a pod on CPU:
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/distributed/spmd_train.py --dp 4 --tp 2
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel import mesh as pmesh


class NetWithLoss(gluon.HybridBlock):
    def __init__(self, net):
        super().__init__()
        self.net = net
        self.loss = gluon.loss.SoftmaxCrossEntropyLoss()

    def forward(self, x, y):
        return self.loss(self.net(x), y)


def main():
    from jax.sharding import PartitionSpec as P

    p = argparse.ArgumentParser()
    p.add_argument("--dp", type=int, default=-1,
                   help="data-parallel ways (-1: all remaining chips)")
    p.add_argument("--tp", type=int, default=1, help="tensor-parallel ways")
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--iters", type=int, default=20)
    args = p.parse_args()

    mesh = pmesh.make_mesh({"dp": args.dp, "tp": args.tp})
    print(f"mesh: {dict(mesh.shape)}")

    net = nn.HybridSequential()
    net.add(nn.Dense(256, activation="relu"))
    net.add(nn.Dense(256, activation="relu"))
    net.add(nn.Dense(10))
    net.initialize(init=mx.init.Xavier())
    mod = NetWithLoss(net)

    onp.random.seed(0)
    X = onp.random.randn(args.batch_size, 64).astype(onp.float32)
    Y = onp.random.randint(0, 10, (args.batch_size,))
    x = mx.np.array(X)
    y = mx.np.array(Y, dtype="int32")
    mod(x, y)   # materialize shapes

    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})
    # Megatron-style: first Dense column-parallel, rest replicated
    step = gluon.FusedTrainStep(
        mod, trainer, mesh=mesh,
        partition_rules=[(r"net\.0\.weight", P("tp", None))],
        data_spec=P("dp"))

    for i in range(args.iters):
        loss = step(x, y, batch_size=args.batch_size)
        if i % 5 == 0 or i == args.iters - 1:
            print(f"iter {i:3d}  loss {float(loss.asnumpy().mean()):.4f}")

    w = net.collect_params()["0.weight"].data()._data
    print("first-layer weight sharding:", w.sharding)


if __name__ == "__main__":
    main()
