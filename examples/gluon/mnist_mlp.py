"""Classic Gluon training loop (reference `example/gluon/mnist.py` shape,
BASELINE config 1): MLP on MNIST-like data with hybridize + Trainer.

Uses the real MNIST via `gluon.data.vision.MNIST` when its files are
present locally; otherwise falls back to a synthetic stand-in so the
script runs anywhere (no network egress in this environment).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn


def get_data(batch_size):
    try:
        train = gluon.data.vision.MNIST(train=True).transform_first(
            gluon.data.vision.transforms.ToTensor())
        return gluon.data.DataLoader(train, batch_size, shuffle=True)
    except Exception:
        print("MNIST files not found; using synthetic data")
        X = onp.random.rand(2048, 1, 28, 28).astype("float32")
        y = onp.random.randint(0, 10, 2048)
        ds = gluon.data.ArrayDataset(X, y.astype("float32"))
        return gluon.data.DataLoader(ds, batch_size, shuffle=True)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--lr", type=float, default=0.1)
    args = p.parse_args()

    net = nn.HybridSequential()
    net.add(nn.Dense(128, activation="relu"),
            nn.Dense(64, activation="relu"),
            nn.Dense(10))
    net.initialize(init=mx.init.Xavier())
    net.hybridize()

    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = gluon.metric.Accuracy()

    data = get_data(args.batch_size)
    for epoch in range(args.epochs):
        metric.reset()
        for x, y in data:
            x = x.reshape(x.shape[0], -1)
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(x.shape[0])
            metric.update(y, out)
        name, acc = metric.get()
        print(f"epoch {epoch}: {name}={acc:.4f}")


if __name__ == "__main__":
    main()
