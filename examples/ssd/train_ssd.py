"""Tiny-SSD object-detection training, end to end.

Reference shape: the SSD pipeline of the reference's example zoo —
`ImageDetIter` feeding `MultiBoxPrior`/`MultiBoxTarget`/`MultiBoxDetection`
(`python/mxnet/image/detection.py:625`,
`src/operator/contrib/multibox_*.cc`).  This example packs a synthetic
shapes dataset into a .rec, streams it through the detection-aware
augmentation pipeline, and trains a two-scale SSD head until the loss
drops; inference decodes + NMS-filters boxes with `multibox_detection`.

Run (CPU mesh or one TPU chip):
    python examples/ssd/train_ssd.py --steps 60
"""
import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.recordio import IRHeader, MXIndexedRecordIO, pack_img

NUM_CLASSES = 2  # squares (0) and wide rectangles (1)


def make_dataset(path, n=64, size=64, seed=0):
    """Synthetic detection .rec: bright class-coded rectangles on a dark
    noisy background, labels in the packed det wire format
    (header_width=2, obj_width=5, normalized corners)."""
    rng = onp.random.RandomState(seed)
    rec = MXIndexedRecordIO(path + ".idx", path + ".rec", "w")
    for i in range(n):
        img = rng.randint(0, 40, (size, size, 3)).astype(onp.uint8)
        objs = []
        for _ in range(1 + int(rng.randint(0, 2))):
            cls = int(rng.randint(0, NUM_CLASSES))
            w = rng.uniform(0.25, 0.4) * (1.8 if cls == 1 else 1.0)
            h = rng.uniform(0.25, 0.4) * (0.6 if cls == 1 else 1.0)
            x1 = rng.uniform(0.02, 0.95 - w)
            y1 = rng.uniform(0.02, 0.95 - h)
            x2, y2 = x1 + w, y1 + h
            color = (255, 80, 80) if cls == 0 else (80, 255, 80)
            xs, ys = int(x1 * size), int(y1 * size)
            xe, ye = int(x2 * size), int(y2 * size)
            img[ys:ye, xs:xe] = color
            objs.append([cls, x1, y1, x2, y2])
        flat = [2.0, 5.0]
        for o in objs:
            flat.extend(o)
        rec.write_idx(i, pack_img(
            IRHeader(0, onp.asarray(flat, onp.float32), i, 0), img,
            quality=95))
    rec.close()
    return path + ".rec"


class TinySSD(gluon.HybridBlock):
    """Two-scale SSD: conv backbone -> per-scale (cls, loc) heads.

    Anchors come from `multibox_prior` on each feature map; forward
    returns (anchors (1, N, 4), cls_preds (B, N, C+1), loc_preds
    (B, N*4)) — the contract `multibox_target`/`multibox_detection`
    consume."""

    SIZES = [(0.25, 0.35), (0.45, 0.6)]
    RATIOS = [(1.0, 2.0, 0.5)] * 2

    def __init__(self, num_classes=NUM_CLASSES):
        super().__init__()
        self.num_classes = num_classes
        self.backbone = nn.HybridSequential()
        for filters in (16, 32):
            self.backbone.add(nn.Conv2D(filters, 3, padding=1),
                              nn.BatchNorm(), nn.Activation("relu"),
                              nn.MaxPool2D(2))
        self.stage2 = nn.HybridSequential()
        self.stage2.add(nn.Conv2D(64, 3, padding=1), nn.BatchNorm(),
                        nn.Activation("relu"), nn.MaxPool2D(2))
        self.cls_heads, self.loc_heads = [], []
        for k in range(2):
            a = len(self.SIZES[k]) + len(self.RATIOS[k]) - 1
            ch = nn.Conv2D(a * (num_classes + 1), 3, padding=1)
            lh = nn.Conv2D(a * 4, 3, padding=1)
            setattr(self, f"cls_head{k}", ch)
            setattr(self, f"loc_head{k}", lh)
            self.cls_heads.append(ch)
            self.loc_heads.append(lh)

    def forward(self, x):
        feats = [self.backbone(x)]
        feats.append(self.stage2(feats[0]))
        anchors, cls_preds, loc_preds = [], [], []
        for k, f in enumerate(feats):
            anchors.append(mx.nd.contrib.multibox_prior(
                f, sizes=self.SIZES[k], ratios=self.RATIOS[k]))
            c = self.cls_heads[k](f)           # (B, A*(C+1), H, W)
            l = self.loc_heads[k](f)           # (B, A*4, H, W)
            B = c.shape[0]
            cls_preds.append(
                c.transpose(0, 2, 3, 1).reshape(B, -1, self.num_classes + 1))
            loc_preds.append(l.transpose(0, 2, 3, 1).reshape(B, -1))
        anchor = mx.np.concatenate(anchors, axis=1)
        return (anchor, mx.np.concatenate(cls_preds, axis=1),
                mx.np.concatenate(loc_preds, axis=1))


def ssd_loss(cls_preds, cls_target, loc_preds, loc_target, loc_mask):
    """Softmax CE on anchor classes + smooth-L1 on masked offsets — the
    loss the reference pairs with MultiBoxTarget."""
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    l1 = gluon.loss.HuberLoss(rho=1.0)
    cls_l = ce(cls_preds.reshape(-1, cls_preds.shape[-1]),
               cls_target.reshape(-1))
    loc_l = l1(loc_preds * loc_mask, loc_target * loc_mask)
    return cls_l.mean() + loc_l.mean()


class SSDWithLoss(gluon.HybridBlock):
    """net + target assignment + loss in ONE hybridized program — a
    training step is a single XLA dispatch (docs/MIGRATION.md 'fuse the
    whole step'); multibox_target traces into the same program."""

    def __init__(self, net):
        super().__init__()
        self.net = net

    def forward(self, x, y):
        anchor, cls_preds, loc_preds = self.net(x)
        loc_t, loc_m, cls_t = mx.nd.contrib.multibox_target(anchor, y)
        return ssd_loss(cls_preds, cls_t, loc_preds, loc_t, loc_m)


def train(rec_path, steps=60, batch_size=8, lr=0.2, log=print):
    it = mx.image.ImageDetIter(
        batch_size=batch_size, data_shape=(3, 64, 64),
        path_imgrec=rec_path, shuffle=True,
        rand_mirror=True, mean=True, std=True)
    net = TinySSD()
    net.initialize(init=mx.init.Xavier())
    netloss = SSDWithLoss(net)
    netloss.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": lr, "momentum": 0.9},
                            kvstore="tpu_ici")
    losses = []
    step = 0
    while step < steps:
        it.reset()
        for batch in it:
            if step >= steps:
                break
            with autograd.record():
                loss = netloss(batch.data[0], batch.label[0])
            loss.backward()
            trainer.step(batch_size)
            losses.append(float(loss.asnumpy()))
            if step % 10 == 0:
                log(f"step {step:4d}  loss {losses[-1]:.4f}")
            step += 1
    return net, it, losses


def detect(net, it):
    """Decode one batch: returns (B, N, 6) rows of
    [cls, score, x1, y1, x2, y2], NMS-filtered."""
    it.reset()
    batch = next(iter(it))
    anchor, cls_preds, loc_preds = net(batch.data[0])
    cls_prob = mx.npx.softmax(cls_preds, axis=-1).transpose(0, 2, 1)
    return mx.nd.contrib.multibox_detection(
        cls_prob, loc_preds, anchor, nms_threshold=0.45, threshold=0.05)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--lr", type=float, default=0.2)
    p.add_argument("--data-dir", default=None)
    args = p.parse_args()
    root = args.data_dir or tempfile.mkdtemp(prefix="ssd_synth_")
    rec = make_dataset(os.path.join(root, "synth"))
    net, it, losses = train(rec, steps=args.steps,
                            batch_size=args.batch_size, lr=args.lr)
    first = sum(losses[:5]) / 5
    last = sum(losses[-5:]) / 5
    print(f"loss {first:.4f} -> {last:.4f}")
    assert last < first, "SSD training did not reduce the loss"
    out = detect(net, it)
    kept = (out.asnumpy()[:, :, 0] >= 0).sum()
    print(f"detections kept after NMS: {int(kept)}")


if __name__ == "__main__":
    main()
