"""ResNet ImageNet training (reference
`example/image-classification/train_imagenet.py` shape, BASELINE configs
2-3): model-zoo network + ImageRecord pipeline + data-parallel Trainer.

Point --rec-train at an im2rec pack (tools/im2rec.py); without one the
script trains on synthetic batches so it runs anywhere.  Multi-device
data parallelism follows the classic pattern: initialize(ctx=...) +
split_and_load + kvstore.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon.model_zoo import vision
from mxnet_tpu.gluon.utils import split_and_load


def parse():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50_v1")
    p.add_argument("--rec-train", default=None,
                   help=".rec file from tools/im2rec.py")
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "bfloat16"])
    p.add_argument("--kv-store", default="device")
    p.add_argument("--num-devices", type=int, default=1)
    return p.parse_args()


_MEAN = onp.array([123.68, 116.779, 103.939], onp.float32)
_STD = onp.array([58.393, 57.12, 57.375], onp.float32)


def batches(args, ctxs):
    if args.rec_train:
        # native C++ pipeline (src/image_pipeline.cc): GIL-free JPEG
        # decode threads -> NHWC uint8; normalize on DEVICE so XLA fuses
        # it into the first conv (host normalization would halve
        # throughput).  Falls back to the PIL ImageIter if libjpeg is
        # unavailable.
        try:
            it = mx.io.ImageRecordIter(
                path_imgrec=args.rec_train, batch_size=args.batch_size,
                data_shape=(3, 224, 224), resize=256, rand_crop=True,
                rand_mirror=True, shuffle=True, layout="NHWC")
        except (RuntimeError, IOError):
            it = mx.image.ImageIter(
                args.batch_size, (3, 224, 224), path_imgrec=args.rec_train,
                shuffle=True,
                aug_list=mx.image.CreateAugmenter((3, 224, 224), resize=256,
                                                  rand_crop=True,
                                                  rand_mirror=True,
                                                  mean=True, std=True))
            while True:
                it.reset()
                for b in it:
                    yield b.data[0].astype(args.dtype), b.label[0]
        # prefetch-to-device double buffering (io/prefetch.py): the H2D
        # transfer for batch N+1 rides the wire while step N computes —
        # the step-time law becomes max(feed, compute), not the sum
        pf = mx.io.DevicePrefetcher(it, depth=3, dtypes=(None, onp.int32))
        mean = mx.np.array(_MEAN)
        std = mx.np.array(_STD)
        while True:
            for data, labels in pf:
                x = ((data.astype("float32") - mean) / std) \
                    .astype(args.dtype)
                # NHWC -> NCHW for the reference-layout model zoo
                yield mx.np.transpose(x, (0, 3, 1, 2)), labels
            pf.reset()
    else:
        x = mx.np.array(onp.random.uniform(-1, 1,
                                           (args.batch_size, 3, 224, 224)),
                        dtype=args.dtype)
        y = mx.np.array(onp.random.randint(0, 1000, (args.batch_size,)),
                        dtype="int32")
        while True:
            yield x, y


def main():
    args = parse()
    ctxs = [mx.cpu(i) for i in range(args.num_devices)] \
        if args.num_devices > 1 else [mx.current_context()]
    net = getattr(vision, args.model)()
    net.initialize(init=mx.init.Xavier(), ctx=ctxs)
    if args.dtype == "bfloat16":
        net.cast("bfloat16")
    net.hybridize(static_alloc=True)

    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9},
                            kvstore=args.kv_store)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    speed = mx.callback.Speedometer(args.batch_size, frequent=10)
    from collections import namedtuple
    P = namedtuple("P", ["epoch", "nbatch", "eval_metric"])

    gen = batches(args, ctxs)
    for i in range(args.iters):
        x, y = next(gen)
        xs = split_and_load(x, ctxs)
        ys = split_and_load(y, ctxs)
        with autograd.record():
            losses = [loss_fn(net(xb), yb).mean() for xb, yb in zip(xs, ys)]
        autograd.backward(losses)
        trainer.step(args.batch_size)
        speed(P(0, i + 1, None))
    print("final loss:",
          sum(float(l.asnumpy()) for l in losses) / len(losses))


if __name__ == "__main__":
    main()
