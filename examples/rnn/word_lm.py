"""Word-level LSTM language model (reference `example/rnn/word_lm`,
BASELINE config 5): bucketed corpus -> RNNModel -> perplexity.

Reads a plain-text corpus with --data; otherwise trains on a synthetic
token stream so the script runs anywhere.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import math

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.contrib import text
from mxnet_tpu.io import BucketSentenceIter
from mxnet_tpu.models import RNNModel


def load_corpus(path, vocab_size):
    if path:
        with open(path) as f:
            raw = f.read()
        counter = text.utils.count_tokens_from_str(raw, to_lower=True)
        vocab = text.Vocabulary(counter, most_freq_count=vocab_size - 1)
        sentences = [
            [vocab.to_indices(t) for t in line.lower().split()]
            for line in raw.splitlines() if line.strip()
        ]
        return sentences, len(vocab)
    onp.random.seed(0)
    sentences = [list(onp.random.randint(1, vocab_size,
                                         onp.random.randint(5, 30)))
                 for _ in range(500)]
    return sentences, vocab_size


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--data", default=None, help="plain-text corpus")
    p.add_argument("--vocab-size", type=int, default=200)
    p.add_argument("--num-hidden", type=int, default=128)
    p.add_argument("--num-embed", type=int, default=128)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--lr", type=float, default=1.0)
    p.add_argument("--tied", action="store_true")
    args = p.parse_args()

    sentences, vocab_size = load_corpus(args.data, args.vocab_size)
    it = BucketSentenceIter(sentences, args.batch_size,
                            buckets=[10, 20, 30], layout="TN")

    model = RNNModel(vocab_size, num_embed=args.num_embed,
                     num_hidden=args.num_hidden, num_layers=args.num_layers,
                     tie_weights=args.tied, dropout=0.2)
    model.initialize(init=mx.init.Xavier())
    trainer = gluon.Trainer(model.collect_params(), "sgd",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.epochs):
        it.reset()
        total, count = 0.0, 0
        for batch in it:
            with autograd.record():
                logits = model(batch.data[0])
                loss = loss_fn(logits, batch.label[0]).mean()
            loss.backward()
            gluon.utils.clip_global_norm(
                [p.grad() for p in model.collect_params().values()
                 if p.grad_req != "null"], 0.25)
            trainer.step(args.batch_size)
            total += float(loss.asnumpy())
            count += 1
        ppl = math.exp(total / max(count, 1))
        print(f"epoch {epoch}: perplexity {ppl:.1f}")


if __name__ == "__main__":
    main()
