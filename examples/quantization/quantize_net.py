"""Post-training INT8 quantization walkthrough.

Reference shape: `example/quantization/imagenet_gen_qsym_onedn.py` —
train (or load) a float model, calibrate on sample batches, convert to
int8, compare accuracy and latency.  The TPU path quantizes Gluon blocks
directly (`contrib.quantization.quantize_net`); the int8 matmul/conv run
on the MXU with int32 accumulation.

Run: python examples/quantization/quantize_net.py [--mode entropy]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.contrib import quantization as q
from mxnet_tpu.gluon import nn


def make_net():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(32, kernel_size=3, padding=1, activation="relu"))
    net.add(nn.MaxPool2D(2))
    net.add(nn.Conv2D(64, kernel_size=3, padding=1, activation="relu"))
    net.add(nn.MaxPool2D(2))
    net.add(nn.Dense(128, activation="relu"))
    net.add(nn.Dense(10))
    net.initialize(init=mx.init.Xavier())
    return net


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--mode", default="naive", choices=["naive", "entropy"])
    p.add_argument("--batches", type=int, default=8)
    args = p.parse_args()

    onp.random.seed(0)
    net = make_net()

    # quick synthetic training so the float model is not random noise
    X = onp.random.rand(512, 1, 28, 28).astype("float32")
    Yv = (X.mean(axis=(1, 2, 3)) * 10).astype("int64") % 10
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})
    mod = _NetWithLoss(net, loss_fn)
    fused = gluon.FusedTrainStep(mod, trainer)
    for ep in range(3):
        for i in range(0, 512, 64):
            x = mx.np.array(X[i:i + 64])
            y = mx.np.array(Yv[i:i + 64], dtype="int32")
            loss = fused(x, y, batch_size=64)
        print(f"epoch {ep}: loss {float(loss.asnumpy().mean()):.4f}")

    xs = mx.np.array(X[:256])
    float_logits = net(xs).asnumpy()
    t0 = time.perf_counter()
    net(xs).wait_to_read()
    t_float = time.perf_counter() - t0

    calib = [mx.np.array(X[i:i + 32]) for i in range(0, 32 * args.batches, 32)]
    qnet = q.quantize_net(net, calib_data=calib, calib_mode=args.mode)
    print("converted:", [type(c).__name__ for c in qnet._children.values()])

    int8_logits = qnet(xs).asnumpy()
    qnet(xs).wait_to_read()
    t0 = time.perf_counter()
    qnet(xs).wait_to_read()
    t_int8 = time.perf_counter() - t0

    agree = (int8_logits.argmax(1) == float_logits.argmax(1)).mean()
    print(f"float->int8 argmax agreement: {agree:.3f}")
    print(f"latency: float {t_float * 1e3:.1f} ms, int8 {t_int8 * 1e3:.1f} ms")


class _NetWithLoss(gluon.HybridBlock):
    def __init__(self, net, loss_fn):
        super().__init__()
        self.net = net
        self.loss_fn = loss_fn

    def forward(self, x, y):
        return self.loss_fn(self.net(x), y)


if __name__ == "__main__":
    main()
