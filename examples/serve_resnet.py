"""Serving a Gluon vision model on TPU: wrap -> warmup -> submit -> stats.

The serving quickstart from docs/SERVING.md, end to end on ResNet-18:

1. wrap the block in an `Endpoint` (bounded queue + dynamic batcher +
   executable cache);
2. `warmup()` precompiles every batch bucket so no request ever pays a
   compile;
3. clients `submit()` from many threads; the batcher coalesces them
   into padded power-of-two batches, one device call per batch;
4. `stats()` reports QPS, latency percentiles, batch occupancy, and the
   executable-cache hit rate (>= 95% is the health bar — lower means
   the bucket grid does not match the traffic).

Run:  python examples/serve_resnet.py [--requests 64] [--clients 8]
(On a machine without a TPU this runs on CPU; shapes are kept small so
the demo finishes in seconds.)
"""
import argparse
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu.gluon.model_zoo import vision


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=64,
                    help="requests per client thread")
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--batch", type=int, default=16,
                    help="max rows per device call")
    ap.add_argument("--latency-ms", type=float, default=5.0,
                    help="batching deadline")
    ap.add_argument("--size", type=int, default=64,
                    help="input image side (224 for real traffic)")
    args = ap.parse_args()

    net = vision.resnet18_v1()
    net.initialize()

    # wrap: any Block becomes a service (same as mx.serve.Endpoint(net))
    ep = net.as_endpoint(max_batch_size=args.batch,
                         max_latency_ms=args.latency_ms,
                         max_queue=1024, timeout_ms=30_000)

    # warmup: precompile the whole bucket grid before taking traffic
    example = mx.np.zeros((1, 3, args.size, args.size))
    t0 = time.perf_counter()
    n = ep.warmup(example)
    print(f"warmup: {n} executables ({ep.spec.batch_buckets} batch "
          f"buckets) in {time.perf_counter() - t0:.1f}s")

    # traffic: N client threads submitting single-image requests
    rng = onp.random.default_rng(0)
    img = rng.standard_normal((1, 3, args.size, args.size)).astype("float32")
    errors = []

    def client():
        try:
            for _ in range(args.requests):
                fut = ep.submit(img)           # -> concurrent.futures.Future
                probs = fut.result()
                assert probs.shape == (1, 1000)
        except Exception as exc:               # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=client)
               for _ in range(args.clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    assert not errors, errors[:1]

    s = ep.stats()
    total = args.clients * args.requests
    print(f"\nserved {total} requests in {wall:.2f}s "
          f"({total / wall:.0f} req/s wall)")
    print(f"  p50/p95/p99 latency: {s['latency_ms_p50']:.1f} / "
          f"{s['latency_ms_p95']:.1f} / {s['latency_ms_p99']:.1f} ms")
    print(f"  device calls: {s['batches']}  "
          f"mean occupancy: {s['mean_batch_occupancy']:.2f}")
    print(f"  cache hit rate: {s['cache_hit_rate']:.1%} "
          f"(misses: {s['cache_misses']})")
    ep.shutdown(drain=True)


if __name__ == "__main__":
    main()
