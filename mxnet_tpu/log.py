"""Logging helpers (reference: `python/mxnet/log.py`)."""
from __future__ import annotations

import logging
import sys

__all__ = ["get_logger"]

_LOGGER_FMT = "%(asctime)-15s %(message)s"


def get_logger(name=None, filename=None, filemode=None, level=logging.WARNING):
    """Create/retrieve a configured logger (reference log.py:73)."""
    logger = logging.getLogger(name)
    if getattr(logger, "_init_done", False):
        logger.setLevel(level)
        return logger
    logger._init_done = True
    if filename:
        mode = filemode or "a"
        hdlr = logging.FileHandler(filename, mode)
    else:
        hdlr = logging.StreamHandler(sys.stderr)
    hdlr.setFormatter(logging.Formatter(_LOGGER_FMT))
    logger.addHandler(hdlr)
    logger.setLevel(level)
    return logger
