"""Native (C++) runtime components: build-on-first-use loader.

The reference ships its runtime as `libmxnet.so` built by CMake; here the
native pieces live in `mxnet_tpu/src/*.cc` and are compiled once into
`libmxtpu.so` next to this package (g++ is in the image).  Pure-python
fallbacks exist for every native path, so a missing toolchain degrades
gracefully rather than breaking import.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_lock = threading.Lock()
_lib = None
_tried = False

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(os.path.dirname(_HERE), "src")
_SO = os.path.join(_HERE, "libmxtpu.so")


# image_pipeline.cc links libjpeg and builds into its own .so so a system
# without jpeg headers only loses that path (PIL fallback remains)
_IMG_SRC_NAMES = ("image_pipeline.cc",)
_IMG_SO = os.path.join(_HERE, "libmxtpu_img.so")


def _compile(srcs, so_path, extra=()):
    if not srcs:
        return False
    newest_src = max(os.path.getmtime(s) for s in srcs)
    if os.path.exists(so_path) and os.path.getmtime(so_path) >= newest_src:
        return True
    # compile to a per-pid temp file and rename: concurrent importers
    # (DataLoader workers, parallel jobs) must never load a half-written .so
    tmp = "%s.tmp.%d" % (so_path, os.getpid())
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", tmp] + \
        list(srcs) + list(extra)
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so_path)
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    return True


def _build():
    srcs = [os.path.join(_SRC, f) for f in sorted(os.listdir(_SRC))
            if f.endswith(".cc") and f not in _IMG_SRC_NAMES]
    return _compile(srcs, _SO)


def lib():
    """The loaded native library, or None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        # lockscan: disable=blocking-under-lock -- build-once barrier: the compile MUST run under _lock so concurrent importers block until the .so exists instead of racing the compiler; cold-start only, never on a hot path
        if not _build():
            return None
        try:
            L = ctypes.CDLL(_SO)
        except OSError:
            return None
        # recordio
        L.rio_last_error.restype = ctypes.c_char_p
        L.rio_open_reader.restype = ctypes.c_void_p
        L.rio_open_reader.argtypes = [ctypes.c_char_p]
        L.rio_close_reader.argtypes = [ctypes.c_void_p]
        L.rio_num_records.restype = ctypes.c_int64
        L.rio_num_records.argtypes = [ctypes.c_void_p]
        L.rio_read_record.restype = ctypes.c_int
        L.rio_read_record.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint64)]
        L.rio_read_at.restype = ctypes.c_int
        L.rio_read_at.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint64)]
        L.rio_next_record.restype = ctypes.c_int
        L.rio_next_record.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint64)]
        L.rio_reset.argtypes = [ctypes.c_void_p]
        L.rio_record_offset.restype = ctypes.c_uint64
        L.rio_record_offset.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        L.rio_seek.restype = ctypes.c_int
        L.rio_seek.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        L.rio_reader_tell.restype = ctypes.c_uint64
        L.rio_reader_tell.argtypes = [ctypes.c_void_p]
        L.rio_open_writer.restype = ctypes.c_void_p
        L.rio_open_writer.argtypes = [ctypes.c_char_p, ctypes.c_int]
        L.rio_writer_tell.restype = ctypes.c_int64
        L.rio_writer_tell.argtypes = [ctypes.c_void_p]
        L.rio_write_record.restype = ctypes.c_int
        L.rio_write_record.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_uint64]
        L.rio_close_writer.argtypes = [ctypes.c_void_p]
        _lib = L
        return _lib


class NativeRecordReader:
    """Indexed, zero-copy reader over the native mmap core."""

    def __init__(self, path):
        L = lib()
        if L is None:
            raise RuntimeError("native library unavailable")
        self._lib = L
        self._h = L.rio_open_reader(path.encode())
        if not self._h:
            raise IOError(L.rio_last_error().decode())

    def __len__(self):
        return self._lib.rio_num_records(self._h)

    def read(self, i):
        data = ctypes.POINTER(ctypes.c_uint8)()
        n = ctypes.c_uint64()
        if self._lib.rio_read_record(self._h, i, ctypes.byref(data),
                                     ctypes.byref(n)) != 0:
            raise IOError(self._lib.rio_last_error().decode())
        return ctypes.string_at(data, n.value)

    def read_at(self, offset):
        data = ctypes.POINTER(ctypes.c_uint8)()
        n = ctypes.c_uint64()
        if self._lib.rio_read_at(self._h, offset, ctypes.byref(data),
                                 ctypes.byref(n)) != 0:
            raise IOError(self._lib.rio_last_error().decode())
        return ctypes.string_at(data, n.value)

    def next(self):
        data = ctypes.POINTER(ctypes.c_uint8)()
        n = ctypes.c_uint64()
        rc = self._lib.rio_next_record(self._h, ctypes.byref(data),
                                       ctypes.byref(n))
        if rc == -1:  # EOF (including a truncated trailing record)
            return None
        if rc < -1:
            raise IOError(self._lib.rio_last_error().decode())
        return ctypes.string_at(data, n.value)

    def reset(self):
        self._lib.rio_reset(self._h)

    def seek_offset(self, offset):
        """Position the sequential cursor at the record starting at byte
        ``offset`` (as stored in .idx files)."""
        if self._lib.rio_seek(self._h, offset) != 0:
            raise IOError(self._lib.rio_last_error().decode())

    def tell(self):
        """Byte offset of the next sequential record (file size at EOF)."""
        return self._lib.rio_reader_tell(self._h)

    def offset(self, i):
        return self._lib.rio_record_offset(self._h, i)

    def close(self):
        if getattr(self, "_h", None):
            self._lib.rio_close_reader(self._h)
            self._h = None

    def __del__(self):
        self.close()


class NativeRecordWriter:
    def __init__(self, path, append=False):
        L = lib()
        if L is None:
            raise RuntimeError("native library unavailable")
        self._lib = L
        self._h = L.rio_open_writer(path.encode(), 1 if append else 0)
        if not self._h:
            raise IOError(L.rio_last_error().decode())

    def tell(self):
        return self._lib.rio_writer_tell(self._h)

    def write(self, buf):
        if self._lib.rio_write_record(self._h, bytes(buf), len(buf)) != 0:
            raise IOError(self._lib.rio_last_error().decode())

    def close(self):
        if getattr(self, "_h", None):
            self._lib.rio_close_writer(self._h)
            self._h = None

    def __del__(self):
        self.close()


def parse_libsvm(path):
    """Parse a LibSVM file through the C++ core: returns numpy
    (labels, indptr, indices, values, num_cols).  Falls back to a pure
    python parser when the native library is unavailable."""
    import numpy as onp

    L = lib()
    if L is not None:
        if not getattr(L, "_lsvm_ready", False):
            L.lsvm_last_error.restype = ctypes.c_char_p
            L.lsvm_open.restype = ctypes.c_void_p
            L.lsvm_open.argtypes = [ctypes.c_char_p]
            L.lsvm_close.argtypes = [ctypes.c_void_p]
            L.lsvm_num_rows.restype = ctypes.c_int64
            L.lsvm_num_rows.argtypes = [ctypes.c_void_p]
            L.lsvm_nnz.restype = ctypes.c_int64
            L.lsvm_nnz.argtypes = [ctypes.c_void_p]
            L.lsvm_max_index.restype = ctypes.c_int32
            L.lsvm_max_index.argtypes = [ctypes.c_void_p]
            L.lsvm_copy.argtypes = [
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_float)]
            L._lsvm_ready = True
        h = L.lsvm_open(path.encode())
        if not h:
            raise IOError(L.lsvm_last_error().decode())
        try:
            n = L.lsvm_num_rows(h)
            nnz = L.lsvm_nnz(h)
            labels = onp.empty(n, onp.float32)
            indptr = onp.empty(n + 1, onp.int64)
            indices = onp.empty(nnz, onp.int32)
            values = onp.empty(nnz, onp.float32)
            L.lsvm_copy(
                h,
                labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                indptr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                values.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
            ncols = int(L.lsvm_max_index(h)) + 1
        finally:
            L.lsvm_close(h)
        return labels, indptr, indices, values, ncols

    # pure-python fallback (raises IOError on corrupt rows, matching the
    # native path's error contract)
    labels, indptr, indices, values = [], [0], [], []
    ncols = 0
    with open(path) as f:
        for line_no, line in enumerate(f, 1):
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            try:
                parts = line.split()
                labels.append(float(parts[0]))
                for feat in parts[1:]:
                    idx, val = feat.split(":")
                    if int(idx) < 0:
                        raise ValueError("negative feature index")
                    indices.append(int(idx))
                    values.append(float(val))
                    ncols = max(ncols, int(idx) + 1)
            except ValueError as e:
                raise IOError(
                    f"bad libsvm row at line {line_no}: {e}") from e
            indptr.append(len(indices))
    return (onp.asarray(labels, onp.float32),
            onp.asarray(indptr, onp.int64),
            onp.asarray(indices, onp.int32),
            onp.asarray(values, onp.float32), ncols)


_img_lib = None
_img_tried = False


def img_lib():
    """The jpeg image-pipeline library, or None if unavailable."""
    global _img_lib, _img_tried
    if _img_lib is not None or _img_tried:
        return _img_lib
    with _lock:
        if _img_lib is not None or _img_tried:
            return _img_lib
        _img_tried = True
        srcs = [os.path.join(_SRC, f) for f in _IMG_SRC_NAMES
                if os.path.exists(os.path.join(_SRC, f))]
        # lockscan: disable=blocking-under-lock -- build-once barrier: same contract as lib() above — concurrent importers must block on _lock until the .so exists; cold-start only
        if not _compile(srcs, _IMG_SO, extra=["-ljpeg", "-pthread"]):
            return None
        try:
            L = ctypes.CDLL(_IMG_SO)
        except OSError:
            return None
        L.imgpipe_last_error.restype = ctypes.c_char_p
        L.imgpipe_create.restype = ctypes.c_void_p
        L.imgpipe_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
            ctypes.c_int, ctypes.c_int]
        L.imgpipe_num_records.restype = ctypes.c_int64
        L.imgpipe_num_records.argtypes = [ctypes.c_void_p]
        L.imgpipe_part_records.restype = ctypes.c_int64
        L.imgpipe_part_records.argtypes = [ctypes.c_void_p]
        L.imgpipe_ready_batches.restype = ctypes.c_int
        L.imgpipe_ready_batches.argtypes = [ctypes.c_void_p]
        L.imgpipe_decode_errors.restype = ctypes.c_int64
        L.imgpipe_decode_errors.argtypes = [ctypes.c_void_p]
        L.imgpipe_next.restype = ctypes.c_int
        L.imgpipe_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_float)]
        L.imgpipe_destroy.argtypes = [ctypes.c_void_p]
        _img_lib = L
        return _img_lib


def parse_csv(path):
    """Parse a numeric CSV through the C++ core (`src/csv.cc`, reference
    `src/io/iter_csv.cc` role): returns a float32 (rows, cols) numpy
    array.  Falls back to numpy parsing when the native library is
    unavailable."""
    import numpy as onp

    L = lib()
    if L is not None:
        if not getattr(L, "_csv_ready", False):
            L.csv_last_error.restype = ctypes.c_char_p
            L.csv_open.restype = ctypes.c_void_p
            L.csv_open.argtypes = [ctypes.c_char_p]
            L.csv_close.argtypes = [ctypes.c_void_p]
            L.csv_rows.restype = ctypes.c_int64
            L.csv_rows.argtypes = [ctypes.c_void_p]
            L.csv_cols.restype = ctypes.c_int64
            L.csv_cols.argtypes = [ctypes.c_void_p]
            L.csv_copy.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_float)]
            L._csv_ready = True
        h = L.csv_open(os.fsencode(path))
        if not h:
            raise IOError(L.csv_last_error().decode())
        try:
            rows, cols = L.csv_rows(h), L.csv_cols(h)
            out = onp.empty((rows, cols), onp.float32)
            if out.size:
                L.csv_copy(h, out.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_float)))
            return out
        finally:
            L.csv_close(h)
    # fallback: numpy text parsing
    out = onp.loadtxt(path, delimiter=",", dtype=onp.float32, ndmin=2,
                      comments="#")
    return out
