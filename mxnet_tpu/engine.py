"""Engine controls (reference: `python/mxnet/engine.py`).

The reference bulks small engine ops to amortize dispatch
(`threaded_engine.h:507`).  On TPU, XLA fusion inside a jit is the real
bulking; these knobs are kept for API compatibility — they record the
requested size and advise hybridize/FusedTrainStep, which subsume them."""
from __future__ import annotations

import contextlib

__all__ = ["set_bulk_size", "bulk"]

_bulk_size = 0


def set_bulk_size(size):
    """Set the op-bulking budget; returns the previous value.  Advisory on
    TPU: tracing (hybridize / FusedTrainStep) fuses unconditionally."""
    global _bulk_size
    prev, _bulk_size = _bulk_size, int(size)
    return prev


@contextlib.contextmanager
def bulk(size):
    """Scoped bulking (reference `engine.bulk`)."""
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)
