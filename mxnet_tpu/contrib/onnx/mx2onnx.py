"""Symbol graph -> ONNX export.

Reference: `python/mxnet/contrib/onnx/mx2onnx/` (`export_model`,
`_export_onnx.py` MXNetGraph + the per-op converter registry in
`_op_translations.py`).  Same architecture here: walk the Symbol graph
topologically, run one converter per op to emit NodeProto(s), collect
parameters as initializers, wrap in Graph/ModelProto — encoded by the
wire codec in `proto.py` since the `onnx` package is absent.
"""
from __future__ import annotations

import numpy as onp

from . import proto as P

__all__ = ["export_model"]

_CONVERTERS = {}


def register(name):
    def deco(fn):
        _CONVERTERS[name] = fn
        return fn
    return deco


def _tup(attrs, key, default=None):
    v = attrs.get(key, default)
    if v is None:
        return None
    if isinstance(v, (int, float)):
        return (int(v),)
    return tuple(int(x) for x in v)


# -- converters (subset mirroring the reference's _op_translations) ---------

@register("FullyConnected")
@register("fully_connected")
def _fc(name, ins, attrs):
    n = attrs.get("num_hidden")
    del n  # shape is carried by the weight initializer
    flatten = attrs.get("flatten", True)
    nodes = []
    data = ins[0]
    if flatten:
        nodes.append(P.node_proto("Flatten", [data], [name + "_flat"],
                                  name + "_flat", [P.attr_int("axis", 1)]))
        data = name + "_flat"
    if len(ins) >= 3 and ins[2] is not None:
        nodes.append(P.node_proto(
            "Gemm", [data, ins[1], ins[2]], [name], name,
            [P.attr_int("transB", 1)]))
    else:
        nodes.append(P.node_proto(
            "Gemm", [data, ins[1]], [name], name,
            [P.attr_int("transB", 1)]))
    return nodes


@register("Convolution")
@register("convolution")
def _conv(name, ins, attrs):
    kernel = _tup(attrs, "kernel")
    stride = _tup(attrs, "stride") or (1,) * len(kernel)
    dilate = _tup(attrs, "dilate") or (1,) * len(kernel)
    pad = _tup(attrs, "pad") or (0,) * len(kernel)
    group = int(attrs.get("num_group", 1))
    a = [P.attr_ints("kernel_shape", kernel),
         P.attr_ints("strides", stride),
         P.attr_ints("dilations", dilate),
         P.attr_ints("pads", pad + pad),
         P.attr_int("group", group)]
    return [P.node_proto("Conv", [i for i in ins if i is not None],
                         [name], name, a)]


@register("BatchNorm")
@register("batch_norm")
def _bn(name, ins, attrs):
    a = [P.attr_float("epsilon", float(attrs.get("eps", 1e-3))),
         P.attr_float("momentum", float(attrs.get("momentum", 0.9)))]
    return [P.node_proto("BatchNormalization", ins[:5], [name], name, a)]


@register("Activation")
@register("activation")
def _act(name, ins, attrs):
    op = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
          "softrelu": "Softplus", "softsign": "Softsign"}[
              attrs.get("act_type", "relu")]
    return [P.node_proto(op, ins[:1], [name], name)]


@register("LeakyReLU")
@register("leaky_relu")
def _leaky(name, ins, attrs):
    act = attrs.get("act_type", "leaky")
    if act == "leaky":
        return [P.node_proto("LeakyRelu", ins[:1], [name], name,
                             [P.attr_float("alpha",
                                           float(attrs.get("slope", 0.25)))])]
    if act == "elu":
        return [P.node_proto("Elu", ins[:1], [name], name,
                             [P.attr_float("alpha",
                                           float(attrs.get("slope", 0.25)))])]
    if act == "prelu":
        return [P.node_proto("PRelu", ins[:2], [name], name)]
    raise ValueError(f"cannot export LeakyReLU act_type={act}")


@register("Pooling")
@register("pooling")
def _pool(name, ins, attrs):
    ptype = attrs.get("pool_type", "max")
    if attrs.get("global_pool"):
        op = "GlobalMaxPool" if ptype == "max" else "GlobalAveragePool"
        return [P.node_proto(op, ins[:1], [name], name)]
    kernel = _tup(attrs, "kernel")
    stride = _tup(attrs, "stride") or kernel
    pad = _tup(attrs, "pad") or (0,) * len(kernel)
    a = [P.attr_ints("kernel_shape", kernel),
         P.attr_ints("strides", stride),
         P.attr_ints("pads", pad + pad)]
    op = "MaxPool" if ptype == "max" else "AveragePool"
    if ptype == "avg":
        a.append(P.attr_int("count_include_pad",
                            int(bool(attrs.get("count_include_pad", True)))))
    return [P.node_proto(op, ins[:1], [name], name, a)]


@register("Flatten")
def _flatten(name, ins, attrs):
    return [P.node_proto("Flatten", ins[:1], [name], name,
                         [P.attr_int("axis", 1)])]


@register("softmax")
def _softmax(name, ins, attrs):
    return [P.node_proto("Softmax", ins[:1], [name], name,
                         [P.attr_int("axis", int(attrs.get("axis", -1)))])]


@register("SoftmaxOutput")
def _softmax_output(name, ins, attrs):
    # inference semantics of the training head = plain softmax over axis 1
    return [P.node_proto("Softmax", ins[:1], [name], name,
                         [P.attr_int("axis", 1)])]


@register("Concat")
@register("concat")
def _concat(name, ins, attrs):
    return [P.node_proto("Concat", ins, [name], name,
                         [P.attr_int("axis", int(attrs.get("dim", 1)))])]


@register("Embedding")
@register("embedding")
def _embedding(name, ins, attrs):
    # ONNX Gather(table, indices); mxnet order is (indices, table)
    return [P.node_proto("Gather", [ins[1], ins[0]], [name], name,
                         [P.attr_int("axis", 0)])]


@register("Reshape")
@register("reshape")
def _reshape(name, ins, attrs, extra_init=None):
    shape = _tup(attrs, "shape")
    init = P.tensor_proto(name + "_shape",
                          onp.asarray(shape, onp.int64))
    extra_init.append(init)
    return [P.node_proto("Reshape", [ins[0], name + "_shape"], [name],
                         name)]


@register("transpose")
def _transpose(name, ins, attrs):
    axes = _tup(attrs, "axes")
    a = [P.attr_ints("perm", axes)] if axes else []
    return [P.node_proto("Transpose", ins[:1], [name], name, a)]


@register("Dropout")
@register("dropout")
def _dropout(name, ins, attrs):
    return [P.node_proto("Identity", ins[:1], [name], name)]  # inference


for _mx, _ox in [("_plus", "Add"), ("_minus", "Sub"), ("_mul", "Mul"),
                 ("_div", "Div"), ("broadcast_add", "Add"),
                 ("broadcast_sub", "Sub"), ("broadcast_mul", "Mul"),
                 ("broadcast_div", "Div"), ("elemwise_add", "Add"),
                 ("elemwise_sub", "Sub"), ("elemwise_mul", "Mul"),
                 ("elemwise_div", "Div"), ("add", "Add"),
                 ("subtract", "Sub"), ("multiply", "Mul"),
                 ("true_divide", "Div"), ("dot", "MatMul"),
                 ("matmul", "MatMul"), ("maximum", "Max"),
                 ("minimum", "Min")]:
    def _bin(name, ins, attrs, _op=_ox):
        return [P.node_proto(_op, ins[:2], [name], name)]
    _CONVERTERS[_mx] = _bin

for _mx, _ox in [("relu", "Relu"), ("sigmoid", "Sigmoid"),
                 ("tanh", "Tanh"), ("exp", "Exp"), ("log", "Log"),
                 ("sqrt", "Sqrt"), ("abs", "Abs"), ("negative", "Neg"),
                 ("identity", "Identity"), ("BlockGrad", "Identity"),
                 ("stop_gradient", "Identity"), ("Cast", "Identity")]:
    def _un(name, ins, attrs, _op=_ox):
        return [P.node_proto(_op, ins[:1], [name], name)]
    _CONVERTERS[_mx] = _un


# -- graph walk -------------------------------------------------------------


def export_model(sym, params, input_shapes=None, input_types=None,
                 onnx_file_path="model.onnx", opset_version=13,
                 run_shape_inference=False):
    """Serialize ``sym`` + ``params`` to an ONNX file (reference
    `mx2onnx.export_model`).  ``params`` maps free-variable names to
    NDArrays/arrays; remaining free variables become graph inputs with
    shapes from ``input_shapes`` (dict name->shape or list in
    list_arguments order)."""
    from ...symbol import Symbol, _ScalarSymbol

    params = {k: (v.asnumpy() if hasattr(v, "asnumpy") else onp.asarray(v))
              for k, v in (params or {}).items()}
    # strip the Module-era arg:/aux: prefixes
    params = {k.split(":", 1)[-1]: v for k, v in params.items()}

    args = sym.list_arguments()
    data_inputs = [a for a in args if a not in params]
    if isinstance(input_shapes, dict):
        shape_of = input_shapes
    else:
        shape_of = dict(zip(data_inputs, input_shapes or []))

    nodes, initializers, extra_init = [], [], []
    name_of = {}
    counter = [0]

    def walk(s):
        if id(s) in name_of:
            return name_of[id(s)]
        if isinstance(s, _ScalarSymbol):
            nm = f"const_{counter[0]}"
            counter[0] += 1
            initializers.append(P.tensor_proto(
                nm, onp.asarray(s._value, onp.float32)))
            name_of[id(s)] = nm
            return nm
        if s._op is None:
            name_of[id(s)] = s._name
            return s._name
        ins = [walk(i) for i in s._inputs]
        # keyword tensor inputs follow in their declared order
        kw = {k: walk(v) for k, v in s._kw_inputs.items()}
        if kw:
            order = ("data", "weight", "bias", "gamma", "beta",
                     "moving_mean", "moving_var", "lhs", "rhs")
            ins = ins + [kw[k] for k in order if k in kw] + \
                [v for k, v in kw.items() if k not in order]
        conv = _CONVERTERS.get(s._op)
        if conv is None:
            raise NotImplementedError(
                f"no ONNX converter for op {s._op!r} (have "
                f"{sorted(_CONVERTERS)})")
        nm = s._name if s._name != s._op else f"{s._op}_{counter[0]}"
        counter[0] += 1
        try:
            new_nodes = conv(nm, ins, s._attrs, extra_init=extra_init)
        except TypeError:
            new_nodes = conv(nm, ins, s._attrs)
        nodes.extend(new_nodes)
        name_of[id(s)] = nm
        return nm

    out_name = walk(sym)

    for k in args:
        if k in params:
            initializers.append(P.tensor_proto(k, params[k]))
    initializers.extend(extra_init)

    g_inputs = [P.value_info(n, shape_of.get(n, ())) for n in data_inputs]
    g_outputs = [P.value_info(out_name, ())]
    graph = P.graph_proto(nodes, "mxnet_tpu_graph", initializers,
                          g_inputs, g_outputs)
    blob = P.model_proto(graph, opset=opset_version)
    with open(onnx_file_path, "wb") as f:
        f.write(blob)
    return onnx_file_path
