"""Symbol graph -> ONNX export.

Reference: `python/mxnet/contrib/onnx/mx2onnx/` (`export_model`,
`_export_onnx.py` MXNetGraph + the per-op converter registry in
`_op_translations.py`).  Same architecture here: walk the Symbol graph
topologically, run one converter per op to emit NodeProto(s), collect
parameters as initializers, wrap in Graph/ModelProto — encoded by the
wire codec in `proto.py` since the `onnx` package is absent.
"""
from __future__ import annotations

import numpy as onp

from . import proto as P

__all__ = ["export_model", "export_block"]

_CONVERTERS = {}


def register(name):
    def deco(fn):
        _CONVERTERS[name] = fn
        return fn
    return deco


def _tup(attrs, key, default=None):
    v = attrs.get(key, default)
    if v is None:
        return None
    if isinstance(v, (int, float)):
        return (int(v),)
    return tuple(int(x) for x in v)


# -- converters (subset mirroring the reference's _op_translations) ---------

@register("FullyConnected")
@register("fully_connected")
def _fc(name, ins, attrs):
    n = attrs.get("num_hidden")
    del n  # shape is carried by the weight initializer
    flatten = attrs.get("flatten", True)
    nodes = []
    data = ins[0]
    if flatten:
        nodes.append(P.node_proto("Flatten", [data], [name + "_flat"],
                                  name + "_flat", [P.attr_int("axis", 1)]))
        data = name + "_flat"
    if len(ins) >= 3 and ins[2] is not None:
        nodes.append(P.node_proto(
            "Gemm", [data, ins[1], ins[2]], [name], name,
            [P.attr_int("transB", 1)]))
    else:
        nodes.append(P.node_proto(
            "Gemm", [data, ins[1]], [name], name,
            [P.attr_int("transB", 1)]))
    return nodes


@register("Convolution")
@register("convolution")
def _conv(name, ins, attrs):
    kernel = _tup(attrs, "kernel")
    stride = _tup(attrs, "stride") or (1,) * len(kernel)
    dilate = _tup(attrs, "dilate") or (1,) * len(kernel)
    pad = _tup(attrs, "pad") or (0,) * len(kernel)
    group = int(attrs.get("num_group", 1))
    a = [P.attr_ints("kernel_shape", kernel),
         P.attr_ints("strides", stride),
         P.attr_ints("dilations", dilate),
         P.attr_ints("pads", pad + pad),
         P.attr_int("group", group)]
    return [P.node_proto("Conv", [i for i in ins if i is not None],
                         [name], name, a)]


@register("BatchNorm")
@register("batch_norm")
def _bn(name, ins, attrs):
    a = [P.attr_float("epsilon", float(attrs.get("eps", 1e-3))),
         P.attr_float("momentum", float(attrs.get("momentum", 0.9)))]
    return [P.node_proto("BatchNormalization", ins[:5], [name], name, a)]


@register("Activation")
@register("activation")
def _act(name, ins, attrs):
    op = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
          "softrelu": "Softplus", "softsign": "Softsign"}[
              attrs.get("act_type", "relu")]
    return [P.node_proto(op, ins[:1], [name], name)]


@register("LeakyReLU")
@register("leaky_relu")
def _leaky(name, ins, attrs, extra_init=None):
    act = attrs.get("act_type", "leaky")
    if act == "leaky":
        return [P.node_proto("LeakyRelu", ins[:1], [name], name,
                             [P.attr_float("alpha",
                                           float(attrs.get("slope", 0.25)))])]
    if act == "elu":
        return [P.node_proto("Elu", ins[:1], [name], name,
                             [P.attr_float("alpha",
                                           float(attrs.get("slope", 0.25)))])]
    if act == "prelu":
        return [P.node_proto("PRelu", ins[:2], [name], name)]
    if act == "gelu":
        # exact erf gelu as an opset-17 subgraph:
        # 0.5 * x * (1 + erf(x / sqrt(2)))
        extra_init.append(P.tensor_proto(
            name + "_rsqrt2", onp.asarray(1.0 / onp.sqrt(2.0), onp.float32)))
        extra_init.append(P.tensor_proto(
            name + "_half", onp.asarray(0.5, onp.float32)))
        extra_init.append(P.tensor_proto(
            name + "_one", onp.asarray(1.0, onp.float32)))
        x = ins[0]
        return [
            P.node_proto("Mul", [x, name + "_rsqrt2"], [name + "_s"],
                         name + "_s"),
            P.node_proto("Erf", [name + "_s"], [name + "_e"], name + "_e"),
            P.node_proto("Add", [name + "_e", name + "_one"],
                         [name + "_a"], name + "_a"),
            P.node_proto("Mul", [x, name + "_a"], [name + "_m"],
                         name + "_m"),
            P.node_proto("Mul", [name + "_m", name + "_half"], [name],
                         name),
        ]
    raise ValueError(f"cannot export LeakyReLU act_type={act}")


@register("Pooling")
@register("pooling")
def _pool(name, ins, attrs):
    ptype = attrs.get("pool_type", "max")
    if attrs.get("global_pool"):
        op = "GlobalMaxPool" if ptype == "max" else "GlobalAveragePool"
        return [P.node_proto(op, ins[:1], [name], name)]
    kernel = _tup(attrs, "kernel")
    stride = _tup(attrs, "stride") or kernel
    pad = _tup(attrs, "pad") or (0,) * len(kernel)
    a = [P.attr_ints("kernel_shape", kernel),
         P.attr_ints("strides", stride),
         P.attr_ints("pads", pad + pad)]
    op = "MaxPool" if ptype == "max" else "AveragePool"
    if ptype == "avg":
        a.append(P.attr_int("count_include_pad",
                            int(bool(attrs.get("count_include_pad", True)))))
    return [P.node_proto(op, ins[:1], [name], name, a)]


@register("Flatten")
def _flatten(name, ins, attrs):
    return [P.node_proto("Flatten", ins[:1], [name], name,
                         [P.attr_int("axis", 1)])]


@register("softmax")
def _softmax(name, ins, attrs):
    return [P.node_proto("Softmax", ins[:1], [name], name,
                         [P.attr_int("axis", int(attrs.get("axis", -1)))])]


@register("SoftmaxOutput")
def _softmax_output(name, ins, attrs):
    # inference semantics of the training head = plain softmax over axis 1
    return [P.node_proto("Softmax", ins[:1], [name], name,
                         [P.attr_int("axis", 1)])]


@register("Concat")
@register("concat")
def _concat(name, ins, attrs):
    return [P.node_proto("Concat", ins, [name], name,
                         [P.attr_int("axis", int(attrs.get("dim", 1)))])]


@register("Embedding")
@register("embedding")
def _embedding(name, ins, attrs):
    # ONNX Gather(table, indices); mxnet order is (indices, table)
    return [P.node_proto("Gather", [ins[1], ins[0]], [name], name,
                         [P.attr_int("axis", 0)])]


@register("Reshape")
@register("reshape")
def _reshape(name, ins, attrs, extra_init=None):
    shape = _tup(attrs, "shape")
    init = P.tensor_proto(name + "_shape",
                          onp.asarray(shape, onp.int64))
    extra_init.append(init)
    return [P.node_proto("Reshape", [ins[0], name + "_shape"], [name],
                         name)]


@register("transpose")
def _transpose(name, ins, attrs):
    axes = _tup(attrs, "axes")
    a = [P.attr_ints("perm", axes)] if axes else []
    return [P.node_proto("Transpose", ins[:1], [name], name, a)]


@register("Dropout")
@register("dropout")
def _dropout(name, ins, attrs):
    return [P.node_proto("Identity", ins[:1], [name], name)]  # inference


for _mx, _ox in [("_plus", "Add"), ("_minus", "Sub"), ("_mul", "Mul"),
                 ("_div", "Div"), ("broadcast_add", "Add"),
                 ("broadcast_sub", "Sub"), ("broadcast_mul", "Mul"),
                 ("broadcast_div", "Div"), ("elemwise_add", "Add"),
                 ("elemwise_sub", "Sub"), ("elemwise_mul", "Mul"),
                 ("elemwise_div", "Div"), ("add", "Add"),
                 ("subtract", "Sub"), ("multiply", "Mul"),
                 ("true_divide", "Div"), ("dot", "MatMul"),
                 ("matmul", "MatMul"), ("maximum", "Max"),
                 ("minimum", "Min")]:
    def _bin(name, ins, attrs, _op=_ox):
        return [P.node_proto(_op, ins[:2], [name], name)]
    _CONVERTERS[_mx] = _bin

for _mx, _ox in [("relu", "Relu"), ("sigmoid", "Sigmoid"),
                 ("tanh", "Tanh"), ("exp", "Exp"), ("log", "Log"),
                 ("sqrt", "Sqrt"), ("abs", "Abs"), ("negative", "Neg"),
                 ("identity", "Identity"), ("BlockGrad", "Identity"),
                 ("stop_gradient", "Identity"), ("Cast", "Identity")]:
    def _un(name, ins, attrs, _op=_ox):
        return [P.node_proto(_op, ins[:1], [name], name)]
    _CONVERTERS[_mx] = _un


# -- round-3 breadth (VERDICT r2 #4): Pad/Clip/Slice/TopK/Where/... ---------

@register("Pad")
@register("pad")
def _pad(name, ins, attrs, extra_init=None):
    mode = attrs.get("mode", "constant")
    pw = _tup(attrs, "pad_width")
    # mxnet pad_width is (before0, after0, before1, after1, ...);
    # ONNX wants all befores then all afters
    befores = pw[0::2]
    afters = pw[1::2]
    extra_init.append(P.tensor_proto(
        name + "_pads", onp.asarray(befores + afters, onp.int64)))
    node_ins = [ins[0], name + "_pads"]
    if mode == "constant":
        extra_init.append(P.tensor_proto(
            name + "_cval",
            onp.asarray(float(attrs.get("constant_value", 0.0)), onp.float32)))
        node_ins.append(name + "_cval")
    onnx_mode = {"constant": "constant", "edge": "edge",
                 "reflect": "reflect"}[mode]
    return [P.node_proto("Pad", node_ins, [name], name,
                         [P.attr_string("mode", onnx_mode)])]


@register("clip")
def _clip(name, ins, attrs, extra_init=None):
    # scalar bounds may arrive either as attrs (a_min/a_max) or as
    # constant inputs (Symbol positional scalars)
    node_ins = [ins[0]]
    if len(ins) >= 3:
        node_ins += [ins[1], ins[2]]
    else:
        extra_init.append(P.tensor_proto(
            name + "_min", onp.asarray(float(attrs.get("a_min", 0.0)),
                                       onp.float32)))
        extra_init.append(P.tensor_proto(
            name + "_max", onp.asarray(float(attrs.get("a_max", 0.0)),
                                       onp.float32)))
        node_ins += [name + "_min", name + "_max"]
    return [P.node_proto("Clip", node_ins, [name], name)]


@register("slice")
def _slice(name, ins, attrs, extra_init=None):
    begin = _tup(attrs, "begin")
    end = _tup(attrs, "end")
    step = _tup(attrs, "step") or (1,) * len(begin)
    axes = tuple(range(len(begin)))
    big = 2 ** 31 - 1
    end = tuple(big if e is None else int(e) for e in end)
    begin = tuple(0 if b is None else int(b) for b in begin)
    for suffix, vals in (("_starts", begin), ("_ends", end),
                         ("_axes", axes), ("_steps", step)):
        extra_init.append(P.tensor_proto(
            name + suffix, onp.asarray(vals, onp.int64)))
    return [P.node_proto(
        "Slice", [ins[0], name + "_starts", name + "_ends",
                  name + "_axes", name + "_steps"], [name], name)]


@register("slice_axis")
def _slice_axis(name, ins, attrs, extra_init=None):
    axis = int(attrs.get("axis", 0))
    begin = int(attrs.get("begin", 0))
    end = attrs.get("end")
    end = 2 ** 31 - 1 if end is None else int(end)
    for suffix, vals in (("_starts", (begin,)), ("_ends", (end,)),
                         ("_axes", (axis,))):
        extra_init.append(P.tensor_proto(
            name + suffix, onp.asarray(vals, onp.int64)))
    return [P.node_proto(
        "Slice", [ins[0], name + "_starts", name + "_ends", name + "_axes"],
        [name], name)]


@register("topk")
def _topk(name, ins, attrs, extra_init=None):
    k = int(attrs.get("k", 1))
    axis = int(attrs.get("axis", -1))
    ret_typ = attrs.get("ret_typ", "indices")
    extra_init.append(P.tensor_proto(name + "_k",
                                     onp.asarray([k], onp.int64)))
    outs = {"value": [name, name + "_idx_unused"],
            "indices": [name + "_val_unused", name],
            "both": [name, name + "_1"]}[ret_typ]
    a = [P.attr_int("axis", axis),
         P.attr_int("largest", int(not attrs.get("is_ascend", False))),
         P.attr_int("sorted", 1)]
    return [P.node_proto("TopK", [ins[0], name + "_k"], outs, name, a)]


@register("where")
def _where(name, ins, attrs):
    return [P.node_proto("Where", ins[:3], [name], name)]


@register("expand_dims")
def _expand_dims(name, ins, attrs, extra_init=None):
    extra_init.append(P.tensor_proto(
        name + "_axes", onp.asarray([int(attrs.get("axis", 0))], onp.int64)))
    return [P.node_proto("Unsqueeze", [ins[0], name + "_axes"],
                         [name], name)]


@register("squeeze")
def _squeeze(name, ins, attrs, extra_init=None):
    axis = attrs.get("axis")
    if axis is None:
        return [P.node_proto("Squeeze", ins[:1], [name], name)]
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    extra_init.append(P.tensor_proto(
        name + "_axes", onp.asarray(axes, onp.int64)))
    return [P.node_proto("Squeeze", [ins[0], name + "_axes"], [name], name)]


@register("broadcast_like")
def _broadcast_like(name, ins, attrs):
    # Expand to the runtime shape of the second input
    return [P.node_proto("Shape", [ins[1]], [name + "_shape"],
                         name + "_shape"),
            P.node_proto("Expand", [ins[0], name + "_shape"], [name], name)]


@register("broadcast_to")
def _broadcast_to(name, ins, attrs, extra_init=None):
    shape = _tup(attrs, "shape")
    extra_init.append(P.tensor_proto(
        name + "_shape", onp.asarray(shape, onp.int64)))
    return [P.node_proto("Expand", [ins[0], name + "_shape"], [name], name)]


for _mx, _ox in [("_power", "Pow"), ("power", "Pow"), ("broadcast_power", "Pow"),
                 ("mod", "Mod"), ("broadcast_mod", "Mod"),
                 ("equal", "Equal"), ("broadcast_equal", "Equal"),
                 ("greater", "Greater"), ("broadcast_greater", "Greater"),
                 ("lesser", "Less"), ("less", "Less"),
                 ("broadcast_lesser", "Less")]:
    def _bin2(name, ins, attrs, _op=_ox):
        return [P.node_proto(_op, ins[:2], [name], name)]
    _CONVERTERS[_mx] = _bin2


def _reduce(onnx_op):
    def conv(name, ins, attrs, extra_init=None):
        axis = attrs.get("axis")
        a = [P.attr_int("keepdims", int(bool(attrs.get("keepdims", False))))]
        axes = None
        if axis is not None:
            axes = (axis,) if isinstance(axis, int) else tuple(axis)
        if onnx_op == "ReduceSum":
            # ReduceSum-13 takes axes as an INPUT; the other reductions
            # keep the attribute form until opset 18
            node_ins = [ins[0]]
            if axes is not None:
                extra_init.append(P.tensor_proto(
                    name + "_axes", onp.asarray(axes, onp.int64)))
                node_ins.append(name + "_axes")
            return [P.node_proto(onnx_op, node_ins, [name], name, a)]
        if axes is not None:
            a.append(P.attr_ints("axes", axes))
        return [P.node_proto(onnx_op, ins[:1], [name], name, a)]
    return conv


for _mx, _ox in [("sum", "ReduceSum"), ("mean", "ReduceMean"),
                 ("max", "ReduceMax"), ("min", "ReduceMin"),
                 ("prod", "ReduceProd"), ("norm", "ReduceL2")]:
    _CONVERTERS[_mx] = _reduce(_ox)
    _CONVERTERS["reduce_" + _mx] = _reduce(_ox)


@register("argmax")
def _argmax(name, ins, attrs):
    return [P.node_proto("ArgMax", ins[:1], [name], name,
                         [P.attr_int("axis", int(attrs.get("axis", 0))),
                          P.attr_int("keepdims",
                                     int(bool(attrs.get("keepdims", False))))])]


@register("LayerNorm")
@register("layer_norm")
def _layer_norm(name, ins, attrs):
    return [P.node_proto(
        "LayerNormalization", ins[:3], [name], name,
        [P.attr_int("axis", int(attrs.get("axis", -1))),
         P.attr_float("epsilon", float(attrs.get("eps", 1e-5)))])]


@register("log_softmax")
def _log_softmax(name, ins, attrs):
    return [P.node_proto("LogSoftmax", ins[:1], [name], name,
                         [P.attr_int("axis", int(attrs.get("axis", -1)))])]


@register("stack")
def _stack(name, ins, attrs, extra_init=None):
    axis = int(attrs.get("axis", 0))
    nodes = []
    unsq = []
    extra_init.append(P.tensor_proto(
        name + "_axes", onp.asarray([axis], onp.int64)))
    for i, x in enumerate(ins):
        nodes.append(P.node_proto("Unsqueeze", [x, name + "_axes"],
                                  [f"{name}_u{i}"], f"{name}_u{i}"))
        unsq.append(f"{name}_u{i}")
    nodes.append(P.node_proto("Concat", unsq, [name], name,
                              [P.attr_int("axis", axis)]))
    return nodes


def _rnn_onnx_nodes(name, ins, attrs, extra_init, weights):
    """Emit per-layer ONNX LSTM/GRU/RNN nodes from captured weight VALUES
    (`weights`: list of (i2h_w, i2h_b, h2h_w, h2h_b) numpy arrays per
    layer).  MXNet LSTM gate order i,f,g,o -> ONNX i,o,f,c
    (`src/operator/rnn-inl.h:421` vs ONNX LSTM spec); GRU z,r,n stays
    r,z,n -> ONNX z,r,h needs the same swap."""
    mode = attrs["mode"]
    hidden = attrs["hidden"]
    x = ins[0]
    h0, c0 = ins[1], ins[2]
    nodes = []
    h_outs, c_outs = [], []
    op = {"lstm": "LSTM", "gru": "GRU",
          "rnn_relu": "RNN", "rnn_tanh": "RNN"}[mode]

    def perm(w):
        if mode == "lstm":   # i,f,g,o -> i,o,f,c(g)
            i, f, g, o = onp.split(w, 4, axis=0)
            return onp.concatenate([i, o, f, g], axis=0)
        if mode == "gru":    # mxnet r,z,n -> onnx z,r,h
            r, z, n = onp.split(w, 3, axis=0)
            return onp.concatenate([z, r, n], axis=0)
        return w

    extra_init.append(P.tensor_proto(
        name + "_sq1", onp.asarray([1], onp.int64)))
    extra_init.append(P.tensor_proto(
        name + "_sq0", onp.asarray([0], onp.int64)))
    cur = x
    for layer, (wi, bi, wh, bh) in enumerate(weights):
        ln = f"{name}_l{layer}"
        W = perm(wi)[None]                    # (1, G*H, C)
        R = perm(wh)[None]
        B = onp.concatenate([perm(bi), perm(bh)])[None]
        extra_init.append(P.tensor_proto(ln + "_W", W.astype(onp.float32)))
        extra_init.append(P.tensor_proto(ln + "_R", R.astype(onp.float32)))
        extra_init.append(P.tensor_proto(ln + "_B", B.astype(onp.float32)))
        # initial states: slice layer `layer` from the stacked (L, N, H)
        for tag, full in (("_h0", h0),) + ((("_c0", c0),)
                                           if mode == "lstm" else ()):
            extra_init.append(P.tensor_proto(
                ln + tag + "_starts", onp.asarray([layer], onp.int64)))
            extra_init.append(P.tensor_proto(
                ln + tag + "_ends", onp.asarray([layer + 1], onp.int64)))
            nodes.append(P.node_proto(
                "Slice", [full, ln + tag + "_starts", ln + tag + "_ends",
                          name + "_sq0"], [ln + tag], ln + tag))
        node_ins = [cur, ln + "_W", ln + "_R", ln + "_B", "", ln + "_h0"]
        outs = [ln + "_Y", ln + "_Yh"]
        if mode == "lstm":
            node_ins.append(ln + "_c0")
            outs.append(ln + "_Yc")
        a = [P.attr_int("hidden_size", hidden)]
        if mode == "gru":
            # this backend's GRU applies the reset gate AFTER the
            # recurrent linear incl. its bias (rnn_layer.py:51-55) —
            # ONNX linear_before_reset=1; the default 0 places Rb
            # outside the reset multiply and diverges whenever Rb != 0
            a.append(P.attr_int("linear_before_reset", 1))
        if mode == "rnn_relu":
            a.append(P.attr_strings("activations", ["Relu"]))
        nodes.append(P.node_proto(op, node_ins, outs, ln, a))
        # Y: (T, 1, N, H) -> (T, N, H)
        nodes.append(P.node_proto("Squeeze", [ln + "_Y", name + "_sq1"],
                                  [ln + "_Ysq"], ln + "_Ysq"))
        cur = ln + "_Ysq"
        h_outs.append(ln + "_Yh")
        c_outs.append(ln + "_Yc" if mode == "lstm" else ln + "_Yh")
    # final output aliases
    nodes.append(P.node_proto("Identity", [cur], [name], name))
    if len(h_outs) == 1:
        nodes.append(P.node_proto("Identity", [h_outs[0]],
                                  [name + "_1"], name + "_1"))
        nodes.append(P.node_proto("Identity", [c_outs[0]],
                                  [name + "_2"], name + "_2"))
    else:
        nodes.append(P.node_proto("Concat", h_outs, [name + "_1"],
                                  name + "_1", [P.attr_int("axis", 0)]))
        nodes.append(P.node_proto("Concat", c_outs, [name + "_2"],
                                  name + "_2", [P.attr_int("axis", 0)]))
    return nodes


# -- graph walk -------------------------------------------------------------


def export_model(sym, params, input_shapes=None, input_types=None,
                 onnx_file_path="model.onnx", opset_version=17,
                 run_shape_inference=False):
    """Serialize ``sym`` + ``params`` to an ONNX file (reference
    `mx2onnx.export_model`).  ``params`` maps free-variable names to
    NDArrays/arrays; remaining free variables become graph inputs with
    shapes from ``input_shapes`` (dict name->shape or list in
    list_arguments order)."""
    from ...symbol import Symbol, _ScalarSymbol

    params = {k: (v.asnumpy() if hasattr(v, "asnumpy") else onp.asarray(v))
              for k, v in (params or {}).items()}
    # strip the Module-era arg:/aux: prefixes
    params = {k.split(":", 1)[-1]: v for k, v in params.items()}

    args = sym.list_arguments()
    data_inputs = [a for a in args if a not in params]
    if isinstance(input_shapes, dict):
        shape_of = input_shapes
    else:
        shape_of = dict(zip(data_inputs, input_shapes or []))

    nodes, initializers, extra_init = [], [], []
    name_of = {}
    counter = [0]

    def walk(s):
        if id(s) in name_of:
            return name_of[id(s)]
        if isinstance(s, _ScalarSymbol):
            nm = f"const_{counter[0]}"
            counter[0] += 1
            initializers.append(P.tensor_proto(
                nm, onp.asarray(s._value, onp.float32)))
            name_of[id(s)] = nm
            return nm
        if s._op is None:
            name_of[id(s)] = s._name
            return s._name
        ins = [walk(i) for i in s._inputs]
        # keyword tensor inputs follow in their declared order
        kw = {k: walk(v) for k, v in s._kw_inputs.items()}
        if kw:
            order = ("data", "weight", "bias", "gamma", "beta",
                     "moving_mean", "moving_var", "lhs", "rhs")
            ins = ins + [kw[k] for k in order if k in kw] + \
                [v for k, v in kw.items() if k not in order]
        conv = _CONVERTERS.get(s._op)
        if conv is None:
            raise NotImplementedError(
                f"no ONNX converter for op {s._op!r} (have "
                f"{sorted(_CONVERTERS)})")
        nm = s._name if s._name != s._op else f"{s._op}_{counter[0]}"
        counter[0] += 1
        try:
            new_nodes = conv(nm, ins, s._attrs, extra_init=extra_init)
        except TypeError:
            new_nodes = conv(nm, ins, s._attrs)
        nodes.extend(new_nodes)
        name_of[id(s)] = nm
        return nm

    out_name = walk(sym)

    for k in args:
        if k in params:
            initializers.append(P.tensor_proto(k, params[k]))
    initializers.extend(extra_init)

    g_inputs = [P.value_info(n, shape_of.get(n, ())) for n in data_inputs]
    g_outputs = [P.value_info(out_name, ())]
    graph = P.graph_proto(nodes, "mxnet_tpu_graph", initializers,
                          g_inputs, g_outputs)
    blob = P.model_proto(graph, opset=opset_version)
    with open(onnx_file_path, "wb") as f:
        f.write(blob)
    return onnx_file_path


# ---------------------------------------------------------------------------
# Gluon HybridBlock -> ONNX via imperative graph capture
# ---------------------------------------------------------------------------
# The reference exports Gluon models by hybridize-tracing to a Symbol then
# `export_model` (`python/mxnet/gluon/block.py:1300` + mx2onnx).  Here the
# equivalent trace is `ops.invoke._CaptureScope`: one eval-mode forward
# records every dispatched op with its live NDArrays; the entries are then
# lifted into ONNX nodes.  Parameter identity maps array -> initializer
# name; arrays created inside forward (zeros state, constants) are inlined
# as initializers.

def _buf_id(nd):
    return id(nd._data)


def _nd_leaves(obj):
    import jax
    from ...ndarray.ndarray import NDArray
    return [x for x in jax.tree_util.tree_leaves(
        obj, is_leaf=lambda o: isinstance(o, NDArray))
        if isinstance(x, NDArray)]


def _bind(fun, args, kwargs):
    """Full argname->value mapping via the real signature when available."""
    import inspect
    try:
        bound = inspect.signature(fun).bind(*args, **kwargs)
        bound.apply_defaults()
        return dict(bound.arguments)
    except (TypeError, ValueError):
        return None


class _BlockExporter:
    # op name -> (tensor arg names in ONNX input order, attr arg names)
    SPECS = {
        "convolution": (("data", "weight", "bias"),
                        ("kernel", "stride", "dilate", "pad", "num_group")),
        "fully_connected": (("data", "weight", "bias"), ("flatten",)),
        "batch_norm": (("data", "gamma", "beta", "moving_mean",
                        "moving_var"), ("eps",)),
        "activation": (("data",), ("act_type",)),
        "leaky_relu": (("data", "gamma"), ("act_type", "slope")),
        "pooling": (("data",), ("kernel", "stride", "pad", "pool_type",
                                "global_pool", "count_include_pad")),
        "embedding": (("data", "weight"), ()),
        "layer_norm": (("data", "gamma", "beta"), ("axis", "eps")),
        "softmax": (("data",), ("axis",)),
        "log_softmax": (("data",), ("axis",)),
    }
    # capture name -> converter key
    ALIAS = {"activation": "Activation", "convolution": "Convolution",
             "batch_norm": "BatchNorm", "fully_connected": "FullyConnected",
             "pooling": "Pooling", "embedding": "Embedding",
             "leaky_relu": "LeakyReLU", "layer_norm": "LayerNorm",
             "add": "broadcast_add", "subtract": "broadcast_sub",
             "multiply": "broadcast_mul", "true_divide": "broadcast_div",
             "divide": "broadcast_div"}

    def __init__(self):
        self.nodes = []
        self.initializers = []
        self.extra_init = []
        self.names = {}          # buffer id -> onnx name
        self.counter = 0
        self.inlined = set()

    def fresh(self, base):
        self.counter += 1
        return f"{base}_{self.counter}"

    def resolve(self, nd):
        """Name for an input NDArray; unseen arrays become constant
        initializers (values baked at export, like reference params)."""
        key = _buf_id(nd)
        if key in self.names:
            return self.names[key]
        nm = self.fresh("const")
        self.initializers.append(P.tensor_proto(
            nm, onp.asarray(nd._data)))
        self.names[key] = nm
        return nm

    def handle(self, name, fun, args, kwargs, res):
        in_leaves = _nd_leaves((args, kwargs))
        out_leaves = _nd_leaves(res)
        if not in_leaves:
            # creation op (zeros/arange/...): bake the result
            for o in out_leaves:
                self.names.setdefault(_buf_id(o), None)
            for o in out_leaves:
                key = _buf_id(o)
                if self.names[key] is None:
                    nm = self.fresh(name or "const")
                    self.initializers.append(
                        P.tensor_proto(nm, onp.asarray(o._data)))
                    self.names[key] = nm
            return
        nm = self.fresh(name)
        if name.startswith("rnn_"):
            self._handle_rnn(nm, name, args, res)
            return
        if name == "reshape":
            # the target shape often lives in a closure (Flatten-style
            # lambdas); the capture is shape-specialized anyway, so the
            # recorded RESULT's shape is the truth
            ins = [self.resolve(in_leaves[0])]
            attrs = {"shape": tuple(int(s) for s in out_leaves[0].shape)}
            self.nodes.extend(_CONVERTERS["Reshape"](
                nm, ins, attrs, extra_init=self.extra_init))
            self.names[_buf_id(out_leaves[0])] = nm
            return
        if name == "einsum":
            # ONNX has a first-class Einsum (opset 12+); the equation is
            # the first positional arg
            eq = next(a for a in args if isinstance(a, str))
            ins = [self.resolve(x) for x in in_leaves]
            self.nodes.append(P.node_proto(
                "Einsum", ins, [nm], nm, [P.attr_string("equation", eq)]))
            self.names[_buf_id(out_leaves[0])] = nm
            return
        if name == "getitem":
            self._handle_getitem(nm, fun, in_leaves, out_leaves)
            return
        if name in ("concatenate", "concat", "Concat"):
            # the axis lives in the frontend lambda's closure, so recover
            # it from shapes: the one axis where input dims sum to the
            # output while all others match
            ins = [self.resolve(x) for x in in_leaves]
            out_shape = out_leaves[0].shape
            in_shapes = [x.shape for x in in_leaves]
            if any(len(s) != len(out_shape) for s in in_shapes):
                # np.concatenate(axis=None) flatten semantics — no ONNX
                # Concat equivalent; fail loudly rather than export wrong
                raise NotImplementedError(
                    "concatenate with axis=None (rank-collapsing) has no "
                    "ONNX Concat equivalent")
            axis = next(
                (ax for ax in range(len(out_shape))
                 if sum(s[ax] for s in in_shapes) == out_shape[ax]
                 and all(s[:ax] + s[ax + 1:] ==
                         in_shapes[0][:ax] + in_shapes[0][ax + 1:]
                         for s in in_shapes)), None)
            if axis is None:
                raise NotImplementedError(
                    f"cannot infer concat axis: {in_shapes} -> {out_shape}")
            self.nodes.extend(_CONVERTERS["Concat"](
                nm, ins, {"dim": int(axis)}))
            self.names[_buf_id(out_leaves[0])] = nm
            return
        bound = _bind(fun, args, kwargs)
        spec = self.SPECS.get(name)
        if spec is not None and bound is not None:
            tensor_names, attr_names = spec
            ins = []
            for t in tensor_names:
                v = bound.get(t)
                ins.append(self.resolve(v) if v is not None and
                           hasattr(v, "_data") else None)
            while ins and ins[-1] is None:
                ins.pop()
            attrs = {k: bound[k] for k in attr_names
                     if bound.get(k) is not None}
        else:
            # no spec: recover scalar parameters through the real
            # signature so positionally-passed attrs (np.clip(x, 0, 6),
            # np.mean(x, 1)) survive export instead of silently dropping
            ins, attrs = self._generic_ins_attrs(name, fun, args, kwargs,
                                                 in_leaves)
        conv = _CONVERTERS.get(self.ALIAS.get(name, name)) or \
            _CONVERTERS.get(name)
        if conv is None:
            raise NotImplementedError(
                f"no ONNX converter for captured op {name!r}")
        try:
            new_nodes = conv(nm, ins, attrs, extra_init=self.extra_init)
        except TypeError:
            new_nodes = conv(nm, ins, attrs)
        self.nodes.extend(new_nodes)
        outs = [nm] + [f"{nm}_{i}" for i in range(1, len(out_leaves))]
        for o, onm in zip(out_leaves, outs):
            self.names[_buf_id(o)] = onm

    _ATTR_ALIAS = {"min": "a_min", "max": "a_max", "a": None, "x": None,
                   "arr": None, "data": None}
    _SIMPLE = (int, float, bool, str, tuple, list)
    # elementwise binaries: a scalar operand is a CONSTANT INPUT (ONNX
    # tensor), never an attribute
    _BINARY = {"add", "subtract", "multiply", "true_divide", "divide",
               "power", "maximum", "minimum", "mod", "equal", "greater",
               "less", "matmul", "dot", "_plus", "_minus", "_mul", "_div",
               "_power", "broadcast_add", "broadcast_sub", "broadcast_mul",
               "broadcast_div", "where"}

    def _scalar_const(self, v):
        nm = self.fresh("const")
        self.initializers.append(P.tensor_proto(
            nm, onp.asarray(v, onp.float32)))
        return nm

    def _generic_ins_attrs(self, name, fun, args, kwargs, in_leaves):
        if name in self._BINARY:
            ins = [self.resolve(a) if hasattr(a, "_data")
                   else self._scalar_const(a) for a in args]
            return ins, {}
        bound = _bind(fun, args, kwargs)
        if bound is None:
            return ([self.resolve(x) for x in in_leaves],
                    {k: v for k, v in kwargs.items()
                     if isinstance(v, self._SIMPLE)})
        ins, attrs = [], {}
        for k, v in bound.items():
            if hasattr(v, "_data"):
                ins.append(self.resolve(v))
            elif isinstance(v, self._SIMPLE) and k not in ("out", "order",
                                                           "where"):
                key = self._ATTR_ALIAS.get(k, k)
                if key is not None:
                    attrs[key] = v
        return ins, attrs

    def _handle_getitem(self, nm, fun, in_leaves, out_leaves):
        """NDArray.__getitem__ capture: the index is the lambda's closure
        cell (`ndarray.py:315-317`).  Basic indexing (ints/slices) lowers
        to ONNX Slice + Squeeze; anything fancier is rejected."""
        cells = getattr(fun, "__closure__", None) or ()
        if len(cells) != 1:
            raise NotImplementedError("getitem index not recoverable")
        key = cells[0].cell_contents
        key = key if isinstance(key, tuple) else (key,)
        src = in_leaves[0]
        starts, ends, axes, squeeze = [], [], [], []
        big = 2 ** 31 - 1
        for ax, k in enumerate(key):
            if isinstance(k, int):
                kk = k if k >= 0 else k + src.shape[ax]
                starts.append(kk)
                ends.append(kk + 1)
                axes.append(ax)
                squeeze.append(ax)
            elif isinstance(k, slice):
                if k.step not in (None, 1):
                    raise NotImplementedError("strided getitem export")
                if k.start is None and k.stop is None:
                    continue
                starts.append(int(k.start or 0))
                ends.append(big if k.stop is None else int(k.stop))
                axes.append(ax)
            else:
                raise NotImplementedError(
                    f"getitem export supports ints/slices, got {k!r}")
        cur = self.resolve(src)
        if axes:
            for suffix, vals in (("_starts", starts), ("_ends", ends),
                                 ("_axes", axes)):
                self.extra_init.append(P.tensor_proto(
                    nm + suffix, onp.asarray(vals, onp.int64)))
            self.nodes.append(P.node_proto(
                "Slice", [cur, nm + "_starts", nm + "_ends", nm + "_axes"],
                [nm + "_sl" if squeeze else nm],
                nm + "_sl" if squeeze else nm))
            cur = nm + "_sl" if squeeze else nm
        if squeeze:
            self.extra_init.append(P.tensor_proto(
                nm + "_sq", onp.asarray(squeeze, onp.int64)))
            self.nodes.append(P.node_proto(
                "Squeeze", [cur, nm + "_sq"], [nm], nm))
        elif not axes:
            self.nodes.append(P.node_proto("Identity", [cur], [nm], nm))
        self.names[_buf_id(out_leaves[0])] = nm

    def _handle_rnn(self, nm, name, args, res):
        mode = name[len("rnn_"):]
        if mode.endswith("_bi"):
            raise NotImplementedError(
                "bidirectional RNN ONNX export not supported")
        x, h0, c0 = args[0], args[1], args[2]
        flat_w = args[3:]
        assert len(flat_w) % 4 == 0
        weights = []
        for i in range(0, len(flat_w), 4):
            wi, bi, wh, bh = (onp.asarray(w._data) for w in flat_w[i:i + 4])
            weights.append((wi, bi, wh, bh))
        hidden = weights[0][2].shape[1]
        ins = [self.resolve(x), self.resolve(h0), self.resolve(c0)]
        self.nodes.extend(_rnn_onnx_nodes(
            nm, ins, {"mode": mode, "hidden": hidden},
            self.extra_init, weights))
        out_leaves = _nd_leaves(res)
        outs = [nm] + [f"{nm}_{i}" for i in range(1, len(out_leaves))]
        for o, onm in zip(out_leaves, outs):
            self.names[_buf_id(o)] = onm


def export_block(block, example_args, onnx_file_path="model.onnx",
                 input_names=None, opset_version=17):
    """Export a Gluon (Hybrid)Block to ONNX by capturing one eval-mode
    forward (reference flow: hybridize trace -> symbol -> mx2onnx
    `export_model`).  ``example_args``: tuple of NDArrays fixing input
    shapes.  Parameters become initializers named by `collect_params`
    keys."""
    from ...ndarray.ndarray import NDArray
    from ...ops.invoke import _CaptureScope

    if not isinstance(example_args, (list, tuple)):
        example_args = (example_args,)
    example_args = [a if isinstance(a, NDArray) else NDArray(a)
                    for a in example_args]
    block(*example_args)  # ensure shapes/params initialized
    ex = _BlockExporter()

    input_names = input_names or [f"data{i}" if i else "data"
                                  for i in range(len(example_args))]
    for a, nm in zip(example_args, input_names):
        ex.names[_buf_id(a)] = nm

    # parameters by identity of their per-device buffers
    params = block.collect_params()
    param_names = {}
    for pname, p in params.items():
        try:
            datas = p.list_data()
        except Exception:  # mxlint: disable=swallowed-exception -- deferred/uninitialized params have no device copies yet; exporting them as absent is the correct outcome
            datas = [p.data()] if p._data is not None else []
        for d in datas:
            ex.names[_buf_id(d)] = pname
            param_names[_buf_id(d)] = pname

    with _CaptureScope() as cap:
        out = block(*example_args)
    for entry in cap.entries:
        ex.handle(*entry)

    out_leaves = _nd_leaves(out)
    g_outputs = []
    for o in out_leaves:
        onm = ex.names.get(_buf_id(o))
        if onm is None:
            raise RuntimeError("block output was not produced by a "
                               "captured op (non-invoke path?)")
        g_outputs.append(P.value_info(onm, tuple(o.shape)))

    # parameter initializers
    emitted = set()
    for pname, p in params.items():
        try:
            datas = p.list_data()
        except Exception:  # mxlint: disable=swallowed-exception -- deferred/uninitialized params have no device copies yet; exporting them as absent is the correct outcome
            datas = [p.data()] if p._data is not None else []
        for d in datas:
            if ex.names.get(_buf_id(d)) == pname and pname not in emitted:
                ex.initializers.append(
                    P.tensor_proto(pname, onp.asarray(d._data)))
                emitted.add(pname)

    g_inputs = [P.value_info(nm, tuple(a.shape))
                for a, nm in zip(example_args, input_names)]
    graph = P.graph_proto(ex.nodes, "mxnet_tpu_block",
                          ex.initializers + ex.extra_init,
                          g_inputs, g_outputs)
    blob = P.model_proto(graph, opset=opset_version)
    with open(onnx_file_path, "wb") as f:
        f.write(blob)
    return onnx_file_path
