"""Minimal protobuf wire codec for the ONNX message subset.

Reference: `python/mxnet/contrib/onnx/` depends on the `onnx` pip
package; this environment has none, so the ModelProto/GraphProto wire
format (protobuf encoding per `onnx/onnx.proto`, a stable public
schema) is encoded/decoded directly.  Only the fields the converters in
`mx2onnx.py` / `onnx2mx.py` produce and consume are modeled.

Field numbers below are copied from onnx.proto (public schema; stable
across ONNX releases by protobuf compatibility rules).
"""
from __future__ import annotations

import struct

# -- wire primitives ---------------------------------------------------------


def _varint(n):
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field, wire):
    return _varint((field << 3) | wire)


def f_varint(field, value):
    if value < 0:  # two's-complement 64-bit, as protobuf int64 encodes
        value += 1 << 64
    return _tag(field, 0) + _varint(value)


def f_bytes(field, data):
    return _tag(field, 2) + _varint(len(data)) + data


def f_string(field, s):
    return f_bytes(field, s.encode())


def f_msg(field, payload):
    return f_bytes(field, payload)


def f_float(field, v):
    return _tag(field, 5) + struct.pack("<f", v)


def f_packed_int64(field, values):
    payload = b"".join(_varint(v + (1 << 64) if v < 0 else v)
                       for v in values)
    return f_bytes(field, payload)


class Reader:
    def __init__(self, data):
        self.b = memoryview(data)
        self.o = 0

    def eof(self):
        return self.o >= len(self.b)

    def varint(self):
        shift = 0
        val = 0
        while True:
            byte = self.b[self.o]
            self.o += 1
            val |= (byte & 0x7F) << shift
            if not byte & 0x80:
                return val
            shift += 7

    def field(self):
        """-> (field_number, wire_type, value) where value is int for
        varint/fixed, bytes for length-delimited."""
        key = self.varint()
        field, wire = key >> 3, key & 7
        if wire == 0:
            return field, wire, self.varint()
        if wire == 2:
            ln = self.varint()
            out = bytes(self.b[self.o:self.o + ln])
            self.o += ln
            return field, wire, out
        if wire == 5:
            out = struct.unpack_from("<I", self.b, self.o)[0]
            self.o += 4
            return field, wire, out
        if wire == 1:
            out = struct.unpack_from("<Q", self.b, self.o)[0]
            self.o += 8
            return field, wire, out
        raise ValueError(f"unsupported wire type {wire}")


def signed64(v):
    return v - (1 << 64) if v >= (1 << 63) else v


def parse_packed_int64(data):
    r = Reader(data)
    out = []
    while not r.eof():
        out.append(signed64(r.varint()))
    return out


def f32_from_bits(bits):
    return struct.unpack("<f", struct.pack("<I", bits))[0]


def parse_packed_f32(data):
    """Packed repeated float payload (proto3 default packing)."""
    return list(struct.unpack(f"<{len(data) // 4}f", data))


# -- ONNX message builders (field numbers from onnx.proto) -------------------

# TensorProto.DataType
FLOAT, INT64, INT32 = 1, 7, 6


def tensor_proto(name, arr):
    """TensorProto: dims=1(repeated int64), data_type=2, raw_data=9,
    name=8."""
    import numpy as onp

    # NOT ascontiguousarray: it promotes 0-d scalars to shape (1,), which
    # corrupts the dims field (r4 fuzz finding)
    a = onp.asarray(arr, order="C")
    if a.dtype == onp.float32:
        dt = FLOAT
    elif a.dtype == onp.int64:
        dt = INT64
    elif a.dtype == onp.int32:
        dt = INT32
    else:
        a = a.astype(onp.float32)
        dt = FLOAT
    out = b"".join([
        b"".join(f_varint(1, d) for d in a.shape),
        f_varint(2, dt),
        f_string(8, name),
        f_bytes(9, a.tobytes()),
    ])
    return out


def attr_int(name, v):
    """AttributeProto: name=1, type=20 (INT=2), i=3."""
    return f_string(1, name) + f_varint(3, v) + f_varint(20, 2)


def attr_float(name, v):
    return f_string(1, name) + f_float(2, v) + f_varint(20, 1)


def attr_ints(name, vals):
    """AttributeProto INTS (type enum 7): repeated int64 `ints` is FIELD 8
    in onnx.proto (field 7 is `floats`) — r4 golden-bytes audit fix; the
    pre-r4 codec wrote field 7 and was unreadable by external consumers."""
    return f_string(1, name) + \
        b"".join(f_varint(8, v) for v in vals) + f_varint(20, 7)


def attr_string(name, s):
    return f_string(1, name) + f_bytes(4, s.encode()) + f_varint(20, 3)


def attr_strings(name, vals):
    """AttributeProto STRINGS (type enum 8): repeated bytes `strings` is
    FIELD 9 in onnx.proto (field 8 is `ints`) — r4 golden-bytes audit
    fix, same self-consistent-but-wrong pairing as `attr_ints`."""
    return f_string(1, name) + \
        b"".join(f_bytes(9, v.encode()) for v in vals) + f_varint(20, 8)


def node_proto(op_type, inputs, outputs, name="", attrs=()):
    """NodeProto: input=1, output=2, name=3, op_type=4, attribute=5."""
    return b"".join(
        [f_string(1, i) for i in inputs] +
        [f_string(2, o) for o in outputs] +
        [f_string(3, name), f_string(4, op_type)] +
        [f_msg(5, a) for a in attrs])


def value_info(name, shape, elem_type=FLOAT):
    """ValueInfoProto: name=1, type=2 {tensor_type=1 {elem_type=1,
    shape=2 {dim=1 {dim_value=1}}}}."""
    dims = b"".join(
        f_msg(1, f_varint(1, d)) for d in shape)
    ttype = f_varint(1, elem_type) + f_msg(2, dims)
    return f_string(1, name) + f_msg(2, f_msg(1, ttype))


def graph_proto(nodes, name, initializers, inputs, outputs):
    """GraphProto: node=1, name=2, initializer=5, input=11, output=12."""
    return b"".join(
        [f_msg(1, n) for n in nodes] +
        [f_string(2, name)] +
        [f_msg(5, t) for t in initializers] +
        [f_msg(11, i) for i in inputs] +
        [f_msg(12, o) for o in outputs])


def model_proto(graph, producer="mxnet_tpu", opset=17):
    """ModelProto: ir_version=1, producer_name=2, graph=7,
    opset_import=8 {domain=1, version=2}."""
    opset_id = f_string(1, "") + f_varint(2, opset)
    return b"".join([
        f_varint(1, 8),            # IR version 8
        f_string(2, producer),
        f_msg(7, graph),
        f_msg(8, opset_id),
    ])
