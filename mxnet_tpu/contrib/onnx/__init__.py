"""ONNX interchange (reference: `python/mxnet/contrib/onnx/`).

``export_model(sym, params, ...)`` writes an ONNX ModelProto;
``import_model(file)`` returns ``(sym, arg_params, aux_params)``.  The
protobuf wire format is encoded directly (`proto.py`) because the
``onnx`` package is not available in this environment.
"""
from .mx2onnx import export_model, export_block
from .onnx2mx import import_model

__all__ = ["export_model", "export_block", "import_model"]
