"""ONNX -> Symbol graph import.

Reference: `python/mxnet/contrib/onnx/onnx2mx/` (`import_model`,
`import_onnx.py` GraphProto + `_op_translations.py`).  Returns
``(sym, arg_params, aux_params)`` exactly like the reference, so
``import_model`` output feeds `sym.bind`/`eval` or `SymbolBlock`-style
use.  Wire parsing by `proto.py`.
"""
from __future__ import annotations

import numpy as onp

from . import proto as P

__all__ = ["import_model"]


# -- protobuf message readers ------------------------------------------------

def _fields(data):
    r = P.Reader(data)
    while not r.eof():
        yield r.field()


def _parse_attr(data):
    name = None
    out = {}
    for f, _w, v in _fields(data):
        if f == 1:
            name = v.decode()
        elif f == 2:
            out["f"] = P.f32_from_bits(v) if isinstance(v, int) else v
        elif f == 3:
            out["i"] = P.signed64(v)
        elif f == 4:
            out["s"] = v.decode()
        elif f == 5:
            out["t"] = _parse_tensor(v)
        elif f == 7:
            out.setdefault("ints", []).append(P.signed64(v))
    val = out.get("ints")
    if val is None:
        val = out.get("i", out.get("f", out.get("s", out.get("t"))))
    return name, val


_NP_OF = {P.FLOAT: onp.float32, P.INT64: onp.int64, P.INT32: onp.int32,
          11: onp.float64, 10: onp.float16, 9: onp.bool_}


def _parse_tensor(data):
    dims, dtype, raw, name = [], P.FLOAT, b"", ""
    floats, int32s, int64s = [], [], []
    for f, _w, v in _fields(data):
        if f == 1:
            dims.append(P.signed64(v))
        elif f == 2:
            dtype = v
        elif f == 8:
            name = v.decode()
        elif f == 9:
            raw = v
        elif f == 4:
            floats.append(P.f32_from_bits(v))
        elif f == 5:
            int32s.append(P.signed64(v))
        elif f == 7:
            int64s.append(P.signed64(v))
    np_dt = _NP_OF.get(dtype, onp.float32)
    if raw:
        arr = onp.frombuffer(raw, dtype=np_dt)
    elif floats:
        arr = onp.asarray(floats, onp.float32)
    elif int64s:
        arr = onp.asarray(int64s, onp.int64)
    elif int32s:
        arr = onp.asarray(int32s, onp.int32)
    else:
        arr = onp.zeros(0, np_dt)
    return name, arr.reshape(dims) if dims else arr


def _parse_node(data):
    inputs, outputs, name, op, attrs = [], [], "", "", {}
    for f, _w, v in _fields(data):
        if f == 1:
            inputs.append(v.decode())
        elif f == 2:
            outputs.append(v.decode())
        elif f == 3:
            name = v.decode()
        elif f == 4:
            op = v.decode()
        elif f == 5:
            k, val = _parse_attr(v)
            attrs[k] = val
    return dict(op=op, name=name, inputs=inputs, outputs=outputs,
                attrs=attrs)


def _parse_value_info(data):
    name = ""
    for f, _w, v in _fields(data):
        if f == 1:
            name = v.decode()
    return name


def _parse_graph(data):
    nodes, inits, g_in, g_out = [], {}, [], []
    for f, _w, v in _fields(data):
        if f == 1:
            nodes.append(_parse_node(v))
        elif f == 5:
            nm, arr = _parse_tensor(v)
            inits[nm] = arr
        elif f == 11:
            g_in.append(_parse_value_info(v))
        elif f == 12:
            g_out.append(_parse_value_info(v))
    return nodes, inits, g_in, g_out


def _parse_model(data):
    for f, _w, v in _fields(data):
        if f == 7:
            return _parse_graph(v)
    raise ValueError("no graph in ONNX model")


# -- ONNX op -> Symbol builders ---------------------------------------------


def _build(node, ins, consts, sym_mod):
    op = node["op"]
    a = node["attrs"]

    def tup(key, default=None):
        v = a.get(key, default)
        return tuple(v) if v is not None else None

    if op == "Gemm":
        assert a.get("transB", 0) == 1, "only transB Gemm (FC) supported"
        return sym_mod.FullyConnected(
            ins[0], ins[1], ins[2] if len(ins) > 2 else None,
            num_hidden=None, no_bias=len(ins) <= 2, flatten=False)
    if op == "MatMul":
        return sym_mod.dot(ins[0], ins[1])
    if op == "Conv":
        pads = tup("pads") or (0, 0, 0, 0)
        nsp = len(pads) // 2
        return sym_mod.Convolution(
            ins[0], ins[1], ins[2] if len(ins) > 2 else None,
            kernel=tup("kernel_shape"),
            stride=tup("strides") or (1,) * nsp,
            dilate=tup("dilations") or (1,) * nsp,
            pad=pads[:nsp], num_filter=None,
            num_group=int(a.get("group", 1)),
            no_bias=len(ins) <= 2)
    if op == "BatchNormalization":
        return sym_mod.BatchNorm(
            ins[0], ins[1], ins[2], ins[3], ins[4],
            eps=float(a.get("epsilon", 1e-5)),
            momentum=float(a.get("momentum", 0.9)), fix_gamma=False,
            use_global_stats=True)
    if op in ("MaxPool", "AveragePool"):
        pads = tup("pads") or (0, 0, 0, 0)
        nsp = len(pads) // 2
        return sym_mod.Pooling(
            ins[0], kernel=tup("kernel_shape"),
            stride=tup("strides") or tup("kernel_shape"),
            pad=pads[:nsp],
            pool_type="max" if op == "MaxPool" else "avg",
            count_include_pad=bool(a.get("count_include_pad", 1)))
    if op in ("GlobalMaxPool", "GlobalAveragePool"):
        return sym_mod.Pooling(
            ins[0], global_pool=True,
            pool_type="max" if "Max" in op else "avg")
    if op == "Flatten":
        return sym_mod.Flatten(ins[0])
    if op == "Softmax":
        return sym_mod.softmax(ins[0], axis=int(a.get("axis", -1)))
    if op == "Concat":
        return sym_mod.Concat(*ins, dim=int(a.get("axis", 1)))
    if op == "Gather":
        return sym_mod.take(ins[0], ins[1],
                            axis=int(a.get("axis", 0)))
    if op == "Reshape":
        shape = consts.get(node["inputs"][1])
        if shape is None:
            raise NotImplementedError("dynamic Reshape shape input")
        return sym_mod.Reshape(ins[0], shape=tuple(int(s) for s in shape))
    if op == "Transpose":
        perm = tup("perm")
        return sym_mod.transpose(ins[0], axes=perm)
    if op == "LeakyRelu":
        return sym_mod.LeakyReLU(ins[0], act_type="leaky",
                                 slope=float(a.get("alpha", 0.01)))
    if op == "Elu":
        return sym_mod.LeakyReLU(ins[0], act_type="elu",
                                 slope=float(a.get("alpha", 1.0)))
    if op == "PRelu":
        return sym_mod.LeakyReLU(ins[0], ins[1], act_type="prelu")
    if op == "Softplus":
        return sym_mod.Activation(ins[0], act_type="softrelu")
    simple = {"Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh",
              "Exp": "exp", "Log": "log", "Sqrt": "sqrt", "Abs": "abs",
              "Neg": "negative", "Identity": "identity",
              "Add": "broadcast_add", "Sub": "broadcast_sub",
              "Mul": "broadcast_mul", "Div": "broadcast_div",
              "Max": "maximum", "Min": "minimum",
              "Softsign": "softsign"}
    if op in simple:
        return getattr(sym_mod, simple[op])(*ins)
    raise NotImplementedError(f"no importer for ONNX op {op!r}")


def import_model(model_file):
    """ONNX file -> (sym, arg_params, aux_params) (reference
    `onnx2mx.import_model` contract)."""
    from ...ndarray.ndarray import NDArray
    from ... import symbol as sym_mod

    with open(model_file, "rb") as f:
        nodes, inits, g_in, g_out = _parse_model(f.read())

    env = {}
    for name in g_in:
        env[name] = sym_mod.var(name)
    for name in inits:
        env.setdefault(name, sym_mod.var(name))

    aux_names = set()
    for node in nodes:
        ins = []
        for i in node["inputs"]:
            if i not in env:
                env[i] = sym_mod.var(i)
            ins.append(env[i])
        if node["op"] == "BatchNormalization":
            # running mean/var (inputs 3,4) are aux state, as in the
            # reference importer
            aux_names.update(node["inputs"][3:5])
        out = _build(node, ins, inits, sym_mod)
        out._name = node["outputs"][0]
        env[node["outputs"][0]] = out

    outputs = [env[o] for o in g_out]
    out_sym = outputs[0] if len(outputs) == 1 else sym_mod.Group(outputs)

    arg_params, aux_params = {}, {}
    for name, arr in inits.items():
        if name.startswith("const_") or name.endswith("_shape"):
            continue  # inlined constants consumed at build time
        target = aux_params if name in aux_names else arg_params
        target[name] = NDArray(onp.ascontiguousarray(arr))
    return out_sym, arg_params, aux_params
