"""ONNX -> Symbol graph import.

Reference: `python/mxnet/contrib/onnx/onnx2mx/` (`import_model`,
`import_onnx.py` GraphProto + `_op_translations.py`).  Returns
``(sym, arg_params, aux_params)`` exactly like the reference, so
``import_model`` output feeds `sym.bind`/`eval` or `SymbolBlock`-style
use.  Wire parsing by `proto.py`.
"""
from __future__ import annotations

import numpy as onp

from . import proto as P

__all__ = ["import_model"]


# -- protobuf message readers ------------------------------------------------

def _fields(data):
    r = P.Reader(data)
    while not r.eof():
        yield r.field()


_ATTR_STRINGS_ENUM = 8  # AttributeProto.AttributeType.STRINGS


def _parse_attr(data):
    name = None
    out = {}
    atype = None
    f8_bytes = []  # field 8 wire 2: packed ints (official) OR legacy strings
    for f, _w, v in _fields(data):
        if f == 1:
            name = v.decode()
        elif f == 2:
            out["f"] = P.f32_from_bits(v) if isinstance(v, int) else v
        elif f == 3:
            out["i"] = P.signed64(v)
        elif f == 4:
            out["s"] = v.decode()
        elif f == 5:
            out["t"] = _parse_tensor(v)
        elif f == 7 and _w == 0:
            # legacy pre-r4 exports misfiled ints here (field 7 is
            # `floats` in onnx.proto); wire type 0 disambiguates
            out.setdefault("ints", []).append(P.signed64(v))
        elif f == 7 and _w == 5:
            out.setdefault("floats", []).append(P.f32_from_bits(v))
        elif f == 7 and _w == 2:
            # proto3 packed repeated float
            out.setdefault("floats", []).extend(
                P.parse_packed_f32(v))
        elif f == 8 and _w == 0:
            out.setdefault("ints", []).append(P.signed64(v))
        elif f == 8 and _w == 2:
            f8_bytes.append(v)
        elif f == 9 and _w == 2:
            out.setdefault("strings", []).append(v.decode())
        elif f == 20 and _w == 0:
            atype = v
    for v in f8_bytes:
        # the type enum (field 20) disambiguates: STRINGS here means a
        # legacy pre-r4 export that misfiled strings at field 8;
        # otherwise it is official proto3 packed int64
        if atype == _ATTR_STRINGS_ENUM:
            out.setdefault("strings", []).append(v.decode())
        else:
            out.setdefault("ints", []).extend(P.parse_packed_int64(v))
    val = out.get("ints", out.get("strings", out.get("floats")))
    if val is None:
        val = out.get("i", out.get("f", out.get("s", out.get("t"))))
    return name, val


_NP_OF = {P.FLOAT: onp.float32, P.INT64: onp.int64, P.INT32: onp.int32,
          11: onp.float64, 10: onp.float16, 9: onp.bool_}


def _parse_tensor(data):
    # repeated scalar fields (dims, float_data, int32/int64_data) arrive
    # PACKED (wire 2) from official proto3 serializers and unpacked
    # (wire 0/5) from this codec — both are valid wire format and both
    # must parse (r4 review finding)
    dims, dtype, raw, name = [], P.FLOAT, b"", ""
    floats, int32s, int64s = [], [], []
    for f, _w, v in _fields(data):
        if f == 1:
            if _w == 2:
                dims.extend(P.parse_packed_int64(v))
            else:
                dims.append(P.signed64(v))
        elif f == 2:
            dtype = v
        elif f == 8:
            name = v.decode()
        elif f == 9:
            raw = v
        elif f == 4:
            if _w == 2:
                floats.extend(P.parse_packed_f32(v))
            else:
                floats.append(P.f32_from_bits(v))
        elif f == 5:
            if _w == 2:
                int32s.extend(P.parse_packed_int64(v))
            else:
                int32s.append(P.signed64(v))
        elif f == 7:
            if _w == 2:
                int64s.extend(P.parse_packed_int64(v))
            else:
                int64s.append(P.signed64(v))
    np_dt = _NP_OF.get(dtype, onp.float32)
    if raw:
        arr = onp.frombuffer(raw, dtype=np_dt)
    elif floats:
        arr = onp.asarray(floats, onp.float32)
    elif int64s:
        arr = onp.asarray(int64s, onp.int64)
    elif int32s:
        arr = onp.asarray(int32s, onp.int32)
    else:
        arr = onp.zeros(0, np_dt)
    # no dims + one element => scalar TensorProto (absent repeated field
    # = rank 0); a dataless placeholder stays the empty array
    if dims or arr.size == 1:
        arr = arr.reshape(dims)
    return name, arr


def _parse_node(data):
    inputs, outputs, name, op, attrs = [], [], "", "", {}
    for f, _w, v in _fields(data):
        if f == 1:
            inputs.append(v.decode())
        elif f == 2:
            outputs.append(v.decode())
        elif f == 3:
            name = v.decode()
        elif f == 4:
            op = v.decode()
        elif f == 5:
            k, val = _parse_attr(v)
            attrs[k] = val
    return dict(op=op, name=name, inputs=inputs, outputs=outputs,
                attrs=attrs)


def _parse_value_info(data):
    name = ""
    for f, _w, v in _fields(data):
        if f == 1:
            name = v.decode()
    return name


def _parse_graph(data):
    nodes, inits, g_in, g_out = [], {}, [], []
    for f, _w, v in _fields(data):
        if f == 1:
            nodes.append(_parse_node(v))
        elif f == 5:
            nm, arr = _parse_tensor(v)
            inits[nm] = arr
        elif f == 11:
            g_in.append(_parse_value_info(v))
        elif f == 12:
            g_out.append(_parse_value_info(v))
    return nodes, inits, g_in, g_out


def _parse_model(data):
    for f, _w, v in _fields(data):
        if f == 7:
            return _parse_graph(v)
    raise ValueError("no graph in ONNX model")


# -- ONNX op -> Symbol builders ---------------------------------------------


def _build(node, ins, consts, sym_mod, shape_of=None):
    op = node["op"]
    a = node["attrs"]
    shape_of = shape_of or {}

    def tup(key, default=None):
        v = a.get(key, default)
        return tuple(v) if v is not None else None

    if op == "Gemm":
        assert a.get("transB", 0) == 1, "only transB Gemm (FC) supported"
        return sym_mod.FullyConnected(
            ins[0], ins[1], ins[2] if len(ins) > 2 else None,
            num_hidden=None, no_bias=len(ins) <= 2, flatten=False)
    if op == "MatMul":
        return sym_mod.dot(ins[0], ins[1])
    if op == "Conv":
        pads = tup("pads") or (0, 0, 0, 0)
        nsp = len(pads) // 2
        return sym_mod.Convolution(
            ins[0], ins[1], ins[2] if len(ins) > 2 else None,
            kernel=tup("kernel_shape"),
            stride=tup("strides") or (1,) * nsp,
            dilate=tup("dilations") or (1,) * nsp,
            pad=pads[:nsp], num_filter=None,
            num_group=int(a.get("group", 1)),
            no_bias=len(ins) <= 2)
    if op == "BatchNormalization":
        return sym_mod.BatchNorm(
            ins[0], ins[1], ins[2], ins[3], ins[4],
            eps=float(a.get("epsilon", 1e-5)),
            momentum=float(a.get("momentum", 0.9)), fix_gamma=False,
            use_global_stats=True)
    if op in ("MaxPool", "AveragePool"):
        pads = tup("pads") or (0, 0, 0, 0)
        nsp = len(pads) // 2
        return sym_mod.Pooling(
            ins[0], kernel=tup("kernel_shape"),
            stride=tup("strides") or tup("kernel_shape"),
            pad=pads[:nsp],
            pool_type="max" if op == "MaxPool" else "avg",
            count_include_pad=bool(a.get("count_include_pad", 1)))
    if op in ("GlobalMaxPool", "GlobalAveragePool"):
        return sym_mod.Pooling(
            ins[0], global_pool=True,
            pool_type="max" if "Max" in op else "avg")
    if op == "Flatten":
        return sym_mod.Flatten(ins[0])
    if op == "Softmax":
        return sym_mod.softmax(ins[0], axis=int(a.get("axis", -1)))
    if op == "Concat":
        return sym_mod.Concat(*ins, dim=int(a.get("axis", 1)))
    if op == "Gather":
        return sym_mod.take(ins[0], ins[1],
                            axis=int(a.get("axis", 0)))
    if op == "Reshape":
        shape = consts.get(node["inputs"][1])
        if shape is None:
            raise NotImplementedError("dynamic Reshape shape input")
        return sym_mod.Reshape(ins[0], shape=tuple(int(s) for s in shape))
    if op == "Transpose":
        perm = tup("perm")
        return sym_mod.transpose(ins[0], axes=perm)
    if op == "LeakyRelu":
        return sym_mod.LeakyReLU(ins[0], act_type="leaky",
                                 slope=float(a.get("alpha", 0.01)))
    if op == "Elu":
        return sym_mod.LeakyReLU(ins[0], act_type="elu",
                                 slope=float(a.get("alpha", 1.0)))
    if op == "PRelu":
        return sym_mod.LeakyReLU(ins[0], ins[1], act_type="prelu")
    if op == "Softplus":
        return sym_mod.Activation(ins[0], act_type="softrelu")
    if op == "Pad":
        pads = consts.get(node["inputs"][1])
        if pads is None:
            raise NotImplementedError("dynamic Pad input")
        n = len(pads) // 2
        # legacy flat layout: (before0, after0, before1, after1, ...)
        pw = []
        for i in range(n):
            pw.extend([int(pads[i]), int(pads[i + n])])
        mode = a.get("mode", "constant")
        cval = 0.0
        if len(node["inputs"]) > 2 and node["inputs"][2] in consts:
            cval = float(consts[node["inputs"][2]])
        return sym_mod.Pad(ins[0], mode=mode, pad_width=tuple(pw),
                           constant_value=cval)
    if op == "Clip":
        amin = float(onp.ravel(consts[node["inputs"][1]])[0]) \
            if len(node["inputs"]) > 1 and node["inputs"][1] else None
        amax = float(onp.ravel(consts[node["inputs"][2]])[0]) \
            if len(node["inputs"]) > 2 and node["inputs"][2] else None
        return sym_mod.clip(ins[0], amin, amax)
    if op == "Slice":
        starts = consts[node["inputs"][1]]
        ends = consts[node["inputs"][2]]
        axes = consts[node["inputs"][3]] if len(node["inputs"]) > 3 \
            else onp.arange(len(starts))
        steps = consts[node["inputs"][4]] if len(node["inputs"]) > 4 \
            else onp.ones(len(starts), onp.int64)
        out = ins[0]
        big = 2 ** 31 - 1
        for st, en, ax, sp in zip(starts, ends, axes, steps):
            if int(sp) != 1:
                raise NotImplementedError("strided ONNX Slice")
            out = sym_mod.slice_axis(
                out, axis=int(ax), begin=int(st),
                end=None if int(en) >= big else int(en))
        return out
    if op == "Where":
        return sym_mod.where(*ins)
    if op == "Unsqueeze":
        axes = consts.get(node["inputs"][1]) if len(node["inputs"]) > 1 \
            else a.get("axes")
        out = ins[0]
        for ax in sorted(int(x) for x in axes):
            out = sym_mod.expand_dims(out, axis=ax)
        return out
    if op == "Squeeze":
        axes = consts.get(node["inputs"][1]) if len(node["inputs"]) > 1 \
            else a.get("axes")
        if axes is None:
            return sym_mod.squeeze(ins[0])
        axes = tuple(int(x) for x in axes)
        return sym_mod.squeeze(ins[0],
                               axis=axes[0] if len(axes) == 1 else axes)
    if op == "Expand":
        shape_src = shape_of.get(node["inputs"][1])
        if shape_src is not None:
            return sym_mod.broadcast_like(ins[0], shape_src)
        shape = consts.get(node["inputs"][1])
        if shape is None:
            raise NotImplementedError("dynamic Expand shape")
        return sym_mod.broadcast_to(ins[0],
                                    shape=tuple(int(s) for s in shape))
    if op == "TopK":
        k = int(consts[node["inputs"][1]][0])
        axis = int(a.get("axis", -1))
        is_ascend = not bool(a.get("largest", 1))
        vals = sym_mod.topk(ins[0], k=k, axis=axis, ret_typ="value",
                            is_ascend=is_ascend)
        idx = sym_mod.topk(ins[0], k=k, axis=axis, ret_typ="indices",
                           is_ascend=is_ascend)
        return [vals, idx]
    if op in ("ReduceSum", "ReduceMean", "ReduceMax", "ReduceMin",
              "ReduceProd", "ReduceL2"):
        axes = a.get("axes")
        if axes is None and len(node["inputs"]) > 1:
            axes = consts.get(node["inputs"][1])
        axis = tuple(int(x) for x in axes) if axes is not None else None
        keep = bool(a.get("keepdims", 1))
        if op == "ReduceL2":
            return sym_mod.norm(ins[0], ord=2, axis=axis, keepdims=keep)
        fn = {"ReduceSum": "sum", "ReduceMean": "mean", "ReduceMax": "max",
              "ReduceMin": "min", "ReduceProd": "prod"}[op]
        return getattr(sym_mod, fn)(ins[0], axis=axis, keepdims=keep)
    if op == "ArgMax":
        out = sym_mod.argmax(ins[0], axis=int(a.get("axis", 0)))
        if a.get("keepdims", 1):
            out = sym_mod.expand_dims(out, axis=int(a.get("axis", 0)))
        return out
    if op == "LayerNormalization":
        return sym_mod.layer_norm(ins[0], ins[1], ins[2],
                                  axis=int(a.get("axis", -1)),
                                  eps=float(a.get("epsilon", 1e-5)))
    if op == "LogSoftmax":
        return sym_mod.log_softmax(ins[0], axis=int(a.get("axis", -1)))
    if op == "Einsum":
        eq = a.get("equation")
        return sym_mod.einsum(eq, *ins)
    if op in ("LSTM", "GRU", "RNN"):
        return _import_rnn(op, node, ins, consts, sym_mod, a)
    simple = {"Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh",
              "Exp": "exp", "Log": "log", "Sqrt": "sqrt", "Abs": "abs",
              "Neg": "negative", "Identity": "identity",
              "Add": "broadcast_add", "Sub": "broadcast_sub",
              "Mul": "broadcast_mul", "Div": "broadcast_div",
              "Max": "maximum", "Min": "minimum", "Pow": "power",
              "Mod": "mod", "Equal": "equal", "Greater": "greater",
              "Less": "less", "Softsign": "softsign", "Erf": "erf"}
    if op in simple:
        return getattr(sym_mod, simple[op])(*ins)
    raise NotImplementedError(f"no importer for ONNX op {op!r}")


def _import_rnn(op, node, ins, consts, sym_mod, a):
    """ONNX LSTM/GRU/RNN -> legacy fused `RNN` symbol
    (`src/operator/rnn.cc:295` packed-parameter layout).  W/R/B must be
    initializers; gate order is permuted back from ONNX (i,o,f,c / z,r,h)
    to MXNet (i,f,g,o / r,z,n)."""
    W = consts.get(node["inputs"][1])
    R = consts.get(node["inputs"][2])
    B = consts.get(node["inputs"][3])
    if W is None or R is None or B is None:
        raise NotImplementedError("ONNX RNN with non-initializer weights")
    if W.shape[0] != 1:
        raise NotImplementedError("bidirectional ONNX RNN import")
    hidden = int(a["hidden_size"])
    mode = {"LSTM": "lstm", "GRU": "gru", "RNN": "rnn_tanh"}[op]
    if op == "RNN":
        acts = a.get("activations")
        if acts and "relu" in str(acts).lower():
            mode = "rnn_relu"
    if op == "GRU" and not int(a.get("linear_before_reset", 0) or 0):
        # backend GRU math is linear_before_reset=1; a lbr=0 model only
        # matches when the recurrent bias of the candidate gate is zero
        gh3 = B.shape[1] // 2
        rbn = B[0][gh3:][2 * (gh3 // 3):]
        if onp.abs(rbn).max() > 0:
            raise NotImplementedError(
                "ONNX GRU with linear_before_reset=0 and nonzero Rb_h "
                "has no equivalent in this backend's fused GRU")

    def unperm(w):
        if op == "LSTM":   # onnx i,o,f,c -> mxnet i,f,g,o
            i, o, f, c = onp.split(w, 4, axis=0)
            return onp.concatenate([i, f, c, o], axis=0)
        if op == "GRU":    # onnx z,r,h -> mxnet r,z,n
            z, r, h = onp.split(w, 3, axis=0)
            return onp.concatenate([r, z, h], axis=0)
        return w

    Wm = unperm(W[0])
    Rm = unperm(R[0])
    gh = Wm.shape[0]
    Wb = unperm(B[0][:gh])
    Rb = unperm(B[0][gh:])
    packed = onp.concatenate([Wm.ravel(), Rm.ravel(), Wb.ravel(),
                              Rb.ravel()]).astype(onp.float32)
    pname = node["outputs"][0] + "_parameters"
    consts[pname] = packed  # materialized into arg_params by import_model
    params_var = sym_mod.var(pname)
    nout = 3 if op == "LSTM" else 2
    sym_ins = [ins[0], params_var]
    # initial_h is input 5, initial_c input 6 (input 4 = sequence_lens)
    h0 = ins[5] if len(ins) > 5 and node["inputs"][5] else None
    if h0 is None:
        raise NotImplementedError("ONNX RNN without initial_h")
    sym_ins.append(h0)
    if op == "LSTM":
        sym_ins.append(ins[6])
    rnn_sym = sym_mod.Symbol(
        "RNN", sym_ins,
        {"mode": mode, "state_size": hidden, "num_layers": 1,
         "state_outputs": True}, name=node["outputs"][0], nout=nout)
    # ONNX Y is (T, num_dir, N, H): re-add the dir axis
    y = sym_mod.expand_dims(rnn_sym[0], axis=1)
    outs = [y, rnn_sym[1]]
    if op == "LSTM":
        outs.append(rnn_sym[2])
    return outs


def import_model(model_file):
    """ONNX file -> (sym, arg_params, aux_params) (reference
    `onnx2mx.import_model` contract)."""
    from ...ndarray.ndarray import NDArray
    from ... import symbol as sym_mod

    with open(model_file, "rb") as f:
        nodes, inits, g_in, g_out = _parse_model(f.read())

    env = {}
    for name in g_in:
        env[name] = sym_mod.var(name)
    for name in inits:
        env.setdefault(name, sym_mod.var(name))

    aux_names = set()
    shape_of = {}  # ONNX Shape outputs: name -> source Symbol
    for node in nodes:
        ins = []
        for i in node["inputs"]:
            if i == "":
                ins.append(None)
                continue
            if i not in env:
                env[i] = sym_mod.var(i)
            ins.append(env[i])
        if node["op"] == "Shape":
            shape_of[node["outputs"][0]] = ins[0]
            continue
        if node["op"] == "BatchNormalization":
            # running mean/var (inputs 3,4) are aux state, as in the
            # reference importer
            aux_names.update(node["inputs"][3:5])
        out = _build(node, ins, inits, sym_mod, shape_of)
        if isinstance(out, (list, tuple)):
            for o, out_name in zip(out, node["outputs"]):
                if out_name:
                    o._name = out_name
                    env[out_name] = o
        else:
            out._name = node["outputs"][0]
            env[node["outputs"][0]] = out

    outputs = [env[o] for o in g_out]
    out_sym = outputs[0] if len(outputs) == 1 else sym_mod.Group(outputs)

    # exactly the initializers the BUILT graph still references as free
    # variables become params; ones consumed at build time (Reshape
    # shapes, Slice starts, Clip bounds turned into attrs, RNN raw W/R/B
    # repacked into `*_parameters`) are dropped
    free = set(out_sym.list_arguments())
    arg_params, aux_params = {}, {}
    for name, arr in inits.items():
        if name not in free:
            continue
        target = aux_params if name in aux_names else arg_params
        target[name] = NDArray(onp.ascontiguousarray(arr))
    return out_sym, arg_params, aux_params
