"""Contrib namespace (reference: `python/mxnet/contrib/` and the
`_contrib_*` op family in `src/operator/contrib/`)."""
from ..ops.contrib import (box_iou, box_nms, bipartite_matching, roi_align,
                           multibox_prior, multibox_target,
                           multibox_detection, boolean_mask, allclose,
                           index_copy, index_add, index_array,
                           circ_conv, k_smallest_flags, hawkes_ll,
                           interleaved_matmul_selfatt_qk,
                           interleaved_matmul_selfatt_valatt,
                           interleaved_matmul_encdec_qk,
                           interleaved_matmul_encdec_valatt)
# control flow lives under mx.nd.contrib in the reference
# (`python/mxnet/ndarray/contrib.py`: foreach/while_loop/cond)
from ..ops.control_flow import foreach, while_loop, cond  # noqa: F401
from . import text


def div_sqrt_dim(data):
    """Rescale by 1/sqrt(last-dim) (reference `_contrib_div_sqrt_dim`,
    `src/operator/contrib/transformer.cc`)."""
    import math

    from ..ops.invoke import invoke
    return invoke(lambda x: x / math.sqrt(x.shape[-1]), (data,),
                  name="div_sqrt_dim")

# reference CamelCase aliases (mx.nd.contrib.ROIAlign)
ROIAlign = roi_align
MultiBoxDetection = multibox_detection
MultiBoxPrior = multibox_prior
MultiBoxTarget = multibox_target

__all__ = ["box_iou", "box_nms", "bipartite_matching", "roi_align",
           "ROIAlign", "multibox_prior", "MultiBoxPrior", "multibox_target", "MultiBoxTarget", "multibox_detection", "MultiBoxDetection",
           "boolean_mask", "allclose", "index_copy", "index_add", "index_array",
           "circ_conv", "k_smallest_flags", "hawkes_ll",
           "foreach", "while_loop", "cond", "div_sqrt_dim",
           "interleaved_matmul_selfatt_qk", "interleaved_matmul_selfatt_valatt",
           "interleaved_matmul_encdec_qk", "interleaved_matmul_encdec_valatt"]
