"""Contrib namespace (reference: `python/mxnet/contrib/` and the
`_contrib_*` op family in `src/operator/contrib/`)."""
from ..ops.contrib import (box_iou, box_nms, bipartite_matching, roi_align,
                           multibox_prior, multibox_target,
                           multibox_detection, boolean_mask, allclose,
                           index_copy, index_add, index_array,
                           circ_conv, k_smallest_flags, hawkes_ll)
from . import text

# reference CamelCase aliases (mx.nd.contrib.ROIAlign)
ROIAlign = roi_align
MultiBoxDetection = multibox_detection
MultiBoxPrior = multibox_prior
MultiBoxTarget = multibox_target

__all__ = ["box_iou", "box_nms", "bipartite_matching", "roi_align",
           "ROIAlign", "multibox_prior", "MultiBoxPrior", "multibox_target", "MultiBoxTarget", "multibox_detection", "MultiBoxDetection",
           "boolean_mask", "allclose", "index_copy", "index_add", "index_array",
           "circ_conv", "k_smallest_flags", "hawkes_ll"]
