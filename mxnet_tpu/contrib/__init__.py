"""Contrib namespace (reference: `python/mxnet/contrib/` and the
`_contrib_*` op family in `src/operator/contrib/`)."""
from ..ops.contrib import (box_iou, box_nms, bipartite_matching, roi_align,
                           multibox_prior, multibox_target,
                           multibox_detection, boolean_mask, allclose,
                           index_copy, index_add, index_array,
                           circ_conv, k_smallest_flags, hawkes_ll,
                           interleaved_matmul_selfatt_qk,
                           interleaved_matmul_selfatt_valatt,
                           interleaved_matmul_encdec_qk,
                           interleaved_matmul_encdec_valatt,
                           quadratic, box_encode, box_decode, edge_id,
                           getnnz, dynamic_reshape, bilinear_resize_2d)
# int8 surface under its reference contrib home
# (`src/operator/quantization/*.cc` registers `_contrib_quantize*`)
from ..ops.quantization import (quantize, quantize_v2, dequantize,
                                requantize, quantized_fully_connected,
                                quantized_conv)
# group-sparse optimizer kernel (`_contrib_group_adagrad_update`)
from ..ndarray.legacy import group_adagrad_update
# control flow lives under mx.nd.contrib in the reference
# (`python/mxnet/ndarray/contrib.py`: foreach/while_loop/cond)
from ..ops.control_flow import foreach, while_loop, cond  # noqa: F401
from . import text


def div_sqrt_dim(data):
    """Rescale by 1/sqrt(last-dim) (reference `_contrib_div_sqrt_dim`,
    `src/operator/contrib/transformer.cc`)."""
    import math

    from ..ops.invoke import invoke
    return invoke(lambda x: x / math.sqrt(x.shape[-1]), (data,),
                  name="div_sqrt_dim")

def calibrate_entropy(hist, hist_edges, num_quantized_bins=255):
    """`_contrib_calibrate_entropy` (`src/operator/quantization/
    calibrate.cc:95-96`): KL-minimizing symmetric threshold from an
    activation histogram.  Returns ``(threshold, divergence)`` — the
    reference op's two outputs."""
    import numpy as _onp

    from .quantization import _entropy_threshold_from_hist
    h = _onp.asarray(hist.asnumpy() if hasattr(hist, "asnumpy") else hist)
    e = _onp.asarray(hist_edges.asnumpy()
                     if hasattr(hist_edges, "asnumpy") else hist_edges)
    amax = float(_onp.abs(e).max())
    t, kl = _entropy_threshold_from_hist(h, amax, num_quantized_bins,
                                         return_divergence=True)
    return t, kl


def AdaptiveAvgPooling2D(data, output_size=1):  # noqa: N802
    """`_contrib_AdaptiveAvgPooling2D` (`src/operator/contrib/
    adaptive_avg_pooling.cc`): NCHW adaptive average pool."""
    from ..ops.invoke import invoke as _inv
    from ..ops.nn import adaptive_avg_pool2d
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    return _inv(lambda x: adaptive_avg_pool2d(x, tuple(output_size)),
                (data,), name="AdaptiveAvgPooling2D")


def BatchNormWithReLU(*args, **kwargs):  # noqa: N802
    """`_contrib_BatchNormWithReLU`: BN + ReLU — on TPU the fusion is
    XLA's job; the composite compiles to one kernel."""
    from ..ndarray import legacy as _leg
    if kwargs.get("output_mean_var"):
        raise ValueError("BatchNormWithReLU does not return mean/var "
                         "(same as the reference fused op)")
    out_buf = kwargs.pop("out", None)   # relu applies before the rebind
    res = _leg.relu(_leg.BatchNorm(*args, **kwargs))
    if out_buf is not None:
        out_buf._rebind(res._data)
        return out_buf
    return res


BilinearResize2D = bilinear_resize_2d  # reference CamelCase registration

# reference CamelCase aliases (mx.nd.contrib.ROIAlign)
ROIAlign = roi_align
MultiBoxDetection = multibox_detection
MultiBoxPrior = multibox_prior
MultiBoxTarget = multibox_target

__all__ = ["box_iou", "box_nms", "bipartite_matching", "roi_align",
           "ROIAlign", "multibox_prior", "MultiBoxPrior", "multibox_target", "MultiBoxTarget", "multibox_detection", "MultiBoxDetection",
           "boolean_mask", "allclose", "index_copy", "index_add", "index_array",
           "circ_conv", "k_smallest_flags", "hawkes_ll",
           "foreach", "while_loop", "cond", "div_sqrt_dim",
           "interleaved_matmul_selfatt_qk", "interleaved_matmul_selfatt_valatt",
           "interleaved_matmul_encdec_qk", "interleaved_matmul_encdec_valatt",
           "quadratic", "box_encode", "box_decode", "edge_id", "getnnz",
           "dynamic_reshape", "bilinear_resize_2d", "BilinearResize2D",
           "AdaptiveAvgPooling2D", "BatchNormWithReLU", "calibrate_entropy",
           "quantize", "quantize_v2", "dequantize", "requantize",
           "quantized_fully_connected", "quantized_conv",
           "group_adagrad_update"]
