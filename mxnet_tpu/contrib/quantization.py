"""Post-training INT8 quantization for Gluon networks.

Reference: `python/mxnet/contrib/quantization.py` (quantize_net /
quantize_model, `_LayerHistogramCollector`, `_get_optimal_threshold`) over
the C++ `QuantizeGraph` pass (`src/operator/quantization/
quantize_graph_pass.cc:580`).

TPU-native design: instead of a graph-rewriting pass inserting
quantize/dequantize nodes into an nnvm graph, calibration attaches forward
hooks to Dense/Conv blocks (the hook seam replaces the graph pass), and
conversion swaps those children for Quantized* blocks whose forward is an
int8 MXU dot — XLA then fuses the (quantize → int8 op → rescale) chain.
Calibration modes mirror the reference: 'naive' (min/max) and 'entropy'
(KL-optimal threshold over a 2048-bin histogram).
"""
from __future__ import annotations

import numpy as onp

from .. import numpy as mxnp
from ..gluon.block import HybridBlock
from ..gluon.nn.basic_layers import Dense
from ..gluon.nn.conv_layers import Conv2D
from ..gluon.parameter import Constant
from ..ops import quantization as _q
from ..ops.invoke import invoke

__all__ = ["quantize_net", "QuantizedDense", "QuantizedConv2D",
           "calib_entropy_threshold"]


def _smooth(dist, eps=1e-4):
    is_zero = dist == 0
    n_zero = int(is_zero.sum())
    n_nonzero = dist.size - n_zero
    if n_zero == 0 or n_nonzero == 0:
        return onp.maximum(dist, 1e-12)
    out = dist.copy()
    out[is_zero] = eps
    out[~is_zero] -= eps * n_zero / n_nonzero
    return onp.maximum(out, 1e-12)


def calib_entropy_threshold(arr, num_bins=2048, num_quantized_bins=255):
    """KL-divergence-optimal |threshold| for symmetric int8 quantization
    (reference `_get_optimal_threshold`, contrib/quantization.py)."""
    arr = onp.abs(onp.asarray(arr, onp.float32).ravel())
    amax = float(arr.max()) if arr.size else 0.0
    if amax == 0.0:
        return 1e-8
    hist, _ = onp.histogram(arr, bins=num_bins, range=(0, amax))
    return _entropy_threshold_from_hist(hist, amax, num_quantized_bins)


def _entropy_threshold_from_hist(hist, amax, num_quantized_bins=255,
                                 return_divergence=False):
    num_bins = hist.size
    edges = onp.linspace(0.0, amax, num_bins + 1)
    best_kl, best_t = onp.inf, amax
    # candidate thresholds sweep the top half of the histogram
    for i in range(num_quantized_bins // 2, num_bins + 1,
                   max(1, num_bins // 128)):
        t = edges[i] if i < len(edges) else amax
        p = hist[:i].astype(onp.float64).copy()
        outliers = hist[i:].sum()
        if p.size == 0 or p.sum() + outliers == 0:
            continue
        p[-1] += outliers  # clip outliers into the last bin
        # quantize the i bins down to num_quantized_bins, then expand back
        factor = i / num_quantized_bins
        idx = onp.minimum((onp.arange(i) / factor).astype(onp.int64),
                          num_quantized_bins - 1)
        q_small = onp.zeros(num_quantized_bins)
        onp.add.at(q_small, idx, p)
        counts = onp.zeros(num_quantized_bins)
        onp.add.at(counts, idx, (p > 0).astype(onp.float64))
        q = onp.divide(q_small[idx], counts[idx],
                       out=onp.zeros_like(p), where=counts[idx] > 0)
        q[p == 0] = 0
        if q.sum() == 0:
            continue
        # smooth both distributions (reference `_smooth_distribution`):
        # move eps mass onto zero bins so KL stays finite and stable
        pm = _smooth(p / p.sum())
        qm = _smooth(q / q.sum())
        kl = float((pm * onp.log(pm / qm)).sum())
        if kl < best_kl:
            best_kl, best_t = kl, float(t)
    t = max(best_t, 1e-8)
    if return_divergence:
        return t, (best_kl if onp.isfinite(best_kl) else 0.0)
    return t


class _CalibCollector:
    """Forward hooks recording per-block input ranges (reference
    `_LayerHistogramCollector`/min-max collector).  Entropy mode keeps one
    fixed-size histogram per layer — O(num_bins) memory however many
    calibration batches stream through — re-binning the accumulated counts
    whenever a batch widens the observed range."""

    NUM_BINS = 2048

    def __init__(self, mode):
        self.mode = mode
        self.stats = {}       # id(block) -> dict
        self._handles = []

    def attach(self, blocks):
        for blk in blocks:
            self._handles.append(
                blk.register_forward_hook(self._make_hook(blk)))

    def _make_hook(self, blk):
        def hook(block, args, out):
            x = onp.asarray(args[0].asnumpy(), onp.float32)
            st = self.stats.setdefault(id(blk), {"min": onp.inf,
                                                 "max": -onp.inf,
                                                 "absmax": 0.0,
                                                 "hist": None})
            st["min"] = min(st["min"], float(x.min()))
            st["max"] = max(st["max"], float(x.max()))
            bmax = float(onp.abs(x).max())
            if self.mode == "entropy":
                ax = onp.abs(x.ravel())
                if st["hist"] is None:
                    st["hist"] = onp.zeros(self.NUM_BINS, onp.float64)
                if bmax > st["absmax"] and st["absmax"] > 0:
                    # widen: map old bin centers proportionally into the
                    # new range and redistribute the accumulated counts
                    centers = (onp.arange(self.NUM_BINS) + 0.5) * \
                        (st["absmax"] / self.NUM_BINS)
                    idx = onp.minimum(
                        (centers / bmax * self.NUM_BINS).astype(onp.int64),
                        self.NUM_BINS - 1)
                    widened = onp.zeros_like(st["hist"])
                    onp.add.at(widened, idx, st["hist"])
                    st["hist"] = widened
                rng = max(bmax, st["absmax"], 1e-12)
                st["hist"] += onp.histogram(
                    ax, bins=self.NUM_BINS, range=(0, rng))[0]
            st["absmax"] = max(st["absmax"], bmax)
        return hook

    def detach(self):
        for h in self._handles:
            h.detach()

    def threshold(self, blk):
        st = self.stats.get(id(blk))
        if st is None:
            return None
        if self.mode == "entropy":
            if st["hist"] is None or st["absmax"] == 0.0:
                return max(st["absmax"], 1e-8)
            return _entropy_threshold_from_hist(st["hist"], st["absmax"])
        return max(abs(st["min"]), abs(st["max"]), 1e-8)


def _quantize_weight(w, per_channel_axis=0):
    """Symmetric per-output-channel int8 weight quantization; returns
    (int8 ndarray, per-channel scale ndarray)."""
    w = onp.asarray(w, onp.float32)
    red = tuple(i for i in range(w.ndim) if i != per_channel_axis)
    amax = onp.maximum(onp.abs(w).max(axis=red), 1e-12)
    scale = _q.INT8_MAX / amax                       # (channels,)
    shape = [1] * w.ndim
    shape[per_channel_axis] = -1
    qw = onp.clip(onp.round(w * scale.reshape(shape)),
                  -127, 127).astype(onp.int8)
    return qw, scale.astype(onp.float32)


class QuantizedDense(HybridBlock):
    """Int8 Dense: activation quantized online against a calibrated
    threshold, weight pre-quantized per-output-channel (reference
    `quantized_fully_connected.cc` + calibrated requantize)."""

    def __init__(self, qweight, w_scale, bias, act_threshold, units,
                 flatten=True, activation=None):
        super().__init__()
        self._units = units
        self._flatten = flatten
        self._act_threshold = float(act_threshold)
        self.qweight = Constant(qweight, name="qweight")
        self.w_scale = Constant(w_scale, name="w_scale")
        self.bias = None if bias is None else Constant(bias, name="bias")
        for c in (self.qweight, self.w_scale, self.bias):
            if c is not None:
                c.initialize()
        from ..gluon.nn.basic_layers import Activation
        self.act = Activation(activation) if activation else None

    def forward(self, x):
        t = self._act_threshold
        x_scale = _q.INT8_MAX / t

        def f(xd, qw, ws, *bias):
            qx, _, _ = _q.quantize(xd, -t, t)
            return _q.quantized_fully_connected(
                qx, qw, x_scale, ws, bias[0] if bias else None,
                flatten=self._flatten)

        args = (x, self.qweight.data(), self.w_scale.data()) + \
            (() if self.bias is None else (self.bias.data(),))
        out = invoke(f, args, name="quantized_fully_connected",
                     differentiable=False)
        return self.act(out) if self.act is not None else out

    def __repr__(self):
        return f"QuantizedDense({self._units}, int8)"


class QuantizedConv2D(HybridBlock):
    """Int8 2-D convolution (reference `quantized_conv.cc`)."""

    def __init__(self, qweight, w_scale, bias, act_threshold, channels,
                 kernel, strides, padding, dilation, groups, layout,
                 activation=None):
        super().__init__()
        self._conv_args = dict(stride=strides, dilate=dilation, pad=padding,
                               num_filter=channels, num_group=groups,
                               layout=layout)
        self._act_threshold = float(act_threshold)
        self.qweight = Constant(qweight, name="qweight")
        self.w_scale = Constant(w_scale, name="w_scale")
        self.bias = None if bias is None else Constant(bias, name="bias")
        for c in (self.qweight, self.w_scale, self.bias):
            if c is not None:
                c.initialize()
        from ..gluon.nn.basic_layers import Activation
        self.act = Activation(activation) if activation else None

    def forward(self, x):
        t = self._act_threshold
        x_scale = _q.INT8_MAX / t

        def f(xd, qw, ws, *bias):
            qx, _, _ = _q.quantize(xd, -t, t)
            return _q.quantized_conv(qx, qw, x_scale, ws,
                                     bias[0] if bias else None,
                                     **self._conv_args)

        args = (x, self.qweight.data(), self.w_scale.data()) + \
            (() if self.bias is None else (self.bias.data(),))
        out = invoke(f, args, name="quantized_conv", differentiable=False)
        return self.act(out) if self.act is not None else out

    def __repr__(self):
        return f"QuantizedConv2D({self._conv_args['num_filter']}, int8)"


def _quantizable(blk):
    return type(blk) in (Dense, Conv2D)


def _convert(blk, threshold):
    if isinstance(blk, Dense):
        qw, ws = _quantize_weight(blk.weight.data().asnumpy())
        bias = None if blk.bias is None else blk.bias.data().asnumpy()
        return QuantizedDense(qw, ws, bias, threshold, blk._units,
                              flatten=blk._flatten,
                              activation=blk._activation)
    qw, ws = _quantize_weight(blk.weight.data().asnumpy())
    bias = None if blk.bias is None else blk.bias.data().asnumpy()
    return QuantizedConv2D(
        qw, ws, bias, threshold, blk._channels, blk._kernel, blk._strides,
        blk._padding, blk._dilation, blk._groups, blk._layout,
        activation=blk.act._act_type if blk.act is not None else None)


def quantize_net(net, calib_data=None, calib_mode="naive",
                 quantized_dtype="int8", exclude_layers=None):
    """Convert a trained float net's Dense/Conv2D layers to int8 in place
    and return it (reference `quantize_net`, contrib/quantization.py).

    ``calib_data`` is an iterable of input batches (or a single batch) run
    through the net to calibrate activation ranges.  ``calib_mode``:
    'naive' = min/max, 'entropy' = KL-optimal thresholds, 'none' = skip
    layers that would need calibration.  ``exclude_layers`` is a list of
    blocks or block names to leave in float.
    """
    if quantized_dtype != "int8":
        raise ValueError("TPU quantization is symmetric int8")
    exclude = set()
    for e in (exclude_layers or ()):
        exclude.add(e if isinstance(e, str) else id(e))

    targets = []

    def walk(block, prefix):
        for name, child in list(block._children.items()):
            path = f"{prefix}{name}"
            skip = path in exclude or name in exclude or id(child) in exclude
            if _quantizable(child) and not skip:
                targets.append((block, name, child))
            walk(child, path + ".")
    walk(net, "")
    if not targets:
        return net

    # calibration must run eagerly: a hybridized net replays its jit cache
    # (or traces with abstract values), so hooks would observe nothing or
    # tracers; deactivate any hybridized blocks for the calibration pass
    hybridized = []

    def find_active(block):
        if getattr(block, "_active", False):
            hybridized.append(block)
        for child in block._children.values():
            find_active(child)
    find_active(net)
    for blk in hybridized:
        blk._active = False
        blk._clear_cached()

    collector = _CalibCollector(calib_mode)
    if calib_data is not None and calib_mode != "none":
        collector.attach([t[2] for t in targets])
        if isinstance(calib_data, (list, tuple)):
            batches = calib_data
        elif hasattr(calib_data, "__iter__") and not hasattr(
                calib_data, "shape"):
            batches = calib_data   # DataLoader / generator of batches
        else:
            batches = [calib_data]
        for batch in batches:
            net(batch if not isinstance(batch, (list, tuple)) else batch[0])
        collector.detach()

    for parent, name, child in targets:
        threshold = collector.threshold(child)
        if threshold is None:
            continue  # never saw calibration data; stays float
        setattr(parent, name, _convert(child, threshold))

    # re-activate with cleared caches so the next call traces the int8 graph
    for blk in hybridized:
        blk._active = True
        blk._clear_cached()
    return net
