"""Vocabulary (reference `contrib/text/vocab.py` Vocabulary)."""
from __future__ import annotations

__all__ = ["Vocabulary"]


class Vocabulary:
    """Token <-> index mapping built from a token Counter.

    Index 0 is the unknown token; `reserved_tokens` follow, then tokens by
    descending frequency (ties broken alphabetically), truncated by
    `most_freq_count` and filtered by `min_freq` — reference semantics.
    """

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        assert min_freq > 0
        self._unknown_token = unknown_token
        reserved_tokens = list(reserved_tokens or [])
        assert unknown_token not in reserved_tokens, \
            "unknown_token must not appear in reserved_tokens"
        assert len(set(reserved_tokens)) == len(reserved_tokens), \
            "reserved_tokens must be unique"
        self._idx_to_token = [unknown_token] + reserved_tokens
        self._reserved_tokens = reserved_tokens or None
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}
        if counter is not None:
            self._index_counter_keys(counter, most_freq_count, min_freq)

    def _index_counter_keys(self, counter, most_freq_count, min_freq):
        pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
        room = None if most_freq_count is None else most_freq_count
        for token, freq in pairs:
            if freq < min_freq or token in self._token_to_idx:
                continue
            if room is not None:
                if room == 0:
                    break
                room -= 1
            self._token_to_idx[token] = len(self._idx_to_token)
            self._idx_to_token.append(token)

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        """Token(s) -> index/indices; unknown tokens map to index 0."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idxs = [self._token_to_idx.get(t, 0) for t in toks]
        return idxs[0] if single else idxs

    def to_tokens(self, indices):
        import numpy as onp
        single = isinstance(indices, (int, onp.integer))
        idxs = [indices] if single else indices
        for i in idxs:
            if not 0 <= i < len(self._idx_to_token):
                raise ValueError(f"index {i} out of vocabulary range")
        toks = [self._idx_to_token[i] for i in idxs]
        return toks[0] if single else toks
