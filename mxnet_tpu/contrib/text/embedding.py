"""Token embeddings (reference `contrib/text/embedding.py`).

`TokenEmbedding` holds an (V, D) matrix indexed by a `Vocabulary`-style
token map; `CustomEmbedding` loads word-vector text files (the GloVe /
fastText `.txt`/`.vec` format: token then D floats per line).  The
reference's named pretrained downloads (`glove`, `fasttext`) register here
too, but this environment has no network egress — `create()` for them
raises with instructions to use `CustomEmbedding` on a local file.
"""
from __future__ import annotations

import numpy as onp

from ...base import registry
from ...ndarray.ndarray import NDArray
from .vocab import Vocabulary

__all__ = ["TokenEmbedding", "CustomEmbedding", "CompositeEmbedding",
           "register", "create", "get_pretrained_file_names"]


class TokenEmbedding(Vocabulary):
    """Base embedding: vocabulary + idx_to_vec matrix."""

    emb_registry = registry.get_registry("token_embedding")

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._vec_len = 0
        self._idx_to_vec = None

    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        if lower_case_backup:
            toks = [t if t in self._token_to_idx else t.lower()
                    for t in toks]
        idxs = [self._token_to_idx.get(t, 0) for t in toks]
        vecs = self._idx_to_vec.asnumpy()[idxs]
        out = NDArray(vecs[0] if single else vecs)
        return out

    def update_token_vectors(self, tokens, new_vectors):
        toks = [tokens] if isinstance(tokens, str) else tokens
        mat = self._idx_to_vec.asnumpy().copy()
        new = new_vectors.asnumpy() if isinstance(new_vectors, NDArray) \
            else onp.asarray(new_vectors)
        new = new.reshape(len(toks), -1)
        for t, v in zip(toks, new):
            if t not in self._token_to_idx:
                raise ValueError(f"token {t!r} is not in the embedding")
            mat[self._token_to_idx[t]] = v
        self._idx_to_vec = NDArray(mat)

    def _load_embedding_txt(self, path, elem_delim=" ",
                            init_unknown_vec=onp.zeros, encoding="utf8",
                            restrict=False):
        """Load a word-vector text file.  With ``restrict=True`` only
        tokens already in the vocabulary get vectors (file-only tokens are
        ignored); otherwise file tokens extend the vocabulary.  The matrix
        is allocated once after the read (a 400k-line GloVe file must not
        reallocate per token)."""
        tokens, vecs = [], []
        with open(path, encoding=encoding) as f:
            for line_num, line in enumerate(f):
                parts = line.rstrip().split(elem_delim)
                if line_num == 0 and len(parts) == 2:
                    continue  # fastText header: "<count> <dim>"
                token, elems = parts[0], parts[1:]
                if len(elems) <= 1:
                    continue  # malformed line, as reference warns+skips
                tokens.append(token)
                vecs.append([float(e) for e in elems])
        self._vec_len = len(vecs[0]) if vecs else 0
        if not restrict:
            for token in tokens:
                if token not in self._token_to_idx:
                    self._token_to_idx[token] = len(self._idx_to_token)
                    self._idx_to_token.append(token)
        mat = onp.zeros((len(self), self._vec_len), onp.float32)
        mat[0] = init_unknown_vec(self._vec_len)
        for token, vec in zip(tokens, vecs):
            idx = self._token_to_idx.get(token)
            if idx is not None:
                mat[idx] = vec
        self._idx_to_vec = NDArray(mat)


class CustomEmbedding(TokenEmbedding):
    """Embedding from a local word-vector text file (reference
    `CustomEmbedding`): each line `token<delim>v1<delim>...<delim>vD`."""

    def __init__(self, pretrained_file_path, elem_delim=" ", encoding="utf8",
                 init_unknown_vec=onp.zeros, vocabulary=None, **kwargs):
        if vocabulary is not None:
            kwargs.setdefault("counter", None)
        super().__init__(**kwargs)
        if vocabulary is not None:
            # restrict to an existing vocabulary's tokens
            self._idx_to_token = list(vocabulary.idx_to_token)
            self._token_to_idx = dict(vocabulary.token_to_idx)
        self._load_embedding_txt(pretrained_file_path, elem_delim,
                                 init_unknown_vec, encoding,
                                 restrict=vocabulary is not None)


class CompositeEmbedding(TokenEmbedding):
    """Concatenate several embeddings over one vocabulary (reference
    `CompositeEmbedding`)."""

    def __init__(self, vocabulary, token_embeddings):
        super().__init__()
        if not isinstance(token_embeddings, (list, tuple)):
            token_embeddings = [token_embeddings]
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        parts = [emb.get_vecs_by_tokens(self._idx_to_token).asnumpy()
                 for emb in token_embeddings]
        mat = onp.concatenate(parts, axis=1)
        self._vec_len = mat.shape[1]
        self._idx_to_vec = NDArray(mat)


def register(klass):
    return registry.get_register_func(
        TokenEmbedding, "token_embedding")(klass)


_PRETRAINED = {
    "glove": ["glove.6B.50d.txt", "glove.6B.100d.txt", "glove.6B.200d.txt",
              "glove.6B.300d.txt", "glove.42B.300d.txt",
              "glove.840B.300d.txt"],
    "fasttext": ["wiki.en.vec", "wiki.simple.vec"],
}


def get_pretrained_file_names(embedding_name=None):
    """Names of the reference's downloadable embedding files (reference
    `get_pretrained_file_names`); files must be supplied locally here."""
    if embedding_name is None:
        return dict(_PRETRAINED)
    if embedding_name not in _PRETRAINED:
        raise KeyError(f"unknown embedding {embedding_name!r}")
    return list(_PRETRAINED[embedding_name])


def create(embedding_name, **kwargs):
    """Create a named embedding.  Downloadable pretrained sets are not
    available without network egress; load the file locally instead."""
    klass = TokenEmbedding.emb_registry.find(embedding_name.lower())
    if klass is not None:
        return klass(**kwargs)
    if embedding_name.lower() in _PRETRAINED:
        raise RuntimeError(
            f"pretrained {embedding_name!r} requires a download; fetch the "
            "file yourself and use contrib.text.embedding.CustomEmbedding("
            "path) instead")
    raise KeyError(f"unknown embedding {embedding_name!r}")
