"""Text utilities (reference: `python/mxnet/contrib/text/`)."""
from . import utils
from .vocab import Vocabulary
from .embedding import (TokenEmbedding, CustomEmbedding, CompositeEmbedding,
                        register, create, get_pretrained_file_names)

__all__ = ["utils", "Vocabulary", "TokenEmbedding", "CustomEmbedding",
           "CompositeEmbedding", "register", "create",
           "get_pretrained_file_names"]
