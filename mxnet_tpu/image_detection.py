"""Detection-aware image augmentation + iterator.

Reference: `python/mxnet/image/detection.py:1` (DetAugmenter family +
``ImageDetIter``) and the packed-label record format of
`src/io/iter_image_det_recordio.cc:1`.  Every geometric transform updates
the bounding boxes together with the pixels; labels are normalized
``[cls, xmin, ymin, xmax, ymax, ...]`` rows (coords in [0, 1]) behind a
``[header_width, obj_width, ...header..., objects...]`` flat wire format.

TPU-native design: augmentation is host-side numpy feeding the device
pipeline (decode/augment is the CPU stage of the input pipeline — the
reference runs it in C++ iterator threads; here `io.DataLoader` workers or
`DevicePrefetcher` overlap it with TPU compute).  The detection *ops*
(multibox_prior/target/detection, box_nms) are XLA lowerings in
`ops/contrib.py`; this module is what feeds them.
"""
from __future__ import annotations

import random as pyrandom

import numpy as onp

from .image import (Augmenter, CastAug, ColorJitterAug, ColorNormalizeAug,
                    ForceResizeAug, HueJitterAug, ImageIter, LightingAug,
                    RandomGrayAug, ResizeAug, _as_np, fixed_crop)

__all__ = [
    "DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
    "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
    "CreateMultiRandCropAugmenter", "CreateDetAugmenter", "ImageDetIter",
]


def _box_areas(boxes):
    """Areas of normalized [xmin, ymin, xmax, ymax] rows."""
    return (onp.maximum(0.0, boxes[:, 2] - boxes[:, 0]) *
            onp.maximum(0.0, boxes[:, 3] - boxes[:, 1]))


class DetAugmenter:
    """Base class: ``(image HWC, label (N, 5+)) -> (image, label)``
    (reference `detection.py:40`)."""

    def __call__(self, src, label):
        return src, label


class DetBorrowAug(DetAugmenter):
    """Lift an image-only `image.Augmenter` into the detection pipeline —
    valid only for transforms that don't move pixels spatially (color,
    cast, lighting; reference `detection.py:66`)."""

    def __init__(self, augmenter):
        assert isinstance(augmenter, Augmenter)
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly run ONE augmenter from a list, or none with
    ``skip_prob`` (reference `detection.py:91`)."""

    def __init__(self, aug_list, skip_prob=0.0):
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if self.aug_list and pyrandom.random() >= self.skip_prob:
            src, label = pyrandom.choice(self.aug_list)(src, label)
        return src, label


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image and boxes with probability ``p`` (reference
    `detection.py:127`: xmin' = 1-xmax, xmax' = 1-xmin)."""

    def __init__(self, p):
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() < self.p:
            src = _as_np(src)[:, ::-1]
            label = label.copy()
            new_xmin = 1.0 - label[:, 3]
            label[:, 3] = 1.0 - label[:, 1]
            label[:, 1] = new_xmin
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Constrained random crop (reference `detection.py:153`): sample a
    crop window whose aspect/area fall in range and that covers at least
    ``min_object_covered`` of every (surviving) object; boxes are
    re-normalized to the window and objects cropped below
    ``min_eject_coverage`` of their area are dropped."""

    def __init__(self, min_object_covered=0.1,
                 aspect_ratio_range=(0.75, 1.33), area_range=(0.05, 1.0),
                 min_eject_coverage=0.3, max_attempts=50):
        if not isinstance(aspect_ratio_range, (tuple, list)):
            aspect_ratio_range = (aspect_ratio_range, aspect_ratio_range)
        if not isinstance(area_range, (tuple, list)):
            area_range = (area_range, area_range)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts
        self.enabled = (0 < area_range[1] >= area_range[0] and
                        0 < aspect_ratio_range[0] <= aspect_ratio_range[1])

    def __call__(self, src, label):
        src = _as_np(src)
        h, w = src.shape[0], src.shape[1]
        found = self._propose(label, h, w)
        if found:
            x0, y0, cw, ch, label = found
            src = fixed_crop(src, x0, y0, cw, ch, None)
        return src, label

    def _coverage_ok(self, boxes, window):
        """True when every object overlapping the window is covered at
        least min_object_covered (normalized coords)."""
        x0, y0, x1, y1 = window
        areas = _box_areas(boxes)
        live = areas > 0
        if not live.any():
            return False
        inter = onp.stack([
            onp.maximum(boxes[:, 0], x0), onp.maximum(boxes[:, 1], y0),
            onp.minimum(boxes[:, 2], x1), onp.minimum(boxes[:, 3], y1),
        ], axis=1)
        cov = _box_areas(inter) / onp.maximum(areas, 1e-12)
        cov = cov[live & (cov > 0)]
        return cov.size > 0 and cov.min() > self.min_object_covered

    def _clip_labels(self, label, x0, y0, cw, ch, height, width):
        """Re-normalize boxes to the crop window; drop objects whose
        surviving area fraction is below min_eject_coverage."""
        out = label.copy()
        fx, fy = x0 / width, y0 / height
        fw, fh = cw / width, ch / height
        before = _box_areas(out[:, 1:5])
        out[:, (1, 3)] = (out[:, (1, 3)] - fx) / fw
        out[:, (2, 4)] = (out[:, (2, 4)] - fy) / fh
        out[:, 1:5] = onp.clip(out[:, 1:5], 0.0, 1.0)
        kept = _box_areas(out[:, 1:5]) * fw * fh
        valid = ((out[:, 3] > out[:, 1]) & (out[:, 4] > out[:, 2]) &
                 (kept > self.min_eject_coverage *
                  onp.maximum(before, 1e-12)))
        return out[valid] if valid.any() else None

    def _propose(self, label, height, width):
        if not self.enabled or height <= 0 or width <= 0:
            return None
        lo_area = self.area_range[0] * height * width
        hi_area = self.area_range[1] * height * width
        for _ in range(self.max_attempts):
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            ch_lo = int(round((lo_area / ratio) ** 0.5))
            ch_hi = min(int(round((hi_area / ratio) ** 0.5)),
                        height, int(width / ratio))
            if ch_hi < 1 or ch_lo > ch_hi:
                continue
            ch = pyrandom.randint(min(ch_lo, ch_hi), ch_hi)
            cw = min(int(round(ch * ratio)), width)
            if not (lo_area * 0.99 <= cw * ch <= hi_area * 1.01) or \
                    cw * ch < 2:
                continue
            y0 = pyrandom.randint(0, height - ch)
            x0 = pyrandom.randint(0, width - cw)
            window = (x0 / width, y0 / height,
                      (x0 + cw) / width, (y0 + ch) / height)
            if not self._coverage_ok(label[:, 1:5], window):
                continue
            new_label = self._clip_labels(label, x0, y0, cw, ch,
                                          height, width)
            if new_label is not None:
                return x0, y0, cw, ch, new_label
        return None


class DetRandomPadAug(DetAugmenter):
    """Random expansion pad (reference `detection.py:324`): place the
    image inside a larger canvas filled with ``pad_val``; boxes shrink
    into the canvas coordinates."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50,
                 pad_val=(128, 128, 128)):
        if not isinstance(pad_val, (tuple, list)):
            pad_val = (pad_val,)
        if not isinstance(aspect_ratio_range, (tuple, list)):
            aspect_ratio_range = (aspect_ratio_range, aspect_ratio_range)
        if not isinstance(area_range, (tuple, list)):
            area_range = (area_range, area_range)
        self.pad_val = pad_val
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.enabled = (area_range[1] > 1.0 and
                        area_range[0] <= area_range[1] and
                        0 < aspect_ratio_range[0] <= aspect_ratio_range[1])

    def __call__(self, src, label):
        src = _as_np(src)
        h, w = src.shape[0], src.shape[1]
        found = self._propose(label, h, w)
        if found:
            x0, y0, pw, ph, label = found
            canvas = onp.empty((ph, pw, src.shape[2]), src.dtype)
            canvas[...] = onp.asarray(
                self.pad_val * (src.shape[2] if len(self.pad_val) == 1
                                else 1))[:src.shape[2]]
            canvas[y0:y0 + h, x0:x0 + w] = src
            src = canvas
        return src, label

    def _propose(self, label, height, width):
        if not self.enabled or height <= 0 or width <= 0:
            return None
        lo_area = self.area_range[0] * height * width
        hi_area = self.area_range[1] * height * width
        for _ in range(self.max_attempts):
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            ph_lo = max(int(round((lo_area / ratio) ** 0.5)), height,
                        int(round(width / ratio)))
            ph_hi = max(int(round((hi_area / ratio) ** 0.5)), ph_lo)
            ph = pyrandom.randint(ph_lo, ph_hi)
            pw = int(round(ph * ratio))
            if ph - height < 2 or pw - width < 2:
                continue
            y0 = pyrandom.randint(0, ph - height)
            x0 = pyrandom.randint(0, pw - width)
            out = label.copy()
            out[:, (1, 3)] = (out[:, (1, 3)] * width + x0) / pw
            out[:, (2, 4)] = (out[:, (2, 4)] * height + y0) / ph
            return x0, y0, pw, ph, out
        return None


def CreateMultiRandCropAugmenter(min_object_covered=0.1,
                                 aspect_ratio_range=(0.75, 1.33),
                                 area_range=(0.05, 1.0),
                                 min_eject_coverage=0.3, max_attempts=50,
                                 skip_prob=0.0):
    """One-of-N random crops, each with its own constraint set (reference
    `detection.py:418`): scalar parameters broadcast, list parameters
    must agree in length."""
    def listify(x):
        return x if isinstance(x, list) else [x]

    params = [listify(min_object_covered), listify(aspect_ratio_range),
              listify(area_range), listify(min_eject_coverage),
              listify(max_attempts)]
    n = max(len(p) for p in params)
    params = [p * n if len(p) == 1 else p for p in params]
    assert all(len(p) == n for p in params), \
        "CreateMultiRandCropAugmenter: list parameters must align"
    crops = [DetRandomCropAug(moc, arr, ar, mec, ma)
             for moc, arr, ar, mec, ma in zip(*params)]
    return DetRandomSelectAug(crops, skip_prob=skip_prob)


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, pca_noise=0,
                       hue=0, inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), min_eject_coverage=0.3,
                       max_attempts=50, pad_val=(127, 127, 127)):
    """The standard detection augmentation chain (reference
    `detection.py:483`): resize → color jitter → expansion pad →
    constrained crop → mirror → force-resize to ``data_shape`` →
    cast/normalize.  ``rand_crop``/``rand_pad``/``rand_gray`` are
    probabilities."""
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(
            ColorJitterAug(brightness, contrast, saturation)))
    if hue:
        auglist.append(DetBorrowAug(HueJitterAug(hue)))
    if pca_noise > 0:
        from .image import PCA_EIGVAL, PCA_EIGVEC
        auglist.append(DetBorrowAug(
            LightingAug(pca_noise, PCA_EIGVAL, PCA_EIGVEC)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    if rand_pad > 0:
        auglist.append(DetRandomSelectAug(
            [DetRandomPadAug(aspect_ratio_range,
                             (1.0, area_range[1]), max_attempts, pad_val)],
            skip_prob=1 - rand_pad))
    if rand_crop > 0:
        auglist.append(CreateMultiRandCropAugmenter(
            min_object_covered, aspect_ratio_range,
            (area_range[0], min(area_range[1], 1.0)),
            min_eject_coverage, max_attempts, skip_prob=1 - rand_crop))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    auglist.append(DetBorrowAug(
        ForceResizeAug((data_shape[2], data_shape[1]), inter_method)))
    auglist.append(DetBorrowAug(CastAug()))
    if mean is True:
        mean = onp.array([123.68, 116.28, 103.53])
    if std is True:
        std = onp.array([58.395, 57.12, 57.375])
    if mean is not None:
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator (reference `detection.py:625` over the packed
    label format of `src/io/iter_image_det_recordio.cc:1`).

    The record label is a flat float vector
    ``[header_width, obj_width, <header...>, obj0..., obj1...]`` with one
    ``[cls, xmin, ymin, xmax, ymax, ...]`` row per object (normalized
    corner coords).  Batches pad the object dimension with ``-1`` rows to
    ``label_shape`` so XLA sees one static shape per epoch."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", shuffle=False,
                 aug_list=None, label_width=-1, data_name="data",
                 label_name="label", last_batch_handle="pad", **aug_kwargs):
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape, **aug_kwargs)
        super().__init__(batch_size, data_shape, path_imgrec=path_imgrec,
                         path_imglist=path_imglist, path_root=path_root,
                         shuffle=shuffle,
                         aug_list=[],  # det augmenters applied by us
                         label_width=max(label_width, 1),
                         data_name=data_name, label_name=label_name,
                         last_batch_handle=last_batch_handle)
        self.det_aug_list = aug_list
        self.max_objects, obj_width = self._estimate_label_shape()
        self.label_shape = (self.max_objects, obj_width)
        from .io import DataDesc
        self.provide_label = [DataDesc(
            label_name, (batch_size,) + self.label_shape)]

    # -- label plumbing ----------------------------------------------------
    @staticmethod
    def _parse_label(raw):
        """Flat packed vector -> (N, obj_width) rows (reference
        `detection.py:717`); drops degenerate boxes."""
        raw = onp.asarray(raw, onp.float32).ravel()
        if raw.size < 7:
            raise RuntimeError(f"invalid packed det label size {raw.size}")
        header_width = int(raw[0])
        obj_width = int(raw[1])
        if obj_width < 5 or (raw.size - header_width) % obj_width != 0:
            raise RuntimeError(
                f"label size {raw.size} inconsistent with header "
                f"{header_width}/object width {obj_width}")
        objs = raw[header_width:].reshape(-1, obj_width)
        valid = (objs[:, 3] > objs[:, 1]) & (objs[:, 4] > objs[:, 2])
        if not valid.any():
            raise RuntimeError("sample with no valid boxes")
        return objs[valid]

    def _estimate_label_shape(self):
        """Scan the dataset once for (max_objects, obj_width) (reference
        `detection.py:703`)."""
        max_objs, width = 0, 5
        for i in range(len(self._keys)):
            label = self._raw_label(i)
            try:
                parsed = self._parse_label(label)
            except RuntimeError:
                continue
            max_objs = max(max_objs, parsed.shape[0])
            width = parsed.shape[1]
        if max_objs == 0:
            raise RuntimeError("no sample carries a valid detection label")
        return max_objs, width

    def _raw_label(self, i):
        if self._rec is not None:
            from .recordio import unpack
            header, _ = unpack(self._rec.read_idx(self._keys[i]))
            return onp.asarray(header.label, onp.float32)
        path, label = self._items[i]
        return onp.asarray(label, onp.float32)

    def reshape(self, data_shape=None, label_shape=None):
        """Rebind data/label shapes (reference `detection.py:743`)."""
        from .io import DataDesc
        if data_shape is not None:
            assert len(data_shape) == 3
            self.data_shape = tuple(data_shape)
            self.provide_data = [DataDesc(
                self.provide_data[0].name,
                (self.batch_size,) + self.data_shape)]
        if label_shape is not None:
            self.check_label_shape(label_shape)
            self.label_shape = tuple(label_shape)
            self.max_objects = label_shape[0]
            self.provide_label = [DataDesc(
                self.provide_label[0].name,
                (self.batch_size,) + self.label_shape)]

    def check_label_shape(self, label_shape):
        if len(label_shape) != 2 or label_shape[0] < self.max_objects:
            raise ValueError(
                f"label_shape {label_shape} cannot hold up to "
                f"{self.max_objects} objects")

    def sync_label_shape(self, it, verbose=False):
        """Grow both iterators to the larger label shape (train/val
        pairing; reference `detection.py:967`)."""
        assert isinstance(it, ImageDetIter)
        n = max(self.label_shape[0], it.label_shape[0])
        w = max(self.label_shape[1], it.label_shape[1])
        shape = (n, w)
        self.max_objects = it.max_objects = 0  # allow shrink-to-sync
        self.reshape(label_shape=shape)
        it.reshape(label_shape=shape)
        self.max_objects = it.max_objects = n
        return it

    # -- batch production --------------------------------------------------
    def _read_one(self, i):
        from .recordio import unpack_img
        import os as _os
        if self._rec is not None:
            header, img = unpack_img(
                self._rec.read_idx(self._keys[i]),
                iscolor=1 if self.data_shape[0] == 3 else 0)
            raw = onp.asarray(header.label, onp.float32)
        else:
            path, raw = self._items[i]
            from .image import imread
            img = imread(_os.path.join(self.path_root, path),
                         flag=1 if self.data_shape[0] == 3 else 0)
            raw = onp.asarray(raw, onp.float32)
        label = self._parse_label(raw)
        img = _as_np(img)
        for aug in self.det_aug_list:
            img, label = aug(img, label)
            if label.shape[0] == 0:
                raise RuntimeError("augmentation dropped every box")
        padded = onp.full((self.max_objects, self.label_shape[1]), -1.0,
                          onp.float32)
        n = min(label.shape[0], self.max_objects)
        padded[:n] = label[:n]
        arr = _as_np(img).astype(onp.float32)
        return arr.transpose(2, 0, 1), padded
