"""``ImageRecordIter`` — the high-throughput image input pipeline.

Reference: `src/io/iter_image_recordio_2.cc` (`ImageRecordIter` /
ImageRecordIOParser2) + `src/io/image_aug_default.cc`.  The reference
feeds GPUs from C++ decode threads; the Python/PIL path
(`mxnet_tpu/image.py` ImageIter) cannot keep a TPU fed.  This iterator
drives the native pipeline in `src/image_pipeline.cc`: worker threads
decode JPEG (libjpeg-turbo, DCT-domain downscale) and augment entirely
outside the GIL into a ring of batch slots; Python pops completed
batches.

Per-host sharding (`num_parts`/`part_index`, reference
ImageRecParserParam) gives each host a strided slice of the epoch's
GLOBAL shuffle permutation, so every part's sample order is a pure
function of (seed, epoch, part) and the union over parts is an exact
partition of the record file — the pod-scale input treatment from the
MLPerf TPU work.

Output is NHWC uint8 batches (the TPU-preferred layout); mean/std
normalization and dtype casting belong on device, fused by XLA into the
first conv — do NOT normalize on host.  ``layout='NCHW'`` transposes on
device for reference-parity consumers.  For train-time crop/flip on
device (host ships the pre-crop canvas), see
``gluon.data.DeviceAugment``.
"""
from __future__ import annotations

import ctypes
import logging
import os
import time

import numpy as onp

from ..ndarray.ndarray import NDArray
from .io import DataBatch, DataDesc, DataIter

__all__ = ["ImageRecordIter"]


def _io_metrics():
    from .. import telemetry as _tm

    return (
        _tm.counter("mxtpu_io_decode_errors_total",
                    "Records the native image pipeline failed to decode "
                    "(zero-filled and counted, never dropped)"),
        _tm.counter("mxtpu_io_batches_total",
                    "Batches popped from the native decode ring"),
        _tm.gauge("mxtpu_io_ring_ready",
                  "Completed batches waiting in the decode ring at the "
                  "last pop (0 while compute waits = decode-bound)"),
        _tm.histogram("mxtpu_io_next_wait_seconds",
                      "Consumer wait for the next completed batch"),
    )


class ImageRecordIter(DataIter):
    """Reference-parity constructor args (`io/iter_image_recordio_2.cc`
    ImageRecordParam/ImageRecParserParam subset that is meaningful here).

    data_shape is channel-first (C, H, W) as in the reference; delivery is
    NHWC unless ``layout='NCHW'``.

    ``num_parts``/``part_index`` shard the file across hosts: part ``p``
    reads ``perm[p::num_parts]`` of each epoch's global permutation —
    bit-deterministic per (seed, epoch, part), exact partition by
    construction.  ``preprocess_threads`` defaults to
    ``MXNET_DECODE_THREADS`` (then ``MXNET_CPU_WORKER_NTHREADS``).
    """

    def __init__(self, path_imgrec, batch_size, data_shape=(3, 224, 224),
                 resize=0, rand_crop=False, rand_mirror=False,
                 shuffle=False, preprocess_threads=None, prefetch_buffer=3,
                 seed=0, num_parts=1, part_index=0, layout="NHWC",
                 round_batch=True, **_compat):
        from .._native import img_lib

        super().__init__(batch_size=batch_size)
        L = img_lib()
        if L is None:
            raise RuntimeError(
                "native image pipeline unavailable (libjpeg missing?); "
                "use mxnet_tpu.image.ImageIter (PIL) instead")
        c, h, w = data_shape
        assert c == 3, "pipeline decodes RGB"
        if preprocess_threads is None:
            from ..env import decode_threads
            preprocess_threads = decode_threads()  # MXNET_DECODE_THREADS
        from ..env import io_error_tolerance
        self._err_tolerance = io_error_tolerance()
        self._lib = L
        self._h, self._w = h, w
        self._layout = layout
        # kept for reshard(): the native pipeline bakes the partition
        # into its worker threads, so re-deriving the world after an
        # elastic re-shard rebuilds the handle from these
        self._ctor = dict(
            path_imgrec=path_imgrec, resize=int(resize),
            rand_crop=int(bool(rand_crop)),
            rand_mirror=int(bool(rand_mirror)),
            shuffle=int(bool(shuffle)), seed=int(seed),
            preprocess_threads=int(preprocess_threads),
            prefetch_buffer=int(prefetch_buffer))
        self.num_parts = int(num_parts)
        self.part_index = int(part_index)
        self._handle = L.imgpipe_create(
            path_imgrec.encode(), batch_size, h, w, int(resize),
            int(preprocess_threads), int(prefetch_buffer),
            int(bool(rand_crop)), int(bool(rand_mirror)),
            int(bool(shuffle)), int(seed), int(num_parts), int(part_index))
        if not self._handle:
            raise IOError(L.imgpipe_last_error().decode())
        self._num_records = L.imgpipe_num_records(self._handle)
        self._part_records = L.imgpipe_part_records(self._handle)
        # all parts must deliver the SAME number of batches per epoch:
        # part sizes differ by up to one record (perm[p::num_parts]), and
        # in lockstep SPMD a per-host batch-count mismatch desyncs the
        # hosts at the epoch boundary — collectives mismatch or hang.
        # Derive the count from the minimum part size floor(n/num_parts);
        # the native stream wraps, so a larger part's surplus records
        # simply roll into its next epoch.
        self._batches_per_epoch = \
            (self._num_records // int(num_parts)) // batch_size
        if self._batches_per_epoch == 0:
            # tiny shard: still deliver one (wrapping) batch per epoch
            self._batches_per_epoch = 1
        self._cursor = 0
        # decode-error watermark for the per-window WARNING
        self._err_seen = 0
        self._err_window_base = 0
        self._err_window_records = 0
        self._err_ctr, self._batch_ctr, self._ring_gauge, self._wait_hist = \
            _io_metrics()
        shape = (batch_size, c, h, w) if layout == "NCHW" else \
            (batch_size, h, w, c)
        self.provide_data = [DataDesc("data", shape, onp.uint8)]
        self.provide_label = [DataDesc("softmax_label", (batch_size,),
                                       onp.float32)]

    @property
    def num_records(self):
        return self._num_records

    @property
    def part_records(self):
        """Records owned by this (num_parts, part_index) shard."""
        return self._part_records

    @property
    def decode_errors(self):
        return self._lib.imgpipe_decode_errors(self._handle)

    @property
    def ready_batches(self):
        """Completed batches waiting in the decode ring (occupancy)."""
        return self._lib.imgpipe_ready_batches(self._handle)

    def _account_errors(self):
        """Tick the error counter by delta and WARN when the fraction of
        the current window exceeds MXNET_IO_ERROR_TOLERANCE.  Windows are
        one epoch's worth of records (cheap, and a corrupt file region is
        revisited every epoch so the warning re-fires)."""
        errs = self.decode_errors
        delta = errs - self._err_seen
        if delta > 0:
            self._err_ctr.inc(delta)
            self._err_seen = errs
        self._err_window_records += self.batch_size
        window = max(self._part_records, self.batch_size)
        if self._err_window_records >= window:
            frac = (errs - self._err_window_base) / \
                max(1, self._err_window_records)
            if frac > self._err_tolerance:
                logging.getLogger("mxnet_tpu.io").warning(
                    "ImageRecordIter: %.2f%% of the last %d records failed "
                    "to decode (tolerance %.2f%%) — corrupt records are "
                    "zero-filled, check the .rec file",
                    100.0 * frac, self._err_window_records,
                    100.0 * self._err_tolerance)
            self._err_window_base = errs
            self._err_window_records = 0

    def next_arrays(self):
        """One batch as host numpy (NHWC uint8, f32 labels) — the
        zero-overhead form the bench consumes."""
        n = self.batch_size
        data = onp.empty((n, self._h, self._w, 3), onp.uint8)
        labels = onp.empty((n,), onp.float32)
        t0 = time.perf_counter()
        self._lib.imgpipe_next(
            self._handle,
            data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        self._wait_hist.observe(time.perf_counter() - t0)
        self._batch_ctr.inc()
        self._ring_gauge.set(self.ready_batches)
        self._account_errors()
        return data, labels

    def next(self):
        if self._cursor >= self._batches_per_epoch:
            raise StopIteration
        self._cursor += 1
        data, labels = self.next_arrays()
        d = NDArray(data)
        if self._layout == "NCHW":
            d = NDArray(d._data.transpose(0, 3, 1, 2))
        return DataBatch(data=[d], label=[NDArray(labels)], pad=0)

    def reset(self):
        # the native stream is epoch-continuous (reshuffles itself per
        # wrap); reset only rearms the python epoch counter
        self._cursor = 0

    def reshard(self, num_parts, part_index):
        """Re-derive the shard after an elastic world change: destroy
        the native pipeline and rebuild it for the new
        ``(num_parts, part_index)``.  The sharding law is unchanged —
        part ``p`` reads ``perm[p::num_parts]`` of the (seed, epoch)
        global permutation, so the survivor parts again partition each
        epoch exactly — but unlike the pure-python ``ImageIter`` the
        partition is baked into the worker threads, so the rebuilt
        stream restarts its epoch sequence at 0 (documented cost of a
        re-shard on the native path)."""
        num_parts, part_index = int(num_parts), int(part_index)
        if num_parts < 1 or not 0 <= part_index < num_parts:
            raise ValueError("need 0 <= part_index < num_parts")
        L = self._lib
        c = self._ctor
        self.close()
        self._handle = L.imgpipe_create(
            c["path_imgrec"].encode(), self.batch_size, self._h, self._w,
            c["resize"], c["preprocess_threads"], c["prefetch_buffer"],
            c["rand_crop"], c["rand_mirror"], c["shuffle"], c["seed"],
            num_parts, part_index)
        if not self._handle:
            raise IOError(L.imgpipe_last_error().decode())
        self.num_parts, self.part_index = num_parts, part_index
        self._part_records = L.imgpipe_part_records(self._handle)
        self._batches_per_epoch = max(
            1, (self._num_records // num_parts) // self.batch_size)
        self._cursor = 0
        # the fresh handle's decode-error counter restarts at zero
        self._err_seen = 0
        self._err_window_base = 0
        self._err_window_records = 0

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.imgpipe_destroy(self._handle)
            self._handle = None

    def __del__(self):
        self.close()
