"""``ImageRecordIter`` — the high-throughput image input pipeline.

Reference: `src/io/iter_image_recordio_2.cc` (`ImageRecordIter` /
ImageRecordIOParser2) + `src/io/image_aug_default.cc`.  The reference
feeds GPUs from C++ decode threads; the Python/PIL path
(`mxnet_tpu/image.py` ImageIter) cannot keep a TPU fed.  This iterator
drives the native pipeline in `src/image_pipeline.cc`: worker threads
decode JPEG (libjpeg-turbo, DCT-domain downscale) and augment entirely
outside the GIL into a ring of batch slots; Python pops completed
batches.

Output is NHWC uint8 batches (the TPU-preferred layout); mean/std
normalization and dtype casting belong on device, fused by XLA into the
first conv — do NOT normalize on host.  ``layout='NCHW'`` transposes on
device for reference-parity consumers.
"""
from __future__ import annotations

import ctypes
import os

import numpy as onp

from ..ndarray.ndarray import NDArray
from .io import DataBatch, DataDesc, DataIter

__all__ = ["ImageRecordIter"]


class ImageRecordIter(DataIter):
    """Reference-parity constructor args (`io/iter_image_recordio_2.cc`
    ImageRecordParam/ImageRecParserParam subset that is meaningful here).

    data_shape is channel-first (C, H, W) as in the reference; delivery is
    NHWC unless ``layout='NCHW'``.
    """

    def __init__(self, path_imgrec, batch_size, data_shape=(3, 224, 224),
                 resize=0, rand_crop=False, rand_mirror=False,
                 shuffle=False, preprocess_threads=None, prefetch_buffer=3,
                 seed=0, layout="NHWC", round_batch=True, **_compat):
        from .._native import img_lib

        super().__init__(batch_size=batch_size)
        L = img_lib()
        if L is None:
            raise RuntimeError(
                "native image pipeline unavailable (libjpeg missing?); "
                "use mxnet_tpu.image.ImageIter (PIL) instead")
        c, h, w = data_shape
        assert c == 3, "pipeline decodes RGB"
        if preprocess_threads is None:
            from ..env import cpu_worker_nthreads
            preprocess_threads = cpu_worker_nthreads()  # MXNET_CPU_WORKER_NTHREADS
        self._lib = L
        self._h, self._w = h, w
        self._layout = layout
        self._handle = L.imgpipe_create(
            path_imgrec.encode(), batch_size, h, w, int(resize),
            int(preprocess_threads), int(prefetch_buffer),
            int(bool(rand_crop)), int(bool(rand_mirror)),
            int(bool(shuffle)), int(seed))
        if not self._handle:
            raise IOError(L.imgpipe_last_error().decode())
        self._num_records = L.imgpipe_num_records(self._handle)
        self._batches_per_epoch = self._num_records // batch_size
        self._cursor = 0
        shape = (batch_size, c, h, w) if layout == "NCHW" else \
            (batch_size, h, w, c)
        self.provide_data = [DataDesc("data", shape, onp.uint8)]
        self.provide_label = [DataDesc("softmax_label", (batch_size,),
                                       onp.float32)]

    @property
    def num_records(self):
        return self._num_records

    @property
    def decode_errors(self):
        return self._lib.imgpipe_decode_errors(self._handle)

    def next_arrays(self):
        """One batch as host numpy (NHWC uint8, f32 labels) — the
        zero-overhead form the bench consumes."""
        n = self.batch_size
        data = onp.empty((n, self._h, self._w, 3), onp.uint8)
        labels = onp.empty((n,), onp.float32)
        self._lib.imgpipe_next(
            self._handle,
            data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        return data, labels

    def next(self):
        if self._cursor >= self._batches_per_epoch:
            raise StopIteration
        self._cursor += 1
        data, labels = self.next_arrays()
        d = NDArray(data)
        if self._layout == "NCHW":
            d = NDArray(d._data.transpose(0, 3, 1, 2))
        return DataBatch(data=[d], label=[NDArray(labels)], pad=0)

    def reset(self):
        # the native stream is epoch-continuous (reshuffles itself per
        # wrap); reset only rearms the python epoch counter
        self._cursor = 0

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.imgpipe_destroy(self._handle)
            self._handle = None

    def __del__(self):
        self.close()
