"""Bucketed sequence iterator.

Reference: variable-length bucketing from the legacy RNN examples
(`example/rnn/bucketing/`, `BucketSentenceIter` in mxnet's bucket_io) —
sentences are grouped into a small set of length buckets, padded to the
bucket length, and each batch carries its `bucket_key`.

TPU-native rationale: XLA compiles one program per shape, so free-form
lengths cause a compile storm (SURVEY.md §7 hard-part 3).  A handful of
bucket lengths = a handful of compiled programs; `DataBatch.bucket_key`
is exactly the shape key the jit cache needs.
"""
from __future__ import annotations

import numpy as onp

from .io import DataBatch, DataDesc, DataIter
from ..ndarray.ndarray import NDArray

__all__ = ["BucketSentenceIter"]


class BucketSentenceIter(DataIter):
    """Iterate tokenized sentences in padded length buckets.

    sentences: list of int-lists (token ids).  Each batch yields
    data (N, bucket_len) and label (N, bucket_len) = data shifted left by
    one (next-token prediction), padded with `invalid_label`.
    """

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label", dtype="int32",
                 layout="NT"):
        super().__init__(batch_size)
        if buckets is None:
            lens = onp.bincount([len(s) for s in sentences])
            # auto buckets: lengths that occur often enough to fill a batch
            buckets = [i for i, n in enumerate(lens) if n >= batch_size]
            if not buckets:
                buckets = [max(len(s) for s in sentences)]
        buckets = sorted(buckets)
        self.buckets = buckets
        self.data_name = data_name
        self.label_name = label_name
        self.invalid_label = invalid_label
        self.dtype = dtype
        self.layout = layout

        self.data = [[] for _ in buckets]
        ndiscard = 0
        for s in sentences:
            buck = onp.searchsorted(buckets, len(s))
            if buck == len(buckets):  # longer than the largest bucket
                ndiscard += 1
                continue
            arr = onp.full((buckets[buck],), invalid_label, dtype=dtype)
            arr[:len(s)] = s
            self.data[buck].append(arr)
        self.data = [onp.asarray(x, dtype=dtype) for x in self.data]
        self.ndiscard = ndiscard
        self.default_bucket_key = max(buckets)
        self.reset()

    def _desc_shape(self):
        if self.layout == "TN":
            return (self.default_bucket_key, self.batch_size)
        return (self.batch_size, self.default_bucket_key)

    @property
    def provide_data(self):
        return [DataDesc(self.data_name, self._desc_shape(), self.dtype,
                         layout=self.layout)]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name, self._desc_shape(), self.dtype,
                         layout=self.layout)]

    def reset(self):
        self.curr_idx = 0
        self.idx = []
        for i, buck in enumerate(self.data):
            perm = onp.random.permutation(len(buck))
            # full batches only, like the reference bucket iterator
            for j in range(0, len(buck) - self.batch_size + 1,
                           self.batch_size):
                self.idx.append((i, perm[j:j + self.batch_size]))
        onp.random.shuffle(self.idx)

    def iter_next(self):
        return self.curr_idx < len(self.idx)

    def next(self):
        if not self.iter_next():
            raise StopIteration
        i, rows = self.idx[self.curr_idx]
        self.curr_idx += 1
        data = self.data[i][rows]
        # next-token labels: shift left, pad tail with invalid_label
        label = onp.full_like(data, self.invalid_label)
        label[:, :-1] = data[:, 1:]
        if self.layout == "TN":
            data, label = data.T, label.T
        bucket_len = self.buckets[i]
        return DataBatch(
            [NDArray(data)], [NDArray(label)], pad=0,
            bucket_key=bucket_len,
            provide_data=[DataDesc(self.data_name, data.shape, self.dtype,
                                   layout=self.layout)],
            provide_label=[DataDesc(self.label_name, label.shape, self.dtype,
                                    layout=self.layout)])
