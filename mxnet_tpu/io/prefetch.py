"""Prefetch-to-device double buffering.

Reference: `src/io/iter_prefetcher.h:1` (thread-backed ``PrefetcherIter``)
and the DataLoader ``pin_memory`` path
(`python/mxnet/gluon/data/dataloader.py:48-138`).  The reference overlaps
decode -> H2D -> compute with dedicated prefetch machinery; on TPU the
equivalent is a feeder thread that issues *asynchronous* ``jax.device_put``
transfers for batch N+1..N+depth while the chip executes step N.  PjRt
orders a computation after the definition events of its input buffers, so
the consumer can dispatch the step immediately against an in-flight
transfer — the transfer and the previous step's compute proceed
concurrently and the step-time law becomes ``max(feed, compute)`` instead
of ``feed + compute``.

With ``sharding=`` the feeder builds GLOBAL dp batches: each device gets
its shard by one direct ``device_put`` (`parallel.shard_put`), so the wire
carries each byte exactly once and the fused step consumes the array with
zero host-side replication (its ``place()`` passes equivalently-sharded
inputs through).  This replaces the old chunk-and-concatenate
multi-stream path, which burned a device concat kernel and still
replicated under a mesh.

Two entry points:

- :class:`DevicePrefetcher` — wraps any source yielding tuples of host
  numpy arrays (or a ``DataIter``), delivers device-resident
  :class:`~mxnet_tpu.ndarray.ndarray.NDArray` batches.
- ``NDArray.prefetch_to(ctx)`` (see `ndarray/ndarray.py`) — one-shot async
  copy of a single array.
"""
from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor

import jax
import numpy as onp

from ..context import Context, current_context
from ..ndarray.ndarray import NDArray
from .io import DataBatch, DataIter

__all__ = ["DevicePrefetcher"]

_STOP = object()


def _prefetch_metrics():
    from .. import telemetry as _tm

    return (
        _tm.counter("mxtpu_prefetch_batches_total",
                    "Batches delivered by DevicePrefetcher"),
        _tm.gauge("mxtpu_prefetch_ring_occupancy",
                  "Transferred batches queued ahead of the consumer at "
                  "the last pop (0 while compute waits = feed-bound)"),
        _tm.histogram("mxtpu_prefetch_wait_seconds",
                      "Consumer wait for the next device-resident batch"),
    )


class DevicePrefetcher:
    """Overlap host batch production and H2D transfer with device compute.

    Parameters
    ----------
    source : iterator / DataIter / callable
        Yields per-batch tuples of host numpy arrays.  A ``DataIter`` is
        consumed through ``next_arrays()`` when available (zero-copy host
        path), else ``next()``.  A callable is invoked per batch.
    ctx : Context, optional
        Target device (default: current context).  Ignored when
        ``sharding`` is given.
    depth : int, optional
        Ring depth — how many batches may be in flight (decoded + queued
        on the wire) ahead of the consumer.  Default
        ``MXNET_PREFETCH_DEPTH`` (2): double buffering suffices for
        steady state; 3 absorbs decode jitter.
    dtypes : tuple, optional
        Per-element dtype casts applied host-side before transfer (cheap on
        host; avoids an on-device cast dispatch for e.g. f32->i32 labels).
    sharding : jax.sharding.NamedSharding, optional
        Build dp GLOBAL arrays: the spec is truncated to each array's
        rank (a rank-2 data spec still places rank-1 labels), arrays
        whose leading dim does not divide over the mesh replicate.  The
        per-device shard puts run concurrently on ``transfer_threads``.
    transfer_threads : int
        Pool width for the concurrent per-shard puts of the sharded
        path (default 1 = sequential; use ~device count).  Without
        ``sharding`` the single ``device_put`` needs no pool.
    chunk_threshold : int, optional
        Deprecated, ignored — the chunk-and-concatenate multi-stream
        path is gone (it burned a device concat kernel; the sharded
        path places per-device shards instead).

    Iteration yields tuples of device-resident NDArrays.  The transfer for
    a yielded batch may still be on the wire — PjRt serializes any compute
    consuming it after the transfer completes, which is exactly the overlap
    contract.  StopIteration from the source ends the stream; call
    ``reset()`` to rearm (source must support reset) or ``close()`` to
    reclaim the feeder thread.  Use as a context manager so the feeder
    can never outlive an exception in the consuming loop:

    >>> with DevicePrefetcher(src, sharding=parallel.data_sharding(mesh)) as pf:
    ...     for x, y in pf:
    ...         step(x, y)
    """

    def __init__(self, source, ctx=None, depth=None, dtypes=None,
                 sharding=None, transfer_threads=1, chunk_threshold=None):
        if depth is None:
            from ..env import prefetch_depth
            depth = prefetch_depth()  # MXNET_PREFETCH_DEPTH
        self._ctx = Context(ctx) if ctx is not None else current_context()
        self._dev = self._ctx.jax_device()
        self._depth = max(1, int(depth))
        self._dtypes = dtypes
        self._source = source
        self._sharding = sharding
        self._tthreads = max(1, int(transfer_threads))
        self._pool = (ThreadPoolExecutor(self._tthreads,
                                         thread_name_prefix="mxtpu-h2d")
                      if self._tthreads > 1 else None)
        if sharding is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            mesh, spec = sharding.mesh, sharding.spec
            self._rep = NamedSharding(mesh, PartitionSpec())
            self._rank_shardings = [
                NamedSharding(mesh, PartitionSpec(*spec[:r]))
                for r in range(1, 9)]
            lead = spec[0] if len(spec) else None
            self._dp_size = 1
            for name in ((lead,) if isinstance(lead, str) else (lead or ())):
                self._dp_size *= mesh.shape[name]
        self._batch_ctr, self._ring_gauge, self._wait_hist = \
            _prefetch_metrics()
        self._q = queue.Queue(maxsize=self._depth)
        self._stop = threading.Event()
        self._thread = None
        self._done = False
        self._start()

    def _put(self, a):
        """One array to device: per-shard global placement under a
        sharding, plain async device_put otherwise."""
        if self._sharding is None:
            return jax.device_put(a, self._dev)
        from ..parallel.mesh import shard_put

        if (a.ndim == 0 or a.shape[0] < self._dp_size
                or a.shape[0] % self._dp_size):
            return shard_put(a, self._rep, pool=self._pool)
        return shard_put(a, self._rank_shardings[min(a.ndim, 8) - 1],
                         pool=self._pool)

    # ------------------------------------------------------------------
    def _pull(self):
        from ..resilience import faultline as _faultline

        _faultline.check("data.iterator")
        src = self._source
        if isinstance(src, DataIter):
            if hasattr(src, "next_arrays"):
                return src.next_arrays()
            batch = src.next()
            arrays = [d.asnumpy() for d in batch.data] + \
                     [l.asnumpy() for l in batch.label]
            return tuple(arrays)
        if callable(src):
            return src()
        return next(src)

    def _feed(self):
        while not self._stop.is_set():
            # the WHOLE batch production is under one handler — a dtype
            # cast or device_put that throws must reach the consumer as
            # the exception, not kill the thread and starve __next__
            try:
                arrays = self._pull()
                if self._dtypes is not None:
                    arrays = tuple(
                        a if dt is None else onp.asarray(a, dtype=dt)
                        for a, dt in zip(arrays, self._dtypes))
                # asynchronous: returns immediately with an in-flight
                # buffer; the bounded queue caps in-flight transfers
                bufs = tuple(self._put(a) for a in arrays)
            except StopIteration:
                self._q.put(_STOP)
                return
            except Exception as exc:  # re-raised at the consumer's __next__
                self._q.put(exc)
                return
            while not self._stop.is_set():
                try:
                    self._q.put(bufs, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def _start(self):
        self._thread = threading.Thread(target=self._feed, daemon=True,
                                        name="mxtpu-device-prefetch")
        self._thread.start()

    # ------------------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        import time as _time

        if self._done:
            raise StopIteration
        t0 = _time.perf_counter()
        while True:
            try:
                item = self._q.get(timeout=1.0)
                break
            except queue.Empty:
                # feeder gone without a sentinel (close() raced us, or it
                # died hard) — never block forever on a dead stream
                if self._thread is None or not self._thread.is_alive():
                    self._done = True
                    raise StopIteration from None
        if item is _STOP:
            self._done = True
            raise StopIteration
        if isinstance(item, Exception):
            self._done = True
            raise item
        self._wait_hist.observe(_time.perf_counter() - t0)
        self._batch_ctr.inc()
        self._ring_gauge.set(self._q.qsize())
        return tuple(NDArray(b, ctx=self._ctx) for b in item)

    next = __next__

    def next_batch(self):
        """One batch as a legacy ``DataBatch`` (all-but-last arrays = data,
        last = label) for DataIter-style consumers."""
        arrays = self.__next__()
        return DataBatch(data=list(arrays[:-1]), label=[arrays[-1]], pad=0)

    def reset(self):
        """Drain + restart the feeder (source must support reset)."""
        self.close()
        if hasattr(self._source, "reset"):
            self._source.reset()
        self._stop = threading.Event()
        self._q = queue.Queue(maxsize=self._depth)
        self._done = False
        if self._tthreads > 1 and self._pool is None:
            self._pool = ThreadPoolExecutor(self._tthreads,
                                            thread_name_prefix="mxtpu-h2d")
        self._start()

    def close(self):
        self._stop.set()
        # unblock a feeder waiting on a full queue
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def __enter__(self):
        return self

    def __exit__(self, *_exc):
        # the feeder must never outlive an exception in the consuming
        # loop: close() drains and joins unconditionally
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:  # mxlint: disable=swallowed-exception -- interpreter teardown: queue/thread modules may already be unloaded; nothing to report to
            pass
