"""Prefetch-to-device double buffering.

Reference: `src/io/iter_prefetcher.h:1` (thread-backed ``PrefetcherIter``)
and the DataLoader ``pin_memory`` path
(`python/mxnet/gluon/data/dataloader.py:48-138`).  The reference overlaps
decode -> H2D -> compute with dedicated prefetch machinery; on TPU the
equivalent is a feeder thread that issues *asynchronous* ``jax.device_put``
transfers for batch N+1..N+depth while the chip executes step N.  PjRt
orders a computation after the definition events of its input buffers, so
the consumer can dispatch the step immediately against an in-flight
transfer — the transfer and the previous step's compute proceed
concurrently and the step-time law becomes ``max(feed, compute)`` instead
of ``feed + compute``.

Two entry points:

- :class:`DevicePrefetcher` — wraps any source yielding tuples of host
  numpy arrays (or a ``DataIter``), delivers device-resident
  :class:`~mxnet_tpu.ndarray.ndarray.NDArray` batches.
- ``NDArray.prefetch_to(ctx)`` (see `ndarray/ndarray.py`) — one-shot async
  copy of a single array.
"""
from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor

import jax
import jax.numpy as jnp
import numpy as onp

from ..context import Context, current_context
from ..ndarray.ndarray import NDArray
from .io import DataBatch, DataIter

__all__ = ["DevicePrefetcher"]

_STOP = object()


class DevicePrefetcher:
    """Overlap host batch production and H2D transfer with device compute.

    Parameters
    ----------
    source : iterator / DataIter / callable
        Yields per-batch tuples of host numpy arrays.  A ``DataIter`` is
        consumed through ``next_arrays()`` when available (zero-copy host
        path), else ``next()``.  A callable is invoked per batch.
    ctx : Context, optional
        Target device (default: current context).
    depth : int
        Ring depth — how many batches may be in flight (decoded + queued on
        the wire) ahead of the consumer.  2 suffices for steady state
        (double buffering); 3 absorbs decode jitter.
    dtypes : tuple, optional
        Per-element dtype casts applied host-side before transfer (cheap on
        host; avoids an on-device cast dispatch for e.g. f32->i32 labels).

    Iteration yields tuples of device-resident NDArrays.  The transfer for
    a yielded batch may still be on the wire — PjRt serializes any compute
    consuming it after the transfer completes, which is exactly the overlap
    contract.  StopIteration from the source ends the stream; call
    ``reset()`` to rearm (source must support reset) or ``close()`` to
    reclaim the feeder thread.
    """

    def __init__(self, source, ctx=None, depth=2, dtypes=None,
                 transfer_threads=1, chunk_threshold=1 << 20):
        self._ctx = Context(ctx) if ctx is not None else current_context()
        self._dev = self._ctx.jax_device()
        self._depth = max(1, int(depth))
        self._dtypes = dtypes
        self._source = source
        # transfer_threads > 1 splits big arrays along axis 0, puts the
        # chunks from a pool, and concatenates on device — worth trying on
        # transports that multiplex concurrent streams; on the shared axon
        # tunnel A/B runs showed no consistent win, so default is 1
        self._tthreads = max(1, int(transfer_threads))
        self._chunk_threshold = chunk_threshold
        self._pool = (ThreadPoolExecutor(self._tthreads,
                                         thread_name_prefix="mxtpu-h2d")
                      if self._tthreads > 1 else None)
        self._q = queue.Queue(maxsize=self._depth)
        self._stop = threading.Event()
        self._thread = None
        self._done = False
        self._start()

    def _put(self, a):
        """One array to device: chunked multi-stream put when large."""
        if (self._pool is None or a.nbytes < self._chunk_threshold
                or a.ndim == 0 or a.shape[0] < 2):
            return jax.device_put(a, self._dev)
        n = min(self._tthreads, a.shape[0])
        chunks = onp.array_split(a, n, axis=0)
        parts = list(self._pool.map(
            lambda c: jax.device_put(c, self._dev), chunks))
        return jnp.concatenate(parts, axis=0)

    # ------------------------------------------------------------------
    def _pull(self):
        from ..resilience import faultline as _faultline

        _faultline.check("data.iterator")
        src = self._source
        if isinstance(src, DataIter):
            if hasattr(src, "next_arrays"):
                return src.next_arrays()
            batch = src.next()
            arrays = [d.asnumpy() for d in batch.data] + \
                     [l.asnumpy() for l in batch.label]
            return tuple(arrays)
        if callable(src):
            return src()
        return next(src)

    def _feed(self):
        while not self._stop.is_set():
            # the WHOLE batch production is under one handler — a dtype
            # cast or device_put that throws must reach the consumer as
            # the exception, not kill the thread and starve __next__
            try:
                arrays = self._pull()
                if self._dtypes is not None:
                    arrays = tuple(
                        a if dt is None else onp.asarray(a, dtype=dt)
                        for a, dt in zip(arrays, self._dtypes))
                # asynchronous: returns immediately with an in-flight
                # buffer; the bounded queue caps in-flight transfers
                bufs = tuple(self._put(a) for a in arrays)
            except StopIteration:
                self._q.put(_STOP)
                return
            except Exception as exc:  # re-raised at the consumer's __next__
                self._q.put(exc)
                return
            while not self._stop.is_set():
                try:
                    self._q.put(bufs, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def _start(self):
        self._thread = threading.Thread(target=self._feed, daemon=True,
                                        name="mxtpu-device-prefetch")
        self._thread.start()

    # ------------------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        while True:
            try:
                item = self._q.get(timeout=1.0)
                break
            except queue.Empty:
                # feeder gone without a sentinel (close() raced us, or it
                # died hard) — never block forever on a dead stream
                if self._thread is None or not self._thread.is_alive():
                    self._done = True
                    raise StopIteration from None
        if item is _STOP:
            self._done = True
            raise StopIteration
        if isinstance(item, Exception):
            self._done = True
            raise item
        return tuple(NDArray(b, ctx=self._ctx) for b in item)

    next = __next__

    def next_batch(self):
        """One batch as a legacy ``DataBatch`` (all-but-last arrays = data,
        last = label) for DataIter-style consumers."""
        arrays = self.__next__()
        return DataBatch(data=list(arrays[:-1]), label=[arrays[-1]], pad=0)

    def reset(self):
        """Drain + restart the feeder (source must support reset)."""
        self.close()
        if hasattr(self._source, "reset"):
            self._source.reset()
        self._stop = threading.Event()
        self._q = queue.Queue(maxsize=self._depth)
        self._done = False
        if self._tthreads > 1 and self._pool is None:
            self._pool = ThreadPoolExecutor(self._tthreads,
                                            thread_name_prefix="mxtpu-h2d")
        self._start()

    def close(self):
        self._stop.set()
        # unblock a feeder waiting on a full queue
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # mxlint: disable=swallowed-exception -- interpreter teardown: queue/thread modules may already be unloaded; nothing to report to
            pass
