"""Legacy data-iterator API.

Reference: `python/mxnet/io/io.py:179-799` — `DataDesc`/`DataBatch`/
`DataIter` protocol, `NDArrayIter` (pad/discard/roll_over last-batch
handling, shuffle), `ResizeIter`, `PrefetchingIter`, plus a `CSVIter`
equivalent of the C++ registered iterator (`src/io/iter_csv.cc`).

TPU-native notes: iterators yield host-side batches; the Gluon DataLoader
is the preferred pipeline, but this module keeps classic training scripts
running unmodified.  `PrefetchingIter` uses a background thread per
sub-iterator (the reference's `PrefetcherIter` is a C++ thread; here the
batch assembly is already numpy-bound so a Python thread overlaps fine).
"""
from __future__ import annotations

import collections
import threading

import numpy as onp

from ..ndarray.ndarray import NDArray

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "CSVIter",
           "LibSVMIter", "ResizeIter", "PrefetchingIter"]


class DataDesc(collections.namedtuple("DataDesc", ["name", "shape"])):
    """Data description incl. dtype/layout (reference `io.py` DataDesc)."""

    def __new__(cls, name, shape, dtype=onp.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return "DataDesc[%s,%s,%s,%s]" % (self.name, self.shape, self.dtype,
                                          self.layout)

    @staticmethod
    def get_batch_axis(layout):
        """Index of the batch ('N') axis; 0 when layout is unspecified."""
        if layout is None:
            return 0
        return layout.find("N")

    @staticmethod
    def get_list(shapes, types):
        if types is not None:
            type_dict = dict(types)
            return [DataDesc(x[0], x[1], type_dict[x[0]]) for x in shapes]
        return [DataDesc(x[0], x[1]) for x in shapes]


class DataBatch:
    """One mini-batch (reference `io.py` DataBatch)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None:
            assert isinstance(data, (list, tuple)), "data must be a list"
        if label is not None:
            assert isinstance(label, (list, tuple)), "label must be a list"
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        data_shapes = [d.shape for d in self.data]
        if self.label:
            label_shapes = [l.shape for l in self.label]
        else:
            label_shapes = None
        return "{}: data shapes: {} label shapes: {}".format(
            self.__class__.__name__, data_shapes, label_shapes)


class DataIter:
    """Iterator protocol (reference `io.py` DataIter)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    """Normalize array/list/dict input to an ordered list of (name, NDArray)
    (reference `io/utils.py` `_init_data`)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (onp.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d
                    for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of "
                        "them or dict with them as values")
    out = []
    # sorted by name, as the reference does (`io/utils.py` _init_data) —
    # classic scripts rely on this ordering of batch.data
    for k, v in sorted(data.items()):
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out.append((k, onp.ascontiguousarray(v)))
    return out


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference `io.py` NDArrayIter):
    supports shuffle and `last_batch_handle` in {'pad','discard',
    'roll_over'}."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False,
                               default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.idx = onp.arange(self.data[0][1].shape[0])
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.num_data = self.idx.shape[0]
        assert self.num_data >= batch_size, \
            "batch_size needs to be smaller than data size"
        self.cursor = -batch_size
        self._cache_data = None
        self._cache_label = None
        self._tail = 0
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def hard_reset(self):
        if self.shuffle:
            self._shuffle_data()
        self.cursor = -self.batch_size
        self._cache_data = None
        self._cache_label = None
        self._tail = 0

    def reset(self):
        if self.shuffle:
            self._shuffle_data()
        if self.last_batch_handle == "roll_over" and \
                self._cache_data is not None:
            # the cached tail (``self._tail`` rows) opens the new epoch: the
            # first batch sits at cursor = -tail after iter_next, taking the
            # cache plus batch_size - tail fresh head rows
            self.cursor = -self.batch_size - self._tail
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if not self.iter_next():
            raise StopIteration
        data = self.getdata()
        label = self.getlabel()
        if data[0].shape[0] != self.batch_size:
            if self.last_batch_handle == "discard":
                raise StopIteration
            if self.last_batch_handle == "roll_over":
                # keep the incomplete tail for the next epoch
                self._cache_data = data
                self._cache_label = label
                self._tail = data[0].shape[0]
                raise StopIteration
        return DataBatch(data=data, label=label, pad=self.getpad(),
                         index=None)

    def _getdata(self, data_source, start=None, end=None):
        assert start is not None or end is not None
        start = start if start is not None else 0
        end = end if end is not None else data_source[0][1].shape[0]
        s = slice(start, end)
        return [NDArray(x[1][self.idx[s]]) for x in data_source]

    def _concat(self, first, second):
        assert len(first) == len(second)
        return [NDArray(onp.concatenate(
            (f.asnumpy(), s.asnumpy()), axis=0)) for f, s in zip(first, second)]

    def _is_rolled_batch(self, cache):
        # first batch of an epoch opened by a rolled-over tail: after
        # iter_next the cursor sits at -tail, in (-batch_size, 0)
        return (self.last_batch_handle == "roll_over"
                and cache is not None
                and -self.batch_size < self.cursor < 0)

    def _batchify(self, data_source, cache):
        assert self.cursor < self.num_data, "DataIter needs reset."
        if self._is_rolled_batch(cache):
            # cached tail + the first batch_size - tail fresh head rows
            return self._concat(cache, self._getdata(
                data_source, start=0, end=self.cursor + self.batch_size))
        if self.cursor + self.batch_size <= self.num_data:
            return self._getdata(data_source, start=self.cursor,
                                 end=self.cursor + self.batch_size)
        # incomplete tail of the epoch
        first = self._getdata(data_source, start=self.cursor)
        if self.last_batch_handle == "pad":
            # wrap around to the head of the data
            pad = self.batch_size - self.num_data + self.cursor
            second = self._getdata(data_source, end=pad)
            return self._concat(first, second)
        return first

    def getdata(self):
        rolled = self._is_rolled_batch(self._cache_data)
        batch = self._batchify(self.data, self._cache_data)
        if rolled:
            self._cache_data = None
        return batch

    def getlabel(self):
        if not self.label:
            return []
        rolled = self._is_rolled_batch(self._cache_label)
        batch = self._batchify(self.label, self._cache_label)
        if rolled:
            self._cache_label = None
        return batch

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0

    def _shuffle_data(self):
        onp.random.shuffle(self.idx)


class CSVIter(DataIter):
    """Iterate rows of a CSV file (native parse via `src/csv.cc`, the
    C++ `CSVIter` role, `src/io/iter_csv.cc`): fixed `data_shape` per
    row, optional label CSV, round-robin padding of the last batch."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, dtype="float32",
                 data_name="data", label_name="softmax_label"):
        from .._native import parse_csv

        super().__init__(batch_size)
        data = parse_csv(data_csv).astype(dtype, copy=False)
        n = data.shape[0]
        data = data.reshape((n,) + tuple(data_shape))
        if label_csv is not None:
            label = parse_csv(label_csv).astype(dtype, copy=False)
            label = label.reshape((n,) + tuple(label_shape))
        else:
            label = onp.zeros((n,) + tuple(label_shape), dtype=dtype)
        # both round_batch modes emit the final partial batch at full size
        # with `pad` set (reference `iter_batchloader.h` emits a padded last
        # batch either way; only the fill source differs)
        self._iter = NDArrayIter(
            {data_name: data}, {label_name: label}, batch_size=batch_size,
            last_batch_handle="pad",
            data_name=data_name, label_name=label_name)

    @property
    def provide_data(self):
        return self._iter.provide_data

    @property
    def provide_label(self):
        return self._iter.provide_label

    def reset(self):
        self._iter.reset()

    def iter_next(self):
        return self._iter.iter_next()

    def next(self):
        return self._iter.next()

    def getdata(self):
        return self._iter.getdata()

    def getlabel(self):
        return self._iter.getlabel()

    def getpad(self):
        return self._iter.getpad()


class ResizeIter(DataIter):
    """Resize an iterator to `size` batches per epoch (reference `io.py`
    ResizeIter), re-looping the underlying iterator as needed."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        if hasattr(data_iter, "default_bucket_key"):
            self.default_bucket_key = data_iter.default_bucket_key

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Overlap batch assembly with compute using one background thread per
    sub-iterator (reference `io.py` PrefetchingIter)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        super().__init__()
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0].shape[0] * self.n_iter
        self.data_ready = [threading.Event() for _ in range(self.n_iter)]
        self.data_taken = [threading.Event() for _ in range(self.n_iter)]
        for e in self.data_taken:
            e.set()
        self._stop = threading.Event()
        self.current_batch = None
        # per-iterator slot: [batch_or_None, exception_or_None]; threads
        # close over these objects, NOT over self, so dropping the iterator
        # releases it (the threads are then shut down by close()/__del__)
        self._slots = [[None, None] for _ in range(self.n_iter)]

        def prefetch_func(it, taken, ready, slot, stop):
            while True:
                taken.wait()
                if stop.is_set():
                    break
                try:
                    slot[0] = it.next()
                except StopIteration:
                    slot[0] = None
                except Exception as exc:  # surfaced in iter_next
                    slot[0] = None
                    slot[1] = exc
                taken.clear()
                ready.set()

        self.prefetch_threads = [
            threading.Thread(
                target=prefetch_func,
                args=(self.iters[i], self.data_taken[i], self.data_ready[i],
                      self._slots[i], self._stop),
                daemon=True)
            for i in range(self.n_iter)]
        for t in self.prefetch_threads:
            t.start()

    def close(self):
        """Stop the prefetch threads (also called on garbage collection)."""
        self._stop.set()
        for e in self.data_taken:
            e.set()
        for t in self.prefetch_threads:
            t.join(timeout=1.0)

    def __del__(self):
        self.close()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[
            DataDesc(r[x.name], x.shape, x.dtype)
            if isinstance(x, DataDesc) else DataDesc(*x)
            for x in i.provide_data
        ] for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[
            DataDesc(r[x.name], x.shape, x.dtype)
            if isinstance(x, DataDesc) else DataDesc(*x)
            for x in i.provide_label
        ] for r, i in zip(self.rename_label, self.iters)], [])

    def reset(self):
        for e in self.data_ready:
            e.wait()
        for i in self.iters:
            i.reset()
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()

    def iter_next(self):
        for e in self.data_ready:
            e.wait()
        for slot in self._slots:
            if slot[1] is not None:  # a prefetch thread hit an error
                exc, slot[1] = slot[1], None
                raise exc
        batches = [slot[0] for slot in self._slots]
        if batches[0] is None:
            # all sub-iterators end together
            for b in batches:
                assert b is None, "Number of entry mismatches between iters"
            return False
        for b in batches:
            assert b.pad == batches[0].pad, \
                "Different pad size in sub-iterators"
        self.current_batch = DataBatch(
            sum([b.data for b in batches], []),
            sum([b.label for b in batches], []),
            batches[0].pad,
            batches[0].index,
            provide_data=self.provide_data,
            provide_label=self.provide_label)
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class LibSVMIter(DataIter):
    """Iterate a LibSVM-format file as CSR batches (reference C++
    `LibSVMIter`, `src/io/iter_libsvm.cc`): each batch yields a
    `CSRNDArray` data block and a dense label vector.  Parsing runs in the
    native C++ core (`mxnet_tpu/src/libsvm.cc`) when built."""

    def __init__(self, data_libsvm, data_shape=None, label_libsvm=None,
                 batch_size=1, round_batch=True, data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        from .._native import parse_libsvm
        from ..ndarray import sparse

        labels, indptr, indices, values, ncols = parse_libsvm(data_libsvm)
        if data_shape is not None:
            ncols = data_shape[0] if isinstance(data_shape, (tuple, list)) \
                else int(data_shape)
            if len(indices) and int(indices.max()) >= ncols:
                raise ValueError(
                    f"data_shape={ncols} is smaller than the largest "
                    f"feature index {int(indices.max())} in {data_libsvm}")
        self._sparse = sparse
        self._csr = sparse.CSRNDArray(values, indices, indptr,
                                      (len(labels), ncols))
        if label_libsvm is not None:
            ext_labels = parse_libsvm(label_libsvm)[0]
            if len(ext_labels) != len(labels):
                raise ValueError(
                    f"label file has {len(ext_labels)} rows but data file "
                    f"has {len(labels)}")
            labels = ext_labels
        self._labels = labels
        self._ncols = ncols
        self.num_data = len(labels)
        assert self.num_data >= batch_size
        self._round = round_batch
        self.data_name = data_name
        self.label_name = label_name
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name, (self.batch_size, self._ncols))]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name, (self.batch_size,))]

    def reset(self):
        self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _rows(self, idxs):
        indptr = self._csr.indptr
        data, indices, new_indptr = [], [], [0]
        for r in idxs:
            lo, hi = indptr[r], indptr[r + 1]
            data.append(self._csr.data[lo:hi])
            indices.append(self._csr.indices[lo:hi])
            new_indptr.append(new_indptr[-1] + (hi - lo))
        return self._sparse.CSRNDArray(
            onp.concatenate(data), onp.concatenate(indices),
            onp.asarray(new_indptr, onp.int64),
            (len(idxs), self._ncols))

    def next(self):
        if not self.iter_next():
            raise StopIteration
        end = self.cursor + self.batch_size
        idxs = list(range(self.cursor, min(end, self.num_data)))
        pad = end - self.num_data if end > self.num_data else 0
        if pad:
            if not self._round:
                raise StopIteration
            idxs += list(range(pad))  # wrap to the head, reference-style
        batch = DataBatch([self._rows(idxs)],
                          [NDArray(self._labels[idxs])], pad=pad)
        return batch

    def getpad(self):
        end = self.cursor + self.batch_size
        return end - self.num_data if end > self.num_data else 0
