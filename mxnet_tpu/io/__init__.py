"""Legacy data iterators (reference: `python/mxnet/io/`)."""
from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, CSVIter,
                 ResizeIter, PrefetchingIter)
from .bucket import BucketSentenceIter

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "CSVIter",
           "ResizeIter", "PrefetchingIter", "BucketSentenceIter"]
