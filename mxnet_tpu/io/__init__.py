"""Legacy data iterators (reference: `python/mxnet/io/`)."""
from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, CSVIter,
                 LibSVMIter, ResizeIter, PrefetchingIter)
from .bucket import BucketSentenceIter
from .image_record import ImageRecordIter
from .prefetch import DevicePrefetcher

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "CSVIter",
           "LibSVMIter", "ResizeIter", "PrefetchingIter", "BucketSentenceIter",
           "ImageRecordIter", "DevicePrefetcher"]
