"""``mx.np.linalg`` — XLA lowerings of the reference's linalg ops
(`src/operator/numpy/linalg/`, `src/operator/tensor/la_op.cc`)."""
from __future__ import annotations

import jax.numpy as jnp

from ..ops.invoke import invoke

_FUNCS = [
    "norm", "svd", "qr", "cholesky", "inv", "pinv", "det", "slogdet",
    "eig", "eigh", "eigvals", "eigvalsh", "solve", "lstsq", "matrix_rank",
    "matrix_power", "multi_dot", "tensorinv", "tensorsolve", "cond",
]

_g = globals()
for _name in _FUNCS:
    _jf = getattr(jnp.linalg, _name, None)
    if _jf is None:
        continue

    def _make(jf, name):
        def fn(*args, **kwargs):
            return invoke(jf, args, kwargs, name=f"linalg.{name}")
        fn.__name__ = name
        return fn

    _g[_name] = _make(_jf, _name)

__all__ = [n for n in _FUNCS if n in _g]
