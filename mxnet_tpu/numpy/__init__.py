"""``mx.np`` — the NumPy-compatible imperative op surface.

Reference: `python/mxnet/numpy/multiarray.py` (12k LoC of generated wrappers
over the `_npi.*` C++ ops, `src/operator/numpy/`).  TPU-native design: every
op is a jax.numpy lowering dispatched through `ops/invoke.py`, which gives
async execution, autograd recording, and jit-traceability in one place.  The
554-op C++ registry collapses to this table because XLA owns kernel codegen.
"""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import numeric_types
from ..context import Context, current_context
from ..ndarray.ndarray import NDArray, waitall
from ..ops.invoke import invoke

ndarray = NDArray

# dtype aliases (mx.np.float32 etc.)
float16 = onp.float16
float32 = onp.float32
float64 = onp.float64
bfloat16 = jnp.bfloat16
int8 = onp.int8
int16 = onp.int16
int32 = onp.int32
int64 = onp.int64
uint8 = onp.uint8
uint16 = onp.uint16
uint32 = onp.uint32
uint64 = onp.uint64
bool_ = onp.bool_
pi = onp.pi
e = onp.e
euler_gamma = onp.euler_gamma
inf = onp.inf
nan = onp.nan
newaxis = None
_np_version = onp.__version__


def _apply_out(res, out):
    if out is None:
        return res
    out._rebind(res)
    return out


def _make_op(jfun, name, differentiable=True):
    def fn(*args, **kwargs):
        out = kwargs.pop("out", None)
        res = invoke(jfun, args, kwargs, name=name, differentiable=differentiable)
        return _apply_out(res, out)

    fn.__name__ = name
    fn.__qualname__ = name
    fn.__doc__ = f"TPU lowering of np.{name} (see jax.numpy.{name})."
    return fn


# ops whose outputs are integer/boolean — skip vjp recording
_NON_DIFF = {
    "argmax", "argmin", "argsort", "argwhere", "nonzero", "flatnonzero",
    "searchsorted", "digitize", "bincount", "count_nonzero", "unique",
    "equal", "not_equal", "less", "less_equal", "greater", "greater_equal",
    "isclose", "isfinite", "isinf", "isnan", "isneginf", "isposinf",
    "logical_and", "logical_or", "logical_xor", "logical_not", "signbit",
    "floor_divide", "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "invert", "left_shift", "right_shift", "rint", "fix", "trunc",
    "floor", "ceil", "around", "round", "sign", "allclose", "array_equal",
    "may_share_memory", "shares_memory", "result_type", "unravel_index",
}

_JNP_FUNCS = [
    # elementwise math
    "abs", "absolute", "add", "subtract", "multiply", "divide", "true_divide",
    "floor_divide", "mod", "remainder", "fmod", "power", "float_power",
    "negative", "positive", "reciprocal", "sqrt", "cbrt", "square", "exp",
    "expm1", "exp2", "log", "log2", "log10", "log1p", "sign", "fabs",
    "rint", "fix", "trunc", "floor", "ceil", "around", "round", "clip",
    "maximum", "minimum", "fmax", "fmin", "copysign", "nextafter", "ldexp",
    "gcd", "lcm", "heaviside", "nan_to_num", "real", "imag", "conj",
    "conjugate", "angle", "hypot", "logaddexp", "logaddexp2", "sinc",
    "signbit", "frexp", "modf", "divmod", "trunc",
    # trig / hyperbolic
    "sin", "cos", "tan", "arcsin", "arccos", "arctan", "arctan2",
    "sinh", "cosh", "tanh", "arcsinh", "arccosh", "arctanh",
    "deg2rad", "rad2deg", "degrees", "radians",
    # comparisons / logic
    "equal", "not_equal", "less", "less_equal", "greater", "greater_equal",
    "isclose", "allclose", "array_equal", "isfinite", "isinf", "isnan",
    "isneginf", "isposinf", "logical_and", "logical_or", "logical_xor",
    "logical_not",
    # bitwise
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not", "invert",
    "left_shift", "right_shift",
    # reductions
    "sum", "prod", "mean", "std", "var", "max", "min", "amax", "amin",
    "ptp", "median", "percentile", "quantile", "average", "cumsum",
    "cumprod", "nansum", "nanprod", "nanmean", "nanstd", "nanvar", "nanmax",
    "nanmin", "nanmedian", "nanpercentile", "nanquantile", "all", "any",
    "count_nonzero", "trace",
    # index / search / sort
    "argmax", "argmin", "argsort", "sort", "argwhere", "nonzero",
    "flatnonzero", "searchsorted", "digitize", "bincount", "unique",
    "take", "take_along_axis", "compress", "extract", "unravel_index",
    "diag_indices_from", "tril_indices", "triu_indices",
    # shape manipulation
    "reshape", "ravel", "transpose", "swapaxes", "moveaxis", "rollaxis",
    "expand_dims", "squeeze", "broadcast_to", "broadcast_arrays",
    "atleast_1d", "atleast_2d", "atleast_3d", "concatenate", "stack",
    "vstack", "hstack", "dstack", "column_stack", "row_stack", "split",
    "array_split", "hsplit", "vsplit", "dsplit", "tile", "repeat", "flip",
    "fliplr", "flipud", "roll", "rot90", "pad", "insert", "delete",
    "append", "resize", "trim_zeros", "flatten" if hasattr(jnp, "flatten") else "ravel",
    # linear algebra (top-level)
    "dot", "vdot", "inner", "outer", "matmul", "tensordot", "einsum",
    "kron", "cross", "diag", "diagflat", "diagonal", "tril", "triu",
    "trace", "convolve", "correlate",
    # misc
    "where", "interp", "diff", "ediff1d", "gradient", "histogram",
    "histogram2d", "histogram_bin_edges", "meshgrid", "polyval", "polyfit",
    "apply_along_axis", "may_share_memory", "shares_memory", "result_type",
    "isscalar", "ndim", "shape", "size",
]

_g = globals()
for _name in _JNP_FUNCS:
    if _name in _g:
        continue
    if _name == "fix":  # deprecated alias in jnp; identical semantics
        _g["fix"] = _make_op(jnp.trunc, "fix", differentiable=False)
        continue
    _jf = getattr(jnp, _name, None)
    if _jf is None:
        continue
    _g[_name] = _make_op(_jf, _name, differentiable=_name not in _NON_DIFF)

_NON_DIFF |= {"nanargmax", "nanargmin", "isin", "in1d", "intersect1d",
              "union1d", "setdiff1d", "diag_indices", "packbits",
              "spacing", "ix_"}
# second wave: set ops (data-dependent shapes → eager-only, like
# boolean_mask), nan arg-reductions, statistics, polynomial utilities
for _name in ["nanargmax", "nanargmin", "isin", "intersect1d", "union1d",
              "setdiff1d", "piecewise", "corrcoef", "cov", "unwrap",
              "vander", "diag_indices", "packbits", "spacing",
              "block", "ix_"]:
    if _name in _g:
        continue
    _jf = getattr(jnp, _name, None)
    if _jf is None:
        continue
    _g[_name] = _make_op(_jf, _name, differentiable=_name not in _NON_DIFF)

# window functions (`_npi_blackman/hamming/hanning`,
# `src/operator/numpy/np_window_op.cc`) and index raveling
# (`_ravel_multi_index`, `src/operator/tensor/ravel.cc`)
for _name in ["blackman", "hamming", "hanning", "bartlett", "kaiser"]:
    _jf = getattr(jnp, _name, None)
    if _jf is not None and _name not in _g:
        _g[_name] = _make_op(_jf, _name, differentiable=False)
def _ravel_multi_index(multi_index, dims, mode="raise", order="C"):
    # jnp has no traced 'raise' mode; do the bounds check on host values
    # (this op is eager-only anyway — flat indices feed host-side code)
    if mode == "raise":
        idx = onp.asarray(multi_index.asnumpy()
                          if hasattr(multi_index, "asnumpy")
                          else multi_index)
        lim = onp.asarray(dims).reshape((-1,) + (1,) * (idx.ndim - 1))
        if (idx < 0).any() or (idx >= lim).any():
            raise ValueError("invalid entry in coordinates array")
        mode = "clip"   # already validated; clip is now a no-op
    return jnp.ravel_multi_index(tuple(multi_index), tuple(dims),
                                 mode=mode, order=order)


ravel_multi_index = _make_op(_ravel_multi_index, "ravel_multi_index",
                             differentiable=False)

# renamed/removed jnp aliases with reference-era numpy names
row_stack = _g.get("vstack")
trapz = _make_op(jnp.trapezoid, "trapz")
round_ = _g.get("round")
in1d = _make_op(lambda ar1, ar2, **kw: jnp.isin(ar1, ar2, **kw), "in1d",
                differentiable=False)


# functional variants of numpy's in-place mutators (XLA buffers are
# immutable): these RETURN the updated array instead of mutating
fill_diagonal = _make_op(
    lambda a, val, wrap=False: jnp.fill_diagonal(a, val, wrap=wrap,
                                                 inplace=False),
    "fill_diagonal")
put_along_axis = _make_op(
    lambda a, idx, vals, axis: jnp.put_along_axis(a, idx, vals, axis,
                                                  inplace=False),
    "put_along_axis")


def roots(p):
    """Polynomial roots.  The underlying nonsymmetric eigensolver ('eig')
    has no TPU lowering, so this computes on host numpy — eager-only,
    like the reference's LAPACK-backed ops."""
    arr = p.asnumpy() if hasattr(p, "asnumpy") else onp.asarray(p)
    from ..ndarray.ndarray import NDArray
    return NDArray(jnp.asarray(onp.roots(arr)))


# ---------------------------------------------------------------------------
# creation ops — honor ctx/device kwarg (reference: `mx.np.zeros(ctx=...)`)
# ---------------------------------------------------------------------------
def _creation(jfun, name):
    def fn(*args, ctx=None, device=None, out=None, **kwargs):
        c = Context(ctx or device) if (ctx or device) is not None else current_context()
        with jax.default_device(c.jax_device()):
            res = invoke(jfun, args, kwargs, name=name)
        if isinstance(res, NDArray):
            res._ctx = c
        return _apply_out(res, out)

    fn.__name__ = name
    return fn


def array(object, dtype=None, ctx=None, device=None):
    if dtype is None and not hasattr(object, "dtype"):
        # reference defaults python lists/scalars to float32
        probe = onp.asarray(object)
        if probe.dtype.kind == "f":
            dtype = onp.float32
        elif probe.dtype == onp.int64 and not jax.config.jax_enable_x64:
            dtype = onp.int32
        else:
            dtype = probe.dtype
    return NDArray(object._data if isinstance(object, NDArray) else object,
                   ctx=ctx or device, dtype=dtype)


def asarray(a, dtype=None):
    if isinstance(a, NDArray) and dtype is None:
        return a
    return array(a, dtype=dtype)


def _default_float(dtype):
    return onp.float32 if dtype is None else dtype


def zeros(shape, dtype=None, order="C", ctx=None, device=None):
    return _creation(lambda: jnp.zeros(shape, _default_float(dtype)), "zeros")(
        ctx=ctx, device=device)


def ones(shape, dtype=None, order="C", ctx=None, device=None):
    return _creation(lambda: jnp.ones(shape, _default_float(dtype)), "ones")(
        ctx=ctx, device=device)


def full(shape, fill_value, dtype=None, order="C", ctx=None, device=None, out=None):
    def f(fv):
        return jnp.full(shape, fv, dtype)
    c = Context(ctx or device) if (ctx or device) is not None else current_context()
    with jax.default_device(c.jax_device()):
        res = invoke(f, (fill_value,), name="full")
    return _apply_out(res, out)


def empty(shape, dtype=None, order="C", ctx=None, device=None):
    return zeros(shape, dtype=dtype, ctx=ctx, device=device)


def zeros_like(a, dtype=None, order="C", ctx=None, device=None):
    return invoke(lambda x: jnp.zeros_like(x, dtype=dtype), (a,), name="zeros_like")


def ones_like(a, dtype=None, order="C", ctx=None, device=None):
    return invoke(lambda x: jnp.ones_like(x, dtype=dtype), (a,), name="ones_like")


def full_like(a, fill_value, dtype=None, ctx=None, device=None):
    return invoke(lambda x: jnp.full_like(x, fill_value, dtype=dtype), (a,),
                  name="full_like")


def empty_like(a, dtype=None):
    return zeros_like(a, dtype=dtype)


def arange(start, stop=None, step=1, dtype=None, ctx=None, device=None):
    def f():
        d = dtype
        if d is None:
            # reference arange defaults to float32 unless ints given
            if builtins.all(isinstance(v, (int, type(None)))
                            for v in (start, stop)) and isinstance(step, int):
                d = onp.float32
        return jnp.arange(start, stop, step, d)
    return _creation(f, "arange")(ctx=ctx, device=device)


def linspace(start, stop, num=50, endpoint=True, retstep=False, dtype=None,
             axis=0, ctx=None, device=None):
    return _creation(
        lambda: jnp.linspace(start, stop, num, endpoint=endpoint,
                             retstep=retstep, dtype=dtype, axis=axis),
        "linspace")(ctx=ctx, device=device)


def logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None,
             axis=0, ctx=None, device=None):
    return _creation(
        lambda: jnp.logspace(start, stop, num, endpoint=endpoint, base=base,
                             dtype=dtype, axis=axis),
        "logspace")(ctx=ctx, device=device)


def eye(N, M=None, k=0, dtype=None, ctx=None, device=None):
    return _creation(lambda: jnp.eye(N, M, k, _default_float(dtype)), "eye")(
        ctx=ctx, device=device)


def identity(n, dtype=None, ctx=None, device=None):
    return eye(n, dtype=dtype, ctx=ctx, device=device)


def tri(N, M=None, k=0, dtype=None, ctx=None, device=None):
    return _creation(lambda: jnp.tri(N, M, k, _default_float(dtype)), "tri")(
        ctx=ctx, device=device)


def indices(dimensions, dtype=None, ctx=None, device=None):
    return _creation(lambda: jnp.indices(dimensions, dtype or onp.int32),
                     "indices")(ctx=ctx, device=device)


def copy(a):
    return a.copy() if isinstance(a, NDArray) else array(a)


def may_share_memory(a, b, max_work=None):  # noqa: ARG001
    return False


def shares_memory(a, b, max_work=None):  # noqa: ARG001
    return False


def expm1_(*a, **k):  # compat no-op guard
    raise NotImplementedError


def dtype(d):
    return onp.dtype(d)


def concatenate(seq, axis=0, out=None):
    res = invoke(lambda *xs: jnp.concatenate(xs, axis=axis), tuple(seq),
                 name="concatenate")
    return _apply_out(res, out)


def stack(seq, axis=0, out=None):
    res = invoke(lambda *xs: jnp.stack(xs, axis=axis), tuple(seq), name="stack")
    return _apply_out(res, out)


def copyto(dst, src):
    """NumPy-compatible copyto: broadcast src into dst in place."""
    src_nd = src if isinstance(src, NDArray) else array(src)
    if src_nd.shape != dst.shape:
        src_nd = broadcast_to(src_nd, dst.shape)
    src_nd.copyto(dst)
    return dst


def isnat(*_a, **_k):
    raise NotImplementedError("datetime dtypes are not supported on TPU")


from . import linalg  # noqa: E402
from . import random  # noqa: E402

__all__ = [n for n in dir() if not n.startswith("_")]
