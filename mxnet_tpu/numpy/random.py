"""``mx.np.random`` — sampling ops.

Reference: `src/operator/numpy/random/` + `src/operator/random/sample_op.cc`,
driven by engine PRNG resources (`src/resource.cc:93`).  TPU-native design:
XLA threefry keys from the stateful stream in `mxnet_tpu.random` (fresh key
per draw; traced key stream under hybridize so compiled programs stay random).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as onp

from ..context import Context, current_context
from ..ndarray.ndarray import NDArray
from ..ops.invoke import invoke
from .. import random as _rng

__all__ = [
    "seed", "uniform", "normal", "randn", "rand", "randint", "choice",
    "shuffle", "permutation", "gamma", "beta", "exponential", "poisson",
    "bernoulli", "binomial", "multinomial", "laplace", "gumbel", "logistic",
    "lognormal", "chisquare", "rayleigh", "pareto", "power", "weibull",
    "multivariate_normal", "f", "standard_normal", "standard_exponential",
    "standard_gamma", "t", "geometric", "negative_binomial",
]

seed = _rng.seed


def _size(size, *broadcast_args):
    if size is not None:
        return (size,) if isinstance(size, int) else tuple(size)
    shp = ()
    for a in broadcast_args:
        if hasattr(a, "shape"):
            shp = onp.broadcast_shapes(shp, tuple(a.shape))
    return shp


def _sample(fun, args, size=None, dtype=None, ctx=None, device=None, out=None,
            name="sample"):
    key = _rng.new_key()
    c = Context(ctx or device) if (ctx or device) is not None else None

    def f(*arrs):
        return fun(key, *arrs)

    if c is not None:
        with jax.default_device(c.jax_device()):
            res = invoke(f, args, name=name)
        res._ctx = c
    else:
        res = invoke(f, args, name=name)
    if out is not None:
        out._rebind(res)
        return out
    return res


def uniform(low=0.0, high=1.0, size=None, dtype=None, ctx=None, device=None,
            out=None):
    dtype = dtype or onp.float32
    shp = _size(size, low, high)

    def fun(key, lo, hi):
        lo = jnp.asarray(lo, dtype)
        hi = jnp.asarray(hi, dtype)
        return jax.random.uniform(key, shp, dtype) * (hi - lo) + lo

    return _sample(fun, (low, high), size, dtype, ctx, device, out, "uniform")


def normal(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None, device=None,
           out=None):
    dtype = dtype or onp.float32
    shp = _size(size, loc, scale)

    def fun(key, mu, sigma):
        return jax.random.normal(key, shp, dtype) * jnp.asarray(sigma, dtype) \
            + jnp.asarray(mu, dtype)

    return _sample(fun, (loc, scale), size, dtype, ctx, device, out, "normal")


def standard_normal(size=None, dtype=None, ctx=None, device=None):
    return normal(0.0, 1.0, size=size, dtype=dtype, ctx=ctx, device=device)


def randn(*shape, dtype=None, ctx=None, device=None):
    return normal(0.0, 1.0, size=shape or None, dtype=dtype, ctx=ctx, device=device)


def rand(*shape, dtype=None, ctx=None, device=None):
    return uniform(0.0, 1.0, size=shape or None, dtype=dtype, ctx=ctx, device=device)


def randint(low, high=None, size=None, dtype=None, ctx=None, device=None,
            out=None):
    if high is None:
        low, high = 0, low
    dtype = dtype or onp.int32
    shp = _size(size)

    def fun(key):
        return jax.random.randint(key, shp, low, high, dtype)

    return _sample(fun, (), size, dtype, ctx, device, out, "randint")


def choice(a, size=None, replace=True, p=None, ctx=None, device=None, out=None):
    shp = _size(size)

    def fun(key, *arrs):
        arr = arrs[0] if isinstance(a, NDArray) else jnp.arange(a)
        probs = arrs[-1] if p is not None else None
        return jax.random.choice(key, arr, shp, replace, probs)

    args = tuple(x for x in (a if isinstance(a, NDArray) else None, p)
                 if x is not None)
    return _sample(fun, args, size, None, ctx, device, out, "choice")


def permutation(x, ctx=None, device=None):
    def fun(key, *arrs):
        arr = arrs[0] if arrs else jnp.arange(x)
        return jax.random.permutation(key, arr)

    args = (x,) if isinstance(x, NDArray) else ()
    return _sample(fun, args, None, None, ctx, device, None, "permutation")


def shuffle(x):
    """In-place shuffle along axis 0 (reference `_npi_shuffle`)."""
    x._rebind(permutation(x))
    return None


def gamma(shape, scale=1.0, size=None, dtype=None, ctx=None, device=None,
          out=None):
    dtype = dtype or onp.float32
    shp = _size(size, shape, scale)

    def fun(key, k, theta):
        return jax.random.gamma(key, jnp.asarray(k, dtype), shp, dtype) * \
            jnp.asarray(theta, dtype)

    return _sample(fun, (shape, scale), size, dtype, ctx, device, out, "gamma")


def standard_gamma(shape, size=None, dtype=None, ctx=None, device=None):
    return gamma(shape, 1.0, size=size, dtype=dtype, ctx=ctx, device=device)


def beta(a, b, size=None, dtype=None, ctx=None, device=None):
    dtype = dtype or onp.float32
    shp = _size(size, a, b)

    def fun(key, aa, bb):
        return jax.random.beta(key, jnp.asarray(aa, dtype),
                               jnp.asarray(bb, dtype), shp, dtype)

    return _sample(fun, (a, b), size, dtype, ctx, device, None, "beta")


def exponential(scale=1.0, size=None, dtype=None, ctx=None, device=None,
                out=None):
    dtype = dtype or onp.float32
    shp = _size(size, scale)

    def fun(key, s):
        return jax.random.exponential(key, shp, dtype) * jnp.asarray(s, dtype)

    return _sample(fun, (scale,), size, dtype, ctx, device, out, "exponential")


def standard_exponential(size=None, dtype=None, ctx=None, device=None):
    return exponential(1.0, size=size, dtype=dtype, ctx=ctx, device=device)


def poisson(lam=1.0, size=None, dtype=None, ctx=None, device=None):
    dtype = dtype or onp.int32
    shp = _size(size, lam)

    def fun(key, l):
        return jax.random.poisson(key, jnp.asarray(l), shp).astype(dtype)

    return _sample(fun, (lam,), size, dtype, ctx, device, None, "poisson")


def bernoulli(prob=None, logit=None, size=None, dtype=None, ctx=None,
              device=None):
    dtype = dtype or onp.float32
    assert (prob is None) != (logit is None), "pass exactly one of prob/logit"
    arg = prob if prob is not None else logit
    shp = _size(size, arg)

    def fun(key, p):
        pp = jax.nn.sigmoid(jnp.asarray(p)) if logit is not None else jnp.asarray(p)
        return jax.random.bernoulli(key, pp, shp or None).astype(dtype)

    return _sample(fun, (arg,), size, dtype, ctx, device, None, "bernoulli")


def binomial(n, p, size=None, dtype=None, ctx=None, device=None):
    dtype = dtype or onp.int32
    shp = _size(size, n, p)

    def fun(key, nn, pp):
        return jax.random.binomial(key, jnp.asarray(nn, onp.float32),
                                   jnp.asarray(pp, onp.float32),
                                   shp or None).astype(dtype)

    return _sample(fun, (n, p), size, dtype, ctx, device, None, "binomial")


def multinomial(n, pvals, size=None, ctx=None, device=None):
    shp = _size(size)

    def fun(key, pv):
        counts = jax.random.multinomial(
            key, jnp.asarray(n, onp.float32),
            jnp.asarray(pv, onp.float32),
            shape=(shp + (jnp.asarray(pv).shape[-1],)) if shp else None)
        return counts.astype(onp.int64)

    return _sample(fun, (pvals,), size, None, ctx, device, None, "multinomial")


def laplace(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None, device=None,
            out=None):
    dtype = dtype or onp.float32
    shp = _size(size, loc, scale)

    def fun(key, mu, b):
        return jax.random.laplace(key, shp, dtype) * jnp.asarray(b, dtype) + \
            jnp.asarray(mu, dtype)

    return _sample(fun, (loc, scale), size, dtype, ctx, device, out, "laplace")


def gumbel(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None, device=None):
    dtype = dtype or onp.float32
    shp = _size(size, loc, scale)

    def fun(key, mu, b):
        return jax.random.gumbel(key, shp, dtype) * jnp.asarray(b, dtype) + \
            jnp.asarray(mu, dtype)

    return _sample(fun, (loc, scale), size, dtype, ctx, device, None, "gumbel")


def logistic(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None, device=None):
    dtype = dtype or onp.float32
    shp = _size(size, loc, scale)

    def fun(key, mu, s):
        return jax.random.logistic(key, shp, dtype) * jnp.asarray(s, dtype) + \
            jnp.asarray(mu, dtype)

    return _sample(fun, (loc, scale), size, dtype, ctx, device, None, "logistic")


def lognormal(mean=0.0, sigma=1.0, size=None, dtype=None, ctx=None, device=None):
    dtype = dtype or onp.float32
    shp = _size(size, mean, sigma)

    def fun(key, mu, s):
        return jnp.exp(jax.random.normal(key, shp, dtype) *
                       jnp.asarray(s, dtype) + jnp.asarray(mu, dtype))

    return _sample(fun, (mean, sigma), size, dtype, ctx, device, None, "lognormal")


def chisquare(df, size=None, dtype=None, ctx=None, device=None):
    dtype = dtype or onp.float32
    shp = _size(size, df)

    def fun(key, d):
        return jax.random.chisquare(key, jnp.asarray(d, dtype), shape=shp or None,
                                    dtype=dtype)

    return _sample(fun, (df,), size, dtype, ctx, device, None, "chisquare")


def rayleigh(scale=1.0, size=None, dtype=None, ctx=None, device=None):
    dtype = dtype or onp.float32
    shp = _size(size, scale)

    def fun(key, s):
        return jax.random.rayleigh(key, shape=shp or None, dtype=dtype) * \
            jnp.asarray(s, dtype)

    return _sample(fun, (scale,), size, dtype, ctx, device, None, "rayleigh")


def pareto(a, size=None, dtype=None, ctx=None, device=None):
    dtype = dtype or onp.float32
    shp = _size(size, a)

    def fun(key, aa):
        return jax.random.pareto(key, jnp.asarray(aa, dtype), shape=shp or None,
                                 dtype=dtype) - 1.0

    return _sample(fun, (a,), size, dtype, ctx, device, None, "pareto")


def power(a, size=None, dtype=None, ctx=None, device=None):
    dtype = dtype or onp.float32
    shp = _size(size, a)

    def fun(key, aa):
        u = jax.random.uniform(key, shp, dtype)
        return u ** (1.0 / jnp.asarray(aa, dtype))

    return _sample(fun, (a,), size, dtype, ctx, device, None, "power")


def weibull(a, size=None, dtype=None, ctx=None, device=None):
    dtype = dtype or onp.float32
    shp = _size(size, a)

    def fun(key, aa):
        return jax.random.weibull_min(key, 1.0, jnp.asarray(aa, dtype),
                                      shape=shp or None, dtype=dtype)

    return _sample(fun, (a,), size, dtype, ctx, device, None, "weibull")


def multivariate_normal(mean, cov, size=None, ctx=None, device=None):
    shp = _size(size)

    def fun(key, mu, sigma):
        return jax.random.multivariate_normal(key, mu, sigma,
                                              shape=shp or None)

    return _sample(fun, (mean, cov), size, None, ctx, device, None,
                   "multivariate_normal")


def f(dfnum, dfden, size=None, ctx=None, device=None):
    dtype = onp.float32
    shp = _size(size, dfnum, dfden)

    def fun(key, d1, d2):
        k1, k2 = jax.random.split(key)
        x1 = jax.random.chisquare(key=k1, df=jnp.asarray(d1, dtype),
                                  shape=shp or None, dtype=dtype)
        x2 = jax.random.chisquare(key=k2, df=jnp.asarray(d2, dtype),
                                  shape=shp or None, dtype=dtype)
        return (x1 / jnp.asarray(d1, dtype)) / (x2 / jnp.asarray(d2, dtype))

    return _sample(fun, (dfnum, dfden), size, dtype, ctx, device, None, "f")


def t(df, size=None, ctx=None, device=None):
    """Student's t samples (reference `_npi_student_t`)."""
    dtype = onp.float32
    shp = _size(size, df)

    def fun(key, d):
        return jax.random.t(key, jnp.asarray(d, dtype), shape=shp,
                            dtype=dtype)

    return _sample(fun, (df,), size, dtype, ctx, device, None, "t")


def geometric(p, size=None, ctx=None, device=None):
    """Geometric samples counting trials until first success, support
    {1, 2, ...} (numpy semantics)."""
    dtype = onp.float32
    shp = _size(size, p)

    def fun(key, pp):
        u = jax.random.uniform(key, shp or jnp.shape(pp), dtype,
                               minval=1e-7, maxval=1.0)
        return jnp.ceil(jnp.log1p(-u) / jnp.log1p(-jnp.asarray(pp, dtype)))

    return _sample(fun, (p,), size, dtype, ctx, device, None, "geometric")


def negative_binomial(n, p, size=None, ctx=None, device=None):
    """Negative-binomial samples via the gamma-Poisson mixture."""
    dtype = onp.float32
    shp = _size(size, n, p)

    def fun(key, nn_, pp):
        k1, k2 = jax.random.split(key)
        nn_ = jnp.asarray(nn_, dtype)
        pp = jnp.asarray(pp, dtype)
        lam = jax.random.gamma(k1, jnp.broadcast_to(nn_, shp or jnp.shape(nn_)),
                               dtype=dtype) * (1 - pp) / pp
        return jax.random.poisson(k2, lam).astype(dtype)

    return _sample(fun, (n, p), size, dtype, ctx, device, None,
                   "negative_binomial")
