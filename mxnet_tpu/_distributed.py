"""Multi-host bootstrap shared by package import and `parallel.init_distributed`.

Depends only on os/jax so it can run before anything touches the XLA
backend (reference analogue: ps-lite's DMLC_* env bootstrap,
`src/kvstore/kvstore_dist.h:44`).
"""
from __future__ import annotations

import os
import warnings

# mxlint: disable-file=env-read-at-trace-time -- process bootstrap: every read happens once during package import / jax.distributed init, before any model code can trace

_ENV_VARS = ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
             "JAX_PROCESS_ID")


def read_env():
    """Returns (coordinator_address, num_processes, process_id) from the
    launcher environment, or None if the env is absent or malformed (a
    malformed set warns rather than making the package unimportable)."""
    present = [v for v in _ENV_VARS if v in os.environ]
    if not present:
        return None
    if len(present) < len(_ENV_VARS):
        warnings.warn(
            f"incomplete multi-host environment: have {present}, need all "
            f"of {_ENV_VARS}; skipping jax.distributed bootstrap")
        return None
    try:
        return (os.environ["JAX_COORDINATOR_ADDRESS"],
                int(os.environ["JAX_NUM_PROCESSES"]),
                int(os.environ["JAX_PROCESS_ID"]))
    except ValueError:
        warnings.warn(
            "non-integer JAX_NUM_PROCESSES/JAX_PROCESS_ID; skipping "
            "jax.distributed bootstrap")
        return None


def init_from_env():
    """Call jax.distributed.initialize from the launcher env if present.
    Safe to call more than once; returns True if initialization ran."""
    spec = read_env()
    if spec is None:
        return False
    import jax

    # Cross-process collectives on the host platform need an explicit
    # transport on the pinned jax line (the default CPU client rejects
    # multiprocess programs with INVALID_ARGUMENT); gloo ships in jaxlib.
    # Must be set before the first backend creation, which is why it
    # lives here in the pre-backend bootstrap.
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # mxlint: disable=swallowed-exception -- probing for an older-jax config flag; on newer jax gloo is already the default and the flag is gone
            pass

    try:
        jax.distributed.initialize(coordinator_address=spec[0],
                                   num_processes=spec[1],
                                   process_id=spec[2])
    except RuntimeError:
        return False  # backend already up (interactive import after use)
    # Eager (non-SPMD) ops must land on an ADDRESSABLE device: jax's
    # default is devices()[0], which on rank>0 belongs to process 0 and
    # raises "not fully addressable" on first use.  Pin the per-process
    # default to the first local device (the multi-controller contract).
    jax.config.update("jax_default_device", jax.local_devices()[0])
    _maybe_profile_rank(spec[2])
    return True


def _maybe_profile_rank(rank):
    """Remote-rank profiling (reference analogue: rank 0 switches a
    server's profiler over a kvstore command, `src/kvstore/
    kvstore_dist.h:99`).  In SPMD there is no server role, so the
    launcher carries the request instead: `tools/launch.py
    --profile-rank N [--profile-dir D]` sets MXNET_PROFILE_RANK /
    MXNET_PROFILE_DIR for every worker, and the matching rank starts the
    profiler here and dumps `D/profile_rank{N}.json` (chrome://tracing)
    at exit.  MXNET_PROFILE_RANK=-1 profiles every rank."""
    want = os.environ.get("MXNET_PROFILE_RANK")
    if want is None:
        return
    try:
        want = int(want)
    except ValueError:
        # same warn-don't-crash contract as read_env(): a malformed env
        # var must not make the package unimportable
        warnings.warn(f"MXNET_PROFILE_RANK={want!r} is not an integer; "
                      "profiling request ignored")
        return
    if want != -1 and want != rank:
        return
    import atexit

    from . import profiler
    out_dir = os.environ.get("MXNET_PROFILE_DIR", ".")
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, f"profile_rank{rank}.json")
    profiler.set_config(filename=path, profile_all=True)
    profiler.set_state("run")

    def _dump():
        try:
            profiler.set_state("stop")
            # write to the captured path directly: the training script may
            # have re-pointed the profiler's global filename at its own
            # trace, and the launcher-requested one must not clobber it
            with open(path, "w") as f:
                f.write(profiler.dumps(format="json"))
        except Exception as e:   # teardown must not fail the worker,
            warnings.warn(       # but silence would hide a lost trace
                f"profiler dump to {path} failed: {e}")
    atexit.register(_dump)
