"""Multi-host bootstrap shared by package import and `parallel.init_distributed`.

Depends only on os/jax so it can run before anything touches the XLA
backend (reference analogue: ps-lite's DMLC_* env bootstrap,
`src/kvstore/kvstore_dist.h:44`).
"""
from __future__ import annotations

import os
import warnings

_ENV_VARS = ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
             "JAX_PROCESS_ID")


def read_env():
    """Returns (coordinator_address, num_processes, process_id) from the
    launcher environment, or None if the env is absent or malformed (a
    malformed set warns rather than making the package unimportable)."""
    present = [v for v in _ENV_VARS if v in os.environ]
    if not present:
        return None
    if len(present) < len(_ENV_VARS):
        warnings.warn(
            f"incomplete multi-host environment: have {present}, need all "
            f"of {_ENV_VARS}; skipping jax.distributed bootstrap")
        return None
    try:
        return (os.environ["JAX_COORDINATOR_ADDRESS"],
                int(os.environ["JAX_NUM_PROCESSES"]),
                int(os.environ["JAX_PROCESS_ID"]))
    except ValueError:
        warnings.warn(
            "non-integer JAX_NUM_PROCESSES/JAX_PROCESS_ID; skipping "
            "jax.distributed bootstrap")
        return None


def init_from_env():
    """Call jax.distributed.initialize from the launcher env if present.
    Safe to call more than once; returns True if initialization ran."""
    spec = read_env()
    if spec is None:
        return False
    import jax

    try:
        jax.distributed.initialize(coordinator_address=spec[0],
                                   num_processes=spec[1],
                                   process_id=spec[2])
    except RuntimeError:
        return False  # backend already up (interactive import after use)
    # Eager (non-SPMD) ops must land on an ADDRESSABLE device: jax's
    # default is devices()[0], which on rank>0 belongs to process 0 and
    # raises "not fully addressable" on first use.  Pin the per-process
    # default to the first local device (the multi-controller contract).
    jax.config.update("jax_default_device", jax.local_devices()[0])
    return True
