"""Training monitor for debugging intermediate values.

Reference: `python/mxnet/monitor.py` — `Monitor` installs output hooks and
prints per-tensor statistics every N batches.  Here it hooks Gluon blocks
(`register_forward_hook`) instead of executor callbacks; the default
statistic is the same |x|/size norm the reference uses.
"""
from __future__ import annotations

import logging
import re

import numpy as onp

__all__ = ["Monitor"]


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def stat_func(x):  # reference default: mean |x|
                return onp.abs(x).sum() / x.size
        self.stat_func = stat_func
        self.interval = interval
        self.sort = sort
        self.pattern = re.compile(pattern)
        self.queue = []
        self.step = 0
        self.activated = False
        self._handles = []

    def install(self, block, root_name=""):
        """Hook every sub-block's outputs (reference `install_monitor` on
        executors).

        With a hybridized block, inner sub-blocks execute only during the
        one-time jit trace (where values are abstract and unobservable), so
        only the top-level output is monitored — hybridize() trades inner
        visibility for speed, exactly like the reference's fused graphs.
        """
        import jax

        def hook(blk, inputs, output, _name):
            if not self.activated:
                return
            outs = output if isinstance(output, (list, tuple)) else [output]
            for i, o in enumerate(outs):
                key = f"{_name}_output{i}" if len(outs) > 1 \
                    else f"{_name}_output"
                if not self.pattern.match(key) or not hasattr(o, "asnumpy"):
                    continue
                if isinstance(getattr(o, "_data", None), jax.core.Tracer):
                    continue  # inside a jit trace: value is abstract
                self.queue.append(
                    (self.step, key, self.stat_func(o.asnumpy())))

        def walk(b, name):
            self._handles.append(b.register_forward_hook(
                lambda blk, ins, out, _n=name: hook(blk, ins, out, _n)))
            # prefer the public iteration surface; fall back to _children
            # for block-likes that predate the property
            kids = getattr(b, "children", None)
            items = kids.items() if isinstance(kids, dict) \
                else b._children.items()
            for cname, child in items:
                walk(child, f"{name}.{cname}" if name else cname)
        walk(block, root_name or type(block).__name__)
        return self

    def install_endpoint(self, endpoint, name=None):
        """Watch a serving endpoint (`mxnet_tpu.serve.Endpoint`): every
        dispatched batch records occupancy (real rows / bucket slots)
        and device latency into the same tic/toc queue as tensor stats,
        so a training-style monitor loop can watch serving health."""
        _name = name or endpoint.name

        def hook(_ep, real_rows, bucket_rows, latency_s):
            if not self.activated:
                return
            occ_key = f"{_name}_batch_occupancy"
            lat_key = f"{_name}_batch_latency_ms"
            if self.pattern.match(occ_key):
                self.queue.append((self.step, occ_key,
                                   real_rows / max(bucket_rows, 1)))
            if self.pattern.match(lat_key):
                self.queue.append((self.step, lat_key, latency_s * 1e3))

        self._handles.append(endpoint.register_batch_hook(hook))
        return self

    def tic(self):
        """Start collecting for this batch if the interval hits."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """Stop collecting; returns [(step, name, stat)] (reference toc)."""
        if not self.activated:
            return []
        self.activated = False
        res = list(self.queue)
        self.queue = []
        if self.sort:
            res.sort(key=lambda x: x[1])
        return res

    def toc_print(self):
        """Log collected stats at fixed precision (6 decimal places), so
        runs diff cleanly; non-numeric stats fall back to ``str``."""
        for step, name, stat in self.toc():
            try:
                rendered = f"{float(stat):.6f}"
            except (TypeError, ValueError):
                rendered = str(stat)
            logging.info("Batch: %7d %30s %s", step, name, rendered)

    def uninstall(self):
        for h in self._handles:
            h.detach()
        self._handles = []
