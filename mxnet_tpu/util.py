"""Utility scopes and decorators.

Reference: `python/mxnet/util.py` (np-shape / np-array thread-local scopes).
The TPU rebuild is natively NumPy-semantics (there is no legacy 1.x shape
system to toggle away from), so these are compatibility shims that keep user
code importable: `set_np()`/`use_np` are no-ops that record the flag.
"""
from __future__ import annotations

import functools
import threading

_state = threading.local()


def _flags():
    if not hasattr(_state, "np_shape"):
        _state.np_shape = True
        _state.np_array = True
        _state.np_default_dtype = False
    return _state


def set_np(shape=True, array=True, dtype=False):
    f = _flags()
    f.np_shape, f.np_array, f.np_default_dtype = shape, array, dtype


def reset_np():
    set_np(True, True, False)


def is_np_shape():
    return _flags().np_shape


def is_np_array():
    return _flags().np_array


def is_np_default_dtype():
    return _flags().np_default_dtype


def set_np_shape(active=True):
    _flags().np_shape = active
    return True


def use_np(func):
    """Decorator kept for API compat; numpy semantics are always on."""
    if isinstance(func, type):
        return func

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        return func(*args, **kwargs)

    return wrapper


use_np_shape = use_np
use_np_array = use_np


def np_shape(active=True):
    class _Scope:
        def __enter__(self):
            return self

        def __exit__(self, *_):
            return False

    return _Scope()


np_array = np_shape


def get_gpu_count():
    from .context import num_gpus
    return num_gpus()


def getenv(name):
    """Reference ``mx.util.getenv`` parity shim."""
    import os
    # mxlint: disable=env-read-at-trace-time -- public reference-API shim: live read is the documented behavior, host-side by contract
    v = os.environ.get(name)
    return v


def default_array(source_array, ctx=None, dtype=None):
    from .numpy import array
    return array(source_array, ctx=ctx, dtype=dtype)
