"""``mx.sym`` / ``mx.symbol`` — symbolic graph construction.

Reference: `python/mxnet/symbol/` (15.7k LoC of generated wrappers over the
nnvm graph C API: `Symbol`, `var`, compose/bind/eval, `infer_shape`,
`tojson`/`load`, `list_arguments`).

TPU-native design: a Symbol is a lightweight expression node (op name +
input symbols + attrs) — the nnvm graph — whose execution lowers through
the SAME imperative ops the eager path uses, jitted once per bind: XLA is
the graph compiler, so there is no separate symbolic kernel registry to
maintain.  `bind` returns an Executor with forward/backward (backward via
`jax.vjp`, replacing the `MXGradient` pass), `infer_shape` rides
`jax.eval_shape`, and `tojson`/`load` round-trip the node structure.
"""
from __future__ import annotations

import json as _json

import jax
import numpy as onp

from ..context import current_context
from ..ndarray.ndarray import NDArray

__all__ = ["Symbol", "var", "Variable", "Group", "load", "loads"]

_OP_REGISTRY = {}   # op name -> callable over NDArrays/arrays


class Symbol:
    """A node in the symbolic graph (reference `symbol.py` Symbol)."""

    def __init__(self, op, inputs, attrs=None, name=None, nout=1, index=0,
                 kw_inputs=None):
        self._op = op                    # None for variables
        self._inputs = list(inputs)      # Symbol list (positional)
        self._kw_inputs = dict(kw_inputs or {})  # name -> Symbol (keyword
        # tensor args: the canonical legacy style `FullyConnected(data=x,
        # weight=w, ...)`, reference symbol.py compose)
        self._attrs = dict(attrs or {})
        self._name = name or (op if op else "var")
        self._nout = nout
        self._index = index

    def _all_inputs(self):
        return list(self._inputs) + list(self._kw_inputs.values())

    # -- introspection ------------------------------------------------------
    @property
    def name(self):
        return self._name

    def list_arguments(self):
        """Free variables in topological order (reference
        `symbol.py list_arguments`)."""
        seen, order = set(), []

        def walk(s):
            if id(s) in seen:
                return
            seen.add(id(s))
            for i in s._all_inputs():
                walk(i)
            if s._op is None and not isinstance(s, _ScalarSymbol) \
                    and s._name not in order:
                order.append(s._name)
        walk(self)
        return order

    def get_internals(self):
        """All nodes as a Group (reference `get_internals`)."""
        seen, nodes = set(), []

        def walk(s):
            if id(s) in seen:
                return
            seen.add(id(s))
            for i in s._all_inputs():
                walk(i)
            nodes.append(s)
        walk(self)
        return Group(nodes)

    def __getitem__(self, idx):
        if isinstance(idx, str):
            for s in self.get_internals()._outputs:
                if s._name == idx:
                    return s
            raise KeyError(idx)
        if idx < 0 or idx >= self._nout:
            raise IndexError(
                f"symbol {self._name!r} has {self._nout} output(s), "
                f"index {idx} out of range")
        if self._nout == 1:
            return self
        return Symbol("_tuple_get", [self], {"index": idx},
                      name=f"{self._name}[{idx}]")

    # -- composition --------------------------------------------------------
    def _binop(self, other, opname, fn, swap=False):
        if not isinstance(other, Symbol):
            other = _ScalarSymbol(other)
        a, b = (other, self) if swap else (self, other)
        return Symbol(opname, [a, b], name=opname)

    def __add__(self, o):
        return self._binop(o, "_plus", None)

    def __radd__(self, o):
        return self._binop(o, "_plus", None, swap=True)

    def __sub__(self, o):
        return self._binop(o, "_minus", None)

    def __rsub__(self, o):
        return self._binop(o, "_minus", None, swap=True)

    def __mul__(self, o):
        return self._binop(o, "_mul", None)

    def __rmul__(self, o):
        return self._binop(o, "_mul", None, swap=True)

    def __truediv__(self, o):
        return self._binop(o, "_div", None)

    def __rtruediv__(self, o):
        return self._binop(o, "_div", None, swap=True)

    def __pow__(self, o):
        return self._binop(o, "_power", None)

    def __neg__(self):
        return self._binop(-1.0, "_mul", None)

    # -- evaluation ---------------------------------------------------------
    def _eval(self, env):
        """Recursively evaluate against ``env`` name->array; memoized."""
        memo = {}

        def ev(s):
            if id(s) in memo:
                return memo[id(s)]
            if isinstance(s, _ScalarSymbol):
                out = s._value
            elif s._op is None:
                if s._name not in env:
                    raise ValueError(f"unbound symbol variable '{s._name}'")
                out = env[s._name]
            elif s._op == "_tuple_get":
                out = ev(s._inputs[0])[s._attrs["index"]]
            else:
                fn = _OP_REGISTRY[s._op]
                ins = [ev(i) for i in s._inputs]
                kw_ins = {k: ev(v) for k, v in s._kw_inputs.items()}
                out = fn(*ins, **kw_ins, **s._attrs)
                if isinstance(out, NDArray):
                    out = out._data
                elif isinstance(out, (tuple, list)):
                    out = tuple(o._data if isinstance(o, NDArray) else o
                                for o in out)
            memo[id(s)] = out
            return out
        return ev(self)

    def eval(self, ctx=None, **kwargs):
        """Eager evaluation (reference `symbol.py eval`): returns [NDArray]."""
        env = {k: (v._data if isinstance(v, NDArray) else onp.asarray(v))
               for k, v in kwargs.items()}
        out = self._eval(env)
        outs = out if isinstance(out, tuple) else (out,)
        return [NDArray(o, ctx=ctx) for o in outs]

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write"):
        """Compile the graph for repeated execution (reference `bind`);
        the TPU executor is one jitted XLA program."""
        return Executor(self, ctx or current_context(), args or {},
                        args_grad or {}, grad_req)

    simple_bind = bind

    # -- shape/type inference ----------------------------------------------
    def infer_shape(self, **shapes):
        """Shapes of (args, outputs, aux) given input shapes — via
        jax.eval_shape, replacing the nnvm InferShape pass.  Per-arg
        dtypes honor ``var(dtype=...)`` so integer-typed inputs
        (take/one_hot indices, embeddings) infer correctly."""
        names = self.list_arguments()
        dtypes = {}
        for s in self.get_internals()._outputs:
            if s._op is None and not isinstance(s, _ScalarSymbol):
                dt = getattr(s, "_dtype", None)
                if dt is not None:
                    dtypes[s._name] = onp.dtype(dt)
        specs = {}
        for n in names:
            if n not in shapes:
                raise ValueError(f"infer_shape needs a shape for '{n}'")
            specs[n] = jax.ShapeDtypeStruct(tuple(shapes[n]),
                                            dtypes.get(n, onp.float32))
        out = jax.eval_shape(lambda env: self._eval(env), specs)
        outs = out if isinstance(out, tuple) else (out,)
        return ([tuple(shapes[n]) for n in names],
                [tuple(o.shape) for o in outs], [])

    # -- serialization ------------------------------------------------------
    def tojson(self):
        """Serialize node structure (reference `tojson`; the format is a
        plain node list, not the legacy nnvm JSON)."""
        nodes, index = [], {}

        def walk(s):
            if id(s) in index:
                return index[id(s)]
            ins = [walk(i) for i in s._inputs]
            kw_ins = {k: walk(v) for k, v in s._kw_inputs.items()}
            idx = len(nodes)
            entry = {"op": s._op, "name": s._name, "inputs": ins,
                     "attrs": s._attrs}
            if kw_ins:
                entry["kw_inputs"] = kw_ins
            if s._nout != 1:
                entry["nout"] = s._nout
            if isinstance(s, _ScalarSymbol):
                v = s._value
                entry["op"] = "_scalar"
                # tuples (shapes, axes) survive as lists + a tuple flag;
                # ints stay ints so dtype promotion survives a round-trip
                entry["attrs"] = {"value": list(v) if isinstance(v, tuple)
                                  else v,
                                  "tuple": isinstance(v, tuple)}
            nodes.append(entry)
            index[id(s)] = idx
            return idx
        head = walk(self)
        return _json.dumps({"nodes": nodes, "head": head,
                            "format": "mxnet_tpu-sym-v1"})

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    def __repr__(self):
        return f"<Symbol {self._name}>"


class _ScalarSymbol(Symbol):
    def __init__(self, value):
        super().__init__(None, [], name=f"scalar{value}")
        self._value = value

    def list_arguments(self):
        return []


class Group(Symbol):
    """Multiple outputs (reference `Group`)."""

    def __init__(self, symbols):
        super().__init__("_group", list(symbols), name="group",
                         nout=len(symbols))
        self._outputs = list(symbols)

    def _eval(self, env):
        return tuple(s._eval(env) for s in self._outputs)


def var(name, shape=None, dtype=None, **kwargs):
    """Create a free variable (reference `symbol.py var`)."""
    s = Symbol(None, [], name=name)
    s._shape = shape
    s._dtype = dtype
    return s


Variable = var


class Executor:
    """Bound graph (reference `executor.py`): forward/backward over one
    jitted value_and_grad program."""

    def __init__(self, symbol, ctx, args, args_grad, grad_req):
        self._symbol = symbol
        self._ctx = ctx
        self.arg_dict = {k: v if isinstance(v, NDArray) else NDArray(v)
                         for k, v in args.items()}
        self.grad_dict = {k: v if isinstance(v, NDArray) else NDArray(v)
                          for k, v in (args_grad or {}).items()}
        self._grad_req = grad_req
        self._names = symbol.list_arguments()
        self.outputs = []

        def fwd(env):
            return self._symbol._eval(env)
        self._fwd = jax.jit(fwd)

        grad_names = [n for n in self._names
                      if grad_req != "null" and
                      (not self.grad_dict or n in self.grad_dict)]

        def fwd_for_grad(genv, env):
            out = self._symbol._eval({**env, **genv})
            return out if isinstance(out, tuple) else (out,)
        self._grad_names = grad_names
        # cotangents is a tuple with one entry per output; every output's
        # contribution accumulates into the input gradients
        self._vjp_fn = jax.jit(
            lambda genv, env, cts: jax.vjp(
                lambda g: fwd_for_grad(g, env), genv)[1](cts)[0])

    def _env(self):
        return {k: v._data for k, v in self.arg_dict.items()}

    def forward(self, is_train=False, **kwargs):
        for k, v in kwargs.items():
            self.arg_dict[k] = v if isinstance(v, NDArray) else NDArray(v)
        out = self._fwd(self._env())
        outs = out if isinstance(out, tuple) else (out,)
        self.outputs = [NDArray(o, ctx=self._ctx) for o in outs]
        return self.outputs

    def backward(self, out_grads=None):
        env = self._env()
        genv = {k: env[k] for k in self._grad_names}
        rest = {k: v for k, v in env.items() if k not in self._grad_names}
        # use the outputs from the preceding forward (no extra device
        # program); fall back to one forward only if none has run yet
        if self.outputs:
            outs = tuple(o._data for o in self.outputs)
        else:
            out = self._fwd(env)
            outs = out if isinstance(out, tuple) else (out,)
        if out_grads is None:
            cts = tuple(jax.numpy.ones_like(o) for o in outs)
        else:
            gs = out_grads if isinstance(out_grads, (list, tuple)) \
                else [out_grads]
            if len(gs) != len(outs):
                raise ValueError(
                    f"backward got {len(gs)} head gradients for "
                    f"{len(outs)} outputs")
            cts = tuple(g._data if isinstance(g, NDArray) else
                        jax.numpy.asarray(g) for g in gs)
        grads = self._vjp_fn(genv, rest, cts)
        for k, gv in grads.items():
            if k in self.grad_dict:
                if self._grad_req == "add":
                    self.grad_dict[k]._rebind(self.grad_dict[k]._data + gv)
                else:
                    self.grad_dict[k]._rebind(gv)
            else:
                self.grad_dict[k] = NDArray(gv, ctx=self._ctx)
        return [self.grad_dict[n] for n in self._grad_names]


# ---------------------------------------------------------------------------
# op surface: lift the imperative namespaces to symbol builders
# ---------------------------------------------------------------------------
def _register(name, fn):
    _OP_REGISTRY[name] = fn

    def builder(*args, **kwargs):
        name_attr = kwargs.pop("name", None)
        sym_inputs = []
        for a in args:
            if isinstance(a, Symbol):
                sym_inputs.append(a)
            else:
                sym_inputs.append(_ScalarSymbol(a))
        # keyword tensor args (`FullyConnected(data=x, weight=w)`) become
        # named graph inputs, not attrs
        kw_inputs = {k: v for k, v in kwargs.items() if isinstance(v, Symbol)}
        attrs = {k: v for k, v in kwargs.items() if k not in kw_inputs}
        return Symbol(name, sym_inputs, attrs, name=name_attr or name,
                      kw_inputs=kw_inputs)
    builder.__name__ = name
    return builder


def loads(json_str):
    """Rebuild a Symbol from `tojson` output."""
    data = _json.loads(json_str)
    built = {}
    for idx, node in enumerate(data["nodes"]):
        ins = [built[i] for i in node["inputs"]]
        kw_ins = {k: built[i] for k, i in node.get("kw_inputs", {}).items()}
        if node["op"] is None:
            built[idx] = var(node["name"])
        elif node["op"] == "_scalar":
            v = node["attrs"]["value"]
            if node["attrs"].get("tuple"):
                v = tuple(v)
            built[idx] = _ScalarSymbol(v)
        elif node["op"] == "_group":
            built[idx] = Group(ins)
        else:
            built[idx] = Symbol(node["op"], ins, node["attrs"],
                                name=node["name"],
                                nout=node.get("nout", 1), kw_inputs=kw_ins)
    return built[data["head"]]


def load(fname):
    with open(fname) as f:
        return loads(f.read())


def _populate():
    import jax.numpy as jnp

    from .. import numpy as mxnp
    from .. import numpy_extension as mxnpx
    from ..ndarray import legacy as mxlegacy

    # arithmetic primitives used by operator overloads
    _register("_plus", lambda a, b: a + b)
    _register("_minus", lambda a, b: a - b)
    _register("_mul", lambda a, b: a * b)
    _register("_div", lambda a, b: a / b)
    _register("_power", lambda a, b: a ** b)

    g = globals()
    # mx.sym IS the legacy symbol API (reference `symbol/register.py`
    # mirrors `ndarray/register.py`), so the legacy surface registers LAST
    # and overrides colliding np/npx names (sum w/ exclude, legacy dot
    # transpose flags, float-dtype comparisons, Reshape codes, ...)
    for ns in (mxnp, mxnpx, mxlegacy):
        override = ns is mxlegacy
        for attr in dir(ns):
            if attr.startswith("_"):
                continue
            fn = getattr(ns, attr)
            if not callable(fn) or isinstance(fn, type):
                continue
            if attr in ("array", "save", "load", "seed", "waitall",
                        "set_np", "reset_np", "use_np", "is_np_array",
                        "invoke", "apply_aux_update", "is_recording",
                        "is_training", "cpu", "gpu", "tpu",
                        "current_context", "num_gpus", "num_tpus",
                        "random", "Custom"):
                continue
            if attr.endswith("_update"):
                continue  # mutate-output optimizer kernels: no symbolic form
            if attr not in g or override:
                g[attr] = _register(attr, fn)
                if attr not in __all__:
                    __all__.append(attr)

    # multi-output legacy ops need nout on the built Symbol so indexing works
    def _slice_channel_builder(data, num_outputs=1, axis=1,
                               squeeze_axis=False, name=None):
        sym = Symbol("SliceChannel", [data],
                     {"num_outputs": num_outputs, "axis": axis,
                      "squeeze_axis": squeeze_axis},
                     name=name or "SliceChannel", nout=num_outputs)
        return sym
    g["SliceChannel"] = _slice_channel_builder
    g["split"] = _slice_channel_builder

    # sub-namespaces (reference `mx.sym.linalg/image/contrib`): builders
    # lifted from the NDArray-facing modules
    import types as _types

    def _subns(prefix, mod, names):
        ns = _types.SimpleNamespace()
        for n in names:
            fn = getattr(mod, n, None)
            if callable(fn) and not isinstance(fn, type):
                setattr(ns, n, _register(f"_{prefix}_{n}", fn))
        return ns

    from ..ndarray import image as _ndimage
    from ..ndarray import linalg as _ndlinalg
    from .. import contrib as _ndcontrib
    g["linalg"] = _subns("linalg", _ndlinalg, _ndlinalg.__all__)
    g["image"] = _subns("image", _ndimage, _ndimage.__all__)
    g["contrib"] = _subns("contrib", _ndcontrib,
                          [n for n in _ndcontrib.__all__
                           if n not in ("foreach", "while_loop", "cond")])


_populate()
