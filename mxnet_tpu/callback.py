"""Legacy training callbacks.

Reference: `python/mxnet/callback.py` — `Speedometer` (throughput logging),
`do_checkpoint` (epoch-end save), `ProgressBar`, `log_train_metric`; the
classic pre-Gluon fit-loop hooks.  Kept for script compatibility; the
Gluon-era equivalent is `gluon.contrib.estimator` event handlers.
"""
from __future__ import annotations

import logging
import time

__all__ = ["Speedometer", "ProgressBar", "do_checkpoint",
           "module_checkpoint", "log_train_metric"]


class Speedometer:
    """Log throughput + metrics every `frequent` batches (reference
    `callback.py` Speedometer)."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self.init = False
        self.tic = 0
        self.last_count = 0

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                try:
                    speed = self.frequent * self.batch_size / \
                        (time.time() - self.tic)
                except ZeroDivisionError:
                    speed = float("inf")
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset()
                    msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec" \
                        % (param.epoch, count, speed)
                    msg += "".join("\t%s=%f" % kv for kv in name_value)
                    logging.info(msg)
                else:
                    logging.info(
                        "Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                        param.epoch, count, speed)
                self.tic = time.time()
        else:
            self.init = True
            self.tic = time.time()


class ProgressBar:
    """Draw a text progress bar (reference `callback.py` ProgressBar)."""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = int(round(100.0 * count / float(self.total)))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        logging.info("[%s] %s%s\r", prog_bar, percents, "%")


def do_checkpoint(prefix, period=1):
    """Epoch-end checkpoint callback (reference `callback.py
    do_checkpoint`): saves `{prefix}-{epoch:04d}.params` via the model
    checkpoint helpers."""
    from . import model as _model

    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            _model.save_checkpoint(prefix, iter_no + 1, sym, arg, aux)
    return _callback


module_checkpoint = do_checkpoint


def log_train_metric(period, auto_reset=False):
    """Log metrics every `period` batches (reference log_train_metric)."""

    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()
    return _callback
