"""Capture jaxprs + HLO of the real entry points, with their contracts.

Every capture function returns a plain dict spec::

    {"name": "allreduce.bucket_dense", "kind": "allreduce",
     "jaxpr": "...", "lowered": "...", "optimized": "...",
     "contract": {...}, "meta": {...}}

``lowered`` is the pre-optimization HLO (the user program as written —
dtype intent lives here), ``optimized`` the compiled, scheduled module
(collective census, schedule, partitioning live here).  Contracts are
pinned literals, not derived at capture time wherever possible: a
contract computed from the same code it checks can never catch a
regression in that code.  The one exception is the bucketed-step
census, which is derived from the ``GradBucketer`` *plan* and then
cross-checked against the pinned PR 4 headline (160 tensors -> 4
buckets at 1 MB) by ``tests/test_hloscan.py``.

Everything lowers on the virtual 8-device CPU mesh
(``--xla_force_host_platform_device_count=8``), same as tests and the
driver dryrun — no TPU needed.
"""
from __future__ import annotations

import os

_ENTRYPOINTS = {}

#: Bucket cap reproducing the PR 4 headline census on the resnet50
#: profile (benchmark/COLLECTIVES_ANALYSIS.md: 160 -> 4 at 1 MB).
BUCKETED_STEP_BUCKET_BYTES = 1 << 20

#: The ResNet-50-like gradient profile (benchmark/allreduce_bench.py).
RESNET50_PROFILE = [256] * 104 + [1024] * 26 + [16384] * 22 + [65536] * 8


def _entrypoint(name):
    def deco(fn):
        _ENTRYPOINTS[name] = fn
        return fn
    return deco


def entrypoint_names():
    return sorted(_ENTRYPOINTS)


def _ensure_virtual_mesh(n=8):
    """Force the 8-device CPU mesh before the first backend init — the
    same steering tests/conftest.py applies (env alone is read too late
    when a site hook pre-imports jax)."""
    # mxlint: disable=env-read-at-trace-time -- pre-backend-init launcher plumbing: must read current flags each call, never traced
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    # mxlint: disable=env-read-at-trace-time -- same launcher plumbing: respect an explicit platform choice per invocation
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    if jax.local_device_count() < n:
        raise RuntimeError(
            f"analysis capture needs >= {n} devices for the dp mesh, got "
            f"{jax.local_device_count()} — jax initialized before the "
            f"virtual-mesh flags landed (import mxnet_tpu.analysis "
            f"earlier, or export XLA_FLAGS/JAX_PLATFORMS as tools/ci.sh "
            f"does)")


def _stage_texts(traced):
    """(jaxpr, lowered, optimized) texts from a ``jax.stages.Traced``."""
    lowered = traced.lower()
    compiled = lowered.compile()
    return (str(traced.jaxpr),
            lowered.compiler_ir(dialect="hlo").as_hlo_text(),
            compiled.as_text())


def _capture_jit(jitted, args, name, kind, contract, meta=None):
    jaxpr, low, opt = _stage_texts(jitted.trace(*args))
    return {"name": name, "kind": kind, "jaxpr": jaxpr, "lowered": low,
            "optimized": opt, "contract": contract, "meta": meta or {}}


# --------------------------------------------------------------------------
# fused SPMD train step
# --------------------------------------------------------------------------
def build_dp_fused_step():
    """The canonical dp-mesh FusedTrainStep (small MLP + loss on the
    8-device mesh).  Shared by the hloscan capture below and the
    layerscope census (`analysis/census.py`) so both fence the SAME
    program.  Returns ``(fused, (x, y), batch_size, meta)``."""
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu.gluon import FusedTrainStep, Trainer, loss as gloss, nn
    from mxnet_tpu.gluon.block import HybridBlock
    from mxnet_tpu.parallel import mesh as pmesh

    class _NetWithLoss(HybridBlock):
        def __init__(self):
            super().__init__()
            self.d1 = nn.Dense(16, in_units=8)
            self.d2 = nn.Dense(8, in_units=16)
            self.loss_fn = gloss.SoftmaxCrossEntropyLoss()

        def forward(self, x, y):
            return self.loss_fn(self.d2(self.d1(x)), y)

    rng = onp.random.RandomState(7)
    mod = _NetWithLoss()
    mod.initialize()
    tr = Trainer(mod.collect_params(), "sgd",
                 {"learning_rate": 0.1, "momentum": 0.9})
    mesh = pmesh.make_mesh({"dp": 8})
    fused = FusedTrainStep(mod, tr, mesh=mesh)
    x = mx.np.array(rng.uniform(-1, 1, (16, 8)).astype(onp.float32))
    y = mx.np.array(rng.randint(0, 8, (16,)), dtype="int32")
    return fused, (x, y), 16, {"mesh": "dp:8", "params": 4, "batch": 16}


@_entrypoint("fused_train_step.dp")
def _capture_fused_train_step():
    """FusedTrainStep(mesh=dp) on a small MLP: the single donated XLA
    program a data-parallel training step dispatches.  The captured
    program is built by FusedTrainStep._prepare itself — identical arg
    treatment to a live step, not a reconstruction."""
    fused, args, batch_size, _meta = build_dp_fused_step()
    traced = fused.trace(*args, batch_size=batch_size)
    jaxpr, low, opt = _stage_texts(traced)
    # census: one gradient all-reduce per trainable tensor (4: two
    # weights + two biases; the per-sample loss output stays dp-sharded,
    # so no extra loss reduction).  Pinned: an issue-order or sharding
    # regression moves this number, and that is the point (ROADMAP
    # item 1).
    return {
        "name": "fused_train_step.dp", "kind": "train_step",
        "jaxpr": jaxpr, "lowered": low, "optimized": opt,
        "contract": {
            "expect_overlap": True,
            "resharding_free": True,
            "expected_collectives": {"all-reduce": 4},
        },
        "meta": {"mesh": "dp:8", "params": 4, "batch": 16},
    }


def build_recipe_fused_step():
    """The recipe-built dp2.tp2 FusedTrainStep: the same small MLP as
    `build_dp_fused_step`, but the whole SPMD setup comes from the one
    config string — mesh, collected Dense rules, strict coverage audit,
    input spec.  d2 takes a row-split override (Megatron column->row
    pair), exercising user-override precedence over the block defaults.
    Returns ``(fused, (x, y), batch_size, meta)``."""
    import numpy as onp
    from jax.sharding import PartitionSpec as P

    import mxnet_tpu as mx
    from mxnet_tpu.gluon import FusedTrainStep, Trainer, loss as gloss, nn
    from mxnet_tpu.gluon.block import HybridBlock

    class _NetWithLoss(HybridBlock):
        def __init__(self):
            super().__init__()
            self.d1 = nn.Dense(16, in_units=8)
            self.d2 = nn.Dense(8, in_units=16)
            self.loss_fn = gloss.SoftmaxCrossEntropyLoss()

        def forward(self, x, y):
            return self.loss_fn(self.d2(self.d1(x)), y)

    rng = onp.random.RandomState(7)
    mod = _NetWithLoss()
    mod.initialize()
    tr = Trainer(mod.collect_params(), "sgd",
                 {"learning_rate": 0.1, "momentum": 0.9})
    fused = FusedTrainStep(
        mod, tr, recipe="dp2.tp2",
        partition_rules=[(r"d2\.weight$", P(None, "tp")),
                         (r"d2\.bias$", P())])
    x = mx.np.array(rng.uniform(-1, 1, (16, 8)).astype(onp.float32))
    y = mx.np.array(rng.randint(0, 8, (16,)), dtype="int32")
    return fused, (x, y), 16, {"mesh": "dp:2,tp:2", "recipe": "dp2.tp2",
                               "params": 4, "batch": 16}


@_entrypoint("fused_train_step.recipe_tp2")
def _capture_recipe_fused_step():
    """FusedTrainStep(recipe="dp2.tp2") on the small MLP: the compiled
    tensor-parallel step a recipe builds, captured through the same
    `_prepare` path a live step dispatches.  The resharding_free pin is
    the recipe subsystem's compile-time fence: if rule collection or
    placement ever disagrees with what the program computes, GSPMD
    inserts reshard transfers and this artifact fails the scan."""
    fused, args, batch_size, meta = build_recipe_fused_step()
    traced = fused.trace(*args, batch_size=batch_size)
    jaxpr, low, opt = _stage_texts(traced)
    # census: one gradient psum per trainable tensor (4 — tp-sharded
    # grads still psum, over the dp axis only) plus the Megatron pair's
    # activation all-reduces in forward and backward (row-split d2
    # partial outputs, column-split d1 input grads, and the loss
    # reduction), as XLA schedules them on the 2x2 mesh: 8 issues, no
    # all-gather / all-to-all / collective-permute (resharding-free).
    return {
        "name": "fused_train_step.recipe_tp2", "kind": "train_step",
        "jaxpr": jaxpr, "lowered": low, "optimized": opt,
        "contract": {
            "expect_overlap": True,
            "resharding_free": True,
            "expected_collectives": {"all-reduce": 8},
        },
        "meta": meta,
    }


# --------------------------------------------------------------------------
# kvstore collectives
# --------------------------------------------------------------------------
def _ici_devices():
    import jax

    return tuple(jax.local_devices()[:8])


@_entrypoint("allreduce.bucket_dense")
def _capture_allreduce_dense():
    """One dense bucket reduce: the `_allreduce_fn` shard_map+psum
    program the kvstore dispatches per bucket."""
    import jax.numpy as jnp
    import numpy as onp

    from mxnet_tpu.kvstore.tpu_ici import _allreduce_fn

    devices = _ici_devices()
    shape = (16384,)
    allreduce, sharding, _mesh = _allreduce_fn(
        devices, shape, onp.dtype(onp.float32))
    import jax
    spec = jax.ShapeDtypeStruct((len(devices),) + shape, jnp.float32,
                                sharding=sharding)
    return _capture_jit(
        allreduce, (spec,), "allreduce.bucket_dense", "allreduce",
        contract={
            # a bucket reduce IS the collective — exactly one launch, and
            # nothing for it to overlap with inside its own program
            "expected_collectives": {"all-reduce": 1},
            "resharding_free": True,
        },
        meta={"shape": list(shape), "dtype": "float32", "devices": 8})


@_entrypoint("allreduce.bucket_2bit")
def _capture_allreduce_2bit():
    """The compressed bucket reduce: int8 levels ride the ring, each
    device rescales its own shard — the narrow dtype must SURVIVE into
    the collective (EQuARX-style), which the dtype census locks."""
    import jax
    import jax.numpy as jnp
    import numpy as onp

    from mxnet_tpu.kvstore.tpu_ici import _compressed_allreduce_fn

    devices = _ici_devices()
    shape = (16384,)
    allreduce, sharding, _mesh = _compressed_allreduce_fn(
        devices, shape, onp.dtype(onp.float32), 0.01)
    spec = jax.ShapeDtypeStruct((len(devices),) + shape, jnp.int8,
                                sharding=sharding)
    return _capture_jit(
        allreduce, (spec,), "allreduce.bucket_2bit", "allreduce",
        contract={
            "expected_collectives": {"all-reduce": 1},
            "resharding_free": True,
        },
        meta={"shape": list(shape), "dtype": "int8->float32",
              "threshold": 0.01, "devices": 8})


def _capture_allreduce_blockwise(qtype):
    """Shared capture for the block-scaled quantized bucket reduce: the
    fused quantize -> pmax(scale) -> psum(payload) -> dequantize program
    from `_blockwise_allreduce_fn`, taking the stacked gradient AND
    residual shards.  TWO all-reduce ops in the HLO is the honest,
    pinned census: the ~1/256-sized scale-agreement pmax and the
    widened narrow-payload psum both live in ONE compiled launch."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.kvstore.tpu_ici import (DEFAULT_QBLOCK,
                                           _blockwise_allreduce_fn)

    devices = _ici_devices()
    numel = 16384
    allreduce, sharding, _mesh = _blockwise_allreduce_fn(
        devices, numel, "float32", qtype, DEFAULT_QBLOCK)
    spec = jax.ShapeDtypeStruct((len(devices), numel), jnp.float32,
                                sharding=sharding)
    # the third operand is the (n_dev, 1) launch-chain token that orders
    # consecutive blockwise launches without a host fence — pure
    # scheduling, no collective of its own
    tok_spec = jax.ShapeDtypeStruct((len(devices), 1), jnp.float32,
                                    sharding=sharding)
    wire = "int8->int16" if qtype == "int8" else "float8_e4m3->bfloat16"
    return _capture_jit(
        allreduce, (spec, spec, tok_spec), f"allreduce.bucket_{qtype}",
        "allreduce",
        contract={
            # pmax (scale agreement) + psum (payload): both collectives
            # of the fused program, still one launch per bucket
            "expected_collectives": {"all-reduce": 2},
            "resharding_free": True,
        },
        meta={"numel": numel, "dtype": f"float32->{wire}",
              "block": DEFAULT_QBLOCK, "devices": 8})


@_entrypoint("allreduce.bucket_dense_integrity")
def _capture_allreduce_dense_integrity():
    """The DECLARED integrity-mode variant of `allreduce.bucket_dense`
    (``MXNET_KVSTORE_INTEGRITY=1``): the same bucket psum plus the
    in-program digest sideband — a pmax over the packed ``[d, -d]``
    digest pair (max and min agreement in ONE collective) riding the
    SAME launch.  Pinned at 2 all-reduce ops so integrity mode is a
    contract variant, not a launch-count violation; the default dense
    contract above stays at 1."""
    import jax
    import jax.numpy as jnp
    import numpy as onp

    from mxnet_tpu.kvstore.tpu_ici import _allreduce_fn

    devices = _ici_devices()
    shape = (16384,)
    allreduce, sharding, _mesh = _allreduce_fn(
        devices, shape, onp.dtype(onp.float32), True)
    spec = jax.ShapeDtypeStruct((len(devices),) + shape, jnp.float32,
                                sharding=sharding)
    flip = jax.ShapeDtypeStruct((len(devices), 1), jnp.float32,
                                sharding=sharding)
    return _capture_jit(
        allreduce, (spec, flip), "allreduce.bucket_dense_integrity",
        "allreduce",
        contract={
            # payload psum + digest-agreement pmax, one launch
            "expected_collectives": {"all-reduce": 2},
            "resharding_free": True,
        },
        meta={"shape": list(shape), "dtype": "float32", "devices": 8,
              "mode": "integrity"})


@_entrypoint("allreduce.bucket_int8_integrity")
def _capture_allreduce_int8_integrity():
    """The DECLARED integrity-mode variant of `allreduce.bucket_int8`:
    scale-agreement pmax + payload psum + digest-agreement pmax, all in
    the one fused launch — 3 all-reduce ops pinned (the default
    blockwise contract stays at 2)."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.kvstore.tpu_ici import (DEFAULT_QBLOCK,
                                           _blockwise_allreduce_fn)

    devices = _ici_devices()
    numel = 16384
    allreduce, sharding, _mesh = _blockwise_allreduce_fn(
        devices, numel, "float32", "int8", DEFAULT_QBLOCK, True)
    spec = jax.ShapeDtypeStruct((len(devices), numel), jnp.float32,
                                sharding=sharding)
    tok_spec = jax.ShapeDtypeStruct((len(devices), 1), jnp.float32,
                                    sharding=sharding)
    return _capture_jit(
        allreduce, (spec, spec, tok_spec, tok_spec),
        "allreduce.bucket_int8_integrity", "allreduce",
        contract={
            # pmax (scales) + psum (payload) + pmax (digest), one launch
            "expected_collectives": {"all-reduce": 3},
            "resharding_free": True,
        },
        meta={"numel": numel, "dtype": "float32->int8->int16",
              "block": DEFAULT_QBLOCK, "devices": 8,
              "mode": "integrity"})


@_entrypoint("allreduce.bucket_int8")
def _capture_allreduce_int8():
    """Block-scaled int8 bucket reduce (see
    `_capture_allreduce_blockwise`): int8 payload, int16 accumulator."""
    return _capture_allreduce_blockwise("int8")


@_entrypoint("allreduce.bucket_fp8")
def _capture_allreduce_fp8():
    """Block-scaled fp8 bucket reduce (see
    `_capture_allreduce_blockwise`): float8_e4m3 payload, bfloat16
    accumulator."""
    return _capture_allreduce_blockwise("fp8")


class _PlanVal:
    """Shape/dtype stand-in for a gradient copy: exactly what
    GradBucketer's planner reads (``._data.dtype``, ``.shape``,
    ``.size``; `_value_devices` sees a non-jax ``.data`` and records
    host placement), so the REAL planner produces the plan without
    materializing 3.75 MB of fake gradients."""

    def __init__(self, shape, dtype):
        import jax

        self._data = jax.ShapeDtypeStruct(tuple(shape), dtype)
        self.data = self._data
        self.shape = tuple(shape)
        self.size = 1
        for d in shape:
            self.size *= int(d)


def bucketed_step_plan(bucket_bytes=BUCKETED_STEP_BUCKET_BYTES):
    """The GradBucketer plan for the resnet50 profile: list of bucket
    capacities (elements).  This is the planner the trainer runs, fed
    the benchmark's canonical gradient profile."""
    import jax.numpy as jnp

    from mxnet_tpu.kvstore.bucketing import GradBucketer

    items = [(f"g{i}", [_PlanVal((n,), jnp.float32)])
             for i, n in enumerate(RESNET50_PROFILE)]
    bucketer = GradBucketer(bucket_bytes=bucket_bytes)
    plan = bucketer._build_plan(items)
    return [b.capacity for b in plan]


@_entrypoint("allreduce.bucketed_step")
def _capture_bucketed_step():
    """One step's worth of bucketed gradient collectives as a single
    module: the resnet50 profile planned by the real GradBucketer, one
    shard_map psum per bucket.  launch-count on this artifact is the
    compiled-side lock on PR 4's 160 -> 4 collapse: if the planner (or
    a bucketer bypass) changes the bucket count, the census moves and
    the scan fails."""
    import jax
    import jax.numpy as jnp
    import numpy as onp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from mxnet_tpu._compat import shard_map

    capacities = bucketed_step_plan()
    devices = tuple(jax.local_devices()[:8])
    mesh = Mesh(onp.asarray(devices), ("dev",))
    sharding = NamedSharding(mesh, P("dev"))

    def step(*bufs):
        return tuple(jax.lax.psum(b, "dev") for b in bufs)

    reduce_all = shard_map(step, mesh,
                           in_specs=(P("dev"),) * len(capacities),
                           out_specs=(P("dev"),) * len(capacities))
    jitted = jax.jit(reduce_all,
                     in_shardings=(sharding,) * len(capacities),
                     out_shardings=(sharding,) * len(capacities))
    specs = tuple(
        jax.ShapeDtypeStruct((len(devices), cap), jnp.float32,
                             sharding=sharding)
        for cap in capacities)
    return _capture_jit(
        jitted, specs, "allreduce.bucketed_step", "allreduce",
        contract={
            "expected_collectives": {"all-reduce": len(capacities)},
            "resharding_free": True,
        },
        meta={"profile": "resnet50",
              "n_tensors": len(RESNET50_PROFILE),
              "n_buckets": len(capacities),
              "bucket_bytes": BUCKETED_STEP_BUCKET_BYTES,
              "capacities": capacities})


@_entrypoint("allreduce.bucketed_step_int8")
def _capture_bucketed_step_int8():
    """The quantized twin of `allreduce.bucketed_step`: the SAME
    GradBucketer plan over the resnet50 profile, but each bucket runs
    the real `_blockwise_shard_body` int8 math instead of a bare psum.
    The census pins 2 all-reduce ops per bucket (scale pmax + payload
    psum) while the *launch* count the trainer sees stays one per
    bucket — still 4 for the 160-tensor profile, which the dryrun
    `dp_collective_launches_per_step` rider measures at runtime."""
    import jax
    import jax.numpy as jnp
    import numpy as onp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from mxnet_tpu._compat import shard_map
    from mxnet_tpu.kvstore.tpu_ici import (DEFAULT_QBLOCK,
                                           _blockwise_shard_body)

    capacities = bucketed_step_plan()
    devices = tuple(jax.local_devices()[:8])
    mesh = Mesh(onp.asarray(devices), ("dev",))
    sharding = NamedSharding(mesh, P("dev"))
    bodies = [_blockwise_shard_body(cap, onp.dtype(onp.float32), "int8",
                                    DEFAULT_QBLOCK, len(devices))
              for cap in capacities]

    def step(*bufs):
        # bufs = grads then residuals (one of each per bucket), then the
        # launch-chain token, threaded bucket to bucket exactly as the
        # runtime chains consecutive launches
        n = len(capacities)
        tok = bufs[2 * n]
        flat = []
        for body, g, r in zip(bodies, bufs[:n], bufs[n:2 * n]):
            out, new_res, tok = body(g, r, tok)
            flat += [out, new_res]
        return tuple(flat) + (tok,)

    n_arg = 2 * len(capacities) + 1
    reduce_all = shard_map(step, mesh,
                           in_specs=(P("dev"),) * n_arg,
                           out_specs=(P("dev"),) * n_arg)
    jitted = jax.jit(
        reduce_all,
        in_shardings=(sharding,) * n_arg,
        out_shardings=(sharding,) * n_arg)
    specs = tuple(
        jax.ShapeDtypeStruct((len(devices), cap), jnp.float32,
                             sharding=sharding)
        for cap in capacities) * 2 + (
        jax.ShapeDtypeStruct((len(devices), 1), jnp.float32,
                             sharding=sharding),)
    return _capture_jit(
        jitted, specs, "allreduce.bucketed_step_int8", "allreduce",
        contract={
            "expected_collectives": {"all-reduce": 2 * len(capacities)},
            "resharding_free": True,
        },
        meta={"profile": "resnet50",
              "n_tensors": len(RESNET50_PROFILE),
              "n_buckets": len(capacities),
              "bucket_bytes": BUCKETED_STEP_BUCKET_BYTES,
              "block": DEFAULT_QBLOCK,
              "mode": "int8",
              "capacities": capacities})


# --------------------------------------------------------------------------
# flash attention
# --------------------------------------------------------------------------
def _flash_fn():
    import functools

    from mxnet_tpu.ops.pallas_kernels import flash_attention

    # interpret mode: the kernel lowers to plain HLO on CPU — the same
    # program structure (blocked streaming, masks) without Mosaic
    return functools.partial(flash_attention, causal=True, interpret=True)


def _flash_specs():
    import jax
    import jax.numpy as jnp

    shape = (1, 2, 16, 8)   # (B, H, T, D): tiny — capture, not perf
    return tuple(jax.ShapeDtypeStruct(shape, jnp.bfloat16)
                 for _ in range(3))


@_entrypoint("flash_attention.fwd")
def _capture_flash_fwd():
    import jax

    fa = _flash_fn()
    jitted = jax.jit(lambda q, k, v: fa(q, k, v))
    return _capture_jit(
        jitted, _flash_specs(), "flash_attention.fwd", "kernel",
        contract=_flash_contract(),
        meta={"shape": [1, 2, 16, 8], "dtype": "bfloat16",
              "causal": True, "mode": "interpret"})


@_entrypoint("flash_attention.bwd")
def _capture_flash_bwd():
    import jax
    import jax.numpy as jnp

    fa = _flash_fn()

    def loss(q, k, v):
        return jnp.sum(fa(q, k, v).astype(jnp.float32))

    jitted = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    return _capture_jit(
        jitted, _flash_specs(), "flash_attention.bwd", "kernel",
        contract=_flash_contract(),
        meta={"shape": [1, 2, 16, 8], "dtype": "bfloat16",
              "causal": True, "mode": "interpret"})


def _flash_contract():
    return {
        "dtype_policy": "bf16",
        "collective_free": True,
        "resharding_free": True,
        "waivers": [
            {"rule": "dtype-cliff",
             "reason": "flash softmax accumulates scores/log-sum-exp in "
                       "f32 by design (the kernel's documented numerics: "
                       "bf16 operands, f32 running max/denominator) — "
                       "the f32 island is the NaN fence, not a leak"},
        ],
    }


# --------------------------------------------------------------------------
# serve endpoint
# --------------------------------------------------------------------------
@_entrypoint("serve.endpoint")
def _capture_serve_endpoint():
    """The serve Endpoint's cached executable for one bucket: the very
    program traffic runs through (ExecutableCache.hlo_texts), not a
    re-lowering.  Single-device serving must stay collective- and
    host-callback-free."""
    import numpy as onp

    import mxnet_tpu as mx

    net = mx.gluon.nn.Dense(8, in_units=16)
    net.initialize()
    ep = mx.serve.Endpoint(net, max_batch_size=4, batch_buckets=[4],
                           start=False)
    x = onp.zeros((4, 16), onp.float32)
    ep._ensure_executable([x])
    ep._cache.warm([((4, 16), onp.float32)])
    texts = ep._cache.hlo_texts()
    sig, opt = sorted(texts.items())[0]
    return {
        "name": "serve.endpoint", "kind": "serve",
        "jaxpr": None, "lowered": None, "optimized": opt,
        "contract": {
            "collective_free": True,
            "resharding_free": True,
        },
        "meta": {"signature": sig, "entries": len(texts)},
    }


# --------------------------------------------------------------------------
# driver API
# --------------------------------------------------------------------------
def capture_one(name):
    _ensure_virtual_mesh()
    try:
        fn = _ENTRYPOINTS[name]
    except KeyError:
        raise KeyError(
            f"unknown artifact {name!r}; known: {entrypoint_names()}") \
            from None
    return fn()


def capture_all(names=None):
    """Capture specs for ``names`` (default: every entry point)."""
    _ensure_virtual_mesh()
    names = entrypoint_names() if not names else list(names)
    return [capture_one(n) for n in names]
