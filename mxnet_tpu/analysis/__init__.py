"""Compiled-artifact capture for static analysis (tools/hloscan).

This package turns the project's *real* entry points — the fused SPMD
train step, the bucketed kvstore collectives, the flash-attention
kernels, the serve endpoint's cached executable — into inspectable
artifacts: jaxpr text, lowered (pre-optimization) HLO, and the
optimized/scheduled HLO the backend actually runs, each bundled with
the **contract** that entry point declares (expected collective
census, dtype policy, sharding promises).

It deliberately knows nothing about rules or findings: the analyzer
side lives in ``tools/hloscan`` and consumes the plain dict specs
returned here, so the library keeps zero dependencies on tooling.

``census`` builds on the same captures for the per-layer
speed-of-light census (tools/layerscope): per-instruction cost
modeling over the optimized HLO, name-stack layer bucketing, roofline
bound classification, and MFU-floor contracts.
"""
from .capture import (  # noqa: F401
    build_dp_fused_step,
    capture_all,
    capture_one,
    entrypoint_names,
)
from .census import (  # noqa: F401
    build_census,
    census_entrypoint_names,
    census_one,
    compiled_cost_summary,
    harvest_cost_analysis,
)
