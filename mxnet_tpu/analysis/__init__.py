"""Compiled-artifact capture for static analysis (tools/hloscan).

This package turns the project's *real* entry points — the fused SPMD
train step, the bucketed kvstore collectives, the flash-attention
kernels, the serve endpoint's cached executable — into inspectable
artifacts: jaxpr text, lowered (pre-optimization) HLO, and the
optimized/scheduled HLO the backend actually runs, each bundled with
the **contract** that entry point declares (expected collective
census, dtype policy, sharding promises).

It deliberately knows nothing about rules or findings: the analyzer
side lives in ``tools/hloscan`` and consumes the plain dict specs
returned here, so the library keeps zero dependencies on tooling.
"""
from .capture import (  # noqa: F401
    capture_all,
    capture_one,
    entrypoint_names,
)
