"""Per-layer speed-of-light census with roofline attribution.

The aggregate bench numbers (7.35x V100 fp32, 54.8% BERT MFU) hide
per-layer sag; ROADMAP item 5 calls for a per-layer achieved-TF/s census
"committed as the evidence standard for every future perf PR".  This
module is that census:

* Gluon blocks push ``jax.named_scope(block.name)`` around ``forward``
  (gluon/block.py), so every op in the compiled HLO carries its block
  path in ``metadata={op_name="..."}`` — forward ops as
  ``jvp(<root>)/<child>/<op>``, backward ops as
  ``transpose(jvp(<root>))/<child>/<op>``, the fused optimizer update
  under ``optimizer/``.
* :func:`per_instruction_costs` walks the optimized HLO text with a
  static cost model (dot/conv FLOPs from shapes and dimension numbers,
  elementwise sizes, operand+result bytes) — ``compiled.cost_analysis()``
  on this toolchain returns only per-program aggregates, so the
  per-instruction split is modeled here and cross-checked against the
  XLA aggregate (recorded in ``totals``).
* :func:`bucket_costs` groups instruction costs by name-stack layer and
  phase (fwd/bwd), :func:`build_census` classifies each bucket against a
  per-device roofline (:data:`PEAKS`) and emits the JSON-stable artifact
  consumed by ``tools/layerscope`` and the bench riders.
* :func:`evaluate_contract` fences the result hloscan-style: per-layer
  MFU-floor contracts with REQUIRED-reason waivers; the ResNet stem and
  BN-backward (VERDICT items 3/6) land as waived known-offenders so the
  census documents them instead of hiding them.

On the virtual CPU mesh the census runs in **cost-model-only** mode:
bound classes and ``mfu_sol`` (the shape-intrinsic speed-of-light MFU,
``min(1, intensity/ridge)``) come from the model alone.  On real
hardware, :func:`attach_timings` joins measured per-region seconds (the
PR 2 profiler timeline / ``jax.profiler.TraceAnnotation`` regions) to
produce achieved TF/s, GB/s and measured MFU.

Like ``capture.py``, this module carries zero tooling dependency — the
CLI/driver/baseline layers live in ``tools/layerscope``.
"""
from __future__ import annotations

import json
import re

__all__ = [
    "PEAKS", "CONTRACTS", "SCHEMA",
    "harvest_cost_analysis", "compiled_cost_summary",
    "per_instruction_costs", "parse_op_name", "bucket_costs",
    "classify_bound", "build_census", "evaluate_contract",
    "attach_timings", "timings_from_trace", "publish_metrics",
    "census_entrypoint_names", "census_one", "layer_names",
]

SCHEMA = "mxtpu-layer-census-v1"

#: Per-device roofline peaks.  ``flops`` is the dense bf16 matmul peak,
#: ``bw`` the HBM bandwidth, ``launch_s`` the per-kernel dispatch floor
#: used for the launch-bound class.  The CPU mesh has no meaningful
#: roofline of its own, so cost-model-only runs classify against the
#: *target* chip (default v5e) — the census models what the chip would
#: be bound by, not what the host happens to do.
PEAKS = {
    "tpu-v5e": {"flops": 197e12, "bw": 819e9, "launch_s": 2e-6},
    "tpu-v4": {"flops": 275e12, "bw": 1228e9, "launch_s": 2e-6},
}
DEFAULT_DEVICE = "tpu-v5e"


# --------------------------------------------------------------------------
# cost_analysis() harvesting — THE single implementation (the benchmark
# experiments import this instead of hand-rolling the dict walk)
# --------------------------------------------------------------------------
def harvest_cost_analysis(ca):
    """Normalize a raw ``compiled.cost_analysis()`` result.

    This toolchain returns either a dict or a single-element list of
    dicts, with space-separated keys (``"bytes accessed"``) and only
    per-program aggregates.  Returns a plain-float dict with stable
    snake_case keys: ``flops``, ``bytes_accessed``, ``transcendentals``
    (absent entries -> 0.0).
    """
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    ca = dict(ca or {})
    return {
        "flops": float(ca.get("flops", 0.0) or 0.0),
        "bytes_accessed": float(ca.get("bytes accessed", 0.0) or 0.0),
        "transcendentals": float(ca.get("transcendentals", 0.0) or 0.0),
    }


def compiled_cost_summary(compiled):
    """``harvest_cost_analysis`` straight off a ``jax.stages.Compiled``."""
    return harvest_cost_analysis(compiled.cost_analysis())


# --------------------------------------------------------------------------
# op_name -> (layer path, phase)
# --------------------------------------------------------------------------
# transformation wrappers jax wraps scope components in; ``transpose``
# marks the VJP transpose pass (the backward program)
_WRAP_RE = re.compile(r"^([A-Za-z_][\w.\-]*)\((.*)\)$")
_DROP_WRAPPERS = frozenset({"jit", "pjit"})
_KEEP_WRAPPERS = frozenset({
    "jvp", "vjp", "transpose", "remat", "checkpoint", "custom_jvp",
    "custom_vjp", "vmap", "pmap", "shard_map", "rematted_computation",
    "named"})


def parse_op_name(op_name):
    """Split an HLO ``op_name`` path into ``(layer_path, phase)``.

    ``jit(...)``/``pjit(...)`` components are function frames, not
    layers — dropped.  ``jvp(x)``/``transpose(jvp(x))`` unwrap to ``x``;
    a ``transpose`` wrapper anywhere marks the instruction as backward.
    The trailing component (the primitive name) is discarded.

    >>> parse_op_name("jit(f)/jit(main)/transpose(jvp(net))/d1/dot_general")
    (('net', 'd1'), 'bwd')
    """
    if not op_name:
        return (), "fwd"
    comps = op_name.split("/")[:-1]   # last component is the primitive
    path, phase = [], "fwd"
    for comp in comps:
        c, drop = comp, False
        while True:
            m = _WRAP_RE.match(c)
            if not m:
                break
            wrapper, inner = m.groups()
            if wrapper == "transpose":
                phase = "bwd"
            if wrapper in _DROP_WRAPPERS:
                drop = True
            elif wrapper not in _KEEP_WRAPPERS:
                break             # unknown wrapper: keep the component
            c = inner
        if drop or not c or c == "main":
            continue
        path.append(c)
    return tuple(path), phase


# --------------------------------------------------------------------------
# optimized-HLO per-instruction cost model
# --------------------------------------------------------------------------
_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
    "token": 0, "opaque": 0,
}

_SHAPE_RE = re.compile(r"\b([a-z]+\d*(?:e\d+m\d+\w*)?)\[([\d,]*)\]")
_INSTR_RE = re.compile(
    r"^\s*(ROOT\s+)?%?([\w.\-]+)\s*=\s*(.*?)\s+([\w\-]+)\(")
_COMP_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
_CALLS_RE = re.compile(r"calls=%?([\w.\-]+)")
_TOAPPLY_RE = re.compile(r"to_apply=%?([\w.\-]+)")
_BODY_RE = re.compile(r"(?:body|condition)=%?([\w.\-]+)")
_BRANCH_RE = re.compile(r"branch_computations=\{([^}]*)\}")
_CONTRACT_DIMS_RE = re.compile(r"lhs_contracting_dims=\{([\d,]*)\}")
_DIM_LABELS_RE = re.compile(r"dim_labels=(\w+)_(\w+)->(\w+)")

# no data movement or math of their own
_FREE_OPS = frozenset({
    "parameter", "constant", "get-tuple-element", "tuple", "bitcast",
    "after-all", "partition-id", "replica-id", "domain", "opt-barrier",
})
# pure data movement: a metadata-less fusion/call made of nothing but
# these is compiler glue (layout/precision adapters), not a layer's math
_MOVEMENT_OPS = _FREE_OPS | frozenset({
    "convert", "copy", "transpose", "reshape", "slice", "pad",
    "broadcast", "concatenate", "reverse",
})
_ELEMENTWISE_TRANSCENDENTAL = frozenset({
    "exponential", "exponential-minus-one", "log", "log-plus-one",
    "logistic", "tanh", "rsqrt", "sqrt", "cbrt", "power", "sine",
    "cosine", "tan", "atan2", "erf", "erf-inv", "expm1", "log1p",
})
_ELEMENTWISE = frozenset({
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "negate", "abs", "compare", "select", "and", "or", "xor", "not",
    "floor", "ceil", "round-nearest-afz", "round-nearest-even", "clamp",
    "sign", "remainder", "shift-left", "shift-right-arithmetic",
    "shift-right-logical", "convert", "is-finite",
}) | _ELEMENTWISE_TRANSCENDENTAL


def _shape_elems_bytes(text, float_cap=None):
    """(total elements, total bytes) over every dtype[dims] in ``text``
    (a tuple shape contributes each component).  ``float_cap`` caps the
    per-element width charged for float tensors — see
    :func:`per_instruction_costs` on host-mesh float normalization."""
    elems = byts = 0
    for dtype, dims in _SHAPE_RE.findall(text):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        elems += n
        w = _DTYPE_BYTES.get(dtype, 4)
        if float_cap and dtype in ("f32", "f64") and w > float_cap:
            w = float_cap
        byts += n * w
    return elems, byts


def _split_operands(after_open_paren):
    """Text inside the top-level parens of an instruction line (operand
    list), cut at the balanced close; returns (operands, attrs)."""
    depth, i = 1, 0
    while i < len(after_open_paren) and depth:
        ch = after_open_paren[i]
        if ch in "({[":
            depth += 1
        elif ch in ")}]":
            depth -= 1
        i += 1
    return after_open_paren[:i - 1], after_open_paren[i:]


_OPERAND_NAME_RE = re.compile(r"%([\w.\-]+)")


class _Instr:
    __slots__ = ("name", "opcode", "result", "operands", "attrs",
                 "op_name")

    def __init__(self, name, opcode, result, operands, attrs, op_name):
        self.name = name
        self.opcode = opcode
        self.result = result
        self.operands = operands
        self.attrs = attrs
        self.op_name = op_name

    @property
    def operand_names(self):
        return _OPERAND_NAME_RE.findall(self.operands)


def _parse_computations(hlo_text):
    """{comp_name: [instr...]} plus the ENTRY name and the set of
    computations called as fusion bodies (their instructions carry flops
    but no memory traffic of their own)."""
    comps, entry, fused = {}, None, set()
    applied = set()           # reduce/scatter reducers: modeled at caller
    current = None
    for line in hlo_text.splitlines():
        if "= " not in line and "{" in line:
            m = _COMP_RE.match(line.strip())
            if m:
                current = m.group(2)
                comps[current] = []
                if m.group(1):
                    entry = current
            continue
        m = _INSTR_RE.match(line)
        if not m or current is None:
            continue
        _root, name, result, opcode, rest = (
            m.group(1), m.group(2), m.group(3), m.group(4),
            line[m.end():])
        operands, attrs = _split_operands(rest)
        op_name = ""
        mm = _OPNAME_RE.search(attrs)
        if mm:
            op_name = mm.group(1)
        instr = _Instr(name, opcode, result, operands, attrs, op_name)
        comps[current].append(instr)
        if opcode == "fusion":
            for cname in _CALLS_RE.findall(attrs):
                fused.add(cname)
        elif opcode != "call":
            for cname in _TOAPPLY_RE.findall(attrs):
                applied.add(cname)
        for rx in (_BODY_RE,):
            for cname in rx.findall(attrs):
                applied.discard(cname)   # while bodies are walked fully
    return comps, entry, fused, applied


def _instr_flops(instr):
    """Modeled FLOPs (and transcendental count) for one instruction."""
    op = instr.opcode
    if op in _FREE_OPS:
        return 0.0, 0.0
    out_elems, _ = _shape_elems_bytes(instr.result)
    if op == "dot":
        shapes = _SHAPE_RE.findall(instr.operands)
        if not shapes:
            return 0.0, 0.0
        lhs_dims = [int(d) for d in shapes[0][1].split(",") if d]
        m = _CONTRACT_DIMS_RE.search(instr.attrs)
        k = 1
        if m:
            for d in m.group(1).split(","):
                if d:
                    k *= lhs_dims[int(d)] if int(d) < len(lhs_dims) else 1
        return 2.0 * out_elems * k, 0.0
    if op == "convolution":
        shapes = _SHAPE_RE.findall(instr.operands)
        if len(shapes) < 2:
            return 0.0, 0.0
        rhs_dims = [int(d) for d in shapes[1][1].split(",") if d]
        kernel_elems = 1
        for d in rhs_dims:
            kernel_elems *= d
        m = _DIM_LABELS_RE.search(instr.attrs)
        out_features = 1
        if m:
            kernel_labels, out_labels = m.group(2), m.group(3)
            o_idx = kernel_labels.find("o")
            if 0 <= o_idx < len(rhs_dims):
                out_features = rhs_dims[o_idx] or 1
        # 2 * (output positions) * (MACs per position); exact for fwd
        # and grouped convs, same-order for the wgrad transpose layouts
        return 2.0 * out_elems * kernel_elems / max(out_features, 1), 0.0
    if op in ("reduce", "reduce-window", "select-and-scatter"):
        in_elems, _ = _shape_elems_bytes(instr.operands)
        return float(in_elems), 0.0
    if op in _ELEMENTWISE:
        tr = float(out_elems) if op in _ELEMENTWISE_TRANSCENDENTAL else 0.0
        return float(out_elems), tr
    return 0.0, 0.0


def _movement_only_callee(comps, ins):
    """True when ``ins`` is a fusion/call whose called computation(s)
    contain nothing but data-movement ops (see per_instruction_costs on
    why such glue must not inherit a layer scope)."""
    if ins.opcode == "fusion":
        called = _CALLS_RE.findall(ins.attrs)
    elif ins.opcode == "call":
        called = _TOAPPLY_RE.findall(ins.attrs)
    else:
        return False
    if not called:
        return False
    for cname in called:
        inner = comps.get(cname)
        if not inner or any(i.opcode not in _MOVEMENT_OPS for i in inner):
            return False
    return True


def per_instruction_costs(hlo_text, mxu_float_cap=None):
    """Walk optimized HLO text; one cost record per instruction:
    ``{"name", "opcode", "op_name", "flops", "bytes", "transcendentals"}``.

    Fusion bodies contribute FLOPs through their inner instructions
    (which carry their own op_name metadata) while the fusion
    instruction itself carries the kernel's memory traffic — inner
    values live in registers/VMEM.  reduce/scatter applied computations
    are modeled at the caller.

    An XLA rewrite pass occasionally emits an instruction with no
    metadata (e.g. the canonicalized input-gradient convolution); such
    instructions inherit the op_name of their first annotated operand
    so a multi-MFLOP kernel never lands in the unattributed bucket over
    a compiler cosmetic.  The exception: a metadata-less fusion/call
    whose called computation is pure data movement (layout transposes,
    precision round-trips — :data:`_MOVEMENT_OPS`) does NOT inherit.
    Those are host-backend glue between layers (e.g. the NHWC copy
    feeding a neighbor's wgrad conv); inheriting would charge one
    layer's bucket for a copy the compiler inserted on behalf of
    another, so they pool unattributed instead (they carry zero FLOPs,
    leaving attribution coverage untouched).

    ``mxu_float_cap`` (bytes per element, e.g. ``2`` for a bf16
    program) corrects a host-mesh lowering artifact on MXU ops: the CPU
    backend's float-normalization pass widens every bf16 convolution /
    dot to f32 (the HLO shows the tell-tale ``bf16 -> f32`` convert
    sandwich around each one), which would double the byte traffic the
    roofline charges those ops.  The target chip runs them
    native-width, so when set, float operand/result tensors of
    ``convolution``/``dot`` instructions are charged at most the cap.
    Non-MXU instructions keep their lowered widths — f32 BN statistics
    and f32 master weights are genuinely f32 on device too.
    """
    comps, entry, fused, applied = _parse_computations(hlo_text)
    effective = {}            # instr name -> effective op_name
    records = []
    for cname, instrs in comps.items():
        skip = cname in applied and cname not in fused
        in_fusion = cname in fused
        for ins in instrs:
            eff = ins.op_name
            if not eff and not _movement_only_callee(comps, ins):
                for op in ins.operand_names:
                    eff = effective.get(op, "")
                    if eff:
                        break
            effective[ins.name] = eff
            if skip:
                continue
            flops, trans = _instr_flops(ins)
            if ins.opcode == "fusion":
                flops = 0.0     # inner instructions carry the math
            byts = 0.0
            if not in_fusion and ins.opcode not in _FREE_OPS:
                cap = (mxu_float_cap
                       if ins.opcode in ("convolution", "dot") else None)
                _e_in, b_in = _shape_elems_bytes(ins.operands, cap)
                _e_out, b_out = _shape_elems_bytes(ins.result, cap)
                byts = float(b_in + b_out)
            if flops or byts or trans:
                records.append({
                    "name": ins.name, "opcode": ins.opcode,
                    "op_name": eff, "flops": flops,
                    "bytes": byts, "transcendentals": trans,
                })
    return records


# --------------------------------------------------------------------------
# bucketing + roofline
# --------------------------------------------------------------------------
UNATTRIBUTED = "(unattributed)"


def bucket_costs(records, known_layers=()):
    """Group per-instruction costs by (layer path, phase).

    An instruction is *attributed* when its cleaned op_name path
    contains at least one known layer scope; everything else pools under
    ``(unattributed)`` so a scoping regression shows up as a giant
    anonymous bucket instead of vanishing.
    """
    known = set(known_layers)
    rows = {}
    for rec in records:
        path, phase = parse_op_name(rec["op_name"])
        attributed = bool(known) and any(c in known for c in path)
        label = "/".join(path) if (path and attributed) else UNATTRIBUTED
        key = (label, phase)
        row = rows.get(key)
        if row is None:
            row = rows[key] = {
                "layer": label, "phase": phase, "attributed": attributed,
                "flops": 0.0, "bytes": 0.0, "transcendentals": 0.0,
                "instructions": 0,
            }
        row["flops"] += rec["flops"]
        row["bytes"] += rec["bytes"]
        row["transcendentals"] += rec["transcendentals"]
        row["instructions"] += 1
    return list(rows.values())


def classify_bound(flops, byts, n_instr, peaks):
    """(bound class, modeled seconds) against the roofline: the term
    that dominates the modeled kernel time names the bound."""
    t_mxu = flops / peaks["flops"]
    t_hbm = byts / peaks["bw"]
    t_launch = n_instr * peaks["launch_s"]
    t = max(t_mxu, t_hbm, t_launch)
    if t_launch >= max(t_mxu, t_hbm):
        return "launch-bound", t
    return ("MXU-bound" if t_mxu >= t_hbm else "HBM-bound"), t


def build_census(spec, device=DEFAULT_DEVICE):
    """Assemble the census artifact from an entry-point spec
    (``{"entry", "optimized", "cost_analysis", "layers", "contract",
    "meta"}``).  Cost-model-only: measured fields stay ``None`` until
    :func:`attach_timings` joins real region timings."""
    peaks = PEAKS[device]
    # bf16/f16 programs charge MXU ops native-width (the host mesh
    # float-normalizes them to f32 — see per_instruction_costs)
    cap = {"bfloat16": 2, "float16": 2}.get(
        (spec.get("meta") or {}).get("dtype"))
    records = per_instruction_costs(spec["optimized"], mxu_float_cap=cap)
    rows = bucket_costs(records, spec.get("layers", ()))
    ridge = peaks["flops"] / peaks["bw"]

    total_flops = sum(r["flops"] for r in rows) or 1.0
    total_bytes = sum(r["bytes"] for r in rows)
    for row in rows:
        bound, t = classify_bound(
            row["flops"], row["bytes"], row["instructions"], peaks)
        row["bound"] = bound
        row["modeled_time_s"] = t
        row["intensity"] = (row["flops"] / row["bytes"]
                            if row["bytes"] else None)
        # shape-intrinsic speed-of-light MFU: what the roofline permits
        # for this (flops, bytes) mix, launch overhead aside — a floor
        # violated by mfu_sol can NEVER be met by tuning the schedule
        row["mfu_sol"] = (min(1.0, row["intensity"] / ridge)
                          if row["intensity"] is not None
                          else (1.0 if row["flops"] else 0.0))
        row["mfu"] = None
        row["tf_per_s"] = None
        row["gb_per_s"] = None
        row["measured_time_s"] = None
    modeled_total = sum(r["modeled_time_s"] for r in rows) or 1.0
    for row in rows:
        row["pct_time"] = round(100.0 * row["modeled_time_s"] /
                                modeled_total, 3)
    rows.sort(key=lambda r: (-r["modeled_time_s"], r["layer"], r["phase"]))

    attributed = sum(r["flops"] for r in rows if r["attributed"])
    xla = dict(spec.get("cost_analysis") or {})
    doc = {
        "schema": SCHEMA,
        "entry": spec["entry"],
        "device": device,
        "mode": "cost-model",
        "peaks": dict(peaks),
        "attributed_flops_fraction": round(attributed / total_flops, 6),
        "totals": {
            "flops": total_flops,
            "bytes": total_bytes,
            "instructions": sum(r["instructions"] for r in rows),
            "modeled_time_s": modeled_total,
            "xla_flops": xla.get("flops"),
            "xla_bytes_accessed": xla.get("bytes_accessed"),
            "xla_transcendentals": xla.get("transcendentals"),
        },
        "rows": rows,
        "contract": spec.get("contract") or {},
        "meta": dict(spec.get("meta") or {}),
    }
    doc["findings"] = evaluate_contract(doc, doc["contract"])
    return doc


# --------------------------------------------------------------------------
# measured-timings join (real hardware: PR 2 profiler timeline)
# --------------------------------------------------------------------------
def timings_from_trace(trace, layer_labels):
    """Sum per-region seconds out of a chrome-trace dict (the profiler
    timeline / ``jax.profiler.TraceAnnotation`` dump): complete events
    whose name matches a census row label (``layer`` or
    ``layer@phase``).  ``trace`` is the parsed JSON dict."""
    wanted = set(layer_labels)
    out = {}
    for ev in trace.get("traceEvents", []):
        name = ev.get("name")
        if ev.get("ph") not in ("X", "B") or name not in wanted:
            continue
        out[name] = out.get(name, 0.0) + float(ev.get("dur", 0.0)) * 1e-6
    return out


def attach_timings(doc, region_seconds):
    """Join measured per-region seconds onto a cost-model census.

    ``region_seconds`` maps ``layer`` or ``layer@phase`` to seconds.  A
    layer-level time splits across that layer's phases proportionally to
    their modeled time.  Rows with a measurement gain achieved TF/s,
    GB/s and measured MFU; ``pct_time`` re-normalizes over measured
    rows; mode flips to ``measured``.  Contract floors re-evaluate
    against measured MFU where present."""
    peaks = doc["peaks"]
    by_layer = {}
    for row in doc["rows"]:
        by_layer.setdefault(row["layer"], []).append(row)
    for row in doc["rows"]:
        t = region_seconds.get(f"{row['layer']}@{row['phase']}")
        if t is None and row["layer"] in region_seconds:
            group = by_layer[row["layer"]]
            total = sum(r["modeled_time_s"] for r in group) or 1.0
            t = (region_seconds[row["layer"]] *
                 row["modeled_time_s"] / total)
        if t is None or t <= 0:
            continue
        row["measured_time_s"] = t
        row["tf_per_s"] = row["flops"] / t / 1e12
        row["gb_per_s"] = row["bytes"] / t / 1e9
        row["mfu"] = min(1.0, row["flops"] / t / peaks["flops"])
    measured = [r for r in doc["rows"] if r["measured_time_s"]]
    if measured:
        doc["mode"] = "measured"
        total = sum(r["measured_time_s"] for r in measured)
        for r in doc["rows"]:
            r["pct_time"] = (round(100.0 * r["measured_time_s"] / total, 3)
                             if r["measured_time_s"] else 0.0)
        doc["rows"].sort(key=lambda r: (-(r["measured_time_s"] or 0.0),
                                        r["layer"], r["phase"]))
        doc["findings"] = evaluate_contract(doc, doc["contract"])
    return doc


# --------------------------------------------------------------------------
# contracts (hloscan-style: typo'd keys raise, waivers REQUIRE a reason)
# --------------------------------------------------------------------------
KNOWN_CENSUS_CONTRACT_KEYS = frozenset({
    "min_attributed_flops", "mfu_floors", "waivers"})
_RULES = frozenset({"attribution-coverage", "mfu-floor"})


def _row_mfu(row):
    return row["mfu"] if row["mfu"] is not None else row["mfu_sol"]


def evaluate_contract(doc, contract):
    """Findings (list of dicts) for a census against its contract.

    * ``min_attributed_flops``: float — attribution-coverage floor.
    * ``mfu_floors``: ``{pattern: floor}`` — pattern substring-matches a
      row's layer label, with an optional ``@fwd``/``@bwd`` suffix
      restricting the phase; a row whose MFU (measured when available,
      speed-of-light otherwise) sits below the floor is a finding.  A
      floor that matches no row is itself a finding (``stale-floor``) —
      contracts must track the model they fence.
    * ``waivers``: ``[{"rule", "match", "reason"}]`` — ``match``
      substring-matches the finding key.  A waiver without a reason is a
      ``bad-waiver`` finding and waives nothing; a waiver matching no
      finding is a ``stale-waiver`` finding (known-offenders that stop
      offending must be celebrated and removed, not carried).
    """
    unknown = set(contract) - KNOWN_CENSUS_CONTRACT_KEYS
    if unknown:
        raise ValueError(
            f"unknown census contract keys {sorted(unknown)}; known: "
            f"{sorted(KNOWN_CENSUS_CONTRACT_KEYS)}")
    findings = []
    min_attr = contract.get("min_attributed_flops")
    if min_attr is not None and \
            doc["attributed_flops_fraction"] < min_attr:
        findings.append({
            "rule": "attribution-coverage", "key": "coverage",
            "message": (
                f"only {doc['attributed_flops_fraction']:.1%} of modeled "
                f"FLOPs attributed to named Gluon layers (floor "
                f"{min_attr:.0%}) — name-scope propagation regressed or "
                f"a new unscoped compute path appeared"),
            "waived": False, "reason": None})
    for pattern, floor in (contract.get("mfu_floors") or {}).items():
        pat, _, phase = pattern.partition("@")
        matched = False
        for row in doc["rows"]:
            if not row["attributed"] or pat not in row["layer"]:
                continue
            if phase and row["phase"] != phase:
                continue
            matched = True
            mfu = _row_mfu(row)
            if mfu < floor:
                kind = ("measured MFU" if row["mfu"] is not None
                        else "speed-of-light MFU")
                findings.append({
                    "rule": "mfu-floor",
                    "key": f"{row['layer']}@{row['phase']}",
                    "message": (
                        f"{row['layer']} [{row['phase']}] {kind} "
                        f"{mfu:.1%} < floor {floor:.0%} "
                        f"({row['bound']}, intensity "
                        f"{row['intensity'] if row['intensity'] is None else round(row['intensity'], 2)})"),
                    "waived": False, "reason": None})
        if not matched:
            findings.append({
                "rule": "stale-floor", "key": pattern,
                "message": (
                    f"mfu_floors pattern {pattern!r} matches no census "
                    f"row — the layer was renamed or removed; update the "
                    f"contract"),
                "waived": False, "reason": None})
    findings = _apply_waivers(findings, contract.get("waivers") or ())
    return findings


def _apply_waivers(findings, waivers):
    used = [False] * len(waivers)
    for f in findings:
        if f["rule"] not in _RULES:
            continue
        for i, w in enumerate(waivers):
            if w.get("rule") != f["rule"] or \
                    w.get("match", "") not in f["key"]:
                continue
            used[i] = True
            reason = (w.get("reason") or "").strip()
            if reason:
                f["waived"] = True
                f["reason"] = reason
            break
    out = list(findings)
    for i, w in enumerate(waivers):
        reason = (w.get("reason") or "").strip()
        if not reason:
            out.append({
                "rule": "bad-waiver",
                "key": f"{w.get('rule')}|{w.get('match')}",
                "message": (
                    f"waiver for {w.get('rule')!r} match "
                    f"{w.get('match')!r} has no reason — every waiver "
                    f"must explain why the sag is accepted"),
                "waived": False, "reason": None})
        elif not used[i]:
            out.append({
                "rule": "stale-waiver",
                "key": f"{w.get('rule')}|{w.get('match')}",
                "message": (
                    f"waiver for {w.get('rule')!r} match "
                    f"{w.get('match')!r} matched no finding — the "
                    f"offender stopped offending; remove the waiver"),
                "waived": False, "reason": None})
    return out


# --------------------------------------------------------------------------
# telemetry
# --------------------------------------------------------------------------
def publish_metrics(doc, registry=None):
    """Publish ``mxtpu_layer_mfu{entry,layer}`` (measured MFU when
    joined, speed-of-light MFU in cost-model mode) and
    ``mxtpu_layer_time_fraction{entry,layer}`` gauges."""
    from .. import telemetry as _telemetry
    reg = registry or _telemetry.default_registry()
    mfu_g = reg.gauge(
        "mxtpu_layer_mfu",
        "Per-layer MFU from the layerscope census (measured when region "
        "timings are joined, speed-of-light from the cost model "
        "otherwise)", labelnames=("entry", "layer"))
    frac_g = reg.gauge(
        "mxtpu_layer_time_fraction",
        "Per-layer fraction of step time from the layerscope census",
        labelnames=("entry", "layer"))
    for row in doc["rows"]:
        label = f"{row['layer']}@{row['phase']}"
        mfu_g.labels(entry=doc["entry"], layer=label).set(_row_mfu(row))
        frac_g.labels(entry=doc["entry"], layer=label).set(
            row["pct_time"] / 100.0)


# --------------------------------------------------------------------------
# entry points (census-only registry; the dp step reuses capture.py's
# builder so what the census walks is the very program a step dispatches)
# --------------------------------------------------------------------------
def layer_names(block, extra=("optimizer",)):
    """Every scope-name component in a block tree (plus pseudo-layers
    like the fused optimizer update)."""
    names = set(extra)

    def walk(b):
        names.add(b.name)
        for child in b._children.values():
            walk(child)

    walk(block)
    return sorted(names)


#: Census contracts per entry point.  The resnet_profile floors encode
#: ROADMAP item 5 / VERDICT items 3 and 6: the 7x7/s2 stem and
#: BN-backward are *known* offenders — documented via waivers with the
#: refutation evidence, not hidden.
CONTRACTS = {
    "fused_train_step_dp": {
        "min_attributed_flops": 0.90,
    },
    "quantized_allreduce": {
        "min_attributed_flops": 0.90,
    },
    "resnet_profile": {
        # The stem and bn@bwd floors used to carry reasoned waivers
        # (VERDICT items 3/6).  PR 18 retired both: the stem runs in
        # space-to-depth form (SpaceToDepthStem — dense K=192
        # contraction, ops/stem.py) and BN-backward's reduction epilogue
        # is one joint variadic reduce (ops/nn.py _bn_bwd_sums, the
        # tuned bn_bwd_epilogue Pallas kernel on TPU), so the floors now
        # simply pass — see docs/AUTOTUNE.md "waiver retirement".
        "min_attributed_flops": 0.90,
        "mfu_floors": {"stem": 0.50, "bn@bwd": 0.10},
    },
}


def _census_fused_train_step_dp():
    from . import capture as _capture
    _capture._ensure_virtual_mesh()
    fused, args, batch_size, meta = _capture.build_dp_fused_step()
    compiled = fused.lower(*args, batch_size=batch_size).compile()
    return {
        "entry": "fused_train_step_dp",
        "optimized": compiled.as_text(),
        "cost_analysis": harvest_cost_analysis(compiled.cost_analysis()),
        "layers": layer_names(fused._block),
        "contract": CONTRACTS["fused_train_step_dp"],
        "meta": meta,
    }


def _census_resnet_profile():
    """A ResNet-shaped FusedTrainStep: space-to-depth stem + two fused
    conv+BN+relu units + pooled head, sized to compile fast on the CPU
    mesh while keeping the stem/BN cost structure honest at recipe
    realism:

    * bf16 activations/weights (the production dtype; the census only
      lowers+compiles, it never executes, so bf16 costs nothing in
      fidelity) with f32 BN statistics;
    * the stem is :class:`~mxnet_tpu.gluon.nn.SpaceToDepthStem` — the
      transform that retired the stem MFU waiver.  The s2d packing
      itself rides the ROOT scope, not the stem bucket: it belongs to
      the input pipeline (MLPerf practice packs on the host), and the
      stem floor fences the conv the chip actually runs;
    * each body unit is a ``_FusedConvBN`` — conv + BN + relu traced in
      ONE named scope, because that is the execution unit the target
      chip schedules: BN's backward reduction epilogue (the tuned
      ``bn_bwd_epilogue`` Pallas kernel, ops/nn.py) and the dx
      elementwise chain fuse into the conv backward, so splitting them
      into separate census buckets would charge the fused kernel's
      traffic twice and fence a boundary that does not exist on device.
      The ``bn@bwd`` floor fences these fused units;
    * convs are bias-free (each feeds a BatchNorm that would absorb the
      bias; a broadcast add would double the layer's output bytes);
    * the head pools before the Dense so head flops stay a footnote."""
    import numpy as onp

    from . import capture as _capture
    _capture._ensure_virtual_mesh()

    import mxnet_tpu as mx
    from mxnet_tpu.gluon import FusedTrainStep, Trainer, loss as gloss, nn
    from mxnet_tpu.gluon.block import HybridBlock
    from mxnet_tpu.gluon.nn.basic_layers import _resolve_init
    from mxnet_tpu.gluon.parameter import Parameter

    class _FusedConvBN(HybridBlock):
        """3x3 conv + BatchNorm + relu in one named scope (see the
        profile docstring for why the census buckets them jointly)."""

        def __init__(self, channels, in_channels):
            super().__init__()
            self._channels = channels
            self.weight = Parameter(
                "weight", shape=(channels, in_channels, 3, 3),
                dtype="bfloat16", init=None, allow_deferred_init=True)
            self.gamma = Parameter("gamma", shape=(channels,),
                                   init=_resolve_init("ones"))
            self.beta = Parameter("beta", shape=(channels,),
                                  init=_resolve_init("zeros"))
            self.running_mean = Parameter(
                "running_mean", shape=(channels,),
                init=_resolve_init("zeros"), differentiable=False)
            self.running_var = Parameter(
                "running_var", shape=(channels,),
                init=_resolve_init("ones"), differentiable=False)

        def forward(self, x):
            h = mx.npx.convolution(
                x, self.weight.data(), None, kernel=(3, 3),
                stride=(1, 1), dilate=(1, 1), pad=(1, 1),
                num_filter=self._channels, num_group=1, layout="NCHW")
            h = mx.npx.batch_norm(
                h, self.gamma.data(), self.beta.data(),
                self.running_mean.data(), self.running_var.data(),
                eps=1e-5, momentum=0.9, fix_gamma=False,
                use_global_stats=False, axis=1)
            return mx.npx.relu(h)

    class _ResNetProfile(HybridBlock):
        def __init__(self):
            super().__init__()
            self.stem = nn.SpaceToDepthStem(64, in_channels=3,
                                            dtype="bfloat16")
            self.convbn = _FusedConvBN(64, in_channels=64)
            self.convbn2 = _FusedConvBN(64, in_channels=64)
            self.head = nn.Dense(8, in_units=64, dtype="bfloat16")
            self.loss_fn = gloss.SoftmaxCrossEntropyLoss()

        def forward(self, x, y):
            xs = mx.nd.space_to_depth(x, 2)     # input pipeline, root scope
            h = self.convbn(self.stem(xs))
            h = self.convbn2(h) + h             # residual join, root scope
            h = h.mean(axis=(2, 3))             # pooled head, root scope
            return self.loss_fn(self.head(h), y)

    rng = onp.random.RandomState(3)
    net = _ResNetProfile()
    net.initialize()
    tr = Trainer(net.collect_params(), "sgd",
                 {"learning_rate": 0.1, "momentum": 0.9})
    step = FusedTrainStep(net, tr)
    x = mx.np.array(rng.uniform(-1, 1, (8, 3, 64, 64)).astype(onp.float32),
                    dtype="bfloat16")
    y = mx.np.array(rng.randint(0, 8, (8,)), dtype="int32")
    compiled = step.lower(x, y, batch_size=8).compile()
    return {
        "entry": "resnet_profile",
        "optimized": compiled.as_text(),
        "cost_analysis": harvest_cost_analysis(compiled.cost_analysis()),
        "layers": layer_names(net),
        "contract": CONTRACTS["resnet_profile"],
        "meta": {"batch": 8, "input": [8, 3, 64, 64], "dtype": "bfloat16",
                 "profile": "resnet-s2d-stem-bn"},
    }


def _census_quantized_allreduce():
    """The block-scaled int8 bucket reduce, attributed to its three
    named scopes (``quantize``/``allreduce``/``dequantize``) so the
    compression overhead is a roofline-classified line item: the
    quantize/dequantize elementwise cost must stay a small, HBM-bound
    tax next to the payload collective it shrinks."""
    import jax
    import jax.numpy as jnp

    from . import capture as _capture
    _capture._ensure_virtual_mesh()

    from mxnet_tpu.kvstore.tpu_ici import (DEFAULT_QBLOCK,
                                           _blockwise_allreduce_fn)

    devices = tuple(jax.local_devices()[:8])
    numel = 16384
    allreduce, sharding, _mesh = _blockwise_allreduce_fn(
        devices, numel, "float32", "int8", DEFAULT_QBLOCK)
    spec = jax.ShapeDtypeStruct((len(devices), numel), jnp.float32,
                                sharding=sharding)
    tok_spec = jax.ShapeDtypeStruct((len(devices), 1), jnp.float32,
                                    sharding=sharding)
    compiled = allreduce.lower(spec, spec, tok_spec).compile()
    return {
        "entry": "quantized_allreduce",
        "optimized": compiled.as_text(),
        "cost_analysis": harvest_cost_analysis(compiled.cost_analysis()),
        "layers": ("quantize", "allreduce", "dequantize"),
        "contract": CONTRACTS["quantized_allreduce"],
        "meta": {"numel": numel, "mode": "int8",
                 "block": DEFAULT_QBLOCK, "devices": 8},
    }


_CENSUS_ENTRYPOINTS = {
    "fused_train_step_dp": _census_fused_train_step_dp,
    "quantized_allreduce": _census_quantized_allreduce,
    "resnet_profile": _census_resnet_profile,
}


def census_entrypoint_names():
    return sorted(_CENSUS_ENTRYPOINTS)


def _canon(name):
    return name.replace(".", "_").replace("-", "_")


def census_one(name, device=DEFAULT_DEVICE):
    """Capture + census one entry point (accepts ``fused_train_step_dp``
    or the capture-style ``fused_train_step.dp`` spelling)."""
    fn = _CENSUS_ENTRYPOINTS.get(_canon(name))
    if fn is None:
        raise KeyError(
            f"unknown census entry {name!r}; known: "
            f"{census_entrypoint_names()}")
    return build_census(fn(), device=device)


def dumps(doc):
    """Canonical JSON for the artifact (sorted keys, stable floats)."""
    return json.dumps(doc, indent=1, sort_keys=True)
