// Native CSV parser.
//
// Reference: `src/io/iter_csv.cc` (CSVIter — the registered C++ iterator
// parsing numeric CSV rows into dense batches; the reference never touches
// python for the hot parse).  TPU-native design mirrors libsvm.cc: the
// whole file parses once into a flat float32 row-major buffer that the
// python side copies out in one memcpy and feeds to NDArrayIter-style
// batching — no per-token python work.
//
// Dialect: comma / tab / space separated floats, one row per line; blank
// lines and '#' comments skipped; ragged rows are an error (the reference
// CHECKs row width against data_shape the same way).

#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <string>
#include <vector>

namespace {

thread_local std::string g_csv_error;

struct CSV {
  std::vector<float> values;  // row-major
  int64_t rows = 0;
  int64_t cols = -1;
};

}  // namespace

extern "C" {

const char *csv_last_error() { return g_csv_error.c_str(); }

void *csv_open(const char *path) {
  std::ifstream in(path);
  if (!in) {
    g_csv_error = std::string("open failed: ") + std::strerror(errno);
    return nullptr;
  }
  auto *p = new CSV();
  std::string line;
  size_t line_no = 0;
  while (std::getline(in, line)) {
    ++line_no;
    const char *s = line.c_str();
    while (*s == ' ' || *s == '\t') ++s;
    if (*s == '\0' || *s == '#') continue;
    int64_t row_cols = 0;
    while (*s != '\0') {
      char *end = nullptr;
      float v = std::strtof(s, &end);
      if (end == s) {
        g_csv_error = "bad value at line " + std::to_string(line_no);
        delete p;
        return nullptr;
      }
      p->values.push_back(v);
      ++row_cols;
      s = end;
      while (*s == ',' || *s == ' ' || *s == '\t' || *s == '\r') ++s;
    }
    if (p->cols < 0) {
      p->cols = row_cols;
    } else if (row_cols != p->cols) {
      g_csv_error = "ragged row at line " + std::to_string(line_no) +
                    ": got " + std::to_string(row_cols) + " values, "
                    "expected " + std::to_string(p->cols);
      delete p;
      return nullptr;
    }
    ++p->rows;
  }
  if (p->cols < 0) p->cols = 0;
  return p;
}

void csv_close(void *h) { delete static_cast<CSV *>(h); }

int64_t csv_rows(void *h) { return static_cast<CSV *>(h)->rows; }

int64_t csv_cols(void *h) { return static_cast<CSV *>(h)->cols; }

void csv_copy(void *h, float *dst) {
  auto *p = static_cast<CSV *>(h);
  std::memcpy(dst, p->values.data(), p->values.size() * sizeof(float));
}

}  // extern "C"
