// Native RecordIO reader/writer.
//
// Reference: dmlc-core recordio (consumed via `src/io/` in the reference
// framework; python mirror `python/mxnet/recordio.py`).  Format-compatible:
// records framed as [kMagic:u32][(cflag<<29|len):u32][payload][pad to 4B],
// kMagic = 0xced7230a.
//
// TPU-native design: the reader memory-maps the file, so reads are O(1)
// zero-copy pointer returns (the python layer wraps them in bytes as
// needed) and sequential throughput is bounded by page-cache bandwidth,
// not python struct parsing.  The sequential cursor is a byte offset, and
// the per-record offset index is built lazily on first indexed access —
// opening a 100GB .rec for .idx-driven training touches no payload pages.
// A truncated trailing record (producer killed mid-write) ends the stream
// instead of poisoning the whole file.  This is the native core under
// MXIndexedRecordIO and the ImageRecord dataset pipeline.

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint64_t kLenMask = (1u << 29) - 1;

thread_local std::string g_last_error;

void set_error(const std::string &msg) { g_last_error = msg; }

struct Reader {
  int fd = -1;
  const uint8_t *base = nullptr;
  uint64_t size = 0;
  uint64_t cursor = 0;            // byte offset of the next sequential record
  bool scanned = false;
  std::vector<uint64_t> offsets;  // lazy index: offset of each record header
};

struct Writer {
  FILE *fp = nullptr;
};

// Header at `off` if a complete record starts there: 0 on success, -1 on a
// clean end (EOF / truncated tail), -2 on corrupt magic.
int parse_header(const Reader *r, uint64_t off, uint64_t *len) {
  if (off > r->size || r->size - off < 8) return -1;
  uint32_t magic, lrec;
  std::memcpy(&magic, r->base + off, 4);
  std::memcpy(&lrec, r->base + off + 4, 4);
  if (magic != kMagic) {
    set_error("corrupt record magic at offset " + std::to_string(off));
    return -2;
  }
  *len = lrec & kLenMask;
  if (*len > r->size - off - 8) return -1;  // truncated tail: tolerate
  return 0;
}

uint64_t record_end(uint64_t off, uint64_t len) {
  return off + 8 + len + (4 - len % 4) % 4;
}

// Build the record-offset index (first indexed access only).  Stops at a
// truncated tail; a corrupt header mid-file also ends the index (preceding
// complete records stay readable, matching the tolerant-tail policy).
void ensure_scanned(Reader *r) {
  if (r->scanned) return;
  uint64_t pos = 0, len;
  while (parse_header(r, pos, &len) == 0) {
    r->offsets.push_back(pos);
    pos = record_end(pos, len);
  }
  r->scanned = true;
}

}  // namespace

extern "C" {

const char *rio_last_error() { return g_last_error.c_str(); }

void *rio_open_reader(const char *path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) {
    set_error(std::string("open failed: ") + std::strerror(errno));
    return nullptr;
  }
  struct stat st;
  if (fstat(fd, &st) != 0) {
    set_error(std::string("fstat failed: ") + std::strerror(errno));
    ::close(fd);
    return nullptr;
  }
  auto *r = new Reader();
  r->fd = fd;
  r->size = static_cast<uint64_t>(st.st_size);
  if (r->size > 0) {
    void *m = mmap(nullptr, r->size, PROT_READ, MAP_PRIVATE, fd, 0);
    if (m == MAP_FAILED) {
      set_error(std::string("mmap failed: ") + std::strerror(errno));
      ::close(fd);
      delete r;
      return nullptr;
    }
    r->base = static_cast<const uint8_t *>(m);
  }
  // cheap sanity check: the first record's magic (catches non-recordio
  // files without scanning the whole mmap)
  if (r->size >= 8) {
    uint32_t magic;
    std::memcpy(&magic, r->base, 4);
    if (magic != kMagic) {
      set_error("corrupt record magic at offset 0");
      munmap(const_cast<uint8_t *>(r->base), r->size);
      ::close(fd);
      delete r;
      return nullptr;
    }
  }
  return r;
}

void rio_close_reader(void *h) {
  auto *r = static_cast<Reader *>(h);
  if (!r) return;
  if (r->base) munmap(const_cast<uint8_t *>(r->base), r->size);
  if (r->fd >= 0) ::close(r->fd);
  delete r;
}

int64_t rio_num_records(void *h) {
  auto *r = static_cast<Reader *>(h);
  ensure_scanned(r);
  return r->offsets.size();
}

// Read record i; returns 0 on success, data points into the mmap (valid
// until rio_close_reader).
int rio_read_record(void *h, int64_t i, const uint8_t **data, uint64_t *len) {
  auto *r = static_cast<Reader *>(h);
  ensure_scanned(r);
  if (i < 0 || static_cast<uint64_t>(i) >= r->offsets.size()) {
    set_error("record index out of range");
    return -1;
  }
  uint64_t pos = r->offsets[i];
  uint32_t lrec;
  std::memcpy(&lrec, r->base + pos + 4, 4);
  *len = lrec & kLenMask;
  *data = r->base + pos + 8;
  return 0;
}

// Read record at byte offset `off` (for .idx-file compatibility).
// Bounds checks avoid uint64 overflow: a hostile .idx offset near 2^64
// must fail cleanly, not wrap past the check into an OOB mmap read.
int rio_read_at(void *h, uint64_t off, const uint8_t **data, uint64_t *len) {
  auto *r = static_cast<Reader *>(h);
  switch (parse_header(r, off, len)) {
    case -1:
      set_error("offset out of range or truncated record");
      return -1;
    case -2:
      return -1;
    default:
      *data = r->base + off + 8;
      return 0;
  }
}

// Position the sequential cursor at byte offset `off` (the values stored
// in .idx files; python fp.seek semantics — validity is checked on read).
int rio_seek(void *h, uint64_t off) {
  auto *r = static_cast<Reader *>(h);
  if (off > r->size) {
    set_error("seek offset past end of file");
    return -1;
  }
  r->cursor = off;
  return 0;
}

// Byte offset of the next sequential record — the reader-side tell() used
// when building .idx files.
uint64_t rio_reader_tell(void *h) {
  return static_cast<Reader *>(h)->cursor;
}

// Sequential read at the cursor; 0 on success, -1 at EOF (incl. a
// truncated trailing record), -2 on corrupt magic.
int rio_next_record(void *h, const uint8_t **data, uint64_t *len) {
  auto *r = static_cast<Reader *>(h);
  int rc = parse_header(r, r->cursor, len);
  if (rc != 0) return rc;
  *data = r->base + r->cursor + 8;
  r->cursor = record_end(r->cursor, *len);
  return 0;
}

void rio_reset(void *h) { static_cast<Reader *>(h)->cursor = 0; }

uint64_t rio_record_offset(void *h, int64_t i) {
  auto *r = static_cast<Reader *>(h);
  ensure_scanned(r);
  if (i < 0 || static_cast<uint64_t>(i) >= r->offsets.size()) return ~0ull;
  return r->offsets[i];
}

void *rio_open_writer(const char *path, int append) {
  FILE *fp = std::fopen(path, append ? "ab" : "wb");
  if (!fp) {
    set_error(std::string("fopen failed: ") + std::strerror(errno));
    return nullptr;
  }
  auto *w = new Writer();
  w->fp = fp;
  return w;
}

int64_t rio_writer_tell(void *h) {
  auto *w = static_cast<Writer *>(h);
  return ftell(w->fp);
}

int rio_write_record(void *h, const uint8_t *data, uint64_t len) {
  auto *w = static_cast<Writer *>(h);
  if (len & ~kLenMask) {
    set_error("record length " + std::to_string(len) +
              " exceeds the 29-bit frame limit");
    return -1;
  }
  uint32_t header[2] = {kMagic, static_cast<uint32_t>(len)};
  if (std::fwrite(header, 4, 2, w->fp) != 2) {
    set_error("short write (header)");
    return -1;
  }
  if (len && std::fwrite(data, 1, len, w->fp) != len) {
    set_error("short write (payload)");
    return -1;
  }
  uint64_t pad = (4 - len % 4) % 4;
  static const uint8_t zeros[4] = {0, 0, 0, 0};
  if (pad && std::fwrite(zeros, 1, pad, w->fp) != pad) {
    set_error("short write (pad)");
    return -1;
  }
  return 0;
}

void rio_close_writer(void *h) {
  auto *w = static_cast<Writer *>(h);
  if (!w) return;
  if (w->fp) std::fclose(w->fp);
  delete w;
}

}  // extern "C"
