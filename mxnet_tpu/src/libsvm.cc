// Native LibSVM parser.
//
// Reference: `src/io/iter_libsvm.cc` (LibSVMIter parsing "label idx:val ..."
// rows into CSR batches).  TPU-native design: the file is read once into
// flat CSR arrays (labels / indptr / indices / values) that the python side
// copies out in four bulk memcpys — no per-token python work, so a
// multi-GB CTR dataset parses at native speed and lands directly in the
// CSRNDArray container.

#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <string>
#include <vector>

namespace {

thread_local std::string g_last_error;

struct LibSVM {
  std::vector<float> labels;
  std::vector<int64_t> indptr;   // size rows+1
  std::vector<int32_t> indices;
  std::vector<float> values;
  int32_t max_index = -1;
};

}  // namespace

extern "C" {

const char *lsvm_last_error() { return g_last_error.c_str(); }

void *lsvm_open(const char *path) {
  std::ifstream in(path);
  if (!in) {
    g_last_error = std::string("open failed: ") + std::strerror(errno);
    return nullptr;
  }
  auto *p = new LibSVM();
  p->indptr.push_back(0);
  std::string line;
  size_t line_no = 0;
  while (std::getline(in, line)) {
    ++line_no;
    const char *s = line.c_str();
    char *end = nullptr;
    // skip blank / comment lines
    while (*s == ' ' || *s == '\t') ++s;
    if (*s == '\0' || *s == '#') continue;
    float label = std::strtof(s, &end);
    if (end == s) {
      g_last_error = "bad label at line " + std::to_string(line_no);
      delete p;
      return nullptr;
    }
    s = end;
    while (*s != '\0') {
      while (*s == ' ' || *s == '\t') ++s;
      if (*s == '\0' || *s == '#') break;
      long idx = std::strtol(s, &end, 10);
      if (end == s || *end != ':') {
        g_last_error = "bad feature at line " + std::to_string(line_no);
        delete p;
        return nullptr;
      }
      if (idx < 0 || idx > INT32_MAX) {
        g_last_error = "feature index out of range at line " +
                       std::to_string(line_no);
        delete p;
        return nullptr;
      }
      s = end + 1;
      float val = std::strtof(s, &end);
      if (end == s) {
        g_last_error = "bad value at line " + std::to_string(line_no);
        delete p;
        return nullptr;
      }
      s = end;
      p->indices.push_back(static_cast<int32_t>(idx));
      p->values.push_back(val);
      if (idx > p->max_index) p->max_index = static_cast<int32_t>(idx);
    }
    p->labels.push_back(label);
    p->indptr.push_back(static_cast<int64_t>(p->indices.size()));
  }
  return p;
}

void lsvm_close(void *h) { delete static_cast<LibSVM *>(h); }

int64_t lsvm_num_rows(void *h) {
  return static_cast<LibSVM *>(h)->labels.size();
}

int64_t lsvm_nnz(void *h) {
  return static_cast<LibSVM *>(h)->values.size();
}

int32_t lsvm_max_index(void *h) {
  return static_cast<LibSVM *>(h)->max_index;
}

// Bulk copy-out into caller-allocated buffers.
void lsvm_copy(void *h, float *labels, int64_t *indptr, int32_t *indices,
               float *values) {
  auto *p = static_cast<LibSVM *>(h);
  std::memcpy(labels, p->labels.data(), p->labels.size() * sizeof(float));
  std::memcpy(indptr, p->indptr.data(), p->indptr.size() * sizeof(int64_t));
  std::memcpy(indices, p->indices.data(),
              p->indices.size() * sizeof(int32_t));
  std::memcpy(values, p->values.data(), p->values.size() * sizeof(float));
}

}  // extern "C"
