// Native threaded image pipeline: RecordIO -> JPEG decode -> augment ->
// batched NHWC uint8.
//
// Reference: `src/io/iter_image_recordio_2.cc` (ImageRecordIOParser2),
// `src/io/image_aug_default.cc` (DefaultImageAugmenter) and
// `src/io/image_recordio.h` — the reference feeds its GPUs from C++
// decode threads because a Python/PIL loop cannot keep up with the chip.
// Same logic here: worker threads decode with libjpeg(-turbo) entirely
// outside the GIL into a ring of pre-allocated batch slots; Python pops
// completed batches in order and ships them to the TPU.  DCT-domain
// scaled decode (scale_denom in {1,2,4,8}) trims decode cost when the
// stored image is much larger than the crop, exactly like the reference's
// cv::IMREAD_REDUCED paths.
//
// Record payload layout is the im2rec IRHeader
// (`python/mxnet/recordio.py`): [flag:u32][label:f32][id:u64][id2:u64]
// (+flag extra f32 labels) followed by the encoded image.
//
// Built into libmxtpu_img.so (separate from libmxtpu.so so a missing
// libjpeg only disables this path; python PIL fallback remains).

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <memory>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <jpeglib.h>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint64_t kLenMask = (1u << 29) - 1;
constexpr int kIRHeaderBytes = 24;  // <IfQQ

thread_local std::string g_err;

struct JpegErr {
  jpeg_error_mgr pub;
  jmp_buf jb;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr *e = reinterpret_cast<JpegErr *>(cinfo->err);
  longjmp(e->jb, 1);
}

// -- bilinear resize, uint8 HWC ---------------------------------------------
void resize_bilinear(const uint8_t *src, int sh, int sw, uint8_t *dst,
                     int dh, int dw, int c) {
  const float ry = dh > 1 ? float(sh - 1) / (dh - 1) : 0.f;
  const float rx = dw > 1 ? float(sw - 1) / (dw - 1) : 0.f;
  for (int y = 0; y < dh; ++y) {
    float fy = y * ry;
    int y0 = int(fy);
    int y1 = y0 + 1 < sh ? y0 + 1 : y0;
    float wy = fy - y0;
    const uint8_t *r0 = src + size_t(y0) * sw * c;
    const uint8_t *r1 = src + size_t(y1) * sw * c;
    uint8_t *out = dst + size_t(y) * dw * c;
    for (int x = 0; x < dw; ++x) {
      float fx = x * rx;
      int x0 = int(fx);
      int x1 = x0 + 1 < sw ? x0 + 1 : x0;
      float wx = fx - x0;
      for (int k = 0; k < c; ++k) {
        float top = r0[x0 * c + k] * (1 - wx) + r0[x1 * c + k] * wx;
        float bot = r1[x0 * c + k] * (1 - wx) + r1[x1 * c + k] * wx;
        out[x * c + k] = uint8_t(top * (1 - wy) + bot * wy + 0.5f);
      }
    }
  }
}

struct Slot {
  std::vector<uint8_t> data;    // batch * H * W * C
  std::vector<float> labels;    // batch
  uint64_t batch_no = 0;        // which batch may currently be written
  std::atomic<int> completed{0};
  std::mutex m;
  std::condition_variable cv_writable;
  std::condition_variable cv_ready;
};

struct Pipeline {
  // record file
  int fd = -1;
  const uint8_t *base = nullptr;
  uint64_t fsize = 0;
  std::vector<std::pair<uint64_t, uint32_t>> recs;  // payload off, len

  // config
  int batch = 0, H = 0, W = 0, C = 3;
  int resize_short = 0;       // 0 = off
  bool rand_crop = false, rand_mirror = false, shuffle = false;
  uint64_t seed = 0;
  int depth = 3;
  // per-host sharding: this reader owns the strided slice
  // perm[part_index::num_parts] of each epoch's GLOBAL permutation, so
  // every part's order is a pure function of (seed, epoch, part) and the
  // union over parts is an exact partition of the record file
  int num_parts = 1, part_index = 0;
  uint64_t part_n = 0;        // records owned by this part

  // epoch order cache (shared_ptr snapshots: a worker holds its epoch's
  // permutation by refcount, so regeneration for a later epoch can never
  // race a reader still finishing an old one)
  std::mutex order_m;
  uint64_t order_epoch[2] = {~0ull, ~0ull};
  std::shared_ptr<const std::vector<uint32_t>> order[2];

  std::vector<std::unique_ptr<Slot>> slots;
  std::vector<std::thread> workers;
  std::atomic<uint64_t> next_index{0};
  std::atomic<bool> stop{false};
  std::atomic<uint64_t> decode_errors{0};

  uint64_t consumer_batch = 0;

  ~Pipeline() {
    stop.store(true);
    for (auto &s : slots) {
      std::lock_guard<std::mutex> lk(s->m);
      s->cv_writable.notify_all();
    }
    for (auto &t : workers) t.join();
    if (base) munmap(const_cast<uint8_t *>(base), fsize);
    if (fd >= 0) close(fd);
  }

  std::shared_ptr<const std::vector<uint32_t>> epoch_order(uint64_t epoch) {
    std::lock_guard<std::mutex> lk(order_m);
    int slot = epoch & 1;
    if (order_epoch[slot] != epoch) {
      auto o = std::make_shared<std::vector<uint32_t>>(recs.size());
      for (uint32_t i = 0; i < o->size(); ++i) (*o)[i] = i;
      if (shuffle) {
        std::mt19937_64 rng(seed ^ (epoch * 0x9e3779b97f4a7c15ull));
        for (size_t i = o->size() - 1; i > 0; --i) {
          std::swap((*o)[i], (*o)[rng() % (i + 1)]);
        }
      }
      order[slot] = std::move(o);
      order_epoch[slot] = epoch;
    }
    return order[slot];
  }

  bool decode_one(const uint8_t *payload, uint32_t len, uint8_t *out,
                  float *label, std::mt19937_64 &rng) {
    if (len < kIRHeaderBytes) return false;
    uint32_t flag;
    std::memcpy(&flag, payload, 4);
    std::memcpy(label, payload + 4, 4);
    uint64_t skip = kIRHeaderBytes + uint64_t(flag) * 4;
    if (len <= skip) return false;
    const uint8_t *jpg = payload + skip;
    uint64_t jlen = len - skip;

    // declared BEFORE setjmp: after a longjmp the function resumes at the
    // setjmp site and returns normally, so these destructors still run
    // (declaring them later would leak the decode buffers on corrupt
    // scan data)
    std::vector<uint8_t> buf;
    std::vector<uint8_t> rbuf;

    jpeg_decompress_struct cinfo;
    JpegErr jerr;
    cinfo.err = jpeg_std_error(&jerr.pub);
    jerr.pub.error_exit = jpeg_err_exit;
    if (setjmp(jerr.jb)) {
      jpeg_destroy_decompress(&cinfo);
      return false;
    }
    jpeg_create_decompress(&cinfo);
    jpeg_mem_src(&cinfo, const_cast<uint8_t *>(jpg), jlen);
    if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
      jpeg_destroy_decompress(&cinfo);
      return false;
    }
    cinfo.out_color_space = JCS_RGB;
    // DCT-domain downscale: largest denom keeping both dims >= what the
    // later resize/crop needs (reference IMREAD_REDUCED_COLOR_*)
    int need_h = resize_short > 0 ? resize_short : H;
    int need_w = resize_short > 0 ? resize_short : W;
    int denom = 1;
    for (int d = 2; d <= 8; d *= 2) {
      if (int(cinfo.image_height) / d >= need_h &&
          int(cinfo.image_width) / d >= need_w) {
        denom = d;
      }
    }
    cinfo.scale_num = 1;
    cinfo.scale_denom = denom;
    cinfo.dct_method = JDCT_ISLOW;
    // IFAST saves ~10% decode time but visibly degrades high-frequency
    // content; ISLOW + SIMD (libjpeg-turbo) is the reference default too
    
    jpeg_start_decompress(&cinfo);
    int dw = cinfo.output_width, dh = cinfo.output_height;
    int dc = cinfo.output_components;  // 3 (RGB forced)
    buf.resize(size_t(dw) * dh * dc);
    while (cinfo.output_scanline < cinfo.output_height) {
      uint8_t *row = buf.data() + size_t(cinfo.output_scanline) * dw * dc;
      jpeg_read_scanlines(&cinfo, &row, 1);
    }
    jpeg_finish_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);

    // optional shorter-side resize
    const uint8_t *img = buf.data();
    int ih = dh, iw = dw;
    if (resize_short > 0 && std::min(dh, dw) != resize_short) {
      if (dh < dw) {
        ih = resize_short;
        iw = int(int64_t(dw) * resize_short / dh);
      } else {
        iw = resize_short;
        ih = int(int64_t(dh) * resize_short / dw);
      }
      rbuf.resize(size_t(ih) * iw * dc);
      resize_bilinear(buf.data(), dh, dw, rbuf.data(), ih, iw, dc);
      img = rbuf.data();
    }
    if (ih < H || iw < W) {  // undersized source: upscale to crop size
      rbuf.resize(size_t(H) * W * dc);
      std::vector<uint8_t> tmp(rbuf);
      resize_bilinear(img, ih, iw, tmp.data(), H, W, dc);
      rbuf.swap(tmp);
      img = rbuf.data();
      ih = H;
      iw = W;
    }

    // crop (random in train, center otherwise) + optional mirror
    int y0 = (ih - H) / 2, x0 = (iw - W) / 2;
    if (rand_crop) {
      y0 = ih == H ? 0 : int(rng() % uint64_t(ih - H + 1));
      x0 = iw == W ? 0 : int(rng() % uint64_t(iw - W + 1));
    }
    bool mirror = rand_mirror && (rng() & 1);
    for (int y = 0; y < H; ++y) {
      const uint8_t *src = img + (size_t(y0 + y) * iw + x0) * dc;
      uint8_t *dst = out + size_t(y) * W * C;
      if (!mirror) {
        std::memcpy(dst, src, size_t(W) * C);
      } else {
        for (int x = 0; x < W; ++x) {
          std::memcpy(dst + size_t(x) * C, src + size_t(W - 1 - x) * C, C);
        }
      }
    }
    return true;
  }

  void worker(int wid) {
    std::mt19937_64 rng(seed ^ (0xabcdef12345678ull + wid));
    while (!stop.load(std::memory_order_relaxed)) {
      uint64_t i = next_index.fetch_add(1);
      uint64_t batch_no = i / batch;
      Slot &s = *slots[batch_no % depth];
      {
        std::unique_lock<std::mutex> lk(s.m);
        s.cv_writable.wait(lk, [&] {
          return stop.load(std::memory_order_relaxed) ||
                 s.batch_no == batch_no;
        });
      }
      if (stop.load(std::memory_order_relaxed)) break;
      // i counts PART-LOCAL samples; map to the part's strided view of
      // the epoch's global permutation
      uint64_t epoch = i / part_n;
      uint64_t j = uint64_t(part_index) + (i % part_n) * uint64_t(num_parts);
      uint32_t rec = (*epoch_order(epoch))[j];
      uint8_t *out = s.data.data() + size_t(i % batch) * H * W * C;
      float label = -1.f;
      bool ok = decode_one(base + recs[rec].first, recs[rec].second, out,
                           &label, rng);
      if (!ok) {
        std::memset(out, 0, size_t(H) * W * C);
        decode_errors.fetch_add(1);
      }
      s.labels[i % batch] = label;
      if (s.completed.fetch_add(1) + 1 == batch) {
        std::lock_guard<std::mutex> lk(s.m);
        s.cv_ready.notify_all();
      }
    }
  }

  int ready_batches() const {
    // gauge only (racy reads are fine): completed slots the consumer has
    // not yet popped — 0 while compute waits means the decode pool, not
    // the chip, bounds the run
    int n = 0;
    for (const auto &s : slots) {
      if (s->completed.load(std::memory_order_relaxed) == batch) ++n;
    }
    return n;
  }

  int next(uint8_t *out_data, float *out_labels) {
    Slot &s = *slots[consumer_batch % depth];
    {
      std::unique_lock<std::mutex> lk(s.m);
      s.cv_ready.wait(lk, [&] {
        return s.batch_no == consumer_batch &&
               s.completed.load() == batch;
      });
    }
    std::memcpy(out_data, s.data.data(), s.data.size());
    std::memcpy(out_labels, s.labels.data(), s.labels.size() * 4);
    {
      std::lock_guard<std::mutex> lk(s.m);
      s.completed.store(0);
      s.batch_no += depth;
      s.cv_writable.notify_all();
    }
    ++consumer_batch;
    return batch;
  }
};

bool scan_records(Pipeline *p) {
  uint64_t off = 0;
  while (off + 8 <= p->fsize) {
    uint32_t magic, lrec;
    std::memcpy(&magic, p->base + off, 4);
    std::memcpy(&lrec, p->base + off + 4, 4);
    if (magic != kMagic) break;
    uint64_t len = lrec & kLenMask;
    if (off + 8 + len > p->fsize) break;  // truncated tail
    uint32_t cflag = lrec >> 29;
    if (cflag == 0) {  // plain (non-split) record
      p->recs.emplace_back(off + 8, uint32_t(len));
    }
    off += 8 + ((len + 3) & ~3ull);
  }
  return !p->recs.empty();
}

}  // namespace

extern "C" {

const char *imgpipe_last_error() { return g_err.c_str(); }

void *imgpipe_create(const char *path, int batch, int h, int w,
                     int resize_short, int nthreads, int depth,
                     int rand_crop, int rand_mirror, int shuffle,
                     uint64_t seed, int num_parts, int part_index) {
  auto p = std::make_unique<Pipeline>();
  p->fd = open(path, O_RDONLY);
  if (p->fd < 0) {
    g_err = std::string("open failed: ") + path;
    return nullptr;
  }
  struct stat st;
  if (fstat(p->fd, &st) != 0 || st.st_size == 0) {
    g_err = "empty or unreadable record file";
    return nullptr;
  }
  p->fsize = uint64_t(st.st_size);
  void *m = mmap(nullptr, p->fsize, PROT_READ, MAP_PRIVATE, p->fd, 0);
  if (m == MAP_FAILED) {
    g_err = "mmap failed";
    return nullptr;
  }
  p->base = static_cast<const uint8_t *>(m);
  madvise(m, p->fsize, MADV_WILLNEED);
  if (!scan_records(p.get())) {
    g_err = "no records found (bad magic?)";
    return nullptr;
  }
  p->batch = batch;
  p->H = h;
  p->W = w;
  p->resize_short = resize_short;
  p->rand_crop = rand_crop != 0;
  p->rand_mirror = rand_mirror != 0;
  p->shuffle = shuffle != 0;
  p->seed = seed;
  if (num_parts < 1 || part_index < 0 || part_index >= num_parts) {
    g_err = "invalid shard: need 0 <= part_index < num_parts";
    return nullptr;
  }
  p->num_parts = num_parts;
  p->part_index = part_index;
  {
    uint64_t n = p->recs.size();
    uint64_t pi = uint64_t(part_index), np = uint64_t(num_parts);
    p->part_n = n > pi ? (n - pi + np - 1) / np : 0;
  }
  if (p->part_n == 0) {
    g_err = "shard owns no records (num_parts exceeds record count?)";
    return nullptr;
  }
  p->depth = depth < 2 ? 2 : depth;
  if (nthreads < 1) nthreads = 1;
  for (int i = 0; i < p->depth; ++i) {
    auto s = std::make_unique<Slot>();
    s->data.resize(size_t(batch) * h * w * p->C);
    s->labels.resize(batch);
    s->batch_no = i;
    p->slots.push_back(std::move(s));
  }
  for (int i = 0; i < nthreads; ++i) {
    p->workers.emplace_back(&Pipeline::worker, p.get(), i);
  }
  return p.release();
}

int64_t imgpipe_num_records(void *h) {
  return int64_t(static_cast<Pipeline *>(h)->recs.size());
}

int64_t imgpipe_part_records(void *h) {
  return int64_t(static_cast<Pipeline *>(h)->part_n);
}

// Completed batches waiting in the ring (occupancy gauge for telemetry).
int imgpipe_ready_batches(void *h) {
  return static_cast<Pipeline *>(h)->ready_batches();
}

int64_t imgpipe_decode_errors(void *h) {
  return int64_t(static_cast<Pipeline *>(h)->decode_errors.load());
}

// Blocks until the next batch is complete; fills caller buffers
// (batch*H*W*3 uint8, batch float32).  Returns batch size.
int imgpipe_next(void *h, uint8_t *out_data, float *out_labels) {
  return static_cast<Pipeline *>(h)->next(out_data, out_labels);
}

void imgpipe_destroy(void *h) { delete static_cast<Pipeline *>(h); }

}  // extern "C"
