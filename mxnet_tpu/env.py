"""Environment-variable configuration surface.

Reference: the 102 documented ``MXNET_*`` variables
(`docs/static_site/src/pages/api/faq/env_var.md`).  On the TPU rebuild a
large fraction is owned by XLA/PjRt (memory pools, engine threads, cudnn
autotune); the table below documents every variable this framework
actually honors, what it does here, and which reference knobs it
subsumes.  ``mxnet_tpu.env.describe()`` prints the live table.

Handled at import (see ``apply()`` call in ``mxnet_tpu/__init__``):

=========================== =================================================
variable                     behavior
=========================== =================================================
MXNET_SEED                   seeds the global RNG streams at import
MXNET_ENGINE_TYPE            ``NaiveEngine`` = synchronous dispatch: every
                             op blocks until its result is ready, so async
                             errors surface at the faulting op (the
                             reference's debug engine); default
                             ``ThreadedEngine`` = PjRt async streams
MXNET_EXEC_BULK_EXEC_TRAIN   advisory bulking budget -> engine.set_bulk_size
MXNET_CPU_WORKER_NTHREADS    default worker count for the native image
                             pipeline and thread DataLoaders
MXNET_PROFILER_AUTOSTART     start the profiler at import (chrome trace)
MXNET_ENFORCE_DETERMINISM    forbid nondeterministic op paths: sets XLA's
                             deterministic-ops flag before backend init
MXNET_HOME                   cache root (model_store, datasets)
MXNET_HEARTBEAT_INTERVAL     kvstore liveness stamp period (seconds)
MXNET_KVSTORE_BUCKETING      ``0`` disables bucketed gradient allreduce —
                             Trainer/kvstore fall back to one collective
                             per parameter (default: bucketing on)
MXNET_KVSTORE_BUCKET_BYTES   gradient-bucket payload cap in bytes for the
                             fused allreduce (default 4194304 = 4 MB;
                             read when a store's bucketer is created)
MXNET_GPU_MEM_POOL_RESERVE   accepted, no-op (PjRt owns device memory);
                             use XLA_PYTHON_CLIENT_MEM_FRACTION
MXNET_STORAGE_FALLBACK_LOG_VERBOSE  accepted, no-op (no storage fallback:
                             sparse compute is explicit here)
=========================== =================================================

Read by their owning subsystem (import-time reads are baked in for the
process — set them before ``import mxnet_tpu``; the runtime reads say
so explicitly).  mxlint's ``env-var-undocumented`` rule and
``tests/test_env_vars.py`` both enforce that every ``MXNET_*`` access
in the codebase appears in this module:

=========================== =================================================
variable                     behavior
=========================== =================================================
MXNET_ENGINE_DEBUG           read once at import (`ops/invoke.py`):
                             stale-read diagnostics — warn at backward
                             when a recorded input was mutated in place
                             (reference §5.2 versioned-var visibility)
MXNET_DROPOUT_RNG            read once at import (`ops/nn.py`):
                             ``rbg`` (default, XLA hardware RNG) or
                             ``threefry`` dropout mask bitstream; see
                             docs/DESIGN.md "Dropout RNG streams"
MXNET_TELEMETRY_STEADY_STEPS retrace-watchdog steady-state call count:
                             a jit cache miss after this many calls of a
                             watched function logs a WARNING (default 2;
                             read when a watchdog is constructed)
MXNET_PROFILE_RANK           set by ``tools/launch.py --profile-rank``:
                             the matching rank (or every rank, ``-1``)
                             starts the profiler at import and dumps a
                             chrome trace at exit
MXNET_PROFILE_DIR            output directory for the launcher-requested
                             profile dumps (default ``.``)
MXNET_KVSTORE_SPARSE_HOST_BOUND  row-sparse pushpull crossover: below
                             this many touched rows the host union beats
                             the device sort (default 256; re-read per
                             pushpull so it can be tuned mid-run)
MXNET_TPU_MODEL_REPO         colon-separated directories searched for
                             pretrained weight files (no network egress;
                             read at each ``get_model_file`` call)
MXNET_FAULTLINE              chaos fault plan for ``resilience.faultline``:
                             inline JSON (list of ``{site, kind, at,
                             times}`` specs) or ``@/path/to/plan.json``;
                             read once at the first instrumented-site
                             arrival, so set it before training starts.
                             Leave unset outside chaos runs
MXNET_CHECKPOINT_KEEP        checkpoints retained by
                             ``resilience.CheckpointManager.prune()``
                             (default 3; read when a manager is created)
MXNET_KVSTORE_RETRIES        transient-fault retry budget for KV reads,
                             per-key pushpull, bucketed collectives, and
                             the serve model call (default 3 retries =
                             4 attempts; re-read per retry loop so it can
                             be tuned mid-run)
MXNET_KVSTORE_QBLOCK         scale-block size (elements) for the
                             block-scaled int8/fp8 quantized allreduce
                             (default 256; read when
                             ``set_gradient_compression`` is called, and
                             ``compression_params['block']`` overrides it
                             per store); see docs/DESIGN.md
                             "Block-scaled quantized allreduce"
MXNET_DECODE_THREADS         decode-pool width for the native image
                             pipeline (``ImageRecordIter``); default
                             falls back to MXNET_CPU_WORKER_NTHREADS
                             (read when an iterator is constructed)
MXNET_PREFETCH_DEPTH         ``DevicePrefetcher`` ring depth — batches
                             resident on device ahead of compute
                             (default 2; read when a prefetcher is
                             constructed, including the DataLoader
                             ``prefetch_to_device`` path)
MXNET_IO_ERROR_TOLERANCE     decode-error fraction per window of records
                             above which ``ImageRecordIter`` logs a
                             WARNING and keeps ticking
                             ``mxtpu_io_decode_errors_total`` (default
                             0.01; read at iterator construction)
MXNET_SERVE_REPLICAS         default replica count for ``serve.Fleet``
                             (default 2; read when a fleet is created
                             without an explicit ``replicas=``)
MXNET_SERVE_DEADLINE_MS      base request deadline for the fleet's SLA
                             classes: interactive = 1x, standard = 4x,
                             batch = 20x (default 1000 ms; read when a
                             router's class table is built)
MXNET_SERVE_EJECT_AFTER      consecutive replica failures before the
                             fleet ejects it from routing (default 2 —
                             the tpu_ici two-observation suspicion rule;
                             read when a fleet is created)
MXNET_ELASTIC                ``1`` lets ``resilience.ElasticSupervisor``
                             re-shard onto the survivor mesh after a
                             permanent host loss instead of re-raising
                             ``DeadNodeError`` (default 0: abort to
                             checkpoint, the pre-elastic behavior; read
                             when a supervisor is created without an
                             explicit ``elastic=``)
MXNET_ELASTIC_MIN_WORLD      smallest world the supervisor will shrink
                             to; a fault leaving fewer survivors aborts
                             to checkpoint instead of resharding
                             (default 1; read at supervisor creation)
MXNET_ELASTIC_SCALING        batch/lr scaling rule across a world-size
                             change: ``linear`` (default — per-host
                             batch constant, so global batch AND lr
                             scale by world/base_world; loss scale
                             untouched) or ``none`` (keep the lr; the
                             global batch still shrinks with the world
                             and the supervisor logs that the effective
                             step size changed).  Read at supervisor
                             creation; the applied rule is always
                             logged, never silent
MXNET_SENTINEL_SLOW_FACTOR   straggler-demotion threshold for
                             ``resilience.sentinel.StragglerPolicy``: a
                             rank whose step-time EMA exceeds factor x
                             the pod median for M consecutive
                             observations is declared DEGRADED and
                             resharded away exactly like a dead node
                             (default 3.0; read when a policy is
                             created)
MXNET_SENTINEL_LOSS_FACTOR   divergence-rollback threshold for
                             ``resilience.sentinel.DivergenceSentinel``:
                             a synced loss above factor x the warmed-up
                             EMA (or non-finite) trips an automatic
                             rollback to the newest complete checkpoint
                             (default 10.0; read when a sentinel is
                             created)
MXNET_SENTINEL_ROLLBACKS     divergence rollbacks the supervisor takes
                             before surfacing ``DivergenceError``
                             (default 2; read at supervisor creation)
MXNET_PARALLEL_RECIPE        default sharding recipe string
                             (``"dp2.tp2"`` etc., grammar in
                             docs/SHARDING.md) used by
                             ``FusedTrainStep``/dryrun when the caller
                             passes neither ``mesh`` nor ``recipe``
                             (default unset: plain dp over all devices;
                             read when a fused step is constructed)
MXNET_RECIPE_STRICT          overrides the recipe's auto strict-coverage
                             policy: ``1`` forces the placement audit to
                             raise on any non-scalar param no partition
                             rule matched, ``0`` always allows the
                             replicated fallback (default unset = auto:
                             strict whenever the recipe has a non-dp
                             axis of size > 1; read when a recipe's
                             strictness is resolved)
MXNET_KVSTORE_INTEGRITY      ``1`` turns on the allreduce integrity
                             sideband: a per-device digest of each
                             bucket's psum result is agreement-checked
                             in-program (pmax-vs-pmin, same launch);
                             disagreement ticks
                             ``mxtpu_integrity_violations_total`` and
                             the step-guard skips the update so a
                             flipped bit never reaches the optimizer
                             (default 0; read when a store's bucketer
                             is created)
MXNET_BLACKBOX               ``0`` disables the ``observe`` flight
                             recorder entirely — no events recorded, no
                             postmortem dumps (default on; read when
                             the recorder is created or ``reset()``)
MXNET_BLACKBOX_EVENTS        flight-recorder ring capacity in events;
                             older events are overwritten and counted
                             in the dump's ``dropped`` field (default
                             4096; read at recorder creation/reset)
MXNET_BLACKBOX_DIR           fixed directory for postmortem dumps;
                             default unset: dumps land next to the
                             checkpoint step dirs (``<root>/blackbox``)
                             or ``./blackbox`` with no checkpoint root
                             (read at each dump)
MXNET_AUTOTUNE               ``0`` disables the autotune winner cache:
                             every tuned kernel (flash attention, the
                             scan-LSTM cell, the s2d stem, the
                             BN-backward epilogue) silently uses its
                             documented static default and ``tune.best``
                             stops warning about misses (default on;
                             read once at the first cache consult and
                             memoized for the process —
                             ``tune.invalidate()`` re-reads)
MXNET_AUTOTUNE_CACHE         path of the autotune winner cache to read
                             instead of the committed
                             ``tools/autotune_cache.json`` (e.g. a
                             freshly swept cache under review; read
                             once at the first cache consult, see
                             docs/AUTOTUNE.md)
MXNET_LOCKSCAN_WITNESS       ``1`` installs the lock-acquisition
                             witness (``mxnet_tpu.lockwitness``) as the
                             very first package import: every
                             package-created Lock/RLock/Condition is
                             wrapped, held->acquired order edges are
                             recorded per thread, an acquisition that
                             closes a cycle raises
                             ``LockOrderViolation``, and a process with
                             recorded violations exits 70.  On in ci.sh
                             chaos/storm/endure; read at import only —
                             set before ``import mxnet_tpu``
                             (docs/STATIC_ANALYSIS.md "Concurrency
                             contracts")
MXNET_LOCKSCAN_REPORT        path where the witness dumps its observed
                             order graph (JSON) at process exit, for
                             ``python -m tools.lockscan --crosscheck``
                             against the static model (read at exit;
                             only meaningful with the witness on)
=========================== =================================================
"""
from __future__ import annotations

import os

__all__ = ["apply", "describe", "is_naive_engine", "cpu_worker_nthreads",
           "decode_threads", "prefetch_depth", "io_error_tolerance",
           "serve_replicas", "serve_deadline_ms", "serve_eject_after",
           "elastic_enabled", "elastic_min_world", "elastic_scaling",
           "sentinel_slow_factor", "sentinel_loss_factor",
           "sentinel_rollbacks", "kvstore_integrity",
           "parallel_recipe", "recipe_strict", "blackbox_enabled",
           "blackbox_events", "blackbox_dir", "autotune_enabled",
           "autotune_cache_path", "lockscan_witness",
           "lockscan_report_path"]

_naive_engine = False


def is_naive_engine():
    return _naive_engine


def cpu_worker_nthreads(default=None):
    v = os.environ.get("MXNET_CPU_WORKER_NTHREADS")
    if v is None:
        return default if default is not None else (os.cpu_count() or 1)
    return max(1, int(v))


def decode_threads(default=None):
    """Decode-pool width for the native image pipeline; falls back to
    the general worker knob when MXNET_DECODE_THREADS is unset."""
    v = os.environ.get("MXNET_DECODE_THREADS")
    if v is None:
        return cpu_worker_nthreads(default)
    return max(1, int(v))


def prefetch_depth(default=2):
    v = os.environ.get("MXNET_PREFETCH_DEPTH")
    if v is None:
        return default
    return max(1, int(v))


def io_error_tolerance(default=0.01):
    v = os.environ.get("MXNET_IO_ERROR_TOLERANCE")
    if v is None:
        return default
    return max(0.0, float(v))


def serve_replicas(default=2):
    v = os.environ.get("MXNET_SERVE_REPLICAS")
    if v is None:
        return default
    return max(1, int(v))


def serve_deadline_ms(default=1000.0):
    """Base deadline for the fleet SLA classes (interactive = 1x)."""
    v = os.environ.get("MXNET_SERVE_DEADLINE_MS")
    if v is None:
        return default
    return max(1.0, float(v))


def serve_eject_after(default=2):
    """Consecutive failures before a fleet replica is ejected."""
    v = os.environ.get("MXNET_SERVE_EJECT_AFTER")
    if v is None:
        return default
    return max(1, int(v))


def elastic_enabled(default=False):
    """Whether the elastic supervisor may re-shard onto survivors after
    a permanent host loss (default: abort to checkpoint instead)."""
    v = os.environ.get("MXNET_ELASTIC")
    if v is None:
        return default
    return v not in ("0", "")


def elastic_min_world(default=1):
    """Smallest world the supervisor will shrink to; fewer survivors
    abort to checkpoint."""
    v = os.environ.get("MXNET_ELASTIC_MIN_WORLD")
    if v is None:
        return default
    return max(1, int(v))


def elastic_scaling(default="linear"):
    """Batch/lr scaling rule across a world-size change: ``linear`` or
    ``none`` (see the docstring table; the choice is always logged)."""
    v = os.environ.get("MXNET_ELASTIC_SCALING")
    if v is None:
        return default
    if v not in ("linear", "none"):
        raise ValueError(
            f"MXNET_ELASTIC_SCALING={v!r}: expected 'linear' or 'none'")
    return v


def sentinel_slow_factor(default=3.0):
    """Straggler-demotion threshold: step-time EMA over pod-median
    ratio above which a rank is suspected (see StragglerPolicy)."""
    v = os.environ.get("MXNET_SENTINEL_SLOW_FACTOR")
    if v is None:
        return default
    return max(1.0, float(v))


def sentinel_loss_factor(default=10.0):
    """Divergence threshold: loss over warmed-up EMA ratio above which
    the DivergenceSentinel trips an auto-rollback."""
    v = os.environ.get("MXNET_SENTINEL_LOSS_FACTOR")
    if v is None:
        return default
    return max(1.0, float(v))


def sentinel_rollbacks(default=2):
    """Divergence rollbacks the supervisor takes before surfacing
    ``DivergenceError``."""
    v = os.environ.get("MXNET_SENTINEL_ROLLBACKS")
    if v is None:
        return default
    return max(0, int(v))


def kvstore_integrity(default=False):
    """Whether the bucketed allreduce runs the in-program integrity
    sideband (digest agreement check inside the same launch)."""
    v = os.environ.get("MXNET_KVSTORE_INTEGRITY")
    if v is None:
        return default
    return v not in ("0", "")


def parallel_recipe(default=None):
    """Default sharding recipe string for FusedTrainStep/dryrun when the
    caller passes neither mesh nor recipe (None = plain dp)."""
    v = os.environ.get("MXNET_PARALLEL_RECIPE")
    if v is None or not v.strip():
        return default
    return v.strip()


def recipe_strict(default=None):
    """Tri-state strict-coverage override for sharding recipes: None
    (unset — the recipe's auto policy applies), True (``1``: the audit
    raises on uncovered non-scalar params), or False (``0``: always
    allow the replicated fallback)."""
    v = os.environ.get("MXNET_RECIPE_STRICT")
    if v is None or v == "":
        return default
    return v != "0"


def blackbox_enabled(default=True):
    """Whether the ``observe`` flight recorder records at all."""
    v = os.environ.get("MXNET_BLACKBOX")
    if v is None:
        return default
    return v not in ("0", "")


def blackbox_events(default=4096):
    """Flight-recorder ring capacity (events); older events are
    overwritten."""
    v = os.environ.get("MXNET_BLACKBOX_EVENTS")
    if v is None:
        return default
    return max(16, int(v))


def blackbox_dir(default=None):
    """Fixed postmortem-dump directory; None = next to the checkpoint
    dir (``<root>/blackbox``) or ``./blackbox``."""
    v = os.environ.get("MXNET_BLACKBOX_DIR")
    if v is None or not v.strip():
        return default
    return v.strip()


def autotune_enabled(default=True):
    """Whether tuned dispatch consults the autotune winner cache at all
    (``0`` = static defaults everywhere, no miss warnings)."""
    v = os.environ.get("MXNET_AUTOTUNE")
    if v is None:
        return default
    return v not in ("0", "")


def autotune_cache_path(default=None):
    """Cache-file override; None = the committed
    ``tools/autotune_cache.json``."""
    v = os.environ.get("MXNET_AUTOTUNE_CACHE")
    if v is None or not v.strip():
        return default
    return v.strip()


def lockscan_witness(default=False):
    """Whether the lock-acquisition witness is requested.  NOTE: the
    install itself happens at the top of ``mxnet_tpu/__init__`` from a
    direct environ read (the witness must patch the lock factories
    before any package import creates one) — this helper only reports
    the setting."""
    v = os.environ.get("MXNET_LOCKSCAN_WITNESS")
    if v is None:
        return default
    return v not in ("0", "")


def lockscan_report_path(default=None):
    """Where the witness dumps its observed order graph at exit; None =
    no dump.  (Read at exit by ``mxnet_tpu.lockwitness``.)"""
    v = os.environ.get("MXNET_LOCKSCAN_REPORT")
    if v is None or not v.strip():
        return default
    return v.strip()


def apply():
    """Read the environment once at package import."""
    global _naive_engine

    if os.environ.get("MXNET_ENFORCE_DETERMINISM", "0") not in ("0", ""):
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_gpu_deterministic_ops" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_gpu_deterministic_ops=true").strip()

    _naive_engine = os.environ.get("MXNET_ENGINE_TYPE") == "NaiveEngine"

    bulk = os.environ.get("MXNET_EXEC_BULK_EXEC_TRAIN")
    if bulk is not None:
        from . import engine
        try:
            engine.set_bulk_size(int(bulk))
        except ValueError:
            pass

    seed = os.environ.get("MXNET_SEED")
    if seed is not None:
        from . import random as _rng
        try:
            _rng.seed(int(seed))
        except ValueError:
            pass

    if os.environ.get("MXNET_PROFILER_AUTOSTART", "0") not in ("0", ""):
        from . import profiler
        profiler.set_state("run")


def describe():
    """The live table: (name, current value, honored?)."""
    names = ["MXNET_SEED", "MXNET_ENGINE_TYPE", "MXNET_EXEC_BULK_EXEC_TRAIN",
             "MXNET_CPU_WORKER_NTHREADS", "MXNET_PROFILER_AUTOSTART",
             "MXNET_ENFORCE_DETERMINISM", "MXNET_HOME",
             "MXNET_HEARTBEAT_INTERVAL", "MXNET_KVSTORE_BUCKETING",
             "MXNET_KVSTORE_BUCKET_BYTES", "MXNET_GPU_MEM_POOL_RESERVE",
             "MXNET_STORAGE_FALLBACK_LOG_VERBOSE",
             # subsystem-owned knobs (second docstring table); mxlint's
             # env-var-undocumented rule diffs this list against every
             # MXNET_* access in the codebase
             "MXNET_ENGINE_DEBUG", "MXNET_DROPOUT_RNG",
             "MXNET_TELEMETRY_STEADY_STEPS", "MXNET_PROFILE_RANK",
             "MXNET_PROFILE_DIR", "MXNET_KVSTORE_SPARSE_HOST_BOUND",
             "MXNET_TPU_MODEL_REPO", "MXNET_FAULTLINE",
             "MXNET_CHECKPOINT_KEEP", "MXNET_KVSTORE_RETRIES",
             "MXNET_KVSTORE_QBLOCK", "MXNET_DECODE_THREADS",
             "MXNET_PREFETCH_DEPTH", "MXNET_IO_ERROR_TOLERANCE",
             "MXNET_SERVE_REPLICAS", "MXNET_SERVE_DEADLINE_MS",
             "MXNET_SERVE_EJECT_AFTER", "MXNET_ELASTIC",
             "MXNET_ELASTIC_MIN_WORLD", "MXNET_ELASTIC_SCALING",
             "MXNET_SENTINEL_SLOW_FACTOR", "MXNET_SENTINEL_LOSS_FACTOR",
             "MXNET_SENTINEL_ROLLBACKS", "MXNET_KVSTORE_INTEGRITY",
             "MXNET_PARALLEL_RECIPE", "MXNET_RECIPE_STRICT",
             "MXNET_BLACKBOX", "MXNET_BLACKBOX_EVENTS",
             "MXNET_BLACKBOX_DIR", "MXNET_AUTOTUNE",
             "MXNET_AUTOTUNE_CACHE", "MXNET_LOCKSCAN_WITNESS",
             "MXNET_LOCKSCAN_REPORT"]
    return [(n, os.environ.get(n), n in __doc__) for n in names]
