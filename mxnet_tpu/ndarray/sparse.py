"""Sparse NDArray types: ``row_sparse`` and ``csr``.

Reference: `include/mxnet/ndarray.h` storage types (`kRowSparseStorage`,
`kCSRStorage`) + `python/mxnet/ndarray/sparse.py` (`CSRNDArray`,
`RowSparseNDArray`, `csr_matrix`, `row_sparse_array`, `dot`, `retain`,
`tostype`).

TPU-native stance (SURVEY.md §7): XLA has no sparse buffer type, and on
the MXU dense gather/scatter is the fast path, so sparse arrays here are
host-side index/value containers for data interchange (the reference's
main uses: CTR-style CSR datasets and row_sparse gradients for wide
embeddings).  Compute (`dot`) lowers through `jax.experimental.sparse`
BCOO, which XLA compiles to gather/scatter-matmul; converting `tostype
('default')` materializes a dense NDArray on device.
"""
from __future__ import annotations

import jax
import numpy as onp

from .ndarray import NDArray


@jax.jit
def _dot_jit(s, d):
    return s @ d


@jax.jit
def _dot_t_jit(s, d):
    return s.T @ d

__all__ = ["CSRNDArray", "RowSparseNDArray", "csr_matrix",
           "row_sparse_array", "dot", "retain", "zeros", "array"]


class _SparseNDArray:
    """Common container behavior (shape/dtype/context/tostype)."""

    stype = None

    def __init__(self, shape, dtype):
        self._shape = tuple(int(s) for s in shape)
        self._dtype = onp.dtype(dtype)

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._dtype

    def __repr__(self):
        return (f"<{type(self).__name__} {self._shape} "
                f"stype={self.stype}>")

    def asnumpy(self):
        raise NotImplementedError

    def tostype(self, stype):
        if stype == self.stype:
            return self
        if stype == "default":
            # device-side scatter (no host round trip)
            return NDArray(self.dense_data())
        raise ValueError(
            f"cannot convert {self.stype} directly to {stype!r}")

    def copy(self):
        if self.stype == "row_sparse":
            return RowSparseNDArray(self.data, self.indices, self._shape,
                                    self._dtype)
        return CSRNDArray(self.data, self.indices, self.indptr, self._shape,
                          self._dtype)


class CSRNDArray(_SparseNDArray):
    """Compressed sparse row matrix (reference `CSRNDArray`).

    Device-backed (round 3, VERDICT r2 #6): ``data``/``indices``/``indptr``
    are jax arrays, so CSR compute (``sparse.dot`` BCOO contraction,
    ``tostype('default')`` scatter) runs on device without a host round
    trip; host copies are made only by ``asnumpy``-style exits."""

    stype = "csr"

    def __init__(self, data, indices, indptr, shape, dtype=None):
        import jax.numpy as jnp

        data = data if isinstance(data, jax.Array) else \
            jnp.asarray(onp.asarray(data))
        super().__init__(shape, dtype or data.dtype)
        assert len(self._shape) == 2, "csr is 2-D"
        self.data = data.astype(self._dtype)
        self.indices = jnp.asarray(
            indices if isinstance(indices, jax.Array)
            else onp.asarray(indices, onp.int32)).astype(jnp.int32)
        # int64-capable on host; device side int32 suffices for indexing
        # within one buffer (XLA index space)
        self.indptr = jnp.asarray(
            indptr if isinstance(indptr, jax.Array)
            else onp.asarray(indptr, onp.int64)).astype(jnp.int32)
        assert self.indptr.shape == (self._shape[0] + 1,)
        assert self.data.shape == self.indices.shape

    @property
    def nnz(self):
        return int(self.data.shape[0])

    def _row_indices(self):
        """Device-side expansion of indptr to per-nnz row ids (static nnz
        so it stays jittable)."""
        import jax.numpy as jnp

        counts = jnp.diff(self.indptr)
        return jnp.repeat(jnp.arange(self._shape[0], dtype=jnp.int32),
                          counts, total_repeat_length=self.nnz)

    def dense_data(self):
        import jax.numpy as jnp

        out = jnp.zeros(self._shape, self._dtype)
        return out.at[self._row_indices(), self.indices].set(self.data)

    def asnumpy(self):
        return onp.asarray(self.dense_data())

    def _to_bcoo(self):
        import jax.numpy as jnp
        from jax.experimental import sparse as jsparse
        idx = jnp.stack([self._row_indices(), self.indices], axis=1)
        return jsparse.BCOO((self.data, idx), shape=self._shape)

    def __getitem__(self, r):
        indptr = onp.asarray(self.indptr)
        lo, hi = int(indptr[r]), int(indptr[r + 1])
        out = onp.zeros((self._shape[1],), self._dtype)
        out[onp.asarray(self.indices[lo:hi])] = onp.asarray(
            self.data[lo:hi])
        return NDArray(out)


class RowSparseNDArray(_SparseNDArray):
    """First-dim-sparse tensor (reference `RowSparseNDArray`): `data`
    holds only the rows listed in `indices`.

    Device-backed: ``data``/``indices`` are jax arrays, so a row-sparse
    gradient never leaves HBM — the optimizers consume it as one XLA
    scatter over the touched rows (`ops/sparse_grad.py`)."""

    stype = "row_sparse"

    def __init__(self, data, indices, shape, dtype=None):
        import jax.numpy as jnp

        data = jnp.asarray(data)
        super().__init__(shape, dtype or data.dtype)
        self.data = data.astype(self._dtype)
        if isinstance(indices, jax.Array):
            self.indices = indices.astype(jnp.int32)
        else:  # host list/tuple/ndarray (possibly empty)
            self.indices = jnp.asarray(onp.asarray(indices, onp.int32))
        assert self.data.shape[0] == self.indices.shape[0]
        assert self.data.shape[1:] == self._shape[1:]

    def _set_rows(self, indices, values):
        """In-place rebind (the engine's sparse grad-buffer write; object
        identity is preserved for Trainer's list_grad captures)."""
        import jax.numpy as jnp

        self.indices = jnp.asarray(indices).astype(jnp.int32)
        self.data = jnp.asarray(values).astype(self._dtype)

    def _clear(self):
        import jax.numpy as jnp

        self.indices = jnp.zeros((0,), jnp.int32)
        self.data = jnp.zeros((0,) + self._shape[1:], self._dtype)

    def dense_data(self):
        """Dense jax array (scatter; duplicates summed)."""
        import jax.numpy as jnp

        out = jnp.zeros(self._shape, self._dtype)
        return out.at[self.indices].add(self.data)

    def asnumpy(self):
        return onp.asarray(self.dense_data())


def csr_matrix(arg1, shape=None, dtype=None):
    """Create a CSRNDArray from (data, indices, indptr) or a dense source
    (reference `sparse.csr_matrix`)."""
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        if shape is None:
            # infer as the reference does: rows from indptr, cols from the
            # largest column index
            indices_arr = onp.asarray(indices, onp.int32)
            shape = (len(indptr) - 1,
                     int(indices_arr.max()) + 1 if indices_arr.size else 0)
        return CSRNDArray(data, indices, indptr, shape, dtype)
    dense = arg1.asnumpy() if isinstance(arg1, NDArray) else onp.asarray(arg1)
    assert dense.ndim == 2
    rows, cols = onp.nonzero(dense)
    indptr = onp.zeros(dense.shape[0] + 1, onp.int32)
    onp.cumsum(onp.bincount(rows, minlength=dense.shape[0]), out=indptr[1:])
    return CSRNDArray(dense[rows, cols], cols, indptr,
                      shape or dense.shape, dtype)


def row_sparse_array(arg1, shape=None, dtype=None):
    """Create a RowSparseNDArray from (data, indices) or a dense source."""
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        if shape is None:
            data_arr = onp.asarray(arg1[0])
            indices_arr = onp.asarray(indices, onp.int32)
            rows = int(indices_arr.max()) + 1 if indices_arr.size else 0
            shape = (rows,) + data_arr.shape[1:]
        return RowSparseNDArray(data, indices, shape, dtype)
    dense = arg1.asnumpy() if isinstance(arg1, NDArray) else onp.asarray(arg1)
    nz_rows = onp.nonzero(dense.reshape(dense.shape[0], -1).any(axis=1))[0]
    return RowSparseNDArray(dense[nz_rows], nz_rows, shape or dense.shape,
                            dtype)


def array(source, stype="csr", **kwargs):
    if stype == "csr":
        return csr_matrix(source, **kwargs)
    if stype == "row_sparse":
        return row_sparse_array(source, **kwargs)
    raise ValueError(f"unknown stype {stype!r}")


def zeros(stype, shape, dtype="float32"):
    if stype == "csr":
        return CSRNDArray(onp.zeros((0,), dtype), [], onp.zeros(
            (shape[0] + 1,), onp.int32), shape, dtype)
    if stype == "row_sparse":
        return RowSparseNDArray(onp.zeros((0,) + tuple(shape[1:]), dtype),
                                [], shape, dtype)
    raise ValueError(f"unknown stype {stype!r}")


def dot(lhs, rhs, transpose_a=False):
    """Sparse-dense matmul (reference `sparse.dot` with `FComputeEx`
    kernels): csr @ dense or csr.T @ dense via a BCOO contraction compiled
    by XLA.  Differentiable w.r.t. the dense operand (the sparse side is
    data, as in the reference's CTR use)."""
    if not isinstance(lhs, CSRNDArray):
        raise TypeError("sparse.dot expects a CSR lhs")
    from ..ops.invoke import invoke

    bcoo = lhs._to_bcoo()
    jit_fn = _dot_t_jit if transpose_a else _dot_jit
    return invoke(lambda d: jit_fn(bcoo, d), (rhs,), name="sparse_dot")


def retain(rs, indices):
    """Keep only the listed rows of a row_sparse array (reference
    `sparse.retain`)."""
    if not isinstance(rs, RowSparseNDArray):
        raise TypeError("retain expects a RowSparseNDArray")
    want = onp.asarray(indices, onp.int32)
    have = onp.asarray(rs.indices)
    mask = onp.isin(have, want)
    return RowSparseNDArray(onp.asarray(rs.data)[mask], have[mask], rs.shape,
                            rs.dtype)


def adagrad_update(weight, grad, history, lr, epsilon=1e-7, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, out=None):
    """`_sparse_adagrad_update` (`src/operator/optimizer_op.cc:888`) under
    its reference home `mx.nd.sparse.adagrad_update`; accepts dense or
    row_sparse gradients (see `ndarray.legacy.sparse_adagrad_update`)."""
    from .legacy import sparse_adagrad_update
    return sparse_adagrad_update(weight, grad, history, lr, epsilon=epsilon,
                                 wd=wd, rescale_grad=rescale_grad,
                                 clip_gradient=clip_gradient, out=out)


__all__.append("adagrad_update")
