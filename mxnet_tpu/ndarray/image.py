"""``mx.nd.image`` — NDArray-facing image operator namespace.

Reference: `python/mxnet/ndarray/image.py` (generated from
`src/operator/image/`).  Kernels live in `mxnet_tpu/ops/image_ops.py`;
this module routes NDArrays through the imperative ``invoke`` path so the
ops participate in the tape/profiler like any other operator.
"""
from __future__ import annotations

from ..ops import image_ops as _im
from ..ops.invoke import invoke

__all__ = list(_im.__all__)

# randomized ops draw host scalars at dispatch; none are differentiable
# except to_tensor/normalize/resize/crop, which jnp handles through vjp
_NON_DIFF = {"random_flip_left_right", "random_flip_top_bottom"}


def _wrap(name):
    jf = getattr(_im, name)

    def fn(*args, **kwargs):
        kwargs.pop("out", None)
        return invoke(jf, args, kwargs, name=f"image_{name}",
                      differentiable=name not in _NON_DIFF)

    fn.__name__ = name
    fn.__doc__ = jf.__doc__
    return fn


_g = globals()
for _name in __all__:
    _g[_name] = _wrap(_name)
