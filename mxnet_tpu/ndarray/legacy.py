"""The legacy ``mx.nd.*`` generated-op surface.

Reference: `python/mxnet/ndarray/register.py:265-277` generates ~21k LoC of
wrappers over the registered ops (kernels in `src/operator/`); this module
provides the same names and argument conventions over the TPU lowerings —
CamelCase layer ops (`FullyConnected`, `Convolution`, `BatchNorm`, ...),
the broadcast/elemwise zoo, legacy reductions (with ``exclude``), the
special-code ``Reshape``, training heads with custom backward semantics
(`SoftmaxOutput`), the fused ``RNN`` op, and the fused optimizer update
kernels.  Everything dispatches through ``ops.invoke`` so autograd records
it, and through the same lowerings Gluon uses, so the two APIs agree.

``out=`` follows the reference's mutate-output convention: the result is
rebound into the given NDArray (version bump; see `ndarray/ndarray.py`).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as onp

from .. import numpy_extension as _npx
from ..context import current_context
from ..ops import legacy_math as _lm
from ..ops import nn as _nn
from ..ops.invoke import invoke
from .ndarray import NDArray


def _nd(x):
    return x if isinstance(x, NDArray) else NDArray(jnp.asarray(x))


def _ret(res, out=None):
    if out is None:
        return res
    out._rebind(res._data if isinstance(res, NDArray) else jnp.asarray(res))
    return out


def _inplace(arr, new):
    """Mutate-in-place contract of the optimizer kernels: the state arg is
    rebound to the updated value (reference kMutate outputs)."""
    arr = _nd(arr)
    arr._rebind(new._data if isinstance(new, NDArray) else jnp.asarray(new))
    return arr


# ---------------------------------------------------------------------------
# unary math missing from mx.np (`src/operator/tensor/elemwise_unary_op.cc`)
# ---------------------------------------------------------------------------

def rsqrt(data, out=None):
    return _ret(invoke(lambda d: jax.lax.rsqrt(d), (data,), name="rsqrt"), out)


def rcbrt(data, out=None):
    return _ret(invoke(lambda d: 1.0 / jnp.cbrt(d), (data,), name="rcbrt"),
                out)


def softsign(data, out=None):
    return _ret(invoke(lambda d: d / (1 + jnp.abs(d)), (data,),
                       name="softsign"), out)


def hard_sigmoid(data, alpha=0.2, beta=0.5, out=None):
    return _ret(invoke(lambda d: jnp.clip(alpha * d + beta, 0, 1), (data,),
                       name="hard_sigmoid"), out)


def reciprocal(data, out=None):
    return _ret(invoke(lambda d: 1.0 / d, (data,), name="reciprocal"), out)


# ---------------------------------------------------------------------------
# broadcast / elemwise binary zoo.  Legacy comparisons return the lhs float
# dtype, not bool (`src/operator/tensor/elemwise_binary_broadcast_op_logic.cc`)
# ---------------------------------------------------------------------------

def _binary(name, fn, boolout=False):
    def op(lhs, rhs, out=None):
        def lower(a, b):
            r = fn(a, b)
            if boolout:
                dt = a.dtype if jnp.issubdtype(a.dtype, jnp.floating) \
                    else jnp.float32
                r = r.astype(dt)
            return r
        return _ret(invoke(lower, (lhs, rhs), name=name), out)
    op.__name__ = name
    return op


broadcast_add = _binary("broadcast_add", jnp.add)
broadcast_plus = broadcast_add
broadcast_sub = _binary("broadcast_sub", jnp.subtract)
broadcast_minus = broadcast_sub
broadcast_mul = _binary("broadcast_mul", jnp.multiply)
broadcast_div = _binary("broadcast_div", jnp.divide)
broadcast_mod = _binary("broadcast_mod", jnp.mod)
broadcast_power = _binary("broadcast_power", jnp.power)
broadcast_maximum = _binary("broadcast_maximum", jnp.maximum)
broadcast_minimum = _binary("broadcast_minimum", jnp.minimum)
broadcast_hypot = _binary("broadcast_hypot", jnp.hypot)
broadcast_equal = _binary("broadcast_equal", jnp.equal, True)
broadcast_not_equal = _binary("broadcast_not_equal", jnp.not_equal, True)
broadcast_greater = _binary("broadcast_greater", jnp.greater, True)
broadcast_greater_equal = _binary("broadcast_greater_equal",
                                  jnp.greater_equal, True)
broadcast_lesser = _binary("broadcast_lesser", jnp.less, True)
broadcast_lesser_equal = _binary("broadcast_lesser_equal",
                                 jnp.less_equal, True)
broadcast_logical_and = _binary("broadcast_logical_and",
                                jnp.logical_and, True)
broadcast_logical_or = _binary("broadcast_logical_or", jnp.logical_or, True)
broadcast_logical_xor = _binary("broadcast_logical_xor",
                                jnp.logical_xor, True)
elemwise_add = _binary("elemwise_add", jnp.add)
elemwise_sub = _binary("elemwise_sub", jnp.subtract)
elemwise_mul = _binary("elemwise_mul", jnp.multiply)
elemwise_div = _binary("elemwise_div", jnp.divide)
equal = broadcast_equal
not_equal = broadcast_not_equal
greater = broadcast_greater
greater_equal = broadcast_greater_equal
lesser = broadcast_lesser
lesser_equal = broadcast_lesser_equal


# ---------------------------------------------------------------------------
# legacy reductions (`exclude` convention) and ordering ops
# ---------------------------------------------------------------------------

def _reduction(name):
    def op(data, axis=None, keepdims=False, exclude=False, out=None):
        return _ret(invoke(_lm.reduce_op, (data,),
                           dict(axis=axis, keepdims=keepdims,
                                exclude=exclude, op=name), name=name), out)
    op.__name__ = name
    return op


sum = _reduction("sum")              # noqa: A001
mean = _reduction("mean")
prod = _reduction("prod")
nansum = _reduction("nansum")
nanprod = _reduction("nanprod")
max = _reduction("max")              # noqa: A001
min = _reduction("min")              # noqa: A001
sum_axis = sum
max_axis = max
min_axis = min


def norm(data, ord=2, axis=None, keepdims=False, out=None):  # noqa: A002
    return _ret(invoke(_lm.norm, (data,),
                       dict(ord=ord, axis=axis, keepdims=keepdims),
                       name="norm"), out)


def moments(data, axes=None, keepdims=False):
    axes = tuple(axes) if axes is not None else None
    return invoke(_lm.moments, (data,), dict(axes=axes, keepdims=keepdims),
                  name="moments")


def argmax(data, axis=None, keepdims=False, out=None):
    return _ret(invoke(
        lambda d: jnp.argmax(d, axis=axis, keepdims=keepdims).astype(
            jnp.float32),
        (data,), name="argmax", differentiable=False), out)


def argmin(data, axis=None, keepdims=False, out=None):
    return _ret(invoke(
        lambda d: jnp.argmin(d, axis=axis, keepdims=keepdims).astype(
            jnp.float32),
        (data,), name="argmin", differentiable=False), out)


def argmax_channel(data, out=None):
    return _ret(invoke(_lm.argmax_channel, (data,), name="argmax_channel",
                       differentiable=False), out)


def sort(data, axis=-1, is_ascend=True, out=None):
    def lower(d):
        s = jnp.sort(d, axis=axis)
        return s if is_ascend else jnp.flip(s, axis=axis)
    return _ret(invoke(lower, (data,), name="sort"), out)


def argsort(data, axis=-1, is_ascend=True, dtype="float32", out=None):
    def lower(d):
        s = jnp.argsort(d, axis=axis)
        if not is_ascend:
            s = jnp.flip(s, axis=axis)
        return s.astype(dtype)
    return _ret(invoke(lower, (data,), name="argsort",
                       differentiable=False), out)


topk = _npx.topk
pick = _npx.pick
one_hot = _npx.one_hot


# ---------------------------------------------------------------------------
# shape manipulation
# ---------------------------------------------------------------------------

def Reshape(data, shape=None, reverse=False, out=None, **_ignored):
    return _ret(invoke(_lm.legacy_reshape, (data,),
                       dict(shape=tuple(shape), reverse=reverse),
                       name="Reshape"), out)


reshape = Reshape


def transpose(data, axes=None, out=None):
    axes = tuple(axes) if axes else None
    return _ret(invoke(lambda d: jnp.transpose(d, axes), (data,),
                       name="transpose"), out)


def SwapAxis(data, dim1=0, dim2=0, out=None):
    return _ret(invoke(lambda d: jnp.swapaxes(d, dim1, dim2), (data,),
                       name="SwapAxis"), out)


swapaxes = SwapAxis


def expand_dims(data, axis, out=None):
    return _ret(invoke(lambda d: jnp.expand_dims(d, axis), (data,),
                       name="expand_dims"), out)


def squeeze(data, axis=None, out=None):
    return _ret(invoke(lambda d: jnp.squeeze(d, axis=axis), (data,),
                       name="squeeze"), out)


def Flatten(data, out=None):
    return _ret(invoke(lambda d: d.reshape(d.shape[0], -1), (data,),
                       name="Flatten"), out)


flatten = Flatten


def Concat(*data, dim=1, out=None, num_args=None):
    return _ret(invoke(lambda *a: jnp.concatenate(a, axis=dim), data,
                       name="Concat"), out)


concat = Concat


def stack(*data, axis=0, out=None, num_args=None):
    return _ret(invoke(lambda *a: jnp.stack(a, axis=axis), data,
                       name="stack"), out)


def SliceChannel(data, num_outputs=1, axis=1, squeeze_axis=False):
    def lower(d):
        parts = jnp.split(d, num_outputs, axis=axis)
        if squeeze_axis:
            parts = [jnp.squeeze(p, axis=axis) for p in parts]
        return tuple(parts)
    return list(invoke(lower, (data,), name="SliceChannel"))


split = SliceChannel


def tile(data, reps, out=None):
    return _ret(invoke(lambda d: jnp.tile(d, tuple(reps)), (data,),
                       name="tile"), out)


def repeat(data, repeats=1, axis=None, out=None):
    return _ret(invoke(lambda d: jnp.repeat(d, repeats, axis=axis), (data,),
                       name="repeat"), out)


def reverse(data, axis=0, out=None):
    return _ret(invoke(_lm.reverse, (data,), dict(axis=axis),
                       name="reverse"), out)


flip = reverse


def depth_to_space(data, block_size, out=None):
    return _ret(invoke(_lm.depth_to_space, (data,),
                       dict(block_size=block_size), name="depth_to_space"),
                out)


def space_to_depth(data, block_size, out=None):
    return _ret(invoke(_lm.space_to_depth, (data,),
                       dict(block_size=block_size), name="space_to_depth"),
                out)


def diag(data, k=0, out=None):
    def lower(d):
        if d.ndim == 1:
            return jnp.diag(d, k)
        return jnp.diagonal(d, offset=k, axis1=-2, axis2=-1)
    return _ret(invoke(lower, (data,), name="diag"), out)


def broadcast_axis(data, axis=(), size=(), out=None):
    return _ret(invoke(_lm.broadcast_axis, (data,),
                       dict(axis=axis, size=size), name="broadcast_axis"),
                out)


broadcast_axes = broadcast_axis


def broadcast_to(data, shape=None, out=None):
    return _ret(invoke(_lm.broadcast_to, (data,), dict(shape=tuple(shape)),
                       name="broadcast_to"), out)


def shape_array(data, out=None):
    return _ret(_nd(jnp.asarray(onp.asarray(_nd(data).shape, onp.int64))),
                out)


def size_array(data, out=None):
    return _ret(_nd(jnp.asarray(onp.asarray([_nd(data).size], onp.int64))),
                out)


def Cast(data, dtype="float32", out=None):
    return _ret(invoke(lambda d: d.astype(dtype), (data,), name="Cast"), out)


cast = Cast


def amp_cast(data, dtype="float32", out=None):
    return Cast(data, dtype, out)


def amp_multicast(*data, num_outputs=None, cast_narrow=False):
    dts = [_nd(d)._data.dtype for d in data]
    widths = [jnp.dtype(dt).itemsize for dt in dts]
    target = dts[int(onp.argmin(widths))] if cast_narrow else \
        dts[int(onp.argmax(widths))]
    return [Cast(d, target) for d in data]


# ---------------------------------------------------------------------------
# indexing / gather
# ---------------------------------------------------------------------------

def slice(data, begin=None, end=None, step=None, out=None):  # noqa: A001
    return _ret(invoke(_lm.slice_op, (data,),
                       dict(begin=tuple(begin) if begin else None,
                            end=tuple(end) if end else None,
                            step=tuple(step) if step else None),
                       name="slice"), out)


def slice_axis(data, axis=0, begin=0, end=None, out=None):
    return _ret(invoke(_lm.slice_axis, (data,),
                       dict(axis=axis, begin=begin, end=end),
                       name="slice_axis"), out)


slice_like = _npx.slice_like
gather_nd = _npx.gather_nd
scatter_nd = _npx.scatter_nd
reshape_like = _npx.reshape_like
broadcast_like = _npx.broadcast_like


def take(a, indices, axis=0, mode="clip", out=None):
    return _ret(invoke(_lm.take, (a, indices), dict(axis=axis, mode=mode),
                       name="take"), out)


def batch_take(a, indices, out=None):
    return _ret(invoke(_lm.batch_take, (a, indices), name="batch_take"), out)


def where(condition, x, y, out=None):
    return _ret(invoke(
        lambda c, a, b: jnp.where(c.astype(bool), a, b),
        (condition, x, y), name="where"), out)


def clip(data, a_min=None, a_max=None, out=None):
    return _ret(invoke(lambda d: jnp.clip(d, a_min, a_max), (data,),
                       name="clip"), out)


# ---------------------------------------------------------------------------
# linear algebra
# ---------------------------------------------------------------------------

def dot(lhs, rhs, transpose_a=False, transpose_b=False, out=None,
        forward_stype=None):
    """Legacy dot: reduce last axis of lhs with first of rhs; the transpose
    flags flip which end is reduced (`src/operator/tensor/dot-inl.h`)."""
    def lower(a, b):
        aa = 0 if transpose_a else a.ndim - 1
        bb = b.ndim - 1 if transpose_b else 0
        return jnp.tensordot(a, b, axes=((aa,), (bb,)))
    return _ret(invoke(lower, (lhs, rhs), name="dot"), out)


batch_dot = _npx.batch_dot
khatri_rao = _npx.khatri_rao


# ---------------------------------------------------------------------------
# CamelCase layer ops
# ---------------------------------------------------------------------------

def Activation(data, act_type="relu", out=None):
    return _ret(_npx.activation(data, act_type=act_type), out)


def SoftmaxActivation(data, mode="instance", out=None):
    axis = 1 if mode == "channel" else -1
    return _ret(_npx.softmax(_nd(data), axis=axis), out)


def FullyConnected(data, weight=None, bias=None, num_hidden=None,
                   no_bias=False, flatten=True, out=None):
    return _ret(_npx.fully_connected(
        data, weight, None if no_bias else bias, num_hidden=num_hidden,
        flatten=flatten), out)


def Convolution(data, weight=None, bias=None, kernel=None, stride=None,
                dilate=None, pad=None, num_filter=None, num_group=1,
                workspace=1024, no_bias=False, cudnn_tune=None,
                cudnn_off=False, layout=None, out=None):
    return _ret(_npx.convolution(
        data, weight, None if no_bias else bias, kernel=kernel,
        stride=stride, dilate=dilate, pad=pad, num_filter=num_filter,
        num_group=num_group, layout=layout or "NCHW"), out)


def Deconvolution(data, weight=None, bias=None, kernel=None, stride=None,
                  dilate=None, pad=None, adj=None, target_shape=None,
                  num_filter=None, num_group=1, workspace=512, no_bias=True,
                  cudnn_tune=None, cudnn_off=False, layout=None, out=None):
    return _ret(_npx.deconvolution(
        data, weight, None if no_bias else bias, kernel=kernel,
        stride=stride, dilate=dilate, pad=pad, adj=adj,
        num_filter=num_filter, num_group=num_group,
        layout=layout or "NCHW"), out)


def Pooling(data, kernel=None, pool_type="max", global_pool=False,
            cudnn_off=False, pooling_convention="valid", stride=None,
            pad=None, p_value=2, count_include_pad=True, layout=None,
            out=None):
    return _ret(_npx.pooling(
        data, kernel=kernel, pool_type=pool_type, stride=stride, pad=pad,
        global_pool=global_pool, count_include_pad=count_include_pad,
        layout=layout or "NCHW",
        pooling_convention=pooling_convention), out)


def BatchNorm(data, gamma=None, beta=None, moving_mean=None, moving_var=None,
              eps=1e-3, momentum=0.9, fix_gamma=True, use_global_stats=False,
              output_mean_var=False, axis=1, cudnn_off=False, out=None):
    return _ret(_npx.batch_norm(
        data, gamma, beta, moving_mean, moving_var, eps=eps,
        momentum=momentum, fix_gamma=fix_gamma,
        use_global_stats=use_global_stats,
        output_mean_var=output_mean_var, axis=axis), out)


def LayerNorm(data, gamma=None, beta=None, axis=-1, eps=1e-5, out=None):
    return _ret(_npx.layer_norm(data, gamma, beta, axis=axis, eps=eps), out)


def InstanceNorm(data, gamma=None, beta=None, eps=1e-3, out=None):
    return _ret(_npx.instance_norm(data, gamma, beta, eps=eps), out)


def GroupNorm(data, gamma=None, beta=None, num_groups=1, eps=1e-5, out=None):
    return _ret(_npx.group_norm(data, gamma, beta, num_groups=num_groups,
                                eps=eps), out)


def L2Normalization(data, eps=1e-10, mode="instance", out=None):
    return _ret(_npx.l2_normalization(data, eps=eps, mode=mode), out)


def LRN(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5, out=None):
    return _ret(invoke(_lm.lrn, (data,),
                       dict(alpha=alpha, beta=beta, knorm=knorm, nsize=nsize),
                       name="LRN"), out)


def Dropout(data, p=0.5, mode="training", axes=None, cudnn_off=False,
            out=None):
    return _ret(_npx.dropout(data, p=p, axes=axes,
                             mode=None if mode == "training" else mode), out)


def Embedding(data, weight=None, input_dim=None, output_dim=None,
              dtype="float32", sparse_grad=False, out=None):
    return _ret(_npx.embedding(data, weight, input_dim=input_dim,
                               output_dim=output_dim, dtype=dtype,
                               sparse_grad=sparse_grad), out)


def LeakyReLU(data, gamma=None, act_type="leaky", slope=0.25,
              lower_bound=0.125, upper_bound=0.334, out=None):
    return _ret(_npx.leaky_relu(data, gamma, act_type=act_type, slope=slope,
                                lower_bound=lower_bound,
                                upper_bound=upper_bound), out)


def Pad(data, mode="constant", pad_width=None, constant_value=0.0, out=None):
    return _ret(invoke(_lm.pad, (data,),
                       dict(mode=mode, pad_width=tuple(pad_width),
                            constant_value=constant_value), name="Pad"), out)


pad = Pad


def Crop(*data, offset=(0, 0), h_w=(0, 0), center_crop=False, num_args=None,
         out=None):
    like = data[1] if len(data) > 1 else None
    args = (data[0],) if like is None else (data[0], like)

    def lower(d, lk=None):
        return _lm.crop(d, offset=tuple(offset), h_w=tuple(h_w),
                        center_crop=center_crop, like=lk)
    return _ret(invoke(lower, args, name="Crop"), out)


def UpSampling(*data, scale=2, sample_type="nearest", num_args=None,
               workspace=512, num_filter=0, multi_input_mode="concat",
               out=None):
    ups = [invoke(_lm.upsampling, (d,),
                  dict(scale=scale, sample_type=sample_type),
                  name="UpSampling") for d in data[:1]] + \
          [_nd(d) for d in data[1:]]
    if len(ups) == 1:
        return _ret(ups[0], out)
    return _ret(invoke(lambda *a: jnp.concatenate(a, axis=1), tuple(ups),
                       name="UpSampling"), out)


def SequenceMask(data, sequence_length=None, use_sequence_length=False,
                 value=0.0, axis=0, out=None):
    return _ret(_npx.sequence_mask(data, sequence_length,
                                   use_sequence_length=use_sequence_length,
                                   value=value, axis=axis), out)


def SequenceLast(data, sequence_length=None, use_sequence_length=False,
                 axis=0, out=None):
    return _ret(_npx.sequence_last(data, sequence_length,
                                   use_sequence_length=use_sequence_length,
                                   axis=axis), out)


def SequenceReverse(data, sequence_length=None, use_sequence_length=False,
                    axis=0, out=None):
    return _ret(_npx.sequence_reverse(data, sequence_length,
                                      use_sequence_length=use_sequence_length,
                                      axis=axis), out)


def RNN(data, parameters=None, state=None, state_cell=None,
        sequence_length=None, state_size=None, num_layers=1,
        bidirectional=False, mode="lstm", p=0.0, state_outputs=False,
        projection_size=None, use_sequence_length=False,
        lstm_state_clip_min=None, lstm_state_clip_max=None,
        lstm_state_clip_nan=False, out=None):
    """Fused multi-layer RNN (`src/operator/rnn.cc`); data layout TNC;
    parameters are the flat packed vector (weights then biases)."""
    args = (data, parameters, state) + (
        (state_cell,) if mode == "lstm" else ())

    def lower(d, w, s, c=None):
        return _lm.rnn(d, w, s, state_cell=c, state_size=state_size,
                       num_layers=num_layers, bidirectional=bidirectional,
                       mode=mode, p=p)
    res = invoke(lower, args, name="RNN")
    if not state_outputs:
        return res[0]
    return list(res)


def SoftmaxOutput(data, label=None, grad_scale=1.0, ignore_label=-1.0,
                  multi_output=False, use_ignore=False, preserve_shape=False,
                  normalization="null", out_grad=False, smooth_alpha=0.0,
                  out=None):
    return _ret(invoke(
        _lm.softmax_output, (data, label),
        dict(grad_scale=grad_scale, ignore_label=ignore_label,
             multi_output=multi_output, use_ignore=use_ignore,
             normalization=normalization, smooth_alpha=smooth_alpha),
        name="SoftmaxOutput"), out)


Softmax = SoftmaxOutput  # ancient alias (reference keeps it too)


def LinearRegressionOutput(data, label=None, grad_scale=1.0, out=None):
    return _ret(invoke(_lm.linear_regression_output, (data, label),
                       dict(grad_scale=grad_scale),
                       name="LinearRegressionOutput"), out)


def MAERegressionOutput(data, label=None, grad_scale=1.0, out=None):
    return _ret(invoke(_lm.mae_regression_output, (data, label),
                       dict(grad_scale=grad_scale),
                       name="MAERegressionOutput"), out)


def LogisticRegressionOutput(data, label=None, grad_scale=1.0, out=None):
    return _ret(invoke(_lm.logistic_regression_output, (data, label),
                       dict(grad_scale=grad_scale),
                       name="LogisticRegressionOutput"), out)


def SVMOutput(data, label=None, margin=1.0, regularization_coefficient=1.0,
              use_linear=False, out=None):
    return _ret(invoke(_lm.svm_output, (data, label),
                       name="SVMOutput"), out)


def softmax_cross_entropy(data, label, out=None):
    return _ret(invoke(_lm.softmax_cross_entropy, (data, label),
                       name="softmax_cross_entropy"), out)


def BlockGrad(data, out=None):
    return _ret(invoke(jax.lax.stop_gradient, (data,), name="BlockGrad"), out)


stop_gradient = BlockGrad
make_loss = _npx.make_loss
MakeLoss = make_loss
smooth_l1 = _npx.smooth_l1
log_softmax = _npx.log_softmax
softmax = _npx.softmax


def softmin(data, axis=-1, out=None):
    return _ret(_npx.softmax(_nd(data) * -1, axis=axis), out)


def relu(data, out=None):
    return _ret(_npx.relu(data), out)


def sigmoid(data, out=None):
    return _ret(_npx.sigmoid(data), out)


def identity(data, out=None):
    return _ret(invoke(lambda d: d, (data,), name="identity"), out)


copy = identity  # noqa: A001


def IdentityAttachKLSparseReg(data, sparseness_target=0.1, penalty=0.001,
                              momentum=0.9, out=None):
    return identity(data, out)


def Custom(*data, op_type=None, **kwargs):
    """Bridge into the python CustomOp registry (`operator.py`)."""
    from ..operator import invoke_custom
    return invoke_custom(*[_nd(d) for d in data], op_type=op_type, **kwargs)


# spatial ops (already TPU-lowered in ops/spatial.py)
SpatialTransformer = _npx.spatial_transformer
GridGenerator = _npx.grid_generator
BilinearSampler = _npx.bilinear_sampler
ROIPooling = _npx.roi_pooling
im2col = _npx.im2col
col2im = _npx.col2im


def CTCLoss(data, label, data_lengths=None, label_lengths=None,
            use_data_lengths=False, use_label_lengths=False,
            blank_label="first", out=None):
    from ..gluon.loss import CTCLoss as _G
    ls = _G(layout="TNC", label_layout="NT")
    return _ret(ls(_nd(data), _nd(label),
                   _nd(data_lengths) if use_data_lengths else None,
                   _nd(label_lengths) if use_label_lengths else None), out)


ctc_loss = CTCLoss


# ---------------------------------------------------------------------------
# misc kernels
# ---------------------------------------------------------------------------

def add_n(*args, out=None):
    return _ret(invoke(_lm.add_n, args, name="add_n"), out)


ElementWiseSum = add_n


def all_finite(data, init_output=True, out=None):
    return _ret(invoke(_lm.all_finite, (data,), name="all_finite",
                       differentiable=False), out)


multi_all_finite = _npx.multi_all_finite


def cast_storage(data, stype="default", out=None):
    from . import sparse as _sp
    if stype == "default":
        if isinstance(data, _sp._SparseNDArray):
            return _ret(data.tostype("default"), out)
        return _ret(_nd(data), out)
    arr = data if isinstance(data, NDArray) else _nd(data)
    return arr.tostype(stype)


def zeros_like(data, out=None):
    return _ret(invoke(jnp.zeros_like, (data,), name="zeros_like",
                       differentiable=False), out)


def ones_like(data, out=None):
    return _ret(invoke(jnp.ones_like, (data,), name="ones_like",
                       differentiable=False), out)


def zeros(shape, ctx=None, dtype="float32", out=None):
    return _ret(_nd(jnp.zeros(shape, dtype)), out)


def ones(shape, ctx=None, dtype="float32", out=None):
    return _ret(_nd(jnp.ones(shape, dtype)), out)


def full(shape, val, ctx=None, dtype="float32", out=None):
    return _ret(_nd(jnp.full(shape, val, dtype)), out)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32",
           out=None):
    a = jnp.arange(start, stop, step, dtype)
    if repeat > 1:
        a = jnp.repeat(a, repeat)
    return _ret(_nd(a), out)


def eye(N, M=0, k=0, ctx=None, dtype="float32", out=None):  # noqa: N803
    return _ret(_nd(jnp.eye(int(N), int(M) if M else None, k, dtype=dtype)),
                out)


# ---------------------------------------------------------------------------
# fused optimizer update kernels — mutate-output contract: `out` (and the
# state inputs) are rebound to the updated values, matching the reference's
# in-place semantics (`src/operator/optimizer_op.cc`)
# ---------------------------------------------------------------------------

def _f(v, default):
    return default if v is None else float(v)


def sgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True, out=None):
    new_w = invoke(_lm.sgd_update, (weight, grad),
                   dict(lr=_f(lr, 0.0), wd=_f(wd, 0.0),
                        rescale_grad=_f(rescale_grad, 1.0),
                        clip_gradient=_f(clip_gradient, -1.0)),
                   name="sgd_update", differentiable=False)
    return _ret(new_w, out if out is not None else _nd(weight))


def sgd_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True,
                   out=None):
    new_w, new_mom = invoke(
        _lm.sgd_mom_update, (weight, grad, mom),
        dict(lr=_f(lr, 0.0), momentum=_f(momentum, 0.0), wd=_f(wd, 0.0),
             rescale_grad=_f(rescale_grad, 1.0),
             clip_gradient=_f(clip_gradient, -1.0)),
        name="sgd_mom_update", differentiable=False)
    _inplace(mom, new_mom)
    return _ret(new_w, out if out is not None else _nd(weight))


def nag_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, out=None):
    new_w, new_mom = invoke(
        _lm.nag_mom_update, (weight, grad, mom),
        dict(lr=_f(lr, 0.0), momentum=_f(momentum, 0.0), wd=_f(wd, 0.0),
             rescale_grad=_f(rescale_grad, 1.0),
             clip_gradient=_f(clip_gradient, -1.0)),
        name="nag_mom_update", differentiable=False)
    _inplace(mom, new_mom)
    return _ret(new_w, out if out is not None else _nd(weight))


def adam_update(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True, out=None):
    new_w, new_mean, new_var = invoke(
        _lm.adam_update, (weight, grad, mean, var),
        dict(lr=_f(lr, 0.0), beta1=_f(beta1, 0.9), beta2=_f(beta2, 0.999),
             epsilon=_f(epsilon, 1e-8), wd=_f(wd, 0.0),
             rescale_grad=_f(rescale_grad, 1.0),
             clip_gradient=_f(clip_gradient, -1.0)),
        name="adam_update", differentiable=False)
    _inplace(mean, new_mean)
    _inplace(var, new_var)
    return _ret(new_w, out if out is not None else _nd(weight))


def rmsprop_update(weight, grad, n, lr, gamma1=0.95, epsilon=1e-8, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0,
                   out=None):
    new_w, new_n = invoke(
        _lm.rmsprop_update, (weight, grad, n),
        dict(lr=_f(lr, 0.0), gamma1=_f(gamma1, 0.95),
             epsilon=_f(epsilon, 1e-8), wd=_f(wd, 0.0),
             rescale_grad=_f(rescale_grad, 1.0),
             clip_gradient=_f(clip_gradient, -1.0),
             clip_weights=_f(clip_weights, -1.0)),
        name="rmsprop_update", differentiable=False)
    _inplace(n, new_n)
    return _ret(new_w, out if out is not None else _nd(weight))


def rmspropalex_update(weight, grad, n, g, delta, lr, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0, out=None):
    new_w, new_n, new_g, new_delta = invoke(
        _lm.rmspropalex_update, (weight, grad, n, g, delta),
        dict(lr=_f(lr, 0.0), gamma1=_f(gamma1, 0.95),
             gamma2=_f(gamma2, 0.9), epsilon=_f(epsilon, 1e-8),
             wd=_f(wd, 0.0), rescale_grad=_f(rescale_grad, 1.0),
             clip_gradient=_f(clip_gradient, -1.0),
             clip_weights=_f(clip_weights, -1.0)),
        name="rmspropalex_update", differentiable=False)
    _inplace(n, new_n)
    _inplace(g, new_g)
    _inplace(delta, new_delta)
    return _ret(new_w, out if out is not None else _nd(weight))


def ftrl_update(weight, grad, z, n, lr, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0, out=None):
    new_w, new_z, new_n = invoke(
        _lm.ftrl_update, (weight, grad, z, n),
        dict(lr=_f(lr, 0.0), lamda1=_f(lamda1, 0.01), beta=_f(beta, 1.0),
             wd=_f(wd, 0.0), rescale_grad=_f(rescale_grad, 1.0),
             clip_gradient=_f(clip_gradient, -1.0)),
        name="ftrl_update", differentiable=False)
    _inplace(z, new_z)
    _inplace(n, new_n)
    return _ret(new_w, out if out is not None else _nd(weight))


def signsgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, out=None):
    new_w = invoke(
        _lm.signsgd_update, (weight, grad),
        dict(lr=_f(lr, 0.0), wd=_f(wd, 0.0),
             rescale_grad=_f(rescale_grad, 1.0),
             clip_gradient=_f(clip_gradient, -1.0)),
        name="signsgd_update", differentiable=False)
    return _ret(new_w, out if out is not None else _nd(weight))


def signum_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0, out=None):
    new_w, new_mom = invoke(
        _lm.signum_update, (weight, grad, mom),
        dict(lr=_f(lr, 0.0), momentum=_f(momentum, 0.0), wd=_f(wd, 0.0),
             rescale_grad=_f(rescale_grad, 1.0),
             clip_gradient=_f(clip_gradient, -1.0), wd_lh=_f(wd_lh, 0.0)),
        name="signum_update", differentiable=False)
    _inplace(mom, new_mom)
    return _ret(new_w, out if out is not None else _nd(weight))


def mp_sgd_update(weight, grad, weight32, lr, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=True, out=None):
    new_w, new_w32 = invoke(
        _lm.mp_sgd_update, (weight, grad, weight32),
        dict(lr=_f(lr, 0.0), wd=_f(wd, 0.0),
             rescale_grad=_f(rescale_grad, 1.0),
             clip_gradient=_f(clip_gradient, -1.0)),
        name="mp_sgd_update", differentiable=False)
    _inplace(weight32, new_w32)
    return _ret(new_w, out if out is not None else _nd(weight))


def mp_sgd_mom_update(weight, grad, mom, weight32, lr, momentum=0.0, wd=0.0,
                      rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True,
                      out=None):
    new_w, new_mom, new_w32 = invoke(
        _lm.mp_sgd_mom_update, (weight, grad, mom, weight32),
        dict(lr=_f(lr, 0.0), momentum=_f(momentum, 0.0), wd=_f(wd, 0.0),
             rescale_grad=_f(rescale_grad, 1.0),
             clip_gradient=_f(clip_gradient, -1.0)),
        name="mp_sgd_mom_update", differentiable=False)
    _inplace(mom, new_mom)
    _inplace(weight32, new_w32)
    return _ret(new_w, out if out is not None else _nd(weight))


# ---------------------------------------------------------------------------
# legacy random ops (`src/operator/random/sample_op.cc`): random_* draw a
# fixed shape; sample_* broadcast over array-valued params
# ---------------------------------------------------------------------------

def random_uniform(low=0.0, high=1.0, shape=(1,), dtype="float32", ctx=None,
                   out=None):
    from .. import numpy as _mxnp
    return _ret(_mxnp.random.uniform(low, high, size=tuple(shape)).astype(
        dtype), out)


def random_normal(loc=0.0, scale=1.0, shape=(1,), dtype="float32", ctx=None,
                  out=None):
    from .. import numpy as _mxnp
    return _ret(_mxnp.random.normal(loc, scale, size=tuple(shape)).astype(
        dtype), out)


def random_gamma(alpha=1.0, beta=1.0, shape=(1,), dtype="float32", ctx=None,
                 out=None):
    from .. import numpy as _mxnp
    return _ret((_mxnp.random.standard_gamma(alpha, size=tuple(shape))
                 * beta).astype(dtype), out)


def random_exponential(lam=1.0, shape=(1,), dtype="float32", ctx=None,
                       out=None):
    from .. import numpy as _mxnp
    return _ret(_mxnp.random.exponential(1.0 / lam,
                                         size=tuple(shape)).astype(dtype),
                out)


def random_poisson(lam=1.0, shape=(1,), dtype="float32", ctx=None, out=None):
    from .. import numpy as _mxnp
    return _ret(_mxnp.random.poisson(lam, size=tuple(shape)).astype(dtype),
                out)


def random_randint(low, high, shape=(1,), dtype="int32", ctx=None, out=None):
    from .. import numpy as _mxnp
    return _ret(_mxnp.random.randint(low, high,
                                     size=tuple(shape)).astype(dtype), out)


def random_negative_binomial(k=1, p=1.0, shape=(1,), dtype="float32",
                             ctx=None, out=None):
    from .. import numpy as _mxnp
    return _ret(_mxnp.random.negative_binomial(
        k, p, size=tuple(shape)).astype(dtype), out)


def _expand(p, tail):
    return p.reshape(p.shape + (1,) * len(tail)) if tail else p


def sample_uniform(low, high=None, shape=(), dtype="float32", ctx=None,
                   out=None):
    """Per-element parameterized draws: out.shape = low.shape + shape
    (`src/operator/random/multisample_op.cc`)."""
    from .. import numpy as _mxnp
    lo, hi = _nd(low), _nd(high if high is not None else 1.0)
    tail = tuple(shape) if shape else ()
    u = _mxnp.random.uniform(0.0, 1.0, size=tuple(lo.shape) + tail)
    res = u * (_expand(hi, tail) - _expand(lo, tail)) + _expand(lo, tail)
    return _ret(res.astype(dtype), out)


def sample_normal(mu, sigma=None, shape=(), dtype="float32", ctx=None,
                  out=None):
    from .. import numpy as _mxnp
    m, s = _nd(mu), _nd(sigma if sigma is not None else 1.0)
    tail = tuple(shape) if shape else ()
    z = _mxnp.random.normal(0.0, 1.0, size=tuple(m.shape) + tail)
    res = z * _expand(s, tail) + _expand(m, tail)
    return _ret(res.astype(dtype), out)


class _LegacyRandom:  # noqa: E302
    """`mx.nd.random` submodule with legacy kwargs (shape=, ctx=)."""
    uniform = staticmethod(random_uniform)
    normal = staticmethod(random_normal)
    gamma = staticmethod(random_gamma)
    exponential = staticmethod(random_exponential)
    poisson = staticmethod(random_poisson)
    randint = staticmethod(random_randint)
    negative_binomial = staticmethod(random_negative_binomial)

    @staticmethod
    def seed(s):
        from .. import random as _r
        _r.seed(s)

    @staticmethod
    def shuffle(data, **kwargs):
        from .. import numpy as _mxnp
        return _mxnp.random.permutation(_nd(data))

    @staticmethod
    def multinomial(data, shape=(), get_prob=False, dtype="int32", **kw):
        from .. import numpy as _mxnp
        return _mxnp.random.multinomial(1, _nd(data), size=shape or None)


random = _LegacyRandom()


# public surface = every op defined above; incidental imports (jnp, onp,
# invoke, ...) stay private so mx.nd forwarding can't leak them
import types as _types  # noqa: E402

__all__ = sorted(
    n for n, v in list(globals().items())
    if not n.startswith("_") and not isinstance(v, _types.ModuleType)
    and n not in ("NDArray", "invoke", "current_context", "annotations")
)


# ---------------------------------------------------------------------------
# straggler kernels: FTML/LAMB phases, mp_nag, multi-tensor + preloaded
# optimizer variants, LARS helpers, Correlation
# (`src/operator/optimizer_op.cc`, `contrib/multi_*.cc`, `correlation.cc`)
# ---------------------------------------------------------------------------

erf = _npx.erf
erfinv = _npx.erfinv
CuDNNBatchNorm = BatchNorm  # cudnn alias: same semantics


def ftml_update(weight, grad, d, v, z, lr, beta1=0.6, beta2=0.999,
                epsilon=1e-8, t=1, wd=0.0, rescale_grad=1.0,
                clip_grad=-1.0, out=None):
    new_w, new_d, new_v, new_z = invoke(
        _lm.ftml_update, (weight, grad, d, v, z),
        dict(lr=_f(lr, 0.0), beta1=_f(beta1, 0.6), beta2=_f(beta2, 0.999),
             epsilon=_f(epsilon, 1e-8), t=int(t), wd=_f(wd, 0.0),
             rescale_grad=_f(rescale_grad, 1.0),
             clip_grad=_f(clip_grad, -1.0)),
        name="ftml_update", differentiable=False)
    _inplace(d, new_d)
    _inplace(v, new_v)
    _inplace(z, new_z)
    return _ret(new_w, out if out is not None else _nd(weight))


def lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0, out=None):
    g, new_mean, new_var = invoke(
        _lm.lamb_update_phase1, (weight, grad, mean, var),
        dict(beta1=_f(beta1, 0.9), beta2=_f(beta2, 0.999),
             epsilon=_f(epsilon, 1e-6), t=int(t),
             bias_correction=bool(bias_correction), wd=_f(wd, 0.0),
             rescale_grad=_f(rescale_grad, 1.0),
             clip_gradient=_f(clip_gradient, -1.0)),
        name="lamb_update_phase1", differentiable=False)
    _inplace(mean, new_mean)
    _inplace(var, new_var)
    return _ret(g, out)


def lamb_update_phase2(weight, g, r1, r2, lr, lower_bound=-1.0,
                       upper_bound=-1.0, out=None):
    new_w = invoke(
        _lm.lamb_update_phase2, (weight, g, r1, r2),
        dict(lr=_f(lr, 0.0), lower_bound=_f(lower_bound, -1.0),
             upper_bound=_f(upper_bound, -1.0)),
        name="lamb_update_phase2", differentiable=False)
    return _ret(new_w, out if out is not None else _nd(weight))


mp_lamb_update_phase1 = lamb_update_phase1  # master weights arrive as f32
mp_lamb_update_phase2 = lamb_update_phase2


def mp_nag_mom_update(weight, grad, mom, weight32, lr, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                      out=None):
    new_w, new_mom, new_w32 = invoke(
        _lm.mp_nag_mom_update, (weight, grad, mom, weight32),
        dict(lr=_f(lr, 0.0), momentum=_f(momentum, 0.0), wd=_f(wd, 0.0),
             rescale_grad=_f(rescale_grad, 1.0),
             clip_gradient=_f(clip_gradient, -1.0)),
        name="mp_nag_mom_update", differentiable=False)
    _inplace(mom, new_mom)
    _inplace(weight32, new_w32)
    return _ret(new_w, out if out is not None else _nd(weight))


def _multi_update(single, n_state):
    """Multi-tensor variant over the single-tensor kernel (reference
    `multi_sgd_update` etc: flattened [w0..wn, g0..gn, s0..sn] inputs,
    per-tensor lrs/wds)."""
    def op(*data, lrs=(), wds=(), num_weights=None, rescale_grad=1.0,
           clip_gradient=-1.0, momentum=0.0, out=None, **kw):
        n = num_weights if num_weights is not None else \
            len(data) // (2 + n_state)
        ws = data[:n]
        gs = data[n:2 * n]
        states = [data[(2 + s) * n:(3 + s) * n] for s in range(n_state)]
        outs = out if out is not None else [_nd(w) for w in ws]
        for i in range(n):
            sargs = [st[i] for st in states]
            single(ws[i], gs[i], *sargs, lr=lrs[i], wd=wds[i],
                   rescale_grad=rescale_grad, clip_gradient=clip_gradient,
                   out=outs[i],
                   **({"momentum": momentum} if n_state else {}))
        return outs
    return op


multi_sgd_update = _multi_update(sgd_update, 0)
multi_sgd_mom_update = _multi_update(sgd_mom_update, 1)


def _multi_mp_update(single, n_state):
    def op(*data, lrs=(), wds=(), num_weights=None, rescale_grad=1.0,
           clip_gradient=-1.0, momentum=0.0, out=None, **kw):
        n = num_weights if num_weights is not None else \
            len(data) // (3 + n_state)
        ws = data[:n]
        gs = data[n:2 * n]
        states = [data[(2 + s) * n:(3 + s) * n] for s in range(n_state)]
        w32s = data[(2 + n_state) * n:(3 + n_state) * n]
        outs = out if out is not None else [_nd(w) for w in ws]
        for i in range(n):
            sargs = [st[i] for st in states]
            single(ws[i], gs[i], *sargs, w32s[i], lr=lrs[i], wd=wds[i],
                   rescale_grad=rescale_grad, clip_gradient=clip_gradient,
                   out=outs[i],
                   **({"momentum": momentum} if n_state else {}))
        return outs
    return op


multi_mp_sgd_update = _multi_mp_update(mp_sgd_update, 0)
multi_mp_sgd_mom_update = _multi_mp_update(mp_sgd_mom_update, 1)


def _preloaded(multi):
    """preloaded_*: lrs/wds arrive as trailing NDArray inputs rather than
    attrs (`src/operator/contrib/preloaded_multi_sgd.cc`)."""
    def op(*data, num_weights=None, out=None, **kw):
        lrs = onp.asarray(_nd(data[-2]).asnumpy()).ravel()
        wds = onp.asarray(_nd(data[-1]).asnumpy()).ravel()
        return multi(*data[:-2], lrs=lrs.tolist(), wds=wds.tolist(),
                     num_weights=num_weights, out=out, **kw)
    return op


preloaded_multi_sgd_update = _preloaded(multi_sgd_update)
preloaded_multi_sgd_mom_update = _preloaded(multi_sgd_mom_update)
preloaded_multi_mp_sgd_update = _preloaded(multi_mp_sgd_update)
preloaded_multi_mp_sgd_mom_update = _preloaded(multi_mp_sgd_mom_update)


def adamw_update(weight, grad, mean, var, rescale_grad=1.0, lr=0.001,
                 beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0, eta=1.0,
                 clip_gradient=-1.0, out=None):
    """AdamW with decoupled weight decay (`src/operator/contrib/adamw.cc:79`).
    ``rescale_grad`` may be an NDArray — the reference passes the dynamic
    loss-scale as a tensor input and SKIPS the whole update (weight decay
    and EMA state included) when it is 0 or non-finite, the overflow-step
    contract of dynamic loss scaling (`adamw-inl.h:454`)."""
    if isinstance(rescale_grad, NDArray):
        new_w, new_mean, new_var = invoke(
            _lm.adamw_update_dynamic,
            (weight, grad, mean, var, rescale_grad),
            dict(lr=_f(lr, 0.001), beta1=_f(beta1, 0.9),
                 beta2=_f(beta2, 0.999), epsilon=_f(epsilon, 1e-8),
                 wd=_f(wd, 0.0), eta=_f(eta, 1.0),
                 clip_gradient=_f(clip_gradient, -1.0)),
            name="adamw_update", differentiable=False)
        _inplace(mean, new_mean)
        _inplace(var, new_var)
        return _ret(new_w, out if out is not None else _nd(weight))
    new_w, new_mean, new_var = invoke(
        _lm.adamw_update, (weight, grad, mean, var),
        dict(lr=_f(lr, 0.001), beta1=_f(beta1, 0.9), beta2=_f(beta2, 0.999),
             epsilon=_f(epsilon, 1e-8), wd=_f(wd, 0.0), eta=_f(eta, 1.0),
             rescale_grad=_f(rescale_grad, 1.0),
             clip_gradient=_f(clip_gradient, -1.0)),
        name="adamw_update", differentiable=False)
    _inplace(mean, new_mean)
    _inplace(var, new_var)
    return _ret(new_w, out if out is not None else _nd(weight))


def mp_adamw_update(weight, grad, mean, var, weight32, rescale_grad=1.0,
                    lr=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                    eta=1.0, clip_gradient=-1.0, out=None):
    """`src/operator/contrib/adamw.cc:34` — f32 master weights; tensor
    loss-scale gets the same skip-on-overflow contract as adamw_update."""
    if isinstance(rescale_grad, NDArray):
        new_w, new_mean, new_var, new_w32 = invoke(
            _lm.mp_adamw_update_dynamic,
            (weight, grad, mean, var, weight32, rescale_grad),
            dict(lr=_f(lr, 0.001), beta1=_f(beta1, 0.9),
                 beta2=_f(beta2, 0.999), epsilon=_f(epsilon, 1e-8),
                 wd=_f(wd, 0.0), eta=_f(eta, 1.0),
                 clip_gradient=_f(clip_gradient, -1.0)),
            name="mp_adamw_update", differentiable=False)
        _inplace(mean, new_mean)
        _inplace(var, new_var)
        _inplace(weight32, new_w32)
        return _ret(new_w, out if out is not None else _nd(weight))
    new_w, new_mean, new_var, new_w32 = invoke(
        _lm.mp_adamw_update, (weight, grad, mean, var, weight32),
        dict(lr=_f(lr, 0.001), beta1=_f(beta1, 0.9), beta2=_f(beta2, 0.999),
             epsilon=_f(epsilon, 1e-8), wd=_f(wd, 0.0), eta=_f(eta, 1.0),
             rescale_grad=_f(rescale_grad, 1.0),
             clip_gradient=_f(clip_gradient, -1.0)),
        name="mp_adamw_update", differentiable=False)
    _inplace(mean, new_mean)
    _inplace(var, new_var)
    _inplace(weight32, new_w32)
    return _ret(new_w, out if out is not None else _nd(weight))


def _multi_4state(single, mp, name, extra_lists=("etas",)):
    """Multi-tensor adamw/lamb/lans variants
    (`src/operator/contrib/adamw.cc:143`, `multi_lamb.cc`,
    `multi_lans.cc`): flattened [w_i, g_i, mean_i, var_i(, w32_i)] inputs,
    per-tensor lrs/wds (+etas for adamw, step_count for lamb/lans)."""
    stride = 5 if mp else 4

    def op(*data, lrs=(), wds=(), etas=(), step_count=(), num_tensors=None,
           num_weights=None, rescale_grad=1.0, clip_gradient=-1.0,
           beta1=0.9, beta2=0.999, epsilon=None, bias_correction=True,
           lower_bound=-1.0, upper_bound=-1.0, out=None, **kw):
        n = num_tensors if num_tensors is not None else (
            num_weights if num_weights is not None else len(data) // stride)
        # reference layout: per-tensor consecutive [w_i, g_i, mean_i,
        # var_i(, w32_i)] (`multi_lans-inl.h` FillMultiLANSKernelParam)
        groups = [data[i * stride:(i + 1) * stride] for i in range(n)]
        outs = out if out is not None else [_nd(g[0]) for g in groups]
        for i, g in enumerate(groups):
            kwargs = dict(lr=lrs[i], wd=wds[i], rescale_grad=rescale_grad,
                          clip_gradient=clip_gradient, beta1=beta1,
                          beta2=beta2, out=outs[i])
            if epsilon is not None:   # else each single's reference
                kwargs["epsilon"] = epsilon  # default (1e-8 adamw, 1e-6
                #                              lamb/lans) applies
            if "etas" in extra_lists:
                kwargs["eta"] = etas[i] if etas else 1.0
            if "step_count" in extra_lists:
                kwargs["t"] = int(step_count[i]) if len(step_count) else 1
                kwargs["lower_bound"] = lower_bound
                kwargs["upper_bound"] = upper_bound
            if "bias_correction" in extra_lists:
                kwargs["bias_correction"] = bias_correction
            single(*g, **kwargs)
        return outs
    op.__name__ = name
    return op


def _lamb_single(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                 rescale_grad=1.0, clip_gradient=-1.0, lower_bound=-1.0,
                 upper_bound=-1.0, out=None):
    new_w, new_mean, new_var = invoke(
        _lm.full_lamb_update, (weight, grad, mean, var),
        dict(lr=_f(lr, 0.0), beta1=_f(beta1, 0.9), beta2=_f(beta2, 0.999),
             epsilon=_f(epsilon, 1e-6), t=int(t),
             bias_correction=bool(bias_correction), wd=_f(wd, 0.0),
             rescale_grad=_f(rescale_grad, 1.0),
             clip_gradient=_f(clip_gradient, -1.0),
             lower_bound=_f(lower_bound, -1.0),
             upper_bound=_f(upper_bound, -1.0)),
        name="multi_lamb_update", differentiable=False)
    _inplace(mean, new_mean)
    _inplace(var, new_var)
    return _ret(new_w, out if out is not None else _nd(weight))


def _lans_single(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, t=1, wd=0.0, rescale_grad=1.0,
                 clip_gradient=-1.0, lower_bound=-1.0, upper_bound=-1.0,
                 out=None):
    new_w, new_mean, new_var = invoke(
        _lm.lans_update, (weight, grad, mean, var),
        dict(lr=_f(lr, 0.0), beta1=_f(beta1, 0.9), beta2=_f(beta2, 0.999),
             epsilon=_f(epsilon, 1e-6), t=int(t), wd=_f(wd, 0.0),
             rescale_grad=_f(rescale_grad, 1.0),
             clip_gradient=_f(clip_gradient, -1.0),
             lower_bound=_f(lower_bound, -1.0),
             upper_bound=_f(upper_bound, -1.0)),
        name="multi_lans_update", differentiable=False)
    _inplace(mean, new_mean)
    _inplace(var, new_var)
    return _ret(new_w, out if out is not None else _nd(weight))


def _adamw_single(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                  epsilon=1e-8, wd=0.0, eta=1.0, rescale_grad=1.0,
                  clip_gradient=-1.0, out=None):
    return adamw_update(weight, grad, mean, var, rescale_grad=rescale_grad,
                        lr=lr, beta1=beta1, beta2=beta2, epsilon=epsilon,
                        wd=wd, eta=eta, clip_gradient=clip_gradient, out=out)


def _mp_single(single):
    def op(weight, grad, mean, var, weight32, **kw):
        out = kw.pop("out", None)
        # single() rebinds weight32 in place (its mutate contract); the
        # low-precision copy tracks it
        new_w32 = single(weight32, _nd(grad).astype("float32"), mean, var,
                         **kw)
        low = _nd(new_w32).astype(_nd(weight).dtype)
        return _ret(low, out if out is not None else _nd(weight))
    return op


multi_adamw_update = _multi_4state(_adamw_single, False,
                                   "multi_adamw_update")
multi_mp_adamw_update = _multi_4state(_mp_single(_adamw_single), True,
                                      "multi_mp_adamw_update")
multi_lamb_update = _multi_4state(_lamb_single, False, "multi_lamb_update",
                                  extra_lists=("step_count",
                                               "bias_correction"))
multi_mp_lamb_update = _multi_4state(_mp_single(_lamb_single), True,
                                     "multi_mp_lamb_update",
                                     extra_lists=("step_count",
                                                  "bias_correction"))
multi_lans_update = _multi_4state(_lans_single, False, "multi_lans_update",
                                  extra_lists=("step_count",))
multi_mp_lans_update = _multi_4state(_mp_single(_lans_single), True,
                                     "multi_mp_lans_update",
                                     extra_lists=("step_count",))


def sparse_adagrad_update(weight, grad, history, lr, epsilon=1e-7, wd=0.0,
                          rescale_grad=1.0, clip_gradient=-1.0, out=None):
    """`_sparse_adagrad_update` (`src/operator/optimizer_op.cc:888`).
    Weight decay is rejected exactly like the reference ("non-zero values
    for the weight decay option are not supported") — without a wd term,
    densified row_sparse grads are exact: a zero row leaves both the
    history and the weight row unchanged."""
    if _f(wd, 0.0) != 0.0:
        raise ValueError("sparse_adagrad_update does not support weight "
                         "decay (reference contract)")
    from . import sparse as _sp
    if isinstance(grad, _sp._SparseNDArray):
        grad = grad.tostype("default")
    new_w, new_hist = invoke(
        _lm.adagrad_update, (weight, grad, history),
        dict(lr=_f(lr, 0.0), epsilon=_f(epsilon, 1e-7),
             rescale_grad=_f(rescale_grad, 1.0),
             clip_gradient=_f(clip_gradient, -1.0)),
        name="sparse_adagrad_update", differentiable=False)
    _inplace(history, new_hist)
    return _ret(new_w, out if out is not None else _nd(weight))


def group_adagrad_update(weight, grad, history, lr, epsilon=1e-5,
                         rescale_grad=1.0, clip_gradient=-1.0, out=None):
    """`_contrib_group_adagrad_update`
    (`src/operator/contrib/optimizer_op-inl.h:96`): one accumulator per
    weight row."""
    from . import sparse as _sp
    if isinstance(grad, _sp._SparseNDArray):
        grad = grad.tostype("default")
    new_w, new_hist = invoke(
        _lm.group_adagrad_update, (weight, grad, history),
        dict(lr=_f(lr, 0.0), epsilon=_f(epsilon, 1e-5),
             rescale_grad=_f(rescale_grad, 1.0),
             clip_gradient=_f(clip_gradient, -1.0)),
        name="group_adagrad_update", differentiable=False)
    _inplace(history, new_hist)
    return _ret(new_w, out if out is not None else _nd(weight))


def multi_sum_sq(*arrays, num_arrays=None, out=None):
    return _ret(invoke(_lm.multi_sum_sq, arrays, name="multi_sum_sq",
                       differentiable=False), out)


def multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, eta=0.001,
               eps=1e-8, rescale_grad=1.0, out=None):
    return _ret(invoke(
        _lm.multi_lars, (lrs, weights_sum_sq, grads_sum_sq, wds),
        dict(eta=_f(eta, 0.001), eps=_f(eps, 1e-8),
             rescale_grad=_f(rescale_grad, 1.0)),
        name="multi_lars", differentiable=False), out)


def reset_arrays(*arrays, num_arrays=None):
    """Zero each array in place (`src/operator/contrib/reset_arrays.cc`)."""
    for a in arrays:
        nd_a = _nd(a)
        nd_a._rebind(jnp.zeros_like(nd_a._data))
    return None


def Correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True, out=None):
    return _ret(invoke(
        _lm.correlation, (data1, data2),
        dict(kernel_size=kernel_size, max_displacement=max_displacement,
             stride1=stride1, stride2=stride2, pad_size=pad_size,
             is_multiply=bool(is_multiply)), name="Correlation"), out)


# recompute the export list to include everything above
__all__ = sorted(
    n for n, v in list(globals().items())
    if not n.startswith("_") and not isinstance(v, _types.ModuleType)
    and n not in ("NDArray", "invoke", "current_context", "annotations")
)
