"""``mx.nd.linalg`` — the legacy la_op operator family.

Reference: `python/mxnet/ndarray/linalg.py` (generated from
`src/operator/tensor/la_op.cc:29-1050`).  NDArray in / NDArray out via the
imperative ``invoke`` path; kernels live in `mxnet_tpu/ops/la_op.py`.
NumPy-style names (`mx.np.linalg.*`) remain available as fallthrough for
scripts that used the aliased surface.
"""
from __future__ import annotations

from ..ops import la_op as _la
from ..ops.invoke import invoke

__all__ = ["gemm", "gemm2", "potrf", "potri", "trmm", "trsm", "syrk",
           "gelqf", "syevd", "sumlogdiag", "extractdiag", "makediag",
           "extracttrian", "maketrian", "inverse", "det", "slogdet"]


def _wrap(name):
    jf = getattr(_la, name)

    def fn(*args, **kwargs):
        kwargs.pop("out", None)  # reference out= is write-to; rebind covers
        return invoke(jf, args, kwargs, name=f"linalg_{name}")

    fn.__name__ = name
    fn.__doc__ = jf.__doc__
    return fn


_g = globals()
for _name in __all__:
    _g[_name] = _wrap(_name)


def __getattr__(name):
    from ..numpy import linalg as _np_linalg
    if hasattr(_np_linalg, name):
        return getattr(_np_linalg, name)
    raise AttributeError(
        f"module 'mxnet_tpu.ndarray.linalg' has no attribute {name!r}")
