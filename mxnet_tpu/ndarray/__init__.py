"""``mx.nd`` — legacy NDArray namespace.

Reference: `python/mxnet/ndarray/` (21k LoC of generated wrappers).  The TPU
rebuild is natively NumPy-semantics; this namespace re-exports the np surface
under the legacy names users expect (`mx.nd.array`, `mx.nd.waitall`,
`elemwise_add`, ...) so Gluon-era scripts keep running.
"""
from __future__ import annotations

from .ndarray import NDArray, array, empty, from_jax, waitall
from . import sparse


def _lazy_np():
    from .. import numpy as _np
    return _np


def __getattr__(name):
    legacy = {
        "elemwise_add": "add",
        "elemwise_sub": "subtract",
        "elemwise_mul": "multiply",
        "elemwise_div": "true_divide",
        "broadcast_add": "add",
        "broadcast_sub": "subtract",
        "broadcast_mul": "multiply",
        "broadcast_div": "true_divide",
        "broadcast_maximum": "maximum",
        "broadcast_minimum": "minimum",
        "broadcast_power": "power",
    }
    np_mod = _lazy_np()
    if name in legacy:
        return getattr(np_mod, legacy[name])
    if hasattr(np_mod, name):
        return getattr(np_mod, name)
    raise AttributeError(f"module 'mxnet_tpu.ndarray' has no attribute {name!r}")


def save(fname, data):
    from ..utils.serialization import save_ndarrays
    save_ndarrays(fname, data)


def load(fname, ctx=None):
    from ..utils.serialization import load_ndarrays
    return load_ndarrays(fname, ctx=ctx)
