"""``mx.nd`` — legacy NDArray namespace.

Reference: `python/mxnet/ndarray/` (21k LoC of generated wrappers).  The TPU
rebuild is natively NumPy-semantics; this namespace re-exports the np surface
under the legacy names users expect (`mx.nd.array`, `mx.nd.waitall`,
`elemwise_add`, ...) so Gluon-era scripts keep running.
"""
from __future__ import annotations

from .ndarray import NDArray, array, empty, from_jax, waitall
from . import sparse


def _lazy_np():
    from .. import numpy as _np
    return _np


def __getattr__(name):
    import importlib
    # sub-namespaces (reference `python/mxnet/ndarray/contrib.py`,
    # `ndarray/image.py`, `ndarray/linalg.py`): mx.nd.contrib.box_nms,
    # mx.nd.image.to_tensor, mx.nd.linalg.gemm2, ...
    if name == "linalg":
        return importlib.import_module(".linalg", __name__)
    if name == "image":
        return importlib.import_module(".image", __name__)
    if name == "contrib":
        from .. import contrib as _contrib
        return _contrib
    # the generated legacy op surface (reference
    # `python/mxnet/ndarray/register.py:265-277`) takes precedence: its
    # arg conventions (exclude=, special reshape codes, CamelCase layer
    # ops, mutate-output optimizer kernels) differ from mx.np
    _legacy = importlib.import_module(".legacy", __name__)
    if name == "legacy":
        return _legacy
    if not name.startswith("_") and hasattr(_legacy, name):
        return getattr(_legacy, name)
    np_mod = _lazy_np()
    if hasattr(np_mod, name):
        return getattr(np_mod, name)
    raise AttributeError(f"module 'mxnet_tpu.ndarray' has no attribute {name!r}")


def save(fname, data):
    from ..utils.serialization import save_ndarrays
    save_ndarrays(fname, data)


def load(fname, ctx=None):
    from ..utils.serialization import load_ndarrays
    return load_ndarrays(fname, ctx=ctx)
